// keystone-tpu native JPEG decode fast path.
//
// Reference capability: loaders/ImageLoaderUtils.scala:22-47 — executors
// decode JPEGs in parallel at cluster scale. On a TPU VM the host input
// pipeline is the analogue, and Python/PIL decoding holds the GIL enough
// that thread pools saturate ~1 core. This library provides a C decode
// path (libjpeg, which this image ships as libjpeg.so.62):
//
//   - DCT-domain scaled decode ("draft mode"): pick the largest
//     denominator d in {1,2,4,8} with ceil(dim/d) still >= the target on
//     both axes, so most of the inverse DCT of a large photo is skipped
//     when decoding to 256^2.
//   - separable triangle-filter (antialiased bilinear) resize to the
//     exact (target, target) square — the same filter family PIL's
//     BILINEAR resample uses, so outputs track the PIL fallback path
//     within JPEG/resample tolerance rather than bitwise.
//   - grayscale JPEGs are expanded to RGB by libjpeg; CMYK/YCCK (no RGB
//     conversion in libjpeg) and malformed streams return failure and
//     the caller falls back to PIL for that image.
//
// ctypes releases the GIL for the duration of each call, so the
// streaming loader's *thread* pool scales across cores with this path
// (no spawn+IPC tax). A batch entry point with an internal thread pool
// is provided for bulk benchmarks.
//
// Built as its own shared library (libkeystone_jpeg.so) so environments
// without libjpeg still get libkeystone_io.so.

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <algorithm>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

// libjpeg's default error handler calls exit(); trampoline to longjmp.
struct JumpErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit_trampoline(j_common_ptr cinfo) {
  JumpErrorMgr* err = reinterpret_cast<JumpErrorMgr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

// silent, but still counts corrupt-data warnings (msg_level < 0) the
// way the default handler does — decode_one turns them into failure
void emit_message_quiet(j_common_ptr cinfo, int msg_level) {
  if (msg_level < 0) cinfo->err->num_warnings++;
}

// Separable triangle-filter resample (PIL precompute_coeffs shape):
// support widens with the downscale factor, so minification is
// antialiased; magnification degrades to classic bilinear.
struct ResampleAxis {
  std::vector<int> start;      // first source index per output pixel
  std::vector<int> count;      // taps per output pixel
  std::vector<float> weights;  // concatenated, count[i] each
  int max_count = 0;
};

void build_axis(int in_size, int out_size, ResampleAxis* ax) {
  const double scale = static_cast<double>(in_size) / out_size;
  const double filterscale = std::max(scale, 1.0);
  const double support = 1.0 * filterscale;  // triangle filter support
  ax->start.resize(out_size);
  ax->count.resize(out_size);
  ax->weights.clear();
  for (int xx = 0; xx < out_size; ++xx) {
    const double center = (xx + 0.5) * scale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    double total = 0.0;
    std::vector<double> w(xmax - xmin);
    for (int x = xmin; x < xmax; ++x) {
      double t = (x - center + 0.5) / filterscale;
      double v = t < 0 ? 1.0 + t : 1.0 - t;  // triangle
      if (v < 0.0) v = 0.0;
      w[x - xmin] = v;
      total += v;
    }
    if (total <= 0.0) {  // degenerate: nearest
      xmin = std::min(std::max(static_cast<int>(center), 0), in_size - 1);
      xmax = xmin + 1;
      w.assign(1, 1.0);
      total = 1.0;
    }
    ax->start[xx] = xmin;
    ax->count[xx] = xmax - xmin;
    ax->max_count = std::max(ax->max_count, xmax - xmin);
    for (double v : w) ax->weights.push_back(static_cast<float>(v / total));
  }
}

// rows: in_h x in_w x 3 uint8 -> out: target x target x 3 float32.
void resize_rgb(const unsigned char* src, int in_w, int in_h, int target,
                float* out) {
  ResampleAxis hx, vx;
  build_axis(in_w, target, &hx);
  build_axis(in_h, target, &vx);
  // horizontal pass: (in_h, target, 3) float
  std::vector<float> tmp(static_cast<size_t>(in_h) * target * 3);
  for (int y = 0; y < in_h; ++y) {
    const unsigned char* row = src + static_cast<size_t>(y) * in_w * 3;
    float* trow = tmp.data() + static_cast<size_t>(y) * target * 3;
    const float* wp = hx.weights.data();
    for (int xx = 0; xx < target; ++xx) {
      const int s = hx.start[xx];
      const int c = hx.count[xx];
      float r = 0.f, g = 0.f, b = 0.f;
      for (int k = 0; k < c; ++k) {
        const float w = wp[k];
        const unsigned char* px = row + (s + k) * 3;
        r += w * px[0];
        g += w * px[1];
        b += w * px[2];
      }
      wp += c;
      trow[xx * 3 + 0] = r;
      trow[xx * 3 + 1] = g;
      trow[xx * 3 + 2] = b;
    }
  }
  // vertical pass
  const float* wp = vx.weights.data();
  for (int yy = 0; yy < target; ++yy) {
    const int s = vx.start[yy];
    const int c = vx.count[yy];
    float* orow = out + static_cast<size_t>(yy) * target * 3;
    std::memset(orow, 0, sizeof(float) * target * 3);
    for (int k = 0; k < c; ++k) {
      const float w = wp[k];
      const float* trow = tmp.data() + static_cast<size_t>(s + k) * target * 3;
      for (int i = 0; i < target * 3; ++i) orow[i] += w * trow[i];
    }
    wp += c;
  }
}

int decode_one(const unsigned char* data, int64_t len, int target,
               float* out) {
  jpeg_decompress_struct cinfo;
  JumpErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit_trampoline;
  jerr.pub.emit_message = emit_message_quiet;
  std::vector<unsigned char> pixels;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  if (cinfo.jpeg_color_space == JCS_CMYK ||
      cinfo.jpeg_color_space == JCS_YCCK) {
    // libjpeg has no CMYK->RGB conversion; caller falls back to PIL
    jpeg_destroy_decompress(&cinfo);
    return 3;
  }
  cinfo.out_color_space = JCS_RGB;
  // draft-mode scale, PIL Image.draft semantics: the largest power-of-2
  // denom <= min(w//target, h//target) — floor, so the scaled image
  // always has at least `target` FULL pixels per axis and the resize
  // step still antialiases on both axes
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  const unsigned floor_scale =
      std::min(cinfo.image_width / static_cast<unsigned>(target),
               cinfo.image_height / static_cast<unsigned>(target));
  for (unsigned d = 8; d >= 1; d /= 2) {
    if (d <= floor_scale) {
      cinfo.scale_denom = d;
      break;
    }
  }
  cinfo.dct_method = JDCT_ISLOW;
  jpeg_start_decompress(&cinfo);
  const int w = cinfo.output_width;
  const int h = cinfo.output_height;
  if (w <= 0 || h <= 0 || cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return 4;
  }
  pixels.resize(static_cast<size_t>(w) * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = pixels.data() + static_cast<size_t>(cinfo.output_scanline) * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  // libjpeg "recovers" truncated/corrupt streams by gray-filling and
  // counting a warning; the PIL fallback raises on those, so treat any
  // warning as failure to keep the two decode paths' accept sets equal
  const long warnings = cinfo.err->num_warnings;
  jpeg_destroy_decompress(&cinfo);
  if (warnings > 0) return 5;
  resize_rgb(pixels.data(), w, h, target, out);
  return 0;
}

}  // namespace

extern "C" {

// Decode one JPEG to (target, target, 3) float32 RGB. Returns 0 on
// success; nonzero (corrupt stream / CMYK / non-RGB output) means the
// caller should fall back to its Python decoder for this image.
int jpeg_decode_f32(const unsigned char* data, int64_t len, int target,
                    float* out) {
  return decode_one(data, len, target, out);
}

// Batch decode: n JPEGs in one concatenated buffer with offsets (n+1
// entries). out is n*target*target*3 floats; ok[i] is set to 1 on
// success, 0 on failure (that slot's pixels are undefined). threads<=0
// uses hardware_concurrency. Returns the number decoded successfully.
int64_t jpeg_decode_batch_f32(const unsigned char* data,
                              const int64_t* offsets, int64_t n, int target,
                              float* out, unsigned char* ok, int threads) {
  if (n <= 0) return 0;  // nt would clamp to 0 and chunk would SIGFPE
  int nt = threads > 0 ? threads
                       : static_cast<int>(std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > n) nt = static_cast<int>(n);
  const size_t img_floats = static_cast<size_t>(target) * target * 3;
  std::vector<std::thread> workers;
  std::vector<int64_t> counts(nt, 0);
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    workers.emplace_back([&, t]() {
      int64_t lo = t * chunk;
      int64_t hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        int rc = decode_one(data + offsets[i], offsets[i + 1] - offsets[i],
                            target, out + i * img_floats);
        ok[i] = rc == 0 ? 1 : 0;
        if (rc == 0) ++counts[t];
      }
    });
  }
  for (auto& w : workers) w.join();
  int64_t total = 0;
  for (int64_t c : counts) total += c;
  return total;
}

}  // extern "C"
