// keystone-tpu native text featurization.
//
// The reference's text featurization chain (Trim -> LowerCase ->
// Tokenizer -> NGrams(HashingTF), nodes/nlp/*.scala) runs on the JVM per
// partition; here the equivalent host-side hot path is one fused
// multi-threaded C++ pass per document batch: trim + ASCII lowercase +
// tokenize on non-word bytes + FNV-1a rolling n-gram hashing into a
// fixed feature space, emitting numeric CSR triplets — no string
// marshaling back to Python. Hash semantics are bit-identical to
// keystone_tpu/ops/nlp/hashing_tf.py (stable_hash + rolling combine).
//
// Non-ASCII bytes (>= 0x80) are treated as word characters, which
// matches Python \w for letters; callers with heavy non-ASCII
// punctuation should use the Python path.

#include <cstdint>
#include <cstring>
#include <thread>
#include <unordered_map>
#include <algorithm>
#include <vector>

namespace {

constexpr uint32_t kFnvOffset = 0x811C9DC5u;
constexpr uint32_t kFnvPrime = 0x01000193u;

inline bool is_word_byte(unsigned char c) {
  return (c >= '0' && c <= '9') || (c >= 'A' && c <= 'Z') ||
         (c >= 'a' && c <= 'z') || c == '_' || c >= 0x80;
}

struct DocOut {
  std::vector<int32_t> cols;
  std::vector<float> vals;
};

void process_doc(const char* begin, const char* end, int min_order,
                 int max_order, int64_t num_features, bool binarize,
                 DocOut* out) {
  // trim
  while (begin < end && static_cast<unsigned char>(*begin) <= ' ') ++begin;
  while (end > begin && static_cast<unsigned char>(end[-1]) <= ' ') --end;

  // tokenize + per-token FNV-1a over lowercased bytes. Java/Scala
  // String.split semantics (mirrored by the Python Tokenizer): an
  // empty doc is the no-match case and tokenizes to [""] (hash = bare
  // FNV offset, stable_hash("")); a doc that starts with a separator
  // yields a leading EMPTY token only when a word token follows —
  // trailing empties are all stripped, so a separator-only doc yields
  // ZERO tokens.
  std::vector<uint32_t> token_hashes;
  if (begin >= end) {
    token_hashes.push_back(kFnvOffset);
  } else if (!is_word_byte(static_cast<unsigned char>(*begin))) {
    const char* q = begin;
    while (q < end && !is_word_byte(static_cast<unsigned char>(*q))) ++q;
    if (q < end) token_hashes.push_back(kFnvOffset);
  }
  const char* p = begin;
  while (p < end) {
    while (p < end && !is_word_byte(static_cast<unsigned char>(*p))) ++p;
    if (p >= end) break;
    uint32_t h = kFnvOffset;
    while (p < end && is_word_byte(static_cast<unsigned char>(*p))) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c >= 'A' && c <= 'Z') c += 'a' - 'A';
      h = (h ^ c) * kFnvPrime;
      ++p;
    }
    token_hashes.push_back(h);
  }

  // rolling n-gram hash counting (hashing_tf.py NGramsHashingTF.apply)
  std::unordered_map<int32_t, float> counts;
  const int64_t n = static_cast<int64_t>(token_hashes.size());
  for (int64_t i = 0; i < n; ++i) {
    uint32_t h = kFnvOffset;
    for (int order = 1; order <= max_order; ++order) {
      if (i + order > n) break;
      h = (h ^ token_hashes[i + order - 1]) * kFnvPrime;
      if (order >= min_order) {
        counts[static_cast<int32_t>(h % num_features)] += 1.0f;
      }
    }
  }

  out->cols.reserve(counts.size());
  out->vals.reserve(counts.size());
  for (const auto& kv : counts) out->cols.push_back(kv.first);
  std::sort(out->cols.begin(), out->cols.end());
  for (int32_t c : out->cols) {
    out->vals.push_back(binarize ? 1.0f : counts[c]);
  }
}

}  // namespace

extern "C" {

// Fused trim/lowercase/tokenize/ngram-hash TF over a document batch.
// docs: concatenated UTF-8 bytes; offsets: n_docs+1 byte offsets.
// Emits CSR: row_ptr (n_docs+1), then up to `cap` (col, val) pairs in
// document order with per-document columns ascending. Returns total nnz,
// or -1 if `cap` was too small (caller re-invokes with a larger buffer).
int64_t text_ngram_hash_tf(const char* docs, const int64_t* offsets,
                           int64_t n_docs, int min_order, int max_order,
                           int64_t num_features, int binarize,
                           int64_t* row_ptr, int32_t* out_cols,
                           float* out_vals, int64_t cap, int num_threads) {
  if (n_docs == 0) {
    row_ptr[0] = 0;
    return 0;
  }
  std::vector<DocOut> results(static_cast<size_t>(n_docs));
  int nt = num_threads > 0 ? num_threads : 1;
  if (nt > n_docs) nt = static_cast<int>(n_docs);
  std::vector<std::thread> workers;
  workers.reserve(nt);
  for (int t = 0; t < nt; ++t) {
    workers.emplace_back([&, t]() {
      for (int64_t i = t; i < n_docs; i += nt) {
        process_doc(docs + offsets[i], docs + offsets[i + 1], min_order,
                    max_order, num_features, binarize != 0, &results[i]);
      }
    });
  }
  for (auto& w : workers) w.join();

  int64_t nnz = 0;
  row_ptr[0] = 0;
  for (int64_t i = 0; i < n_docs; ++i) {
    nnz += static_cast<int64_t>(results[i].cols.size());
    row_ptr[i + 1] = nnz;
  }
  if (nnz > cap) return -1;
  int64_t at = 0;
  for (int64_t i = 0; i < n_docs; ++i) {
    std::memcpy(out_cols + at, results[i].cols.data(),
                results[i].cols.size() * sizeof(int32_t));
    std::memcpy(out_vals + at, results[i].vals.data(),
                results[i].vals.size() * sizeof(float));
    at += static_cast<int64_t>(results[i].cols.size());
  }
  return nnz;
}

}  // extern "C"
