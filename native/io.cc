// keystone-tpu native IO runtime.
//
// The reference framework's runtime substrate is JVM/Spark with native
// C++ kernels behind JNI (SURVEY.md §2.9); on TPU the compute kernels are
// XLA programs, and the native layer moves to where it still pays: the
// host input pipeline. This library provides the hot host-side paths —
// numeric CSV parsing and CIFAR binary record decoding, both
// multi-threaded — exposed over a C ABI consumed via ctypes
// (keystone_tpu/native.py), with pure-Python fallbacks when the shared
// library is absent.
//
// Build: `make -C native` (g++ -O3 -fPIC -shared -pthread).

#include <cctype>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Counts rows and columns of a numeric CSV. Returns 0 on success.
int csv_dims(const char* path, int64_t* rows, int64_t* cols);

// Parses a numeric CSV into a preallocated rows*cols float32 buffer.
// Multi-threaded over row chunks. Returns 0 on success.
int csv_read_f32(const char* path, float* out, int64_t rows, int64_t cols,
                 int num_threads);

// Decodes CIFAR binary records: n records of (1 label byte + c*h*w
// channel-plane bytes). labels: n int32; images: n*h*w*c float32 in
// (row, col, channel) order. Returns number of records, or -1.
int64_t cifar_read(const char* path, int32_t* labels, float* images,
                   int64_t max_records, int channels, int dim);
}

namespace {

struct FileBuf {
  char* data = nullptr;
  size_t size = 0;
  ~FileBuf() { std::free(data); }
  bool load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    data = static_cast<char*>(std::malloc(n + 1));
    if (!data) {
      std::fclose(f);
      return false;
    }
    size = std::fread(data, 1, n, f);
    data[size] = '\0';
    std::fclose(f);
    return true;
  }
};

// Parses one line of comma/space-separated floats; returns count parsed.
// std::from_chars: locale-free, ~4x faster than strtof on numeric CSVs.
int64_t parse_line(const char* p, const char* end, float* out,
                   int64_t max_vals) {
  int64_t n = 0;
  while (p < end && n < max_vals) {
    while (p < end && (*p == ',' || *p == ' ' || *p == '\t')) ++p;
    if (p >= end || *p == '\n' || *p == '\r') break;
    // from_chars rejects the leading '+' strtof accepted
    bool neg = false;
    if (*p == '+') {
      ++p;
    } else if (*p == '-') {
      neg = true;
      ++p;
    }
    float v = 0.0f;
    auto res = std::from_chars(p, end, v);
    if (res.ec != std::errc() || res.ptr == p) break;
    out[n++] = neg ? -v : v;
    p = res.ptr;
  }
  return n;
}

}  // namespace

int csv_dims(const char* path, int64_t* rows, int64_t* cols) {
  FileBuf buf;
  if (!buf.load(path)) return 1;
  int64_t r = 0, c = 0;
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  // columns from the first non-empty line
  while (p < end && (*p == '\n' || *p == '\r')) ++p;
  const char* line_end = static_cast<const char*>(
      memchr(p, '\n', end - p));
  if (!line_end) line_end = end;
  for (const char* q = p; q < line_end; ++q) {
    if (*q == ',') ++c;
  }
  if (line_end > p) ++c;
  // count non-empty lines
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!nl) nl = end;
    for (const char* q = p; q < nl; ++q) {
      if (!std::isspace(static_cast<unsigned char>(*q))) {
        ++r;
        break;
      }
    }
    p = nl + 1;
  }
  *rows = r;
  *cols = c;
  return 0;
}

int csv_read_f32(const char* path, float* out, int64_t rows, int64_t cols,
                 int num_threads) {
  FileBuf buf;
  if (!buf.load(path)) return 1;
  // index line starts
  std::vector<const char*> lines;
  lines.reserve(rows);
  const char* p = buf.data;
  const char* end = buf.data + buf.size;
  while (p < end && static_cast<int64_t>(lines.size()) < rows) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!nl) nl = end;
    for (const char* q = p; q < nl; ++q) {
      if (!std::isspace(static_cast<unsigned char>(*q))) {
        lines.push_back(p);
        break;
      }
    }
    p = nl + 1;
  }
  if (static_cast<int64_t>(lines.size()) != rows) return 2;

  int nt = num_threads > 0 ? num_threads
                           : std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  std::vector<std::thread> workers;
  std::vector<int> errors(nt, 0);
  int64_t chunk = (rows + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    workers.emplace_back([&, t]() {
      int64_t lo = t * chunk;
      int64_t hi = std::min(rows, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        int64_t got = parse_line(lines[i], end, out + i * cols, cols);
        if (got != cols) {
          errors[t] = 1;
          return;
        }
      }
    });
  }
  for (auto& w : workers) w.join();
  for (int e : errors)
    if (e) return 3;
  return 0;
}

int64_t cifar_read(const char* path, int32_t* labels, float* images,
                   int64_t max_records, int channels, int dim) {
  FileBuf buf;
  if (!buf.load(path)) return -1;
  const int64_t rec_len = 1 + channels * dim * dim;
  int64_t n = buf.size / rec_len;
  if (n > max_records) n = max_records;
  const int64_t img_px = dim * dim;
  for (int64_t i = 0; i < n; ++i) {
    const unsigned char* rec =
        reinterpret_cast<unsigned char*>(buf.data) + i * rec_len;
    labels[i] = rec[0];
    float* dst = images + i * img_px * channels;
    for (int c = 0; c < channels; ++c) {
      const unsigned char* plane = rec + 1 + c * img_px;
      for (int64_t px = 0; px < img_px; ++px) {
        dst[px * channels + c] = static_cast<float>(plane[px]);
      }
    }
  }
  return n;
}
