import time, numpy as np, jax, jax.numpy as jnp
from jax import lax
N, D, K, B = 49_152, 1024, 10, 4096
NB = N // B
lam, gamma = 1e-2, 1e-3
X = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)

def x3(A, Bm):
    return lax.dot_general(A, Bm, (((1,), (1,)), ((), ())),
        precision=lax.DotAlgorithmPreset.BF16_BF16_F32_X3)

def timeit(name, fn, *args, reps=3):
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    print(f"{name:44s} compile+run {time.perf_counter()-t0:6.1f} s", flush=True)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        best = min(best, time.perf_counter() - t0)
    print(f"{name:44s} {best*1e3:9.2f} ms", flush=True)

@jax.jit
def rt_probe(s):
    return s + 1.0
timeit("tunnel RT (scalar)", rt_probe, jnp.float32(1.0))

# build 12 distinct PSD diag blocks, batched
@jax.jit
def make_psd_batch(X):
    def one(s):
        Xb = lax.dynamic_slice_in_dim(X, s * B, B, axis=0)
        nb = jnp.sum(Xb * Xb, 1)
        d2 = nb[:, None] + nb[None, :] - 2.0 * x3(Xb, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
        return Kb + lam * jnp.eye(B, dtype=jnp.float32)
    return jax.vmap(one)(jnp.arange(NB))
Ab = make_psd_batch(X)
np.asarray(Ab[:1, :1, :1])
print("diag blocks built", flush=True)

timeit("batched K_BB build (12 diag blocks)", make_psd_batch, X)

@jax.jit
def seq_chol(Ab):
    def step(c, i):
        L = jnp.linalg.cholesky(Ab[i] + c * 1e-12)
        return c + L.sum() * 1e-20, None
    c, _ = lax.scan(step, jnp.float32(0), jnp.arange(NB))
    return c
timeit("12x sequential cholesky(4096) scan", seq_chol, Ab)

@jax.jit
def batch_chol(Ab):
    return jnp.linalg.cholesky(Ab)
timeit("batched cholesky (12,4096,4096)", batch_chol, Ab)

L1 = jnp.linalg.cholesky(Ab[0])
rhs = jax.random.normal(jax.random.PRNGKey(2), (B, K), jnp.float32)
np.asarray(L1[:1, :1])

@jax.jit
def seq_trisolve(L, rhs):
    def step(c, _):
        z = lax.linalg.triangular_solve(L, rhs + c, left_side=True,
                                        lower=True)
        w = lax.linalg.triangular_solve(L, z, left_side=True, lower=True,
                                        transpose_a=True)
        return c + w.sum() * 1e-20, None
    c, _ = lax.scan(step, rhs * 0, jnp.arange(NB))
    return c
timeit("12x tri-solve pair (k=10)", seq_trisolve, L1, rhs)
