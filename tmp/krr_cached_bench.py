import time, dataclasses as dc, numpy as np, jax, jax.numpy as jnp
import sys
sys.path.insert(0, "/root/repo")
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator, KernelRidgeRegression,
)
from keystone_tpu.ops.util.nodes import ClassLabelIndicators
from keystone_tpu.parallel.dataset import Dataset

N, D, K, BLOCK = 49_152, 1024, 10, 4096

@jax.jit
def gen(key):
    kx, ky = jax.random.split(key)
    X = jax.random.normal(kx, (N, D), jnp.float32)
    y = jax.random.randint(ky, (N,), 0, K, jnp.int32)
    return X, y

X, y = gen(jax.random.PRNGKey(0))
Xd = Dataset.from_array(X, n=N)
labels = ClassLabelIndicators(K).apply_batch(Dataset.from_array(y))

@jax.jit
def rt_probe(s):
    return s + 1.0
np.asarray(rt_probe(jnp.float32(1.0)))
t0 = time.perf_counter(); np.asarray(rt_probe(jnp.float32(2.0)))
rt = (time.perf_counter() - t0) * 1e3
print(f"RT {rt:.1f} ms", flush=True)

results = {}
for label, cache, epochs in [
    ("uncached E=1", False, 1), ("cached   E=1", True, 1),
    ("uncached E=3", False, 3), ("cached   E=3", True, 3),
]:
    est = KernelRidgeRegression(
        GaussianKernelGenerator(gamma=1e-3), lam=1e-2,
        block_size=BLOCK, num_epochs=epochs, cache_kernel=cache,
    )
    m = est.fit(Xd, labels)
    np.asarray(m.model[:1, :1])  # warm/compile
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(est.fit(Xd, labels).model[:1, :1])
        best = min(best, time.perf_counter() - t0)
    results[label] = best * 1e3
    print(f"{label}: {best*1e3:8.2f} ms wall  (device ~{best*1e3-rt:7.2f})",
          flush=True)

w_u = np.asarray(KernelRidgeRegression(
    GaussianKernelGenerator(gamma=1e-3), lam=1e-2, block_size=BLOCK,
    num_epochs=1, cache_kernel=False).fit(Xd, labels).model)
w_c = np.asarray(KernelRidgeRegression(
    GaussianKernelGenerator(gamma=1e-3), lam=1e-2, block_size=BLOCK,
    num_epochs=1, cache_kernel=True).fit(Xd, labels).model)
d = np.abs(w_u - w_c).max() / max(np.abs(w_u).max(), 1e-30)
print(f"cached vs uncached rel diff: {d:.2e}", flush=True)
print("ALL DONE", flush=True)
