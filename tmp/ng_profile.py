import cProfile, pstats, io, time
import numpy as np, jax.numpy as jnp
import sys
sys.path.insert(0, "/root/repo")
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.pipelines.text.newsgroups import NewsgroupsConfig, build_pipeline
from keystone_tpu.parallel.dataset import Dataset

rng = np.random.default_rng(0)
vocab = [f"w{i:04d}" for i in range(2000)]
docs, ys = [], []
for i in range(2000):
    c = i % 20
    words = rng.choice(vocab[c * 80: c * 80 + 200], size=60)
    docs.append(" ".join(words))
    ys.append(c)
train = LabeledData(
    data=Dataset.from_items(docs),
    labels=Dataset.from_array(jnp.asarray(np.asarray(ys, np.int32))),
)
conf = NewsgroupsConfig(n_grams=2, common_features=10_000)

def run_once():
    pipe = build_pipeline(train, conf)
    preds = pipe.apply(train.data).get()
    np.asarray(preds.padded()[:1])

run_once()
t0 = time.perf_counter(); run_once()
print(f"wall {1e3*(time.perf_counter()-t0):.1f} ms", flush=True)

pr = cProfile.Profile()
pr.enable()
run_once()
pr.disable()
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(28)
print(s.getvalue())
