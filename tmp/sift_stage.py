import time, sys, numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
from keystone_tpu.ops.images.sift import (
    _sep_conv2d, _gaussian_kernel, _triangular_kernel, SIFTExtractor,
    MAGNIF, NUM_ORIENTATIONS,
)

B, H, W = 128, 256, 256
imgs = jnp.asarray(np.random.default_rng(0).random((B, H, W), np.float32))

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter(); force(fn(*args))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:40s} {best*1e3:9.2f} ms", flush=True)

@jax.jit
def rt(s): return s + 1.0
force(rt(jnp.float32(1.0)))
t0=time.perf_counter(); force(rt(jnp.float32(2.0)))
print(f"RT {1e3*(time.perf_counter()-t0):.1f} ms", flush=True)

# stage A: the 4 gaussian pre-smooths (one per scale) on all images
@jax.jit
def stage_smooth(x):
    acc = jnp.float32(0)
    for scale in range(4):
        bin_size = 4 + 2 * scale
        k = _gaussian_kernel(bin_size / MAGNIF)
        sm = _sep_conv2d(x, k, edge_pad=True)
        acc = acc + sm.sum()
    return acc
timeit("4x gaussian smooth (sep conv)", stage_smooth, imgs)

# stage B: + gradient/one-hot planes
@jax.jit
def stage_planes(x):
    acc = jnp.float32(0)
    for scale in range(4):
        bin_size = 4 + 2 * scale
        k = _gaussian_kernel(bin_size / MAGNIF)
        sm = _sep_conv2d(x, k, edge_pad=True)
        gy, gx = jnp.gradient(sm, axis=(1, 2))
        mag = jnp.sqrt(gx*gx + gy*gy)
        ang = jnp.arctan2(gy, gx) % (2.0*jnp.pi)
        t = ang / (2.0*jnp.pi) * 8
        b0 = jnp.floor(t); frac = t - b0
        b0 = b0.astype(jnp.int32) % 8
        b1 = (b0 + 1) % 8
        planes = (jax.nn.one_hot(b0, 8, axis=1) * (mag*(1-frac))[:, None]
                  + jax.nn.one_hot(b1, 8, axis=1) * (mag*frac)[:, None])
        acc = acc + planes.sum()
    return acc
timeit("+ gradients/one-hot planes", stage_planes, imgs)

# stage C: + triangular conv on the 8-plane stacks
@jax.jit
def stage_tri(x):
    acc = jnp.float32(0)
    for scale in range(4):
        bin_size = 4 + 2 * scale
        k = _gaussian_kernel(bin_size / MAGNIF)
        sm = _sep_conv2d(x, k, edge_pad=True)
        gy, gx = jnp.gradient(sm, axis=(1, 2))
        mag = jnp.sqrt(gx*gx + gy*gy)
        ang = jnp.arctan2(gy, gx) % (2.0*jnp.pi)
        t = ang / (2.0*jnp.pi) * 8
        b0 = jnp.floor(t); frac = t - b0
        b0 = b0.astype(jnp.int32) % 8
        b1 = (b0 + 1) % 8
        planes = (jax.nn.one_hot(b0, 8, axis=1) * (mag*(1-frac))[:, None]
                  + jax.nn.one_hot(b1, 8, axis=1) * (mag*frac)[:, None])
        planes = planes.reshape(-1, H, W)
        smoothed = _sep_conv2d(planes, _triangular_kernel(bin_size))
        acc = acc + smoothed.sum()
    return acc
timeit("+ triangular sep conv (8 planes)", stage_tri, imgs)

# full SIFT via bucketed vmap (as jit_batch does)
ext = SIFTExtractor(scale_step=1)
vf = jax.jit(jax.vmap(ext.apply))
timeit("full SIFT (vmap apply)", vf, imgs)
