"""Layout experiment: normalize in (8,4,nfy,4,nfx) layout, relayout once."""
import time, sys, numpy as np, jax, jax.numpy as jnp
from functools import partial
sys.path.insert(0, "/root/repo")
from keystone_tpu.ops.images.sift import (
    SIFTExtractor, _sep_conv2d, _gaussian_kernel, _triangular_kernel,
    _window_factors, _dsift_one_scale, MAGNIF, CONTRAST_THRESHOLD,
    NUM_SPATIAL_BINS, DESCRIPTOR_DIMS,
)

B, H, W = 128, 256, 256
rng = np.random.default_rng(0)
imgs = jnp.asarray(rng.random((B, H, W), np.float32))

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(4)]
        for o in outs: force(o)
        best = min(best, (time.perf_counter() - t0) / 4)
    print(f"{name:36s} {best*1e3:9.2f} ms/batch", flush=True)

@partial(jax.jit, static_argnames=("bin_size", "step", "bound_min"))
def _dsift_alt(img, *, bin_size, step, bound_min):
    Hh, Ww = img.shape
    gy, gx = jnp.gradient(img)
    mag = jnp.sqrt(gx*gx + gy*gy)
    ang = jnp.arctan2(gy, gx) % (2.0*jnp.pi)
    t = ang / (2.0*jnp.pi) * 8
    b0 = jnp.floor(t); frac = t - b0
    b0 = b0.astype(jnp.int32) % 8
    b1 = (b0 + 1) % 8
    planes = (jax.nn.one_hot(b0, 8, axis=0) * (mag*(1-frac))
              + jax.nn.one_hot(b1, 8, axis=0) * (mag*frac))
    smoothed = _sep_conv2d(planes, _triangular_kernel(bin_size))
    extent = 3*bin_size
    nfy = max((Hh - 1 - bound_min - extent)//step + 1, 0)
    nfx = max((Ww - 1 - bound_min - extent)//step + 1, 0)
    def bin_slices(x, axis, nf):
        parts = [jax.lax.slice_in_dim(
            x, bound_min + j*bin_size,
            bound_min + j*bin_size + (nf-1)*step + 1,
            stride=step, axis=axis) for j in range(4)]
        return jnp.stack(parts, axis=axis)
    g = bin_slices(smoothed, 1, nfy)   # (8, j, nfy, W)
    g = bin_slices(g, 3, nfx)          # (8, j, nfy, i, nfx)
    wf = jnp.asarray(_window_factors(bin_size))
    g = g * wf[None, :, None, None, None] * wf[None, None, None, :, None]
    # all math in this layout; reduce over (t, j, i) -> (nfy, nfx)
    norms = jnp.sqrt(jnp.sum(g*g, axis=(0, 1, 3)))
    g = g / jnp.maximum(norms, 1e-12)[None, None, :, None, :]
    g = jnp.minimum(g, 0.2)
    n2 = jnp.sqrt(jnp.sum(g*g, axis=(0, 1, 3)))
    g = g / jnp.maximum(n2, 1e-12)[None, None, :, None, :]
    g = jnp.where((norms >= CONTRAST_THRESHOLD)[None, None, :, None, :],
                  g, 0.0)
    g = jnp.minimum(jnp.floor(g * 512.0), 255.0)
    # one relayout at the end: (t,j,fy,i,fx) -> (fy,fx,j,i,t) flat
    out = jnp.transpose(g, (2, 4, 1, 3, 0)).reshape(-1, 128)
    return out, norms.reshape(-1)

def apply_alt(img):
    x = img
    descs = []
    for scale in range(4):
        bin_size = 4 + 2*scale
        k = _gaussian_kernel(bin_size / MAGNIF)
        sm = _sep_conv2d(x[None], k, edge_pad=True)[0]
        bound = 9 - 3*scale
        d, _ = _dsift_alt(sm, bin_size=bin_size, step=3+scale, bound_min=bound)
        descs.append(d)
    return jnp.concatenate(descs, axis=0).T

ext = SIFTExtractor(scale_step=1)
cur = jax.jit(jax.vmap(ext.apply))
alt = jax.jit(jax.vmap(apply_alt))
timeit("current SIFT", cur, imgs)
timeit("alt layout SIFT", alt, imgs)
a = np.asarray(cur(imgs[:2]))
b = np.asarray(alt(imgs[:2]))
print("parity max diff:", np.abs(a - b).max(), flush=True)
