import time, numpy as np, jax, jax.numpy as jnp
from jax import lax
N, D, K, B = 49_152, 1024, 10, 4096
NB = N // B
lam, gamma = 1e-2, 1e-3
X = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)

def x3(A, Bm):
    return lax.dot_general(A, Bm, (((1,), (1,)), ((), ())),
        precision=lax.DotAlgorithmPreset.BF16_BF16_F32_X3)

def timeit(name, fn, *args, reps=3):
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    print(f"{name:44s} compile+run {time.perf_counter()-t0:6.1f} s", flush=True)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        best = min(best, time.perf_counter() - t0)
    print(f"{name:44s} {best*1e3:9.2f} ms", flush=True)

@jax.jit
def rt_probe(s):
    return s + 1.0
timeit("tunnel RT (scalar)", rt_probe, jnp.float32(1.0))

@jax.jit
def make_psd_scan(X):
    def one(c, i):
        Xb = lax.dynamic_slice_in_dim(X, i * B, B, axis=0)
        nb = jnp.sum(Xb * Xb, 1)
        d2 = nb[:, None] + nb[None, :] - 2.0 * x3(Xb, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
        return c, Kb + lam * jnp.eye(B, dtype=jnp.float32)
    _, Ab = lax.scan(one, jnp.float32(0), jnp.arange(NB))
    return Ab
Ab = make_psd_scan(X)
np.asarray(Ab[:1, :1, :1])
timeit("scan K_BB build (12 diag blocks)", make_psd_scan, X)

@jax.jit
def seq_chol(Ab):
    def step(c, i):
        L = jnp.linalg.cholesky(Ab[i] + c * 1e-12)
        return c + L.sum() * 1e-20, None
    c, _ = lax.scan(step, jnp.float32(0), jnp.arange(NB))
    return c
timeit("12x sequential cholesky(4096) scan", seq_chol, Ab)

@jax.jit
def batch_chol(Ab):
    return jnp.linalg.cholesky(Ab).sum()
timeit("batched cholesky (12,4096,4096)", batch_chol, Ab)
