"""Banded-GEMM separable conv vs conv_general_dilated for SIFT."""
import time, sys, numpy as np, jax, jax.numpy as jnp
from functools import partial
sys.path.insert(0, "/root/repo")
from keystone_tpu.ops.images.sift import (
    SIFTExtractor, _sep_conv2d, _gaussian_kernel, _triangular_kernel,
    _window_factors, MAGNIF, CONTRAST_THRESHOLD,
)

B, H, W = 128, 256, 256
rng = np.random.default_rng(0)
imgs = jnp.asarray(rng.random((B, H, W), np.float32))

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(4)]
        for o in outs: force(o)
        best = min(best, (time.perf_counter() - t0) / 4)
    print(f"{name:36s} {best*1e3:9.2f} ms/batch", flush=True)

def band_matrix(k, n, edge_pad):
    """(n, n) such that (x @ Bm)[i] = sum_d k[d] x[i + d - pad] with
    zero or edge padding."""
    pad = (len(k) - 1) // 2
    Bm = np.zeros((n, n), np.float32)
    for i in range(n):
        for d, kv in enumerate(k):
            j = i + d - pad
            if 0 <= j < n:
                Bm[j, i] += kv
            elif edge_pad:
                Bm[min(max(j, 0), n - 1), i] += kv
    return Bm

_BANDS = {}
def get_band(key, k, n, edge_pad):
    if key not in _BANDS:
        _BANDS[key] = jnp.asarray(band_matrix(k, n, edge_pad))
    return _BANDS[key]

def sep_conv_gemm(planes, k, edge_pad=False):
    """(P, H, W) -> same-size separable conv via two banded GEMMs."""
    P, Hh, Ww = planes.shape
    hp = jax.lax.Precision.HIGHEST
    Bw = get_band(("w", len(k), float(k[0]), Ww, edge_pad), k, Ww, edge_pad)
    Bh = get_band(("h", len(k), float(k[0]), Hh, edge_pad), k, Hh, edge_pad)
    x = jnp.matmul(planes, Bw, precision=hp)           # conv along W
    x = jnp.matmul(Bh.T, x.reshape(P, Hh, Ww).transpose(0, 2, 1) if False else x.transpose(0, 2, 1), precision=hp)
    return x.transpose(0, 2, 1) if False else jnp.matmul(
        planes * 0, planes * 0, precision=hp)  # placeholder (unused)

# simpler: x conv along W: (P,H,W)@(W,W); along H: einsum hj,pjw->phw
def sep_conv_gemm2(planes, k, edge_pad=False):
    P, Hh, Ww = planes.shape
    hp = jax.lax.Precision.HIGHEST
    Bw = get_band(("w", tuple(np.round(k, 9)), Ww, edge_pad), k, Ww, edge_pad)
    Bh = get_band(("h", tuple(np.round(k, 9)), Hh, edge_pad), k, Hh, edge_pad)
    x = jnp.matmul(planes, Bw, precision=hp)
    x = jnp.einsum("hj,pjw->phw", Bh.T, x, precision=hp)
    return x

# parity check vs _sep_conv2d
pl = jnp.asarray(rng.random((8, H, W), np.float32))
for bs, ep in [(7, False), (11, False)]:
    k = _triangular_kernel((bs + 1) // 2)
    a = np.asarray(_sep_conv2d(pl, k, edge_pad=ep))
    b = np.asarray(sep_conv_gemm2(pl, k, edge_pad=ep))
    print(f"tri k={len(k)} edge={ep}: max diff {np.abs(a-b).max():.2e}",
          flush=True)
kg = _gaussian_kernel(4 / MAGNIF)
a = np.asarray(_sep_conv2d(pl, kg, edge_pad=True))
b = np.asarray(sep_conv_gemm2(pl, kg, edge_pad=True))
print(f"gauss edge=True: max diff {np.abs(a-b).max():.2e}", flush=True)

# timing: all 4 scales of tri conv on (8B, H, W)
big = jnp.asarray(rng.random((8 * B, H, W), np.float32))

@jax.jit
def tri_conv_cur(x):
    acc = jnp.float32(0)
    for scale in range(4):
        acc = acc + _sep_conv2d(x, _triangular_kernel(4 + 2*scale)).sum()
    return acc

@jax.jit
def tri_conv_gemm(x):
    acc = jnp.float32(0)
    for scale in range(4):
        acc = acc + sep_conv_gemm2(x, _triangular_kernel(4 + 2*scale)).sum()
    return acc

timeit("4x tri conv (conv_general)", tri_conv_cur, big)
timeit("4x tri conv (banded GEMM)", tri_conv_gemm, big)
