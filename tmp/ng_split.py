import time, numpy as np, jax.numpy as jnp, sys
sys.path.insert(0, "/root/repo")
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.pipelines.text.newsgroups import NewsgroupsConfig, build_pipeline
from keystone_tpu.parallel.dataset import Dataset

rng = np.random.default_rng(0)
vocab = [f"w{i:04d}" for i in range(2000)]
docs, ys = [], []
for i in range(2000):
    c = i % 20
    docs.append(" ".join(rng.choice(vocab[c*80:c*80+200], size=60)))
    ys.append(c)
train = LabeledData(
    data=Dataset.from_items(docs),
    labels=Dataset.from_array(jnp.asarray(np.asarray(ys, np.int32))),
)
conf = NewsgroupsConfig(n_grams=2, common_features=10_000)

for rep in range(3):
    t0 = time.perf_counter()
    pipe = build_pipeline(train, conf)
    t1 = time.perf_counter()
    res = pipe.apply(train.data)
    t2 = time.perf_counter()
    preds = res.get()
    t3 = time.perf_counter()
    np.asarray(preds.padded()[:1])
    t4 = time.perf_counter()
    print(f"build {1e3*(t1-t0):7.1f}  apply(lazy) {1e3*(t2-t1):6.1f}  "
          f"get {1e3*(t3-t2):7.1f}  sync {1e3*(t4-t3):6.1f}", flush=True)
