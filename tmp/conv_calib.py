import time, sys, numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")

P, H, W = 1024, 256, 256
rng = np.random.default_rng(0)
x = jnp.asarray(rng.random((P, H, W), np.float32))
Bm = jnp.asarray(rng.random((256, 256), np.float32))

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(4)]
        for o in outs: force(o)
        best = min(best, (time.perf_counter() - t0) / 4)
    print(f"{name:44s} {best*1e3:9.2f} ms/call", flush=True)

hp = jax.lax.Precision.HIGHEST

@jax.jit
def one_gemm(x, Bm):
    return jnp.matmul(x, Bm, precision=hp).sum()
timeit("1x (1024*256,256)@(256,256) HIGHEST", one_gemm, x, Bm)

@jax.jit
def one_gemm_x3(x, Bm):
    y = jax.lax.dot_general(x, Bm, (((2,), (0,)), ((), ())),
        precision=jax.lax.DotAlgorithmPreset.BF16_BF16_F32_X3)
    return y.sum()
timeit("1x same GEMM X3", one_gemm_x3, x, Bm)

@jax.jit
def eight_gemm(x, Bm):
    acc = jnp.float32(0)
    for _ in range(8):
        acc = acc + jnp.matmul(x, Bm, precision=hp).sum()
    return acc
timeit("8x same GEMM HIGHEST", eight_gemm, x, Bm)

@jax.jit
def sep_both_axes(x, Bm):
    y = jnp.matmul(x, Bm, precision=hp)          # along W
    yt = jnp.swapaxes(y, 1, 2)
    z = jnp.matmul(yt, Bm, precision=hp)         # along H
    return jnp.swapaxes(z, 1, 2).sum()
timeit("sep conv via 2 GEMM + 2 transpose", sep_both_axes, x, Bm)
