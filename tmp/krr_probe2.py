import time, numpy as np, jax, jax.numpy as jnp
from jax import lax
N, D, K, B = 49_152, 1024, 10, 4096
NB = N // B
lam, gamma = 1e-2, 1e-3
X = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)
norms = jnp.sum(X * X, axis=1)
mask = jnp.ones((N,), jnp.float32)
W = jnp.zeros((N, K), jnp.float32)
starts = jnp.arange(NB, dtype=jnp.int32) * B

def x3(A, Bm):
    return lax.dot_general(A, Bm, (((1,), (1,)), ((), ())),
        precision=lax.DotAlgorithmPreset.BF16_BF16_F32_X3)

def timeit(name, fn, *args, reps=3):
    t0 = time.perf_counter()
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    print(f"{name:44s} compile+run {time.perf_counter()-t0:6.1f} s", flush=True)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        best = min(best, time.perf_counter() - t0)
    print(f"{name:44s} {best*1e3:9.2f} ms", flush=True)

@jax.jit
def rt_probe(s):
    return s + 1.0
timeit("tunnel RT (scalar)", rt_probe, jnp.float32(1.0))

@jax.jit
def gemm_only(X, starts):
    def step(c, s):
        Xb = lax.dynamic_slice_in_dim(X, s, B, axis=0)
        return c + x3(X, Xb).sum(), None
    c, _ = lax.scan(step, jnp.float32(0), starts)
    return c
timeit("12x kernel cross-GEMM (X3) [sum]", gemm_only, X, starts)

@jax.jit
def kgen(X, norms, mask, starts):
    def step(c, s):
        Xb = lax.dynamic_slice_in_dim(X, s, B, axis=0)
        nb = lax.dynamic_slice_in_dim(norms, s, B, axis=0)
        mb = lax.dynamic_slice_in_dim(mask, s, B, axis=0)
        d2 = norms[:, None] + nb[None, :] - 2.0 * x3(X, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0)) * mask[:, None] * mb[None, :]
        return c + Kb.sum(), None
    c, _ = lax.scan(step, jnp.float32(0), starts)
    return c
timeit("12x kernel block gen (+exp+mask) [sum]", kgen, X, norms, mask, starts)

@jax.jit
def kgen_resid(X, norms, mask, W, starts):
    def step(c, s):
        Xb = lax.dynamic_slice_in_dim(X, s, B, axis=0)
        nb = lax.dynamic_slice_in_dim(norms, s, B, axis=0)
        d2 = norms[:, None] + nb[None, :] - 2.0 * x3(X, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
        r = lax.dot_general(Kb, W + c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST)
        return c + r.sum() * 1e-20, None
    c, _ = lax.scan(step, jnp.float32(0), starts)
    return c
timeit("  + residual K^T W (HIGHEST) [sum]", kgen_resid, X, norms, mask, W, starts)
