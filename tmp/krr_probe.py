"""Component breakdown for krr_block_solve on the real chip."""
import time, numpy as np, jax, jax.numpy as jnp
from functools import partial
from jax import lax

N, D, K, B = 49_152, 1024, 10, 4096
NB = N // B
lam = 1e-2
gamma = 1e-3

kx = jax.random.PRNGKey(0)
X = jax.random.normal(kx, (N, D), jnp.float32)
norms = jnp.sum(X * X, axis=1)
mask = jnp.ones((N,), jnp.float32)
W = jnp.zeros((N, K), jnp.float32)
Y = jax.random.normal(jax.random.PRNGKey(1), (N, K), jnp.float32)
starts = jnp.arange(NB, dtype=jnp.int32) * B

def x3(A, Bm):
    return lax.dot_general(A, Bm, (((1,), (1,)), ((), ())),
        precision=lax.DotAlgorithmPreset.BF16_BF16_F32_X3)

def timeit(name, fn, *args, reps=3):
    out = fn(*args); np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
        best = min(best, time.perf_counter() - t0)
    print(f"{name:42s} {best*1e3:9.2f} ms")
    return best

# RT probe
@jax.jit
def rt_probe(s):
    return s + 1.0
timeit("tunnel RT (scalar)", rt_probe, jnp.float32(1.0))

# 1. kernel-gen GEMM only (no exp), scanned over 12 blocks
@jax.jit
def gemm_only(X, starts):
    def step(c, s):
        Xb = lax.dynamic_slice_in_dim(X, s, B, axis=0)
        d = x3(X, Xb)
        return c + d[0, 0], None
    c, _ = lax.scan(step, jnp.float32(0), starts)
    return c
timeit("12x kernel cross-GEMM (X3, no exp)", gemm_only, X, starts)

# 2. full kernel block gen (with exp+mask), scanned
@jax.jit
def kgen(X, norms, mask, starts):
    def step(c, s):
        Xb = lax.dynamic_slice_in_dim(X, s, B, axis=0)
        nb = lax.dynamic_slice_in_dim(norms, s, B, axis=0)
        mb = lax.dynamic_slice_in_dim(mask, s, B, axis=0)
        d2 = norms[:, None] + nb[None, :] - 2.0 * x3(X, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0)) * mask[:, None] * mb[None, :]
        return c + Kb[0, 0], None
    c, _ = lax.scan(step, jnp.float32(0), starts)
    return c
timeit("12x kernel block gen (GEMM+exp+mask)", kgen, X, norms, mask, starts)

# 3. + residual contraction
@jax.jit
def kgen_resid(X, norms, mask, W, starts):
    def step(c, s):
        Xb = lax.dynamic_slice_in_dim(X, s, B, axis=0)
        nb = lax.dynamic_slice_in_dim(norms, s, B, axis=0)
        d2 = norms[:, None] + nb[None, :] - 2.0 * x3(X, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
        r = lax.dot_general(Kb, W, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST)
        return c + r[0, 0], None
    c, _ = lax.scan(step, jnp.float32(0), starts)
    return c
timeit("  + residual K^T W (HIGHEST)", kgen_resid, X, norms, mask, W, starts)

# 4. 12 sequential cholesky (scan) on a fixed PSD block
A1 = None
@jax.jit
def make_psd(X):
    Xb = lax.dynamic_slice_in_dim(X, 0, B, axis=0)
    d2 = jnp.sum(Xb*Xb,1)[:,None] + jnp.sum(Xb*Xb,1)[None,:] - 2.0*x3(Xb, Xb)
    Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
    return Kb + lam * jnp.eye(B, dtype=jnp.float32)
A1 = make_psd(X)
np.asarray(A1[:1,:1])

@jax.jit
def seq_chol(A):
    def step(c, _):
        L = jnp.linalg.cholesky(A + c * 1e-12)
        return c + L[0, 0], None
    c, _ = lax.scan(step, jnp.float32(0), jnp.arange(NB))
    return c
timeit("12x sequential cholesky(4096) scan", seq_chol, A1)

# 5. one batched cholesky (12, 4096, 4096)
Abatch = jnp.broadcast_to(A1, (NB, B, B)) + (
    jnp.arange(NB, dtype=jnp.float32)[:, None, None] * 1e-9)
np.asarray(Abatch[:1, :1, :1])
@jax.jit
def batch_chol(Ab):
    return jnp.linalg.cholesky(Ab)
timeit("batched cholesky (12,4096,4096)", batch_chol, Abatch)

# 6. triangular solve pair, k=10 rhs, sequential x12
L1 = jnp.linalg.cholesky(A1)
rhs = jax.random.normal(jax.random.PRNGKey(2), (B, K), jnp.float32)
np.asarray(L1[:1,:1])
@jax.jit
def seq_trisolve(L, rhs):
    def step(c, _):
        z = lax.linalg.triangular_solve(L, rhs + c, left_side=True, lower=True)
        w = lax.linalg.triangular_solve(L, z, left_side=True, lower=True,
                                        transpose_a=True)
        return c + w[:1, :1] * 1e-12, None
    c, _ = lax.scan(step, rhs[:1, :1] * 0, jnp.arange(NB))
    return c
timeit("12x tri-solve pair (k=10)", seq_trisolve, L1, rhs)

# 7. batched explicit inverse via cholesky + 2 batched tri-solves vs I
@jax.jit
def batch_inv(Ab):
    L = jnp.linalg.cholesky(Ab)
    eye = jnp.broadcast_to(jnp.eye(B, dtype=jnp.float32), Ab.shape)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return lax.dot_general(Linv, Linv, (((1,), (1,)), ((2,), (2,))).__class__((((1,), (1,)), ((0,), (0,)))))
# simpler: einsum
@jax.jit
def batch_inv2(Ab):
    L = jnp.linalg.cholesky(Ab)
    eye = jnp.broadcast_to(jnp.eye(B, dtype=jnp.float32), Ab.shape)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    return jnp.einsum('bki,bkj->bij', Linv, Linv)
timeit("batched inverse (chol+trtri+gemm)", batch_inv2, Abatch)
