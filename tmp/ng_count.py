import time, numpy as np, jax.numpy as jnp, sys
sys.path.insert(0, "/root/repo")
from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.pipelines.text.newsgroups import NewsgroupsConfig, build_pipeline
from keystone_tpu.parallel.dataset import Dataset
import keystone_tpu.ops.stats.nodes as sn
import keystone_tpu.ops.nlp.ngrams as ng

calls = {"tf": 0, "ngram": 0}
_tf0 = sn.TermFrequency.apply
def tf_apply(self, terms):
    calls["tf"] += 1
    return _tf0(self, terms)
sn.TermFrequency.apply = tf_apply
_ng0 = ng.NGramsFeaturizer.apply
def ng_apply(self, toks):
    calls["ngram"] += 1
    return _ng0(self, toks)
ng.NGramsFeaturizer.apply = ng_apply

rng = np.random.default_rng(0)
vocab = [f"w{i:04d}" for i in range(2000)]
docs, ys = [], []
for i in range(2000):
    c = i % 20
    docs.append(" ".join(rng.choice(vocab[c*80:c*80+200], size=60)))
    ys.append(c)
train = LabeledData(
    data=Dataset.from_items(docs),
    labels=Dataset.from_array(jnp.asarray(np.asarray(ys, np.int32))),
)
conf = NewsgroupsConfig(n_grams=2, common_features=10_000)

for rep in range(3):
    calls["tf"] = calls["ngram"] = 0
    t0 = time.perf_counter()
    pipe = build_pipeline(train, conf)
    preds = pipe.apply(train.data).get()
    np.asarray(preds.padded()[:1])
    print(f"rep {rep}: {1e3*(time.perf_counter()-t0):7.1f} ms  "
          f"tf calls {calls['tf']}  ngram calls {calls['ngram']}", flush=True)
