import time, numpy as np, jax, jax.numpy as jnp
from jax import lax
N, D, K, B = 49_152, 1024, 10, 4096
NB = N // B
lam, gamma = 1e-2, 1e-3
X = jax.random.normal(jax.random.PRNGKey(0), (N, D), jnp.float32)

def x3(A, Bm):
    return lax.dot_general(A, Bm, (((1,), (1,)), ((), ())),
        precision=lax.DotAlgorithmPreset.BF16_BF16_F32_X3)

def force(out):
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.ravel()[:1] if leaf.ndim else leaf)

def timeit(name, fn, *args, reps=3):
    t0 = time.perf_counter()
    force(fn(*args))
    print(f"{name:46s} compile+run {time.perf_counter()-t0:6.1f} s", flush=True)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        force(fn(*args))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:46s} {best*1e3:9.2f} ms", flush=True)

@jax.jit
def rt_probe(s):
    return s + 1.0
timeit("tunnel RT (scalar)", rt_probe, jnp.float32(1.0))

@jax.jit
def make_psd_scan(X):
    def one(c, i):
        Xb = lax.dynamic_slice_in_dim(X, i * B, B, axis=0)
        nb = jnp.sum(Xb * Xb, 1)
        d2 = nb[:, None] + nb[None, :] - 2.0 * x3(Xb, Xb)
        Kb = jnp.exp(-gamma * jnp.maximum(d2, 0.0))
        return c, Kb + lam * jnp.eye(B, dtype=jnp.float32)
    _, Ab = lax.scan(one, jnp.float32(0), jnp.arange(NB))
    return Ab
Ab = make_psd_scan(X)
force(Ab)

@jax.jit
def batch_inverse(Ab):
    L = jnp.linalg.cholesky(Ab)
    eye = jnp.broadcast_to(jnp.eye(B, dtype=jnp.float32), Ab.shape)
    Linv = lax.linalg.triangular_solve(L, eye, left_side=True, lower=True)
    # A^-1 = L^-T L^-1 as one batched GEMM
    Minv = lax.dot_general(
        Linv, Linv, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=lax.Precision.HIGHEST)
    return Minv

t0 = time.perf_counter()
M = batch_inverse(Ab)
force(M)
print(f"batched inverse compile+run {time.perf_counter()-t0:6.1f} s", flush=True)
timeit("batched inverse (chol + trsm(I) + gemm)", batch_inverse, Ab)

# accuracy of the inverse-apply vs direct f64 solve on block 0
rhs = jax.random.normal(jax.random.PRNGKey(2), (B, K), jnp.float32)
A0 = np.asarray(Ab[0], np.float64)
w_ref = np.linalg.solve(A0, np.asarray(rhs, np.float64))

@jax.jit
def apply_inv(M0, A0j, rhs):
    w = M0 @ rhs
    r = rhs - A0j @ w
    w = w + M0 @ r          # refine 1
    r = rhs - A0j @ w
    return w + M0 @ r       # refine 2
w2 = apply_inv(M[0], Ab[0], rhs)
err = np.abs(np.asarray(w2, np.float64) - w_ref).max() / np.abs(w_ref).max()
print(f"inverse-apply (2 GEMM refines) rel err: {err:.2e}", flush=True)

@jax.jit
def apply_inv0(M0, rhs):
    return M0 @ rhs
w0 = apply_inv0(M[0], rhs)
err0 = np.abs(np.asarray(w0, np.float64) - w_ref).max() / np.abs(w_ref).max()
print(f"inverse-apply (no refine) rel err: {err0:.2e}", flush=True)
print("ALL DONE", flush=True)
