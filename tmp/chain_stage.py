import time, sys, numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
import bench
from keystone_tpu.ops.images.fisher_vector import FisherVector
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
from keystone_tpu.ops.learning import BatchPCATransformer
from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
from keystone_tpu.ops.stats import NormalizeRows, SignedHellingerMapper
from keystone_tpu.workflow.api import Pipeline

rng = np.random.default_rng(0)
imgs = bench._fixture_images(128, 256)
X = jnp.asarray(imgs)

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [fn(*args) for _ in range(4)]
        for o in outs: force(o)
        best = min(best, (time.perf_counter() - t0) / 4)
    print(f"{name:44s} {best*1e3:9.2f} ms/batch ({128/best:7.1f} ex/s)", flush=True)

desc_dim, vocab = 64, 16
pca = jnp.asarray(rng.standard_normal((desc_dim, 128)).astype(np.float32) * 0.1)
gmm = GaussianMixtureModel(
    jnp.asarray(rng.standard_normal((desc_dim, vocab)), jnp.float32),
    jnp.ones((desc_dim, vocab), jnp.float32),
    jnp.ones((vocab,), jnp.float32) / vocab,
)

# 1. sift branch through hellinger (pre-PCA)
p1 = (PixelScaler().and_then(GrayScaler())
      .and_then(SIFTExtractor(scale_step=1))
      .and_then(SignedHellingerMapper())).fit().jit_batch()
timeit("sift + hellinger", p1, X)

# 2. + PCA
p2 = (PixelScaler().and_then(GrayScaler())
      .and_then(SIFTExtractor(scale_step=1))
      .and_then(SignedHellingerMapper())
      .and_then(BatchPCATransformer(pca.T))).fit().jit_batch()
timeit("+ batch PCA", p2, X)

# 3. + FV
p3 = (PixelScaler().and_then(GrayScaler())
      .and_then(SIFTExtractor(scale_step=1))
      .and_then(SignedHellingerMapper())
      .and_then(BatchPCATransformer(pca.T))
      .and_then(FisherVector(gmm))).fit().jit_batch()
timeit("+ fisher vector", p3, X)

# 4. full (both branches)
full = bench._build_fv_pipeline(rng, desc_dim, vocab).fit().jit_batch()
timeit("full two-branch chain", full, X)
