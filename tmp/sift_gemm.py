"""GEMM-formulated dense SIFT: fold tri-conv + bin sampling + window
factors into two per-scale banded sampling matrices."""
import time, sys, numpy as np, jax, jax.numpy as jnp
from functools import partial
sys.path.insert(0, "/root/repo")
from keystone_tpu.ops.images.sift import (
    SIFTExtractor, _sep_conv2d, _gaussian_kernel, _window_factors,
    MAGNIF, CONTRAST_THRESHOLD,
)

def sampling_matrix(n, nf, bin_size, step, bound):
    """(n, nf*4): col f*4+j = tri(y - (bound + f*step + j*bin)) * wf[j],
    zero outside [0, n) — exactly tri-conv (zero pad) then sample."""
    wf = _window_factors(bin_size)
    A = np.zeros((n, nf * 4), np.float32)
    ys = np.arange(n)
    for f in range(nf):
        for j in range(4):
            c = bound + f * step + j * bin_size
            tri = np.maximum(0.0, (bin_size - np.abs(ys - c)) / bin_size)
            A[:, f * 4 + j] = tri * wf[j]
    return A

_MATS = {}
def get_mats(H, W, bin_size, step, bound):
    key = (H, W, bin_size, step, bound)
    if key not in _MATS:
        extent = 3 * bin_size
        nfy = max((H - 1 - bound - extent) // step + 1, 0)
        nfx = max((W - 1 - bound - extent) // step + 1, 0)
        _MATS[key] = (
            sampling_matrix(H, nfy, bin_size, step, bound),
            sampling_matrix(W, nfx, bin_size, step, bound),
            nfy, nfx,
        )
    return _MATS[key]

hp = jax.lax.Precision.HIGHEST

def dsift_gemm(img, bin_size, step, bound):
    H, W = img.shape
    Ay, Ax, nfy, nfx = get_mats(H, W, bin_size, step, bound)
    gy, gx = jnp.gradient(img)
    mag = jnp.sqrt(gx*gx + gy*gy)
    ang = jnp.arctan2(gy, gx) % (2.0*jnp.pi)
    t = ang / (2.0*jnp.pi) * 8
    b0 = jnp.floor(t); frac = t - b0
    b0 = b0.astype(jnp.int32) % 8
    b1 = (b0 + 1) % 8
    planes = (jax.nn.one_hot(b0, 8, axis=0) * (mag*(1-frac))
              + jax.nn.one_hot(b1, 8, axis=0) * (mag*frac))  # (8,H,W)
    # y-axis: (8, H, W) -> (8, nfy*4, W); x-axis -> (8, nfy*4, nfx*4)
    t1 = jnp.einsum("thw,hm->tmw", planes, Ay, precision=hp)
    t2 = jnp.einsum("tmw,wn->tmn", t1, Ax, precision=hp)
    # (t, fy, j, fx, i) -> (fy, fx, j, i, t) -> (ndesc, 128)
    g = t2.reshape(8, nfy, 4, nfx, 4)
    g = jnp.transpose(g, (1, 3, 2, 4, 0))
    raw = g.reshape(-1, 128)
    norms = jnp.linalg.norm(raw, axis=1)
    desc = raw / jnp.maximum(norms, 1e-12)[:, None]
    desc = jnp.minimum(desc, 0.2)
    desc = desc / jnp.maximum(jnp.linalg.norm(desc, axis=1), 1e-12)[:, None]
    return desc, norms

def apply_gemm(img):
    x = img
    descs = []
    for scale in range(4):
        bin_size = 4 + 2*scale
        k = _gaussian_kernel(bin_size / MAGNIF)
        sm = _sep_conv2d(x[None], k, edge_pad=True)[0]
        bound = 9 - 3*scale
        d, n = dsift_gemm(sm, bin_size, 3 + scale, bound)
        d = jnp.where((n >= CONTRAST_THRESHOLD)[:, None], d, 0.0)
        descs.append(d)
    all_desc = jnp.concatenate(descs, axis=0)
    return jnp.minimum(jnp.floor(all_desc * 512.0), 255.0).T

B, H, W = 128, 256, 256
rng = np.random.default_rng(0)
# textured images (SIFT is data-dependent via contrast threshold)
xg, yg = np.meshgrid(np.arange(W), np.arange(H))
base = 0.5 + 0.3*np.sin(xg/5.0) + 0.2*np.cos(yg/7.0)
imgs = np.clip(base[None] + 0.05*rng.standard_normal((B, H, W)), 0, 1).astype(np.float32)
imgs = jnp.asarray(imgs)

ext = SIFTExtractor(scale_step=1)
cur = jax.jit(jax.vmap(ext.apply))
new = jax.jit(jax.vmap(apply_gemm))

a = np.asarray(cur(imgs[:4]))
b = np.asarray(new(imgs[:4]))
print("shapes", a.shape, b.shape, flush=True)
diff = np.abs(a - b)
print(f"within +-1: {(diff <= 1.0).mean()*100:.3f}%  max {diff.max()}", flush=True)

def force(x): np.asarray(x.ravel()[:1])
def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter(); force(fn(*args))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:30s} {best*1e3:9.2f} ms wall (RT ~100)", flush=True)

timeit("current SIFT 128 imgs", cur, imgs)
timeit("GEMM SIFT 128 imgs", new, imgs)
