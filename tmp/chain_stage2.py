import time, sys, numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
import bench
from keystone_tpu.ops.images.sift import SIFTExtractor, _sep_conv2d, _gaussian_kernel, MAGNIF
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.core import GrayScaler, PixelScaler

rng = np.random.default_rng(0)
imgs = bench._fixture_images(128, 256)
X = jnp.asarray(imgs)

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=4):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter(); force(fn(*args))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:40s} {best*1e3:9.2f} ms wall", flush=True)

@jax.jit
def rt(s): return s + 1.0
force(rt(jnp.float32(1.0)))
t0=time.perf_counter(); force(rt(jnp.float32(2.0)))
print(f"RT {1e3*(time.perf_counter()-t0):.1f} ms", flush=True)

# full chain
full = bench._build_fv_pipeline(rng, 64, 16).fit().jit_batch()
timeit("full chain", full, X)

# SIFT alone (with gray)
ext = SIFTExtractor(scale_step=1)
gray = jax.jit(jax.vmap(lambda im: GrayScaler().apply(PixelScaler().apply(im))))
Xg = gray(X); force(Xg)
sift_v = jax.jit(jax.vmap(ext.apply))
timeit("SIFT (vmapped, gray input)", sift_v, Xg)

# gaussian smooths alone
@jax.jit
def smooths(x):
    acc = jnp.float32(0)
    for scale in range(4):
        k = _gaussian_kernel((4 + 2*scale) / MAGNIF)
        acc = acc + _sep_conv2d(x, k).sum()
    return acc
timeit("4x gaussian smooth [sum]", smooths, Xg)

# LCS alone
lcs_v = jax.jit(jax.vmap(LCSExtractor(4, 16, 6).apply))
timeit("LCS (vmapped)", lcs_v, X)
