"""Stage breakdown of the flagship featurize on the real chip."""
import time, sys, numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
import bench

rng = np.random.default_rng(0)
imgs = bench._fixture_images(128, 256)
X = jnp.asarray(imgs)
print("batch", X.shape, X.dtype, flush=True)

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

def timeit(name, fn, *args, reps=3):
    force(fn(*args))
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter(); force(fn(*args))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:34s} {best*1e3:9.2f} ms  ({128/best:7.1f} ex/s)", flush=True)
    return best

@jax.jit
def rt(s): return s + 1.0
force(rt(jnp.float32(1.0)))
t0=time.perf_counter(); force(rt(jnp.float32(2.0)))
print(f"RT {1e3*(time.perf_counter()-t0):.1f} ms", flush=True)

# full chain
full = bench._build_fv_pipeline(rng, 64, 16).fit().jit_batch()
timeit("full SIFT+LCS+FV chain", full, X)

# SIFT branch alone (gray + sift + hellinger)
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
from keystone_tpu.ops.stats import SignedHellingerMapper
from keystone_tpu.workflow.api import Pipeline

sift_only = (PixelScaler().and_then(GrayScaler())
             .and_then(SIFTExtractor(scale_step=1))).fit().jit_batch()
timeit("SIFT extract only", sift_only, X)

lcs_only = LCSExtractor(4, 16, 6).to_pipeline().fit().jit_batch()
timeit("LCS extract only", lcs_only, X)

full_sift_branch = bench._build_fv_pipeline(rng, 64, 16)  # rebuild for fresh rng state parity not needed
