import time, sys, numpy as np, jax, jax.numpy as jnp
sys.path.insert(0, "/root/repo")
import bench

rng = np.random.default_rng(0)
imgs = bench._fixture_images(128, 256)
X = jnp.asarray(imgs)
full = bench._build_fv_pipeline(rng, 64, 16).fit().jit_batch()

def force(a):
    np.asarray(jax.tree_util.tree_leaves(a)[0].ravel()[:1])

force(full(X))
for rep in range(4):
    t0 = time.perf_counter()
    outs = [full(X) for _ in range(8)]
    for o in outs: force(o)
    dt = time.perf_counter() - t0
    print(f"8x128 imgs: {dt*1e3:8.1f} ms  -> {8*128/dt:7.1f} ex/s", flush=True)
