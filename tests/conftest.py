"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests run Spark in local[n] mode with multi-partition RDDs
standing in for a cluster (SURVEY.md §4); the equivalent here is
--xla_force_host_platform_device_count=8 so sharding/collective code paths are
exercised without TPU hardware. Must be set before jax initializes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from keystone_tpu.parallel.virtual import provision_devices  # noqa: E402

# Tests always run on the virtual CPU mesh (fast, deterministic, no TPU
# needed) — skip the real-device probe.
provision_devices(8, probe_real=False)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def reset_pipeline_env():
    """Each test gets a fresh global pipeline environment (reference:
    PipelineContext.afterEach calls PipelineEnv.reset)."""
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.parallel import mesh as mesh_lib

    PipelineEnv.get_or_create().reset()
    mesh_lib.set_mesh(None)
    yield
    PipelineEnv.get_or_create().reset()
    mesh_lib.set_mesh(None)


@pytest.fixture
def mesh8():
    """An 8-way data-parallel mesh over the virtual CPU devices."""
    from keystone_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh(n_data=8)
    with mesh_lib.use_mesh(m):
        yield m
