"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests run Spark in local[n] mode with multi-partition RDDs
standing in for a cluster (SURVEY.md §4); the equivalent here is
--xla_force_host_platform_device_count=8 so sharding/collective code paths are
exercised without TPU hardware. Must be set before jax initializes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# tests/ itself, so suites in subdirectories can import shared fixture
# helpers (jpeg_fixtures) regardless of collection order
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from keystone_tpu.parallel.virtual import provision_devices  # noqa: E402

# Tests run on the virtual CPU mesh by default (fast, deterministic, no
# TPU needed). KEYSTONE_TPU_TEST_REAL=1 runs the same suite against the
# real accelerator instead — the hardware-sanity sweep that catches
# TPU-only failures (e.g. DEFAULT-precision f32 matmuls) CPU runs hide.
_REAL = os.environ.get("KEYSTONE_TPU_TEST_REAL") == "1"
if not _REAL:
    provision_devices(8, probe_real=False)
else:
    import jax

    if jax.devices()[0].platform == "cpu":
        raise RuntimeError(
            "KEYSTONE_TPU_TEST_REAL=1 but no accelerator is attached — "
            "this sweep exists to catch hardware-only failures; running "
            "it on CPU would silently prove nothing"
        )

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """One shared gate for @pytest.mark.needs_mesh8 — sharded tests skip
    on single-chip hardware (the KEYSTONE_TPU_TEST_REAL sweep) instead of
    each module rolling its own skipif."""
    import jax

    if len(jax.devices()) >= 8:
        return
    skip = pytest.mark.skip(reason="needs the 8-device (virtual) mesh")
    for item in items:
        if "needs_mesh8" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def reset_pipeline_env():
    """Each test gets a fresh global pipeline environment (reference:
    PipelineContext.afterEach calls PipelineEnv.reset)."""
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.parallel import mesh as mesh_lib

    PipelineEnv.get_or_create().reset()
    mesh_lib.set_mesh(None)
    yield
    PipelineEnv.get_or_create().reset()
    mesh_lib.set_mesh(None)


@pytest.fixture
def mesh8():
    """An 8-way data-parallel mesh over the virtual CPU devices (or
    whatever the real hardware has under KEYSTONE_TPU_TEST_REAL=1)."""
    import jax

    from keystone_tpu.parallel import mesh as mesh_lib

    n = min(8, len(jax.devices())) if _REAL else 8
    m = mesh_lib.make_mesh(n_data=n, devices=jax.devices()[:n])
    with mesh_lib.use_mesh(m):
        yield m
