"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests run Spark in local[n] mode with multi-partition RDDs
standing in for a cluster (SURVEY.md §4); the equivalent here is
--xla_force_host_platform_device_count=8 so sharding/collective code paths are
exercised without TPU hardware. Must be set before jax initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax may be preloaded at interpreter startup (axon platform plugin); the
# env vars above are then too late — force the config directly before any
# backend initialization.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def reset_pipeline_env():
    """Each test gets a fresh global pipeline environment (reference:
    PipelineContext.afterEach calls PipelineEnv.reset)."""
    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.parallel import mesh as mesh_lib

    PipelineEnv.get_or_create().reset()
    mesh_lib.set_mesh(None)
    yield
    PipelineEnv.get_or_create().reset()
    mesh_lib.set_mesh(None)


@pytest.fixture
def mesh8():
    """An 8-way data-parallel mesh over the virtual CPU devices."""
    from keystone_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh(n_data=8)
    with mesh_lib.use_mesh(m):
        yield m
