"""Suppression + baseline mechanics: the two escape hatches must work
(inline `# lint: disable=`, checked-in baseline), round-trip through
files, and go STALE the moment the offending line changes — the
baseline only ever shrinks."""

import json
import textwrap

from keystone_tpu.analysis.core import (
    Baseline,
    FileContext,
    run_analysis,
)
from keystone_tpu.analysis.rules import (
    StrippableAssertRule,
    default_rules,
)

BAD = "def gate(ok):\n    assert ok\n"


def write_pkg(tmp_path, source=BAD):
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(source)
    return pkg


# -- inline suppressions ----------------------------------------------------


def test_trailing_suppression_silences_one_line():
    ctx = FileContext(
        "m.py", "pkg/m.py",
        "def gate(ok):\n"
        "    assert ok  # lint: disable=strippable-assert\n"
        "    assert ok\n",
    )
    fs = list(StrippableAssertRule().check_file(ctx))
    # both raw findings exist; the runner applies suppression
    assert len(fs) == 2
    assert ctx.suppressed("strippable-assert", 2)
    assert not ctx.suppressed("strippable-assert", 3)


def test_standalone_suppression_covers_next_code_line(tmp_path):
    write_pkg(
        tmp_path,
        "def gate(ok):\n"
        "    # lint: disable=strippable-assert\n"
        "    assert ok\n",
    )
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert result.findings == []
    assert result.suppressed == 1


def test_marker_inside_string_literal_is_not_a_suppression(tmp_path):
    # only real COMMENT tokens count: a string containing the marker
    # must not become an unreviewable escape hatch
    write_pkg(
        tmp_path,
        "def gate(ok):\n"
        '    assert ok, "see docs: # lint: disable=strippable-assert"\n',
    )
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert len(result.findings) == 1
    assert result.suppressed == 0


def test_standalone_suppression_skips_justification_comments(tmp_path):
    # README tells authors to justify next to the suppression; the
    # justification comment must not swallow the suppression target
    write_pkg(
        tmp_path,
        "def gate(ok):\n"
        "    # lint: disable=strippable-assert\n"
        "    # justification: exercised only in the debug REPL\n"
        "    assert ok\n",
    )
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert result.findings == []
    assert result.suppressed == 1


def test_trailing_suppression_on_wrapped_statement(tmp_path):
    # black-wrapped call: the comment trails the LAST physical line,
    # the finding anchors to the first — the node's span bridges them
    write_pkg(
        tmp_path,
        "def gate(ok, msg):\n"
        "    assert (\n"
        "        ok\n"
        "    ), msg  # lint: disable=strippable-assert\n",
    )
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert result.findings == []
    assert result.suppressed == 1


def test_with_header_calls_are_visible_to_lock_rules():
    # `with self._lock, fut.result():` blocks while the first lock is
    # held — item expressions must be walked with earlier locks pushed
    from keystone_tpu.analysis.rules import BlockingUnderLockRule

    ctx = FileContext(
        "m.py", "pkg/m.py",
        "import threading\n\n\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def bad(self, fut):\n"
        "        with self._lock, fut.result():\n"
        "            pass\n",
    )
    fs = list(BlockingUnderLockRule().check_file(ctx))
    assert len(fs) == 1
    assert "result" in fs[0].message


def test_suppression_is_per_rule(tmp_path):
    write_pkg(
        tmp_path,
        "def gate(ok):\n"
        "    assert ok  # lint: disable=guarded-by\n",
    )
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert len(result.findings) == 1  # wrong rule name: still fires


# -- baseline round trip ----------------------------------------------------


def test_baseline_round_trip(tmp_path):
    write_pkg(tmp_path)
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert len(result.findings) == 1
    path = tmp_path / "baseline.json"
    Baseline.from_findings(
        result.findings, justification="grandfathered"
    ).save(str(path))

    loaded = Baseline.load(str(path))
    assert len(loaded) == 1
    again = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert again.unbaselined(loaded) == []
    assert loaded.stale_entries(again.findings) == []
    # the file is honest JSON with the justification field
    doc = json.loads(path.read_text())
    assert doc["version"] == 1
    assert doc["findings"][0]["justification"] == "grandfathered"


def test_baseline_goes_stale_when_the_line_changes(tmp_path):
    write_pkg(tmp_path)
    first = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    baseline = Baseline.from_findings(first.findings)

    # fix the offending line: finding disappears, entry is stale
    write_pkg(tmp_path, "def gate(ok):\n    return bool(ok)\n")
    fixed = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert fixed.findings == []
    assert len(baseline.stale_entries(fixed.findings)) == 1

    # a DIFFERENT assert on the same line number is NOT covered by the
    # old entry (identity keys on source text, not line numbers)
    write_pkg(tmp_path, "def gate(ok):\n    assert ok != 1\n")
    changed = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert len(changed.unbaselined(baseline)) == 1


def test_baseline_survives_unrelated_edits_above(tmp_path):
    write_pkg(tmp_path)
    baseline = Baseline.from_findings(
        run_analysis(
            str(tmp_path), ["pkg"], [StrippableAssertRule()]
        ).findings
    )
    # push the assert down two lines; identity keys on line TEXT
    write_pkg(
        tmp_path,
        "import os\n\n\ndef gate(ok):\n    assert ok\n",
    )
    moved = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert len(moved.findings) == 1
    assert moved.unbaselined(baseline) == []


def test_duplicate_lines_distinguished_by_index(tmp_path):
    write_pkg(
        tmp_path,
        "def gate(ok):\n    assert ok\n\n\n"
        "def gate2(ok):\n    assert ok\n",
    )
    result = run_analysis(
        str(tmp_path), ["pkg"], [StrippableAssertRule()]
    )
    assert len(result.findings) == 2
    assert {f.index for f in result.findings} == {0, 1}
    # baselining only the first leaves the second live
    baseline = Baseline.from_findings(result.findings[:1])
    assert len(result.unbaselined(baseline)) == 1


def test_parse_error_becomes_finding(tmp_path):
    write_pkg(tmp_path, "def broken(:\n")
    result = run_analysis(str(tmp_path), ["pkg"], default_rules())
    assert len(result.findings) == 1
    assert result.findings[0].rule == "parse-error"
