"""THE gate: keystone-lint over ``keystone_tpu/`` itself, inside
tier-1. A PR that introduces an unbaselined violation of any contract
rule — an unlocked write to guarded state, blocking work under a lock,
a strippable assert, a zero-stamped degradable series, a hot-path host
sync, fault-catalog drift — fails the normal test suite, not a
separate CI lane. The baseline must stay empty-or-justified: every
entry carries a justification, and stale entries fail too."""

import json
import os

from keystone_tpu.analysis.cli import DEFAULT_BASELINE
from keystone_tpu.analysis.core import Baseline, run_analysis
from keystone_tpu.analysis.rules import default_rules

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def test_keystone_tpu_is_lint_clean():
    result = run_analysis(
        REPO_ROOT, ["keystone_tpu"], default_rules()
    )
    baseline = Baseline.load(
        os.path.join(REPO_ROOT, DEFAULT_BASELINE)
    )
    live = result.unbaselined(baseline)
    assert live == [], (
        "keystone-lint found unbaselined contract violations:\n"
        + "\n".join(f.render() for f in live)
        + "\nFix them, add a justified `# lint: disable=<rule>`, or "
        "(last resort) baseline them with a justification — see "
        "README 'Static analysis'."
    )
    stale = baseline.stale_entries(result.findings)
    assert stale == [], (
        "stale LINT_BASELINE.json entries (the finding was fixed or "
        "its line changed) — delete them so the baseline only "
        f"shrinks:\n{json.dumps(stale, indent=2)}"
    )


def test_baseline_entries_are_justified():
    baseline = Baseline.load(
        os.path.join(REPO_ROOT, DEFAULT_BASELINE)
    )
    unjustified = [
        e for e in baseline.entries
        if not str(e.get("justification", "")).strip()
        or str(e.get("justification", "")).startswith("TODO")
    ]
    assert unjustified == [], (
        "baseline entries without a real justification:\n"
        + json.dumps(unjustified, indent=2)
    )


def test_cli_gate_matches_library_verdict(capsys):
    # the exact command CI runs (bin/smoke-lint.sh) must agree with
    # the library-level run above — exit 0, clean JSON
    from keystone_tpu.analysis.cli import main

    rc = main(["--root", REPO_ROOT, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0, doc["findings"]
    assert doc["clean"] is True
