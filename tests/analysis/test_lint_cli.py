"""CLI contract: exit codes, JSON schema, --write-baseline,
--changed-only plumbing, --list-rules. Everything drives
``cli.main(argv)`` in-process — no subprocess, no jax import."""

import json

import pytest

from keystone_tpu.analysis.cli import main
from keystone_tpu.analysis.rules import ALL_RULES

CLEAN = "def add(a, b):\n    return a + b\n"
DIRTY = "def gate(ok):\n    assert ok\n"


def write_proj(tmp_path, source):
    pkg = tmp_path / "keystone_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "mod.py").write_text(source)
    return tmp_path


def test_clean_tree_exits_zero(tmp_path, capsys):
    write_proj(tmp_path, CLEAN)
    assert main(["--root", str(tmp_path)]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_findings_exit_one_and_render(tmp_path, capsys):
    write_proj(tmp_path, DIRTY)
    assert main(["--root", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "strippable-assert" in out
    assert "keystone_tpu/mod.py:2" in out


def test_json_schema(tmp_path, capsys):
    write_proj(tmp_path, DIRTY)
    assert main(["--root", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert doc["clean"] is False
    assert doc["counts"]["findings"] == 1
    f = doc["findings"][0]
    assert f["rule"] == "strippable-assert"
    assert f["path"] == "keystone_tpu/mod.py"
    assert f["line"] == 2
    assert doc["rules"] == [cls.name for cls in ALL_RULES]


def test_write_baseline_then_clean(tmp_path, capsys):
    write_proj(tmp_path, DIRTY)
    assert main(["--root", str(tmp_path), "--write-baseline"]) == 0
    baseline = tmp_path / "LINT_BASELINE.json"
    assert baseline.exists()
    doc = json.loads(baseline.read_text())
    assert len(doc["findings"]) == 1
    # the default baseline path is picked up on the next run
    capsys.readouterr()
    assert main(["--root", str(tmp_path)]) == 0
    assert "1 baselined" in capsys.readouterr().out


def test_stale_baseline_fails_until_deleted(tmp_path, capsys):
    write_proj(tmp_path, DIRTY)
    assert main(["--root", str(tmp_path), "--write-baseline"]) == 0
    write_proj(tmp_path, CLEAN)  # fixed: entry now stale
    assert main(["--root", str(tmp_path)]) == 1
    assert "stale baseline entry" in capsys.readouterr().out


def test_bad_baseline_is_usage_error(tmp_path, capsys):
    write_proj(tmp_path, CLEAN)
    bad = tmp_path / "LINT_BASELINE.json"
    bad.write_text("{\"nope\": true}")
    assert main(["--root", str(tmp_path)]) == 2


def test_unknown_option_is_usage_error(tmp_path):
    assert main(["--root", str(tmp_path), "--frobnicate"]) == 2


def test_list_rules(capsys):
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for cls in ALL_RULES:
        assert cls.name in out


def test_explicit_paths_limit_the_run(tmp_path):
    root = write_proj(tmp_path, DIRTY)
    (root / "keystone_tpu" / "clean.py").write_text(CLEAN)
    assert main(
        ["--root", str(root), "keystone_tpu/clean.py"]
    ) == 0
    assert main(
        ["--root", str(root), "keystone_tpu/mod.py"]
    ) == 1


def test_nonexistent_path_is_usage_error(tmp_path, capsys):
    # a typo'd path must fail loudly, not lint nothing and exit 0
    write_proj(tmp_path, DIRTY)
    rc = main(["--root", str(tmp_path), "keystone_tpu/engin.py"])
    assert rc == 2
    assert "no such path" in capsys.readouterr().err


def test_changed_only_rejects_explicit_paths(tmp_path, capsys):
    write_proj(tmp_path, CLEAN)
    rc = main(
        ["--root", str(tmp_path), "--changed-only", "keystone_tpu"]
    )
    assert rc == 2
    assert "mutually exclusive" in capsys.readouterr().err


def test_json_files_counts_analyzed_files(tmp_path, capsys):
    root = write_proj(tmp_path, CLEAN)
    (root / "keystone_tpu" / "second.py").write_text(CLEAN)
    assert main(["--root", str(root), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["files"] == 2


def test_write_baseline_rejects_scoped_runs(tmp_path, capsys):
    # a slice regeneration would silently drop other files' entries
    write_proj(tmp_path, DIRTY)
    rc = main(
        ["--root", str(tmp_path), "--write-baseline",
         "keystone_tpu/mod.py"]
    )
    assert rc == 2
    assert "full run" in capsys.readouterr().err
    assert not (tmp_path / "LINT_BASELINE.json").exists()
    assert main(
        ["--root", str(tmp_path), "--write-baseline", "--changed-only"]
    ) == 2


def test_changed_only_without_git_falls_back(tmp_path, capsys):
    # tmp_path is no git repo: --changed-only must warn and lint fully
    write_proj(tmp_path, DIRTY)
    rc = main(["--root", str(tmp_path), "--changed-only"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "needs git" in captured.err


@pytest.mark.parametrize("flag", ["--baseline", "--root"])
def test_dangling_option_argument(flag):
    assert main([flag]) == 2
