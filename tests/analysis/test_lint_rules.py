"""Per-rule fixture snippets: each of the seven rules proven to FIRE
on its defect pattern and to STAY QUIET on the compliant twin. The
snippets are miniature versions of the real incidents the rules
encode (tracer ring swap, build-under-pool-lock, chaos-row asserts,
zero-stamped MFU, per-row delivery slicing, catalog drift, dark
metric families)."""

import textwrap

import pytest

from keystone_tpu.analysis.core import FileContext, Project, run_analysis
from keystone_tpu.analysis.rules import (
    AbsentNotZeroRule,
    BlockingUnderLockRule,
    FaultPointDriftRule,
    GuardedByRule,
    HotPathHostSyncRule,
    MetricFamilyDriftRule,
    StrippableAssertRule,
)


def findings_for(rule, source, rel="pkg/mod.py"):
    ctx = FileContext(rel, rel, textwrap.dedent(source))
    return list(rule.check_file(ctx))


# -- guarded-by -------------------------------------------------------------


GUARDED_CLASS = """
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = []  # guarded-by: _lock
        self._free = {{}}  # guarded-by: _lock

    def mutate(self):
        {body}
"""


@pytest.mark.parametrize(
    "body",
    [
        "self._ring = []",                      # rebind
        "self._ring += [1]",                    # augmented
        "self._free['k'] = 1",                  # item assign
        "self._ring.append(1)",                 # container mutation
        "self._free.setdefault('k', []).append(1)",
        "del self._free['k']",
    ],
)
def test_guarded_by_fires_on_unlocked_writes(body):
    fs = findings_for(
        GuardedByRule(), GUARDED_CLASS.format(body=body)
    )
    assert len(fs) == 1, fs
    assert fs[0].rule == "guarded-by"
    assert "_lock" in fs[0].message


@pytest.mark.parametrize(
    "body",
    [
        "with self._lock:\n            self._ring = []",
        "with self._lock:\n            self._ring.append(1)",
        "x = self._ring",            # reads are not writes
        "n = len(self._free)",
        "x = self._free.get('k')",   # non-mutating method
    ],
)
def test_guarded_by_quiet_on_locked_or_read(body):
    assert findings_for(
        GuardedByRule(), GUARDED_CLASS.format(body=body)
    ) == []


def test_guarded_by_exempts_init_and_locked_suffix():
    src = """
    import threading


    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._ring = []  # guarded-by: _lock
            self._ring = [1]  # re-init is still construction

        def _drop_locked(self):
            self._ring = []
    """
    assert findings_for(GuardedByRule(), src) == []


def test_guarded_by_cross_object_write():
    # the enable_tracing incident: a module function rebuilding a
    # guarded attribute through the global instance
    src = """
    import threading


    class Tracer:
        def __init__(self):
            self._lock = threading.Lock()
            self._ring = []  # guarded-by: _lock


    _global = Tracer()


    def resize_bad(n):
        _global._ring = [None] * n


    def resize_good(n):
        with _global._lock:
            _global._ring = [None] * n
    """
    fs = findings_for(GuardedByRule(), src)
    assert len(fs) == 1
    assert "_global._ring" in fs[0].message


# -- blocking-under-lock ----------------------------------------------------


LOCKED_BODY = """
import threading
import time


class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def run(self, fut, engine, thread):
        {body}
"""


@pytest.mark.parametrize(
    "body",
    [
        "with self._lock:\n            time.sleep(1.0)",
        "with self._lock:\n            fut.result()",
        "with self._lock:\n            engine.warmup(example=1)",
        "with self._lock:\n            thread.join()",
        (
            "with self._lock:\n"
            "            engines = self.build_replacements(None)"
        ),
    ],
)
def test_blocking_under_lock_fires(body):
    fs = findings_for(
        BlockingUnderLockRule(), LOCKED_BODY.format(body=body)
    )
    assert len(fs) == 1, fs
    assert fs[0].rule == "blocking-under-lock"


@pytest.mark.parametrize(
    "body",
    [
        # the fixed shape: build OUTSIDE, re-point under the lock
        (
            "engines = self.build_replacements(None)\n"
            "        with self._lock:\n"
            "            self.e = engines"
        ),
        "time.sleep(1.0)",                      # no lock held
        "with self._lock:\n            x = ', '.join(['a'])",  # str join
        # Condition.wait releases the lock it waits on
        "with self._lock:\n            self._lock.wait(0.05)",
    ],
)
def test_blocking_under_lock_quiet(body):
    assert findings_for(
        BlockingUnderLockRule(), LOCKED_BODY.format(body=body)
    ) == []


# -- strippable-assert ------------------------------------------------------


def test_strippable_assert_fires_outside_tests():
    fs = findings_for(
        StrippableAssertRule(),
        "def gate(ok):\n    assert ok, 'enforced'\n",
        rel="keystone_tpu/serving/bench.py",
    )
    assert len(fs) == 1
    assert fs[0].rule == "strippable-assert"


def test_strippable_assert_quiet_in_tests_and_on_raise():
    assert findings_for(
        StrippableAssertRule(),
        "def test_x():\n    assert 1 == 1\n",
        rel="tests/serving/test_x.py",
    ) == []
    assert findings_for(
        StrippableAssertRule(),
        (
            "def gate(ok):\n"
            "    if not ok:\n"
            "        raise AssertionError('enforced')\n"
        ),
        rel="keystone_tpu/serving/bench.py",
    ) == []


# -- absent-not-zero --------------------------------------------------------


def test_absent_not_zero_fires_on_unlabeled_preregistration():
    src = """
    class Metrics:
        def __init__(self, registry):
            self._mfu = registry.gauge(
                "keystone_serving_mfu", "rolling MFU"
            )
    """
    fs = findings_for(AbsentNotZeroRule(), src)
    assert len(fs) == 1
    assert "pre-registered" in fs[0].message


def test_absent_not_zero_quiet_on_labeled_or_lazy_registration():
    src = """
    class Metrics:
        def __init__(self, registry):
            self._mem = registry.gauge(
                "keystone_device_memory_bytes", "hbm",
                ("device", "kind", "stat"),
            )

        def on_available(self, registry):
            self._mfu = registry.gauge("keystone_serving_mfu", "mfu")
    """
    assert findings_for(AbsentNotZeroRule(), src) == []


def test_absent_not_zero_fires_on_zero_stamp():
    src = """
    def degrade(self):
        self.mfu_gauge.set(0)
    """
    fs = findings_for(AbsentNotZeroRule(), src)
    assert len(fs) == 1
    assert "literal 0" in fs[0].message


def test_absent_not_zero_quiet_on_real_zero():
    # staging bytes: an empty pool is a measured zero, not an unknown
    assert findings_for(
        AbsentNotZeroRule(),
        "def on_swap(self):\n    old.metrics.set_staging_bytes(0)\n",
    ) == []


def test_absent_not_zero_fires_on_none_fallback_emission():
    src = """
    def families(m, mfu):
        return MetricFamily(
            "keystone_serving_mfu", "gauge", "mfu",
            [Sample("", {}, mfu if mfu is not None else 0.0)],
        )
    """
    fs = findings_for(AbsentNotZeroRule(), src)
    assert len(fs) == 1
    assert "zero fallback" in fs[0].message


def test_absent_not_zero_fires_on_inverted_none_fallback():
    # the same defect spelled the other way round must not slip by
    src = """
    def families(m, mfu):
        return MetricFamily(
            "keystone_serving_mfu", "gauge", "mfu",
            [Sample("", {}, 0.0 if mfu is None else mfu)],
        )
    """
    fs = findings_for(AbsentNotZeroRule(), src)
    assert len(fs) == 1
    assert "zero fallback" in fs[0].message


def test_absent_not_zero_quiet_on_one_hot_emission():
    # `1.0 if side == r else 0.0` is a one-hot value, not an absence
    # fallback — the real roofline emission must stay clean
    src = """
    def families(m, r):
        return MetricFamily(
            "keystone_device_roofline_bound", "gauge", "side",
            [Sample("", {}, 1.0 if "compute" == r else 0.0)],
        )
    """
    assert findings_for(AbsentNotZeroRule(), src) == []


# -- hot-path-host-sync -----------------------------------------------------


HOT_MODULES = {
    "hot/engine.py": {"gather_once"},
}


@pytest.mark.parametrize(
    "body",
    [
        "y = float(x)",
        "y = x.item()",
        "y = np.asarray(x)",
        "for i, f in enumerate(futs):\n        f.set_result(x[i])",
    ],
)
def test_host_sync_fires_in_hot_module(body):
    src = f"import numpy as np\n\n\ndef deliver(x, futs):\n    {body}\n"
    fs = findings_for(
        HotPathHostSyncRule(modules=HOT_MODULES), src,
        rel="hot/engine.py",
    )
    assert len(fs) == 1, fs
    assert fs[0].rule == "hot-path-host-sync"


def test_host_sync_quiet_on_allowlisted_point_and_cold_modules():
    src = (
        "import numpy as np\n\n\n"
        "def gather_once(x, futs):\n"
        "    host = np.asarray(x)\n"
        "    for i, f in enumerate(futs):\n"
        "        f.set_result(host[i])\n"
    )
    # allowlisted gather point in the hot module: quiet
    assert findings_for(
        HotPathHostSyncRule(modules=HOT_MODULES), src,
        rel="hot/engine.py",
    ) == []
    # same code outside the designated modules: not in scope
    assert findings_for(
        HotPathHostSyncRule(modules=HOT_MODULES), src,
        rel="cold/util.py",
    ) == []


def test_host_sync_quiet_on_float_of_literal_and_dict_lookup():
    src = (
        "def warm(self, want):\n"
        "    x = float('nan')\n"
        "    for b in want:\n"
        "        self._aot[b] = {}\n"
        "        r = self._aot[b]\n"
    )
    assert findings_for(
        HotPathHostSyncRule(modules=HOT_MODULES), src,
        rel="hot/engine.py",
    ) == []


# -- fault-point-drift ------------------------------------------------------


def drift_project(
    tmp_path,
    catalog=("a.point", "b.point"),
    wired=("a.point", "b.point"),
    readme=("a.point", "b.point"),
    tested=("a.point", "b.point"),
):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    entries = ",\n".join(f'    "{p}": "doc"' for p in catalog)
    (pkg / "faults.py").write_text(
        "FAULT_POINTS = {\n" + entries + ",\n}\n"
    )
    calls = "\n".join(
        f'    fire("{p}", None)' for p in wired
    ) or "    pass"
    (pkg / "hot.py").write_text(
        "from pkg.faults import FAULT_POINTS\n\n\n"
        "def fire(p, ctx):\n    return None\n\n\n"
        "def serve():\n" + calls + "\n"
    )
    rows = "\n".join(f"| `{p}` | effect |" for p in readme)
    (tmp_path / "README.md").write_text(
        "# demo\n\n**Fault-point catalog** table:\n\n"
        "| point | effect |\n|---|---|\n" + rows + "\n\n## Next\n"
    )
    tests = tmp_path / "tests"
    tests.mkdir()
    body = "\n".join(f'    arm("{p}")' for p in tested) or "    pass"
    (tests / "test_chaos.py").write_text(
        "def test_points():\n" + body + "\n"
    )
    return FaultPointDriftRule(
        faults_rel="pkg/faults.py",
        readme_rel="README.md",
        tests_rel="tests",
        package_rel="pkg",
    )


def run_drift(tmp_path, rule):
    result = run_analysis(str(tmp_path), ["pkg"], [rule])
    return [f for f in result.findings if f.rule == "fault-point-drift"]


def test_drift_quiet_when_all_four_agree(tmp_path):
    rule = drift_project(tmp_path)
    assert run_drift(tmp_path, rule) == []


def test_drift_fires_on_readme_missing_point(tmp_path):
    rule = drift_project(tmp_path, readme=("a.point",))
    fs = run_drift(tmp_path, rule)
    assert len(fs) == 1 and "missing from the README" in fs[0].message


def test_drift_fires_on_readme_phantom_point(tmp_path):
    rule = drift_project(
        tmp_path, readme=("a.point", "b.point", "ghost.point")
    )
    fs = run_drift(tmp_path, rule)
    assert len(fs) == 1 and "does not catalog" in fs[0].message


def test_drift_fires_on_unwired_catalog_point(tmp_path):
    rule = drift_project(tmp_path, wired=("a.point",))
    fs = run_drift(tmp_path, rule)
    assert len(fs) == 1 and "no `fire(...)`" in fs[0].message
    assert fs[0].path == "pkg/faults.py"


def test_drift_fires_on_untested_point(tmp_path):
    rule = drift_project(tmp_path, tested=("a.point",))
    fs = run_drift(tmp_path, rule)
    assert len(fs) == 1 and "nowhere under tests/" in fs[0].message


def test_drift_fires_on_wired_uncataloged_point(tmp_path):
    rule = drift_project(
        tmp_path, wired=("a.point", "b.point", "rogue.point")
    )
    fs = run_drift(tmp_path, rule)
    assert len(fs) == 1 and "missing from FAULT_POINTS" in fs[0].message
    assert fs[0].path == "pkg/hot.py"


def test_drift_project_scan_survives_file_slices(tmp_path):
    # a --changed-only-style slice (faults.py only) must still see the
    # call sites in the unchanged files — the wired scan reads the
    # whole package from disk, not the analysis slice
    rule = drift_project(tmp_path)
    result = run_analysis(
        str(tmp_path), ["pkg/faults.py"], [rule]
    )
    assert [
        f for f in result.findings if f.rule == "fault-point-drift"
    ] == []


# -- metric-family-drift ----------------------------------------------------


def family_project(
    tmp_path,
    registered=("keystone_demo_hits_total", "keystone_demo_depth"),
    fstring_field=None,
    readme=("keystone_demo_hits_total", "keystone_demo_depth"),
    with_table=True,
):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    lines = ["reg = object()\n\n\ndef wire(reg):"]
    for fam in registered:
        lines.append(f'    reg.counter("{fam}", "help")')
    if fstring_field:
        lines.append(
            f'    reg.gauge(f"keystone_demo_{{{fstring_field}}}_total",'
            ' "help")'
        )
    if len(lines) == 1:
        lines.append("    pass")
    (pkg / "metrics.py").write_text("\n".join(lines) + "\n")
    if with_table:
        rows = "\n".join(f"| `{f}` | counter | doc |" for f in readme)
        (tmp_path / "README.md").write_text(
            "# demo\n\n**Metric-family catalog** — every exported "
            "family:\n\n| family | kind | meaning |\n|---|---|---|\n"
            + rows + "\n\n## Next\n"
        )
    else:
        (tmp_path / "README.md").write_text("# demo\n\nno table here\n")
    return MetricFamilyDriftRule(
        readme_rel="README.md", package_rel="pkg"
    )


def run_family(tmp_path, rule, paths=("pkg",)):
    result = run_analysis(str(tmp_path), list(paths), [rule])
    return [
        f for f in result.findings if f.rule == "metric-family-drift"
    ]


def test_family_quiet_when_code_and_readme_agree(tmp_path):
    rule = family_project(tmp_path)
    assert run_family(tmp_path, rule) == []


def test_family_fires_on_undocumented_registration(tmp_path):
    rule = family_project(tmp_path, readme=("keystone_demo_depth",))
    fs = run_family(tmp_path, rule)
    assert len(fs) == 1
    assert "keystone_demo_hits_total" in fs[0].message
    assert "missing from the README" in fs[0].message
    assert fs[0].path == "README.md"


def test_family_fires_on_phantom_readme_row(tmp_path):
    rule = family_project(
        tmp_path,
        readme=(
            "keystone_demo_hits_total", "keystone_demo_depth",
            "keystone_demo_ghost",
        ),
    )
    fs = run_family(tmp_path, rule)
    assert len(fs) == 1
    assert "nothing in the package registers" in fs[0].message


def test_family_fires_when_table_missing_entirely(tmp_path):
    rule = family_project(tmp_path, with_table=False)
    fs = run_family(tmp_path, rule)
    assert len(fs) == 1 and "no 'Metric-family catalog'" in fs[0].message


def test_family_fstring_pattern_matches_rows(tmp_path):
    # an f-string family covers every row its wildcard matches: the
    # rows are neither phantom nor is the pattern unmatched
    rule = family_project(
        tmp_path,
        registered=(),
        fstring_field="field",
        readme=(
            "keystone_demo_device_seconds_total",
            "keystone_demo_h2d_bytes_total",
        ),
    )
    assert run_family(tmp_path, rule) == []


def test_family_fstring_pattern_unmatched_fires(tmp_path):
    rule = family_project(
        tmp_path, registered=(), fstring_field="field", readme=()
    )
    fs = run_family(tmp_path, rule)
    assert len(fs) == 1
    assert "matches no row" in fs[0].message
    assert fs[0].path == "pkg/metrics.py"


def test_family_scan_survives_file_slices(tmp_path):
    # slicing the analysis to one unrelated file must not hide the
    # registrations in metrics.py — the scan reads the package from
    # disk like the fault-point rule
    rule = family_project(tmp_path)
    (tmp_path / "pkg" / "other.py").write_text("x = 1\n")
    assert run_family(tmp_path, rule, paths=("pkg/other.py",)) == []
