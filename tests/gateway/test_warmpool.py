"""Warm engine pool: prebuilt-engine swap (build outside the pool
lock), background next-generation rotation, and the gateway lifecycle
riding the AOT executable store end to end."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.gateway.lifecycle import Gateway
from keystone_tpu.gateway.metrics import GatewayMetrics
from keystone_tpu.gateway.pool import EnginePool
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving import aot
from keystone_tpu.serving.aot import AotStore

from gateway_fixtures import D, batch, reference

WARM = jnp.zeros((D,), jnp.float32)


def make_pool(fitted, n_lanes=2, buckets=(4,)):
    return EnginePool(
        lambda name: fitted.compiled(buckets=buckets, name=name),
        n_lanes,
        name="warmpool-test",
        max_delay_ms=2.0,
        metrics=GatewayMetrics(
            registry=MetricsRegistry(), gateway="warmpool-test"
        ),
    )


def test_pool_swap_accepts_prebuilt_engines(fitted):
    pool = make_pool(fitted, n_lanes=2)
    with pool:
        prebuilt = [
            fitted.compiled(buckets=(8,), name=pool.lane_name(i))
            for i in range(2)
        ]
        for eng in prebuilt:
            eng.warmup(example=WARM)
        old = pool.swap(engines=prebuilt)
        assert len(old) == 2
        assert [l.engine for l in pool.lanes] == prebuilt
        xs = batch(6, seed=7)
        futs = [pool.submit(x) for x in xs]
        rows = np.stack([np.asarray(f.result(timeout=30)) for f in futs])
    np.testing.assert_allclose(
        rows, reference(fitted, xs), rtol=1e-5, atol=1e-6
    )


def test_pool_swap_rejects_wrong_prebuilt_count(fitted):
    pool = make_pool(fitted, n_lanes=2)
    with pool:
        lonely = fitted.compiled(buckets=(8,), name="only-one")
        with pytest.raises(ValueError, match="one prebuilt engine"):
            pool.swap(engines=[lonely])
        # the failed swap left the original engines serving
        assert pool.submit(batch(1)[0]).result(timeout=30) is not None


def test_background_swap_rotates_under_traffic(fitted):
    with Gateway(
        fitted, buckets=(4,), n_lanes=2, max_delay_ms=2.0,
        warmup_example=WARM, name="bg-swap",
        registry=MetricsRegistry(),
    ) as gw:
        stop = threading.Event()
        failures = []

        def client():
            while not stop.is_set():
                try:
                    gw.predict(batch(1, seed=3)[0]).result(timeout=30)
                except Exception as e:  # pragma: no cover - fail loud
                    failures.append(e)

        t = threading.Thread(target=client, daemon=True)
        t.start()
        fut = gw.swap_engines((4, 8), background=True)
        assert fut.result(timeout=60) is True
        stop.set()
        t.join(timeout=30)
        assert gw.buckets == (4, 8)
        assert not failures, f"requests failed across the swap: {failures}"
        # traffic still resolves on the rotated engines
        out = gw.predict(batch(1, seed=4)[0]).result(timeout=30)
        assert np.asarray(out).shape == (3,)


def test_background_swap_after_close_is_a_noop(fitted):
    gw = Gateway(
        fitted, buckets=(4,), n_lanes=1, max_delay_ms=2.0,
        warmup_example=WARM, name="bg-closed",
        registry=MetricsRegistry(),
    )
    gw.close()
    fut = gw.swap_engines((4, 8), background=True)
    assert fut.result(timeout=60) is False  # dropped, nothing rotated


def test_gateway_lanes_and_next_generation_ride_the_aot_store(
    fitted, tmp_path, monkeypatch
):
    """The zero-cold-start lifecycle: with the store configured, every
    lane engine (and every next-generation engine a swap builds) warms
    from serialized executables — zero traces after the store is
    populated."""
    from keystone_tpu.parallel import runtime

    monkeypatch.setattr(runtime, "_aot_dir", None)
    monkeypatch.setattr(aot, "_configured", None)
    root = str(tmp_path / "aot")
    assert runtime.setup_aot_cache(root) == root
    store = aot.configured_store()

    # generation 0 populates (misses + saves); lane 1 already hits the
    # entries lane 0 saved moments earlier
    with Gateway(
        fitted, buckets=(4,), n_lanes=2, max_delay_ms=2.0,
        warmup_example=WARM, name="aot-gw-0",
        registry=MetricsRegistry(),
    ):
        pass
    saves0, hits0 = store.saves, store.hits
    assert saves0 >= 1

    # a brand-new "process" (fresh gateway, same store): every lane hits
    with Gateway(
        fitted, buckets=(4,), n_lanes=2, max_delay_ms=2.0,
        warmup_example=WARM, name="aot-gw-1",
        registry=MetricsRegistry(),
    ) as gw:
        assert store.hits >= hits0 + 2
        for lane in gw.pool.lanes:
            assert lane.engine.aot_report()[4]["status"] == "hit"
            assert lane.engine.metrics.compile_count == 0
        hits1 = store.hits
        # the warm pool: a same-bucket background rotation deserializes
        # the next generation instead of compiling it
        t0 = time.perf_counter()
        fut = gw.swap_engines((4,), background=True)
        assert fut.result(timeout=60) is True
        swap_s = time.perf_counter() - t0
        assert store.hits >= hits1 + 2
        for lane in gw.pool.lanes:
            assert lane.engine.metrics.compile_count == 0
        out = gw.predict(batch(1, seed=9)[0]).result(timeout=30)
        assert np.asarray(out).shape == (3,)
        # not a strict perf assert, just a sanity ceiling: a
        # deserialize-based rotation must not take compile-scale time
        assert swap_s < 30
