"""Shared gateway test helpers: a tiny two-layer fitted pipeline (the
same shape tests/serving uses) and its reference apply. A plain module
(not conftest.py) so `import gateway_fixtures` is unambiguous in a
full-suite run."""

import dataclasses

import jax.numpy as jnp
import numpy as np
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Transformer

D = 6


@dataclasses.dataclass(eq=False)
class Affine(Transformer):
    W: object
    b: object

    def apply(self, x):
        return jnp.tanh(x @ self.W + self.b)


def make_fitted():
    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.standard_normal((D, 8)).astype(np.float32))
    w2 = jnp.asarray(rng.standard_normal((8, 3)).astype(np.float32))
    pipe = Affine(w1, jnp.zeros(8, jnp.float32)).and_then(
        Affine(w2, jnp.ones(3, jnp.float32))
    )
    return pipe.fit()


def batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, D)).astype(np.float32)


def reference(fitted, xs):
    return np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(xs))).array()
    )
