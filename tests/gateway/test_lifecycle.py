"""Gateway lifecycle: the live autoscale/swap loop and graceful drain.

The acceptance test here is swap-under-load: responses straddling a
live engine swap must be numerically identical to the pre-swap
engine's, with zero failed requests."""

import threading
import time

import numpy as np
import pytest

from keystone_tpu.gateway import Gateway, Overloaded
from keystone_tpu.gateway.lifecycle import MIN_REBUCKET_OBSERVATIONS
from keystone_tpu.observability.registry import MetricsRegistry

from gateway_fixtures import D, batch, reference


def make_gateway(fitted, **kw):
    kw.setdefault("buckets", (4, 8))
    kw.setdefault("n_lanes", 2)
    kw.setdefault("max_delay_ms", 2.0)
    kw.setdefault("warmup_example", np.zeros(D, np.float32))
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("name", "test-gw")
    return Gateway(fitted, **kw)


def test_predict_matches_reference(fitted):
    with make_gateway(fitted) as gw:
        xs = batch(10, seed=41)
        want = reference(fitted, xs)
        futs = [gw.predict(x) for x in xs]
        rows = np.stack(
            [np.asarray(f.result(timeout=30)) for f in futs]
        )
    np.testing.assert_allclose(rows, want, rtol=1e-5, atol=1e-6)


def test_swap_under_load_zero_failures_identical_outputs(fitted):
    """Acceptance: under concurrent load, a forced live engine swap
    completes with zero failed requests, and every response — before,
    straddling, and after the swap — equals the pre-swap engine's
    output for the same input."""
    n_clients, per_client = 4, 40
    xs = batch(16, seed=42)
    want = reference(fitted, xs)  # the pre-swap engine's outputs
    with make_gateway(fitted) as gw:
        failures = []
        mismatches = []
        started = threading.Barrier(n_clients + 1)

        def client(tid):
            rng = np.random.default_rng(tid)
            started.wait()
            for _ in range(per_client):
                i = int(rng.integers(0, len(xs)))
                try:
                    out = np.asarray(
                        gw.predict(xs[i]).result(timeout=30)
                    )
                except Exception as e:  # pragma: no cover - must not
                    failures.append(e)
                    continue
                if not np.allclose(
                    out, want[i], rtol=1e-5, atol=1e-6
                ):
                    mismatches.append(i)

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_clients)
        ]
        for t in threads:
            t.start()
        started.wait()
        time.sleep(0.02)  # clients in flight
        gw.swap_engines((2, 8))  # build + warm + atomic swap, mid-load
        for t in threads:
            t.join()
        assert not failures, failures[:3]
        assert not mismatches, (
            f"{len(mismatches)} responses diverged across the swap"
        )
        assert gw.metrics.swap_count() == 1
        assert gw.buckets == (2, 8)
        assert all(
            lane.engine.buckets == (2, 8) for lane in gw.pool.lanes
        )


def test_rebucket_needs_evidence_unless_forced(fitted):
    with make_gateway(fitted, buckets=(4, 8)) as gw:
        # no traffic at all: even force falls back to the same buckets
        # but still swaps (the drill semantics)
        assert gw.rebucket() is False
        assert gw.metrics.swap_count() == 0
        for x in batch(3, seed=43):
            gw.predict(x).result(timeout=30)
        # a handful of observations is not evidence
        assert sum(gw.observed_sizes().values()) > 0
        assert gw.rebucket() is False
        assert gw.rebucket(force=True) is True
        assert gw.metrics.swap_count() == 1


def test_rebucket_acts_on_observed_traffic(fitted):
    with make_gateway(
        fitted, buckets=(8,), rebucket_k=2, max_delay_ms=0.5
    ) as gw:
        # all-singleton traffic: the padding-minimal 2-bucket set over
        # sizes {1..} must include a small bucket
        sent = 0
        while sent < MIN_REBUCKET_OBSERVATIONS:
            gw.predict(batch(1, seed=sent)[0]).result(timeout=30)
            sent += 1
        assert gw.rebucket() is True
        assert gw.buckets[-1] == 8  # forced max bucket survives
        assert gw.buckets[0] < 8  # and a tighter bucket appeared
        # idempotent: the proposal now matches the active set
        assert gw.rebucket() is False


def test_rebucket_audit_logs_observed_vs_predicted(fitted):
    """Every swap records a goodput audit: the observed padding
    efficiency under the outgoing buckets (live per-bucket counters)
    next to the model-predicted efficiency of the proposal — the
    auditable trail for ``suggest_buckets`` decisions."""
    from keystone_tpu.serving.autoscale import predicted_efficiency

    with make_gateway(
        fitted, buckets=(8,), rebucket_k=2, max_delay_ms=0.5
    ) as gw:
        assert gw.last_rebucket_audit is None
        for i in range(MIN_REBUCKET_OBSERVATIONS):
            gw.predict(batch(1, seed=i)[0]).result(timeout=30)
        observed_before = gw.observed_goodput()
        assert observed_before["goodput_rows"] >= MIN_REBUCKET_OBSERVATIONS
        # singleton rows through an 8-bucket: efficiency is poor
        assert observed_before["efficiency"] < 0.5
        hist = gw.observed_sizes()
        assert gw.rebucket() is True
        audit = gw.last_rebucket_audit
        assert audit["from_buckets"] == [8]
        assert audit["to_buckets"] == list(gw.buckets)
        assert audit["observed_efficiency_before"] == pytest.approx(
            observed_before["efficiency"], rel=0.2
        )
        # the prediction in the audit is the autoscale model's number
        # for the histogram that drove the proposal
        assert audit["predicted_efficiency_after"] == pytest.approx(
            predicted_efficiency(hist, gw.buckets), rel=0.2
        )
        # the re-bucket it proposed is an actual improvement
        assert (
            audit["predicted_efficiency_after"]
            > audit["observed_efficiency_before"]
        )


def test_maintenance_loop_rebuckets_in_background(fitted):
    with make_gateway(
        fitted, buckets=(8,), rebucket_k=2, max_delay_ms=0.5,
        maintenance_interval_s=0.2,
    ) as gw:
        for i in range(MIN_REBUCKET_OBSERVATIONS + 8):
            gw.predict(batch(1, seed=i)[0]).result(timeout=30)
        deadline = time.perf_counter() + 10
        while (
            gw.metrics.swap_count() == 0
            and time.perf_counter() < deadline
        ):
            time.sleep(0.05)
        assert gw.metrics.swap_count() >= 1
        assert gw.buckets[0] < 8


def test_graceful_close_flips_ready_then_drains(fitted):
    gw = make_gateway(fitted)
    assert gw.ready
    fut = gw.predict(batch(1, seed=44)[0])
    gw.close()
    assert not gw.ready
    # the admitted request resolved during the drain
    assert np.asarray(fut.result(timeout=5)).shape == (3,)
    with pytest.raises(Overloaded) as e:
        gw.predict(batch(1)[0])
    assert e.value.reason == "closed"
    gw.close()  # idempotent


def test_ready_gauge_tracks_lifecycle(fitted):
    reg = MetricsRegistry()
    gw = make_gateway(fitted, registry=reg, name="gauge-gw")
    g = reg.gauge("keystone_gateway_ready", labelnames=("gateway",))
    assert g.get(("gauge-gw",)) == 1.0
    gw.close()
    assert g.get(("gauge-gw",)) == 0.0


def test_beyond_capacity_traffic_sheds_typed_admitted_resolves(fitted):
    """Overload semantics: flooding past the queue bound sheds the
    excess IMMEDIATELY with typed Overloaded(queue_full) errors, while
    every admitted request still resolves correctly."""
    with make_gateway(
        fitted, n_lanes=1, max_pending=8, lane_capacity=2,
        max_delay_ms=20.0, name="shed-gw",
    ) as gw:
        xs = batch(8, seed=45)
        want = reference(fitted, xs)
        admitted, shed = [], []
        for i in range(120):
            j = i % len(xs)
            try:
                admitted.append((j, gw.predict(xs[j])))
            except Overloaded as e:
                assert e.reason == "queue_full"
                shed.append(e)
        assert shed, "flood never hit the queue bound"
        assert len(admitted) >= 8
        for j, fut in admitted:
            np.testing.assert_allclose(
                np.asarray(fut.result(timeout=30)), want[j],
                rtol=1e-5, atol=1e-6,
            )
        assert gw.metrics.shed_count("queue_full") == len(shed)
        assert gw.metrics.outcome_count("ok") == len(admitted)
