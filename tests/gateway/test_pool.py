"""EnginePool: least-loaded routing across shared-nothing lanes,
retry-to-another-lane on lane failure, per-lane health benching, and
the atomic build-then-swap contract."""

import numpy as np
import pytest

from keystone_tpu.gateway.metrics import GatewayMetrics
from keystone_tpu.gateway.pool import UNHEALTHY_AFTER, EnginePool
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.metrics import ServingMetrics

from gateway_fixtures import D, batch, reference


def make_pool(fitted, n_lanes=2, buckets=(4,), **kw):
    metrics = GatewayMetrics(
        registry=MetricsRegistry(), gateway="pool-test"
    )
    pool = EnginePool(
        lambda name: fitted.compiled(buckets=buckets, name=name),
        n_lanes,
        name="pool-test",
        max_delay_ms=2.0,
        metrics=metrics,
        **kw,
    )
    return pool, metrics


class BrokenEngine:
    """Duck-typed engine whose every dispatch fails — a dead lane."""

    def __init__(self, name="broken"):
        self.name = name
        self.max_bucket = 4
        self.buckets = (4,)
        self.metrics = ServingMetrics()

    def apply(self, data, sync=False, owned=False):
        raise RuntimeError("lane hardware gone")


def test_requests_fan_across_lanes_and_resolve(fitted):
    pool, _ = make_pool(fitted, n_lanes=2)
    xs = batch(16, seed=31)
    want = reference(fitted, xs)
    with pool:
        futs = [pool.submit(x) for x in xs]
        rows = np.stack(
            [np.asarray(f.result(timeout=30)) for f in futs]
        )
    np.testing.assert_allclose(rows, want, rtol=1e-5, atol=1e-6)
    served = [l.engine.metrics.examples.total for l in pool.lanes]
    assert sum(served) == 16
    assert all(s > 0 for s in served)  # least-loaded used BOTH lanes


def test_lane_failure_retries_on_another_lane(fitted):
    pool, metrics = make_pool(fitted, n_lanes=2)
    with pool:
        pool.lanes[0].batcher.swap_engine(BrokenEngine())
        xs = batch(12, seed=32)
        want = reference(fitted, xs)
        futs = [pool.submit(x) for x in xs]
        rows = np.stack(
            [np.asarray(f.result(timeout=30)) for f in futs]
        )
        # every request resolved correctly despite a dead lane...
        np.testing.assert_allclose(rows, want, rtol=1e-5, atol=1e-6)
        # ...because failures retried onto the healthy lane
        assert metrics.retry_count() >= 1
        assert pool.lanes[1].engine.metrics.examples.total == 12
        # and the dead lane got benched after consecutive failures
        assert not pool.lanes[0].healthy
        assert pool.healthy_lanes() == 1


def test_health_restores_on_success(fitted):
    pool, _ = make_pool(fitted, n_lanes=2)
    with pool:
        lane = pool.lanes[0]
        for _ in range(UNHEALTHY_AFTER):
            lane.mark_failed()
        assert not lane.healthy
        lane.mark_ok()
        assert lane.healthy


def test_request_caused_errors_never_bench_lanes(fitted):
    """A deterministically-bad request (fails on every lane it touches)
    charges NO lane's health — malformed client traffic can't starve
    well-formed requests by benching the pool."""
    pool, _ = make_pool(fitted, n_lanes=2)
    with pool:
        bad = np.zeros(D + 3, np.float32)  # wrong feature dim
        for _ in range(UNHEALTHY_AFTER + 2):
            with pytest.raises(Exception):
                pool.submit(bad).result(timeout=30)
        assert pool.healthy_lanes() == 2  # nobody benched
        # and good traffic still flows at full capacity
        out = pool.submit(batch(1, seed=37)[0]).result(timeout=30)
        assert np.asarray(out).shape == (3,)


def test_swap_is_atomic_on_factory_failure(fitted):
    pool, metrics = make_pool(fitted, n_lanes=2)
    with pool:
        old_engines = [l.engine for l in pool.lanes]

        calls = []

        def bad_factory(name):
            calls.append(name)
            if len(calls) == 2:  # second lane's build explodes
                raise RuntimeError("OOM compiling replacement")
            return fitted.compiled(buckets=(2, 4), name=name)

        with pytest.raises(RuntimeError):
            pool.swap(bad_factory)
        # the failed swap touched NOTHING: old engines still serving
        assert [l.engine for l in pool.lanes] == old_engines
        assert metrics.swap_count() == 0
        out = pool.submit(batch(1, seed=33)[0]).result(timeout=30)
        assert np.asarray(out).shape == (3,)


def test_swap_replaces_every_lane_and_counts(fitted):
    pool, metrics = make_pool(fitted, n_lanes=2)
    with pool:
        xs = batch(6, seed=34)
        want = reference(fitted, xs)
        for x in xs[:3]:
            pool.submit(x).result(timeout=30)
        old = pool.swap(
            lambda name: fitted.compiled(buckets=(2, 8), name=name),
            warmup_example=np.zeros(D, np.float32),
        )
        assert len(old) == 2
        assert all(l.engine.buckets == (2, 8) for l in pool.lanes)
        assert metrics.swap_count() == 1
        rows = np.stack(
            [
                np.asarray(pool.submit(x).result(timeout=30))
                for x in xs[3:]
            ]
        )
        np.testing.assert_allclose(rows, want[3:], rtol=1e-5, atol=1e-6)


def test_closed_pool_rejects(fitted):
    pool, _ = make_pool(fitted, n_lanes=1)
    pool.close()
    with pytest.raises(RuntimeError):
        pool.submit(batch(1)[0])
    with pytest.raises(RuntimeError):
        pool.swap()


def test_lane_capacity_and_free_accounting(fitted):
    pool, _ = make_pool(fitted, n_lanes=2, lane_capacity=3)
    with pool:
        assert pool.free_capacity() == 6
        assert pool.total_load() == 0
        futs = [pool.submit(x) for x in batch(4, seed=35)]
        for f in futs:
            f.result(timeout=30)
        assert pool.total_load() == 0  # all resolved -> load drained


def test_retry_is_bounded_not_a_lane_tour(fitted):
    """A deterministically-bad request executes on at most
    1 + max_retries lanes (default: two), not every lane in the pool."""
    attempts = []

    class CountingBrokenEngine(BrokenEngine):
        def apply(self, data, sync=False, owned=False):
            attempts.append(self.name)
            raise RuntimeError("always fails")

    pool, metrics = make_pool(fitted, n_lanes=4)
    with pool:
        for lane in pool.lanes:
            lane.batcher.swap_engine(
                CountingBrokenEngine(f"broken{lane.index}")
            )
        fut = pool.submit(batch(1, seed=36)[0])
        with pytest.raises(RuntimeError):
            fut.result(timeout=30)
        assert len(attempts) == 2  # first attempt + exactly one retry
        assert metrics.retry_count() == 1


def test_lane_capacity_follows_engine_swap(fitted):
    """An unpinned lane's capacity tracks the CURRENT engine's window
    size — a rebucket to larger buckets widens the lane instead of
    throttling at the old bucket's scale."""
    pool, _ = make_pool(fitted, n_lanes=1, buckets=(4,))
    with pool:
        assert pool.lanes[0].capacity == 8  # 2 windows of 4
        pool.swap(lambda name: fitted.compiled(buckets=(16,), name=name))
        assert pool.lanes[0].capacity == 32  # follows the new bucket


def test_submit_time_raise_never_benches_and_retries(fitted):
    """An example whose spec can't even be computed (ragged pytree)
    raises at lane-submit time; it must retry like a dispatch failure
    and charge no lane's health."""
    pool, metrics = make_pool(fitted, n_lanes=2)
    with pool:
        ragged = [[1.0, 2.0], [3.0]]  # np.asarray raises at spec time
        for _ in range(UNHEALTHY_AFTER + 1):
            with pytest.raises(Exception):
                pool.submit(ragged).result(timeout=30)
        assert pool.healthy_lanes() == 2
        assert metrics.retry_count() >= 1  # the retry path engaged
        out = pool.submit(batch(1, seed=38)[0]).result(timeout=30)
        assert np.asarray(out).shape == (3,)
