import pytest

from gateway_fixtures import make_fitted


@pytest.fixture(scope="session")
def fitted():
    return make_fitted()
