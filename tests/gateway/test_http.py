"""Gateway HTTP frontend end to end (ephemeral port, CPU backend,
mirroring tests/observability/test_admin.py): /predict round-trip,
/metrics scrape with the gateway series, readiness-vs-liveness
semantics, the forced-swap route, and the admit -> coalesce ->
dispatch span chain."""

import itertools
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.observability import (
    disable_tracing,
    enable_tracing,
    get_global_registry,
    get_tracer,
)

from gateway_fixtures import D, batch, make_fitted, reference


_gw_ids = itertools.count()


@pytest.fixture
def served():
    """A live gateway + frontend on an ephemeral port. Uses the GLOBAL
    registry (like production) with a unique gateway name per test so
    counter assertions never see another test's series."""
    fitted = make_fitted()
    gw = Gateway(
        fitted,
        buckets=(4, 8),
        n_lanes=2,
        max_delay_ms=2.0,
        warmup_example=np.zeros(D, np.float32),
        name=f"http-gw{next(_gw_ids)}",
    )
    srv = GatewayServer(gw, port=0).start()
    yield fitted, gw, srv
    gw.close()
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(srv.url(path), timeout=15) as resp:
        return resp.status, resp.read().decode("utf-8")


def _post(srv, path, doc):
    req = urllib.request.Request(
        srv.url(path),
        data=json.dumps(doc).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_predict_round_trip_and_metrics_scrape(served):
    """Acceptance: POST /predict round-trips through admission ->
    lanes -> micro-batch -> engine, and GET /metrics shows the gateway
    series (typed counters + native histograms) alongside the lanes'
    engine series."""
    fitted, gw, srv = served
    xs = batch(4, seed=51)
    want = reference(fitted, xs)
    status, doc = _post(srv, "/predict", {"instances": xs.tolist()})
    assert status == 200
    np.testing.assert_allclose(
        np.asarray(doc["predictions"], np.float32), want,
        rtol=1e-4, atol=1e-5,
    )

    _, metrics = _get(srv, "/metrics")
    name = gw.name
    for line in [
        f'keystone_gateway_requests_total{{gateway="{name}",status="ok"}} 4',
        f'keystone_gateway_ready{{gateway="{name}"}} 1',
        '# TYPE keystone_gateway_request_latency_seconds histogram',
        f'keystone_gateway_request_latency_seconds_bucket'
        f'{{gateway="{name}",le="+Inf"}} 4',
        f'keystone_gateway_request_latency_seconds_count'
        f'{{gateway="{name}"}} 4',
        'keystone_gateway_queue_wait_seconds_bucket',
        # the shared-nothing lanes export per-engine serving series
        f'keystone_serving_examples_total{{engine="{name}-lane0"}}',
        f'keystone_serving_examples_total{{engine="{name}-lane1"}}',
    ]:
        assert line in metrics, f"missing {line!r} in:\n{metrics}"


def test_readyz_is_readiness_not_liveness(served):
    _, gw, srv = served
    status, body = _get(srv, "/readyz")
    assert (status, body) == (200, "ok\n")
    gw.close()
    # draining: alive (healthz 200) but NOT ready (readyz 503)
    status, _ = _get(srv, "/healthz")
    assert status == 200
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/readyz")
    assert e.value.code == 503
    assert e.value.read().decode() == "draining\n"
    # the fleet router's probe reads routing load off the same
    # response — present on the draining answer too
    assert e.value.headers.get("X-Keystone-Load") == "0"


def test_readyz_load_report_header(served):
    """Every /readyz answer carries X-Keystone-Load (queued + in-lane
    requests) — the header the fleet registry's probes parse."""
    _, gw, srv = served
    with urllib.request.urlopen(srv.url("/readyz"), timeout=15) as resp:
        load = resp.headers.get("X-Keystone-Load")
    assert load is not None
    assert float(load) == 0.0  # idle gateway
    _post(srv, "/predict", {"instances": batch(2, seed=52).tolist()})
    with urllib.request.urlopen(srv.url("/readyz"), timeout=15) as resp:
        assert float(resp.headers.get("X-Keystone-Load")) >= 0.0


def test_predict_after_drain_is_503_typed(served):
    _, gw, srv = served
    gw.close()
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/predict", {"instances": [batch(1)[0].tolist()]})
    assert e.value.code == 503
    doc = json.loads(e.value.read())
    assert doc["error"] == "overloaded"
    assert doc["reason"] == "closed"


def test_bad_requests_are_400(served):
    _, _, srv = served
    for body in [{"instances": []}, {"nope": 1}, {"instances": "x"}]:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv, "/predict", body)
        assert e.value.code == 400


def test_forced_swap_via_http_keeps_serving(served):
    fitted, gw, srv = served
    xs = batch(2, seed=52)
    want = reference(fitted, xs)
    _post(srv, "/predict", {"instances": xs.tolist()})
    status, doc = _post(srv, "/swap", {})
    assert status == 200 and doc["swapped"] is True
    status, doc = _post(srv, "/predict", {"instances": xs.tolist()})
    assert status == 200
    np.testing.assert_allclose(
        np.asarray(doc["predictions"], np.float32), want,
        rtol=1e-4, atol=1e-5,
    )
    assert gw.metrics.swap_count() == 1
    _, metrics = _get(srv, "/metrics")
    assert (
        f'keystone_gateway_engine_swaps_total{{gateway="{gw.name}"}} 1'
        in metrics
    )


def test_admit_span_parents_coalesce_dispatch_chain():
    """The gateway.admit span (client thread) parents the window's
    microbatch.coalesce span (dispatcher thread), which parents the
    lane pipeline's per-stage spans (each on its own stage thread) —
    the full cross-thread chain in one trace. With serial lanes
    (pipeline_depth=0) the same chain ends in serving.dispatch."""
    tracer = enable_tracing()
    tracer.clear()
    try:
        fitted = make_fitted()
        with Gateway(
            fitted, buckets=(4,), n_lanes=1, max_delay_ms=2.0,
            warmup_example=np.zeros(D, np.float32), name="span-gw",
        ) as gw:
            gw.predict(batch(1, seed=53)[0]).result(timeout=30)
        spans = {s.name: s for s in get_tracer().recent()}
        admit = spans["gateway.admit"]
        coalesce = spans["microbatch.coalesce"]
        assert coalesce.parent_id == admit.span_id
        for stage in ("host_prep", "upload", "compute", "deliver"):
            stage_span = spans[f"pipeline.{stage}"]
            assert stage_span.parent_id == coalesce.span_id
            assert stage_span.trace_id == admit.trace_id
        assert admit.attrs["gateway"] == "span-gw"

        # serial lanes keep the original admit -> coalesce ->
        # serving.dispatch chain
        tracer.clear()
        with Gateway(
            fitted, buckets=(4,), n_lanes=1, max_delay_ms=2.0,
            warmup_example=np.zeros(D, np.float32),
            name="span-gw-serial", pipeline_depth=0,
        ) as gw:
            gw.predict(batch(1, seed=54)[0]).result(timeout=30)
        spans = {s.name: s for s in get_tracer().recent()}
        dispatch = spans["serving.dispatch"]
        assert dispatch.parent_id == spans["microbatch.coalesce"].span_id
    finally:
        disable_tracing()
        get_tracer().clear()


def test_metrics_route_serves_global_registry(served):
    _, _, srv = served
    assert srv.registry is get_global_registry()
    _, body = _get(srv, "/metrics")
    assert body.endswith("\n")


def test_bad_deadline_ms_is_400(served):
    _, _, srv = served
    for bad in ["fast", -5, 0, True]:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv, "/predict", {
                "instances": [batch(1)[0].tolist()], "deadline_ms": bad,
            })
        assert e.value.code == 400, f"deadline_ms={bad!r}"


def test_request_log_emits_structured_json_lines(capsys):
    """--request-log: one JSON line per /predict instance on stdout
    with status, latency_ms, lane, and the trace id that keys the
    flight recorder — greppable forensics from the process log."""
    tracer = enable_tracing()
    tracer.clear()
    gw = Gateway(
        make_fitted(), buckets=(4,), n_lanes=2, max_delay_ms=2.0,
        warmup_example=np.zeros(D, np.float32),
        name=f"http-log-gw{next(_gw_ids)}",
    )
    srv = GatewayServer(gw, port=0, request_log=True).start()
    try:
        xs = batch(2, seed=54)
        status, _ = _post(srv, "/predict", {"instances": xs.tolist()})
        assert status == 200
        lines = [
            json.loads(ln)
            for ln in capsys.readouterr().out.splitlines()
            if ln.startswith('{"ts"')
        ]
        assert len(lines) == 2
        for line in lines:
            assert line["path"] == "/predict"
            assert line["status"] == 200
            assert line["latency_ms"] > 0
            assert line["lane"] in (0, 1)
            assert (
                isinstance(line["trace_id"], str)
                and len(line["trace_id"]) == 32
            )
        # the logged trace ids are real: the tracer knows their spans
        for line in lines:
            spans = get_tracer().spans_for_trace(line["trace_id"])
            assert any(s.name == "gateway.admit" for s in spans)
        # error path logs too (draining -> 503 closed)
        gw.close()
        with pytest.raises(urllib.error.HTTPError):
            _post(srv, "/predict", {"instances": xs.tolist()})
        err_lines = [
            json.loads(ln)
            for ln in capsys.readouterr().out.splitlines()
            if ln.startswith('{"ts"')
        ]
        assert any(
            ln["status"] == 503 and ln["error"] == "closed"
            for ln in err_lines
        )
    finally:
        gw.close()
        srv.stop()
        disable_tracing()
        tracer.clear()


def test_request_log_off_by_default(served, capsys):
    _, _, srv = served
    assert srv.request_log is False
    _post(srv, "/predict", {"instances": batch(1, seed=55).tolist()})
    out = capsys.readouterr().out
    assert '"path": "/predict"' not in out


def test_gateway_serves_slz_and_debugz(served):
    """Single-port deployments get the forensic surfaces from the
    gateway frontend itself (mirroring the admin endpoint)."""
    _, _, srv = served
    _, slz = _get(srv, "/slz")
    assert "slos" in json.loads(slz)
    _, debugz = _get(srv, "/debugz")
    assert "records" in json.loads(debugz)
    # error parity with the admin endpoint (same shared routing):
    # chrome format without a trace id is a 400, unknown trace a 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/debugz?format=chrome")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv, "/debugz?trace_id=deadbeef&format=chrome")
    assert e.value.code == 404
