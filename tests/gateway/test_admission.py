"""AdmissionController policy unit tests against a stub pool: bounded
queue, typed sheds (queue_full / deadline / expired / closed), deadline
propagation, and the queue-wait metrics."""

import threading
import time
from concurrent.futures import Future

import pytest

from keystone_tpu.gateway.admission import AdmissionController, Overloaded
from keystone_tpu.gateway.metrics import GatewayMetrics
from keystone_tpu.observability.registry import MetricsRegistry


class FakePool:
    """A pool whose capacity and completions the test controls."""

    def __init__(self, capacity=0):
        self.capacity = capacity
        self.submitted = []
        self._listeners = []
        self._lock = threading.Lock()

    def add_free_listener(self, fn):
        self._listeners.append(fn)

    def free_capacity(self):
        return self.capacity

    def total_load(self):
        with self._lock:
            return len([f for f in self.submitted if not f[1].done()])

    def submit(self, example, parent_span_id=None):
        fut = Future()
        with self._lock:
            self.submitted.append((example, fut))
        return fut

    def open_capacity(self, n=1_000_000):
        self.capacity = n
        for fn in self._listeners:
            fn()

    def resolve_all(self, value="ok"):
        with self._lock:
            pending = [f for _, f in self.submitted if not f.done()]
        for f in pending:
            f.set_result(value)


def make_admission(pool, **kw):
    metrics = GatewayMetrics(
        registry=MetricsRegistry(), gateway=kw.pop("name", "test-gw")
    )
    return AdmissionController(pool, metrics=metrics, **kw), metrics


def test_queue_full_sheds_with_typed_error():
    pool = FakePool(capacity=0)  # nothing drains: queue must bound
    adm, metrics = make_admission(pool, max_pending=2)
    try:
        adm.submit("a")
        adm.submit("b")
        with pytest.raises(Overloaded) as e:
            adm.submit("c")
        assert e.value.reason == "queue_full"
        assert e.value.queue_depth == 2
        assert metrics.shed_count("queue_full") == 1
        assert metrics.outcome_count("shed") == 1
    finally:
        pool.open_capacity()
        adm.close()
        pool.resolve_all()


def test_routes_when_capacity_frees_and_records_queue_wait():
    pool = FakePool(capacity=0)
    adm, metrics = make_admission(pool)
    fut = adm.submit("x")
    time.sleep(0.05)
    assert not pool.submitted  # held in the admission queue
    pool.open_capacity()
    deadline = time.perf_counter() + 5
    while not pool.submitted and time.perf_counter() < deadline:
        time.sleep(0.005)
    assert pool.submitted and pool.submitted[0][0] == "x"
    pool.resolve_all("result")
    assert fut.result(timeout=5) == "result"
    assert metrics.outcome_count("ok") == 1
    # the ~50ms queue hold landed in the queue-wait histogram
    assert metrics.queue_wait.get_count(("test-gw",)) == 1
    assert metrics.request_latency.get_count(("test-gw",)) == 1
    adm.close()


def test_deadline_expired_in_queue_is_shed_at_handoff():
    pool = FakePool(capacity=0)
    adm, metrics = make_admission(pool)
    fut = adm.submit("x", deadline_ms=30.0)
    time.sleep(0.1)  # deadline dies while queued
    pool.open_capacity()
    with pytest.raises(Overloaded) as e:
        fut.result(timeout=5)
    assert e.value.reason == "expired"
    assert metrics.shed_count("expired") == 1
    assert not pool.submitted  # no engine time spent on a dead request
    adm.close()


def test_estimated_wait_sheds_undeliverable_deadlines():
    pool = FakePool(capacity=0)
    adm, metrics = make_admission(pool, max_pending=1000)
    # seed the completion-rate estimator: 10 completions over ~1s
    # -> ~10/s; with 50 queued the estimated wait is ~5s
    now = time.perf_counter()
    with adm._comp_lock:
        for i in range(10):
            adm._completions.append(now - 1.0 + i * 0.1)
    for _ in range(50):
        adm.submit("bulk")  # no deadline: always admitted
    est = adm.estimated_wait_s()
    assert est is not None and est > 1.0
    with pytest.raises(Overloaded) as e:
        adm.submit("urgent", deadline_ms=10.0)
    assert e.value.reason == "deadline"
    assert e.value.est_wait_s == pytest.approx(est, rel=0.5)
    assert metrics.shed_count("deadline") == 1
    # a deadline the estimate CAN meet is admitted
    adm.submit("patient", deadline_ms=60_000.0)
    pool.open_capacity()
    adm.close()
    pool.resolve_all()


def test_closed_rejects_new_but_drains_admitted():
    pool = FakePool(capacity=0)
    adm, metrics = make_admission(pool)
    fut = adm.submit("queued-before-close")
    closer = threading.Thread(target=adm.close)
    closer.start()
    time.sleep(0.05)
    with pytest.raises(Overloaded) as e:
        adm.submit("late")
    assert e.value.reason == "closed"
    assert metrics.shed_count("closed") == 1
    # the already-admitted request still routes during the drain
    pool.open_capacity()
    closer.join(timeout=5)
    assert not closer.is_alive()
    pool.resolve_all("drained")
    assert fut.result(timeout=5) == "drained"


def test_lane_error_counts_as_error_outcome():
    pool = FakePool(capacity=10)
    adm, metrics = make_admission(pool)
    fut = adm.submit("x")
    deadline = time.perf_counter() + 5
    while not pool.submitted and time.perf_counter() < deadline:
        time.sleep(0.005)
    pool.submitted[0][1].set_exception(RuntimeError("lane died"))
    with pytest.raises(RuntimeError):
        fut.result(timeout=5)
    assert metrics.outcome_count("error") == 1
    adm.close()


def test_max_pending_validation():
    with pytest.raises(ValueError):
        make_admission(FakePool(), max_pending=0)


# -- SLO-pressure early shed -----------------------------------------------


def test_slo_pressure_sheds_before_queue_full():
    """The burn watchdog's lever: under pressure the EFFECTIVE queue
    bound shrinks, arrivals past it shed with the dedicated
    slo_pressure reason, and the hard queue_full bound still owns the
    truly-full case."""
    pool = FakePool(capacity=0)
    adm, metrics = make_admission(pool, max_pending=8)
    try:
        adm.set_pressure(0.75)  # effective bound: 8 * 0.25 = 2
        assert adm.effective_max_pending == 2
        adm.submit("a")
        adm.submit("b")
        with pytest.raises(Overloaded) as e:
            adm.submit("c")
        assert e.value.reason == "slo_pressure"
        assert metrics.shed_count("slo_pressure") == 1
        assert metrics.outcome_count("shed") == 1
        # releasing the pressure restores the full bound immediately
        adm.set_pressure(0.0)
        assert adm.effective_max_pending == 8
        adm.submit("c")
        assert adm.queue_depth == 3
    finally:
        pool.open_capacity()
        adm.close()
        pool.resolve_all()


def test_pressure_clamped_and_never_below_one_slot():
    pool = FakePool(capacity=0)
    adm, _ = make_admission(pool, max_pending=4)
    try:
        adm.set_pressure(99.0)  # clamped to 1.0
        assert adm.pressure == 1.0
        assert adm.effective_max_pending == 1  # never zero
        adm.set_pressure(-3.0)
        assert adm.pressure == 0.0
        assert adm.effective_max_pending == 4
    finally:
        pool.open_capacity()
        adm.close()


def test_trace_id_rides_the_returned_future():
    from keystone_tpu.observability.tracing import (
        disable_tracing,
        enable_tracing,
    )

    pool = FakePool(capacity=0)
    adm, _ = make_admission(pool, max_pending=4)
    tracer = enable_tracing()
    try:
        fut = adm.submit("a")
        assert isinstance(fut.trace_id, str) and len(fut.trace_id) == 32
    finally:
        disable_tracing()
        tracer.clear()
        pool.open_capacity()
        adm.close()
        pool.resolve_all()


def test_finish_feeds_flight_recorder_on_error():
    """An errored request is tail-sampled no matter how fast it was."""
    from keystone_tpu.observability.flight import FlightRecorder
    from keystone_tpu.observability.tracing import Tracer

    pool = FakePool(capacity=1_000_000)
    flight = FlightRecorder(
        tracer=Tracer(), latency_threshold_s=1e9,
        registry=MetricsRegistry(),
    )
    metrics = GatewayMetrics(
        registry=MetricsRegistry(), gateway="flight-gw"
    )
    adm = AdmissionController(
        pool, max_pending=4, metrics=metrics, name="flight-gw",
        flight=flight, forensic_threshold_s=1e9,
    )
    try:
        fut = adm.submit("a")
        # resolve the lane future with an error -> _finish captures
        deadline = time.perf_counter() + 5
        while not pool.submitted and time.perf_counter() < deadline:
            time.sleep(0.01)
        pool.submitted[0][1].set_exception(RuntimeError("lane died"))
        with pytest.raises(RuntimeError):
            fut.result(timeout=5)
        deadline = time.perf_counter() + 5
        while not flight.records() and time.perf_counter() < deadline:
            time.sleep(0.01)
        (record,) = flight.records()
        assert record.reason == "error"
        assert record.attrs["gateway"] == "flight-gw"
        assert "lane died" in record.attrs["error"]
    finally:
        adm.close()
        pool.resolve_all()
