"""The end-to-end forensic chain (ISSUE 4 acceptance): one injected
over-SLO request (a) raises the fast-window burn gauge and flips
admission to early-shed, (b) is captured with its full span tree at
/debugz and round-trips to a valid Chrome trace, (c) appears as an
exemplar trace_id on the latency histogram at /metrics, and (d) its
spans arrive at a stub in-process OTLP collector — stdlib only."""

import itertools
import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from keystone_tpu.gateway import Gateway, GatewayServer, Overloaded
from keystone_tpu.observability import (
    OtlpSpanExporter,
    disable_tracing,
    enable_tracing,
    get_tracer,
)

from gateway_fixtures import D, batch, make_fitted

_ids = itertools.count()


class StubOtlpCollector:
    """Minimal in-process OTLP/HTTP collector: records POSTed spans."""

    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length))
                with outer._lock:
                    for rs in body["resourceSpans"]:
                        for ss in rs["scopeSpans"]:
                            outer.spans.extend(ss["spans"])
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        ).start()

    @property
    def endpoint(self):
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def snapshot(self):
        with self._lock:
            return list(self.spans)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def _get(srv, path, accept=None):
    req = urllib.request.Request(
        srv.url(path), headers={"Accept": accept} if accept else {}
    )
    with urllib.request.urlopen(req, timeout=15) as resp:
        return resp.status, resp.read().decode("utf-8")


@pytest.fixture
def forensic_plane():
    """Tracing + OTLP stub + a gateway whose latency SLO is impossible
    (0.1 ms), so every real request is an injected over-SLO request."""
    tracer = enable_tracing()
    tracer.clear()
    collector = StubOtlpCollector()
    exporter = OtlpSpanExporter(
        collector.endpoint, flush_interval_s=0.05
    ).install(tracer)
    name = f"forensic-gw{next(_ids)}"
    gw = Gateway(
        make_fitted(),
        buckets=(4, 8),
        n_lanes=2,
        max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=name,
        max_pending=4,
        slo_latency_s=0.0001,       # unmeetable: everything breaches
        slo_target=0.5,             # budget 50% -> all-bad burn = 2.0
        slo_fast_window_s=0.3,
        slo_slow_window_s=30.0,
        slo_sample_interval_s=0.05,
        slo_shed_burn=1.5,
        slo_sustain_samples=2,
        slo_pressure=0.75,
    )
    srv = GatewayServer(gw, port=0).start()
    yield gw, srv, collector, exporter
    gw.close()
    srv.stop()
    exporter.shutdown()
    collector.close()
    disable_tracing()
    tracer.clear()


def test_forensic_chain_end_to_end(forensic_plane):
    gw, srv, collector, exporter = forensic_plane
    xs = batch(4, seed=7)

    # --- drive traffic; every request breaches the 0.1 ms SLO ---------
    for i in range(4):
        gw.predict(xs[i]).result(timeout=30)

    # --- (a) burn gauge rises and admission flips to early-shed -------
    # keep a trickle of (always-breaching) traffic flowing while the
    # monitor samples: burns are windowed DELTAS, so a burst that fully
    # completes before the baseline sample would read as zero burn
    deadline = time.perf_counter() + 15
    while (
        gw.admission.pressure == 0.0 and time.perf_counter() < deadline
    ):
        gw.predict(xs[0]).result(timeout=30)
        time.sleep(0.02)
    assert gw.admission.pressure == 0.75, (
        "SLO watchdog never tightened admission; slz="
        + json.dumps(gw.slo_monitor.status())
    )
    assert gw.admission.effective_max_pending == 1  # 4 * (1 - 0.75)
    burns = gw.slo_monitor.burn_rates(f"{gw.name}:latency")
    assert burns["fast"] is not None and burns["fast"] >= 1.5
    # the burn gauge is on the scrape surface
    _, metrics_body = _get(srv, "/metrics")
    assert "keystone_slo_burn_rate" in metrics_body
    assert f'slo="{gw.name}:latency",window="fast"' in metrics_body
    assert (
        f'keystone_gateway_slo_pressure{{gateway="{gw.name}"}} 0.75'
        in metrics_body
    )
    # /readyz stays 200 but surfaces the burn state
    status, ready_body = _get(srv, "/readyz")
    assert status == 200
    assert "slo burning" in ready_body

    # early shed demonstrably fires before the hard queue bound: burst
    # submits faster than the lanes drain until one sheds
    shed_reason = None
    pending = []
    deadline = time.perf_counter() + 15
    while shed_reason is None and time.perf_counter() < deadline:
        try:
            for i in range(32):
                pending.append(gw.predict(xs[i % 4]))
        except Overloaded as e:
            shed_reason = e.reason
    for f in pending:
        try:
            f.result(timeout=30)
        except Exception:
            pass
    assert shed_reason == "slo_pressure", shed_reason
    assert gw.metrics.shed_count("slo_pressure") >= 1

    # --- (b) flight recorder captured the span tree at /debugz --------
    records = gw.flight.records()
    assert records, "no flight records despite guaranteed breaches"
    record = records[0]
    assert record.reason == "slo_breach"
    assert record.attrs["gateway"] == gw.name
    assert record.attrs["lane"] in (0, 1)
    span_names = {s.name for s in record.spans}
    assert "gateway.admit" in span_names
    assert "microbatch.coalesce" in span_names
    # pipelined lanes (the default) replace serving.dispatch with the
    # per-stage chain; deliver is still open at capture time (futures
    # resolve inside it), so the record holds the first three stages
    assert {
        "pipeline.host_prep", "pipeline.upload", "pipeline.compute"
    } <= span_names
    trace_id = record.trace_id
    _, debugz = _get(srv, "/debugz")
    doc = json.loads(debugz)
    assert any(r["trace_id"] == trace_id for r in doc["records"])
    # Chrome round-trip for exactly this request
    _, chrome = _get(
        srv, f"/debugz?trace_id={trace_id}&format=chrome"
    )
    chrome_doc = json.loads(chrome)
    events = chrome_doc["traceEvents"]
    assert {e["name"] for e in events if e["ph"] == "X"} == span_names
    assert all(
        e["args"]["trace_id"] == trace_id
        for e in events if e["ph"] == "X"
    )

    # --- (c) the trace id is an exemplar on the latency histogram -----
    # exemplars only travel in the OpenMetrics rendering (the classic
    # text parser would reject the mid-line '#'), negotiated by Accept
    _, metrics_body = _get(
        srv, "/metrics", accept="application/openmetrics-text"
    )
    assert metrics_body.endswith("# EOF\n")
    # a plain scrape of the same surface stays classic and exemplar-free
    _, plain_body = _get(srv, "/metrics")
    assert "# {" not in plain_body
    exemplar_lines = [
        ln for ln in metrics_body.splitlines()
        if ln.startswith(
            f'keystone_gateway_request_latency_seconds_bucket'
            f'{{gateway="{gw.name}"'
        ) and " # {" in ln
    ]
    assert exemplar_lines, "latency histogram carries no exemplars"
    exemplified = {
        ln.split('trace_id="')[1].split('"')[0] for ln in exemplar_lines
    }
    captured = {r.trace_id for r in gw.flight.records()}
    assert exemplified & captured, (
        "no exemplar trace_id matches a flight record"
    )

    # --- (d) the spans arrived at the OTLP collector ------------------
    assert exporter.flush(10.0)
    otlp_spans = collector.snapshot()
    ours = [s for s in otlp_spans if s["traceId"] == trace_id]
    assert {s["name"] for s in ours} >= {
        "gateway.admit", "microbatch.coalesce", "pipeline.compute",
    }
    # /slz shows both objectives of this gateway
    _, slz = _get(srv, "/slz")
    slz_names = {s["name"] for s in json.loads(slz)["slos"]}
    assert f"{gw.name}:latency" in slz_names
    assert f"{gw.name}:availability" in slz_names


def test_watchdog_requires_consecutive_hot_samples():
    """'Sustained' means CONSECUTIVE over-threshold burn samples: a
    cooler sample in between resets the streak, so two isolated spikes
    (possibly hours apart) never trip admission tightening."""

    class _StubMonitor:
        fast = 0.0

        def burn_rates(self, name):
            return {"fast": self.fast, "slow": None}

    gw = Gateway(
        make_fitted(),
        buckets=(4,),
        n_lanes=1,
        warmup_example=np.zeros(D, np.float32),
        name=f"streak-gw{next(_ids)}",
        slo_latency_s=0.25,
        slo_shed_burn=4.0,
        slo_sustain_samples=2,
        slo_sample_interval_s=3600.0,  # the real monitor stays quiet
    )
    try:
        mon = _StubMonitor()
        # spike, cool-but-burning (>=1), spike again: streak broken
        for fast in (4.5, 2.0, 4.2):
            mon.fast = fast
            gw._slo_watchdog(mon)
        assert gw.admission.pressure == 0.0, (
            "non-consecutive spikes must not tighten admission"
        )
        # two consecutive spikes DO trip it
        for fast in (4.5, 4.2):
            mon.fast = fast
            gw._slo_watchdog(mon)
        assert gw.admission.pressure == 0.75
        # moderate burn (>= 1) holds the pressure; sub-1 releases it
        mon.fast = 2.0
        gw._slo_watchdog(mon)
        assert gw.admission.pressure == 0.75
        mon.fast = 0.5
        gw._slo_watchdog(mon)
        assert gw.admission.pressure == 0.0
    finally:
        gw.close()


def test_slo_plane_off_by_default():
    """No SLO declared -> no monitor, no flight recorder, no pressure
    path, no exemplars: the whole forensic plane is zero-overhead."""
    gw = Gateway(
        make_fitted(),
        buckets=(4,),
        n_lanes=1,
        warmup_example=np.zeros(D, np.float32),
        name=f"plain-gw{next(_ids)}",
    )
    try:
        assert gw.slo_monitor is None
        assert gw.flight is None
        assert gw.slo_status() is None
        assert gw.admission.flight is None
        assert gw.admission.pressure == 0.0
        gw.predict(batch(1, seed=3)[0]).result(timeout=30)
        fam = gw.metrics.request_latency.collect()
        # the family is shared process-wide; only THIS gateway's cells
        # are guaranteed exemplar-free (untraced requests carry no ids)
        assert all(
            s.exemplar is None
            for s in fam.samples
            if s.labels.get("gateway") == gw.name
        )
    finally:
        gw.close()
