"""The placement optimizer is PURE: no jax, no device, no clock.
Same profiles + same budget -> byte-identical plan; bucket choice
defers to ``suggest_buckets``'s exact DP; the replicated-vs-sharded
decision prices ``params_nbytes`` against the per-chip HBM budget;
spare lanes split by demand with a deterministic largest-remainder."""

import pytest

from keystone_tpu.serving.autoscale import suggest_buckets
from keystone_tpu.zoo import (
    ChipBudget,
    ModelProfile,
    plan_placement,
)

HIST = {1: 500, 4: 120, 16: 40, 64: 5}


def test_plan_is_deterministic_and_order_insensitive():
    profiles = [
        ModelProfile("beta", histogram=HIST, params_nbytes=1000),
        ModelProfile("alpha", histogram={2: 50}, params_nbytes=2000),
    ]
    budget = ChipBudget(hbm_bytes=10**9, n_chips=2, lane_budget=5)
    a = plan_placement(profiles, budget).to_dict()
    b = plan_placement(list(reversed(profiles)), budget).to_dict()
    assert a == b
    assert [p["model"] for p in a["placements"]] == ["alpha", "beta"]


def test_buckets_from_histogram_match_the_dp():
    prof = ModelProfile(
        "m", histogram=HIST, fallback_buckets=(8, 32, 128)
    )
    plan = plan_placement([prof], ChipBudget())
    placement = plan.placement_for("m")
    assert placement.buckets == suggest_buckets(
        HIST, 3, max_bucket=128
    )
    assert placement.predicted_efficiency is not None
    assert 0.0 < placement.predicted_efficiency <= 1.0


def test_cold_model_uses_fallback_buckets_verbatim():
    prof = ModelProfile("cold", fallback_buckets=(4, 16))
    placement = plan_placement([prof], ChipBudget()).placement_for(
        "cold"
    )
    assert placement.buckets == (4, 16)
    assert placement.predicted_efficiency is None


def test_sharding_decision_prices_params_against_hbm():
    big = ModelProfile("big", params_nbytes=900)
    small = ModelProfile("small", params_nbytes=100)
    # param budget = 1000 * 0.8 = 800 < big's 900
    budget = ChipBudget(hbm_bytes=1000, n_chips=4)
    plan = plan_placement([big, small], budget)
    assert plan.placement_for("big").sharded is True
    # a sharded model gets exactly ONE lane: extra lanes would
    # multiply HBM (each lane holds a param copy), not throughput
    assert plan.placement_for("big").lanes == 1
    assert plan.placement_for("small").sharded is False
    assert "mesh-sharded" in plan.placement_for("big").reason


def test_over_budget_without_chips_stays_replicated():
    big = ModelProfile("big", params_nbytes=900)
    plan = plan_placement([big], ChipBudget(hbm_bytes=1000, n_chips=1))
    assert plan.placement_for("big").sharded is False
    assert "no model axis" in plan.placement_for("big").reason


def test_no_hbm_budget_disables_the_decision():
    big = ModelProfile("big", params_nbytes=10**15)
    plan = plan_placement([big], ChipBudget(hbm_bytes=None, n_chips=8))
    assert plan.placement_for("big").sharded is False


def test_lane_split_proportional_with_floor_one():
    hot = ModelProfile("hot", histogram={8: 900})
    warm = ModelProfile("warm", histogram={8: 90})
    cold = ModelProfile("cold", histogram={8: 10})
    plan = plan_placement(
        [hot, warm, cold], ChipBudget(lane_budget=10)
    )
    lanes = {
        p.model_id: p.lanes for p in plan.placements
    }
    assert sum(lanes.values()) == 10
    assert lanes["cold"] >= 1
    assert lanes["hot"] > lanes["warm"] >= lanes["cold"]
    shares = {
        p.model_id: p.demand_share for p in plan.placements
    }
    assert sum(shares.values()) == pytest.approx(1.0)


def test_lane_split_tie_breaks_by_id():
    a = ModelProfile("a", histogram={4: 100})
    b = ModelProfile("b", histogram={4: 100})
    # 3 lanes over two equal demands: floor 1 each, the one spare
    # lane's remainders tie -> lexicographically first id wins
    plan = plan_placement([a, b], ChipBudget(lane_budget=3))
    assert plan.placement_for("a").lanes == 2
    assert plan.placement_for("b").lanes == 1


def test_validation_errors():
    with pytest.raises(ValueError, match="duplicate"):
        plan_placement(
            [ModelProfile("m"), ModelProfile("m")], ChipBudget()
        )
    with pytest.raises(ValueError, match="lane budget"):
        plan_placement(
            [ModelProfile("a"), ModelProfile("b")],
            ChipBudget(lane_budget=1),
        )
