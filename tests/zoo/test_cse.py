"""Cross-model featurize CSE: grouping is by content fingerprint
(``featurize_token``), and a ``SharedPrefixEngine`` computes the
shared prefix once per window — one trace per bucket for the WHOLE
group, outputs bit-matching each member's solo engine."""

import numpy as np
import pytest

from keystone_tpu.serving.bench import build_pipeline
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.featurize import build_featurize_pipeline
from keystone_tpu.zoo import SharedPrefixEngine, featurize_groups

IMG = 8


@pytest.fixture(scope="module")
def featurize():
    feat, feat_d = build_featurize_pipeline(img=IMG)
    return feat, feat_d


def _raws(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, IMG, IMG, 3), dtype=np.uint8)


def test_featurize_groups_by_content_not_name(featurize):
    feat, _ = featurize
    # a second, independently built chain with the SAME seed carries
    # the same params -> same token -> same group
    twin, _ = build_featurize_pipeline(img=IMG)
    other, _ = build_featurize_pipeline(img=IMG, seed=12)
    groups = featurize_groups(
        {"a": feat, "b": twin, "zzz": other}
    )
    assert ("a", "b") in groups
    assert ("zzz",) in groups


def test_featurize_groups_unfingerprintable_hosts_solo(featurize):
    feat, _ = featurize

    class Opaque:
        """No fittable structure: featurize_token raises."""

    groups = featurize_groups({"a": feat, "weird": Opaque()})
    # it can't PROVE equality with anything, so it never shares
    assert ("weird",) in groups
    assert ("a",) in groups


def test_shared_prefix_engine_matches_solo_per_model(featurize):
    feat, feat_d = featurize
    heads = {
        "alpha": build_pipeline(d=feat_d, hidden=16, depth=2, seed=1),
        "beta": build_pipeline(d=feat_d, hidden=16, depth=2, seed=2),
    }
    buckets = (2, 4)
    shared = SharedPrefixEngine(
        feat, heads, buckets, donate=False, name="cse-shared"
    )
    raws = _raws(3, seed=3)
    out = shared.apply(raws, sync=True)
    assert sorted(out) == ["alpha", "beta"]
    for mid, head in heads.items():
        solo = CompiledPipeline(
            head, buckets, featurize=feat, aot_store=None,
            donate=False, name=f"cse-solo-{mid}",
        )
        want = np.asarray(solo.apply(_raws(3, seed=3), sync=True))
        np.testing.assert_allclose(
            np.asarray(out[mid]), want, rtol=1e-4, atol=1e-5
        )


def test_shared_prefix_traces_once_per_bucket(featurize):
    feat, feat_d = featurize
    heads = {
        "alpha": build_pipeline(d=feat_d, hidden=16, depth=2, seed=1),
        "beta": build_pipeline(d=feat_d, hidden=16, depth=2, seed=2),
    }
    shared = SharedPrefixEngine(
        feat, heads, (2, 4), donate=False, name="cse-counters"
    )
    shared.apply(_raws(3), sync=True)   # bucket 4: first trace
    shared.apply(_raws(4), sync=True)   # bucket 4 again: cached
    shared.apply(_raws(2), sync=True)   # bucket 2: second trace
    # ONE program per bucket serves the whole group — this is the
    # counter seam the serving_zoo bench row gates on
    assert shared.metrics.compiles.total == 2
    assert shared.metrics.dispatches.total == 3


def test_shared_prefix_engine_rejects_bad_compositions(featurize):
    feat, feat_d = featurize
    head = build_pipeline(d=feat_d, hidden=16, depth=2, seed=1)
    with pytest.raises(ValueError, match="featurize prefix"):
        SharedPrefixEngine(None, {"a": head}, (2,))
    with pytest.raises(ValueError, match="at least one head"):
        SharedPrefixEngine(feat, {}, (2,))
    with pytest.raises(ValueError, match="param_sharding"):
        SharedPrefixEngine(
            feat, {"a": head}, (2,), param_sharding=True
        )
