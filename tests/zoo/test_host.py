"""ModelZoo hosting behavior: lazy page-in through the
build-outside-lock path (deduped under concurrency), CSE co-hosting,
LRU resident-set eviction with pinning, drain isolation between
models, plan overrides, and the /planz document."""

import threading

import numpy as np
import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.bench import build_pipeline
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.featurize import build_featurize_pipeline
from keystone_tpu.zoo import (
    BuiltModel,
    ModelPlacement,
    ModelRegistry,
    ModelSpec,
    ModelZoo,
    PlacementPlan,
    UnknownModel,
)

D = 6
IMG = 8


def _head(seed):
    return build_pipeline(d=D, hidden=8, depth=2, seed=seed)


def _solo_spec(mid, seed, **kw):
    head = _head(seed)
    kw.setdefault("buckets", (2, 4))
    kw.setdefault("lanes", 1)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("warmup_example", np.zeros(D, np.float32))
    return ModelSpec(
        model_id=mid,
        build=lambda: BuiltModel(fitted=head),
        **kw,
    ), head


def _zoo(specs, **kw):
    reg = ModelRegistry()
    for spec in specs:
        reg.register(spec)
    kw.setdefault("cse", False)
    kw.setdefault("aot_namespaces", False)
    kw.setdefault("metrics_registry", MetricsRegistry())
    return ModelZoo(reg, **kw)


def _solo_want(head, x, featurize=None):
    eng = CompiledPipeline(
        head, (2, 4), featurize=featurize, aot_store=None,
        donate=False,
    )
    return np.asarray(eng.apply(np.asarray(x)[None], sync=True))[0]


def test_resolve_default_and_unknown():
    spec_a, _ = _solo_spec("alpha", 1, default=True)
    spec_b, _ = _solo_spec("beta", 2)
    zoo = _zoo([spec_a, spec_b])
    assert zoo.resolve(None)[0] == "alpha"
    assert zoo.resolve("beta")[0] == "beta"
    with pytest.raises(UnknownModel) as ei:
        zoo.resolve("nope")
    assert ei.value.registered == ("alpha", "beta")
    # nothing paged in by lookups alone
    assert zoo.planz()["actual"]["alpha"]["resident"] is False
    zoo.close()


def test_predict_routes_per_model():
    spec_a, head_a = _solo_spec("alpha", 1, default=True)
    spec_b, head_b = _solo_spec("beta", 2)
    with _zoo([spec_a, spec_b]) as zoo:
        x = np.linspace(-1, 1, D).astype(np.float32)
        got_a = np.asarray(zoo.predict(x, "alpha").result(timeout=60))
        got_b = np.asarray(zoo.predict(x, "beta").result(timeout=60))
        got_default = np.asarray(zoo.predict(x).result(timeout=60))
        np.testing.assert_allclose(
            got_a, _solo_want(head_a, x), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            got_b, _solo_want(head_b, x), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_array_equal(got_default, got_a)
        assert not np.allclose(got_a, got_b)


def test_cse_group_shares_one_gateway_with_parity():
    feat, feat_d = build_featurize_pipeline(img=IMG)
    heads = {
        "alpha": build_pipeline(d=feat_d, hidden=8, depth=2, seed=1),
        "beta": build_pipeline(d=feat_d, hidden=8, depth=2, seed=2),
    }

    def spec(mid, default=False):
        return ModelSpec(
            model_id=mid,
            build=lambda h=heads[mid]: BuiltModel(
                fitted=h, featurize=feat
            ),
            buckets=(2, 4),
            lanes=1,
            max_delay_ms=1.0,
            input_dtype=np.uint8,
            default=default,
        )

    with _zoo([spec("alpha", True), spec("beta")], cse=True) as zoo:
        hosted = zoo.host()
        assert ("alpha", "beta") in hosted
        # one unit, one gateway, one engine set for both models
        assert zoo.gateway_for("alpha") is zoo.gateway_for("beta")
        rng = np.random.default_rng(5)
        x = rng.integers(0, 256, (IMG, IMG, 3), dtype=np.uint8)
        for mid in heads:
            got = np.asarray(zoo.predict(x, mid).result(timeout=60))
            eng = CompiledPipeline(
                heads[mid], (2, 4), featurize=feat, aot_store=None,
                donate=False,
            )
            want = np.asarray(eng.apply(x[None], sync=True))[0]
            np.testing.assert_allclose(
                got, want, rtol=1e-4, atol=1e-5
            )
        row = zoo.planz()["actual"]["alpha"]
        assert row["resident"] is True
        assert row["shared_with"] == ["beta"]


def test_predict_many_joins_across_units():
    spec_a, head_a = _solo_spec("alpha", 1, default=True)
    spec_b, head_b = _solo_spec("beta", 2)
    with _zoo([spec_a, spec_b]) as zoo:
        x = np.linspace(-1, 1, D).astype(np.float32)
        out = zoo.predict_many(x).result(timeout=60)
        assert sorted(out) == ["alpha", "beta"]
        np.testing.assert_allclose(
            np.asarray(out["alpha"]), _solo_want(head_a, x),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(out["beta"]), _solo_want(head_b, x),
            rtol=1e-4, atol=1e-5,
        )


def test_lru_eviction_respects_pinning():
    spec_keep, _ = _solo_spec("keep", 1, pinned=True, default=True)
    spec_b, _ = _solo_spec("bbb", 2)
    spec_c, _ = _solo_spec("ccc", 3)
    zoo = _zoo([spec_keep, spec_b, spec_c], max_resident=1)
    x = np.zeros(D, np.float32)
    try:
        zoo.predict(x, "keep").result(timeout=60)
        zoo.predict(x, "bbb").result(timeout=60)
        # keep is pinned: hosting bbb overflows the cap but never
        # evicts the pinned model
        actual = zoo.planz()["actual"]
        assert actual["keep"]["resident"] is True
        assert actual["bbb"]["resident"] is True
        zoo.predict(x, "ccc").result(timeout=60)
        actual = zoo.planz()["actual"]
        assert actual["keep"]["resident"] is True
        assert actual["bbb"]["resident"] is False  # the LRU victim
        assert actual["ccc"]["resident"] is True
        assert zoo._evictions_c.get(("bbb",)) == 1.0
        assert zoo._resident_g.get(("bbb",)) == 0.0
        # an evicted model pages back in on demand, same answers
        got = np.asarray(zoo.predict(x, "bbb").result(timeout=60))
        assert got.shape == (D,)
        assert zoo._pageins_c.get(("bbb",)) == 2.0
    finally:
        zoo.close()


def test_lru_order_is_by_last_use():
    spec_a, _ = _solo_spec("aaa", 1, default=True)
    spec_b, _ = _solo_spec("bbb", 2)
    spec_c, _ = _solo_spec("ccc", 3)
    zoo = _zoo([spec_a, spec_b, spec_c], max_resident=2)
    x = np.zeros(D, np.float32)
    try:
        zoo.predict(x, "aaa").result(timeout=60)
        zoo.predict(x, "bbb").result(timeout=60)
        zoo.predict(x, "aaa").result(timeout=60)  # refresh aaa
        zoo.predict(x, "ccc").result(timeout=60)
        actual = zoo.planz()["actual"]
        assert actual["bbb"]["resident"] is False  # least recent
        assert actual["aaa"]["resident"] is True
        assert actual["ccc"]["resident"] is True
    finally:
        zoo.close()


def test_concurrent_cold_predicts_page_in_once():
    spec, _ = _solo_spec("solo", 1, default=True)
    zoo = _zoo([spec])
    x = np.zeros(D, np.float32)
    outs, errors = [], []

    def client():
        try:
            outs.append(zoo.predict(x, "solo").result(timeout=60))
        except Exception as e:  # pragma: no cover - fails the test
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(8)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(outs) == 8
        # every concurrent cold request waited on ONE build instead
        # of compiling a duplicate generation
        assert zoo._pageins_c.get(("solo",)) == 1.0
    finally:
        zoo.close()


def test_evicting_one_model_never_stalls_another():
    spec_a, _ = _solo_spec("aaa", 1, default=True)
    spec_b, head_b = _solo_spec("bbb", 2)
    zoo = _zoo([spec_a, spec_b])
    x = np.zeros(D, np.float32)
    try:
        zoo.predict(x, "aaa").result(timeout=60)
        zoo.predict(x, "bbb").result(timeout=60)
        # eviction drains aaa on a background thread; bbb keeps
        # serving the whole time
        assert zoo.evict("aaa") is True
        got = np.asarray(zoo.predict(x, "bbb").result(timeout=60))
        np.testing.assert_allclose(
            got, _solo_want(head_b, x), rtol=1e-4, atol=1e-5
        )
        assert zoo.evict("aaa") is False  # already gone
    finally:
        zoo.close()


def test_plan_overrides_spec_shape():
    spec, _ = _solo_spec("mmm", 1, buckets=(2, 4), lanes=1,
                         default=True)
    plan = PlacementPlan(
        placements=(ModelPlacement(
            model_id="mmm", buckets=(1, 8), lanes=2, sharded=False,
            params_nbytes=0, demand_share=1.0,
            predicted_efficiency=None, reason="test",
        ),),
        lane_budget=2,
        hbm_budget_bytes=None,
    )
    with _zoo([spec], plan=plan) as zoo:
        gw = zoo.gateway_for("mmm")
        status = gw.pool.status()
        assert tuple(status["buckets"]) == (1, 8)
        assert status["lanes"] == 2
        doc = zoo.planz()
        assert doc["plan"]["placements"][0]["lanes"] == 2
        # spec shape still reported next to the plan's
        assert doc["actual"]["mmm"]["spec_lanes"] == 1


def test_closed_zoo_rejects_work():
    spec, _ = _solo_spec("solo", 1, default=True)
    zoo = _zoo([spec])
    zoo.predict(np.zeros(D, np.float32)).result(timeout=60)
    zoo.close()
    assert zoo.ready is False
    with pytest.raises(RuntimeError, match="closed"):
        zoo.predict(np.zeros(D, np.float32))


def _goodput(zoo):
    per = zoo.attribution.per_model()
    return {m: cell["goodput_rows"] for m, cell in per.items()}


def _engine_examples(zoo, mid):
    return sum(
        lane.engine.metrics.examples.total
        for lane in zoo.gateway_for(mid).pool.lanes
    )


def test_predict_many_shared_unit_accounts_each_model_once():
    """One ``predict_many`` over a co-hosted pair is ONE submit to the
    shared unit: the engine sees exactly one admitted row, and the
    ledger charges each member its even split of that single row —
    never a full row per member (double counting) and never zero."""
    feat, feat_d = build_featurize_pipeline(img=IMG)
    heads = {
        "alpha": build_pipeline(d=feat_d, hidden=8, depth=2, seed=1),
        "beta": build_pipeline(d=feat_d, hidden=8, depth=2, seed=2),
    }

    def spec(mid, default=False):
        return ModelSpec(
            model_id=mid,
            build=lambda h=heads[mid]: BuiltModel(
                fitted=h, featurize=feat
            ),
            buckets=(2, 4),
            lanes=1,
            max_delay_ms=1.0,
            input_dtype=np.uint8,
            default=default,
        )

    with _zoo([spec("alpha", True), spec("beta")], cse=True) as zoo:
        zoo.host()
        rng = np.random.default_rng(7)
        x = rng.integers(0, 256, (IMG, IMG, 3), dtype=np.uint8)
        zoo.predict_many(x).result(timeout=60)  # warm compile path
        rows0 = _engine_examples(zoo, "alpha")
        good0 = _goodput(zoo)
        out = zoo.predict_many(x).result(timeout=60)
        assert sorted(out) == ["alpha", "beta"]
        assert _engine_examples(zoo, "alpha") == rows0 + 1
        good = _goodput(zoo)
        assert good["alpha"] - good0.get("alpha", 0) == pytest.approx(0.5)
        assert good["beta"] - good0.get("beta", 0) == pytest.approx(0.5)
        # and the sum invariant survives: ledger total == engine total
        assert sum(good.values()) == pytest.approx(
            _engine_examples(zoo, "alpha")
        )


def test_predict_many_solo_units_account_each_model_once():
    """Across SOLO units the fan-out is one submit per unit: each
    model's engine admits one row and each model's ledger account is
    charged exactly one full row."""
    spec_a, _ = _solo_spec("alpha", 1, default=True)
    spec_b, _ = _solo_spec("beta", 2)
    with _zoo([spec_a, spec_b]) as zoo:
        x = np.linspace(-1, 1, D).astype(np.float32)
        zoo.predict_many(x).result(timeout=60)  # warm compile path
        rows0 = {m: _engine_examples(zoo, m) for m in ("alpha", "beta")}
        good0 = _goodput(zoo)
        zoo.predict_many(x).result(timeout=60)
        for mid in ("alpha", "beta"):
            assert _engine_examples(zoo, mid) == rows0[mid] + 1
            assert _goodput(zoo)[mid] - good0.get(mid, 0) == (
                pytest.approx(1.0)
            )
