"""The zoo-mode HTTP frontend: per-model /predict/<model> routing,
the bare-/predict default model, the typed unknown-model 404 with the
registered ids, /planz, model-labeled zoo metrics on /metrics, and
the 404 copy enumerating the zoo routes — plus the single-model
server's typed refusal of model paths."""

import itertools
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.bench import build_pipeline
from keystone_tpu.zoo import (
    BuiltModel,
    ModelRegistry,
    ModelSpec,
    ModelZoo,
)

from gateway_fixtures import D as GW_D, make_fitted

D = 6
_ids = itertools.count()


def _spec(mid, seed, **kw):
    head = build_pipeline(d=D, hidden=8, depth=2, seed=seed)
    kw.setdefault("buckets", (2, 4))
    kw.setdefault("lanes", 1)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("warmup_example", np.zeros(D, np.float32))
    return ModelSpec(
        model_id=mid, build=lambda: BuiltModel(fitted=head), **kw
    )


@pytest.fixture
def served_zoo():
    reg = MetricsRegistry()
    registry = ModelRegistry()
    registry.register(_spec("alpha", 1, default=True, pinned=True))
    registry.register(_spec("beta", 2))
    zoo = ModelZoo(
        registry, cse=False, aot_namespaces=False,
        metrics_registry=reg,
    )
    zoo.host()
    srv = GatewayServer(zoo=zoo, port=0, registry=reg).start()
    yield zoo, srv
    zoo.close()
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(srv.url(path), timeout=15) as resp:
        return resp.status, resp.read().decode("utf-8")


def _post(srv, path, doc):
    req = urllib.request.Request(
        srv.url(path),
        data=json.dumps(doc).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def _post_error(srv, path, doc):
    try:
        _post(srv, path, doc)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    raise AssertionError(f"POST {path} unexpectedly succeeded")


def test_per_model_routing_and_default(served_zoo):
    _, srv = served_zoo
    doc = {"instances": [np.linspace(-1, 1, D).tolist()]}
    _, bare = _post(srv, "/predict", doc)
    _, alpha = _post(srv, "/predict/alpha", doc)
    _, beta = _post(srv, "/predict/beta", doc)
    # bare /predict serves the DEFAULT model, bit-for-bit
    assert bare["predictions"] == alpha["predictions"]
    assert alpha["predictions"] != beta["predictions"]


def test_unknown_model_typed_404(served_zoo):
    _, srv = served_zoo
    code, body = _post_error(
        srv, "/predict/nope", {"instances": [[0.0] * D]}
    )
    assert code == 404
    assert body["error"] == "unknown_model"
    assert body["model"] == "nope"
    assert sorted(body["registered"]) == ["alpha", "beta"]


def test_planz_reports_plan_vs_actual(served_zoo):
    zoo, srv = served_zoo
    status, raw = _get(srv, "/planz")
    assert status == 200
    doc = json.loads(raw)
    assert doc["default_model"] == "alpha"
    assert doc["plan"] is None  # no optimizer plan applied
    assert set(doc["actual"]) == {"alpha", "beta"}
    assert doc["actual"]["alpha"]["resident"] is True
    assert doc["actual"]["alpha"]["pinned"] is True
    assert doc["actual"]["alpha"]["lanes"] == 1


def test_metrics_carry_model_labels(served_zoo):
    _, srv = served_zoo
    _post(srv, "/predict/beta", {"instances": [[0.0] * D]})
    _, metrics = _get(srv, "/metrics")
    assert 'keystone_zoo_resident{model="alpha"} 1' in metrics
    assert 'keystone_zoo_resident{model="beta"} 1' in metrics
    assert 'keystone_zoo_pageins_total{model="beta"} 1' in metrics


def test_404_copy_enumerates_zoo_routes(served_zoo):
    _, srv = served_zoo
    try:
        _get(srv, "/nonexistent")
        raise AssertionError("GET /nonexistent unexpectedly 200")
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        assert e.code == 404
        assert "/predict/<model>" in body
        assert "/planz" in body
    try:
        _post(srv, "/nonexistent", {})
        raise AssertionError("POST /nonexistent unexpectedly 200")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert "/predict/<model>" in e.read().decode()


def test_readyz_and_swap_in_zoo_mode(served_zoo):
    zoo, srv = served_zoo
    status, _ = _get(srv, "/readyz")
    assert status == 200
    status, swapped = _post(srv, "/swap", {})
    assert status == 200
    assert set(swapped["swapped"]) == {"alpha", "beta"}


def test_single_model_server_refuses_model_paths():
    reg = MetricsRegistry()
    gw = Gateway(
        make_fitted(),
        buckets=(2, 4),
        n_lanes=1,
        max_delay_ms=1.0,
        warmup_example=np.zeros(GW_D, np.float32),
        name=f"zoo-http-solo{next(_ids)}",
        registry=reg,
    )
    srv = GatewayServer(gw, port=0, registry=reg).start()
    try:
        code, body = _post_error(
            srv, "/predict/alpha", {"instances": [[0.0] * GW_D]}
        )
        assert code == 404
        assert body["error"] == "unknown_model"
        assert body["registered"] == []
        assert "--zoo" in body["detail"]
        # /planz is a zoo-mode route: typed 404 without one
        try:
            _get(srv, "/planz")
            raise AssertionError("/planz unexpectedly 200")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert json.loads(e.read())["error"] == "no_zoo"
    finally:
        gw.close()
        srv.stop()


def test_server_requires_exactly_one_plane():
    with pytest.raises(ValueError, match="exactly one"):
        GatewayServer(port=0)
    registry = ModelRegistry()
    registry.register(_spec("solo", 1, default=True))
    zoo = ModelZoo(
        registry, cse=False, aot_namespaces=False,
        metrics_registry=MetricsRegistry(),
    )
    gw = Gateway(
        make_fitted(),
        buckets=(2,),
        n_lanes=1,
        warmup_example=np.zeros(GW_D, np.float32),
        name=f"zoo-http-both{next(_ids)}",
        registry=MetricsRegistry(),
    )
    try:
        with pytest.raises(ValueError, match="exactly one"):
            GatewayServer(gw, port=0, zoo=zoo)
    finally:
        gw.close()
        zoo.close()
