"""ModelSpec/ModelRegistry naming plane: id validation, the single
default model, duplicate rejection, UnknownModel's typed payload, and
the ``--zoo`` JSON spec loader."""

import json

import pytest

from keystone_tpu.zoo import (
    BuiltModel,
    ModelRegistry,
    ModelSpec,
    UnknownModel,
    load_zoo_spec,
)


def _spec(mid, **kw):
    return ModelSpec(
        model_id=mid, build=lambda: BuiltModel(fitted=object()), **kw
    )


def test_register_get_and_insertion_order():
    reg = ModelRegistry()
    reg.register(_spec("alpha"))
    reg.register(_spec("beta"))
    assert reg.ids() == ("alpha", "beta")
    assert reg.get("alpha").model_id == "alpha"
    assert "beta" in reg and "gamma" not in reg
    assert len(reg) == 2


def test_default_model_first_registered_unless_flagged():
    reg = ModelRegistry()
    reg.register(_spec("alpha"))
    reg.register(_spec("beta", default=True))
    assert reg.default_id == "beta"
    # no default flag anywhere -> the first registered
    reg2 = ModelRegistry((_spec("a"), _spec("b")))
    assert reg2.default_id == "a"
    assert ModelRegistry().default_id is None


def test_duplicate_id_and_second_default_rejected():
    reg = ModelRegistry()
    reg.register(_spec("alpha", default=True))
    with pytest.raises(ValueError, match="already registered"):
        reg.register(_spec("alpha"))
    with pytest.raises(ValueError, match="default model is already"):
        reg.register(_spec("beta", default=True))


@pytest.mark.parametrize(
    "bad", ["", "-leading", "has space", "slash/id", "x" * 65]
)
def test_model_id_charset_enforced(bad):
    # ids ride URL paths, metric labels, and AOT namespaces
    with pytest.raises(ValueError, match="model id"):
        _spec(bad)


def test_spec_normalizes_buckets_and_rejects_nonsense():
    spec = _spec("m", buckets=(32, 8, 8))
    assert spec.buckets == (8, 32)
    with pytest.raises(ValueError, match="buckets"):
        _spec("m", buckets=(0, 4))
    with pytest.raises(ValueError, match="lane"):
        _spec("m", lanes=0)


def test_unknown_model_carries_registered_ids():
    reg = ModelRegistry((_spec("alpha"), _spec("beta")))
    with pytest.raises(UnknownModel) as ei:
        reg.get("nope")
    assert ei.value.model_id == "nope"
    assert ei.value.registered == ("alpha", "beta")
    # it IS a KeyError, so dict-style call sites keep working
    assert isinstance(ei.value, KeyError)


def test_load_zoo_spec(tmp_path):
    path = tmp_path / "zoo.json"
    path.write_text(json.dumps({"models": [
        {"name": "alpha", "d": 12, "buckets": [4, 8], "lanes": 1,
         "default": True, "pinned": True, "slo_latency_ms": 250,
         "expected_sizes": {"1": 500, "8": 12}},
        {"name": "beta", "d": 12},
    ]}))
    reg = load_zoo_spec(str(path))
    assert reg.ids() == ("alpha", "beta")
    assert reg.default_id == "alpha"
    alpha = reg.get("alpha")
    assert alpha.pinned is True
    assert alpha.buckets == (4, 8)
    assert alpha.slo_latency_s == pytest.approx(0.25)
    # JSON object keys are strings; the spec normalizes them to ints
    assert alpha.expected_sizes == {1: 500, 8: 12}


def test_load_zoo_spec_rejects_empty_and_bad_featurize(tmp_path):
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"models": []}))
    with pytest.raises(ValueError, match="no 'models'"):
        load_zoo_spec(str(empty))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"models": [
        {"name": "m", "device_featurize": "warp-drive"}
    ]}))
    with pytest.raises(ValueError, match="device_featurize"):
        load_zoo_spec(str(bad))
