import os
import sys

# the shared tiny-pipeline helpers live next to the gateway suite;
# rootdir conftest only puts tests/ itself on the path
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "gateway",
    ),
)
