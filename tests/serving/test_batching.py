"""MicroBatcher: concurrent submits coalesce into one dispatch, every
future resolves with its own per-example-correct row, deadlines flush
lone requests, close() drains."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.engine import CompiledPipeline

from test_engine import D, batch, make_fitted


@pytest.fixture(scope="module")
def fitted():
    return make_fitted()


def test_concurrent_submits_coalesce_and_resolve(fitted):
    engine = CompiledPipeline(fitted, buckets=(4, 16))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    n = 16
    xs = batch(n, seed=7)
    want = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(xs))).array()
    )
    futures = [None] * n
    # a generous deadline so every thread's submit lands inside the
    # first coalescing window (deterministic on a loaded CI box)
    with MicroBatcher(engine, max_delay_ms=300.0) as mb:
        barrier = threading.Barrier(4)

        def client(tid):
            barrier.wait()
            for i in range(tid, n, 4):
                futures[i] = mb.submit(xs[i])

        threads = [
            threading.Thread(target=client, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rows = [np.asarray(f.result(timeout=30)) for f in futures]
    for i in range(n):
        np.testing.assert_allclose(
            rows[i], want[i], rtol=1e-5, atol=1e-6
        )
    # the requests coalesced instead of dispatching one-by-one
    assert engine.metrics.max_coalesced >= 2
    assert engine.metrics.dispatches.total < n + len(engine.buckets)
    assert engine.metrics.request_latency.count == n


def test_deadline_flushes_a_lone_request(fitted):
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(engine, max_delay_ms=10.0) as mb:
        t0 = time.perf_counter()
        out = mb.submit(batch(1)[0]).result(timeout=30)
        dt = time.perf_counter() - t0
    assert np.asarray(out).shape == (3,)
    # flushed by the deadline, not by a full bucket (generous ceiling:
    # CI boxes stall, but a broken deadline hangs until close())
    assert dt < 20.0


def test_full_bucket_dispatches_before_deadline(fitted):
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    xs = batch(4, seed=3)
    with MicroBatcher(engine, max_delay_ms=10_000.0, max_batch=4) as mb:
        futures = [mb.submit(x) for x in xs]
        rows = [np.asarray(f.result(timeout=30)) for f in futures]
    want = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(xs))).array()
    )
    np.testing.assert_allclose(np.stack(rows), want, rtol=1e-5, atol=1e-6)


def test_close_drains_then_rejects(fitted):
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    mb = MicroBatcher(engine, max_delay_ms=5_000.0)
    fut = mb.submit(batch(1, seed=9)[0])
    mb.close()
    assert fut.result(timeout=5) is not None  # flushed, not dropped
    with pytest.raises(RuntimeError):
        mb.submit(batch(1)[0])


def test_max_batch_validation(fitted):
    engine = CompiledPipeline(fitted, buckets=(4,))
    with pytest.raises(ValueError):
        MicroBatcher(engine, max_batch=8)


def test_mixed_shape_streams_coalesce_separately(fitted):
    """Two interleaved well-formed request streams with different specs
    (single example vs a [2, D] pair treated as one example of a
    2-example pipeline input... here: different dtypes) each coalesce
    into their own spec-homogeneous windows — neither stream errors,
    every future resolves with its own correct row, and no dispatched
    window ever mixes specs (the stack() would raise if one did)."""
    engine = CompiledPipeline(fitted, buckets=(4, 16))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    n = 8
    xs32 = batch(n, seed=11)
    xs64 = batch(n, seed=12).astype(np.float64)
    want32 = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(xs32))).array()
    )
    futures = {}
    with MicroBatcher(engine, max_delay_ms=100.0) as mb:
        for i in range(n):  # strictly interleaved submission order
            futures[("f32", i)] = mb.submit(xs32[i])
            futures[("f64", i)] = mb.submit(xs64[i])
        rows = {
            k: np.asarray(f.result(timeout=30))
            for k, f in futures.items()
        }
    for i in range(n):
        np.testing.assert_allclose(
            rows[("f32", i)], want32[i], rtol=1e-5, atol=1e-6
        )
        # f64 input downcasts on the jnp.stack to the engine's f32
        # path; correctness vs the f32 reference of the same values
        want_i = np.asarray(
            fitted.apply(
                Dataset.from_array(jnp.asarray(xs64[i:i + 1], jnp.float32))
            ).array()
        )[0]
        np.testing.assert_allclose(
            rows[("f64", i)], want_i, rtol=1e-4, atol=1e-5
        )
    # both streams still coalesced (not 2n solo dispatches)
    assert engine.metrics.max_coalesced >= 2
    assert engine.metrics.request_latency.count == 2 * n


def test_swap_engine_mid_stream(fitted):
    """The live re-bucket hook: swapping the engine behind the batcher
    mid-stream loses no requests, later windows dispatch through the
    replacement (its metrics see them), and results are identical to
    the pre-swap engine's."""
    old = CompiledPipeline(fitted, buckets=(4,), name="swap-old")
    old.warmup(example=jnp.zeros((D,), jnp.float32))
    new = CompiledPipeline(fitted, buckets=(2, 8), name="swap-new")
    new.warmup(example=jnp.zeros((D,), jnp.float32))
    xs = batch(8, seed=21)
    want = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(xs))).array()
    )
    with MicroBatcher(old, max_delay_ms=5.0) as mb:
        first = [mb.submit(x) for x in xs[:4]]
        for f in first:
            f.result(timeout=30)
        returned = mb.swap_engine(new)
        assert returned is old
        assert mb.max_batch == new.max_bucket  # default follows the swap
        second = [mb.submit(x) for x in xs[4:]]
        rows = [
            np.asarray(f.result(timeout=30)) for f in first + second
        ]
    np.testing.assert_allclose(np.stack(rows), want, rtol=1e-5, atol=1e-6)
    # post-swap traffic ran on the replacement engine
    assert new.metrics.examples.total == 4
    assert old.metrics.examples.total >= 4


def test_error_propagates_to_futures(fitted):
    """A dispatch-level failure (bad spec opening a window) resolves
    the affected futures with the exception instead of hanging callers
    — and poisons only its own window: the next well-formed request
    opens a fresh window and succeeds."""
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(engine, max_delay_ms=5.0) as mb:
        fut = mb.submit(jnp.zeros((D + 1,)))  # opens a window whose
        # spec the pipeline's matmul rejects at trace time
        with pytest.raises(Exception):
            fut.result(timeout=30)
        good = mb.submit(batch(1, seed=2)[0])  # new window, accepted
        assert np.asarray(good.result(timeout=30)).shape == (3,)


def test_items_mode_array_items_segregate_by_shape(fitted):
    """The items-mode window-homogeneity fix: with a host featurizer
    installed, ARRAY items key windows by (shape, dtype) instead of
    collapsing every submission into one stream — mixed-size raw
    inputs coalesce per shape, so the hook always sees a
    shape-homogeneous window (no ragged stacks, no padding every
    window to the largest item ever seen)."""
    seen_windows = []
    lock = threading.Lock()

    def featurize(items):
        shapes = {np.asarray(it).shape for it in items}
        with lock:
            seen_windows.append(shapes)
        assert len(shapes) == 1, f"ragged window: {shapes}"
        (shape,) = shapes
        if shape == (2, D):
            # "large" items fold their two halves together
            return np.stack(
                [np.asarray(it, np.float32).mean(axis=0) for it in items]
            )
        return np.stack([np.asarray(it, np.float32) for it in items])

    engine = CompiledPipeline(fitted, buckets=(4, 16))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    n = 6
    small = batch(n, seed=21)
    big = np.stack([batch(2, seed=30 + i) for i in range(n)])
    futures = {}
    with MicroBatcher(
        engine, max_delay_ms=100.0, host_featurize=featurize
    ) as mb:
        for i in range(n):  # strictly interleaved
            futures[("small", i)] = mb.submit(small[i])
            futures[("big", i)] = mb.submit(big[i])
        rows = {
            k: np.asarray(f.result(timeout=30))
            for k, f in futures.items()
        }
    want_small = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(small))).array()
    )
    want_big = np.asarray(
        fitted.apply(
            Dataset.from_array(jnp.asarray(big.mean(axis=1)))
        ).array()
    )
    for i in range(n):
        np.testing.assert_allclose(
            rows[("small", i)], want_small[i], rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            rows[("big", i)], want_big[i], rtol=1e-5, atol=1e-6
        )
    # both shape streams still coalesced (not 2n solo windows), and no
    # window ever mixed shapes (the featurize assert above is the proof)
    assert engine.metrics.max_coalesced >= 2
    assert all(len(s) == 1 for s in seen_windows)


def test_items_mode_non_array_items_share_one_stream(fitted):
    """Non-array raw items (lists/strings/records) still have no
    stable per-item spec: they keep the single shared items stream and
    the hook owns homogeneity — the pre-fix contract, unchanged."""
    calls = []

    def featurize(items):
        calls.append(len(items))
        return np.stack(
            [np.asarray(it, np.float32) for it in items]
        )

    engine = CompiledPipeline(fitted, buckets=(8,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    items = [list(batch(1, seed=50 + i)[0]) for i in range(6)]
    with MicroBatcher(
        engine, max_delay_ms=100.0, host_featurize=featurize
    ) as mb:
        futs = [mb.submit(it) for it in items]
        for f in futs:
            f.result(timeout=30)
    # all six lists coalesced into shared windows (one stream)
    assert engine.metrics.max_coalesced >= 2
