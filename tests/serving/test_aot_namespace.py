"""AOT store namespaces + GC (the model-zoo satellites): LRU-by-mtime
eviction within ONE namespace, pinned entries surviving any budget,
per-namespace byte gauges, and the isolation contracts — namespaced
fingerprints never collide across models, and a cross-namespace plant
is rejected off the stored meta before a pickle byte is touched."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.aot import AotStore, bucket_key
from keystone_tpu.serving.bench import build_pipeline

D = 16
EXAMPLE = jnp.zeros((D,), jnp.float32)


@pytest.fixture(scope="module")
def fitted():
    return build_pipeline(d=D, hidden=D, depth=2)


def _store(tmp_path, namespace=None):
    return AotStore(
        str(tmp_path / "aot"),
        registry=MetricsRegistry(),
        namespace=namespace,
    )


def _warm(fitted, store, buckets=(2, 4, 8), name="aot-ns"):
    eng = fitted.compiled(
        buckets=buckets, name=name, aot_store=store
    )
    eng.warmup(example=EXAMPLE)
    return eng


def _stamp_mtimes(store, keys):
    """Force a known LRU order: keys[0] oldest ... keys[-1] newest."""
    import os

    base = 1_700_000_000
    for i, key in enumerate(keys):
        os.utime(store.path_for(key), (base + i, base + i))


# -- gc ---------------------------------------------------------------------

def test_gc_evicts_lru_by_mtime(tmp_path, fitted):
    store = _store(tmp_path, namespace="m")
    _warm(fitted, store)
    keys = store.entries()
    assert len(keys) == 3
    _stamp_mtimes(store, keys)
    report = store.gc(0)
    # everything went, OLDEST FIRST — mtime is the LRU axis
    assert report["evicted"] == list(keys)
    assert report["kept_bytes"] == 0
    assert store.namespace_bytes() == 0


def test_gc_stops_at_the_budget(tmp_path, fitted):
    store = _store(tmp_path, namespace="m")
    _warm(fitted, store)
    keys = store.entries()
    _stamp_mtimes(store, keys)
    total = store.namespace_bytes()
    report = store.gc(total - 1)
    # one eviction (the least recently used) was enough
    assert report["evicted"] == [keys[0]]
    assert report["over_budget"] is False
    assert store.namespace_bytes() == report["kept_bytes"]


def test_gc_never_evicts_pinned(tmp_path, fitted):
    store = _store(tmp_path, namespace="m")
    _warm(fitted, store)
    keys = store.entries()
    _stamp_mtimes(store, keys)
    pinned = keys[0]  # the LRU victim-to-be
    report = store.gc(0, pinned=[pinned])
    assert pinned not in report["evicted"]
    assert sorted(report["evicted"]) == sorted(keys[1:])
    # the pin beat the byte target, and the report says so
    assert report["over_budget"] is True
    assert store.namespace_bytes() > 0


def test_gc_is_namespace_blind_to_other_models(tmp_path, fitted):
    other = build_pipeline(d=D, hidden=D, depth=2, seed=9)
    store_a = _store(tmp_path, namespace="model-a")
    store_b = AotStore(
        store_a.root, registry=MetricsRegistry(), namespace="model-b"
    )
    _warm(fitted, store_a, name="aot-ns-a")
    _warm(other, store_b, name="aot-ns-b")
    b_before = store_b.namespace_bytes()
    assert b_before > 0
    # model A's churn GCs model A — B's executables are invisible
    report = store_a.gc(0)
    assert store_a.namespace_bytes() == 0
    assert store_b.namespace_bytes() == b_before
    keys_b = store_b.entries()
    assert keys_b
    assert all(store_b.read_meta(k) is not None for k in keys_b)


def test_namespace_bytes_gauge_exported(tmp_path, fitted):
    store = _store(tmp_path, namespace="gauged")
    _warm(fitted, store)
    assert store.namespace_bytes() > 0
    assert store._bytes_g.get(("gauged",)) == float(
        store.namespace_bytes()
    )
    store.gc(0)
    assert store._bytes_g.get(("gauged",)) == 0.0


# -- fingerprint isolation --------------------------------------------------

def _key(**kw):
    kw.setdefault("specs", [((D,), "float32")])
    kw.setdefault("buckets", (2, 4))
    kw.setdefault("bucket", 2)
    kw.setdefault("donate", False)
    kw.setdefault("shard", False)
    kw.setdefault("model_token", "tok")
    kw.setdefault("identity", {"jax": "test"})
    return bucket_key(**kw)


def test_namespaces_never_collide_in_the_key():
    key_a, meta_a = _key(namespace="model-a")
    key_b, meta_b = _key(namespace="model-b")
    key_none, meta_none = _key()
    assert len({key_a, key_b, key_none}) == 3
    assert meta_a["namespace"] == "model-a"
    # single-model stores stay byte-identical to pre-zoo fingerprints:
    # no namespace field at all, so no fleet-wide cold start
    assert "namespace" not in meta_none


def test_featurize_and_sharding_tokens_never_collide():
    plain, _ = _key()
    feat_x, _ = _key(featurize_token="feat-x")
    feat_y, _ = _key(featurize_token="feat-y")
    shard_s, _ = _key(sharding_token="mesh-1x2")
    assert len({plain, feat_x, feat_y, shard_s}) == 4


def test_cross_namespace_plant_rejected(tmp_path, fitted):
    store_a = _store(tmp_path, namespace="model-a")
    _warm(fitted, store_a, name="aot-plant-a")
    key = store_a.entries()[0]
    meta_a = store_a.read_meta(key)
    assert meta_a["namespace"] == "model-a"
    # model B asks for the SAME filename with its own namespace (the
    # planted-entry attack): the stored preamble disagrees, so the
    # load is an ERROR and nothing was unpickled
    store_b = AotStore(
        store_a.root, registry=MetricsRegistry(), namespace="model-b"
    )
    loaded, outcome = store_b.load(
        key, dict(meta_a, namespace="model-b")
    )
    assert loaded is None and outcome == "error"
    assert store_b.errors == 1
    # the rightful owner still loads it
    loaded, outcome = store_a.load(key, meta_a)
    assert loaded is not None and outcome == "hit"
