"""Persistent-compile-cache wiring (parallel/runtime.py)."""

import os

import jax
import pytest

from keystone_tpu.parallel import runtime

_KNOBS = (
    "jax_compilation_cache_dir",
    "jax_persistent_cache_min_compile_time_secs",
    "jax_persistent_cache_min_entry_size_bytes",
)


@pytest.fixture
def cache_config_sandbox(monkeypatch):
    """Reset the module's idempotency latch AND restore the global jax
    knobs afterwards — otherwise the rest of the tier-1 suite would
    persist every tiny CPU compile into a pytest tmp dir."""
    monkeypatch.setattr(runtime, "_cache_dir", None)
    saved = {}
    for name in _KNOBS:
        try:
            saved[name] = getattr(jax.config, name)
        except AttributeError:
            pass
    yield
    for name, val in saved.items():
        try:
            jax.config.update(name, val)
        except Exception:
            pass


def test_setup_compilation_cache_configures_jax(
    tmp_path, cache_config_sandbox
):
    d = str(tmp_path / "xla-cache")
    got = runtime.setup_compilation_cache(d)
    if got is None:  # jax build without the persistent-cache knobs
        return
    assert got == d
    assert jax.config.jax_compilation_cache_dir == d
    assert os.path.isdir(d)
    # idempotent: a second call (e.g. bench + engine both init) keeps
    # the first dir rather than re-pointing the cache mid-process
    assert runtime.setup_compilation_cache("/elsewhere") == d


def test_env_var_resolution(tmp_path, cache_config_sandbox, monkeypatch):
    d = str(tmp_path / "from-env")
    monkeypatch.setenv("KEYSTONE_COMPILE_CACHE", d)
    got = runtime.setup_compilation_cache()
    if got is not None:
        assert got == d
