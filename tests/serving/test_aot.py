"""AOT executable cache (serving/aot.py): fingerprint round trip,
cache-key invalidation (corrupt entry / bucket-list change / jax
version bump -> counted miss or error + silent recompile, never an
exception on the serving path), metrics families, /varz status, and
the serve-aot-build CLI."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving import aot
from keystone_tpu.serving.aot import AotStore
from keystone_tpu.serving.bench import build_pipeline

D = 16
EXAMPLE = jnp.zeros((D,), jnp.float32)


@pytest.fixture(scope="module")
def fitted():
    return build_pipeline(d=D, hidden=D, depth=2)


def make_store(tmp_path) -> AotStore:
    return AotStore(str(tmp_path / "aot"), registry=MetricsRegistry())


def warm_engine(fitted, store, buckets=(4, 8), name=None):
    eng = fitted.compiled(buckets=buckets, name=name, aot_store=store)
    eng.warmup(example=EXAMPLE)
    return eng


def statuses(engine):
    return {b: v["status"] for b, v in engine.aot_report().items()}


# -- the round trip --------------------------------------------------------

def test_roundtrip_second_engine_hits_with_zero_compiles(tmp_path, fitted):
    store = make_store(tmp_path)
    e1 = warm_engine(fitted, store, name="aot-rt-1")
    assert statuses(e1) == {4: "saved", 8: "saved"}
    assert e1.metrics.compile_count == 2  # the save generation compiles

    e2 = warm_engine(fitted, store, name="aot-rt-2")
    assert statuses(e2) == {4: "hit", 8: "hit"}
    # the whole point: NOT ONE trace or compile on the loaded engine
    assert e2.metrics.compile_count == 0
    assert store.hits == 2 and store.misses == 2 and store.errors == 0

    x = np.random.default_rng(0).standard_normal((5, D)).astype(np.float32)
    a = np.asarray(e1.apply(x, sync=True))
    b = np.asarray(e2.apply(x, sync=True))
    assert np.array_equal(a, b), "stored executable diverged from jit"


def test_hit_engine_still_registers_cost_models(tmp_path, fitted):
    """Device truth must survive the AOT path: the loaded executable's
    cost_analysis feeds the same MFU/goodput plane (this container's
    CPU backend reports cost analysis; the assert is conditional so a
    backend without it degrades to absent, not to a failure)."""
    store = make_store(tmp_path)
    e1 = warm_engine(fitted, store, name="aot-cm-1")
    e2 = warm_engine(fitted, store, name="aot-cm-2")
    if e1.metrics.cost_models:
        assert sorted(e2.metrics.cost_models) == sorted(
            e1.metrics.cost_models
        )


# -- cache-key invalidation ------------------------------------------------

def test_corrupt_entry_counts_error_and_recompiles(tmp_path, fitted):
    store = make_store(tmp_path)
    warm_engine(fitted, store, name="aot-c-1")
    for key in store.entries():
        with open(store.path_for(key), "wb") as f:
            f.write(b"not a pickle at all")
    e2 = warm_engine(fitted, store, name="aot-c-2")
    # every bucket fell back to a real compile, silently, and the
    # report says ERROR (matching the store counters) with the repair
    # (the broken entry was recompiled and re-saved) visible
    assert statuses(e2) == {4: "error", 8: "error"}
    assert {v.get("fallback") for v in e2.aot_report().values()} == {
        "saved"
    }
    assert e2.metrics.compile_count == 2
    assert store.errors == 2
    # and the fallback engine actually serves
    out = e2.apply(np.zeros((3, D), np.float32), sync=True)
    assert np.asarray(out).shape[0] == 3


def test_meta_mismatch_rejected_before_unpickling(tmp_path, fitted):
    """Defense in depth: an entry whose STORED meta (the plain-JSON
    preamble — readable without trusting the entry) disagrees with the
    requested fingerprint must not install, and the rejection happens
    before a single pickle byte is touched."""
    store = make_store(tmp_path)
    warm_engine(fitted, store, name="aot-t-1")
    key = store.entries()[0]
    stored = store.read_meta(key)
    assert stored is not None and stored["model_token"]
    loaded, outcome = store.load(
        key, dict(stored, model_token="someone-else")
    )
    assert loaded is None and outcome == "error"
    assert store.errors == 1
    # pickle never ran: the same entry still loads for the TRUE meta
    loaded, outcome = store.load(key, stored)
    assert loaded is not None and outcome == "hit"


def test_model_token_framing_blocks_adjacent_value_collisions():
    """Unframed hashing folded (1, 23) and (12, 3) to the same bytes;
    a token collision means one model serving another's predictions,
    so every hashed component is framed."""
    import hashlib

    def tok(v):
        h = hashlib.sha256()
        aot._hash_update(h, v)
        return h.hexdigest()

    assert tok([1, 23]) != tok([12, 3])
    assert tok([1, 23]) != tok(["1", 23])
    assert tok({"a": 1, "b": 2}) != tok({"a": 12, "b": ""})
    assert tok([[1], 2]) != tok([[1, 2]])


def test_changed_bucket_list_misses(tmp_path, fitted):
    """The bucket LIST is part of the fingerprint (not just the bucket):
    an engine re-bucketed to (4, 16) must not reuse the (4, 8) entry
    for bucket 4 — the stored program is correct either way, but a
    fingerprint that ignored the list would alias generations and make
    store bookkeeping unauditable."""
    store = make_store(tmp_path)
    warm_engine(fitted, store, buckets=(4, 8), name="aot-b-1")
    e2 = warm_engine(fitted, store, buckets=(4, 16), name="aot-b-2")
    assert statuses(e2) == {4: "saved", 16: "saved"}
    assert store.misses == 4 and store.errors == 0


def test_jax_version_bump_invalidates(tmp_path, fitted, monkeypatch):
    """A jax/jaxlib upgrade must produce a counted miss + silent
    recompile: serialized executables are PJRT bytes pinned to the
    toolchain that built them."""
    store = make_store(tmp_path)
    warm_engine(fitted, store, name="aot-v-1")
    monkeypatch.setattr(
        aot, "runtime_versions",
        lambda: {"jax": "99.0.0", "jaxlib": "99.0.0"},
    )
    e2 = warm_engine(fitted, store, name="aot-v-2")
    assert statuses(e2) == {4: "saved", 8: "saved"}
    assert e2.metrics.compile_count == 2
    assert store.hits == 0 and store.errors == 0


def test_unexecutable_entry_falls_back_and_charges_error(
    tmp_path, fitted, monkeypatch
):
    """An entry that deserializes but won't RUN (e.g. stale device
    topology) is uninstalled after the validation dispatch and the
    bucket recompiles — serving never sees the exception."""
    store = make_store(tmp_path)

    class Boom:
        def __call__(self, staged):
            raise RuntimeError("stale executable")

    monkeypatch.setattr(store, "load", lambda key, meta: (Boom(), "hit"))
    e = warm_engine(fitted, store, name="aot-x-1")
    assert statuses(e) == {4: "error", 8: "error"}
    assert store.errors == 2
    assert e.metrics.compile_count == 2
    out = e.apply(np.zeros((2, D), np.float32), sync=True)
    assert np.asarray(out).shape[0] == 2


def test_off_spec_input_detours_through_side_jit(tmp_path, fitted):
    """A stored executable is shape/dtype-rigid where jit is
    polymorphic: an off-spec input (here int32 rows) must serve like
    on a cold engine — never a TypeError out of apply() — WITHOUT
    costing on-spec traffic its zero-compile program."""
    store = make_store(tmp_path)
    warm_engine(fitted, store, name="aot-os-1")
    e2 = warm_engine(fitted, store, name="aot-os-2")
    assert statuses(e2)[4] == "hit"
    assert e2.metrics.compile_count == 0
    installed = e2._fns[4]
    x_int = np.arange(3 * D, dtype=np.int32).reshape(3, D)
    out = np.asarray(e2.apply(x_int, sync=True))
    e_jit = fitted.compiled(buckets=(4, 8), name="aot-os-jit",
                            aot_store=False)
    e_jit.warmup(example=EXAMPLE)
    assert np.array_equal(
        out, np.asarray(e_jit.apply(x_int, sync=True))
    )
    # the stray request traced ONE side program (exactly what a cold
    # engine would have done for that aval) and the stored executable
    # is still installed — on-spec traffic stays zero-compile
    assert e2.metrics.compile_count == 1
    assert e2._fns[4] is installed
    assert statuses(e2)[4] == "hit"
    x = np.zeros((2, D), np.float32)
    assert np.asarray(e2.apply(x, sync=True)).shape[0] == 2
    assert e2.metrics.compile_count == 1  # served by the stored exec
    # a second off-spec request reuses the cached side fn (jit's
    # per-aval cache) — no further compiles
    again = np.arange(2 * D, dtype=np.int32).reshape(2, D)
    assert np.asarray(e2.apply(again, sync=True)).shape[0] == 2
    assert e2.metrics.compile_count == 1


# -- fingerprint properties ------------------------------------------------

def test_pipeline_token_stable_across_use_and_distinguishes_weights():
    f1 = build_pipeline(d=8, hidden=8, depth=1)
    before = aot.pipeline_token(f1)
    # memoized on the pipeline (N lanes hash the model once, not N
    # times); drop the memo so the recompute below is a REAL one
    assert f1._aot_pipeline_token == before
    del f1._aot_pipeline_token
    eng = f1.compiled(buckets=(2,), aot_store=False)
    eng.warmup(example=jnp.zeros((8,), jnp.float32))
    # lazily-attached operator caches must not shift the token (a
    # token that changed when the pipeline RAN would turn every
    # restart into a miss)
    assert aot.pipeline_token(f1) == before
    f2 = build_pipeline(d=8, hidden=8, depth=2)
    assert aot.pipeline_token(f2) != before


def test_pipeline_token_hashes_graph_wiring():
    """Same operators in the same topo order, DIFFERENT edges: a
    multi-input node fed (A(x), x) vs (A(x), A(x)) computes different
    things, so the tokens must differ — likewise a re-pointed sink."""
    from keystone_tpu.workflow.api import FittedPipeline, Identity
    from keystone_tpu.workflow.graph import Graph

    g0 = Graph(
        sources=frozenset(), sink_dependencies={}, operators={},
        dependencies={},
    )
    g0, src = g0.add_source()
    g0, a = g0.add_node(Identity(), [src])

    # the token only hashes structure + operator identity, so Identity
    # stands in for a real multi-input join here
    g1, j1 = g0.add_node(Identity(), [a, src])
    g1, sink1 = g1.add_sink(j1)
    p1 = FittedPipeline(g1, src, sink1)

    g2, j2 = g0.add_node(Identity(), [a, a])
    g2, sink2 = g2.add_sink(j2)
    p2 = FittedPipeline(g2, src, sink2)

    assert aot.pipeline_token(p1) != aot.pipeline_token(p2)

    # sink re-pointed from the join back to the first node: same graph
    # body, different exposed value -> different token
    g3, sink3 = g2.add_sink(a)
    p3 = FittedPipeline(g3, src, sink3)
    assert aot.pipeline_token(p3) != aot.pipeline_token(p2)


def test_bucket_key_varies_by_every_field():
    specs = [((D,), np.float32)]
    base, _ = aot.bucket_key(specs, (4, 8), 4, donate=False,
                             shard=False, model_token="m")
    for kwargs in (
        dict(buckets=(4, 16)),
        dict(bucket=8),
        dict(donate=True),
        dict(shard=True),
        dict(model_token="other"),
    ):
        args = dict(specs=specs, buckets=(4, 8), bucket=4,
                    donate=False, shard=False, model_token="m")
        args.update(kwargs)
        key, _ = aot.bucket_key(**args)
        assert key != base, f"fingerprint ignored {kwargs}"
    other_spec, _ = aot.bucket_key(
        [((D,), np.float64)], (4, 8), 4, donate=False, shard=False,
        model_token="m",
    )
    assert other_spec != base


def test_bucket_key_featurize_token_isolates():
    """Fused device-featurize programs must never share an entry with
    the unfused model, nor with the same model fused behind a DIFFERENT
    featurizer — the featurize parameters are constants inside the
    serialized executable exactly like the model weights."""
    specs = [((8, 8, 3), np.uint8)]
    args = dict(specs=specs, buckets=(4,), bucket=4, donate=False,
                shard=False, model_token="m")
    plain, plain_meta = aot.bucket_key(**args)
    fused1, meta1 = aot.bucket_key(**args, featurize_token="f1")
    fused2, meta2 = aot.bucket_key(**args, featurize_token="f2")
    assert len({plain, fused1, fused2}) == 3
    # unfused meta carries NO featurize key: pre-featurize store
    # entries keep their fingerprints across the upgrade (no
    # fleet-wide cold start), while fused metas pin their token
    assert "featurize_token" not in plain_meta
    assert (meta1["featurize_token"], meta2["featurize_token"]) == (
        "f1", "f2"
    )


# -- device-featurize isolation --------------------------------------------

def _fused_pair():
    """Two featurize chains differing only in filter weights, plus a
    model sized to their shared output dim."""
    from keystone_tpu.serving.bench import build_pipeline
    from keystone_tpu.serving.featurize import build_featurize_pipeline

    feat1, feat_d = build_featurize_pipeline(
        img=8, channels=3, filters=4, conv_size=3,
        pool_stride=4, pool_size=4, seed=3,
    )
    feat2, _ = build_featurize_pipeline(
        img=8, channels=3, filters=4, conv_size=3,
        pool_stride=4, pool_size=4, seed=4,
    )
    model = build_pipeline(d=feat_d, hidden=8, depth=2)
    return feat1, feat2, model, feat_d


def _fused_engine(model, feat, store, name):
    eng = model.compiled(
        buckets=(4,), featurize=feat, aot_store=store, name=name
    )
    eng.warmup(example=jnp.zeros((8, 8, 3), jnp.uint8))
    return eng


def test_featurize_roundtrip_and_two_featurizers_never_collide(tmp_path):
    """The isolation contract end to end: a fused engine's entry hits
    for the SAME featurizer (zero compiles, identical outputs) and
    misses for a different one — which recompiles and serves its own
    correct answers, never the cached featurizer's."""
    feat1, feat2, model, feat_d = _fused_pair()
    store = make_store(tmp_path)
    raw = np.random.default_rng(5).integers(
        0, 256, (3, 8, 8, 3), dtype=np.uint8
    )

    e1 = _fused_engine(model, feat1, store, "aot-dfz-1")
    assert statuses(e1) == {4: "saved"}
    out1 = np.asarray(e1.apply(raw, sync=True))

    e2 = _fused_engine(model, feat1, store, "aot-dfz-2")
    assert statuses(e2) == {4: "hit"}
    assert e2.metrics.compile_count == 0
    np.testing.assert_array_equal(
        np.asarray(e2.apply(raw, sync=True)), out1
    )

    # different featurizer weights -> different fingerprint -> MISS
    # (never a hit on feat1's executable), fresh compile, own answers
    e3 = _fused_engine(model, feat2, store, "aot-dfz-3")
    assert statuses(e3) == {4: "saved"}
    assert e3.metrics.compile_count == 1
    out3 = np.asarray(e3.apply(raw, sync=True))
    want3 = np.asarray(
        model._batch_run(feat2._batch_run(jnp.asarray(raw)))
    )[:3]
    np.testing.assert_allclose(out3, want3, rtol=1e-4, atol=1e-6)
    assert not np.allclose(out3, out1)

    # and the unfused model shares nothing with the fused entries
    entries_before = set(store.entries())
    plain = model.compiled(buckets=(4,), aot_store=store, name="aot-dfz-p")
    plain.warmup(example=jnp.zeros((feat_d,), jnp.float32))
    assert statuses(plain) == {4: "saved"}
    assert set(store.entries()) > entries_before


def test_featurize_cross_load_falls_back_counted(tmp_path):
    """A cross-load attempt — feat1's entry bytes sitting at feat2's
    key (filename collision, copy mistake, hostile store) — is
    rejected on the meta re-check BEFORE anything is unpickled:
    counted as an error, recompiled, correct answer."""
    from keystone_tpu.serving.aot import pipeline_token, runtime_identity

    feat1, feat2, model, _feat_d = _fused_pair()
    store = make_store(tmp_path)
    e1 = _fused_engine(model, feat1, store, "aot-xl-1")
    assert statuses(e1) == {4: "saved"}

    specs = [((8, 8, 3), np.dtype(np.uint8))]
    ident = runtime_identity()
    key1, _ = aot.bucket_key(
        specs, e1.buckets, 4, donate=e1.donate, shard=False,
        model_token=pipeline_token(model), identity=ident,
        featurize_token=pipeline_token(feat1),
    )
    key2, _ = aot.bucket_key(
        specs, e1.buckets, 4, donate=e1.donate, shard=False,
        model_token=pipeline_token(model), identity=ident,
        featurize_token=pipeline_token(feat2),
    )
    # plant feat1's entry at feat2's key
    import shutil

    shutil.copyfile(store.path_for(key1), store.path_for(key2))
    errors_before = store.errors

    e2 = _fused_engine(model, feat2, store, "aot-xl-2")
    # the planted entry was rejected (stored meta disagrees with the
    # requested fingerprint), the error was counted, and the engine
    # recompiled its own program — never a wrong answer
    assert statuses(e2)[4] in ("error",)
    assert store.errors > errors_before
    assert e2.metrics.compile_count == 1
    raw = np.random.default_rng(6).integers(
        0, 256, (2, 8, 8, 3), dtype=np.uint8
    )
    want = np.asarray(
        model._batch_run(feat2._batch_run(jnp.asarray(raw)))
    )[:2]
    np.testing.assert_allclose(
        np.asarray(e2.apply(raw, sync=True)), want, rtol=1e-4, atol=1e-6
    )


def test_flagship_featurize_roundtrip_zero_compiles(tmp_path):
    """The flagship SIFT+LCS->FV chain — branched DAG, Pallas hot
    loops — through the AOT store: the save generation compiles, a
    second engine HITS with zero traces/compiles and serves bitwise-
    equal outputs (the serialized executable covers the whole fused
    program, Pallas lowering included), and ``pipeline_token``
    distinguishes the flagship chain from the demo conv chain so their
    entries can never collide."""
    from keystone_tpu.serving.aot import pipeline_token
    from keystone_tpu.serving.featurize import (
        build_featurize_pipeline,
        build_flagship_featurize_pipeline,
    )

    IMG = 34  # > the LCS keypoint border (2*16)
    flagship, feat_d = build_flagship_featurize_pipeline(
        img=IMG, desc_dim=8, vocab=8
    )
    model = build_pipeline(d=feat_d, hidden=8, depth=2)
    store = make_store(tmp_path)
    raw = np.random.default_rng(9).integers(
        0, 256, (3, IMG, IMG, 3), dtype=np.uint8
    )

    def engine(name):
        eng = model.compiled(
            buckets=(4,), featurize=flagship, aot_store=store, name=name
        )
        eng.warmup(example=jnp.zeros((IMG, IMG, 3), jnp.uint8))
        return eng

    e1 = engine("aot-fl-1")
    assert statuses(e1) == {4: "saved"}
    out1 = np.asarray(e1.apply(raw, sync=True))

    e2 = engine("aot-fl-2")
    assert statuses(e2) == {4: "hit"}
    assert e2.metrics.compile_count == 0
    np.testing.assert_array_equal(
        np.asarray(e2.apply(raw, sync=True)), out1
    )

    # the flagship fingerprint is its own: a demo conv chain with the
    # same uint8 input spec can never share an entry
    demo, _ = build_featurize_pipeline(img=IMG)
    assert pipeline_token(flagship) != pipeline_token(demo)
    assert pipeline_token(flagship) == pipeline_token(
        build_flagship_featurize_pipeline(img=IMG, desc_dim=8, vocab=8)[0]
    )


# -- observability ---------------------------------------------------------

def test_metrics_families_on_scrape(tmp_path, fitted):
    from keystone_tpu.observability.prometheus import render

    reg = MetricsRegistry()
    store = AotStore(str(tmp_path / "aot"), registry=reg)
    warm_engine(fitted, store, name="aot-m-1")  # misses + saves
    warm_engine(fitted, store, name="aot-m-2")  # hits
    text = render(reg.collect())
    assert "keystone_aot_cache_hits_total 2" in text
    assert "keystone_aot_cache_misses_total 2" in text
    # no errors happened: the family exists but carries no cells yet
    assert "# TYPE keystone_aot_cache_errors_total counter" in text
    assert "keystone_aot_cache_load_seconds_count 2" in text
    assert 'keystone_aot_cache_load_seconds_bucket{le="+Inf"} 2' in text


def test_configured_store_and_varz_status(tmp_path, monkeypatch, fitted):
    """setup_aot_cache -> configured_store -> the default "auto"
    engine path, and the aot_cache block on /varz's build info."""
    from keystone_tpu.observability import admin
    from keystone_tpu.parallel import runtime

    monkeypatch.setattr(runtime, "_aot_dir", None)
    monkeypatch.setattr(aot, "_configured", None)
    assert aot.configured_store() is None
    assert aot.status() == {"dir": None}

    root = str(tmp_path / "auto-aot")
    assert runtime.setup_aot_cache(root) == root
    store = aot.configured_store()
    assert store is not None and store.root == root
    # default engines (aot_store="auto") ride the configured store
    eng = fitted.compiled(buckets=(4,), name="aot-auto")
    eng.warmup(example=EXAMPLE)
    assert statuses(eng) == {4: "saved"}
    info = admin.build_info()
    assert info["aot_cache"]["dir"] == root
    assert info["aot_cache"]["entries"] == 1
    assert info["aot_cache"]["saves"] == 1


def test_setup_aot_cache_env_and_idempotence(tmp_path, monkeypatch):
    from keystone_tpu.parallel import runtime

    monkeypatch.setattr(runtime, "_aot_dir", None)
    monkeypatch.setenv("KEYSTONE_AOT_CACHE", str(tmp_path / "env-aot"))
    assert runtime.setup_aot_cache() == str(tmp_path / "env-aot")
    # idempotent: a second call (even with another arg) keeps the first
    assert runtime.setup_aot_cache(str(tmp_path / "other")) == str(
        tmp_path / "env-aot"
    )
    assert runtime.aot_cache_dir() == str(tmp_path / "env-aot")


# -- the serve-aot-build CLI -----------------------------------------------

def test_build_main_populates_then_hits(tmp_path, monkeypatch, capsys):
    from keystone_tpu.parallel import runtime

    monkeypatch.setattr(runtime, "_aot_dir", None)
    monkeypatch.setattr(aot, "_configured", None)
    # keep the process-global persistent compile cache out of the test
    monkeypatch.setattr(
        runtime, "setup_compilation_cache", lambda *a, **k: None
    )
    argv = ["--d", "8", "--hidden", "8", "--depth", "1",
            "--buckets", "2,4", "--aot-cache", str(tmp_path / "store")]
    assert aot.build_main(argv) == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["aot"] == {"2": {"status": "saved"},
                             "4": {"status": "saved"}}
    assert report["entries"] == 2

    # second build: everything already stored -> hits, rc 0
    monkeypatch.setattr(runtime, "_aot_dir", None)
    monkeypatch.setattr(aot, "_configured", None)
    assert aot.build_main(argv) == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert {v["status"] for v in report["aot"].values()} == {"hit"}


# -- mesh-sharded program isolation (serving/sharding.py) ------------------

def test_bucket_key_sharding_token_isolates():
    """A mesh-sharded bucket program (GSPMD-partitioned, params as
    arguments) must never share an entry with the replicated program
    of the same model, nor with a different partitioning — while
    replicated fingerprints stay byte-identical to pre-sharding
    stores (no fleet-wide cold start on upgrade)."""
    specs = [((D,), np.float32)]
    args = dict(specs=specs, buckets=(4,), bucket=4, donate=False,
                shard=False, model_token="m")
    plain, plain_meta = aot.bucket_key(**args)
    shd1, meta1 = aot.bucket_key(**args, sharding_token="s1")
    shd2, meta2 = aot.bucket_key(**args, sharding_token="s2")
    assert len({plain, shd1, shd2}) == 3
    # replicated meta carries NO sharding key: existing entries keep
    # their fingerprints across the upgrade
    assert "sharding_token" not in plain_meta
    assert (meta1["sharding_token"], meta2["sharding_token"]) == (
        "s1", "s2"
    )
    # explicit None is the replicated fingerprint, byte for byte
    none_key, none_meta = aot.bucket_key(**args, sharding_token=None)
    assert none_key == plain and none_meta == plain_meta
    # and the two token kinds can't stand in for each other
    feat, _ = aot.bucket_key(**args, featurize_token="s1")
    assert feat != shd1


@pytest.fixture
def model_mesh():
    from keystone_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.make_mesh(n_data=1, n_model=8)
    with mesh_lib.use_mesh(m):
        yield m


def _sharded_engine(fitted, store, name, mesh):
    eng = fitted.compiled(
        buckets=(4,), name=name, aot_store=store,
        param_sharding=True, mesh=mesh,
    )
    eng.warmup(example=EXAMPLE)
    return eng


@pytest.mark.needs_mesh8
def test_sharded_roundtrip_and_replicated_never_collide(
    tmp_path, fitted, model_mesh
):
    """End to end: a sharded engine's entry hits for the SAME
    partitioning (zero compiles, identical outputs); the replicated
    engine for the same model gets its own distinct entry, never the
    sharded executable."""
    store = make_store(tmp_path)
    x = np.random.default_rng(2).standard_normal((3, D)).astype(
        np.float32
    )

    e1 = _sharded_engine(fitted, store, "aot-shd-1", model_mesh)
    assert statuses(e1) == {4: "saved"}
    out1 = np.asarray(e1.apply(x, sync=True))

    e2 = _sharded_engine(fitted, store, "aot-shd-2", model_mesh)
    assert statuses(e2) == {4: "hit"}
    assert e2.metrics.compile_count == 0
    np.testing.assert_array_equal(
        np.asarray(e2.apply(x, sync=True)), out1
    )

    # replicated engine, same model + specs: MISS, own entry
    entries_before = set(store.entries())
    plain = fitted.compiled(buckets=(4,), aot_store=store,
                            name="aot-shd-p")
    plain.warmup(example=EXAMPLE)
    assert statuses(plain) == {4: "saved"}
    assert set(store.entries()) > entries_before
    np.testing.assert_allclose(
        np.asarray(plain.apply(x, sync=True)), out1,
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.needs_mesh8
def test_sharded_cross_plant_rejected_at_meta_recheck(
    tmp_path, fitted, model_mesh
):
    """A sharded entry's bytes planted at the replicated key (and vice
    versa) are rejected on the stored-meta re-check BEFORE anything is
    unpickled: counted error, recompile, never a wrong program."""
    import shutil

    from keystone_tpu.parallel import mesh as mesh_lib
    from keystone_tpu.serving import sharding as sharding_lib
    from keystone_tpu.serving.aot import pipeline_token, runtime_identity

    store = make_store(tmp_path)
    e1 = _sharded_engine(fitted, store, "aot-xp-1", model_mesh)
    assert statuses(e1) == {4: "saved"}

    specs = [((D,), np.dtype(np.float32))]
    ident = runtime_identity()
    token = pipeline_token(fitted)
    shd_key, _ = aot.bucket_key(
        specs, (4,), 4, donate=e1.donate, shard=False,
        model_token=token, identity=ident,
        sharding_token=sharding_lib.sharding_token(
            e1.param_sharding, model_mesh
        ),
    )
    plain_key, _ = aot.bucket_key(
        specs, (4,), 4, donate=e1.donate, shard=False,
        model_token=token, identity=ident,
    )
    assert shd_key in store.entries()
    # plant the sharded entry at the replicated fingerprint
    shutil.copyfile(store.path_for(shd_key), store.path_for(plain_key))
    errors_before = store.errors

    plain = fitted.compiled(buckets=(4,), aot_store=store,
                            name="aot-xp-p")
    plain.warmup(example=EXAMPLE)
    assert statuses(plain)[4] == "error"
    assert store.errors > errors_before
    assert plain.metrics.compile_count == 1  # counted recompile
    x = np.random.default_rng(3).standard_normal((2, D)).astype(
        np.float32
    )
    np.testing.assert_allclose(
        np.asarray(plain.apply(x, sync=True)),
        np.asarray(e1.apply(x, sync=True)),
        rtol=1e-5, atol=1e-6,
    )

    # the reverse plant: replicated bytes at a DIFFERENT mesh's key
    m24 = mesh_lib.make_mesh(n_data=2, n_model=4)
    with mesh_lib.use_mesh(m24):
        other_key, _ = aot.bucket_key(
            specs, (4,), 4, donate=e1.donate, shard=False,
            model_token=token, identity=ident,
            sharding_token=sharding_lib.sharding_token(
                sharding_lib.resolve_param_sharding(True, fitted), m24
            ),
        )
        assert other_key not in store.entries()
        shutil.copyfile(
            store.path_for(shd_key), store.path_for(other_key)
        )
        e24 = _sharded_engine(fitted, store, "aot-xp-24", m24)
    assert statuses(e24)[4] == "error"
    assert e24.metrics.compile_count == 1
