"""Bucket autoscaler: propose a bucket set from observed traffic."""

import numpy as np
import pytest

from keystone_tpu.serving.autoscale import (
    padding_waste,
    predicted_efficiency,
    suggest_buckets,
)
from keystone_tpu.serving.metrics import ServingMetrics


def test_predicted_efficiency_matches_waste_model():
    hist = {3: 2, 7: 1}  # through buckets (4, 8): waste 2*1 + 1*1 = 3
    assert padding_waste(hist, (4, 8)) == 3
    # 13 valid rows, 16 shipped
    assert predicted_efficiency(hist, (4, 8)) == pytest.approx(13 / 16)
    # exact-fit traffic: no waste, efficiency 1
    assert predicted_efficiency({4: 5, 8: 2}, (4, 8)) == 1.0
    # empty histogram: no prediction, not a fake number
    assert predicted_efficiency({}, (4, 8)) is None


def test_predicted_efficiency_agrees_with_live_counters():
    """The offline model and the live per-dispatch goodput counters
    must tell the same story for the same traffic (the supersede
    contract: the counters are ground truth, the model predicts
    them)."""
    m = ServingMetrics()
    for size, bucket in ((3, 4), (7, 8), (4, 4), (8, 8)):
        m.record_dispatch(bucket=bucket, n_valid=size)
    live = m.padding_efficiency()
    modeled = predicted_efficiency(m, (4, 8))
    assert live == pytest.approx(modeled)


def test_clustered_traffic_mix_finds_the_clusters():
    """Synthetic mix with three obvious size clusters: the optimal
    3-bucket set is each cluster's max."""
    hist = {}
    for s in (1, 2, 3, 4):
        hist[s] = 100  # interactive singles
    for s in (60, 62, 64):
        hist[s] = 50  # mid batches
    for s in (500, 510, 512):
        hist[s] = 10  # bulk
    buckets = suggest_buckets(hist, 3)
    assert buckets == (4, 64, 512)


def test_proposal_beats_default_buckets_on_waste():
    rng = np.random.default_rng(0)
    hist = {}
    # bimodal: most traffic tiny, a bulk tail
    for s in rng.integers(1, 9, 400):
        hist[int(s)] = hist.get(int(s), 0) + 1
    for s in rng.integers(120, 129, 100):
        hist[int(s)] = hist.get(int(s), 0) + 1
    proposed = suggest_buckets(hist, 3)
    naive = (8, 64, 512)
    assert padding_waste(hist, proposed) <= padding_waste(hist, naive)


def test_k_larger_than_distinct_sizes_returns_sizes():
    assert suggest_buckets({4: 10, 16: 1}, 5) == (4, 16)


def test_single_bucket_is_the_max():
    assert suggest_buckets({3: 5, 7: 1, 12: 2}, 1) == (12,)


def test_largest_observed_size_is_always_covered():
    rng = np.random.default_rng(1)
    hist = {int(s): int(c) for s, c in zip(
        rng.integers(1, 300, 40), rng.integers(1, 50, 40)
    )}
    for k in (1, 2, 4, 6):
        buckets = suggest_buckets(hist, k)
        assert buckets[-1] == max(hist)
        assert len(buckets) <= k
        assert list(buckets) == sorted(set(buckets))


def test_weighting_matters():
    """Same sizes, different counts -> different proposal: the heavy
    size pulls a dedicated bucket."""
    light = suggest_buckets({10: 1, 100: 1, 101: 1000}, 2)
    heavy = suggest_buckets({10: 1000, 100: 1, 101: 1}, 2)
    # exact-fit for the dominant size in both cases
    assert 101 in light
    assert 10 in heavy


def test_max_bucket_clamps_oversized_requests():
    buckets = suggest_buckets({4: 10, 1000: 5}, 2, max_bucket=256)
    assert buckets[-1] == 256


def test_max_bucket_models_chunk_tails_not_clamping():
    """Oversized requests chunk through max_bucket at serving time; the
    proposal must optimize for the TAIL (size % max_bucket), matching
    padding_waste — clamping would report zero waste while serving pays
    for every tail."""
    hist = {10: 100}
    buckets = suggest_buckets(hist, 2, max_bucket=4)
    assert buckets == (2, 4)  # tail of 10 = 4+4+2 is exactly covered
    assert padding_waste(hist, buckets) == 0
    # evenly-chunking traffic: nothing below the forced bucket needed
    assert suggest_buckets({8: 10, 16: 3}, 3, max_bucket=8) == (8,)


def test_max_bucket_is_always_in_the_result():
    assert 8 in suggest_buckets({3: 5}, 2, max_bucket=8)
    assert suggest_buckets({3: 5}, 1, max_bucket=8) == (8,)


def test_exactness_against_brute_force():
    """DP proposal matches exhaustive search over all bucket subsets on
    a small instance."""
    import itertools

    rng = np.random.default_rng(2)
    sizes = sorted(rng.choice(np.arange(1, 40), size=7, replace=False))
    hist = {int(s): int(c) for s, c in zip(sizes, rng.integers(1, 20, 7))}
    for k in (2, 3):
        best = min(
            (
                padding_waste(hist, combo + (max(hist),))
                for combo in itertools.combinations(sorted(hist), k - 1)
            ),
            default=padding_waste(hist, (max(hist),)),
        )
        got = suggest_buckets(hist, k)
        assert padding_waste(hist, got) == best


def test_empty_histogram_raises():
    with pytest.raises(ValueError):
        suggest_buckets({}, 3)
    with pytest.raises(ValueError):
        suggest_buckets(ServingMetrics(), 3)
    with pytest.raises(ValueError):
        suggest_buckets({4: 10}, 0)


def test_reads_live_serving_metrics():
    m = ServingMetrics()
    for _ in range(30):
        m.record_dispatch(bucket=8, n_valid=3, seconds=0.001)
    for _ in range(5):
        m.record_dispatch(bucket=64, n_valid=50, seconds=0.002)
    assert suggest_buckets(m, 2) == (3, 50)
