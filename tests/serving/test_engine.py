"""CompiledPipeline: bounded recompiles, padded-row correctness,
chunking, warmup, and the sharded variant. Everything here runs on the
CPU backend (tier-1: JAX_PLATFORMS=cpu) — the engine uses no TPU-only
APIs on its default path; donation simply disables itself where the
backend doesn't support it."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class Affine(Transformer):
    W: object
    b: object

    def apply(self, x):
        return jnp.tanh(x @ self.W + self.b)


D = 6


def make_fitted():
    rng = np.random.default_rng(0)
    w1 = jnp.asarray(rng.standard_normal((D, 8)).astype(np.float32))
    w2 = jnp.asarray(rng.standard_normal((8, 3)).astype(np.float32))
    pipe = Affine(w1, jnp.zeros(8, jnp.float32)).and_then(
        Affine(w2, jnp.ones(3, jnp.float32))
    )
    return pipe.fit()


@pytest.fixture(scope="module")
def fitted():
    return make_fitted()


def batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, D)).astype(np.float32)


def test_recompile_count_bounded(fitted):
    """The acceptance criterion: >= 6 distinct batch sizes through a
    2-bucket engine trigger exactly 2 XLA traces — the counting wrapper
    is the engine's trace hook, which runs at trace time only."""
    engine = CompiledPipeline(fitted, buckets=(4, 8))
    sizes = [1, 2, 3, 4, 5, 6, 7, 8]
    for n in sizes:
        engine.apply(batch(n, seed=n))
    assert len(set(sizes)) >= 6
    assert engine.metrics.compile_count == 2, engine.metrics.summary()
    assert engine.metrics.compiles.snapshot() == {4: 1, 8: 1}
    # dispatches: one per request, routed to the covering bucket
    assert engine.metrics.dispatches.snapshot() == {4: 4, 8: 4}


def test_padded_rows_do_not_leak(fitted):
    """Bucketed output equals the unbucketed interpreter apply on the
    valid rows."""
    engine = CompiledPipeline(fitted, buckets=(8,))
    x = batch(5)
    got = np.asarray(engine.apply(x))
    want = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(x))).array()
    )
    assert got.shape == (5, 3)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_oversized_batch_chunks_through_max_bucket(fitted):
    engine = CompiledPipeline(fitted, buckets=(2, 4))
    x = batch(11)  # 4 + 4 + 3 -> buckets 4, 4, 4
    got = np.asarray(engine.apply(x))
    want = np.asarray(
        fitted.apply(Dataset.from_array(jnp.asarray(x))).array()
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert engine.metrics.compile_count <= 2
    assert engine.metrics.examples.total == 11


def test_dataset_input_and_bucket_for(fitted):
    engine = CompiledPipeline(fitted, buckets=(4, 16))
    assert engine.bucket_for(1) == 4
    assert engine.bucket_for(4) == 4
    assert engine.bucket_for(5) == 16
    with pytest.raises(ValueError):
        engine.bucket_for(17)
    ds = Dataset.from_array(jnp.asarray(batch(3)))
    got = np.asarray(engine.apply(ds, sync=True))
    want = np.asarray(fitted.apply(ds).array())
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_warmup_precompiles_all_buckets(fitted):
    engine = CompiledPipeline(fitted, buckets=(2, 4, 8))
    times = engine.warmup(example=jnp.zeros((D,), jnp.float32))
    assert sorted(times) == [2, 4, 8]
    assert engine.metrics.compile_count == 3
    # traffic after warmup compiles nothing new
    for n in (1, 3, 5, 7, 8):
        engine.apply(batch(n, seed=n))
    assert engine.metrics.compile_count == 3


def test_warmup_from_template_batch(fitted):
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(batch=batch(9))
    assert engine.metrics.compile_count == 1
    with pytest.raises(ValueError):
        engine.warmup()
    with pytest.raises(ValueError):
        engine.warmup(example=jnp.zeros(D), buckets=[3])


def test_empty_and_bad_buckets(fitted):
    with pytest.raises(ValueError):
        CompiledPipeline(fitted, buckets=())
    with pytest.raises(ValueError):
        CompiledPipeline(fitted, buckets=(0, 4))
    engine = CompiledPipeline(fitted, buckets=(4,))
    with pytest.raises(ValueError):
        engine.apply(batch(0))


def test_metrics_summary_shape(fitted):
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.apply(batch(3), sync=True)
    s = engine.metrics.summary()
    assert s["examples"] == 3
    assert s["padded_rows"] == 1
    assert s["compiles_per_bucket"] == {"4": 1}
    assert s["dispatch_p50_ms"] is not None


def test_warmup_registers_cost_models(fitted):
    """Warmup pulls each bucket program's static XLA cost model via the
    AOT lower/compile path (which shares the jit caches — the
    compile-count contract holds) — on this container's CPU backend
    cost_analysis IS available, so flops/bytes land per bucket and
    scale with the bucket size."""
    engine = CompiledPipeline(fitted, buckets=(4, 8))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    assert engine.metrics.compile_count == 2  # AOT added no traces
    models = engine.metrics.cost_models
    assert set(models) == {4, 8}
    assert models[4]["flops"] > 0
    # twice the rows through the same program ~ twice the modeled work
    assert models[8]["flops"] == pytest.approx(
        2 * models[4]["flops"], rel=0.2
    )
    # dispatches then attribute modeled FLOPs to traffic
    engine.apply(batch(3), sync=True)
    assert engine.metrics.device_flops.total == models[4]["flops"]


def test_cost_analysis_unavailable_degrades_to_absent(fitted, monkeypatch):
    """The graceful-degradation contract: a backend returning no cost
    analysis (None/empty) yields ABSENT cost/MFU/roofline series — not
    zeros, not a crash — and serving works identically."""
    from keystone_tpu.observability import device as device_mod
    from keystone_tpu.observability.prometheus import render
    from keystone_tpu.observability.registry import MetricsRegistry

    monkeypatch.setattr(
        device_mod, "compiled_cost_model", lambda compiled: {}
    )
    reg = MetricsRegistry()
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.metrics.register(registry=reg, engine="no-cost")
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    assert engine.metrics.cost_models == {}
    assert engine.metrics.mfu() is None
    assert engine.metrics.roofline_bound(4) is None
    got = np.asarray(engine.apply(batch(3), sync=True))
    assert got.shape == (3, 3)
    text = render(reg.collect())
    assert "keystone_device_flops_per_dispatch" not in text
    assert "keystone_serving_mfu" not in text
    assert "keystone_device_roofline_bound" not in text
    # goodput accounting is dispatch-side, not cost-model-side: present
    assert (
        'keystone_serving_goodput_rows_total{engine="no-cost",'
        'bucket="4"} 3' in text
    )


def test_cost_model_lowering_failure_is_nonfatal(fitted):
    """An AOT lower/compile that raises (backends without AOT support)
    is swallowed inside ``_register_cost_model``: warmup and serving
    keep working, the model stays absent."""
    engine = CompiledPipeline(fitted, buckets=(4,))

    class BoomFn:
        def lower(self, *a, **k):
            raise NotImplementedError("no AOT on this backend")

    engine._register_cost_model(4, BoomFn(), None)
    assert engine.metrics.cost_models == {}
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    assert np.asarray(engine.apply(batch(2), sync=True)).shape == (2, 3)


@pytest.mark.needs_mesh8
def test_sharded_engine_matches_unsharded(fitted, mesh8):
    """Multi-chip serving: buckets round up to the shard count, the
    staged batch is placed over the mesh data axis, results match."""
    engine = CompiledPipeline(fitted, buckets=(2, 12), shard=True)
    assert engine.buckets == (8, 16)  # rounded to 8 data shards
    for n in (1, 5, 9, 16):
        x = batch(n, seed=n)
        got = np.asarray(engine.apply(x, sync=True))
        want = np.asarray(
            fitted.apply(Dataset.from_array(jnp.asarray(x))).array()
        )
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert engine.metrics.compile_count <= 2


def test_fitted_pipeline_compiled_constructor(fitted):
    engine = fitted.compiled(buckets=(4,))
    assert isinstance(engine, CompiledPipeline)
    x = batch(2)
    np.testing.assert_allclose(
        np.asarray(engine.apply(x)),
        np.asarray(fitted.apply(Dataset.from_array(jnp.asarray(x))).array()),
        rtol=1e-5, atol=1e-6,
    )
