"""Staged lane pipeline (serving/pipeline.py behind
``MicroBatcher(pipeline_depth=N)``): pipelined-vs-serial BIT identity
under mixed-size load, the single-entry fast path, mid-flight engine
swap (old-engine completion + staging-pool rebuild), host-featurize
items mode, buffer-pool reuse (no per-window host allocation growth),
backpressure shedding through the gateway, and the per-stage
metrics/bottleneck attribution."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.serving.pipeline import HostBufferPool

from test_engine import D, batch, make_fitted


@pytest.fixture(scope="module")
def fitted():
    return make_fitted()


def _run_bursts(mb, bursts):
    """Submit each burst, await it fully (deterministic windows: with a
    generous deadline every burst coalesces into exactly one window),
    return the rows in submission order."""
    rows = []
    for xs in bursts:
        futs = [mb.submit(x) for x in xs]
        rows.extend(np.asarray(f.result(timeout=60)) for f in futs)
    return rows


def test_pipelined_matches_serial_bitwise_mixed_sizes(fitted):
    """The tentpole's correctness bar: the staged pipeline composes the
    engine's same stage primitives over identical values, so outputs
    are BIT-identical to serial dispatch — across window sizes hitting
    every bucket, including the size-1 fast path."""
    rng = np.random.default_rng(31)
    sizes = [1, 3, 4, 7, 8, 2, 8, 1]
    bursts = [
        [rng.standard_normal(D).astype(np.float32) for _ in range(n)]
        for n in sizes
    ]
    serial_engine = CompiledPipeline(fitted, buckets=(4, 8))
    serial_engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(
        serial_engine, max_delay_ms=150.0, pipeline_depth=0
    ) as mb:
        want = _run_bursts(mb, bursts)

    piped_engine = CompiledPipeline(fitted, buckets=(4, 8))
    piped_engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(
        piped_engine, max_delay_ms=150.0, pipeline_depth=2
    ) as mb:
        got = _run_bursts(mb, bursts)

    assert len(got) == len(want) == sum(sizes)
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(
            g, w, err_msg=f"row {i} differs between serial and pipelined"
        )
    # the pipelined run actually went through the stage chain
    report = piped_engine.metrics.pipeline_report()
    assert report is not None and report["windows"] == len(sizes)


def test_pipelined_concurrent_load_matches_serial(fitted):
    """Concurrent mixed-size load: windows coalesce nondeterministically
    across 4 client threads, but every request's row still equals the
    serial batcher's row for the same input (row values are independent
    of window grouping through the bucketed program)."""
    engine = CompiledPipeline(fitted, buckets=(4, 16))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    n = 32
    xs = batch(n, seed=33)
    ref_engine = CompiledPipeline(fitted, buckets=(4, 16))
    ref_engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(
        ref_engine, max_delay_ms=100.0, pipeline_depth=0
    ) as mb:
        want = [
            np.asarray(f.result(timeout=60))
            for f in [mb.submit(x) for x in xs]
        ]
    futures = [None] * n
    with MicroBatcher(
        engine, max_delay_ms=5.0, pipeline_depth=2
    ) as mb:
        barrier = threading.Barrier(4)

        def client(tid):
            barrier.wait()
            for i in range(tid, n, 4):
                futures[i] = mb.submit(xs[i])
                if i % 3 == 0:
                    time.sleep(0.002)  # vary window composition

        threads = [
            threading.Thread(target=client, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rows = [np.asarray(f.result(timeout=60)) for f in futures]
    for i in range(n):
        np.testing.assert_array_equal(rows[i], want[i])
    assert engine.metrics.request_latency.count == n


def test_single_entry_fast_path_aliases_no_copy(fitted):
    """A one-request window skips the stack copy: ``_assemble`` lifts
    the caller's tree to a [1, ...] VIEW (owned=False), and the full
    path still returns the right row without corrupting the caller's
    buffer (the engine keeps its protective copy for unowned views)."""
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    x = batch(1, seed=35)[0]
    keep = x.copy()
    with MicroBatcher(engine, max_delay_ms=5.0, pipeline_depth=2) as mb:
        lifted, owned = mb._assemble([x])
        assert owned is False
        assert lifted.shape == (1, D)
        assert np.shares_memory(lifted, x), "fast path must not copy"
        out = np.asarray(mb.submit(x).result(timeout=30))
    assert out.shape == (3,)
    np.testing.assert_array_equal(x, keep)  # caller's buffer untouched


def test_swap_engine_mid_flight_rebuilds_pool(fitted):
    """swap_engine under a pipelined lane: windows already in the
    stages finish on their coalesce-time engine, the host staging pool
    is rebuilt (generation bump — old-bucket buffers drop instead of
    re-pooling), and post-swap traffic runs on the replacement."""
    old = CompiledPipeline(fitted, buckets=(4,), name="pswap-old")
    old.warmup(example=jnp.zeros((D,), jnp.float32))
    new = CompiledPipeline(fitted, buckets=(2, 8), name="pswap-new")
    new.warmup(example=jnp.zeros((D,), jnp.float32))
    xs = batch(12, seed=37)
    ref = CompiledPipeline(fitted, buckets=(4,))
    ref.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(ref, max_delay_ms=100.0, pipeline_depth=0) as mb:
        want_old = [
            np.asarray(f.result(timeout=60))
            for f in [mb.submit(x) for x in xs[:4]]
        ]
    with MicroBatcher(old, max_delay_ms=5.0, pipeline_depth=2) as mb:
        pool = mb._pipeline.pool
        first = [mb.submit(x) for x in xs[:4]]
        for f, w in zip(first, want_old):
            np.testing.assert_array_equal(
                np.asarray(f.result(timeout=60)), w
            )
        gen0, alloc0 = pool.generation, pool.allocations
        assert alloc0 >= 1  # the first windows cut staging buffers
        returned = mb.swap_engine(new)
        assert returned is old
        assert pool.generation == gen0 + 1  # pool rebuilt on swap
        second = [mb.submit(x) for x in xs[4:]]
        rows = [np.asarray(f.result(timeout=60)) for f in second]
    assert all(r.shape == (3,) for r in rows)
    # post-swap traffic ran on the replacement engine, and its windows
    # cut NEW staging buffers (the old engine's are dropped, not reused)
    assert new.metrics.examples.total == 8
    assert old.metrics.examples.total == 4
    assert pool.allocations > alloc0


def test_buffer_pool_reuse_no_allocation_growth(fitted):
    """Steady-state same-bucket windows reuse pooled staging buffers:
    after the pool primes, more windows add ZERO host allocations."""
    engine = CompiledPipeline(fitted, buckets=(8,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    n_windows = 12
    with MicroBatcher(
        engine, max_delay_ms=100.0, max_batch=8, pipeline_depth=2
    ) as mb:
        pool = mb._pipeline.pool
        for k in range(n_windows):
            xs = batch(8, seed=100 + k)
            for f in [mb.submit(x) for x in xs]:
                f.result(timeout=60)
        allocations = pool.allocations
    assert engine.metrics.windows.total == n_windows
    # sequential awaited windows recycle one buffer; the bound below is
    # the pool's absolute cap (depth+1 per key), not per-window growth
    assert allocations <= pool.max_per_key, (
        f"{allocations} host staging allocations for {n_windows} windows"
    )


def test_host_featurize_items_mode(fitted):
    """The pluggable host-featurize hook (items-mode/tokenizer
    front-ends behind the engine): clients submit RAW items (here:
    python lists), the prep stage turns each coalesced window into the
    batched array tree — identically in serial and pipelined modes."""
    weights = np.linspace(0.5, 1.5, D).astype(np.float32)

    def featurize(items):
        # a stand-in for a fused tokenizer: list[list[float]] -> [n, D]
        return np.stack(
            [np.asarray(it, np.float32) * weights for it in items]
        )

    rng = np.random.default_rng(41)
    items = [list(rng.standard_normal(D).astype(np.float32)) for _ in range(6)]

    rows = {}
    for depth in (0, 2):
        engine = CompiledPipeline(fitted, buckets=(8,))
        engine.warmup(example=jnp.zeros((D,), jnp.float32))
        with MicroBatcher(
            engine, max_delay_ms=100.0, pipeline_depth=depth,
            host_featurize=featurize,
        ) as mb:
            futs = [mb.submit(it) for it in items]
            rows[depth] = [
                np.asarray(f.result(timeout=60)) for f in futs
            ]
        # raw items coalesced into shared windows (one spec stream)
        assert engine.metrics.max_coalesced >= 2
    for a, b in zip(rows[0], rows[2]):
        np.testing.assert_array_equal(a, b)


def test_backpressure_sheds_typed_overloaded(fitted):
    """End-to-end backpressure: a slow host-featurize stage fills the
    bounded stage queues, submit_window blocks the dispatcher, pending
    piles up behind the lanes, and the gateway's admission controller
    sheds the flood with typed Overloaded errors while every admitted
    request still resolves."""
    from keystone_tpu.gateway import Gateway, Overloaded
    from keystone_tpu.observability.registry import MetricsRegistry

    def slow_featurize(items):
        time.sleep(0.02)  # make host-prep the narrow stage
        return np.stack([np.asarray(it, np.float32) for it in items])

    xs = batch(8, seed=43)
    with Gateway(
        fitted, buckets=(4,), n_lanes=1, max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        registry=MetricsRegistry(), name="bp-gw",
        pipeline_depth=1, host_featurize=slow_featurize,
        max_pending=8, lane_capacity=4,
    ) as gw:
        admitted, shed = [], []
        deadline = time.perf_counter() + 20
        while not shed and time.perf_counter() < deadline:
            try:
                admitted.append(gw.predict(xs[len(admitted) % 8]))
            except Overloaded as e:
                shed.append(e)
        assert shed, "flood never hit the backpressure bound"
        assert shed[0].reason == "queue_full"
        for f in admitted:
            assert np.asarray(f.result(timeout=60)).shape == (3,)
        assert gw.metrics.shed_count("queue_full") >= 1


def test_stage_metrics_and_bottleneck_attribution(fitted):
    """After pipelined traffic every stage has a seconds series, the
    lane attributes a bottleneck stage, overlap efficiency is defined,
    and the stage families export through the registry scrape."""
    from keystone_tpu.observability.registry import MetricsRegistry

    reg = MetricsRegistry()
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.metrics.register(reg, engine="stage-metrics")
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(
        engine, max_delay_ms=50.0, max_batch=4, pipeline_depth=2
    ) as mb:
        for k in range(4):
            for f in [mb.submit(x) for x in batch(4, seed=50 + k)]:
                f.result(timeout=60)
        report = engine.metrics.pipeline_report()
    assert report["windows"] == 4
    assert set(report["stages"]) == {
        "host_prep", "upload", "compute", "deliver"
    }
    assert report["bottleneck"] in report["stages"]
    assert report["overlap_efficiency"] is not None
    for stage in report["stages"].values():
        assert stage["mean_ms"] >= 0
        assert stage["rate_per_s"] > 0
    from keystone_tpu.observability.prometheus import render

    text = render(reg.collect())
    assert "keystone_serving_stage_seconds" in text
    assert 'stage="host_prep"' in text
    assert "keystone_serving_pipeline_windows_total" in text
    assert "keystone_serving_pipeline_bottleneck" in text
    assert "keystone_serving_pipeline_overlap_efficiency" in text


def test_serial_engine_scrape_has_no_stage_series(fitted):
    """Serial engines never emit empty pipeline families."""
    from keystone_tpu.observability.registry import MetricsRegistry

    reg = MetricsRegistry()
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.metrics.register(reg, engine="serial-only")
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    engine.apply(batch(3, seed=55), sync=True)
    from keystone_tpu.observability.prometheus import render

    text = render(reg.collect())
    assert "keystone_serving_stage_seconds" not in text
    assert "keystone_serving_dispatches_total" in text


def test_dispatch_latency_completion_vs_enqueue(fitted):
    """The dispatch-accounting fix: ``serving.dispatch`` latency is now
    completion-timed (recorded at the sync point), while the old
    enqueue-only number survives as its own series."""
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    engine.apply(batch(3, seed=57), sync=True)
    m = engine.metrics
    # warmup syncs per bucket + the apply: both series populated,
    # completion-timed and enqueue-timed counted independently
    assert m.dispatch_latency.count >= 1
    assert m.dispatch_enqueue_latency.count >= 1
    # pipelined compute stage records the completion series too
    piped = CompiledPipeline(fitted, buckets=(4,))
    piped.warmup(example=jnp.zeros((D,), jnp.float32))
    base = piped.metrics.dispatch_latency.count
    with MicroBatcher(piped, max_delay_ms=5.0, pipeline_depth=2) as mb:
        mb.submit(batch(1, seed=58)[0]).result(timeout=30)
    assert piped.metrics.dispatch_latency.count == base + 1


def test_oversized_pinned_window_falls_back_serial(fitted):
    """A pinned max_batch wider than a post-swap engine's largest
    bucket degrades to the engine's chunked serial apply inside the
    compute stage — degraded, never wrong."""
    old = CompiledPipeline(fitted, buckets=(8,))
    old.warmup(example=jnp.zeros((D,), jnp.float32))
    small = CompiledPipeline(fitted, buckets=(4,))
    small.warmup(example=jnp.zeros((D,), jnp.float32))
    xs = batch(8, seed=61)
    ref = CompiledPipeline(fitted, buckets=(4,))
    ref.warmup(example=jnp.zeros((D,), jnp.float32))
    want = np.asarray(ref.apply(xs, sync=True))
    with MicroBatcher(
        old, max_delay_ms=10_000.0, max_batch=8, pipeline_depth=2
    ) as mb:
        mb.swap_engine(small)  # largest bucket (4) < pinned max_batch (8)
        futs = [mb.submit(x) for x in xs]  # fills one window of 8
        rows = np.stack(
            [np.asarray(f.result(timeout=60)) for f in futs]
        )
    np.testing.assert_array_equal(rows, want)


def test_stage_error_resolves_futures_and_recycles(fitted):
    """A failure inside a stage resolves that window's futures with the
    error (never hangs callers) and the NEXT window still works — the
    stage threads survive and pooled buffers aren't leaked."""
    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(engine, max_delay_ms=5.0, pipeline_depth=2) as mb:
        bad = mb.submit(np.zeros(D + 1, np.float32))  # wrong width:
        # fails at trace/compute time inside the stage chain
        with pytest.raises(Exception):
            bad.result(timeout=60)
        good = mb.submit(batch(1, seed=63)[0])
        assert np.asarray(good.result(timeout=60)).shape == (3,)


def test_host_prep_failure_does_not_poison_pool(fitted):
    """A featurize hook returning leaves with mismatched leading dims
    makes host_stage fail AFTER the window's staging buffers were
    acquired. The futures must get the error, the REAL buffers must go
    back to the pool (releasing the half-built window's host_tree=None
    used to poison that (bucket, spec) key: every later window sharing
    it popped the None instead of allocating), and the lane must keep
    serving."""
    def featurize(items):
        if any(i == "poison" for i in items):
            # two leaves, second with a leading dim that can't
            # broadcast into the (rows, D) staging buffer
            return (
                np.zeros((len(items), D), np.float32),
                np.zeros((len(items) + 1, D), np.float32),
            )
        return np.stack(
            [np.full((D,), float(len(s)), np.float32) for s in items]
        )

    engine = CompiledPipeline(fitted, buckets=(4,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(
        engine, max_delay_ms=5.0, pipeline_depth=2, host_featurize=featurize
    ) as mb:
        for _ in range(2):  # same poisoned key twice: the second window
            # must re-acquire a usable buffer, not a pooled None
            bad = mb.submit("poison")
            with pytest.raises(Exception):
                bad.result(timeout=60)
        pool = mb._pipeline.pool
        assert all(
            b is not None
            for bufs in pool._free.values()
            for b in bufs
        )
        good = mb.submit("abc")
        assert np.asarray(good.result(timeout=60)).shape == (3,)


def test_goodput_counters_bitwise_against_window_shapes(fitted):
    """Device-truth goodput accounting through the lane pipeline's
    compute stage: known window shapes -> EXACT per-bucket valid/padded
    row counts (the same ``record_dispatch`` path the serial engine
    uses — one code path, same numbers)."""
    engine = CompiledPipeline(fitted, buckets=(4, 8))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    # bursts of 3, 4, 7: windows of exactly those sizes (generous
    # coalesce deadline), dispatching buckets 4, 4, 8
    with MicroBatcher(
        engine, max_delay_ms=150.0, pipeline_depth=2
    ) as mb:
        _run_bursts(
            mb,
            [
                [batch(1, seed=70 + i)[0] for _ in range(n)]
                for i, n in enumerate((3, 4, 7))
            ],
        )
    m = engine.metrics
    assert m.examples.snapshot() == {4: 7, 8: 7}
    assert m.padded_rows.snapshot() == {4: 1, 8: 1}
    assert m.examples.total == 14
    assert m.padded_rows.total == 2
    # efficiency gauge agrees bitwise with the counters: 14 / 16
    assert m.padding_efficiency() == pytest.approx(14 / 16)


def test_staging_bytes_gauge_tracks_pool(fitted):
    """The HostBufferPool's live byte accounting reaches the engine's
    staging-bytes gauge, and pooled + outstanding bytes return to the
    pooled side once windows complete."""
    engine = CompiledPipeline(fitted, buckets=(8,))
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    with MicroBatcher(
        engine, max_delay_ms=50.0, max_batch=8, pipeline_depth=2
    ) as mb:
        pool = mb._pipeline.pool
        for f in [mb.submit(x) for x in batch(8, seed=80)]:
            f.result(timeout=60)
        # one 8-row float32 staging buffer of width D
        expect = 8 * D * 4
        assert pool.staging_bytes == expect * (
            pool.allocations
        )
        assert engine.metrics.staging_bytes == pool.staging_bytes
        # a swap resets the accounting with the pool AND the gauge —
        # a /metrics scrape right after the swap must not export the
        # pre-swap footprint
        mb.swap_engine(engine)
        assert pool.staging_bytes == 0
        assert engine.metrics.staging_bytes == 0
        # swap to an engine with its OWN metrics: post-swap windows
        # gauge the current engine's series only — the retired one
        # stays zeroed (no cross-engine double count)
        engine2 = CompiledPipeline(fitted, buckets=(8,), name="swap-tgt")
        engine2.warmup(example=jnp.zeros((D,), jnp.float32))
        mb.swap_engine(engine2)
        for f in [mb.submit(x) for x in batch(8, seed=81)]:
            f.result(timeout=60)
        assert engine.metrics.staging_bytes == 0
        assert engine2.metrics.staging_bytes == pool.staging_bytes > 0


class TestHostBufferPool:
    def test_acquire_reuse_and_cap(self):
        pool = HostBufferPool(max_per_key=2)
        gen, a = pool.acquire("k", lambda: np.zeros(4))
        assert pool.allocations == 1
        pool.release("k", gen, a)
        gen2, b = pool.acquire("k", lambda: np.zeros(4))
        assert b is a and pool.allocations == 1  # reused, no realloc
        # cap: releasing more than max_per_key drops the excess
        extras = [pool.acquire("k", lambda: np.zeros(4))[1] for _ in range(3)]
        for buf in [b] + extras:
            pool.release("k", gen2, buf)
        assert len(pool._free["k"]) == 2

    def test_generation_bump_drops_stale_buffers(self):
        pool = HostBufferPool()
        gen, a = pool.acquire("k", lambda: np.zeros(4))
        pool.reset()  # engine swap
        pool.release("k", gen, a)  # stale generation: dropped
        assert not pool._free.get("k")
        gen2, b = pool.acquire("k", lambda: np.zeros(4))
        assert gen2 == gen + 1 and b is not a

    def test_release_none_is_dropped(self):
        pool = HostBufferPool()
        gen, _ = pool.acquire("k", lambda: np.zeros(4))
        pool.release("k", gen, None)  # window died pre-attachment
        assert not pool._free.get("k")


def test_uint8_staging_pool_reuse_and_byte_accounting():
    """Device-featurize lanes stage RAW uint8: the per-(bucket, spec)
    pool keys carry the uint8 dtype, steady-state windows reuse the
    pooled raw buffers (zero allocation growth past the cap), and
    both the pool's byte ledger and the staging-bytes gauge account
    the one-byte-per-element footprint exactly (the f32 ledger would
    be 4x this for the same element count)."""
    from keystone_tpu.serving.bench import build_pipeline
    from keystone_tpu.serving.featurize import build_featurize_pipeline

    img, ch = 8, 3
    feat, feat_d = build_featurize_pipeline(
        img=img, channels=ch, filters=4, conv_size=3,
        pool_stride=4, pool_size=4, seed=3,
    )
    model = build_pipeline(d=feat_d, hidden=8, depth=2)
    engine = model.compiled(
        buckets=(4,), featurize=feat, aot_store=False, name="u8-pool"
    )
    engine.warmup(example=jnp.zeros((img, img, ch), jnp.uint8))
    rng = np.random.default_rng(9)
    n_windows = 10
    with MicroBatcher(
        engine, max_delay_ms=100.0, max_batch=4, pipeline_depth=2
    ) as mb:
        pool = mb._pipeline.pool
        for k in range(n_windows):
            raws = rng.integers(0, 256, (4, img, img, ch), dtype=np.uint8)
            for f in [mb.submit(r) for r in raws]:
                f.result(timeout=60)
        allocations = pool.allocations
        # the pool key pins the raw uint8 spec, and its cached size is
        # the raw byte footprint: bucket rows x img x img x ch x 1 B
        raw_buf_bytes = 4 * img * img * ch
        keys = list(pool._key_bytes)
        assert len(keys) == 1
        (bucket, _treedef, leaf_specs) = keys[0]
        assert bucket == 4
        assert leaf_specs == (((img, img, ch), "|u1"),)
        assert pool._key_bytes[keys[0]] == raw_buf_bytes
        assert pool.staging_bytes == raw_buf_bytes * allocations
        assert engine.metrics.staging_bytes == pool.staging_bytes
    # sequential awaited windows recycle buffers: the no-growth bound
    # is the pool cap (depth+1 per key), not per-window growth
    assert allocations <= pool.max_per_key, (
        f"{allocations} uint8 staging allocations for {n_windows} windows"
    )
    assert engine.metrics.windows.total == n_windows
    # and what went over the wire was the raw uint8 footprint: one
    # byte per element, a quarter of what the same elements cost in f32
    assert engine.metrics.h2d_bytes.total == n_windows * raw_buf_bytes
