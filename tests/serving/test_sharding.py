"""serving/sharding.py: the declarative model-sharding layer — named
param extraction, rule matching, shard/gather placement, the
ParamBinder functionalization seam, and the model-sharded engine end
to end (parity vs replicated, compile bound, MFU device accounting).
Runs on the conftest's 8 virtual CPU devices."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as PS

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.serving import sharding
from keystone_tpu.serving.bench import build_pipeline
from keystone_tpu.serving.engine import CompiledPipeline
from keystone_tpu.workflow.api import Transformer

D = 32


@pytest.fixture(scope="module")
def fitted():
    # depth-2 square model: params = {W (32,32), b (32,)} x2
    return build_pipeline(d=D, hidden=D, depth=2)


@pytest.fixture
def mesh18():
    """(data=1, model=8): the pure model-sharding mesh."""
    m = mesh_lib.make_mesh(n_data=1, n_model=8)
    with mesh_lib.use_mesh(m):
        yield m


def batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, D)).astype(np.float32)


# -- named params ----------------------------------------------------------

def test_named_params_names_and_values(fitted):
    params = sharding.named_params(fitted)
    assert sorted(params) == [
        "0/_Affine/W", "0/_Affine/b", "1/_Affine/W", "1/_Affine/b",
    ]
    assert np.shape(params["0/_Affine/W"]) == (D, D)
    assert np.shape(params["1/_Affine/b"]) == (D,)
    # structurally identical pipeline built separately: SAME names
    # (topo position keys the namespace, not node ids)
    assert sorted(sharding.named_params(build_pipeline(
        d=D, hidden=D, depth=2
    ))) == sorted(params)


def test_named_params_skips_non_arrays_and_private():
    @dataclasses.dataclass(eq=False)
    class WithExtras(Transformer):
        W: object
        config: dict = dataclasses.field(default_factory=dict)
        scale: float = 2.0

        def apply(self, x):
            return x @ self.W * self.scale

    fitted = WithExtras(
        jnp.eye(3, dtype=jnp.float32), {"k": np.ones(3)}
    ).to_pipeline().fit()
    params = sharding.named_params(fitted)
    # the dict (even though it holds an array) and the float stay
    # baked constants; only the direct array field is a named param
    assert list(params) == ["0/WithExtras/W"]


# -- rule matching ---------------------------------------------------------

def test_match_first_rule_wins_and_scalars_replicate():
    params = {
        "0/Op/W": np.ones((8, 8), np.float32),
        "0/Op/scale": np.float32(3.0),          # scalar
        "0/Op/one": np.ones((1,), np.float32),  # one element
    }
    specs = sharding.match_partition_rules(
        (
            (r"/W$", PS(None, "model")),
            (r"/W$", PS("model", None)),  # shadowed: first match wins
            (r".*", PS()),
        ),
        params,
    )
    assert specs["0/Op/W"] == PS(None, "model")
    assert specs["0/Op/scale"] == PS()
    assert specs["0/Op/one"] == PS()


def test_match_unmatched_raises_by_name_or_replicates():
    params = {"0/Op/W": np.ones((4, 4), np.float32)}
    with pytest.raises(ValueError, match="0/Op/W"):
        sharding.match_partition_rules((), params)
    specs = sharding.match_partition_rules(
        (), params, unmatched="replicate"
    )
    assert specs["0/Op/W"] == PS()
    with pytest.raises(ValueError, match="unmatched"):
        sharding.match_partition_rules((), params, unmatched="bogus")


def test_default_rules_split_weights_replicate_biases(fitted):
    specs = sharding.match_partition_rules(
        sharding.DEFAULT_RULES, sharding.named_params(fitted)
    )
    assert specs["0/_Affine/W"] == PS(None, mesh_lib.MODEL_AXIS)
    assert specs["1/_Affine/W"] == PS(None, mesh_lib.MODEL_AXIS)
    assert specs["0/_Affine/b"] == PS()
    assert specs["1/_Affine/b"] == PS()


def test_resolve_param_sharding_dict_validates_names(fitted):
    resolved = sharding.resolve_param_sharding(
        {"0/_Affine/W": PS(None, "model")}, fitted
    )
    # named params not in the dict default to replicated
    assert resolved["1/_Affine/W"] == PS()
    with pytest.raises(ValueError, match="nope"):
        sharding.resolve_param_sharding({"nope": PS()}, fitted)


# -- placement -------------------------------------------------------------

@pytest.mark.needs_mesh8
def test_shard_and_gather_roundtrip(mesh18):
    W = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    specs = {"w": PS(None, "model")}
    shard_fns = sharding.make_shard_fns(specs, mesh18)
    placed = shard_fns["w"](W)
    assert len(placed.addressable_shards) == 8
    assert placed.addressable_shards[0].data.shape == (16, 1)
    gathered = sharding.make_gather_fns(specs, mesh18)["w"](placed)
    np.testing.assert_array_equal(np.asarray(gathered), W)


@pytest.mark.needs_mesh8
def test_shard_fn_rejects_uneven_split_by_name(mesh18):
    fns = sharding.make_shard_fns({"w": PS(None, "model")}, mesh18)
    with pytest.raises(ValueError, match="w dim 1"):
        fns["w"](np.ones((4, 6), np.float32))  # 6 % 8 != 0


@pytest.mark.needs_mesh8
def test_shard_fn_rejects_unknown_axis_and_long_spec(mesh18):
    fns = sharding.make_shard_fns({"w": PS("bogus",)}, mesh18)
    with pytest.raises(ValueError, match="bogus"):
        fns["w"](np.ones((8,), np.float32))
    fns = sharding.make_shard_fns({"w": PS(None, None, "model")}, mesh18)
    with pytest.raises(ValueError, match="more entries"):
        fns["w"](np.ones((8, 8), np.float32))


@pytest.mark.needs_mesh8
def test_placed_shard_bytes_and_params_nbytes(mesh18, fitted):
    params = sharding.named_params(fitted)
    total = sharding.params_nbytes(params)
    assert total == 2 * (D * D + D) * 4
    specs = sharding.match_partition_rules(
        sharding.DEFAULT_RULES, params
    )
    fns = sharding.make_shard_fns(specs, mesh18)
    placed = {k: fns[k](v) for k, v in params.items()}
    per_dev = sharding.placed_shard_bytes(placed)
    assert len(per_dev) == 8
    # each device: 1/8 of each W + the full (replicated) biases
    want = 2 * (D * D // 8) * 4 + 2 * D * 4
    assert set(per_dev.values()) == {want}
    assert max(per_dev.values()) < total


# -- the ParamBinder functionalization seam --------------------------------

def test_param_binder_substitutes_and_restores(fitted):
    binder = sharding.ParamBinder(fitted)
    x = batch(4)
    want = np.asarray(fitted._batch_run(jnp.asarray(x)))
    got = np.asarray(jax.jit(binder.run)(binder.params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

    # substituted params are LIVE arguments, not baked constants:
    # zeroed weights change the answer through the same traced fn
    zeroed = {
        k: np.zeros_like(np.asarray(v)) for k, v in binder.params.items()
    }
    out0 = np.asarray(jax.jit(binder.run)(zeroed, jnp.asarray(x)))
    assert not np.allclose(out0, want)
    np.testing.assert_allclose(out0, 0.0, atol=1e-7)  # tanh(0)=0

    # after tracing, the binder's private copy holds the pristine
    # values again (no tracer leaked into a field) and the CALLER's
    # pipeline was never touched
    for i, nid in enumerate(binder._pipeline._topo):
        op = binder._pipeline.graph.operators[nid]
        orig = fitted.graph.operators[fitted._topo[i]]
        np.testing.assert_array_equal(
            np.asarray(op.W), np.asarray(orig.W)
        )


def test_param_binder_on_already_used_pipeline():
    """Regression: a pipeline that already RAN carries lazily-attached
    per-operator jit caches (``_vmapped_apply``) closed over the
    ORIGINAL operators — a shallow copy that kept them would silently
    skip substitution and serve the baked weights. The binder scrubs
    the copies, so substitution works on a warm pipeline too."""
    fitted = build_pipeline(d=8, hidden=8, depth=2)
    x = batch(3)[:, :8].copy()
    fitted._batch_run(jnp.asarray(x))  # populate the op caches
    binder = sharding.ParamBinder(fitted)
    zeroed = {
        k: np.zeros_like(np.asarray(v)) for k, v in binder.params.items()
    }
    out0 = np.asarray(jax.jit(binder.run)(zeroed, jnp.asarray(x)))
    np.testing.assert_allclose(out0, 0.0, atol=1e-7)


# -- sharding token --------------------------------------------------------

@pytest.mark.needs_mesh8
def test_sharding_token_varies_by_spec_and_mesh(fitted):
    params = sharding.named_params(fitted)
    specs = sharding.match_partition_rules(
        sharding.DEFAULT_RULES, params
    )
    m18 = mesh_lib.make_mesh(n_data=1, n_model=8)
    m24 = mesh_lib.make_mesh(n_data=2, n_model=4)
    t = sharding.sharding_token(specs, m18)
    assert t == sharding.sharding_token(specs, m18)  # deterministic
    assert t != sharding.sharding_token(specs, m24)  # mesh topology
    flipped = dict(specs)
    flipped["0/_Affine/W"] = PS("model", None)
    assert t != sharding.sharding_token(flipped, m18)  # spec tree


# -- the model-sharded engine end to end -----------------------------------

@pytest.mark.needs_mesh8
def test_model_sharded_engine_matches_replicated(fitted, mesh18):
    plain = CompiledPipeline(fitted, buckets=(4, 8), name="shd-plain")
    engine = CompiledPipeline(
        fitted, buckets=(4, 8), name="shd-model", param_sharding=True
    )
    assert engine.model_sharded and engine.mesh is mesh18
    # params placed sharded: more than one shard per weight matrix
    placed_w = engine._placed_params["0/_Affine/W"]
    assert len(placed_w.addressable_shards) == 8
    for n in (1, 3, 4, 7, 8, 11):
        x = batch(n, seed=n)
        np.testing.assert_allclose(
            np.asarray(engine.apply(x, sync=True)),
            np.asarray(plain.apply(x, sync=True)),
            rtol=1e-5, atol=1e-6,
        )
    # the compile bound holds for GSPMD programs too
    assert engine.metrics.compile_count == 2


@pytest.mark.needs_mesh8
def test_model_sharded_composes_with_batch_sharding(fitted):
    """Rows over data, weights over model — one 2-D mesh."""
    m = mesh_lib.make_mesh(n_data=2, n_model=4)
    with mesh_lib.use_mesh(m):
        engine = CompiledPipeline(
            fitted, buckets=(4, 8), name="shd-2d",
            shard=True, param_sharding=True,
        )
    assert engine.buckets == (4, 8)  # 2 data shards divide both
    plain = CompiledPipeline(fitted, buckets=(4, 8), name="shd-2d-p")
    x = batch(7, seed=7)
    np.testing.assert_allclose(
        np.asarray(engine.apply(x, sync=True)),
        np.asarray(plain.apply(x, sync=True)),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.needs_mesh8
def test_model_sharded_composes_with_device_featurize(mesh18):
    """The fused featurize∘model program with the MODEL's params
    sharded: the featurize stage stays baked/replicated, the model
    weights ride as sharded arguments, outputs match the replicated
    fused engine."""
    from keystone_tpu.serving.featurize import build_featurize_pipeline

    feat, feat_d = build_featurize_pipeline(img=8)
    model = build_pipeline(d=feat_d, hidden=64, depth=2)
    raw = np.random.default_rng(5).integers(
        0, 256, (3, 8, 8, 3), dtype=np.uint8
    )
    plain = CompiledPipeline(
        model, buckets=(4,), featurize=feat, name="shd-fz-p"
    )
    shd = CompiledPipeline(
        model, buckets=(4,), featurize=feat, name="shd-fz-s",
        param_sharding=True,
    )
    np.testing.assert_allclose(
        np.asarray(shd.apply(raw, sync=True)),
        np.asarray(plain.apply(raw, sync=True)),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.needs_mesh8
def test_model_sharded_rounds_buckets_to_data_shards(fitted):
    """Regression: a model-sharded engine on a mesh with a >1 data
    axis mesh-places its staged batches, so buckets must round up to
    the data-shard count exactly as under ``shard=`` — an unrounded
    bucket failed every dispatch's device_put with a divisibility
    error."""
    m = mesh_lib.make_mesh()  # the DEFAULT mesh: data=8, model=1
    with mesh_lib.use_mesh(m):
        engine = CompiledPipeline(
            fitted, buckets=(2, 12), name="shd-round",
            param_sharding=True,
        )
    assert engine.buckets == (8, 16)
    plain = CompiledPipeline(fitted, buckets=(2, 12), name="shd-round-p")
    x = batch(3, seed=3)
    np.testing.assert_allclose(
        np.asarray(engine.apply(x, sync=True)),
        np.asarray(plain.apply(x, sync=True)),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.needs_mesh8
def test_model_sharded_warmup_then_no_new_compiles(fitted, mesh18):
    engine = CompiledPipeline(
        fitted, buckets=(4, 8), name="shd-warm", param_sharding=True,
        aot_store=None,
    )
    engine.warmup(example=jnp.zeros((D,), jnp.float32))
    before = engine.metrics.compile_count
    assert before == 2
    for n in (1, 4, 6, 8):
        engine.apply(batch(n, seed=n), sync=True)
    assert engine.metrics.compile_count == before


@pytest.mark.needs_mesh8
def test_unmatched_param_fails_engine_construction_by_default(mesh18):
    @dataclasses.dataclass(eq=False)
    class Odd(Transformer):
        weird: object

        def apply(self, x):
            return x + self.weird

    fitted = Odd(
        jnp.ones((D,), jnp.float32)
    ).to_pipeline().fit()
    with pytest.raises(ValueError, match="0/Odd/weird"):
        CompiledPipeline(
            fitted, buckets=(4,), name="shd-odd",
            param_sharding=((r"/W$", PS(None, "model")),),
        )
    # the explicit flag downgrades to replication
    eng = CompiledPipeline(
        fitted, buckets=(4,), name="shd-odd2",
        param_sharding=((r"/W$", PS(None, "model")),),
        param_sharding_unmatched="replicate",
    )
    assert eng.param_sharding["0/Odd/weird"] == PS()


# -- MFU / device accounting (the audit satellite) -------------------------

@pytest.fixture
def pinned_peak(monkeypatch):
    from keystone_tpu.observability import device as device_obs

    monkeypatch.setenv("KEYSTONE_PEAK_FLOPS", "1e9")
    device_obs.reset_device_table()
    yield 1e9
    # drop the table derived under the pinned env so later tests
    # re-derive real peaks (monkeypatch restores the env afterwards)
    device_obs.reset_device_table()


@pytest.mark.needs_mesh8
def test_mfu_denominator_counts_mesh_devices_once(
    fitted, mesh18, pinned_peak
):
    """The regression pin for the accounting audit: a model-sharded
    engine's MFU denominator is peak x MESH devices (8) — counted from
    the mesh, exactly once — while a replicated engine's stays peak x
    1. Pinned via KEYSTONE_PEAK_FLOPS so the denominator is a known
    number, with an injectable clock so the windowed rate divides by
    a statement, not a wall clock."""
    from keystone_tpu.serving.metrics import ServingMetrics

    now = [0.0]
    sharded = CompiledPipeline(
        fitted, buckets=(8,), name="mfu-shd", param_sharding=True,
        metrics=ServingMetrics(clock=lambda: now[0]),
    )
    plain = CompiledPipeline(
        fitted, buckets=(8,), name="mfu-plain",
        metrics=ServingMetrics(clock=lambda: now[0]),
    )
    assert sharded.metrics._n_devices == 8
    assert plain.metrics._n_devices == 1
    sharded.warmup(example=jnp.zeros((D,), jnp.float32))
    plain.warmup(example=jnp.zeros((D,), jnp.float32))
    if not sharded.metrics.cost_models or not plain.metrics.cost_models:
        pytest.skip("backend reports no XLA cost analysis")
    sharded.apply(batch(8), sync=True)
    plain.apply(batch(8), sync=True)
    now[0] = 10.0
    for eng, n_dev in ((sharded, 8), (plain, 1)):
        mfu = eng.metrics.mfu()
        fps = eng.metrics.flops_per_sec()
        assert mfu is not None and fps > 0
        assert mfu == pytest.approx(fps / (pinned_peak * n_dev))


@pytest.mark.needs_mesh8
def test_two_sharded_lanes_each_count_the_mesh_not_lanes_x_mesh(
    fitted, mesh18, pinned_peak
):
    """N lanes sharing one mesh: each lane's engine runs on the SAME 8
    devices, so each denominator is 8 — never 8 x n_lanes."""
    from keystone_tpu.gateway import Gateway

    gw = Gateway(
        fitted, buckets=(4, 8), n_lanes=2, param_sharding=True,
        warmup_example=jnp.zeros((D,), jnp.float32), name="mfu-gw",
    )
    try:
        for lane in gw.pool.lanes:
            assert lane.engine.model_sharded
            assert lane.engine.metrics._n_devices == 8
    finally:
        gw.close()


# -- gateway lifecycle carries the sharding --------------------------------

@pytest.mark.needs_mesh8
def test_gateway_swap_preserves_model_sharding(fitted, mesh18):
    from keystone_tpu.gateway import Gateway

    gw = Gateway(
        fitted, buckets=(4, 8), n_lanes=1, param_sharding=True,
        warmup_example=jnp.zeros((D,), jnp.float32), name="shd-gw",
    )
    plain = CompiledPipeline(fitted, buckets=(4, 8), name="shd-gw-ref")
    try:
        x = batch(1)[0]
        want = np.asarray(plain.apply(batch(1), sync=True))[0]
        got = np.asarray(gw.predict(x).result(timeout=30))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        old = gw.pool.lanes[0].engine
        assert gw.rebucket(force=True)
        new = gw.pool.lanes[0].engine
        assert new is not old and new.model_sharded
        got2 = np.asarray(gw.predict(x).result(timeout=30))
        np.testing.assert_allclose(got2, want, rtol=1e-5, atol=1e-6)
    finally:
        gw.close()
