"""Device-side featurization (``CompiledPipeline(featurize=...)``):
the fused featurize∘model bucket programs must match the two-stage
host path numerically, keep the bounded-compile contract, account raw
H2D bytes exactly (`keystone_serving_h2d_bytes_total`), serve raw
uint8 through the batcher/pipeline bit-identically in serial and
pipelined modes, and survive gateway swaps with the fused stage
intact."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.batching import MicroBatcher
from keystone_tpu.serving.bench import build_pipeline
from keystone_tpu.serving.featurize import build_featurize_pipeline

IMG, C = 8, 3
RAW_BYTES = IMG * IMG * C  # uint8: one byte per pixel-channel


@pytest.fixture(scope="module")
def featurize():
    # tiny geometry: 8x8x3 raw -> 3x3 conv (4 filters) -> rectify ->
    # 4/4 sum-pool -> vectorize; compile cost is milliseconds
    fitted, feat_d = build_featurize_pipeline(
        img=IMG, channels=C, filters=4, conv_size=3,
        pool_stride=4, pool_size=4, seed=3,
    )
    return fitted, feat_d


@pytest.fixture(scope="module")
def model(featurize):
    _, feat_d = featurize
    return build_pipeline(d=feat_d, hidden=8, depth=2)


def raw_batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (n, IMG, IMG, C), dtype=np.uint8)


def fused_engine(model, featurize, buckets=(2, 4), name=None, **kw):
    feat, _ = featurize
    eng = model.compiled(
        buckets=buckets, featurize=feat, name=name, aot_store=False, **kw
    )
    eng.warmup(example=jnp.zeros((IMG, IMG, C), jnp.uint8))
    return eng


def two_stage(model, featurize, raw):
    feat, _ = featurize
    feats = feat._batch_run(jnp.asarray(raw))
    return np.asarray(model._batch_run(feats))[: len(raw)]


def test_fused_matches_two_stage_with_bounded_compiles(model, featurize):
    eng = fused_engine(model, featurize, name="dfz-match")
    for n in (1, 2, 3, 4):
        raw = raw_batch(n, seed=n)
        got = np.asarray(eng.apply(raw, sync=True))
        want = two_stage(model, featurize, raw)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
    # padded dispatches never contaminate valid rows, and the compile
    # count stays one per bucket however many sizes arrived
    assert eng.metrics.compiles.snapshot() == {2: 2, 4: 2} or (
        eng.metrics.compile_count == len(eng.buckets)
    )
    assert eng.metrics.compile_count == len(eng.buckets)


def test_oversized_raw_batch_chunks(model, featurize):
    eng = fused_engine(model, featurize, name="dfz-chunk")
    raw = raw_batch(9, seed=42)  # > max bucket 4: chunks 4+4+1
    got = np.asarray(eng.apply(raw, sync=True))
    np.testing.assert_allclose(
        got, two_stage(model, featurize, raw), rtol=1e-4, atol=1e-6
    )
    assert eng.metrics.compile_count == len(eng.buckets)


def test_h2d_bytes_accounts_raw_uint8(model, featurize):
    """The wire-bytes fact: a fused dispatch stages bucket * raw-uint8
    bytes; the same model behind host featurization stages bucket *
    feat_dim * 4 f32 bytes — the counter IS the reduction."""
    feat, feat_d = featurize
    eng = fused_engine(model, featurize, name="dfz-bytes")
    eng.apply(raw_batch(3), sync=True)  # bucket 4
    assert eng.metrics.h2d_bytes.snapshot() == {4: 4 * RAW_BYTES}
    s = eng.metrics.summary()
    assert s["h2d_bytes_total"] == 4 * RAW_BYTES
    assert s["h2d_bytes_per_example"] == round(4 * RAW_BYTES / 3, 1)

    plain = model.compiled(buckets=(2, 4), aot_store=False, name="dfz-f32")
    plain.warmup(example=jnp.zeros((feat_d,), jnp.float32))
    feats = np.asarray(feat._batch_run(jnp.asarray(raw_batch(3))))[:3]
    plain.apply(feats, sync=True)
    assert plain.metrics.h2d_bytes.snapshot() == {4: 4 * feat_d * 4}


def test_h2d_bytes_family_on_scrape(model, featurize):
    reg = MetricsRegistry()
    eng = fused_engine(model, featurize, name="ignored")
    eng.metrics.register(registry=reg, engine="dfz-scrape")
    eng.apply(raw_batch(2), sync=True)
    fams = {f.name: f for f in reg.collect()}
    fam = fams["keystone_serving_h2d_bytes_total"]
    assert fam.mtype == "counter"
    samples = {
        s.labels["bucket"]: s.value
        for s in fam.samples
        if s.labels.get("engine") == "dfz-scrape"
    }
    assert samples == {"2": 2 * RAW_BYTES}


def test_batcher_raw_uint8_serial_vs_pipelined_bitwise(model, featurize):
    """Raw uint8 requests ride the batcher in ARRAY mode (no host
    hook): pooled uint8 staging buffers, fused dispatch, and the
    pipelined lane stays bit-identical to serial."""
    raws = [raw_batch(1, seed=100 + i)[0] for i in range(6)]
    rows = {}
    for depth in (0, 2):
        eng = fused_engine(model, featurize, name=f"dfz-mb-{depth}")
        with MicroBatcher(
            eng, max_delay_ms=100.0, pipeline_depth=depth
        ) as mb:
            futs = [mb.submit(r) for r in raws]
            rows[depth] = [np.asarray(f.result(timeout=60)) for f in futs]
        assert eng.metrics.examples.total == len(raws)
    for a, b in zip(rows[0], rows[2]):
        np.testing.assert_array_equal(a, b)


# -- the flagship chain ----------------------------------------------------

FIMG = 34  # must clear the LCS keypoint border (img > 2*16)


@pytest.fixture(scope="module")
def flagship():
    from keystone_tpu.serving.featurize import (
        build_flagship_featurize_pipeline,
    )

    # smallest honest geometry: every node class of the full chain
    # (gray->SIFT and LCS branches, PCA, GMM FV, Hellinger/L2, gather,
    # combine) at compile costs a CPU test run can afford
    return build_flagship_featurize_pipeline(
        img=FIMG, desc_dim=8, vocab=8
    )


def test_flagship_branched_dag_fuses_and_matches_two_stage(flagship):
    """The tentpole seam contract on the BRANCHED flagship DAG: the
    gather/combine graph composes through ``CompiledPipeline
    (featurize=)`` exactly like a linear chain — one program per
    bucket, raw uint8 staged and accounted exactly, fused outputs
    matching the two-stage host path at the repo's fusion tolerance
    (single-program XLA reassociates float ops across the seam)."""
    feat, feat_d = flagship
    model = build_pipeline(d=feat_d, hidden=8, depth=2)
    eng = model.compiled(
        buckets=(2, 4), featurize=feat, aot_store=False, name="dfz-fl"
    )
    eng.warmup(example=jnp.zeros((FIMG, FIMG, C), jnp.uint8))
    assert eng.metrics.compile_count == len(eng.buckets)
    rng = np.random.default_rng(21)
    raw = rng.integers(0, 256, (3, FIMG, FIMG, C), dtype=np.uint8)
    got = np.asarray(eng.apply(raw, sync=True))
    feats = feat._batch_run(jnp.asarray(raw))
    want = np.asarray(model._batch_run(feats))[:3]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # no retrace on dispatch, and the wire carried raw pixels
    assert eng.metrics.compile_count == len(eng.buckets)
    assert eng.metrics.h2d_bytes.snapshot() == {4: 4 * FIMG * FIMG * C}


def test_gateway_device_featurize_swap_keeps_fused_stage(model, featurize):
    """The full request plane over raw inputs: predicts match the
    two-stage reference, and a forced live rebucket rebuilds lane
    engines WITH the fused featurize stage (post-swap predicts still
    match and still stage raw bytes)."""
    from keystone_tpu.gateway import Gateway

    feat, _ = featurize
    raws = raw_batch(4, seed=7)
    want = two_stage(model, featurize, raws)
    with Gateway(
        model, buckets=(2, 4), n_lanes=1, max_delay_ms=2.0,
        device_featurize=feat,
        warmup_example=jnp.zeros((IMG, IMG, C), jnp.uint8),
        name="dfz-gw",
    ) as gw:
        got = [
            np.asarray(gw.predict(r).result(timeout=60)) for r in raws
        ]
        np.testing.assert_allclose(
            np.stack(got), want, rtol=1e-4, atol=1e-6
        )
        before = gw.pool.lanes[0].engine
        assert gw.rebucket(force=True)
        after = gw.pool.lanes[0].engine
        assert after is not before
        assert after.featurize is feat
        got2 = [
            np.asarray(gw.predict(r).result(timeout=60)) for r in raws
        ]
        np.testing.assert_allclose(
            np.stack(got2), want, rtol=1e-4, atol=1e-6
        )
        assert after.metrics.h2d_bytes.total > 0
