"""ServingMetrics: windowed rate, atomic latency snapshot, registry
bridge lifecycle."""

import gc

import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.metrics import ServingMetrics
from keystone_tpu.utils.profiling import LatencyRecorder


def test_latency_recorder_p95_and_snapshot():
    rec = LatencyRecorder()
    for v in range(1, 101):  # 1..100 ms
        rec.record(v / 1000.0)
    assert rec.p95 is not None
    snap = rec.snapshot()
    assert snap["count"] == 100
    assert abs(snap["total"] - 5.05) < 1e-9
    assert abs(snap["p50"] - 0.0505) < 1e-3
    assert abs(snap["p95"] - 0.09505) < 1e-3
    assert abs(snap["p99"] - 0.09901) < 1e-3
    # empty recorder: percentiles None, zeros for count/total
    empty = LatencyRecorder().snapshot()
    assert empty == {
        "count": 0, "total": 0.0, "p50": None, "p95": None, "p99": None,
    }


class FakeClock:
    """Injectable ``ServingMetrics`` clock: elapsed time becomes a
    statement (``advance``), not a ``time.sleep`` that a loaded CI
    host can stretch — the windowed-rate tests below used to divide
    by real tiny lifetimes and flake under full-suite load."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def test_windowed_rate_decays_to_zero_but_lifetime_does_not_jump():
    clk = FakeClock()
    m = ServingMetrics(clock=clk)
    clk.advance(1.0)
    m.record_dispatch(bucket=8, n_valid=8, seconds=0.001)
    # fresh traffic: the windowed rate sees all 8 examples
    assert m.examples_per_sec() > 0
    # a very small window that has already passed: rate decays to zero
    clk.advance(0.05)
    assert m.examples_per_sec(window=0.01) == 0.0
    # the lifetime average still counts them (the documented wart the
    # windowed gauge exists to fix: lifetime dilutes over idle time,
    # windowed goes to zero)
    assert m.examples_per_sec_lifetime() > 0


def test_summary_uses_windowed_rate_and_snapshot_quantiles():
    m = ServingMetrics()
    for _ in range(4):
        m.record_dispatch(bucket=8, n_valid=8, seconds=0.002)
    s = m.summary()
    assert "examples_per_sec" in s
    assert "examples_per_sec_lifetime" in s
    assert s["examples_per_sec"] > 0
    assert s["dispatch_p95_ms"] is not None
    assert s["dispatch_p50_ms"] <= s["dispatch_p99_ms"]
    assert s["request_p95_ms"] is None  # no micro-batched requests yet


def test_request_size_histogram_accumulates():
    m = ServingMetrics()
    m.record_dispatch(bucket=8, n_valid=3, seconds=0.001)
    m.record_dispatch(bucket=8, n_valid=3, seconds=0.001)
    m.record_dispatch(bucket=64, n_valid=40, seconds=0.001)
    assert m.request_sizes.snapshot() == {3: 2, 40: 1}


def test_register_exports_and_prunes_after_gc():
    reg = MetricsRegistry()
    m = ServingMetrics()
    m.record_dispatch(bucket=8, n_valid=5, seconds=0.001)
    label = m.register(registry=reg, engine="e-test")
    assert label == "e-test"
    fams = {f.name for f in reg.collect()}
    assert "keystone_serving_compiles_total" in fams
    assert "keystone_serving_dispatch_latency_seconds" in fams
    del m
    gc.collect()
    assert not any("keystone_serving" in f.name for f in reg.collect())


def test_global_register_is_idempotent():
    m = ServingMetrics()
    first = m.register()
    assert m.register() == first  # no double export


def test_windowed_rate_clamps_oversized_window():
    """Events older than RATE_WINDOW_S are pruned at record time, so a
    window larger than that must clamp instead of silently dividing a
    30s sum by more seconds (4x undercount otherwise)."""
    clk = FakeClock()
    m = ServingMetrics(clock=clk)
    m.record_dispatch(bucket=8, n_valid=8, seconds=0.001)
    clk.advance(0.05)
    lifetime = m.examples_per_sec()  # window = lifetime here (young)
    # the fake clock holds still between the reads, so the clamp is
    # EXACT (the real-clock version needed a 50 ms sleep and a wide
    # tolerance, and still flaked under host load)
    assert m.examples_per_sec(window=1e6) == pytest.approx(lifetime)
    assert m.examples_per_sec(window=1e6) > 0


def test_rate_events_prune_past_the_window():
    from keystone_tpu.serving.metrics import RATE_WINDOW_S

    clk = FakeClock()
    m = ServingMetrics(clock=clk)
    m.record_dispatch(bucket=8, n_valid=8)
    # a full rate window plus slack later, a new dispatch prunes the
    # old event: only the fresh 2 examples remain countable
    clk.advance(RATE_WINDOW_S + 1.0)
    m.record_dispatch(bucket=8, n_valid=2)
    assert m.examples_per_sec() == pytest.approx(2 / RATE_WINDOW_S)
    assert len(m._rate_events) == 1


def test_same_label_reregistration_transfers_ownership():
    """The engine-swap loop re-registers a NEW metrics under the SAME
    label while the old engine is still alive: the newest owner wins
    and exactly one series set per label survives (duplicate series
    would fail a whole Prometheus scrape)."""
    reg = MetricsRegistry()
    old = ServingMetrics()
    old.record_dispatch(bucket=8, n_valid=1, seconds=0.001)
    new = ServingMetrics()
    for _ in range(3):
        new.record_dispatch(bucket=8, n_valid=2, seconds=0.001)
    old.register(registry=reg, engine="prod")
    new.register(registry=reg, engine="prod")
    samples = [
        s
        for f in reg.collect()
        if f.name == "keystone_serving_examples_total"
        for s in f.samples
        if s.labels.get("engine") == "prod"
    ]
    assert len(samples) == 1  # no duplicate series
    assert samples[0].value == 6  # the NEW engine's counter
    # the superseded collector pruned itself; old engine still alive
    assert old.examples.total == 1


def _render(reg):
    from keystone_tpu.observability.prometheus import render

    return render(reg.collect())


def test_goodput_families_golden_strings():
    """Per-bucket goodput accounting on the scrape surface: valid vs
    padded rows per bucket and the windowed padding-efficiency gauge."""
    reg = MetricsRegistry()
    m = ServingMetrics()
    m.register(registry=reg, engine="gp")
    m.record_dispatch(bucket=8, n_valid=5)
    m.record_dispatch(bucket=8, n_valid=8)
    m.record_dispatch(bucket=4, n_valid=1)
    text = _render(reg)
    for want in (
        '# TYPE keystone_serving_goodput_rows_total counter',
        'keystone_serving_goodput_rows_total{engine="gp",bucket="4"} 1',
        'keystone_serving_goodput_rows_total{engine="gp",bucket="8"} 13',
        'keystone_serving_padded_rows_total{engine="gp",bucket="4"} 3',
        'keystone_serving_padded_rows_total{engine="gp",bucket="8"} 3',
        '# TYPE keystone_serving_padding_efficiency gauge',
    ):
        assert want in text, f"missing {want!r} in:\n{text}"
    # 14 valid rows of 20 shipped
    assert m.padding_efficiency() == pytest.approx(14 / 20)
    assert (
        f'keystone_serving_padding_efficiency{{engine="gp"}} {14 / 20!r}'
        in text
    )


def test_device_cost_families_golden_strings():
    """Cost model + peaks -> flops-per-dispatch, temp-HBM, modeled
    FLOPs counter, rolling MFU, and the roofline one-hot."""
    reg = MetricsRegistry()
    m = ServingMetrics()
    m.register(registry=reg, engine="dev")
    m.set_cost_model(8, {
        "flops": 1000.0, "bytes_accessed": 10.0, "temp_bytes": 64.0,
    })
    m.set_cost_model(4, {
        "flops": 10.0, "bytes_accessed": 1000.0,
    })
    # ridge point = 1e6 / 1e4 = 100 flops/byte: bucket 8 (100 f/B) is
    # compute-bound, bucket 4 (0.01 f/B) bandwidth-bound
    m.set_device_peaks(1e6, 1e4, n_devices=1)
    m.record_dispatch(bucket=8, n_valid=6)
    text = _render(reg)
    for want in (
        'keystone_device_flops_per_dispatch{engine="dev",bucket="4"} 10',
        'keystone_device_flops_per_dispatch{engine="dev",bucket="8"} 1000',
        'keystone_device_bytes_per_dispatch{engine="dev",bucket="8"} 10',
        'keystone_device_temp_hbm_bytes{engine="dev",bucket="8"} 64',
        'keystone_serving_device_flops_total{engine="dev"} 1000',
        'keystone_device_roofline_bound{engine="dev",bucket="8",'
        'bound="compute"} 1',
        'keystone_device_roofline_bound{engine="dev",bucket="8",'
        'bound="bandwidth"} 0',
        'keystone_device_roofline_bound{engine="dev",bucket="4",'
        'bound="bandwidth"} 1',
        '# TYPE keystone_serving_mfu gauge',
        'keystone_serving_mfu{engine="dev"} ',
    ):
        assert want in text, f"missing {want!r} in:\n{text}"
    # bucket 4 has no temp_bytes: that cell is absent, not zero
    assert (
        'keystone_device_temp_hbm_bytes{engine="dev",bucket="4"}'
        not in text
    )
    assert m.mfu() is not None and m.mfu() > 0
    assert m.roofline_bound(8) == "compute"
    assert m.roofline_bound(4) == "bandwidth"


def test_device_families_absent_without_cost_model_or_peaks():
    """No cost analysis and unknown hardware -> NO device-truth series
    (absent, never zeros), while the classic families still export."""
    reg = MetricsRegistry()
    m = ServingMetrics()
    m.register(registry=reg, engine="bare")
    m.record_dispatch(bucket=8, n_valid=5)
    text = _render(reg)
    for absent in (
        "keystone_device_flops_per_dispatch",
        "keystone_device_bytes_per_dispatch",
        "keystone_device_temp_hbm_bytes",
        "keystone_device_roofline_bound",
        "keystone_serving_device_flops_total",
        "keystone_serving_mfu",
        "keystone_serving_staging_bytes",
    ):
        assert absent not in text, f"{absent} must be absent:\n{text}"
    assert 'keystone_serving_examples_total{engine="bare"} 5' in text
    # peaks without a cost model still yield no MFU (nothing to count)
    m.set_device_peaks(1e12, 1e11)
    assert m.mfu() is None
    # a cost model with peaks but no bytes_accessed: no roofline
    m.set_cost_model(8, {"flops": 5.0})
    assert m.roofline_bound(8) is None


def test_empty_cost_model_is_dropped():
    m = ServingMetrics()
    m.set_cost_model(8, {})
    assert m.cost_models == {}


def test_padding_efficiency_none_before_traffic_and_windowed():
    clk = FakeClock()
    m = ServingMetrics(clock=clk)
    assert m.padding_efficiency() is None
    clk.advance(1.0)
    m.record_dispatch(bucket=8, n_valid=8)
    assert m.padding_efficiency() == pytest.approx(1.0)
    clk.advance(0.05)
    # outside the window: gauge decays to absent, not a stale 1.0
    assert m.padding_efficiency(window=0.01) is None


def test_mfu_scales_with_device_count():
    m = ServingMetrics()
    m.set_cost_model(8, {"flops": 100.0})
    m.record_dispatch(bucket=8, n_valid=8)
    # pin the windowed rate: MFU = flops/s over peak * n_devices
    m.flops_per_sec = lambda window=None: 500.0
    m.set_device_peaks(1e3, None, n_devices=1)
    assert m.mfu() == pytest.approx(0.5)
    m.set_device_peaks(1e3, None, n_devices=4)
    assert m.mfu() == pytest.approx(0.125)


def test_staging_bytes_gauge_exports_when_set():
    reg = MetricsRegistry()
    m = ServingMetrics()
    m.register(registry=reg, engine="stg")
    m.set_staging_bytes(4096)
    assert (
        'keystone_serving_staging_bytes{engine="stg"} 4096'
        in _render(reg)
    )


def test_engine_autoregisters_into_global_registry():
    from keystone_tpu.observability.registry import get_global_registry
    from keystone_tpu.serving.bench import build_pipeline

    fitted = build_pipeline(d=4, hidden=4, depth=1)
    engine = fitted.compiled(buckets=(2,), name="autoreg-test")
    assert engine.name == "autoreg-test"
    samples = [
        s
        for f in get_global_registry().collect()
        if f.name == "keystone_serving_examples_total"
        for s in f.samples
    ]
    assert any(s.labels.get("engine") == "autoreg-test" for s in samples)
