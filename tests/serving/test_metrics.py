"""ServingMetrics: windowed rate, atomic latency snapshot, registry
bridge lifecycle."""

import gc
import time

import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.metrics import ServingMetrics
from keystone_tpu.utils.profiling import LatencyRecorder


def test_latency_recorder_p95_and_snapshot():
    rec = LatencyRecorder()
    for v in range(1, 101):  # 1..100 ms
        rec.record(v / 1000.0)
    assert rec.p95 is not None
    snap = rec.snapshot()
    assert snap["count"] == 100
    assert abs(snap["total"] - 5.05) < 1e-9
    assert abs(snap["p50"] - 0.0505) < 1e-3
    assert abs(snap["p95"] - 0.09505) < 1e-3
    assert abs(snap["p99"] - 0.09901) < 1e-3
    # empty recorder: percentiles None, zeros for count/total
    empty = LatencyRecorder().snapshot()
    assert empty == {
        "count": 0, "total": 0.0, "p50": None, "p95": None, "p99": None,
    }


def test_windowed_rate_decays_to_zero_but_lifetime_does_not_jump():
    m = ServingMetrics()
    m.record_dispatch(bucket=8, n_valid=8, seconds=0.001)
    # fresh traffic: windowed rate sees all 8 examples over a tiny
    # lifetime (clamped window), so it's large and positive
    assert m.examples_per_sec() > 0
    # a very small window that has already passed: rate decays to zero
    time.sleep(0.05)
    assert m.examples_per_sec(window=0.01) == 0.0
    # the lifetime average still counts them (the documented wart the
    # windowed gauge exists to fix: lifetime dilutes over idle time,
    # windowed goes to zero)
    assert m.examples_per_sec_lifetime() > 0


def test_summary_uses_windowed_rate_and_snapshot_quantiles():
    m = ServingMetrics()
    for _ in range(4):
        m.record_dispatch(bucket=8, n_valid=8, seconds=0.002)
    s = m.summary()
    assert "examples_per_sec" in s
    assert "examples_per_sec_lifetime" in s
    assert s["examples_per_sec"] > 0
    assert s["dispatch_p95_ms"] is not None
    assert s["dispatch_p50_ms"] <= s["dispatch_p99_ms"]
    assert s["request_p95_ms"] is None  # no micro-batched requests yet


def test_request_size_histogram_accumulates():
    m = ServingMetrics()
    m.record_dispatch(bucket=8, n_valid=3, seconds=0.001)
    m.record_dispatch(bucket=8, n_valid=3, seconds=0.001)
    m.record_dispatch(bucket=64, n_valid=40, seconds=0.001)
    assert m.request_sizes.snapshot() == {3: 2, 40: 1}


def test_register_exports_and_prunes_after_gc():
    reg = MetricsRegistry()
    m = ServingMetrics()
    m.record_dispatch(bucket=8, n_valid=5, seconds=0.001)
    label = m.register(registry=reg, engine="e-test")
    assert label == "e-test"
    fams = {f.name for f in reg.collect()}
    assert "keystone_serving_compiles_total" in fams
    assert "keystone_serving_dispatch_latency_seconds" in fams
    del m
    gc.collect()
    assert not any("keystone_serving" in f.name for f in reg.collect())


def test_global_register_is_idempotent():
    m = ServingMetrics()
    first = m.register()
    assert m.register() == first  # no double export


def test_windowed_rate_clamps_oversized_window():
    """Events older than RATE_WINDOW_S are pruned at record time, so a
    window larger than that must clamp instead of silently dividing a
    30s sum by more seconds (4x undercount otherwise)."""
    m = ServingMetrics()
    m.record_dispatch(bucket=8, n_valid=8, seconds=0.001)
    lifetime = m.examples_per_sec()  # window = lifetime here (young)
    assert m.examples_per_sec(window=1e6) == pytest.approx(
        lifetime, rel=0.5
    )
    assert m.examples_per_sec(window=1e6) > 0


def test_same_label_reregistration_transfers_ownership():
    """The engine-swap loop re-registers a NEW metrics under the SAME
    label while the old engine is still alive: the newest owner wins
    and exactly one series set per label survives (duplicate series
    would fail a whole Prometheus scrape)."""
    reg = MetricsRegistry()
    old = ServingMetrics()
    old.record_dispatch(bucket=8, n_valid=1, seconds=0.001)
    new = ServingMetrics()
    for _ in range(3):
        new.record_dispatch(bucket=8, n_valid=2, seconds=0.001)
    old.register(registry=reg, engine="prod")
    new.register(registry=reg, engine="prod")
    samples = [
        s
        for f in reg.collect()
        if f.name == "keystone_serving_examples_total"
        for s in f.samples
        if s.labels.get("engine") == "prod"
    ]
    assert len(samples) == 1  # no duplicate series
    assert samples[0].value == 6  # the NEW engine's counter
    # the superseded collector pruned itself; old engine still alive
    assert old.examples.total == 1


def test_engine_autoregisters_into_global_registry():
    from keystone_tpu.observability.registry import get_global_registry
    from keystone_tpu.serving.bench import build_pipeline

    fitted = build_pipeline(d=4, hidden=4, depth=1)
    engine = fitted.compiled(buckets=(2,), name="autoreg-test")
    assert engine.name == "autoreg-test"
    samples = [
        s
        for f in get_global_registry().collect()
        if f.name == "keystone_serving_examples_total"
        for s in f.samples
    ]
    assert any(s.labels.get("engine") == "autoreg-test" for s in samples)
