"""The pure promotion state machine: gate thresholds, the hysteresis
band (no canary↔rollback flapping), terminal states, and the
deterministic canary-fraction arithmetic."""

import pytest

from keystone_tpu.lifecycle.policy import (
    GateInputs,
    PolicyState,
    PromotionConfig,
    tick,
)

CFG = PromotionConfig(
    min_shadow_pairs=4,
    max_shadow_diff=0.5,
    min_canary_requests=4,
    max_canary_error_rate=0.25,
    promote_after_healthy_ticks=2,
)

GOOD = {"candidate_err": 0.01, "incumbent_err": 0.5}
BAD = {"candidate_err": 5.0, "incumbent_err": 0.5}
# between promote_err_ratio (1.0) and rollback_err_ratio (1.5)
MARGINAL = {"candidate_err": 0.6, "incumbent_err": 0.5}


def test_candidate_always_shadows_first():
    state, reason = tick(PolicyState("candidate"), GateInputs(), CFG)
    assert state.stage == "shadow"
    assert reason == "shadow_start"


def test_shadow_waits_for_pairs():
    state, reason = tick(
        PolicyState("shadow"), GateInputs(shadow_pairs=1, **GOOD), CFG
    )
    assert state.stage == "shadow"
    assert reason == "shadow_wait"


def test_shadow_advances_on_pairs_and_good_accuracy():
    state, reason = tick(
        PolicyState("shadow"), GateInputs(shadow_pairs=4, **GOOD), CFG
    )
    assert state.stage == "canary"
    assert reason == "canary_start"


def test_shadow_unknown_accuracy_blocks_but_never_rolls_back():
    state, reason = tick(
        PolicyState("shadow"), GateInputs(shadow_pairs=64), CFG
    )
    assert state.stage == "shadow"
    assert reason == "shadow_wait"


def test_shadow_bad_accuracy_rolls_back_without_pair_evidence():
    # the poisoned-refit path: held-out accuracy alone is enough,
    # no shadow traffic required
    state, reason = tick(
        PolicyState("shadow"), GateInputs(shadow_pairs=0, **BAD), CFG
    )
    assert state.stage == "rolled_back"
    assert reason == "accuracy"


def test_shadow_diff_backstop_without_holdout_proof():
    state, reason = tick(
        PolicyState("shadow"),
        GateInputs(shadow_pairs=8, shadow_max_abs=2.0),
        CFG,
    )
    assert state.stage == "rolled_back"
    assert reason == "shadow_diff"


def test_shadow_diff_tolerated_when_accuracy_proven_good():
    # a refit that corrects a stale incumbent's drift legitimately
    # diverges from it — proven-good candidates may differ
    state, reason = tick(
        PolicyState("shadow"),
        GateInputs(shadow_pairs=8, shadow_max_abs=2.0, **GOOD),
        CFG,
    )
    assert state.stage == "canary"


def test_canary_promotes_after_consecutive_healthy_ticks():
    inputs = GateInputs(canary_requests=8, **GOOD)
    state, reason = tick(PolicyState("canary"), inputs, CFG)
    assert state.stage == "canary"
    assert state.healthy_streak == 1
    assert reason == "canary_healthy"
    state, reason = tick(state, inputs, CFG)
    assert state.stage == "promoted"
    assert reason == "promoted"


def test_canary_error_rate_rolls_back():
    state, reason = tick(
        PolicyState("canary"),
        GateInputs(canary_requests=8, canary_errors=4, **GOOD),
        CFG,
    )
    assert state.stage == "rolled_back"
    assert reason == "canary_errors"


def test_canary_slo_burn_rolls_back():
    state, reason = tick(
        PolicyState("canary"),
        GateInputs(canary_requests=8, slo_breaching=True, **GOOD),
        CFG,
    )
    assert state.stage == "rolled_back"
    assert reason == "slo_burn"


def test_canary_bad_accuracy_rolls_back():
    state, reason = tick(
        PolicyState("canary"), GateInputs(canary_requests=8, **BAD),
        CFG,
    )
    assert state.stage == "rolled_back"
    assert reason == "accuracy"


def test_hysteresis_marginal_resets_streak_without_rollback():
    # the no-flap property: a candidate bouncing between good and
    # marginal windows neither rolls back nor promotes early — it
    # just never accumulates the streak
    state = PolicyState("canary")
    good = GateInputs(canary_requests=8, **GOOD)
    marginal = GateInputs(canary_requests=8, **MARGINAL)
    for _ in range(10):
        state, reason = tick(state, good, CFG)
        assert state.stage == "canary"
        assert state.healthy_streak == 1
        state, reason = tick(state, marginal, CFG)
        assert state.stage == "canary", "hysteresis band rolled back"
        assert state.healthy_streak == 0
        assert reason == "canary_wait"


def test_terminal_states_stay_terminal():
    for stage in ("promoted", "rolled_back"):
        state, reason = tick(
            PolicyState(stage), GateInputs(canary_requests=100, **BAD),
            CFG,
        )
        assert state.stage == stage
        assert reason == "terminal"
        assert state.terminal


def test_idle_does_nothing():
    state, reason = tick(PolicyState("idle"), GateInputs(), CFG)
    assert state.stage == "idle"
    assert reason == "idle"


def test_config_validation():
    with pytest.raises(ValueError):
        PromotionConfig(promote_err_ratio=2.0, rollback_err_ratio=1.5)
    with pytest.raises(ValueError):
        PromotionConfig(promote_err_ratio=0.0)


def test_canary_takes_deterministic_fraction():
    from keystone_tpu.gateway.pool import canary_takes

    for fraction, expect in ((0.0, 0), (0.25, 25), (0.5, 50),
                             (0.1, 10), (1.0, 100)):
        takes = [canary_takes(i, fraction) for i in range(100)]
        assert sum(takes) == expect, fraction
        # deterministic: same sequence twice
        assert takes == [canary_takes(i, fraction) for i in range(100)]


def test_canary_takes_evenly_spaced():
    from keystone_tpu.gateway.pool import canary_takes

    # integer-part advance: over any window of 1/f requests, exactly
    # one is taken — the canary load is smooth, not bursty
    taken = [i for i in range(1000) if canary_takes(i, 0.125)]
    gaps = {b - a for a, b in zip(taken, taken[1:])}
    assert gaps == {8}
