"""Shadow mirror + canary router against fake batchers: diff stats,
the off-response-path contract (a broken candidate costs served
traffic nothing), bounded mirror in-flight, and the canary's
incumbent fallback."""

import threading
from concurrent.futures import Future

import numpy as np
import pytest

from keystone_tpu.lifecycle.routes import CanaryRouter, ShadowMirror


class FakeBatcher:
    """Resolves each submit synchronously through ``fn`` — or holds
    the futures for manual resolution when ``manual=True``."""

    def __init__(self, fn=None, manual=False):
        self.fn = fn or (lambda x: np.asarray(x) * 2.0)
        self.manual = manual
        self.held = []
        self.submits = 0

    def submit(self, example, parent_span_id=None):
        self.submits += 1
        fut = Future()
        if self.manual:
            self.held.append((example, fut))
        else:
            fut.set_result(self.fn(example))
        return fut


def _done(value):
    f = Future()
    f.set_result(np.asarray(value, np.float32))
    return f


def test_mirror_diff_stats():
    mirror = ShadowMirror(FakeBatcher(lambda x: np.asarray(x) + 1.0))
    x = np.ones(4, np.float32)
    mirror.observe(x, _done(x))  # shadow = x+1 -> diff 1.0 everywhere
    stats = mirror.stats()
    assert stats["pairs"] == 1
    assert stats["mean_abs"] == pytest.approx(1.0)
    assert stats["max_abs"] == pytest.approx(1.0)
    assert stats["errors"] == 0


def test_mirror_never_raises_on_broken_candidate():
    class Exploding:
        def submit(self, example, parent_span_id=None):
            raise RuntimeError("candidate engine is gone")

    mirror = ShadowMirror(Exploding())
    mirror.observe(np.ones(4), _done(np.ones(4)))  # must not raise
    stats = mirror.stats()
    assert stats["errors"] == 1
    assert stats["pairs"] == 0


def test_mirror_counts_shadow_errors():
    batcher = FakeBatcher(manual=True)
    mirror = ShadowMirror(batcher)
    mirror.observe(np.ones(4), _done(np.ones(4)))
    _, fut = batcher.held[0]
    fut.set_exception(RuntimeError("candidate dispatch failed"))
    stats = mirror.stats()
    assert stats["errors"] == 1
    assert stats["pairs"] == 0


def test_mirror_bounded_inflight_drops_newest():
    batcher = FakeBatcher(manual=True)  # shadows never resolve
    mirror = ShadowMirror(batcher, max_inflight=3)
    for _ in range(5):
        mirror.observe(np.ones(4), _done(np.ones(4)))
    stats = mirror.stats()
    assert stats["dropped"] == 2
    assert batcher.submits == 3


def test_mirror_pairs_with_pending_primary():
    # the primary can resolve AFTER the shadow: the diff chains off
    # the primary's callback instead of blocking the delivery thread
    mirror = ShadowMirror(FakeBatcher(lambda x: np.asarray(x)))
    primary = Future()
    mirror.observe(np.ones(4), primary)
    assert mirror.stats()["pairs"] == 0
    primary.set_result(np.ones(4, np.float32))
    stats = mirror.stats()
    assert stats["pairs"] == 1
    assert stats["max_abs"] == pytest.approx(0.0)


def test_canary_takes_fraction():
    router = CanaryRouter(FakeBatcher(), 0.25)
    takes = [router.takes() for _ in range(100)]
    assert sum(takes) == 25


def test_canary_serves_from_candidate():
    router = CanaryRouter(FakeBatcher(lambda x: np.asarray(x) * 3.0), 1.0)
    out = Future()
    router.route(np.ones(2, np.float32), None, out, fallback=lambda: None)
    np.testing.assert_array_equal(
        out.result(timeout=5), np.ones(2, np.float32) * 3.0
    )
    assert getattr(out, "canary", False) is True
    assert router.stats() == {
        "fraction": 1.0, "requests": 1, "errors": 0,
    }


def test_canary_submit_failure_falls_back():
    class Exploding:
        def submit(self, example, parent_span_id=None):
            raise RuntimeError("no engine")

    fell_back = []
    router = CanaryRouter(Exploding(), 1.0)
    out = Future()
    router.route(
        np.ones(2), None, out, fallback=lambda: fell_back.append(1)
    )
    assert fell_back == [1]
    assert router.stats()["errors"] == 1
    assert not out.done()  # the fallback path owns resolution now


def test_canary_dispatch_failure_falls_back():
    batcher = FakeBatcher(manual=True)
    fell_back = []
    router = CanaryRouter(batcher, 1.0)
    out = Future()
    router.route(
        np.ones(2), None, out, fallback=lambda: fell_back.append(1)
    )
    _, fut = batcher.held[0]
    fut.set_exception(RuntimeError("candidate died mid-flight"))
    assert fell_back == [1]
    assert router.stats()["errors"] == 1


def test_canary_fraction_validation():
    with pytest.raises(ValueError):
        CanaryRouter(FakeBatcher(), 1.5)
    with pytest.raises(ValueError):
        CanaryRouter(FakeBatcher(), -0.1)
