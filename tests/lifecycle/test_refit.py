"""The streaming-refit accumulator: one-pass normal equations over
feedback chunks (chunk-size independent), exact head recovery,
holdout separation, snapshot/restore, and the poison fault point."""

import numpy as np
import pytest

from keystone_tpu.lifecycle.refit import RefitAccumulator
from keystone_tpu.lifecycle.teacher import teacher_labels
from keystone_tpu.loadgen import faults
from keystone_tpu.serving.bench import affine_head, build_split_pipeline

D, HIDDEN, DEPTH = 6, 8, 2
HEAD_SEED = 99


@pytest.fixture(scope="module")
def split():
    base, W, b = build_split_pipeline(
        d=D, hidden=HIDDEN, depth=DEPTH, seed=3
    )
    return base, W, b


def _labeled(n, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, D)).astype(np.float32)
    Y = teacher_labels(X, D, HIDDEN, DEPTH, seed=3, head_seed=HEAD_SEED)
    return X, Y


def test_recovers_teacher_head(split):
    base, W0, b0 = split
    acc = RefitAccumulator(
        base, feature_dim=HIDDEN, out_dim=D, lam=1e-5, chunk=16
    )
    X, Y = _labeled(600)
    acc.add(X, Y)
    W, b = acc.solve()
    candidate = base.and_then(affine_head(W, b))
    stale = base.and_then(affine_head(W0, b0))
    cand_err, stale_err = acc.holdout_errors(candidate, stale)
    assert cand_err is not None and stale_err is not None
    assert cand_err < stale_err * 1e-2, (cand_err, stale_err)


def test_chunk_size_independence(split):
    """The core one-pass property: folding the same rows in different
    chunkings solves to the same head — so 'streaming refit' is
    accumulate + re-solve regardless of how feedback arrived."""
    base, _, _ = split
    X, Y = _labeled(300)
    solved = []
    for chunk, batches in ((8, 1), (64, 3), (300, 5)):
        acc = RefitAccumulator(
            base, feature_dim=HIDDEN, out_dim=D, lam=1e-4, chunk=chunk
        )
        for part_x, part_y in zip(
            np.array_split(X, batches), np.array_split(Y, batches)
        ):
            acc.add(part_x, part_y)
        W, b = acc.solve()
        solved.append((np.asarray(W), np.asarray(b)))
    for W, b in solved[1:]:
        np.testing.assert_allclose(W, solved[0][0], atol=1e-4)
        np.testing.assert_allclose(b, solved[0][1], atol=1e-4)


def test_holdout_separation(split):
    """Every holdout_every-th row is diverted to the held-out buffer
    and never folded into the normal equations."""
    base, _, _ = split
    acc = RefitAccumulator(
        base, feature_dim=HIDDEN, out_dim=D, chunk=16, holdout_every=4
    )
    X, Y = _labeled(100)
    acc.add(X, Y)
    assert acc.n_holdout == 25
    assert acc.n_accumulated == 75
    assert acc.n_holdout + acc.n_accumulated == 100


def test_holdout_cap(split):
    base, _, _ = split
    acc = RefitAccumulator(
        base, feature_dim=HIDDEN, out_dim=D, chunk=32,
        holdout_every=2, holdout_cap=10,
    )
    X, Y = _labeled(200)
    acc.add(X, Y)
    assert acc.n_holdout == 10
    assert acc.n_accumulated == 190


def test_solve_requires_samples(split):
    base, _, _ = split
    acc = RefitAccumulator(base, feature_dim=HIDDEN, out_dim=D)
    with pytest.raises(RuntimeError):
        acc.solve()


def test_snapshot_restore_discards_later_chunks(split):
    base, _, _ = split
    acc = RefitAccumulator(
        base, feature_dim=HIDDEN, out_dim=D, lam=1e-4, chunk=16
    )
    X, Y = _labeled(200)
    acc.add(X, Y)
    W1, b1 = acc.solve()
    snap = acc.snapshot()
    # fold garbage, then restore: the solve must match the snapshot
    Xg, Yg = _labeled(100, seed=8)
    acc.add(Xg, -np.ones_like(Yg) * 0.9)
    W2, _ = acc.solve()
    assert not np.allclose(np.asarray(W2), np.asarray(W1), atol=1e-3)
    acc.restore(snap)
    W3, b3 = acc.solve()
    np.testing.assert_array_equal(np.asarray(W3), np.asarray(W1))
    np.testing.assert_array_equal(np.asarray(b3), np.asarray(b1))


def test_poison_fault_corrupts_solve_but_not_holdout(split):
    """lifecycle.refit.poison: armed, the accumulated chunks' targets
    are corrupted BEFORE they fold into the normal equations — the
    solved candidate is garbage, while the held-out buffer stays
    clean so the accuracy gate catches exactly this."""
    base, W0, b0 = split
    acc = RefitAccumulator(
        base, feature_dim=HIDDEN, out_dim=D, lam=1e-5, chunk=16
    )
    X, Y = _labeled(400)
    faults.get_injector().arm("lifecycle.refit.poison", count=100)
    try:
        acc.add(X, Y)
    finally:
        faults.get_injector().disarm("lifecycle.refit.poison")
    W, b = acc.solve()
    poisoned = base.and_then(affine_head(W, b))
    stale = base.and_then(affine_head(W0, b0))
    # the holdout rows were diverted before the poison site, so the
    # comparison is against CLEAN labels: the poisoned candidate must
    # look much worse than even the stale incumbent
    cand_err, stale_err = acc.holdout_errors(poisoned, stale)
    assert cand_err > stale_err * 1.5, (cand_err, stale_err)


def test_poison_fires_and_counts(split):
    base, _, _ = split
    acc = RefitAccumulator(
        base, feature_dim=HIDDEN, out_dim=D, chunk=16
    )
    inj = faults.get_injector()
    inj.arm("lifecycle.refit.poison", count=2)
    X, Y = _labeled(64)
    acc.add(X, Y)
    assert inj.status()["fired_total"].get(
        "lifecycle.refit.poison", 0
    ) >= 1
