import pytest

from keystone_tpu.loadgen import faults


@pytest.fixture(autouse=True)
def clean_injector():
    """The injector is process-global: every lifecycle test starts
    and ends with nothing armed, so the poison drills can't leak into
    the rest of the suite."""
    faults.disarm_all()
    yield
    faults.disarm_all()
