"""LifecycleController over a real (tiny) Gateway: end-to-end
promotion, bitwise-identical rollback, poisoned-refit auto-rollback
within one policy tick, and refit-vs-swap concurrency safety."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.gateway import Gateway
from keystone_tpu.lifecycle.controller import LifecycleController
from keystone_tpu.lifecycle.policy import PromotionConfig
from keystone_tpu.lifecycle.teacher import teacher_labels
from keystone_tpu.loadgen import faults
from keystone_tpu.serving.bench import affine_head, build_split_pipeline

D, HIDDEN, DEPTH = 6, 8, 2
HEAD_SEED = 55

CFG = PromotionConfig(
    min_shadow_pairs=2,
    min_canary_requests=2,
    promote_after_healthy_ticks=1,
)


@pytest.fixture(scope="module")
def split():
    return build_split_pipeline(d=D, hidden=HIDDEN, depth=DEPTH, seed=1)


def _labeled(n, seed=21):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, D)).astype(np.float32)
    Y = teacher_labels(X, D, HIDDEN, DEPTH, seed=1, head_seed=HEAD_SEED)
    return X, Y


def _gateway(split):
    base, W0, b0 = split
    stale = base.and_then(affine_head(W0, b0))
    return base, Gateway(
        stale, buckets=(4,), n_lanes=1, max_delay_ms=1.0,
        warmup_example=jnp.zeros((D,), jnp.float32),
        name="test-lifecycle",
    )


def _controller(gw, base, **kw):
    kw.setdefault("config", CFG)
    kw.setdefault("canary_fraction", 0.5)
    kw.setdefault("min_refit_samples", 32)
    return LifecycleController(
        gw, base=base, head_builder=affine_head,
        feature_dim=HIDDEN, out_dim=D, name="m", **kw
    )


def _drive_to(gw, ctrl, target, examples, max_ticks=25):
    """Tick while feeding live traffic until the state machine reaches
    ``target`` (shadow pairs and canary requests both need real
    requests flowing through the pool hooks)."""
    status = ctrl.status()
    for _ in range(max_ticks):
        if status["state"] == target:
            return status
        for i in range(4):
            gw.predict(examples[i % len(examples)]).result(timeout=30)
        time.sleep(0.1)  # let shadow/canary completion callbacks land
        status = ctrl.tick()
    return status


def test_promote_and_bitwise_rollback(split):
    base, gw = _gateway(split)
    rng = np.random.default_rng(3)
    examples = rng.standard_normal((8, D)).astype(np.float32)
    probe = examples[0]
    with gw:
        ctrl = _controller(gw, base)
        try:
            before = np.asarray(gw.predict(probe).result(timeout=30))
            ctrl.add_feedback(*_labeled(200))
            status = ctrl.tick()  # solves v1, arms its shadow
            assert status["state"] == "shadow"
            assert status["version"] == 1
            status = _drive_to(gw, ctrl, "promoted", examples)
            assert status["state"] == "promoted", status
            assert status["promotions"] == 1
            # the candidate beat the stale incumbent on held-out labels
            assert (status["errors"]["candidate"]
                    < status["errors"]["incumbent"])
            after = np.asarray(gw.predict(probe).result(timeout=30))
            assert not np.array_equal(before, after)
            # the promoted model actually tracks the teacher now
            want = teacher_labels(
                probe[None], D, HIDDEN, DEPTH, seed=1,
                head_seed=HEAD_SEED,
            )[0]
            assert float(np.abs(after - want).max()) < 0.05
            # operator rollback un-promotes: the retained incumbent
            # serves BITWISE-identical outputs again
            status = ctrl.force_rollback("test")
            assert status["state"] == "rolled_back"
            restored = np.asarray(gw.predict(probe).result(timeout=30))
            np.testing.assert_array_equal(restored, before)
        finally:
            ctrl.close()


def test_poisoned_refit_rolls_back_within_one_tick(split):
    base, gw = _gateway(split)
    probe = np.linspace(-1, 1, D).astype(np.float32)
    with gw:
        ctrl = _controller(gw, base)
        try:
            before = np.asarray(gw.predict(probe).result(timeout=30))
            faults.get_injector().arm(
                "lifecycle.refit.poison", count=100
            )
            ctrl.add_feedback(*_labeled(200))
            status = ctrl.tick()  # solves the poisoned v1
            assert status["state"] == "shadow"
            status = ctrl.tick()  # the accuracy gate catches it
            assert status["state"] == "rolled_back", status
            assert status["last_reason"] == "accuracy"
            # the incumbent never stopped serving, bit for bit
            after = np.asarray(gw.predict(probe).result(timeout=30))
            np.testing.assert_array_equal(after, before)
            # the tainted accumulation window was discarded: the next
            # cycle does not resurrect the poisoned normal equations
            assert status["refit"]["accumulated"] == 0
        finally:
            ctrl.close()


def test_rollback_discard_allows_clean_recovery(split):
    """After a poisoned rollback, clean feedback must produce a
    promotable candidate — the poison must not linger."""
    base, gw = _gateway(split)
    rng = np.random.default_rng(4)
    examples = rng.standard_normal((8, D)).astype(np.float32)
    with gw:
        ctrl = _controller(gw, base)
        try:
            faults.get_injector().arm("lifecycle.refit.poison", count=100)
            ctrl.add_feedback(*_labeled(200))
            ctrl.tick()
            status = ctrl.tick()
            assert status["state"] == "rolled_back"
            faults.get_injector().disarm("lifecycle.refit.poison")
            ctrl.add_feedback(*_labeled(200, seed=33))
            status = ctrl.tick()
            assert status["state"] == "shadow"
            assert status["version"] == 2
            status = _drive_to(gw, ctrl, "promoted", examples)
            assert status["state"] == "promoted", status
        finally:
            ctrl.close()


def test_no_candidate_until_min_samples(split):
    base, gw = _gateway(split)
    with gw:
        ctrl = _controller(gw, base, min_refit_samples=500)
        try:
            ctrl.add_feedback(*_labeled(100))
            status = ctrl.tick()
            assert status["state"] == "idle"
            assert status["version"] == 0
        finally:
            ctrl.close()


def test_concurrent_refit_vs_swap(split):
    """Policy ticks (candidate builds, engine swaps on promotion) and
    forced pool rebuckets race without deadlock or request failures —
    the swap lock serializes the engine rotations."""
    base, gw = _gateway(split)
    rng = np.random.default_rng(5)
    examples = rng.standard_normal((8, D)).astype(np.float32)
    with gw:
        ctrl = _controller(gw, base)
        errs = []

        def ticker():
            try:
                for i in range(6):
                    ctrl.add_feedback(*_labeled(64, seed=100 + i))
                    ctrl.tick()
                    for j in range(2):
                        gw.predict(examples[j]).result(timeout=30)
            except Exception as e:  # pragma: no cover - the assert
                errs.append(e)

        def swapper():
            try:
                for _ in range(4):
                    gw.rebucket(force=True)
            except Exception as e:  # pragma: no cover - the assert
                errs.append(e)

        try:
            threads = [
                threading.Thread(target=ticker),
                threading.Thread(target=swapper),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "refit-vs-swap deadlock"
            assert not errs, errs
            out = gw.predict(examples[0]).result(timeout=30)
            assert np.asarray(out).shape == (D,)
        finally:
            ctrl.close()
