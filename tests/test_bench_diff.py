"""bench-diff: round-over-round regression gating off bench JSONs —
direction inference per unit, per-row tolerance resolution, vanished/
new/skipped verdicts, and the nonzero exit contract."""

import json

from keystone_tpu.bench_diff import classify, diff_rows, load_rows, main


def _row(metric, value, unit, **extra):
    return {"metric": metric, "value": value, "unit": unit, **extra}


def _index(*rows):
    return {r["metric"]: r for r in rows}


def test_classify_directions():
    assert classify("ms")[0] == "up"
    assert classify("examples/sec")[0] == "down"
    assert classify("x")[0] == "down"
    assert classify("weird_unit") is None


def test_latency_regression_flags_and_rate_regression_flags():
    old = _index(_row("p99", 10.0, "ms"), _row("rate", 100.0,
                                               "examples/sec"))
    new = _index(_row("p99", 14.0, "ms"), _row("rate", 80.0,
                                               "examples/sec"))
    verdicts = {e["metric"]: e["verdict"]
                for e in diff_rows(old, new)}
    assert verdicts == {"p99": "regressed", "rate": "regressed"}


def test_within_tolerance_is_ok_and_direction_matters():
    old = _index(_row("p99", 10.0, "ms"), _row("rate", 100.0,
                                               "examples/sec"))
    # latency DOWN and rate UP are improvements, never regressions
    new = _index(_row("p99", 5.0, "ms"), _row("rate", 200.0,
                                              "examples/sec"))
    verdicts = {e["metric"]: e["verdict"]
                for e in diff_rows(old, new)}
    assert verdicts == {"p99": "improved", "rate": "improved"}
    new = _index(_row("p99", 10.5, "ms"), _row("rate", 95.0,
                                               "examples/sec"))
    verdicts = {e["metric"]: e["verdict"]
                for e in diff_rows(old, new)}
    assert verdicts == {"p99": "ok", "rate": "ok"}


def test_tolerance_resolution_order():
    old = _index(_row("p99", 10.0, "ms"))
    new = _index(_row("p99", 14.0, "ms"))
    # explicit override beats everything
    assert diff_rows(old, new, overrides={"p99": 0.5})[0][
        "verdict"] == "ok"
    # the row's own embedded tolerance beats the global flag
    new_tol = _index(_row("p99", 14.0, "ms", tolerance=0.5))
    assert diff_rows(old, new_tol, tolerance=0.01)[0][
        "verdict"] == "ok"
    # the global flag beats the unit-class default
    assert diff_rows(old, new, tolerance=0.5)[0]["verdict"] == "ok"


def test_vanished_new_and_skipped_rows():
    old = _index(_row("gone", 1.0, "x"),
                 _row("skip", None, "skipped", skipped=True))
    new = _index(_row("fresh", 2.0, "x"),
                 _row("skip", None, "skipped", skipped=True))
    verdicts = {e["metric"]: e["verdict"]
                for e in diff_rows(old, new)}
    assert verdicts == {"gone": "vanished", "fresh": "new",
                        "skip": "skipped"}


def test_uncomparable_units_never_gate():
    old = _index(_row("odd", 1.0, "widgets"))
    new = _index(_row("odd", 100.0, "widgets"))
    assert diff_rows(old, new)[0]["verdict"] == "uncomparable"


def test_load_rows_jsonl_array_and_log_noise(tmp_path):
    rows = [_row("a", 1.0, "ms"), _row("b", 2.0, "x")]
    jsonl = tmp_path / "r.jsonl"
    jsonl.write_text(
        "some log line\n"
        + "\n".join(json.dumps(r) for r in rows)
        + "\nnot json either\n"
    )
    assert set(load_rows(str(jsonl))) == {"a", "b"}
    arr = tmp_path / "r.json"
    arr.write_text(json.dumps(rows))
    assert set(load_rows(str(arr))) == {"a", "b"}
    # duplicate metrics: first row wins (the emitters' guard)
    dup = tmp_path / "dup.jsonl"
    dup.write_text(json.dumps(_row("a", 1.0, "ms")) + "\n"
                   + json.dumps(_row("a", 9.0, "ms")) + "\n")
    assert load_rows(str(dup))["a"]["value"] == 1.0


def test_main_exit_codes(tmp_path, capsys):
    old = tmp_path / "old.jsonl"
    new = tmp_path / "new.jsonl"
    old.write_text(json.dumps(_row("p99", 10.0, "ms")) + "\n")
    new.write_text(json.dumps(_row("p99", 14.0, "ms")) + "\n")
    assert main([str(old), str(new)]) == 1
    assert main([str(old), str(new), "--tolerance", "0.5"]) == 0
    assert main([str(old), str(new), "--set", "p99=0.5"]) == 0
    # missing new-side metric fails unless --allow-missing
    new.write_text(json.dumps(_row("other", 1.0, "x")) + "\n")
    assert main([str(old), str(new)]) == 1
    assert main([str(old), str(new), "--allow-missing"]) == 0
    capsys.readouterr()
    # unreadable / empty inputs are usage errors, not crashes
    assert main([str(tmp_path / "nope.json"), str(new)]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert main([str(empty), str(new)]) == 2
    capsys.readouterr()
