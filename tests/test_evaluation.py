"""Evaluator tests (reference: evaluation/*Suite.scala)."""

import numpy as np

from keystone_tpu.evaluation import (
    AugmentedExamplesEvaluator,
    BinaryClassifierEvaluator,
    MeanAveragePrecisionEvaluator,
    MulticlassClassifierEvaluator,
)


def test_multiclass_confusion_and_accuracy():
    pred = np.array([0, 1, 2, 1, 0, 2])
    lab = np.array([0, 1, 1, 1, 0, 2])
    m = MulticlassClassifierEvaluator(3).evaluate(pred, lab)
    assert m.confusion_matrix[1, 2] == 1  # actual 1 predicted 2
    assert abs(m.total_accuracy - 5 / 6) < 1e-9
    assert abs(m.micro_f1 - 5 / 6) < 1e-9
    assert 0 < m.macro_f1 <= 1
    assert "Accuracy" in m.summary()


def test_binary_evaluator():
    pred = np.array([True, True, False, False])
    lab = np.array([True, False, True, False])
    m = BinaryClassifierEvaluator().evaluate(pred, lab)
    assert (m.tp, m.fp, m.fn, m.tn) == (1, 1, 1, 1)
    assert m.accuracy == 0.5


def test_mean_average_precision_perfect():
    scores = np.array([[0.9, 0.1], [0.8, 0.6], [0.2, 0.7], [0.1, 0.95]])
    actuals = [[0], [0], [1], [1]]
    aps = MeanAveragePrecisionEvaluator(2).evaluate(actuals, scores)
    np.testing.assert_allclose(aps, [1.0, 1.0], atol=1e-9)


def test_augmented_examples_average():
    # two source examples, two augmented copies each
    scores = np.array(
        [[0.6, 0.4], [0.4, 0.6], [0.1, 0.9], [0.2, 0.8]]
    )
    labels = np.array([0, 0, 1, 1])
    names = ["a", "a", "b", "b"]
    m = AugmentedExamplesEvaluator(names, 2).evaluate(scores, labels)
    assert m.total_accuracy == 1.0
