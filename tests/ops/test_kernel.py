"""Kernel ridge tests (reference: KernelModelSuite — block solve vs exact
dual solution)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    KernelRidgeRegression,
)
from keystone_tpu.parallel.dataset import Dataset


def _rbf(A, B, gamma):
    d2 = (
        (A * A).sum(1)[:, None]
        + (B * B).sum(1)[None, :]
        - 2 * A @ B.T
    )
    return np.exp(-gamma * np.maximum(d2, 0))


def test_kernel_block(mesh8):
    rng = np.random.default_rng(0)
    X = rng.standard_normal((40, 5)).astype(np.float32)
    gen = GaussianKernelGenerator(gamma=0.3)
    t = gen.fit(Dataset.of(X).shard())
    km = t.kernel_matrix(Dataset.of(X).shard())
    K = _rbf(X, X, 0.3)
    got = np.asarray(km.block(0, 16))
    # valid region matches to the documented kernel-generation contract:
    # the cross GEMM uses the 3-pass BF16_BF16_F32_X3 algorithm
    # (kernel.py _cross_mm_x3, ~1.5e-5 relative on the dot products →
    # up to ~1e-4-level kernel error ON-CHIP after the γ·d² exponent;
    # CPU emulates the algorithm more accurately, so the CPU bar stays
    # tight); solution-level accuracy is pinned separately by
    # test_krr_matches_reference_translation
    import jax

    atol = 1e-3 if jax.devices()[0].platform != "cpu" else 1e-4
    np.testing.assert_allclose(got[:40, :16], K[:, :16], atol=atol)
    assert np.allclose(got[40:], 0)


def _np_gauss_seidel(K, Y, lam, block_size, num_epochs):
    """numpy translation of KernelRidgeRegression.scala:86-235."""
    n = K.shape[0]
    W = np.zeros((n, Y.shape[1]))
    for _ in range(num_epochs):
        for s in range(0, n, block_size):
            e = min(s + block_size, n)
            Kb = K[:, s:e]
            Kbb = K[s:e, s:e]
            rhs = Y[s:e] - (Kb.T @ W - Kbb.T @ W[s:e])
            W[s:e] = np.linalg.solve(Kbb + lam * np.eye(e - s), rhs)
    return W


def test_krr_matches_reference_translation(mesh8):
    """Same epochs => same iterates as the reference algorithm."""
    rng = np.random.default_rng(1)
    n = 60
    X = rng.standard_normal((n, 4)).astype(np.float32)
    Y = rng.standard_normal((n, 3)).astype(np.float32)
    gamma, lam = 0.5, 0.1
    est = KernelRidgeRegression(
        GaussianKernelGenerator(gamma), lam, block_size=16, num_epochs=5
    )
    model = est.fit(Dataset.of(X).shard(), Dataset.of(Y).shard())
    K = _rbf(X, X, gamma).astype(np.float64)
    W_ref = _np_gauss_seidel(K, Y.astype(np.float64), lam, 16, 5)
    np.testing.assert_allclose(
        np.asarray(model.model)[:n], W_ref, atol=1e-3
    )


def test_krr_converges_to_exact(mesh8):
    """Well-conditioned regime: iterates reach the exact dual solution."""
    rng = np.random.default_rng(1)
    n = 60
    X = rng.standard_normal((n, 4)).astype(np.float32)
    Y = rng.standard_normal((n, 3)).astype(np.float32)
    gamma, lam = 0.5, 2.0
    est = KernelRidgeRegression(
        GaussianKernelGenerator(gamma), lam, block_size=16, num_epochs=30
    )
    model = est.fit(Dataset.of(X).shard(), Dataset.of(Y).shard())
    K = _rbf(X, X, gamma).astype(np.float64)
    W_exact = np.linalg.solve(K + lam * np.eye(n), Y.astype(np.float64))
    np.testing.assert_allclose(
        np.asarray(model.model)[:n], W_exact, atol=5e-3
    )
    # train predictions via blockwise apply match K @ W
    pred = np.asarray(model.apply_batch(Dataset.of(X)).array())
    np.testing.assert_allclose(pred, K @ W_exact, atol=5e-2)


def test_krr_single_apply(mesh8):
    rng = np.random.default_rng(2)
    X = rng.standard_normal((30, 4)).astype(np.float32)
    Y = rng.standard_normal((30, 2)).astype(np.float32)
    est = KernelRidgeRegression(
        GaussianKernelGenerator(0.4), 0.2, block_size=8, num_epochs=10
    )
    model = est.fit(Dataset.of(X), Dataset.of(Y))
    batch = np.asarray(model.apply_batch(Dataset.of(X)).array())
    one = np.asarray(model.apply(X[0]))
    np.testing.assert_allclose(one, batch[0], atol=1e-4)


def test_krr_block_permutation_still_converges(mesh8):
    rng = np.random.default_rng(3)
    n = 48
    X = rng.standard_normal((n, 3)).astype(np.float32)
    Y = rng.standard_normal((n, 2)).astype(np.float32)
    est = KernelRidgeRegression(
        GaussianKernelGenerator(0.5), 2.0, block_size=16, num_epochs=30,
        block_permuter=7,
    )
    model = est.fit(Dataset.of(X), Dataset.of(Y))
    K = _rbf(X, X, 0.5).astype(np.float64)
    W_exact = np.linalg.solve(K + 2.0 * np.eye(n), Y.astype(np.float64))
    np.testing.assert_allclose(np.asarray(model.model)[:n], W_exact, atol=1e-2)


def test_krr_cached_kernel_matches_uncached():
    """cache_kernel=True (prebuilt column blocks + batched diagonal
    Cholesky bank) must reproduce the regenerate-per-block scan — same
    math, restructured schedule (kernel.py _krr_cached_epoch_scan)."""
    import dataclasses as dc

    rng = np.random.default_rng(11)
    n, d, k = 96, 5, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    Xd = Dataset.from_array(jnp.asarray(X))
    Yd = Dataset.from_array(jnp.asarray(Y))
    base = KernelRidgeRegression(
        GaussianKernelGenerator(gamma=0.2), lam=0.3, block_size=32,
        num_epochs=3, block_permuter=5,
    )
    W_cached = np.asarray(
        dc.replace(base, cache_kernel=True).fit(Xd, Yd).model
    )
    W_plain = np.asarray(
        dc.replace(base, cache_kernel=False).fit(Xd, Yd).model
    )
    np.testing.assert_allclose(W_cached, W_plain, rtol=2e-5, atol=1e-6)
    # and both sit on the reference iterates
    K = _rbf(X, X, 0.2).astype(np.float64)
    W_ref = _np_gauss_seidel_perm(K, Y.astype(np.float64), 0.3, 32, 3, 5)
    np.testing.assert_allclose(W_cached[:n], W_ref, atol=1e-3)


def _np_gauss_seidel_perm(K, Y, lam, block_size, num_epochs, permuter):
    """_np_gauss_seidel with the estimator's per-epoch block permutation."""
    n = K.shape[0]
    W = np.zeros((n, Y.shape[1]))
    n_blocks = (n + block_size - 1) // block_size
    for epoch in range(num_epochs):
        order = list(range(n_blocks))
        np.random.default_rng((permuter, epoch)).shuffle(order)
        for b in order:
            s = b * block_size
            e = min(s + block_size, n)
            Kb = K[:, s:e]
            Kbb = K[s:e, s:e]
            rhs = Y[s:e] - (Kb.T @ W - Kbb.T @ W[s:e])
            W[s:e] = np.linalg.solve(Kbb + lam * np.eye(e - s), rhs)
    return W


def test_krr_device_solve_matches_host_solve():
    import dataclasses as dc

    rng = np.random.default_rng(9)
    n, d, k = 96, 6, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    Xd = Dataset.from_array(jnp.asarray(X))
    Yd = Dataset.from_array(jnp.asarray(Y))
    base = KernelRidgeRegression(
        GaussianKernelGenerator(gamma=0.1), lam=0.4, block_size=32,
        num_epochs=2,
    )
    W_dev = np.asarray(dc.replace(base, solve="device").fit(Xd, Yd).model)
    W_host = np.asarray(dc.replace(base, solve="host").fit(Xd, Yd).model)
    np.testing.assert_allclose(W_dev, W_host, rtol=5e-4, atol=5e-5)
