"""L-BFGS tests (reference: LBFGSSuite — distributed vs local solutions)."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.learning import (
    DenseLBFGSwithL2,
    SparseLBFGSwithL2,
)
from keystone_tpu.ops.util.nodes import Sparsify
from keystone_tpu.parallel.dataset import Dataset


def test_dense_lbfgs_recovers_ols(mesh8):
    rng = np.random.default_rng(0)
    A = rng.standard_normal((128, 10)).astype(np.float32)
    W_true = rng.standard_normal((10, 3)).astype(np.float32)
    b = A @ W_true + 0.7
    est = DenseLBFGSwithL2(num_iterations=60, reg_param=0.0)
    model = est.fit(Dataset.of(A).shard(), Dataset.of(b).shard())
    pred = np.asarray(model.apply_batch(Dataset.of(A)).array())
    assert np.abs(pred - b).max() < 0.05


def test_dense_lbfgs_l2_matches_ridge(mesh8):
    rng = np.random.default_rng(1)
    A = rng.standard_normal((200, 6)).astype(np.float32)
    b = rng.standard_normal((200, 2)).astype(np.float32)
    lam = 0.1
    n = A.shape[0]
    est = DenseLBFGSwithL2(
        num_iterations=100, reg_param=lam, fit_intercept=False,
        convergence_tol=1e-10,
    )
    model = est.fit(Dataset.of(A), Dataset.of(b))
    # objective: ||AW-b||^2/(2n) + lam/2 ||W||^2  =>  (A'A/n + lam I) W = A'b/n
    expect = np.linalg.solve(A.T @ A / n + lam * np.eye(6), A.T @ b / n)
    np.testing.assert_allclose(np.asarray(model.W), expect, atol=5e-3)


def test_sparse_lbfgs_runs(mesh8):
    rng = np.random.default_rng(2)
    A = (rng.standard_normal((64, 8)) * (rng.random((64, 8)) < 0.3)).astype(
        np.float32
    )
    W_true = rng.standard_normal((8, 2)).astype(np.float32)
    b = (A @ W_true).astype(np.float32)
    sparse_ds = Sparsify().apply_batch(Dataset.of(A))
    est = SparseLBFGSwithL2(num_iterations=60)
    model = est.fit(sparse_ds, Dataset.of(b))
    pred = np.asarray(model.apply_batch(sparse_ds).array())
    assert np.abs(pred - b).max() < 0.05


def test_lbfgs_weight():
    assert DenseLBFGSwithL2(num_iterations=20).weight == 21


def test_device_lbfgs_matches_host_driver_least_squares():
    import dataclasses as dc

    rng = np.random.default_rng(11)
    n, d, k = 400, 24, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    W_true = rng.standard_normal((d, k)).astype(np.float32)
    Y = X @ W_true
    Xd = Dataset.from_array(jnp.asarray(X))
    Yd = Dataset.from_array(jnp.asarray(Y))
    base = DenseLBFGSwithL2(reg_param=1e-4, num_iterations=40,
                            fit_intercept=False)
    m_dev = dc.replace(base, driver="device").fit(Xd, Yd)
    m_host = dc.replace(base, driver="host").fit(Xd, Yd)
    # both recover the generating model; drivers agree to optimizer noise
    assert np.abs(np.asarray(m_dev.W) - W_true).max() < 5e-2
    assert np.abs(np.asarray(m_dev.W) - np.asarray(m_host.W)).max() < 5e-2


def test_device_lbfgs_logistic_regression_learns():
    from keystone_tpu.ops.learning import LogisticRegressionEstimator

    rng = np.random.default_rng(12)
    n, d, k = 600, 10, 3
    centers = rng.standard_normal((k, d)).astype(np.float32) * 3
    y = rng.integers(0, k, n).astype(np.int32)
    X = centers[y] + rng.standard_normal((n, d)).astype(np.float32)
    Xd = Dataset.from_array(jnp.asarray(X))
    yd = Dataset.from_array(jnp.asarray(y))
    model = LogisticRegressionEstimator(
        num_classes=k, num_iters=30, driver="device"
    ).fit(Xd, yd)
    preds = np.asarray(model.apply_batch(Xd).padded())
    assert (preds == y).mean() > 0.9
    host = LogisticRegressionEstimator(
        num_classes=k, num_iters=30, driver="host"
    ).fit(Xd, yd)
    hp = np.asarray(host.apply_batch(Xd).padded())
    assert (preds == hp).mean() > 0.95


def test_device_lbfgs_line_search_failure_terminates():
    """A pathological objective whose 'gradient' points uphill everywhere
    makes every Armijo trial fail; the driver must stop cleanly at w0
    rather than loop or return NaN."""
    from keystone_tpu.ops.learning.lbfgs import run_lbfgs_device

    def bad_vg(w):
        # claims descent direction -g, but f grows along it
        return jnp.sum(w * w) + 1.0, -jnp.ones_like(w)

    w = run_lbfgs_device(bad_vg, jnp.zeros((4, 2)), 10)
    assert np.isfinite(np.asarray(w)).all()
    # at f32 resolution the backtracked step may be accepted at rounding
    # noise; the property that matters is no runaway along the bogus
    # direction
    assert np.abs(np.asarray(w)).max() < 1e-3


def test_device_lbfgs_convergence_tol_is_traced():
    """Different tolerances reuse one compiled program (tol is a traced
    argument, not a static one)."""
    from keystone_tpu.ops.learning.lbfgs import (
        _lbfgs_device_run, run_lbfgs_device,
    )

    def quad_vg(w, A):
        return 0.5 * jnp.sum((A @ w) * w), A @ w

    A = jnp.eye(8) * jnp.arange(1.0, 9.0)
    w0 = jnp.ones((8,))
    before = _lbfgs_device_run._cache_size()
    w1 = run_lbfgs_device(quad_vg, w0, 50, convergence_tol=1e-2, data=(A,))
    w2 = run_lbfgs_device(quad_vg, w0, 50, convergence_tol=1e-8, data=(A,))
    after = _lbfgs_device_run._cache_size()
    assert after - before == 1  # one compile for both tolerances
    # tighter tolerance gets at least as close to the optimum (0)
    assert np.abs(np.asarray(w2)).max() <= np.abs(np.asarray(w1)).max() + 1e-6
