"""L-BFGS tests (reference: LBFGSSuite — distributed vs local solutions)."""

import numpy as np
import pytest

from keystone_tpu.ops.learning import (
    DenseLBFGSwithL2,
    SparseLBFGSwithL2,
)
from keystone_tpu.ops.util.nodes import Sparsify
from keystone_tpu.parallel.dataset import Dataset


def test_dense_lbfgs_recovers_ols(mesh8):
    rng = np.random.default_rng(0)
    A = rng.standard_normal((128, 10)).astype(np.float32)
    W_true = rng.standard_normal((10, 3)).astype(np.float32)
    b = A @ W_true + 0.7
    est = DenseLBFGSwithL2(num_iterations=60, reg_param=0.0)
    model = est.fit(Dataset.of(A).shard(), Dataset.of(b).shard())
    pred = np.asarray(model.apply_batch(Dataset.of(A)).array())
    assert np.abs(pred - b).max() < 0.05


def test_dense_lbfgs_l2_matches_ridge(mesh8):
    rng = np.random.default_rng(1)
    A = rng.standard_normal((200, 6)).astype(np.float32)
    b = rng.standard_normal((200, 2)).astype(np.float32)
    lam = 0.1
    n = A.shape[0]
    est = DenseLBFGSwithL2(
        num_iterations=100, reg_param=lam, fit_intercept=False,
        convergence_tol=1e-10,
    )
    model = est.fit(Dataset.of(A), Dataset.of(b))
    # objective: ||AW-b||^2/(2n) + lam/2 ||W||^2  =>  (A'A/n + lam I) W = A'b/n
    expect = np.linalg.solve(A.T @ A / n + lam * np.eye(6), A.T @ b / n)
    np.testing.assert_allclose(np.asarray(model.W), expect, atol=5e-3)


def test_sparse_lbfgs_runs(mesh8):
    rng = np.random.default_rng(2)
    A = (rng.standard_normal((64, 8)) * (rng.random((64, 8)) < 0.3)).astype(
        np.float32
    )
    W_true = rng.standard_normal((8, 2)).astype(np.float32)
    b = (A @ W_true).astype(np.float32)
    sparse_ds = Sparsify().apply_batch(Dataset.of(A))
    est = SparseLBFGSwithL2(num_iterations=60)
    model = est.fit(sparse_ds, Dataset.of(b))
    pred = np.asarray(model.apply_batch(sparse_ds).array())
    assert np.abs(pred - b).max() < 0.05


def test_lbfgs_weight():
    assert DenseLBFGSwithL2(num_iterations=20).weight == 21
