"""HOG/DAISY tests against loop translations of the reference."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.ops.images.daisy import DaisyExtractor
from keystone_tpu.ops.images.hog import HogExtractor, UU, VV


def _naive_hog_hist(img, b):
    """Loop translation of HogExtractor.computeHist."""
    X, Y, C = img.shape
    nx, ny = round(X / b), round(Y / b)
    hist = np.zeros((nx, ny, 18))
    for x in range(1, nx * b - 1):
        for y in range(1, ny * b - 1):
            best_mag2, bdx, bdy = -np.inf, 0, 0
            for c in range(C - 1, -1, -1):
                dx = img[x + 1, y, c] - img[x - 1, y, c]
                dy = img[x, y + 1, c] - img[x, y - 1, c]
                m2 = dx * dx + dy * dy
                if m2 > best_mag2:
                    best_mag2, bdx, bdy = m2, dx, dy
            mag = np.sqrt(best_mag2)
            best_dot, best_o = 0.0, 0
            for o in range(9):
                dot = UU[o] * bdy + VV[o] * bdx
                if dot > best_dot:
                    best_o, best_dot = o, dot
                elif -dot > best_dot:
                    best_o, best_dot = o + 9, -dot
            xp = (x + 0.5) / b - 0.5
            yp = (y + 0.5) / b - 0.5
            ixp, iyp = int(np.floor(xp)), int(np.floor(yp))
            vx0, vy0 = xp - ixp, yp - iyp
            for (cx, cy, w) in [
                (ixp, iyp, (1 - vx0) * (1 - vy0)),
                (ixp, iyp + 1, (1 - vx0) * vy0),
                (ixp + 1, iyp, vx0 * (1 - vy0)),
                (ixp + 1, iyp + 1, vx0 * vy0),
            ]:
                if 0 <= cx < nx and 0 <= cy < ny:
                    hist[cx, cy, best_o] += w * mag
    return hist


def test_hog_features_shape_and_energy():
    rng = np.random.default_rng(0)
    img = rng.uniform(0, 255, (32, 32, 3)).astype(np.float32)
    feats = np.asarray(HogExtractor(8).apply(img))
    assert feats.shape == (4, 32)  # (4-2)^2 interior cells... nx=4 -> 2x2
    assert feats.shape[0] == (4 - 2) ** 2
    assert feats[:, :31].max() > 0
    np.testing.assert_allclose(feats[:, 31], 0.0)  # truncation feature
    # all normalized-clamped features within [0, 0.4]
    assert feats[:, :18].max() <= 0.4 + 1e-6


def test_hog_matches_naive_loop():
    """Compare full extractor against the loop translation end-to-end
    (via the histogram, then the same normalization math)."""
    rng = np.random.default_rng(1)
    img = rng.uniform(0, 255, (24, 24, 3)).astype(np.float32)
    b = 8
    hist_naive = _naive_hog_hist(img, b)
    got = np.asarray(HogExtractor(b).apply(img))
    # reproduce features from naive hist
    nx = ny = 3
    combined = hist_naive[:, :, :9] + hist_naive[:, :, 9:]
    norm = (combined**2).sum(2)

    def blk(x0, y0):
        return (
            norm[x0, y0] + norm[x0 + 1, y0] + norm[x0, y0 + 1]
            + norm[x0 + 1, y0 + 1]
        )

    feats = np.zeros((1, 32))
    n1 = 1 / np.sqrt(blk(1, 1) + 1e-4)
    n2 = 1 / np.sqrt(blk(0, 1) + 1e-4)
    n3 = 1 / np.sqrt(blk(1, 0) + 1e-4)
    n4 = 1 / np.sqrt(blk(0, 0) + 1e-4)
    h = hist_naive[1, 1]
    hs = [np.minimum(h * n, 0.2) for n in (n1, n2, n3, n4)]
    feats[0, :18] = 0.5 * sum(hs)
    c = combined[1, 1]
    cs = [np.minimum(c * n, 0.2) for n in (n1, n2, n3, n4)]
    feats[0, 18:27] = 0.5 * sum(cs)
    feats[0, 27:31] = 0.2357 * np.array([x.sum() for x in hs])
    np.testing.assert_allclose(got, feats, atol=1e-4)


def test_daisy_shapes_and_normalization():
    rng = np.random.default_rng(2)
    img = rng.uniform(0, 1, (48, 48)).astype(np.float32)
    ext = DaisyExtractor()
    out = np.asarray(ext.apply(img))
    n_keys = len(range(16, 32, 4)) ** 2
    assert out.shape == (ext.daisy_feature_size, n_keys)
    # every H-sized histogram is L2-normalized (or zero)
    H = ext.daisy_h
    for i in range(0, ext.daisy_feature_size, H):
        norms = np.linalg.norm(out[i : i + H, :], axis=0)
        ok = (np.abs(norms - 1) < 1e-4) | (norms < 1e-6)
        assert ok.all()


def test_daisy_flat_image_zero():
    img = np.full((48, 48), 0.5, np.float32)
    out = np.asarray(DaisyExtractor().apply(img))
    # constant image: gradients are zero away from borders; center
    # histograms of interior keypoints are zero
    assert np.abs(out).max() < 1.0
