"""Built-in taggers (ops/nlp/tagging.py): trainable averaged perceptron +
rule-based POS/NER defaults."""

import numpy as np

from keystone_tpu.ops.nlp.external import NER, POSTagger
from keystone_tpu.ops.nlp.tagging import (
    PerceptronTaggerEstimator,
    rule_ner_tag,
    rule_pos_tag,
)
from keystone_tpu.parallel.dataset import Dataset


def _toy_corpus():
    """Deterministic synthetic tagged corpus: DT (NN|JJ NN) VB [RB]."""
    dts = ["the", "a"]
    jjs = ["big", "small", "red", "old"]
    nns = ["dog", "cat", "house", "tree", "car", "bird"]
    vbs = ["runs", "sits", "falls", "jumps"]
    rbs = ["quickly", "slowly"]
    rng = np.random.default_rng(0)
    sents = []
    for _ in range(200):
        toks, tags = [rng.choice(dts)], ["DT"]
        if rng.random() < 0.5:
            toks.append(rng.choice(jjs))
            tags.append("JJ")
        toks.append(rng.choice(nns))
        tags.append("NN")
        toks.append(rng.choice(vbs))
        tags.append("VB")
        if rng.random() < 0.5:
            toks.append(rng.choice(rbs))
            tags.append("RB")
        sents.append((toks, tags))
    return sents


def test_perceptron_tagger_learns_toy_grammar():
    sents = _toy_corpus()
    train, test = sents[:160], sents[160:]
    tagger = PerceptronTaggerEstimator(n_iter=5).fit(
        Dataset.from_items(train)
    )
    correct = total = 0
    for toks, gold in test:
        pred = [t for _, t in tagger.apply(toks)]
        correct += sum(p == g for p, g in zip(pred, gold))
        total += len(gold)
    assert correct / total > 0.97

    # trained tagger plugs into the POSTagger node as an annotator
    node = POSTagger(annotator=tagger)
    toks = ["the", "red", "dog", "runs"]
    assert [t for _, t in node.apply(toks)] == ["DT", "JJ", "NN", "VB"]


def test_rule_pos_tagger_heuristics():
    tags = rule_pos_tag(
        ["The", "quick", "dogs", "ran", "slowly", "to", "Paris", "in",
         "1995"]
    )
    assert tags[0] == "DT"
    assert tags[2] == "NNS"
    assert tags[4] == "RB"
    assert tags[5] == "IN"
    assert tags[6] == "NNP"  # capitalized mid-sentence
    assert tags[8] == "CD"


def test_rule_ner_entities():
    toks = "Dr . Smith joined Acme Corp in March 2021 with 500 staff".split()
    labels = rule_ner_tag(toks)
    assert labels[toks.index("Smith")] == "PERSON"
    assert labels[toks.index("Acme")] == "ORG"
    assert labels[toks.index("Corp")] == "ORG"
    assert labels[toks.index("March")] == "DATE"
    assert labels[toks.index("2021")] == "DATE"
    assert labels[toks.index("500")] == "NUMBER"
    assert labels[toks.index("with")] == "O"
    # node default path
    assert NER().apply(toks) == labels


def test_corenlp_extractor_ner_replacement_default():
    from keystone_tpu.ops.nlp.external import CoreNLPFeatureExtractor

    grams = CoreNLPFeatureExtractor(orders=[1]).apply(
        "he visited Acme Corp today"
    )
    flat = [g[0] for g in grams]
    assert "org" in flat and "acme" not in flat
    # ner=False disables replacement
    grams_off = CoreNLPFeatureExtractor(orders=[1], ner=False).apply(
        "he visited Acme Corp today"
    )
    assert "acme" in [g[0] for g in grams_off]


def _ner_corpus():
    """Synthetic BIO-tagged corpus covering cases the rule tagger
    systematically misses: lowercase person names, LOC entities (a type
    the rules never emit), sentence-initial persons, orgs without a
    corporate suffix — plus titled persons and suffixed orgs (which the
    rules do get), so beating the baseline requires real learning."""
    rng = np.random.default_rng(7)
    pers = [["karen", "smith"], ["Bob", "Jones"], ["maria", "garcia"],
            ["Wei", "Chen"], ["anna", "kowalski"], ["James", "Lee"]]
    orgs = [["acme", "group"], ["Initech", "Corp"], ["globex"],
            ["the", "north", "wind", "collective"], ["Hooli"]]
    locs = [["springfield"], ["New", "Avalon"], ["east", "haven"],
            ["Porto"], ["riverdale"]]
    sents = []
    for _ in range(320):
        kind = rng.integers(0, 4)
        if kind == 0:  # untitled person mid-sentence
            p = pers[rng.integers(0, len(pers))]
            toks = ["yesterday"] + p + ["visited", "us"]
            tags = ["O", "B-PER"] + ["I-PER"] * (len(p) - 1) + ["O", "O"]
        elif kind == 1:  # sentence-initial person
            p = pers[rng.integers(0, len(pers))]
            toks = p + ["signed", "the", "deal"]
            tags = ["B-PER"] + ["I-PER"] * (len(p) - 1) + ["O", "O", "O"]
        elif kind == 2:  # org as agent
            o = orgs[rng.integers(0, len(orgs))]
            toks = ["engineers", "at"] + o + ["shipped", "it"]
            tags = ["O", "O", "B-ORG"] + ["I-ORG"] * (len(o) - 1) + ["O", "O"]
        else:  # location
            l = locs[rng.integers(0, len(locs))]
            toks = ["they", "moved", "to"] + l + ["recently"]
            tags = ["O", "O", "O", "B-LOC"] + ["I-LOC"] * (len(l) - 1) + ["O"]
        sents.append((toks, tags))
    return sents


def _rule_bio(tokens):
    """Rule NER output mapped onto the BIO scheme for comparison."""
    flat = rule_ner_tag(tokens)
    kind_map = {"PERSON": "PER", "ORG": "ORG", "ENTITY": "ORG"}
    out, prev = [], "O"
    for t in flat:
        k = kind_map.get(t)
        if k is None:
            out.append("O")
        else:
            out.append(("I-" if prev == t else "B-") + k)
        prev = t
    return out


def test_ner_estimator_beats_rule_baseline():
    from keystone_tpu.ops.nlp.tagging import NEREstimator

    sents = _ner_corpus()
    train, test = sents[:256], sents[256:]
    tagger = NEREstimator(n_iter=8).fit(Dataset.from_items(train))

    t_correct = r_correct = total = 0
    for toks, gold in test:
        pred = tagger(toks)
        rule = _rule_bio(toks)
        t_correct += sum(p == g for p, g in zip(pred, gold))
        r_correct += sum(p == g for p, g in zip(rule, gold))
        total += len(gold)
    trained_acc = t_correct / total
    rule_acc = r_correct / total
    assert trained_acc > rule_acc + 0.15, (trained_acc, rule_acc)
    assert trained_acc > 0.9, trained_acc

    # the trained model plugs into the NER node as an annotator
    node = NER(annotator=tagger)
    out = node.apply(["yesterday", "karen", "smith", "visited", "us"])
    assert out[1:3] == ["B-PER", "I-PER"], out
