"""PCA/ZCA tests (reference: PCASuite, ZCAWhitenerSuite)."""

import numpy as np
import pytest

from keystone_tpu.ops.learning import (
    ApproximatePCAEstimator,
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
    ZCAWhitenerEstimator,
)
from keystone_tpu.parallel.dataset import Dataset


def _random_lowrank(n, d, r, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.standard_normal((n, r)) @ rng.standard_normal((r, d))
        + 0.01 * rng.standard_normal((n, d))
    ).astype(np.float32)


def _np_pca(X, dims):
    Xc = X - X.mean(0)
    _, _, vt = np.linalg.svd(Xc, full_matrices=False)
    V = vt.T
    col_max = V.max(0)
    abs_max = np.abs(V).max(0)
    V = V * np.where(col_max == abs_max, 1.0, -1.0)
    return V[:, :dims]


def test_local_pca_matches_numpy():
    X = _random_lowrank(80, 12, 5)
    t = PCAEstimator(4).fit(Dataset.of(X))
    np.testing.assert_allclose(
        np.asarray(t.pca_mat), _np_pca(X, 4), atol=2e-3
    )


def test_distributed_pca_matches_local(mesh8):
    X = _random_lowrank(96, 10, 4, seed=1)
    local = PCAEstimator(3).fit(Dataset.of(X))
    dist = DistributedPCAEstimator(3).fit(Dataset.of(X).shard())
    np.testing.assert_allclose(
        np.abs(np.asarray(dist.pca_mat)),
        np.abs(np.asarray(local.pca_mat)),
        atol=5e-3,
    )


def test_approximate_pca_subspace(mesh8):
    X = _random_lowrank(120, 16, 3, seed=2)
    exact = _np_pca(X, 3)
    approx = np.asarray(ApproximatePCAEstimator(3, seed=0).fit(Dataset.of(X)).pca_mat)
    # compare subspaces via principal angles
    s = np.linalg.svd(exact.T @ approx, compute_uv=False)
    assert s.min() > 0.99


def test_column_pca_on_matrix_items():
    rng = np.random.default_rng(3)
    mats = [rng.standard_normal((8, 20)).astype(np.float32) for _ in range(5)]
    t = LocalColumnPCAEstimator(4).fit(Dataset.from_items(mats))
    out = t.apply(mats[0])
    assert np.asarray(out).shape == (4, 20)


def test_column_pca_optimize_picks_an_option(mesh8):
    rng = np.random.default_rng(4)
    mats = [rng.standard_normal((8, 10)).astype(np.float32) for _ in range(4)]
    est = ColumnPCAEstimator(4)
    chosen = est.optimize([Dataset.from_items(mats)], 4)
    assert isinstance(
        chosen, (LocalColumnPCAEstimator, DistributedColumnPCAEstimator)
    )


def test_zca_whitening_decorrelates():
    rng = np.random.default_rng(5)
    X = (rng.standard_normal((500, 6)) @ rng.standard_normal((6, 6))).astype(
        np.float32
    )
    w = ZCAWhitenerEstimator(eps=1e-6).fit(Dataset.of(X))
    out = np.asarray(w.apply(X))
    cov = out.T @ out / (out.shape[0] - 1)
    np.testing.assert_allclose(cov, np.eye(6), atol=0.15)
    # whitener is symmetric (ZCA, not PCA whitening)
    np.testing.assert_allclose(
        np.asarray(w.whitener), np.asarray(w.whitener).T, atol=1e-4
    )
