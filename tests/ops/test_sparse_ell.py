"""ELL sparse solver (ops/learning/sparse_ell.py): densify correctness,
one-pass normal equations vs dense exact solve, sharded mesh8 path."""

import jax
import pytest as _pytest

# Only the sharded tests need the 8-way mesh (shared needs_mesh8 gate in
# tests/conftest.py); the single-device ELL correctness tests must still
# run in the real-hardware sweep.
mesh8 = _pytest.mark.needs_mesh8


import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.learning import (
    EllLeastSquaresEstimator,
    ell_dataset,
)
from keystone_tpu.ops.learning.sparse_ell import ell_to_dense
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.dataset import Dataset


def _make_ell(rng, n, d, nnz):
    idx = rng.integers(0, d, (n, nnz)).astype(np.int32)
    vals = rng.standard_normal((n, nnz)).astype(np.float32)
    dense = np.zeros((n, d), np.float32)
    for r in range(n):
        for j in range(nnz):
            dense[r, idx[r, j]] += vals[r, j]
    return idx, vals, dense


def test_ell_to_dense_matches_scatter_incl_duplicates():
    rng = np.random.default_rng(0)
    idx, vals, dense = _make_ell(rng, 32, 16, 4)
    out = np.asarray(
        ell_to_dense(jnp.asarray(idx), jnp.asarray(vals), 16),
        np.float32,
    )
    np.testing.assert_allclose(out, dense, atol=0.02)  # bf16 tile


def test_ell_solver_matches_dense_normal_equations():
    rng = np.random.default_rng(1)
    n, d, k, nnz = 4000, 64, 3, 5
    idx, vals, dense = _make_ell(rng, n, d, nnz)
    W_true = rng.standard_normal((d, k)).astype(np.float32)
    Y = dense @ W_true
    lam = 1e-3

    est = EllLeastSquaresEstimator(d=d, lam=lam, chunk=512)
    model = est.fit(ell_dataset(idx, vals), Dataset.from_array(jnp.asarray(Y)))
    W = np.asarray(model.W, np.float64)

    G = dense.T @ dense
    W_ref = np.linalg.solve(G + lam * n * np.eye(d), dense.T @ Y)
    assert np.abs(W - W_ref).max() / np.abs(W_ref).max() < 5e-2  # bf16 Gram
    # the fit actually recovers the generating model
    assert np.abs(W - W_true).max() < 0.15

    # ELL-aware apply: predictions via row gather
    preds = model.apply_batch(ell_dataset(idx, vals))
    np.testing.assert_allclose(
        np.asarray(preds.padded()), dense @ W.astype(np.float32),
        rtol=0.05, atol=0.05,
    )


@mesh8
def test_ell_solver_sharded_mesh8_matches_single():
    rng = np.random.default_rng(2)
    n, d, k, nnz = 1024, 32, 2, 3
    idx, vals, dense = _make_ell(rng, n, d, nnz)
    Y = (dense @ rng.standard_normal((d, k))).astype(np.float32)

    est = EllLeastSquaresEstimator(d=d, lam=1e-3, chunk=64)
    W_single = np.asarray(
        est.fit(ell_dataset(idx, vals),
                Dataset.from_array(jnp.asarray(Y))).W
    )

    mesh = mesh_lib.make_mesh(n_data=8, n_model=1)
    with mesh_lib.use_mesh(mesh):
        sh2 = mesh_lib.data_sharding(mesh)
        ds = ell_dataset(
            jax.device_put(jnp.asarray(idx), sh2),
            jax.device_put(jnp.asarray(vals), sh2),
        )
        Yd = Dataset.from_array(jax.device_put(jnp.asarray(Y), sh2))
        W_sharded = np.asarray(est.fit(ds, Yd).W)
    np.testing.assert_allclose(W_sharded, W_single, rtol=2e-2, atol=2e-3)


def test_ell_pad_rows_contribute_nothing():
    rng = np.random.default_rng(3)
    n, d, k, nnz = 96, 16, 2, 3
    idx, vals, dense = _make_ell(rng, n, d, nnz)
    Y = (dense @ rng.standard_normal((d, k))).astype(np.float32)

    est = EllLeastSquaresEstimator(d=d, lam=1e-3, chunk=32)
    W_plain = np.asarray(
        est.fit(ell_dataset(idx, vals), Dataset.from_array(jnp.asarray(Y))).W
    )
    # same rows + 32 explicit zero-val pad rows (idx arbitrary)
    idx_p = np.concatenate([idx, rng.integers(0, d, (32, nnz)).astype(np.int32)])
    vals_p = np.concatenate([vals, np.zeros((32, nnz), np.float32)])
    Y_p = np.concatenate([Y, np.ones((32, k), np.float32)])  # garbage labels
    W_pad = np.asarray(
        est.fit(ell_dataset(idx_p, vals_p, n=n),
                Dataset.from_array(jnp.asarray(Y_p), n=n)).W
    )
    np.testing.assert_allclose(W_pad, W_plain, rtol=1e-5, atol=1e-6)


@mesh8
def test_ell_sharded_pads_nondivisible_rows():
    rng = np.random.default_rng(4)
    n, d, k, nnz = 1001, 32, 2, 3  # not divisible by 8
    idx, vals, dense = _make_ell(rng, n, d, nnz)
    Y = (dense @ rng.standard_normal((d, k))).astype(np.float32)
    est = EllLeastSquaresEstimator(d=d, lam=1e-3, chunk=64)
    W_single = np.asarray(
        est.fit(ell_dataset(idx, vals), Dataset.from_array(jnp.asarray(Y))).W
    )
    mesh = mesh_lib.make_mesh(n_data=8, n_model=1)
    with mesh_lib.use_mesh(mesh):
        W_sh = np.asarray(
            est.fit(ell_dataset(idx, vals),
                    Dataset.from_array(jnp.asarray(Y))).W
        )
    np.testing.assert_allclose(W_sh, W_single, rtol=2e-2, atol=2e-3)


def test_ell_rank_deficient_lam_zero_is_finite():
    """Columns never hit by any hash bin -> singular Gram; lam=0 must not
    produce NaN/inf (eigh-clamp fallback in the device solver)."""
    rng = np.random.default_rng(5)
    n, d, k, nnz = 256, 64, 2, 3
    idx = rng.integers(0, d // 2, (n, nnz)).astype(np.int32)  # half unused
    vals = rng.standard_normal((n, nnz)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    est = EllLeastSquaresEstimator(d=d, lam=0.0, chunk=64)
    W = np.asarray(
        est.fit(ell_dataset(idx, vals), Dataset.from_array(jnp.asarray(Y))).W
    )
    assert np.isfinite(W).all()


def test_segmented_dispatch_matches_single_pass():
    """Forcing multi-segment accumulation (tiny segment_flops) must
    reproduce the single-dispatch fit exactly — same Gram algebra,
    just split across dispatches (the remote-worker robustness path
    the Amazon-16384 row uses)."""
    import dataclasses as dc

    rng = np.random.default_rng(7)
    n, d, nnz, k = 512, 32, 3, 2
    idx = rng.integers(0, d, (n, nnz)).astype(np.int32)
    vals = rng.standard_normal((n, nnz)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    ds = ell_dataset(jnp.asarray(idx), jnp.asarray(vals))
    labels = Dataset.from_array(jnp.asarray(Y))
    base = EllLeastSquaresEstimator(d=d, lam=1e-2, chunk=64)
    single = base.fit(ds, labels)
    # 2*512*32*32 = 1.05e6 flops; a 3e5 budget forces several segments
    seg = dc.replace(base, segment_flops=3e5).fit(ds, labels)
    np.testing.assert_allclose(
        np.asarray(seg.W), np.asarray(single.W), rtol=1e-6, atol=1e-7
    )
