"""NLP node tests (reference: nodes/nlp suites — NGramsFeaturizerSuite,
StupidBackoffSuite, indexers tests)."""

import numpy as np
import pytest

from keystone_tpu.ops.nlp import (
    HashingTF,
    LowerCase,
    NaiveBitPackIndexer,
    NGram,
    NGramIndexer,
    NGramsCounts,
    NGramsFeaturizer,
    NGramsHashingTF,
    StupidBackoffEstimator,
    Tokenizer,
    Trim,
    WordFrequencyEncoder,
)
from keystone_tpu.parallel.dataset import Dataset


def test_string_utils():
    assert Trim().apply("  hi  ") == "hi"
    assert LowerCase().apply("HeLLo") == "hello"
    assert Tokenizer().apply("Hello, world! foo") == ["Hello", "world", "foo"]


def test_string_utils_reference_suite_fixtures():
    """Port of StringUtilsSuite (nodes/nlp/StringUtilsSuite.scala) with
    its exact fixtures — including Scala split semantics: leading empty
    token kept, trailing empties dropped."""
    strings = [
        "  The quick BROWN fo.X ",
        " ! !.,)JumpeD. ovER the LAZy DOG.. ! ",
    ]
    assert [Trim().apply(s) for s in strings] == [
        "The quick BROWN fo.X",
        "! !.,)JumpeD. ovER the LAZy DOG.. !",
    ]
    assert [LowerCase().apply(s) for s in strings] == [
        "  the quick brown fo.x ",
        " ! !.,)jumped. over the lazy dog.. ! ",
    ]
    assert [Tokenizer().apply(s) for s in strings] == [
        ["", "The", "quick", "BROWN", "fo", "X"],
        ["", "JumpeD", "ovER", "the", "LAZy", "DOG"],
    ]




def test_ngrams_featurizer_orders_and_content():
    grams = NGramsFeaturizer([1, 2, 3]).apply(["a", "b", "c"])
    assert ["a"] in grams and ["a", "b"] in grams and ["a", "b", "c"] in grams
    assert ["c"] in grams and ["b", "c"] in grams
    assert len(grams) == 3 + 2 + 1
    with pytest.raises(ValueError):
        NGramsFeaturizer([1, 3])


def test_ngrams_counts_sorted_desc():
    lines = [[["a"], ["b"], ["a"]], [["a"], ["c"]]]
    out = NGramsCounts().apply(Dataset.from_items(lines)).items()
    assert out[0] == (NGram(("a",)), 3)
    counts = dict(out)
    assert counts[NGram(("b",))] == 1


def test_hashing_tf_deterministic_counts():
    tf = HashingTF(64)
    v1 = tf.apply(["x", "y", "x"])
    v2 = tf.apply(["x", "y", "x"])
    a1, a2 = np.asarray(v1.todense()), np.asarray(v2.todense())
    np.testing.assert_allclose(a1, a2)
    assert a1.sum() == 3.0
    assert a1.max() == 2.0


def test_ngrams_hashing_tf_counts_all_orders():
    tf = NGramsHashingTF([1, 2], 1024)
    v = np.asarray(tf.apply(["a", "b", "c"]).todense())
    # 3 unigrams + 2 bigrams
    assert v.sum() == 5.0


def test_word_frequency_encoder_ranks_and_oov():
    data = Dataset.from_items(
        [["the", "cat"], ["the", "dog"], ["the", "cat", "bird"]]
    )
    t = WordFrequencyEncoder().fit(data)
    assert t.apply(["the"]) == [0]  # most frequent -> rank 0
    assert t.apply(["cat"]) == [1]
    assert t.apply(["unseen"]) == [-1]
    assert t.unigram_counts[0] == 3


def test_bitpack_indexer_roundtrip():
    idx = NaiveBitPackIndexer()
    tri = idx.pack([5, 9, 3])
    assert idx.ngram_order(tri) == 3
    assert [idx.unpack(tri, i) for i in range(3)] == [5, 9, 3]
    bi = idx.remove_farthest_word(tri)
    assert idx.ngram_order(bi) == 2
    assert [idx.unpack(bi, i) for i in range(2)] == [9, 3]
    ctx = idx.remove_current_word(tri)
    assert idx.ngram_order(ctx) == 2
    assert [idx.unpack(ctx, i) for i in range(2)] == [5, 9]


def test_stupid_backoff_scores():
    # corpus: "a b c", "a b d"
    tokens = [["a", "b", "c"], ["a", "b", "d"]]
    unigrams = {"a": 2, "b": 2, "c": 1, "d": 1}
    grams = NGramsFeaturizer([2, 3]).apply_batch(Dataset.from_items(tokens))
    counts = NGramsCounts().apply(grams)
    model = StupidBackoffEstimator(unigrams).fit(counts)
    # seen bigram: freq(a b)/freq(a) = 2/2
    assert model.score(("a", "b")) == pytest.approx(1.0)
    # seen trigram: freq(a b c)/freq(a b) = 1/2
    assert model.score(("a", "b", "c")) == pytest.approx(0.5)
    # unseen trigram backs off: alpha * S(b z) -> alpha^2 * freq(z)/N = 0
    assert model.score(("a", "b", "z")) == pytest.approx(0.0)
    # unseen bigram with seen tail: alpha * freq(b)/numTokens
    assert model.score(("z", "b")) == pytest.approx(0.4 * 2 / 6)
