"""Weighted least-squares tests against a direct numpy f64 translation of
the reference algorithm (reference: BlockWeightedLeastSquaresSuite —
distributed vs local solutions on CSV fixtures, incl. shuffled variants)."""

import numpy as np
import pytest

from keystone_tpu.ops.learning.weighted_ls import (
    BlockWeightedLeastSquaresEstimator,
    PerClassWeightedLeastSquaresEstimator,
)
from keystone_tpu.parallel.dataset import Dataset


def ref_block_weighted_bcd(X, Y, block_size, num_iter, lam, w):
    """numpy f64 translation of BlockWeightedLeastSquares.scala:139-314."""
    X = X.astype(np.float64)
    Y = Y.astype(np.float64)
    n, D = X.shape
    C = Y.shape[1]
    class_of = Y.argmax(1)
    counts = np.bincount(class_of, minlength=C)
    jlm = 2 * w + 2 * (1 - w) * counts / n - 1
    R = Y - jlm[None, :]
    blocks = [(s, min(s + block_size, D)) for s in range(0, D, block_size)]
    W = np.zeros((D, C))
    jm_full = np.zeros((C, D))
    for _ in range(num_iter):
        for (s, e) in blocks:
            Xb = X[:, s:e]
            res_mean = R.mean(0)
            pop_mean = Xb.mean(0)
            pop_cov = Xb.T @ Xb / n - np.outer(pop_mean, pop_mean)
            pop_xtr = Xb.T @ R / n
            delta = np.zeros((e - s, C))
            for c in range(C):
                rows = class_of == c
                Xc = Xb[rows]
                nc = counts[c]
                cmean = Xc.mean(0)
                Xz = Xc - cmean
                ccov = Xz.T @ Xz / nc
                rl = R[rows, c]
                cxtr = Xc.T @ rl / nc
                md = cmean - pop_mean
                jxtx = (
                    pop_cov * (1 - w)
                    + ccov * w
                    + np.outer(md, md) * (1 - w) * w
                )
                mmw = res_mean[c] * (1 - w) + w * rl.mean()
                jm = cmean * w + pop_mean * (1 - w)
                jxtr = pop_xtr[:, c] * (1 - w) + cxtr * w - jm * mmw
                delta[:, c] = np.linalg.solve(
                    jxtx + lam * np.eye(e - s), jxtr - W[s:e, c] * lam
                )
                jm_full[c, s:e] = jm
            W[s:e] += delta
            R = R - Xb @ delta
    b = jlm - np.einsum("cd,dc->c", jm_full, W)
    return W, b


def _weighted_problem(n=90, D=10, C=3, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, C, n)
    centers = rng.standard_normal((C, D)) * 2
    X = (centers[y] + rng.standard_normal((n, D))).astype(np.float32)
    Y = (2.0 * np.eye(C, dtype=np.float32)[y] - 1.0)
    return X, Y, y


@pytest.mark.parametrize("num_iter,block_size", [(1, 10), (2, 4)])
def test_block_weighted_matches_reference_translation(
    mesh8, num_iter, block_size
):
    X, Y, _ = _weighted_problem()
    lam, w = 0.1, 0.6
    est = BlockWeightedLeastSquaresEstimator(
        block_size, num_iter, lam, w, class_chunk=2
    )
    model = est.fit(Dataset.of(X).shard(), Dataset.of(Y).shard())
    W_ref, b_ref = ref_block_weighted_bcd(X, Y, block_size, num_iter, lam, w)
    np.testing.assert_allclose(np.asarray(model.W), W_ref, atol=2e-2)
    np.testing.assert_allclose(
        np.asarray(model.intercept), b_ref, atol=2e-2
    )


def test_block_weighted_classifies(mesh8):
    X, Y, y = _weighted_problem(n=120, D=8, C=3, seed=1)
    est = BlockWeightedLeastSquaresEstimator(8, 2, 0.01, 0.5)
    model = est.fit(Dataset.of(X), Dataset.of(Y))
    pred = np.asarray(model.apply_batch(Dataset.of(X)).array())
    assert (pred.argmax(1) == y).mean() > 0.95


def test_block_weighted_weight():
    assert BlockWeightedLeastSquaresEstimator(10, 3, 0.1, 0.5).weight == 10


def test_per_class_weighted_close_to_block_weighted(mesh8):
    """Both solvers optimize the same mixture-weighted objective; with
    enough sweeps they land close on a well-conditioned problem."""
    X, Y, y = _weighted_problem(n=100, D=6, C=2, seed=2)
    lam, w = 0.05, 0.5
    m1 = BlockWeightedLeastSquaresEstimator(6, 8, lam, w).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    m2 = PerClassWeightedLeastSquaresEstimator(6, 8, lam, w).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    p1 = np.asarray(m1.apply_batch(Dataset.of(X)).array())
    p2 = np.asarray(m2.apply_batch(Dataset.of(X)).array())
    assert (p1.argmax(1) == y).mean() > 0.95
    assert (p2.argmax(1) == y).mean() > 0.95


def test_per_class_weighted_shuffled_invariance(mesh8):
    """Class-grouping must be order-independent (reference tests shuffled
    CSV fixtures)."""
    X, Y, _ = _weighted_problem(n=60, D=6, C=2, seed=3)
    perm = np.random.default_rng(0).permutation(len(X))
    est = BlockWeightedLeastSquaresEstimator(6, 1, 0.1, 0.5)
    m1 = est.fit(Dataset.of(X), Dataset.of(Y))
    m2 = est.fit(Dataset.of(X[perm]), Dataset.of(Y[perm]))
    np.testing.assert_allclose(
        np.asarray(m1.W), np.asarray(m2.W), atol=1e-3
    )


@pytest.mark.parametrize("num_iter,block_size", [(1, 10), (2, 4)])
def test_block_weighted_pcg_matches_reference_translation(
    mesh8, num_iter, block_size
):
    """The matrix-free PCG solve path (solve="pcg") must reproduce the
    same reference translation the Cholesky path does."""
    X, Y, _ = _weighted_problem()
    lam, w = 0.1, 0.6
    est = BlockWeightedLeastSquaresEstimator(
        block_size, num_iter, lam, w, class_chunk=2, solve="pcg"
    )
    model = est.fit(Dataset.of(X).shard(), Dataset.of(Y).shard())
    W_ref, b_ref = ref_block_weighted_bcd(X, Y, block_size, num_iter, lam, w)
    np.testing.assert_allclose(np.asarray(model.W), W_ref, atol=2e-2)
    np.testing.assert_allclose(
        np.asarray(model.intercept), b_ref, atol=2e-2
    )


def test_block_weighted_pcg_agrees_with_chol():
    """pcg and chol are two solvers for the same systems: their fitted
    models must agree far tighter than either's tolerance vs f64."""
    X, Y, _ = _weighted_problem(n=200, D=48, C=4, seed=3)
    kw = dict(block_size=48, num_iter=1, lam=0.05, mixture_weight=0.5)
    chol = BlockWeightedLeastSquaresEstimator(solve="chol", **kw).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    pcg = BlockWeightedLeastSquaresEstimator(solve="pcg", **kw).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    np.testing.assert_allclose(
        np.asarray(pcg.W), np.asarray(chol.W), atol=5e-4
    )


def test_block_weighted_skewed_classes_gathered_layout(mesh8):
    """Heavy class imbalance on EVERY physical path: the chol solver's
    grouped and (explicitly forced) gathered layouts, and the ungrouped
    PCG solver, all against the f64 reference translation. The r3 test
    relied on the auto layout heuristic tripping 'gathered' but the
    fixture never actually crossed the threshold (ADVICE r3) — the
    ``layout`` override pins each path explicitly."""
    rng = np.random.default_rng(5)
    # counts [84, 3, 2, 1]
    y = np.concatenate([
        np.zeros(84, np.int64), np.full(3, 1), np.full(2, 2), [3],
    ])
    C, D = 4, 10
    centers = rng.standard_normal((C, D)) * 2
    X = (centers[y] + rng.standard_normal((len(y), D))).astype(np.float32)
    Y = (2.0 * np.eye(C, dtype=np.float32)[y] - 1.0)
    lam, w = 0.1, 0.6
    W_ref, b_ref = ref_block_weighted_bcd(X, Y, 10, 1, lam, w)
    cases = [
        dict(solve="chol", layout="grouped"),
        dict(solve="chol", layout="gathered"),
        dict(solve="pcg"),
    ]
    for kw in cases:
        est = BlockWeightedLeastSquaresEstimator(
            10, 1, lam, w, class_chunk=2, **kw
        )
        model = est.fit(Dataset.of(X), Dataset.of(Y))
        np.testing.assert_allclose(
            np.asarray(model.W), W_ref, atol=2e-2, err_msg=str(kw)
        )
        np.testing.assert_allclose(
            np.asarray(model.intercept), b_ref, atol=2e-2, err_msg=str(kw)
        )


def test_block_weighted_layout_memory_budget(monkeypatch):
    """The auto layout decision must refuse the grouped copy when it
    would not fit the device memory budget (ADVICE r3), falling back to
    the gathered path — results unchanged."""
    from keystone_tpu.ops.learning import weighted_ls as wls

    X, Y, _ = _weighted_problem(n=96, D=12, C=3, seed=7)
    est = BlockWeightedLeastSquaresEstimator(12, 1, 0.05, 0.5, solve="chol")
    W_normal = np.asarray(est.fit(Dataset.of(X), Dataset.of(Y)).W)
    gathered_ran = {}
    orig = wls._class_chunk_stats_gathered

    def spy(*a, **k):
        gathered_ran["yes"] = True
        return orig(*a, **k)

    monkeypatch.setattr(wls, "_class_chunk_stats_gathered", spy)
    monkeypatch.setattr(wls, "_device_memory_limit", lambda: 1)
    W_tight = np.asarray(est.fit(Dataset.of(X), Dataset.of(Y)).W)
    assert gathered_ran.get("yes"), "tight budget must force gathered"
    np.testing.assert_allclose(W_tight, W_normal, atol=1e-4)


def test_block_weighted_pcg_reports_convergence():
    X, Y, _ = _weighted_problem(n=120, D=16, C=3, seed=2)
    model = BlockWeightedLeastSquaresEstimator(
        16, 1, 0.05, 0.5, solve="pcg"
    ).fit(Dataset.of(X), Dataset.of(Y))
    rel = float(model.solver_info["pcg_max_rel_residual"])
    assert rel < 1e-5, rel  # converged, and the diagnostic surfaces it
    # chol path attaches no PCG diagnostics
    model2 = BlockWeightedLeastSquaresEstimator(
        16, 1, 0.05, 0.5, solve="chol"
    ).fit(Dataset.of(X), Dataset.of(Y))
    assert model2.solver_info is None


def test_block_weighted_pcg_ragged_blocks_match_chol():
    """D not divisible by block_size: the PCG path takes the per-block
    dispatch fallback (non-uniform widths) instead of the fused scan —
    both must produce the same model as the exact chol solver."""
    X, Y, _ = _weighted_problem(n=160, D=20, C=4, seed=9)
    kw = dict(block_size=8, num_iter=2, lam=0.05, mixture_weight=0.5)
    chol = BlockWeightedLeastSquaresEstimator(solve="chol", **kw).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    pcg = BlockWeightedLeastSquaresEstimator(solve="pcg", **kw).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    np.testing.assert_allclose(
        np.asarray(pcg.W), np.asarray(chol.W), atol=5e-4
    )
    np.testing.assert_allclose(
        np.asarray(pcg.intercept), np.asarray(chol.intercept), atol=5e-4
    )


def test_limb_splitting_recovers_f32_products():
    """The bf16 limb decomposition behind the PCG GEMMs: a bf16 x
    3-limb contraction must match the f64 reference to ~2^-24
    relative."""
    import jax.numpy as jnp

    from keystone_tpu.ops.learning.weighted_ls import (
        _dot00, _limb3, _sum3,
    )

    rng = np.random.default_rng(0)
    a16 = jnp.asarray(
        rng.standard_normal((512, 64)).astype(np.float32), jnp.bfloat16
    )
    b32 = jnp.asarray(rng.standard_normal((512, 8)).astype(np.float32))
    exact = np.asarray(a16, np.float64).T @ np.asarray(b32, np.float64)
    scale = np.abs(exact).max()

    out3 = np.asarray(_sum3(_dot00(a16, _limb3(b32, 1)), axis=1))
    assert np.abs(out3 - exact).max() / scale < 1e-6

    # and the limbs themselves reconstruct the f32 operand
    limbs = np.asarray(_limb3(b32, 1), np.float64)
    recon = limbs[:, :8] + limbs[:, 8:16] + limbs[:, 16:]
    assert np.abs(recon - np.asarray(b32, np.float64)).max() < 1e-7


def test_block_weighted_multi_hot_rows_agree_across_solvers():
    """ADVICE r4: multi-hot ±1 indicator rows must land in exactly ONE
    class — the argmax/first-positive (identical for indicators) — in
    BOTH solver paths, so pcg and chol fit the same systems."""
    X, Y, _ = _weighted_problem(n=200, D=48, C=4, seed=5)
    Y = np.asarray(Y).copy()
    # make a third of the rows multi-hot: add a second +1 at a LATER
    # column than the original positive (argmax keeps the first)
    rng = np.random.default_rng(0)
    for i in rng.choice(200, 66, replace=False):
        c = int(np.argmax(Y[i]))
        if c < 3:
            Y[i, c + 1 :][rng.integers(0, 4 - c - 1)] = 1.0
    kw = dict(block_size=48, num_iter=1, lam=0.05, mixture_weight=0.5)
    chol = BlockWeightedLeastSquaresEstimator(solve="chol", **kw).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    pcg = BlockWeightedLeastSquaresEstimator(solve="pcg", **kw).fit(
        Dataset.of(X), Dataset.of(Y)
    )
    np.testing.assert_allclose(
        np.asarray(pcg.W), np.asarray(chol.W), atol=5e-4
    )
