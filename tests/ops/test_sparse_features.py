"""Sparse feature selection/vectorization (ops/util/nodes.py) — port of
the reference SparseFeatureVectorizerSuite (nodes/misc/
SparseFeatureVectorizerSuite.scala) plus the occurrence-counting and
tie-break determinism contracts of CommonSparseFeatures.scala:14-16,37."""

import numpy as np

from keystone_tpu.ops.util.nodes import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseFeatureVectorizer,
)
from keystone_tpu.parallel.dataset import Dataset


def _dense(bcoo):
    return np.asarray(bcoo.todense())


def test_sparse_feature_vectorization():
    # SparseFeatureVectorizerSuite "sparse feature vectorization"
    vec = SparseFeatureVectorizer(
        {"First": 0, "Second": 1, "Third": 2}, dim=3
    )
    out = _dense(vec.apply({"Third": 4.0, "Fourth": 6.0, "First": 1.0}))
    assert out.shape == (3,)
    assert out.tolist() == [1.0, 0.0, 4.0]


def test_all_sparse_feature_selection():
    # SparseFeatureVectorizerSuite "all sparse feature selection"
    train = Dataset.from_items(
        [{"First": 0.0, "Second": 6.0}, {"Third": 3.0, "Second": 4.0}]
    )
    vec = AllSparseFeatures().fit(train)
    out = _dense(vec.apply({"Third": 4.0, "Fourth": 6.0, "First": 1.0}))
    got = {k: out[i] for k, i in vec.feature_index.items()}
    assert set(vec.feature_index) == {"First", "Second", "Third"}
    assert got["First"] == 1.0 and got["Second"] == 0.0
    assert got["Third"] == 4.0


def test_common_sparse_feature_selection():
    # SparseFeatureVectorizerSuite "common sparse feature selection":
    # Second appears 3x, Third 2x -> the top-2 vocabulary. "First"
    # appears once WITH VALUE 0.0 — it still counts as an occurrence
    # (CommonSparseFeatures.scala:37 flatMaps every (feature, value)
    # pair with weight 1) but loses on count.
    train = Dataset.from_items([
        {"First": 0.0, "Second": 6.0},
        {"Third": 3.0, "Second": 4.8},
        {"Third": 7.0, "Fourth": 5.0},
        {"Fifth": 5.0, "Second": 7.3},
    ])
    vec = CommonSparseFeatures(2).fit(train)
    assert set(vec.feature_index) == {"Second", "Third"}
    out = _dense(vec.apply({
        "Third": 4.0, "Seventh": 8.0, "Second": 1.3, "Fourth": 6.0,
        "First": 1.0,
    }))
    got = {k: out[i] for k, i in vec.feature_index.items()}
    assert got["Second"] == np.float32(1.3) and got["Third"] == 4.0


def test_common_sparse_zero_valued_occurrences_count():
    # a feature seen twice with value 0 outranks one seen once with a
    # large value — selection is by occurrence count, never by value
    train = Dataset.from_items([
        {"zero": 0.0, "big": 100.0},
        {"zero": 0.0},
    ])
    vec = CommonSparseFeatures(1).fit(train)
    assert list(vec.feature_index) == ["zero"]


def test_common_sparse_tie_break_is_earliest_appearance():
    # equal counts -> earliest-seen feature wins the top-k cutoff
    # (the reference's zipWithUniqueId min-id tie break,
    # CommonSparseFeatures.scala:14-16,40-42)
    train = Dataset.from_items([
        {"a": 1.0}, {"b": 1.0}, {"c": 1.0},
        {"a": 1.0}, {"b": 1.0}, {"c": 1.0},
    ])
    vec = CommonSparseFeatures(2).fit(train)
    assert list(vec.feature_index) == ["a", "b"]


def test_batch_vectorization_matches_single():
    vec = SparseFeatureVectorizer({"x": 0, "y": 1}, dim=2)
    items = [{"x": 2.0}, {"y": 3.0, "junk": 9.0}, {}]
    batch = np.asarray(
        vec.apply_batch(Dataset.from_items(items)).array().todense()
    )
    singles = np.stack([_dense(vec.apply(it)) for it in items])
    np.testing.assert_array_equal(batch, singles)
