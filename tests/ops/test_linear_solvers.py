"""Solver tests (reference suites: LinearMapperSuite,
BlockLinearMapperSuite — distributed solutions vs local closed form)."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.ops.learning import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
    LocalLeastSquaresEstimator,
)
from keystone_tpu.parallel.dataset import Dataset


def _ols(A, b, lam=0.0):
    d = A.shape[1]
    return np.linalg.solve(A.T @ A + lam * np.eye(d), A.T @ b)


def test_linear_map_estimator_exact(mesh8):
    rng = np.random.default_rng(0)
    A = rng.standard_normal((64, 8)).astype(np.float32)
    W_true = rng.standard_normal((8, 3)).astype(np.float32)
    b = A @ W_true
    model = LinearMapEstimator().fit(
        Dataset.of(A).shard(), Dataset.of(b).shard()
    )
    np.testing.assert_allclose(np.asarray(model.W), W_true, atol=1e-3)
    out = np.asarray(model.apply_batch(Dataset.of(A)).array())
    np.testing.assert_allclose(out, b, atol=1e-2)


def test_linear_map_estimator_l2(mesh8):
    rng = np.random.default_rng(1)
    A = rng.standard_normal((50, 6)).astype(np.float32)
    b = rng.standard_normal((50, 2)).astype(np.float32)
    lam = 0.7
    model = LinearMapEstimator(lam=lam).fit(Dataset.of(A), Dataset.of(b))
    np.testing.assert_allclose(np.asarray(model.W), _ols(A, b, lam), atol=2e-3)


def test_local_least_squares_d_gg_n():
    rng = np.random.default_rng(2)
    A = rng.standard_normal((20, 100)).astype(np.float32)
    b = rng.standard_normal((20, 4)).astype(np.float32)
    model = LocalLeastSquaresEstimator(lam=0.1).fit(Dataset.of(A), Dataset.of(b))
    n = 20
    K = A @ A.T + 0.1 * n * np.eye(n)
    expect = A.T @ np.linalg.solve(K, b)
    np.testing.assert_allclose(np.asarray(model.W), expect, atol=2e-3)


def test_block_ls_single_block_matches_exact(mesh8):
    """With one block and no padding issues, one BCD sweep = exact
    regularized OLS on centered data."""
    rng = np.random.default_rng(3)
    A = rng.standard_normal((64, 8)).astype(np.float32)
    W_true = rng.standard_normal((8, 3)).astype(np.float32)
    b = A @ W_true + 0.5
    est = BlockLeastSquaresEstimator(block_size=8, num_iter=1, lam=0.0)
    model = est.fit(Dataset.of(A).shard(), Dataset.of(b).shard())
    Ac = A - A.mean(0)
    bc = b - b.mean(0)
    expect = _ols(Ac, bc)
    np.testing.assert_allclose(np.asarray(model.W), expect, atol=5e-3)
    pred = np.asarray(model.apply_batch(Dataset.of(A)).array())
    np.testing.assert_allclose(pred, b, atol=5e-2)


def test_block_ls_converges_to_exact_with_iters(mesh8):
    """Multi-block BCD approaches the exact solution as sweeps increase."""
    rng = np.random.default_rng(4)
    A = rng.standard_normal((128, 12)).astype(np.float32)
    W_true = rng.standard_normal((12, 2)).astype(np.float32)
    b = A @ W_true
    lam = 1e-3
    Ac = A - A.mean(0)
    bc = b - b.mean(0)
    exact = _ols(Ac, bc, lam)

    err1 = _fit_err(A, b, lam, num_iter=1, exact=exact)
    err10 = _fit_err(A, b, lam, num_iter=10, exact=exact)
    assert err10 < err1 or err10 < 1e-3
    assert err10 < 1e-2


def _fit_err(A, b, lam, num_iter, exact):
    est = BlockLeastSquaresEstimator(block_size=5, num_iter=num_iter, lam=lam)
    model = est.fit(Dataset.of(A), Dataset.of(b))
    return float(np.abs(np.asarray(model.W) - exact).max())


def test_block_ls_padding_exact(mesh8):
    """Padded rows (n not a multiple of shard count) must not change the
    solution."""
    rng = np.random.default_rng(5)
    A = rng.standard_normal((61, 6)).astype(np.float32)
    b = rng.standard_normal((61, 2)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=6, num_iter=1, lam=0.1)
    m_sharded = est.fit(Dataset.of(A).shard(), Dataset.of(b).shard())
    m_plain = est.fit(Dataset.of(A), Dataset.of(b))
    np.testing.assert_allclose(
        np.asarray(m_sharded.W), np.asarray(m_plain.W), atol=1e-4
    )


def test_block_linear_mapper_apply_and_evaluate(mesh8):
    rng = np.random.default_rng(6)
    A = rng.standard_normal((32, 10)).astype(np.float32)
    b = rng.standard_normal((32, 3)).astype(np.float32)
    est = BlockLeastSquaresEstimator(block_size=4, num_iter=2, lam=0.01)
    model = est.fit(Dataset.of(A), Dataset.of(b))
    seen = []
    model.apply_and_evaluate(Dataset.of(A), lambda out: seen.append(out))
    assert len(seen) == 3  # ceil(10/4) blocks
    final = np.asarray(model.apply_batch(Dataset.of(A)).array())
    np.testing.assert_allclose(np.asarray(seen[-1])[:32], final, atol=1e-4)


def test_block_ls_weight():
    assert BlockLeastSquaresEstimator(10, num_iter=3).weight == 10
