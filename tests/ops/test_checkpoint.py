"""Failure recovery: cadenced loop checkpointing + resume in the block
solvers (reference: KernelRidgeRegression.scala:200-210 checkpoints
lineage every 25 blocks; here the loop state snapshots to disk and a
re-run resumes at the last completed block)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
from keystone_tpu.ops.learning.kernel import (
    GaussianKernelGenerator,
    KernelRidgeRegression,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.checkpoint import LoopCheckpointer


class _Interrupt(Exception):
    pass


def _fail_after(k):
    def cb(count):
        if count >= k:
            raise _Interrupt
    return cb


def test_loop_checkpointer_cadence_and_atomicity(tmp_path):
    p = str(tmp_path / "state.npz")
    ck = LoopCheckpointer(p, every=3)
    saves = []
    for i in range(7):
        ck.tick(lambda: saves.append(i) or {"i": np.int64(i)})
    assert saves == [2, 5]  # steps 3 and 6
    st = ck.load()
    assert int(st["i"]) == 5
    ck.clear()
    assert ck.load() is None


def test_block_ls_resume_matches_uninterrupted(tmp_path):
    rng = np.random.default_rng(0)
    n, d, k = 96, 40, 3
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = (X @ rng.standard_normal((d, k))).astype(np.float32)
    Xd = Dataset.from_array(jnp.asarray(X))
    Yd = Dataset.from_array(jnp.asarray(Y))

    base = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=0.1)
    W_ref = np.asarray(base.fit(Xd, Yd).W)

    p = str(tmp_path / "bls.npz")
    # interrupt mid second sweep (3 blocks/sweep): checkpoint every 2
    # blocks, die after 4 completed block updates
    est = dataclasses.replace(
        base, checkpoint_path=p, checkpoint_every=2,
        block_callback=_fail_after(4),
    )
    with pytest.raises(_Interrupt):
        est.fit(Xd, Yd)
    assert LoopCheckpointer(p).load() is not None

    resumed = dataclasses.replace(base, checkpoint_path=p,
                                  checkpoint_every=2)
    W_res = np.asarray(resumed.fit(Xd, Yd).W)
    np.testing.assert_allclose(W_res, W_ref, rtol=2e-4, atol=2e-5)
    # completed fit clears its snapshot so it can't leak into a later fit
    assert LoopCheckpointer(p).load() is None


def test_krr_resume_matches_uninterrupted(tmp_path):
    rng = np.random.default_rng(1)
    n, d, k = 64, 8, 2
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = rng.standard_normal((n, k)).astype(np.float32)
    Xd = Dataset.from_array(jnp.asarray(X))
    Yd = Dataset.from_array(jnp.asarray(Y))

    base = KernelRidgeRegression(
        GaussianKernelGenerator(gamma=0.05), lam=0.5, block_size=16,
        num_epochs=2, block_permuter=7,
    )
    W_ref = np.asarray(base.fit(Xd, Yd).model)

    p = str(tmp_path / "krr.npz")
    est = dataclasses.replace(
        base, checkpoint_path=p, checkpoint_every=1,
        block_callback=_fail_after(5),
    )
    with pytest.raises(_Interrupt):
        est.fit(Xd, Yd)

    resumed = dataclasses.replace(base, checkpoint_path=p,
                                  checkpoint_every=1)
    W_res = np.asarray(resumed.fit(Xd, Yd).model)
    np.testing.assert_allclose(W_res, W_ref, rtol=1e-5, atol=1e-6)
    assert LoopCheckpointer(p).load() is None


def test_krr_shuffled_schedule_is_deterministic_per_epoch():
    est = KernelRidgeRegression(
        GaussianKernelGenerator(gamma=0.1), lam=0.1, block_size=8,
        num_epochs=3, block_permuter=42,
    )
    o0 = est._epoch_order(0, 6)
    assert est._epoch_order(0, 6) == o0  # replayable
    assert sorted(o0) == list(range(6))
    assert o0 != est._epoch_order(1, 6) or o0 != est._epoch_order(2, 6)


def test_stale_checkpoint_from_different_config_is_discarded(tmp_path):
    rng = np.random.default_rng(2)
    X = rng.standard_normal((64, 32)).astype(np.float32)
    Y = (X @ rng.standard_normal((32, 2))).astype(np.float32)
    Xd = Dataset.from_array(jnp.asarray(X))
    Yd = Dataset.from_array(jnp.asarray(Y))

    p = str(tmp_path / "bls.npz")
    est = BlockLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=0.1, checkpoint_path=p,
        checkpoint_every=1, block_callback=_fail_after(2),
    )
    with pytest.raises(_Interrupt):
        est.fit(Xd, Yd)

    # resume with a DIFFERENT lam: stale snapshot must be ignored, and the
    # result must equal a fresh uninterrupted fit at the new lam
    changed = BlockLeastSquaresEstimator(
        block_size=16, num_iter=2, lam=5.0, checkpoint_path=p,
        checkpoint_every=1,
    )
    W_res = np.asarray(changed.fit(Xd, Yd).W)
    W_ref = np.asarray(
        BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=5.0)
        .fit(Xd, Yd).W
    )
    np.testing.assert_allclose(W_res, W_ref, rtol=1e-6)


def test_corrupt_checkpoint_is_discarded(tmp_path):
    p = str(tmp_path / "bad.npz")
    with open(p, "wb") as f:
        f.write(b"not an npz at all")
    assert LoopCheckpointer(p, fingerprint="x").load() is None
