"""Numerics anchored to the reference repo's own test fixtures.

Round-1 numerical tests compared against this repo's own numpy
translations, which can share a misreading with the implementation; these
tests instead load the exact CSV/PNG fixtures the reference suites use and
assert at the reference suites' tolerances:

- aMat.csv / bMat.csv (+ shuffled, 1-class): BlockWeightedLeastSquaresSuite
  (zero-gradient of the mixture-weighted objective, per-class vs block
  solver agreement, shuffle invariance, 1-class robustness)
- gmm_data.txt: GaussianMixtureModelSuite "GMM Two Centers dataset 3"
- gantrycrane.png / convolved.gantrycrane.csv: ConvolverSuite
  "convolutions should match scipy"
"""

import os

import numpy as np
import pytest

RES = "/root/reference/src/test/resources"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(RES), reason="reference fixtures not mounted"
)


def _load_mats(a_name, b_name):
    A = np.loadtxt(os.path.join(RES, a_name), delimiter=",")
    B = np.loadtxt(os.path.join(RES, b_name), delimiter=",")
    return A.astype(np.float32), B.astype(np.float32)


def weighted_gradient(X, Y, lam, w, W, b):
    """numpy translation of BlockWeightedLeastSquaresSuite.computeGradient
    (reference: BlockWeightedLeastSquaresSuite.scala:19-62): per-example
    weights are (1-w)/n everywhere except the example's true-class column,
    which gets (1-w)/n + w/n_class."""
    X = X.astype(np.float64)
    Y = Y.astype(np.float64)
    n = len(X)
    class_of = Y.argmax(1)
    counts = np.bincount(class_of, minlength=Y.shape[1])
    neg = (1.0 - w) / n
    wts = np.full_like(Y, neg)
    pos = neg + w / np.maximum(counts[class_of], 1)
    wts[np.arange(n), class_of] = pos
    out = (X @ W + b - Y) * wts
    return X.T @ out + lam * W


def _fit_weighted(a_name, b_name, block_size, num_iter, cls):
    from keystone_tpu.parallel.dataset import Dataset

    X, Y = _load_mats(a_name, b_name)
    lam, w = 0.1, 0.3
    est = cls(block_size, num_iter, lam, w)
    model = est.fit(Dataset.of(X), Dataset.of(Y))
    return X, Y, lam, w, model


def test_block_weighted_zero_gradient_fixture(mesh8):
    """Reference: 'BlockWeighted solver solution should have zero
    gradient' (blockSize=4, numIter=10, lam=0.1, w=0.3, tol 1e-2)."""
    from keystone_tpu.ops.learning.weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, Y, lam, w, model = _fit_weighted(
        "aMat.csv", "bMat.csv", 4, 10, BlockWeightedLeastSquaresEstimator
    )
    g = weighted_gradient(
        X, Y, lam, w,
        np.asarray(model.W, np.float64),
        np.asarray(model.intercept, np.float64),
    )
    assert np.linalg.norm(g) == pytest.approx(0.0, abs=1e-2)


def test_block_weighted_indivisible_block_fixture(mesh8):
    """Reference: 'should work with nFeatures not divisible by blockSize'
    (blockSize=5 over 12 features; gradient tol 1e-1, both solvers)."""
    from keystone_tpu.ops.learning.weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
        PerClassWeightedLeastSquaresEstimator,
    )

    for cls in (
        BlockWeightedLeastSquaresEstimator,
        PerClassWeightedLeastSquaresEstimator,
    ):
        X, Y, lam, w, model = _fit_weighted(
            "aMat.csv", "bMat.csv", 5, 10, cls
        )
        g = weighted_gradient(
            X, Y, lam, w,
            np.asarray(model.W, np.float64),
            np.asarray(model.intercept, np.float64),
        )
        assert np.linalg.norm(g) == pytest.approx(0.0, abs=1e-1), cls


def test_per_class_matches_block_weighted_fixture(mesh8):
    """Reference: 'Per-class solver solution should match BlockWeighted
    solver' (blockSize=4, numIter=5; reference tol 1e-6 in f64 — f32 Gram
    accumulation justifies 1e-3 here)."""
    from keystone_tpu.ops.learning.weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
        PerClassWeightedLeastSquaresEstimator,
    )

    _, _, _, _, m1 = _fit_weighted(
        "aMat.csv", "bMat.csv", 4, 5, BlockWeightedLeastSquaresEstimator
    )
    _, _, _, _, m2 = _fit_weighted(
        "aMat.csv", "bMat.csv", 4, 5, PerClassWeightedLeastSquaresEstimator
    )
    assert np.linalg.norm(
        np.asarray(m1.W) - np.asarray(m2.W)
    ) == pytest.approx(0.0, abs=1e-3)
    assert np.linalg.norm(np.asarray(m1.intercept)) == pytest.approx(
        np.linalg.norm(np.asarray(m2.intercept)), abs=1e-3
    )


def test_block_weighted_shuffled_fixture(mesh8):
    """Reference feeds the row-shuffled fixture through the class-grouping
    path; the fit must be row-order invariant."""
    from keystone_tpu.ops.learning.weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
    )

    _, _, _, _, m1 = _fit_weighted(
        "aMat.csv", "bMat.csv", 4, 3, BlockWeightedLeastSquaresEstimator
    )
    _, _, _, _, m2 = _fit_weighted(
        "aMatShuffled.csv",
        "bMatShuffled.csv",
        4,
        3,
        BlockWeightedLeastSquaresEstimator,
    )
    np.testing.assert_allclose(
        np.asarray(m1.W), np.asarray(m2.W), atol=1e-3
    )


def test_block_weighted_one_class_fixture(mesh8):
    """Reference: 'BlockWeighted solver should work with 1 class only'."""
    from keystone_tpu.ops.learning.weighted_ls import (
        BlockWeightedLeastSquaresEstimator,
    )

    X, Y, lam, w, model = _fit_weighted(
        "aMat-1class.csv", "bMat-1class.csv", 4, 10,
        BlockWeightedLeastSquaresEstimator,
    )
    assert np.isfinite(np.asarray(model.W)).all()


def test_gmm_fixture(mesh8):
    """Reference: GaussianMixtureModelSuite 'GMM Two Centers dataset 3' —
    gmm_data.txt, k=2, stopTolerance=0, maxIterations=30; centers ~ 0
    (tol 0.5), variances ~ {(1,25),(25,1)} (tol 2), weights ~ 0.5
    (tol 0.05)."""
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModelEstimator

    data = np.loadtxt(os.path.join(RES, "gmm_data.txt")).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(
        k=2, min_cluster_size=1, seed=0, stop_tolerance=0.0,
        max_iterations=30,
    ).fit(data)
    means = np.asarray(gmm.means, np.float64).T  # (k, d)
    variances = np.asarray(gmm.variances, np.float64).T  # (k, d)
    weights = np.asarray(gmm.weights, np.float64)
    # order-insensitive: sort components by first variance coordinate
    order = np.argsort(variances[:, 0])
    variances = variances[order]
    means = means[order]
    np.testing.assert_allclose(means, np.zeros((2, 2)), atol=0.5)
    np.testing.assert_allclose(
        variances, np.array([[1.0, 25.0], [25.0, 1.0]]), atol=2.0
    )
    np.testing.assert_allclose(weights, [0.5, 0.5], atol=0.05)


def test_convolver_gantrycrane_fixture(mesh8):
    """Reference: ConvolverSuite 'convolutions should match scipy' —
    convolve gantrycrane.png with the 0..26-valued 3x3x3 filter (flipped,
    unnormalized) and compare channel 0 against
    convolved.gantrycrane.csv. Reference images are BGR byte rasters
    (ImageConversions.bufferedImageToWrapper) with x = row; the CSV rows
    are (x, y, value)."""
    from keystone_tpu.ops.images.core import Convolver
    from keystone_tpu.ops.images.image_utils import load_image

    img = load_image(os.path.join(RES, "images", "gantrycrane.png"))
    assert img is not None
    img = np.asarray(img)[:, :, ::-1]  # RGB -> BGR to match the raster

    # kimg: value i at (x, y, 2-c) iterating x, y, c (channel reversed to
    # match python; ConvolverSuite.scala:104-113)
    kimg = np.zeros((3, 3, 3))
    i = 0
    for x in range(3):
        for y in range(3):
            for c in range(3):
                kimg[x, y, 2 - c] = i
                i += 1
    # second filter exists in the reference test; content irrelevant here
    kimg2 = np.zeros((3, 3, 3))
    kimg2[0, 0, 0] = 2.0
    kimg2[2, 0, 1] = 1.0

    # flipFilters=true: flip x, y AND channel (ImageUtils.flipImage:376-389)
    def flip(f):
        return f[::-1, ::-1, ::-1]

    def pack(f):
        # packFilters layout: col = c + x*C + y*C*xDim (Convolver.scala:99)
        k, _, C = f.shape
        out = np.zeros(k * k * C)
        for x in range(k):
            for y in range(k):
                for c in range(C):
                    out[c + x * C + y * C * k] = f[x, y, c]
        return out

    filters = np.stack([pack(flip(kimg)), pack(flip(kimg2))]).astype(
        np.float32
    )
    conv = Convolver(
        filters,
        img.shape[0],
        img.shape[1],
        3,
        normalize_patches=False,
    )
    out = np.asarray(conv.apply(img.astype(np.float32)))

    raw = np.loadtxt(
        os.path.join(RES, "images", "convolved.gantrycrane.csv"),
        delimiter=",",
    )
    xdim = int(raw[:, 0].max()) + 1
    ydim = int(raw[:, 1].max()) + 1
    expected = np.zeros((xdim, ydim))
    expected[raw[:, 0].astype(int), raw[:, 1].astype(int)] = raw[:, 2]
    assert out.shape[:2] == (xdim, ydim)
    np.testing.assert_allclose(out[:, :, 0], expected, rtol=1e-5, atol=1e-2)
