"""f32 matmul precision policy (utils/precision.py, README section):
f32 gets HIGHEST + f32 accumulation, bf16 keeps the native path AND its
dtype through model applies."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.images import Convolver
from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import hi_if_f32, mm

import jax


def test_hi_if_f32_gating():
    f32 = jnp.ones((2, 2), jnp.float32)
    b16 = jnp.ones((2, 2), jnp.bfloat16)
    assert hi_if_f32(f32, f32) == jax.lax.Precision.HIGHEST
    assert hi_if_f32(b16, f32) == jax.lax.Precision.HIGHEST
    assert hi_if_f32(b16, b16) is None


def test_mm_preserves_bf16_activations():
    a = jnp.ones((4, 8), jnp.bfloat16)
    w = jnp.ones((8, 3), jnp.bfloat16)
    assert mm(a, w).dtype == jnp.bfloat16  # bf16 pipeline stays bf16
    assert mm(a.astype(jnp.float32), w).dtype == jnp.float32


def test_linear_mapper_bf16_pipeline_stays_bf16():
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.standard_normal((8, 3)), jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal((5, 8)), jnp.bfloat16)
    out = LinearMapper(W).apply_batch(Dataset.from_array(x))
    assert out.padded().dtype == jnp.bfloat16


def test_convolver_fast_flag_close_to_exact():
    rng = np.random.default_rng(1)
    img = jnp.asarray((rng.random((12, 12, 3)) * 255).astype(np.float32))
    filters = jnp.asarray(rng.standard_normal((8, 27)).astype(np.float32))
    exact = Convolver(filters, 12, 12, 3, normalize_patches=True)
    fast = Convolver(filters, 12, 12, 3, normalize_patches=True, fast=True)
    a = np.asarray(exact.apply(img))
    b = np.asarray(fast.apply(img))
    # fast trades bounded error for speed; on CPU both paths are exact,
    # on TPU the DEFAULT-precision bf16 passes measure 5.3e-3 rel
    # (REAL_SWEEP r3) — the bound documents that measured ceiling
    assert np.abs(a - b).max() / np.abs(a).max() < 8e-3


@pytest.mark.slow
def test_bench_scale_gram_solve_vs_f64_host():
    """Scale-stress (VERDICT r2 #7): at a bench-scale shard (256k x 1024
    bf16, features offset +5 so the centered-Gram algebra G - n·μμᵀ
    cancels ~25x of magnitude), the device f32-Gram BlockLS solve must
    match an all-f64 host solve of the same bf16 data. Documented bound
    (README "f32 matmul precision policy"): max|W_dev − W_f64| /
    max|W_f64| ≤ 1e-3 — f32 accumulation noise over 256k-row sums plus
    the cancellation amplification; measured 3.5e-4 on the virtual CPU
    mesh (~3x margin); bf16 quantization of X itself is identical on
    both sides and does not count against the bound."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator

    N, D, K = 262_144, 1024, 8
    rng = np.random.default_rng(0)
    # +5 mean: the regime where centered-Gram cancellation bites
    Xh = (rng.standard_normal((N, D)) + 5.0).astype(np.float32)
    X = jnp.asarray(Xh, jnp.bfloat16)
    Xb64 = np.asarray(X, np.float64)  # the bf16 values, exactly, in f64
    Wt = rng.standard_normal((D, K))
    Yh = (Xb64 @ Wt).astype(np.float32)
    lam = 1e-2

    est = BlockLeastSquaresEstimator(block_size=D, num_iter=1, lam=lam)
    model = est.fit(
        Dataset.from_array(X), Dataset.from_array(jnp.asarray(Yh))
    )
    W_dev = np.asarray(model.W, np.float64)

    # all-f64 host reference on the SAME bf16-quantized data
    mu = Xb64.mean(0)
    Y64 = Yh.astype(np.float64)
    muy = Y64.mean(0)
    G = Xb64.T @ Xb64 - N * np.outer(mu, mu)
    rhs = Xb64.T @ (Y64 - muy)
    W_ref = np.linalg.solve(G + lam * np.eye(D), rhs)

    rel = np.abs(W_dev - W_ref).max() / np.abs(W_ref).max()
    assert rel <= 1e-3, rel
