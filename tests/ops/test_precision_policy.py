"""f32 matmul precision policy (utils/precision.py, README section):
f32 gets HIGHEST + f32 accumulation, bf16 keeps the native path AND its
dtype through model applies."""

import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images import Convolver
from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.precision import hi_if_f32, mm

import jax


def test_hi_if_f32_gating():
    f32 = jnp.ones((2, 2), jnp.float32)
    b16 = jnp.ones((2, 2), jnp.bfloat16)
    assert hi_if_f32(f32, f32) == jax.lax.Precision.HIGHEST
    assert hi_if_f32(b16, f32) == jax.lax.Precision.HIGHEST
    assert hi_if_f32(b16, b16) is None


def test_mm_preserves_bf16_activations():
    a = jnp.ones((4, 8), jnp.bfloat16)
    w = jnp.ones((8, 3), jnp.bfloat16)
    assert mm(a, w).dtype == jnp.bfloat16  # bf16 pipeline stays bf16
    assert mm(a.astype(jnp.float32), w).dtype == jnp.float32


def test_linear_mapper_bf16_pipeline_stays_bf16():
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.standard_normal((8, 3)), jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal((5, 8)), jnp.bfloat16)
    out = LinearMapper(W).apply_batch(Dataset.from_array(x))
    assert out.padded().dtype == jnp.bfloat16


def test_convolver_fast_flag_close_to_exact():
    rng = np.random.default_rng(1)
    img = jnp.asarray((rng.random((12, 12, 3)) * 255).astype(np.float32))
    filters = jnp.asarray(rng.standard_normal((8, 27)).astype(np.float32))
    exact = Convolver(filters, 12, 12, 3, normalize_patches=True)
    fast = Convolver(filters, 12, 12, 3, normalize_patches=True, fast=True)
    a = np.asarray(exact.apply(img))
    b = np.asarray(fast.apply(img))
    # fast trades bounded error for speed; on CPU both paths are exact
    assert np.abs(a - b).max() / np.abs(a).max() < 5e-3
