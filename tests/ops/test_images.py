"""Image node tests (reference: ConvolverSuite vs a SciPy-generated
reference, PoolerSuite, WindowerSuite)."""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.ops.images import (
    CenterCornerPatcher,
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
    channel_major_vectorize,
    pack_filters,
)
from keystone_tpu.ops.learning import ZCAWhitenerEstimator
from keystone_tpu.parallel.dataset import Dataset


def _naive_convolver(img, filters_packed, k, C, normalize, whitener, var_c):
    """Direct translation of Convolver.makePatches + GEMM
    (Convolver.scala:128-205)."""
    X, Y = img.shape[0], img.shape[1]
    rw, rh = X - k + 1, Y - k + 1
    patch_mat = np.zeros((rw * rh, k * k * C))
    for poy in range(k):
        for pox in range(k):
            for y in range(rh):
                for x in range(rw):
                    for c in range(C):
                        px = c + pox * C + poy * C * k
                        py = x + y * rw
                        patch_mat[py, px] = img[x + pox, y + poy, c]
    if normalize:
        means = patch_mat.mean(1)
        var = ((patch_mat - means[:, None]) ** 2).sum(1) / (
            patch_mat.shape[1] - 1
        )
        sds = np.sqrt(var + var_c)
        patch_mat = (patch_mat - means[:, None]) / sds[:, None]
    if whitener is not None:
        patch_mat = patch_mat - np.asarray(whitener.means)[None, :]
    conv = patch_mat @ filters_packed.T  # (rw*rh, F)
    # result image is RowMajor(resWidth, resHeight, F): idx = f + y*F + x*F*rh?
    # RowMajorArrayVectorizedImage: data[f + c-major...]; we only compare
    # values per (x, y, f) by reshaping fortran-style over (x, y)
    return conv.reshape(rh, rw, -1).transpose(1, 0, 2)  # wait: py = x + y*rw


def test_convolver_matches_naive():
    rng = np.random.default_rng(0)
    k, C, F = 3, 2, 4
    img = rng.standard_normal((8, 7, C)).astype(np.float32)
    filters = rng.standard_normal((F, k * k * C)).astype(np.float32)
    conv = Convolver(
        jnp.asarray(filters), 8, 7, C, normalize_patches=False
    )
    got = np.asarray(conv.apply(jnp.asarray(img)))
    naive = _naive_convolver(img, filters, k, C, False, None, 10.0)
    # naive is (rw, rh, F) after transpose — compare elementwise
    assert got.shape == (6, 5, F)
    np.testing.assert_allclose(got, naive, atol=1e-3)


def test_convolver_normalized_matches_naive():
    rng = np.random.default_rng(1)
    k, C, F = 3, 3, 5
    img = (rng.uniform(0, 1, (9, 9, C))).astype(np.float32)
    filters = rng.standard_normal((F, k * k * C)).astype(np.float32)
    conv = Convolver(
        jnp.asarray(filters), 9, 9, C, normalize_patches=True,
        var_constant=10.0,
    )
    got = np.asarray(conv.apply(jnp.asarray(img)))
    naive = _naive_convolver(img, filters, k, C, True, None, 10.0)
    np.testing.assert_allclose(got, naive, atol=1e-3)


def test_convolver_whitened_matches_naive():
    rng = np.random.default_rng(2)
    k, C, F = 2, 2, 3
    img = rng.uniform(0, 1, (6, 6, C)).astype(np.float32)
    filters = rng.standard_normal((F, k * k * C)).astype(np.float32)
    sample = rng.uniform(0, 1, (50, k * k * C)).astype(np.float32)
    whitener = ZCAWhitenerEstimator(eps=0.1).fit_single(jnp.asarray(sample))
    conv = Convolver(
        jnp.asarray(filters), 6, 6, C, whitener=whitener,
        normalize_patches=True,
    )
    got = np.asarray(conv.apply(jnp.asarray(img)))
    naive = _naive_convolver(img, filters, k, C, True, whitener, 10.0)
    np.testing.assert_allclose(got, naive, atol=1e-3)


def test_pooler_matches_reference_loop():
    rng = np.random.default_rng(3)
    img = rng.standard_normal((27, 27, 2)).astype(np.float32)
    pooler = Pooler(stride=13, pool_size=14)
    got = np.asarray(pooler.apply(jnp.asarray(img)))
    # reference loop: strideStart=7; x,y in {7, 20}; window [x-7, min(x+7, 27))
    assert got.shape == (2, 2, 2)
    for i, x in enumerate([7, 20]):
        for j, y in enumerate([7, 20]):
            for c in range(2):
                window = img[x - 7 : min(x + 7, 27), y - 7 : min(y + 7, 27), c]
                np.testing.assert_allclose(
                    got[i, j, c], window.sum(), rtol=1e-5
                )


def test_symmetric_rectifier():
    img = np.array([[[1.0, -2.0]]], np.float32)
    out = np.asarray(SymmetricRectifier(alpha=0.25).apply(jnp.asarray(img)))
    np.testing.assert_allclose(out[0, 0], [0.75, 0.0, 0.0, 1.75])


def test_windower_counts_and_content():
    rng = np.random.default_rng(4)
    imgs = rng.standard_normal((3, 5, 5, 2)).astype(np.float32)
    out = Windower(2, 3).apply(Dataset.of(imgs))
    # (5-3)/2+1 = 2 positions per axis -> 4 windows per image
    assert out.n == 12
    first = np.asarray(out.array())[0]
    np.testing.assert_allclose(first, imgs[0, 0:3, 0:3, :])


def test_patchers():
    rng = np.random.default_rng(5)
    imgs = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
    cc = CenterCornerPatcher(4, 4, horizontal_flips=True)
    out = cc.apply_batch(Dataset.of(imgs))
    assert out.n == 2 * cc.patches_per_image
    rp = RandomPatcher(3, 4, 4, seed=0)
    out2 = rp.apply_batch(Dataset.of(imgs))
    assert out2.n == 6
    assert np.asarray(out2.array()).shape == (6, 4, 4, 3)


def test_vectorizer_channel_major_layout():
    img = np.arange(2 * 3 * 2, dtype=np.float32).reshape(2, 3, 2)
    vec = np.asarray(channel_major_vectorize(jnp.asarray(img)))
    # vec[c + x*C + y*C*X] == img[x, y, c]
    X, C = 2, 2
    for x in range(2):
        for y in range(3):
            for c in range(2):
                assert vec[c + x * C + y * C * X] == img[x, y, c]


def test_gray_and_pixel_scalers():
    img = np.full((2, 2, 3), 255.0, np.float32)
    gray = np.asarray(GrayScaler().apply(jnp.asarray(img)))
    assert gray.shape == (2, 2, 1)
    np.testing.assert_allclose(gray, 254.99, atol=0.2)
    scaled = np.asarray(PixelScaler().apply(jnp.asarray(img)))
    np.testing.assert_allclose(scaled, 1.0)
