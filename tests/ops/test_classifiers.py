"""NaiveBayes/LogReg/LDA + solver auto-selection tests (reference:
NaiveBayesSuite, LogisticRegressionSuite, LeastSquaresEstimatorSuite)."""

import numpy as np
import pytest

from keystone_tpu.ops.learning import (
    BlockLeastSquaresEstimator,
    DenseLBFGSwithL2,
    LeastSquaresEstimator,
    LinearDiscriminantAnalysis,
    LinearMapEstimator,
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
    SparseLBFGSwithL2,
)
from keystone_tpu.workflow.chain_utils import TransformerLabelEstimatorChain
from keystone_tpu.parallel.dataset import Dataset


def test_naive_bayes_separates_counts():
    # class 0 uses features {0,1}, class 1 uses {2,3}
    X = np.array(
        [[3, 1, 0, 0], [2, 2, 0, 0], [0, 0, 3, 1], [0, 0, 1, 4]],
        np.float32,
    )
    y = np.array([0, 0, 1, 1])
    model = NaiveBayesEstimator(2).fit(Dataset.of(X), Dataset.of(y))
    scores = np.asarray(model.apply_batch(Dataset.of(X)).array())
    assert (scores.argmax(1) == y).all()


def test_naive_bayes_out_of_range_labels_fail_loudly():
    # one_hot would silently zero out-of-range labels; the fit instead
    # poisons the model with NaN (sync-free device-side guard), so the
    # mis-specification cannot pass as a trained model
    X = np.array([[1, 0], [0, 1]], np.float32)
    y = np.array([1, 2])  # 1-based labels with num_classes=2
    model = NaiveBayesEstimator(2).fit(Dataset.of(X), Dataset.of(y))
    scores = np.asarray(model.apply_batch(Dataset.of(X)).array())
    assert np.isnan(scores).all()
    # in-range labels stay NaN-free
    ok = NaiveBayesEstimator(2).fit(Dataset.of(X), Dataset.of(y - 1))
    assert np.isfinite(np.asarray(ok.apply_batch(Dataset.of(X)).array())).all()


def test_logistic_regression_separates(mesh8):
    rng = np.random.default_rng(0)
    n = 200
    X = rng.standard_normal((n, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int32)
    model = LogisticRegressionEstimator(2, num_iters=50).fit(
        Dataset.of(X).shard(), Dataset.of(y)
    )
    pred = np.asarray(model.apply_batch(Dataset.of(X)).array())
    assert (pred == y).mean() > 0.95


def test_lda_projects_separably():
    rng = np.random.default_rng(1)
    X0 = rng.standard_normal((50, 5)) + np.array([3, 0, 0, 0, 0])
    X1 = rng.standard_normal((50, 5)) - np.array([3, 0, 0, 0, 0])
    X = np.concatenate([X0, X1]).astype(np.float32)
    y = np.array([0] * 50 + [1] * 50)
    t = LinearDiscriminantAnalysis(1).fit(Dataset.of(X), Dataset.of(y))
    proj = np.asarray(t.apply_batch(Dataset.of(X)).array()).ravel()
    assert (proj[:50].mean() > 0) != (proj[50:].mean() > 0)
    # the projected class intervals must be (nearly) disjoint
    lo = proj[:50] if proj[:50].mean() < proj[50:].mean() else proj[50:]
    hi = proj[50:] if proj[:50].mean() < proj[50:].mean() else proj[:50]
    assert np.quantile(lo, 0.95) < np.quantile(hi, 0.05)


def test_least_squares_estimator_selection_regimes(mesh8):
    """Cost model picks sensible solvers by regime (reference:
    LeastSquaresEstimatorSuite:11-60)."""
    est = LeastSquaresEstimator(lam=1e-3, num_machines=16)
    rng = np.random.default_rng(2)

    def choose(n, d, k, sparsity):
        nnz = max(int(d * sparsity), 1)
        row = np.zeros(d, np.float32)
        row[rng.choice(d, nnz, replace=False)] = 1.0
        sample = Dataset.of(np.tile(row, (8, 1)))
        lab = Dataset.of(np.zeros((8, k), np.float32))
        return est.optimize([sample, lab], n)

    # dense small-d problems: exact or block solve beats iterating
    dense_small = choose(n=10**6, d=128, k=4, sparsity=1.0)
    # huge-d sparse problems: sparse LBFGS
    sparse_huge = choose(n=10**6, d=100_000, k=2, sparsity=0.0001)
    assert isinstance(sparse_huge, TransformerLabelEstimatorChain)
    assert isinstance(sparse_huge.estimator, SparseLBFGSwithL2)
    # selection returns one of the declared options in all regimes
    assert dense_small is not None


def test_least_squares_estimator_end_to_end(mesh8):
    rng = np.random.default_rng(3)
    A = rng.standard_normal((96, 6)).astype(np.float32)
    W = rng.standard_normal((6, 2)).astype(np.float32)
    b = A @ W
    model = LeastSquaresEstimator(lam=0.0).fit(Dataset.of(A), Dataset.of(b))
    pred = np.asarray(model.apply_batch(Dataset.of(A)).array())
    assert np.abs(pred - b).max() < 0.1
