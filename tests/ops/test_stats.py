"""Stats node tests (reference suites: nodes/stats/*Suite.scala)."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.ops.stats import (
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    StandardScaler,
    TermFrequency,
)
from keystone_tpu.parallel.dataset import Dataset


def test_term_frequency_reference_suite_fixtures():
    """Port of TermFrequencySuite (nodes/misc/TermFrequencySuite.scala):
    simple strings, mixed hashable types (ngram tuples + ints), and the
    log-weighted variant."""
    import math

    out = TermFrequency().apply(["b", "a", "c", "b", "b", "a", "b"])
    assert out == {"a": 2, "b": 4, "c": 1}

    mixed = ["b", "a", "c", ("b", "b"), ("b", "b"), 12, 12, "a", "b", 12]
    out = TermFrequency().apply(mixed)
    assert out == {"a": 2, "b": 2, "c": 1, ("b", "b"): 2, 12: 3}

    out = TermFrequency(lambda x: math.log(x + 1)).apply(
        ["b", "a", "c", "b", "b", "a", "b"]
    )
    assert out == {
        "a": math.log(3), "b": math.log(5), "c": math.log(2),
    }


def test_random_sign_node_involution():
    node = RandomSignNode.create(16, seed=3)
    x = np.random.default_rng(0).standard_normal((5, 16)).astype(np.float32)
    out = np.asarray(node.apply_batch(Dataset.of(x)).array())
    # applying signs twice recovers the input
    again = np.asarray(node.apply_batch(Dataset.of(out)).array())
    np.testing.assert_allclose(again, x, rtol=1e-6)
    assert set(np.unique(np.asarray(node.signs))) <= {-1.0, 1.0}


def test_padded_fft_matches_numpy():
    x = np.random.default_rng(1).standard_normal((3, 10)).astype(np.float32)
    out = np.asarray(PaddedFFT().apply_batch(Dataset.of(x)).array())
    pad = 16
    expect = np.real(np.fft.fft(np.pad(x, ((0, 0), (0, pad - 10)))))[:, :8]
    np.testing.assert_allclose(out, expect, atol=1e-4)
    assert out.shape == (3, 8)


def test_linear_rectifier():
    x = np.array([[-1.0, 0.5, 2.0]], np.float32)
    out = np.asarray(
        LinearRectifier(0.0, 0.25).apply_batch(Dataset.of(x)).array()
    )
    np.testing.assert_allclose(out, [[0.0, 0.25, 1.75]])


def test_normalize_rows():
    x = np.random.default_rng(2).standard_normal((4, 7)).astype(np.float32)
    out = np.asarray(NormalizeRows().apply_batch(Dataset.of(x)).array())
    np.testing.assert_allclose(
        np.linalg.norm(out, axis=1), np.ones(4), rtol=1e-5
    )


def test_signed_hellinger():
    x = np.array([[-4.0, 9.0, 0.0]], np.float32)
    out = np.asarray(
        SignedHellingerMapper().apply_batch(Dataset.of(x)).array()
    )
    np.testing.assert_allclose(out, [[-2.0, 3.0, 0.0]])


def test_standard_scaler_stats(mesh8):
    rng = np.random.default_rng(3)
    x = (rng.standard_normal((100, 5)) * 3 + 7).astype(np.float32)
    ds = Dataset.of(x).shard()
    model = StandardScaler().fit(ds)
    np.testing.assert_allclose(np.asarray(model.mean), x.mean(0), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(model.std), x.std(0, ddof=1), rtol=1e-3
    )
    out = np.asarray(model.apply_batch(ds).array())
    np.testing.assert_allclose(out.mean(0), np.zeros(5), atol=1e-4)
    np.testing.assert_allclose(out.std(0, ddof=1), np.ones(5), rtol=1e-3)


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason='needs 8 data shards'
)
def test_standard_scaler_respects_padding(mesh8):
    # 10 valid rows sharded 8 ways -> padded to 16; stats must use n=10
    x = np.ones((10, 3), np.float32) * 5
    ds = Dataset.of(x).shard()
    assert ds.padded_n == 16
    model = StandardScaler(normalize_std_dev=False).fit(ds)
    np.testing.assert_allclose(np.asarray(model.mean), [5, 5, 5], rtol=1e-6)
    out = model.apply_batch(ds)
    # padding rows stay zero after centering
    assert np.allclose(np.asarray(out.padded())[10:], 0.0)


def test_cosine_random_features_shape_and_range():
    node = CosineRandomFeatures.create(d=6, num_features=32, gamma=0.5, seed=0)
    x = np.random.default_rng(4).standard_normal((9, 6)).astype(np.float32)
    out = np.asarray(node.apply_batch(Dataset.of(x)).array())
    assert out.shape == (9, 32)
    assert np.all(out <= 1.0) and np.all(out >= -1.0)
    single = np.asarray(node.apply(jnp.asarray(x[0])))
    np.testing.assert_allclose(out[0], single, atol=1e-5)


def test_column_sampler_and_sampler():
    mats = [np.random.default_rng(i).standard_normal((4, 20)) for i in range(3)]
    out = ColumnSampler(5, seed=0).apply_batch(Dataset.from_items(mats))
    assert all(np.asarray(m).shape == (4, 5) for m in out.items())
    ds = Sampler(10, seed=0).apply(np.arange(100.0).reshape(50, 2))
    assert ds.n == 10


def test_random_fft_features_matches_composed_branches():
    """Fused RandomFFTFeatures == gather of RandomSignNode -> PaddedFFT ->
    LinearRectifier branches, feature for feature."""
    from keystone_tpu.ops.stats import (
        LinearRectifier, PaddedFFT, RandomFFTFeatures, RandomSignNode,
    )

    rng = np.random.default_rng(0)
    d, f, n = 100, 3, 17
    x = rng.standard_normal((n, d)).astype(np.float32)
    ds = Dataset.from_array(jnp.asarray(x))

    fused = RandomFFTFeatures.create(d, f, seed=5)
    got = np.asarray(fused.apply_batch(ds).padded())

    parts = []
    for i in range(f):
        b = LinearRectifier(0.0).apply_batch(
            PaddedFFT().apply_batch(
                RandomSignNode.create(d, seed=5 + i).apply_batch(ds)
            )
        )
        parts.append(np.asarray(b.padded()))
    want = np.concatenate(parts, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert fused.out_dim == want.shape[1]
    # single-example apply agrees with the batch path
    np.testing.assert_allclose(
        np.asarray(fused.apply(jnp.asarray(x[0]))), want[0],
        rtol=1e-5, atol=1e-5,
    )


def test_random_fft_features_nonzero_threshold_remasks_pad_rows():
    """With rectify_threshold > 0, pad rows must stay exactly zero (the
    Gram-based solvers sum over all padded rows assuming pads are zero),
    and valid rows must match the composed branch path."""
    from keystone_tpu.ops.stats import (
        LinearRectifier, PaddedFFT, RandomFFTFeatures, RandomSignNode,
    )

    rng = np.random.default_rng(1)
    d, f, n, pad_n = 64, 2, 5, 8
    x = np.zeros((pad_n, d), np.float32)
    x[:n] = rng.standard_normal((n, d)).astype(np.float32)
    ds = Dataset.from_array(jnp.asarray(x), n=n)
    thresh = 0.25

    fused = RandomFFTFeatures.create(d, f, seed=3, rectify_threshold=thresh)
    got = np.asarray(fused.apply_batch(ds).padded())
    assert got.shape[0] == pad_n
    np.testing.assert_array_equal(got[n:], 0.0)

    parts = []
    for i in range(f):
        b = LinearRectifier(thresh).apply_batch(
            PaddedFFT().apply_batch(
                RandomSignNode.create(d, seed=3 + i).apply_batch(ds)
            )
        )
        parts.append(np.asarray(b.padded()))
    want = np.concatenate(parts, axis=1)
    np.testing.assert_allclose(got[:n], want[:n], rtol=1e-5, atol=1e-5)
