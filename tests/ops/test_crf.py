"""Linear-chain CRF taggers (ops/nlp/crf.py): exact inference against
brute-force enumeration, padding invariance, a global-decoding task the
greedy perceptron cannot solve, BIO structural guarantees, and node
integration."""

import itertools
import pickle

import numpy as np
import pytest

from keystone_tpu.ops.nlp.crf import (
    CRFNEREstimator,
    CRFTaggerEstimator,
    bio_transition_mask,
    log_partition,
    path_score,
    viterbi,
)
from keystone_tpu.ops.nlp.external import NER, POSTagger
from keystone_tpu.ops.nlp.tagging import PerceptronTaggerEstimator
from keystone_tpu.parallel.dataset import Dataset


# ---------------------------------------------------------------------------
# Exact inference vs brute force
# ---------------------------------------------------------------------------


def _brute(e, trans, start):
    """(logZ, best_path, best_score) by enumerating all T^L paths."""
    L, T = e.shape
    scores = {}
    for path in itertools.product(range(T), repeat=L):
        s = start[path[0]] + sum(e[t, path[t]] for t in range(L))
        s += sum(trans[path[t], path[t + 1]] for t in range(L - 1))
        scores[path] = s
    vals = np.array(list(scores.values()))
    m = vals.max()
    logz = m + np.log(np.exp(vals - m).sum())
    best = max(scores, key=scores.get)
    return logz, list(best), scores[best]


def test_log_partition_and_viterbi_match_brute_force():
    rng = np.random.default_rng(0)
    for trial in range(3):
        L, T = 5, 3
        e = rng.normal(size=(L, T)).astype(np.float32)
        trans = rng.normal(size=(T, T)).astype(np.float32)
        start = rng.normal(size=(T,)).astype(np.float32)
        logz_ref, path_ref, best_ref = _brute(e, trans, start)

        mask = np.ones(L, np.float32)
        logz = float(log_partition(e, trans, start, mask))
        assert abs(logz - logz_ref) < 1e-4, trial

        path = np.asarray(viterbi(e, trans, start, np.int32(L)))
        assert list(path) == path_ref, trial
        s = float(path_score(e, trans, start, path, mask))
        assert abs(s - best_ref) < 1e-4, trial


def test_inference_is_padding_invariant():
    rng = np.random.default_rng(1)
    L, T, pad = 4, 3, 9
    e = rng.normal(size=(L, T)).astype(np.float32)
    trans = rng.normal(size=(T, T)).astype(np.float32)
    start = rng.normal(size=(T,)).astype(np.float32)

    e_pad = np.concatenate([e, rng.normal(size=(pad - L, T))]).astype(
        np.float32
    )
    mask = (np.arange(pad) < L).astype(np.float32)

    logz = float(log_partition(e, trans, start, np.ones(L, np.float32)))
    logz_pad = float(log_partition(e_pad, trans, start, mask))
    assert abs(logz - logz_pad) < 1e-4

    path = np.asarray(viterbi(e, trans, start, np.int32(L)))
    path_pad = np.asarray(viterbi(e_pad, trans, start, np.int32(L)))[:L]
    assert list(path) == list(path_pad)


# ---------------------------------------------------------------------------
# Learning: global decode beats greedy on future-context dependence
# ---------------------------------------------------------------------------


def _garden_path_corpus(n=240, body=5, seed=3):
    """Every body token is the ambiguous 'a'; the final marker token
    ('left'/'right') determines ALL tags. Greedy left-to-right tagging
    with a ±1-token feature window cannot see the marker from tokens
    more than one step away; Viterbi propagates it backward through the
    transition table."""
    rng = np.random.default_rng(seed)
    sents = []
    for _ in range(n):
        if rng.random() < 0.5:
            toks = ["a"] * body + ["left"]
            tags = ["X"] * (body + 1)
        else:
            toks = ["a"] * body + ["right"]
            tags = ["Y"] * (body + 1)
        sents.append((toks, tags))
    return sents


def test_crf_global_decode_beats_greedy_perceptron():
    sents = _garden_path_corpus()
    train, test = sents[:200], sents[200:]

    crf = CRFTaggerEstimator(n_epochs=150, hash_dim=1 << 12).fit(
        Dataset.from_items(train)
    )
    perc = PerceptronTaggerEstimator(n_iter=8).fit(Dataset.from_items(train))

    def acc(tagger):
        c = t = 0
        for toks, gold in test:
            pred = tagger(toks)
            c += sum(p == g for p, g in zip(pred, gold))
            t += len(gold)
        return c / t

    crf_acc, perc_acc = acc(crf), acc(perc)
    # the marker + its neighbour are taggable greedily; the other 4 body
    # tokens are a coin flip for the perceptron but exact for the CRF
    assert crf_acc > 0.99, crf_acc
    assert crf_acc > perc_acc + 0.2, (crf_acc, perc_acc)


def _toy_pos_corpus():
    """Same grammar as test_tagging._toy_corpus: DT (JJ) NN VB (RB)."""
    dts, jjs = ["the", "a"], ["big", "small", "red", "old"]
    nns = ["dog", "cat", "house", "tree", "car", "bird"]
    vbs, rbs = ["runs", "sits", "falls", "jumps"], ["quickly", "slowly"]
    rng = np.random.default_rng(0)
    sents = []
    for _ in range(200):
        toks, tags = [rng.choice(dts)], ["DT"]
        if rng.random() < 0.5:
            toks.append(rng.choice(jjs))
            tags.append("JJ")
        toks.append(rng.choice(nns))
        tags.append("NN")
        toks.append(rng.choice(vbs))
        tags.append("VB")
        if rng.random() < 0.5:
            toks.append(rng.choice(rbs))
            tags.append("RB")
        sents.append((toks, tags))
    return sents


def test_crf_pos_tagger_learns_toy_grammar_and_plugs_into_node():
    sents = _toy_pos_corpus()
    train, test = sents[:160], sents[160:]
    tagger = CRFTaggerEstimator(n_epochs=150, hash_dim=1 << 14).fit(
        Dataset.from_items(train)
    )
    correct = total = 0
    for toks, gold in test:
        pred = [t for _, t in tagger.apply(toks)]
        correct += sum(p == g for p, g in zip(pred, gold))
        total += len(gold)
    assert correct / total > 0.97

    node = POSTagger(annotator=tagger)
    toks = ["the", "red", "dog", "runs"]
    assert [t for _, t in node.apply(toks)] == ["DT", "JJ", "NN", "VB"]


# ---------------------------------------------------------------------------
# BIO constraints
# ---------------------------------------------------------------------------


def test_bio_transition_mask_shapes_and_rules():
    names = ["B-ORG", "B-PER", "I-ORG", "I-PER", "O"]
    tmask, smask = bio_transition_mask(names)
    ix = {n: i for i, n in enumerate(names)}
    # forbidden: O -> I-*, B-PER -> I-ORG, start at I-*
    assert tmask[ix["O"], ix["I-ORG"]] < -1e8
    assert tmask[ix["B-PER"], ix["I-ORG"]] < -1e8
    assert smask[ix["I-PER"]] < -1e8
    # allowed: B-ORG -> I-ORG, I-PER -> I-PER, anything -> O / B-*
    assert tmask[ix["B-ORG"], ix["I-ORG"]] == 0
    assert tmask[ix["I-PER"], ix["I-PER"]] == 0
    assert tmask[ix["O"], ix["B-PER"]] == 0
    assert (tmask[:, ix["O"]] == 0).all()


def _bio_valid(tags):
    prev = "O"
    for t in tags:
        if t.startswith("I-") and prev not in {"B-" + t[2:], "I-" + t[2:]}:
            return False
        prev = t
    return True


def test_crf_ner_constrained_decode_is_always_bio_valid():
    # tiny, deliberately under-trained model + pathological OOV inputs:
    # validity must come from the lattice, not from good weights
    train = [
        (["bob", "smith", "called"], ["B-PER", "I-PER", "O"]),
        (["acme", "corp", "grew"], ["B-ORG", "I-ORG", "O"]),
        (["she", "left"], ["O", "O"]),
    ]
    tagger = CRFNEREstimator(n_epochs=20, hash_dim=1 << 10).fit(
        Dataset.from_items(train)
    )
    rng = np.random.default_rng(5)
    vocab = ["bob", "corp", "zzq", "急", "x1", "—", "smith", "acme"]
    for _ in range(20):
        toks = list(rng.choice(vocab, size=rng.integers(1, 9)))
        out = tagger(toks)
        assert _bio_valid(out), (toks, out)


def test_crf_ner_beats_rule_baseline():
    from tests.ops.test_tagging import _ner_corpus, _rule_bio

    sents = _ner_corpus()
    train, test = sents[:256], sents[256:]
    tagger = CRFNEREstimator(n_epochs=150, hash_dim=1 << 14).fit(
        Dataset.from_items(train)
    )
    t_correct = r_correct = total = 0
    for toks, gold in test:
        pred = tagger(toks)
        assert _bio_valid(pred), (toks, pred)
        rule = _rule_bio(toks)
        t_correct += sum(p == g for p, g in zip(pred, gold))
        r_correct += sum(p == g for p, g in zip(rule, gold))
        total += len(gold)
    trained_acc, rule_acc = t_correct / total, r_correct / total
    assert trained_acc > rule_acc + 0.15, (trained_acc, rule_acc)
    assert trained_acc > 0.9, trained_acc

    node = NER(annotator=tagger)
    out = node.apply(["yesterday", "karen", "smith", "visited", "us"])
    assert out[1:3] == ["B-PER", "I-PER"], out


# ---------------------------------------------------------------------------
# Round-trip + edge cases
# ---------------------------------------------------------------------------


def test_crf_tagger_pickles_and_handles_empty_input():
    train = [(["the", "dog"], ["DT", "NN"]), (["a", "cat"], ["DT", "NN"])]
    tagger = CRFTaggerEstimator(n_epochs=30, hash_dim=1 << 10).fit(
        Dataset.from_items(train)
    )
    clone = pickle.loads(pickle.dumps(tagger))
    toks = ["the", "cat"]
    assert clone(toks) == tagger(toks) == ["DT", "NN"]
    assert tagger([]) == []
    assert clone.apply([]) == []


def test_crf_fit_rejects_all_empty_input():
    with pytest.raises(ValueError):
        CRFTaggerEstimator(n_epochs=1).fit(Dataset.from_items([([], [])]))


def test_crf_ner_rejects_bio_invalid_gold():
    # IOB1-style gold (entity opens with I-X after O) would score -1e9
    # through the constrained lattice; must fail loudly, not silently
    # destroy the loss
    bad = [(["acme", "grew"], ["I-ORG", "O"])]
    with pytest.raises(ValueError, match="BIO"):
        CRFNEREstimator(n_epochs=1).fit(Dataset.from_items(bad))
    # same data trains fine unconstrained
    tagger = CRFNEREstimator(
        n_epochs=5, hash_dim=1 << 10, constrain_bio=False
    ).fit(Dataset.from_items(bad))
    assert len(tagger(["acme", "grew"])) == 2
