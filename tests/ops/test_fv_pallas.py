"""Fused (Pallas) vs unfused Fisher-vector path equivalence, and the
k-threshold physical choice (reference: FisherVector.scala:84-94,
EncEvalSuite fixture constant)."""

import numpy as np
import pytest
import jax.numpy as jnp

from keystone_tpu.ops.images.fisher_vector import (
    EncEvalGMMFisherVectorEstimator,
    FisherVector,
    FisherVectorFused,
    GMMFisherVectorEstimator,
    ScalaGMMFisherVectorEstimator,
)
from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
from keystone_tpu.parallel.dataset import Dataset


def _random_model(d=16, k=32, seed=0):
    rng = np.random.default_rng(seed)
    return GaussianMixtureModel(
        jnp.asarray(rng.standard_normal((d, k)).astype(np.float32)),
        jnp.asarray((rng.random((d, k)) + 0.5).astype(np.float32)),
        jnp.asarray(rng.dirichlet(np.ones(k)).astype(np.float32)),
    )


def test_fused_matches_unfused_single():
    gmm = _random_model()
    rng = np.random.default_rng(1)
    x = rng.standard_normal((16, 300)).astype(np.float32)
    fv_plain = np.asarray(FisherVector(gmm).apply(x))
    fv_fused = np.asarray(FisherVectorFused(gmm).apply(x))
    assert fv_plain.shape == fv_fused.shape == (16, 64)
    np.testing.assert_allclose(fv_fused, fv_plain, rtol=1e-3, atol=1e-4)


def test_fused_matches_unfused_batch():
    gmm = _random_model(d=8, k=32, seed=2)
    rng = np.random.default_rng(3)
    batch = rng.standard_normal((4, 8, 200)).astype(np.float32)
    ds = Dataset.from_array(jnp.asarray(batch))
    out_plain = np.asarray(FisherVector(gmm).apply_batch(ds).padded())
    out_fused = np.asarray(FisherVectorFused(gmm).apply_batch(ds).padded())
    np.testing.assert_allclose(out_fused, out_plain, rtol=1e-3, atol=1e-4)


def test_auto_interpret_parity_vs_numpy_reference():
    """``fisher_vector_stats_pallas`` with NO interpret argument
    anywhere in the call chain: the backend auto-selection
    (``pallas_kernels.auto_interpret``) picks the Pallas interpreter
    off-TPU, and the auto-selected path matches the INDEPENDENT numpy
    FV reference (test_sift_fv._np_fisher_vector) — parity against the
    spec translation, not merely against the jax program it fuses."""
    import jax

    from keystone_tpu.ops.images.pallas_kernels import auto_interpret
    from test_sift_fv import _np_fisher_vector

    assert auto_interpret(None) == (jax.default_backend() != "tpu")

    gmm = _random_model(d=8, k=32, seed=5)
    rng = np.random.default_rng(6)
    x = rng.standard_normal((8, 150)).astype(np.float32)
    got = np.asarray(FisherVectorFused(gmm).apply(x))
    want = _np_fisher_vector(
        np.asarray(gmm.means, np.float64),
        np.asarray(gmm.variances, np.float64),
        np.asarray(gmm.weights, np.float64),
        x.astype(np.float64),
        thresh=gmm.weight_threshold,
    )
    assert got.shape == want.shape == (8, 64)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_optimizable_choice_by_k():
    small = GMMFisherVectorEstimator(k=8)
    large = GMMFisherVectorEstimator(k=32)
    assert isinstance(small._choice(), ScalaGMMFisherVectorEstimator)
    assert isinstance(large._choice(), EncEvalGMMFisherVectorEstimator)
    assert isinstance(
        small.optimize(None, 0), ScalaGMMFisherVectorEstimator
    )
    assert isinstance(
        large.optimize(None, 0), EncEvalGMMFisherVectorEstimator
    )
