"""KMeans++/GMM tests (reference: KMeansPlusPlusSuite,
GaussianMixtureModelSuite)."""

import numpy as np
import pytest

from keystone_tpu.ops.learning import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    KMeansPlusPlusEstimator,
)
from keystone_tpu.parallel.dataset import Dataset


def _blobs(n_per, centers, spread=0.1, seed=0):
    rng = np.random.default_rng(seed)
    xs = [
        c + spread * rng.standard_normal((n_per, len(c)))
        for c in centers
    ]
    return np.concatenate(xs).astype(np.float32)


def test_kmeans_recovers_blobs():
    centers = [np.array([0.0, 0.0]), np.array([5.0, 5.0]), np.array([-5.0, 5.0])]
    X = _blobs(60, centers, seed=0)
    model = KMeansPlusPlusEstimator(3, 20, seed=0).fit(Dataset.of(X))
    means = np.asarray(model.means)
    # each true center has a learned center nearby
    for c in centers:
        assert np.min(np.linalg.norm(means - c, axis=1)) < 0.5


def test_kmeans_assignment_one_hot():
    X = _blobs(10, [np.array([0.0, 0.0]), np.array([9.0, 9.0])], seed=1)
    model = KMeansPlusPlusEstimator(2, 5, seed=0).fit(Dataset.of(X))
    assign = np.asarray(model.apply_batch(Dataset.of(X)).array())
    assert assign.shape == (20, 2)
    np.testing.assert_allclose(assign.sum(1), np.ones(20))
    assert set(np.unique(assign)) <= {0.0, 1.0}


def test_gmm_em_recovers_blobs():
    centers = [np.array([0.0, 0.0]), np.array([6.0, 6.0])]
    X = _blobs(200, centers, spread=0.5, seed=2)
    gmm = GaussianMixtureModelEstimator(
        2, max_iterations=50, min_cluster_size=10, seed=0
    ).fit(Dataset.of(X))
    mu = np.asarray(gmm.means).T  # (k, d)
    for c in centers:
        assert np.min(np.linalg.norm(mu - c, axis=1)) < 0.5
    # posteriors are a (thresholded) distribution
    q = np.asarray(gmm.apply_batch(Dataset.of(X)).array())
    np.testing.assert_allclose(q.sum(1), np.ones(len(X)), atol=1e-5)


def test_gmm_csv_load(tmp_path):
    means = np.array([[0.0, 1.0], [2.0, 3.0]])  # (d=2, k=2)
    variances = np.ones((2, 2))
    weights = np.array([0.4, 0.6])
    mf, vf, wf = (
        tmp_path / "m.csv", tmp_path / "v.csv", tmp_path / "w.csv"
    )
    np.savetxt(mf, means, delimiter=",")
    np.savetxt(vf, variances, delimiter=",")
    np.savetxt(wf, weights, delimiter=",")
    gmm = GaussianMixtureModel.load(str(mf), str(vf), str(wf))
    assert gmm.k == 2 and gmm.dim == 2
    out = gmm.apply(np.array([0.0, 2.0], np.float32))
    assert out.shape == (2,)


def test_fused_gmm_matches_host_stepped_em():
    """The fused lax.while_loop EM (enceval-native analogue) and the
    host-stepped EM produce the same model from the same init/seed."""
    from keystone_tpu.ops.learning import (
        FusedGMMEstimator,
        GaussianMixtureModelEstimator,
        OptimizableGMMEstimator,
    )

    rng = np.random.default_rng(0)
    centers = np.asarray([[0.0, 0.0], [5.0, 5.0], [-5.0, 5.0]], np.float32)
    X = np.concatenate([
        rng.standard_normal((120, 2)).astype(np.float32) * 0.4 + c
        for c in centers
    ])
    kwargs = dict(k=3, max_iterations=30, min_cluster_size=5, seed=1)
    host = GaussianMixtureModelEstimator(**kwargs).fit(X)
    fused = FusedGMMEstimator(**kwargs).fit(X)
    np.testing.assert_allclose(
        np.asarray(fused.means), np.asarray(host.means), rtol=1e-3,
        atol=1e-3,
    )
    np.testing.assert_allclose(
        np.asarray(fused.weights), np.asarray(host.weights), atol=1e-3
    )
    # both recover the true centers (column layout (d, k))
    mu = np.sort(np.asarray(fused.means).T, axis=0)
    np.testing.assert_allclose(mu, np.sort(centers, axis=0), atol=0.3)


def test_optimizable_gmm_picks_fused_at_k32():
    from keystone_tpu.ops.learning import (
        FusedGMMEstimator,
        GaussianMixtureModelEstimator,
        OptimizableGMMEstimator,
    )

    small = OptimizableGMMEstimator(k=8)
    big = OptimizableGMMEstimator(k=32)
    assert type(small.default) is GaussianMixtureModelEstimator
    assert type(big.default) is FusedGMMEstimator
    assert type(big.optimize([], -1)) is FusedGMMEstimator
