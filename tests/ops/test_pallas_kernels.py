"""Featurize hot-loop Pallas kernels (ops/images/pallas_kernels):
kernel-vs-XLA-reference parity (the einsum formulations the kernels
replaced), backend auto-selection, and batched (bucket-vmapped) vs
per-image SIFT/LCS parity on raw uint8 input — the exact shape the
serving engine's fused bucket programs vmap over."""

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.images.pallas_kernels import (
    NUM_ORIENTATIONS,
    auto_interpret,
    plane_sandwich,
    sift_bin_sample,
)


def test_auto_interpret_follows_backend():
    """interpret=None resolves from the live backend (Mosaic on TPU,
    the Pallas interpreter elsewhere); explicit values pass through."""
    assert auto_interpret() == (jax.default_backend() != "tpu")
    assert auto_interpret(None) == (jax.default_backend() != "tpu")
    assert auto_interpret(True) is True
    assert auto_interpret(False) is False


def test_sift_bin_sample_matches_xla_reference():
    """The fused trilinear-orientation-binning + double-GEMM kernel
    equals the one_hot-planes + einsum formulation it replaced."""
    rng = np.random.default_rng(0)
    H, W, M, N = 24, 20, 12, 8
    mag = rng.random((H, W)).astype(np.float32)
    t = (rng.random((H, W)) * NUM_ORIENTATIONS).astype(np.float32)
    ayt = rng.standard_normal((M, H)).astype(np.float32)
    ax = rng.standard_normal((W, N)).astype(np.float32)

    got = np.asarray(
        sift_bin_sample(
            jnp.asarray(mag), jnp.asarray(t), jnp.asarray(ayt),
            jnp.asarray(ax),
        )
    )
    assert got.shape == (NUM_ORIENTATIONS, M, N)

    b0 = np.floor(t).astype(np.int64) % NUM_ORIENTATIONS
    b1 = (b0 + 1) % NUM_ORIENTATIONS
    frac = t - np.floor(t)
    planes = np.zeros((NUM_ORIENTATIONS, H, W), np.float32)
    for o in range(NUM_ORIENTATIONS):
        planes[o] = mag * (
            (1.0 - frac) * (b0 == o) + frac * (b1 == o)
        )
    want = np.einsum("mh,ohw,wn->omn", ayt, planes, ax)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_plane_sandwich_matches_einsum():
    """out[p] = at @ planes[p] @ b, per plane, in one kernel."""
    rng = np.random.default_rng(1)
    P, H, W, M, N = 6, 18, 22, 9, 7
    planes = rng.standard_normal((P, H, W)).astype(np.float32)
    at = rng.standard_normal((M, H)).astype(np.float32)
    b = rng.standard_normal((W, N)).astype(np.float32)
    got = np.asarray(
        plane_sandwich(
            jnp.asarray(planes), jnp.asarray(at), jnp.asarray(b)
        )
    )
    assert got.shape == (P, M, N)
    want = np.einsum("mh,phw,wn->pmn", at, planes, b)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_kernels_vmap_matches_loop():
    """vmap folds a batch over the kernels exactly (the engine's
    bucket programs rely on this batching rule)."""
    rng = np.random.default_rng(2)
    B, H, W, M, N = 3, 16, 14, 6, 5
    mags = rng.random((B, H, W)).astype(np.float32)
    ts = (rng.random((B, H, W)) * NUM_ORIENTATIONS).astype(np.float32)
    ayt = jnp.asarray(rng.standard_normal((M, H)).astype(np.float32))
    ax = jnp.asarray(rng.standard_normal((W, N)).astype(np.float32))
    single = np.stack([
        np.asarray(sift_bin_sample(
            jnp.asarray(m), jnp.asarray(t), ayt, ax
        ))
        for m, t in zip(mags, ts)
    ])
    batched = np.asarray(
        jax.vmap(lambda m, t: sift_bin_sample(m, t, ayt, ax))(
            jnp.asarray(mags), jnp.asarray(ts)
        )
    )
    np.testing.assert_array_equal(batched, single)


def test_sift_batched_vmap_matches_per_image_on_uint8():
    """The bucket_vmap contract through the Pallas hot loop: a vmapped
    raw-uint8 batch yields exactly the per-image descriptor matrices
    (quantized output — any fp divergence would show as whole-step
    jumps, so equality is the honest assertion)."""
    from keystone_tpu.ops.images.sift import SIFTExtractor

    ex = SIFTExtractor(step=4, bin=4, num_scales=2)
    rng = np.random.default_rng(3)
    batch = rng.integers(0, 256, (3, 40, 40, 3), dtype=np.uint8)
    per = np.stack([np.asarray(ex.apply(img)) for img in batch])
    batched = np.asarray(jax.vmap(ex.apply)(jnp.asarray(batch)))
    np.testing.assert_array_equal(batched, per)


def test_lcs_batched_vmap_matches_per_image_on_uint8():
    from keystone_tpu.ops.images.lcs import LCSExtractor

    ex = LCSExtractor(4, 16, 6)
    rng = np.random.default_rng(4)
    batch = rng.integers(0, 256, (3, 40, 40, 3), dtype=np.uint8)
    per = np.stack([np.asarray(ex.apply(img)) for img in batch])
    batched = np.asarray(jax.vmap(ex.apply)(jnp.asarray(batch)))
    np.testing.assert_allclose(batched, per, rtol=1e-5, atol=1e-5)
