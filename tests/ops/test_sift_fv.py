"""SIFT / LCS / FisherVector tests.

The reference validates SIFT against a MATLAB vl_phow export
(feats128.csv) and FV against a fixture-sum constant (EncEvalSuite) — the
CSV fixtures are absent from the reference repo, so these tests validate
against independent numpy translations of the same math plus structural
invariants, and FV against the actual voc_codebook GMM fixtures.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from keystone_tpu.ops.images.fisher_vector import (
    FisherVector,
    ScalaGMMFisherVectorEstimator,
)
from keystone_tpu.ops.images.lcs import LCSExtractor
from keystone_tpu.ops.images.sift import SIFTExtractor
from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
from keystone_tpu.parallel.dataset import Dataset

VOC_CODEBOOK = "/root/reference/src/test/resources/images/voc_codebook"


def _test_image(h=64, w=64, seed=0):
    rng = np.random.default_rng(seed)
    x, y = np.meshgrid(np.arange(w), np.arange(h))
    img = (
        0.5
        + 0.3 * np.sin(x / 5.0)
        + 0.2 * np.cos(y / 7.0)
        + 0.05 * rng.standard_normal((h, w))
    )
    return img.astype(np.float32)


def test_sift_shapes_and_ranges():
    img = _test_image()
    ext = SIFTExtractor(step=4, bin=4, num_scales=2)
    out = np.asarray(ext.apply(img))
    assert out.shape[0] == 128
    assert out.shape[1] > 0
    assert out.min() >= 0 and out.max() <= 255
    # descriptors quantize the [0, 0.5]-ish normalized range
    assert out.max() > 0  # textured image produces energy


def test_sift_descriptor_count_matches_formula():
    img = _test_image(60, 80)
    num_scales = 2
    ext = SIFTExtractor(step=3, bin=4, num_scales=num_scales)
    out = np.asarray(ext.apply(img))
    expected = 0
    H, W = 60, 80
    for s in range(num_scales):
        b = 4 + 2 * s
        bound = (1 + 2 * num_scales) - 3 * s
        extent = 3 * b
        step_s = 3 + s  # default scale_step=1 (SIFTExtractor.scala:16)
        nfy = (H - 1 - bound - extent) // step_s + 1
        nfx = (W - 1 - bound - extent) // step_s + 1
        expected += nfy * nfx
    assert out.shape[1] == expected


def test_sift_flat_image_zeroed_by_contrast_threshold():
    img = np.full((48, 48), 0.5, np.float32)
    out = np.asarray(SIFTExtractor(step=4, bin=4, num_scales=2).apply(img))
    np.testing.assert_allclose(out, 0.0)


def test_sift_rotation_invariance_of_energy():
    """Rotating the image 90 deg permutes descriptors but preserves the
    total descriptor energy approximately (square image, symmetric
    grid)."""
    img = _test_image(64, 64)
    ext = SIFTExtractor(step=4, bin=4, num_scales=1)
    a = np.asarray(ext.apply(img))
    b = np.asarray(ext.apply(np.rot90(img).copy()))
    assert a.shape == b.shape
    assert abs(a.sum() - b.sum()) / max(a.sum(), 1) < 0.05


def test_lcs_matches_naive():
    rng = np.random.default_rng(1)
    img = rng.uniform(0, 1, (40, 40, 3)).astype(np.float32)
    s = 6
    ext = LCSExtractor(stride=4, stride_start=16, sub_patch_size=s)
    got = np.asarray(ext.apply(img))

    # naive translation of LCSExtractor.scala
    def box(c):
        pad_low = (s - 1) // 2
        pad_high = s - 1 - pad_low
        p = np.pad(img[:, :, c], ((pad_low, pad_high), (pad_low, pad_high)))
        out = np.zeros((40, 40))
        for i in range(40):
            for j in range(40):
                out[i, j] = p[i : i + s, j : j + s].mean()
        return out

    means = [box(c) for c in range(3)]
    sqs = []
    for c in range(3):
        img2 = img[:, :, c] ** 2
        pad_low = (s - 1) // 2
        pad_high = s - 1 - pad_low
        p = np.pad(img2, ((pad_low, pad_high), (pad_low, pad_high)))
        out = np.zeros((40, 40))
        for i in range(40):
            for j in range(40):
                out[i, j] = p[i : i + s, j : j + s].mean()
        sqs.append(out)
    stds = [np.sqrt(np.maximum(sqs[c] - means[c] ** 2, 0)) for c in range(3)]

    xs = list(range(16, 40 - 16, 4))
    offs = list(range(-2 * s + s // 2 - 1, s + s // 2 - 1 + 1, s))
    n_keys = len(xs) * len(xs)
    expect = np.zeros((len(offs) * len(offs) * 3 * 2, n_keys), np.float32)
    for xi, x in enumerate(xs):
        for yi, y in enumerate(xs):
            col = xi * len(xs) + yi
            idx = 0
            for c in range(3):
                for nx in offs:
                    for ny in offs:
                        expect[idx, col] = means[c][x + nx, y + ny]
                        idx += 1
                        expect[idx, col] = stds[c][x + nx, y + ny]
                        idx += 1
    np.testing.assert_allclose(got, expect, atol=1e-4)


def _np_fisher_vector(gmm_means, gmm_vars, gmm_weights, x, thresh=1e-4):
    """numpy translation of FisherVector.scala:33-52 + GMM posteriors."""
    d, m = x.shape
    mu, var, w = gmm_means.T, gmm_vars.T, gmm_weights  # (k, d)
    xs = x.T  # (m, d)
    sq = (
        (xs**2) @ (0.5 / var).T
        - xs @ (mu / var).T
        + 0.5 * (mu * mu / var).sum(1)[None, :]
    )
    llh = (
        -0.5 * d * np.log(2 * np.pi)
        - 0.5 * np.log(var).sum(1)[None, :]
        + np.log(w)[None, :]
        - sq
    )
    llh = llh - llh.max(1, keepdims=True)
    q = np.exp(llh)
    q /= q.sum(1, keepdims=True)
    q = np.where(q > thresh, q, 0.0)
    q /= q.sum(1, keepdims=True)
    s0 = q.mean(0)
    s1 = (x @ q) / m
    s2 = ((x * x) @ q) / m
    fv1 = (s1 - gmm_means * s0[None, :]) / (
        np.sqrt(gmm_vars) * np.sqrt(gmm_weights)[None, :]
    )
    fv2 = (
        s2 - 2 * gmm_means * s1 + (gmm_means**2 - gmm_vars) * s0[None, :]
    ) / (gmm_vars * np.sqrt(2 * gmm_weights)[None, :])
    return np.concatenate([fv1, fv2], axis=1)


def test_fisher_vector_matches_numpy_on_voc_codebook():
    gmm = GaussianMixtureModel.load(
        f"{VOC_CODEBOOK}/means.csv",
        f"{VOC_CODEBOOK}/variances.csv",
        f"{VOC_CODEBOOK}/priors",
    )
    rng = np.random.default_rng(0)
    d = gmm.dim
    x = rng.standard_normal((d, 50)).astype(np.float32) * 100
    fv = FisherVector(gmm)
    got = np.asarray(fv.apply(x))
    expect = _np_fisher_vector(
        np.asarray(gmm.means, np.float64),
        np.asarray(gmm.variances, np.float64),
        np.asarray(gmm.weights, np.float64),
        x.astype(np.float64),
    )
    assert got.shape == (d, 2 * gmm.k)
    np.testing.assert_allclose(got, expect, rtol=2e-2, atol=2e-2)


def test_fisher_vector_estimator_end_to_end():
    rng = np.random.default_rng(2)
    mats = [
        rng.standard_normal((8, 30)).astype(np.float32) for _ in range(4)
    ]
    est = ScalaGMMFisherVectorEstimator(k=2, seed=0)
    fv = est.fit(Dataset.from_items(mats))
    out = fv.apply(mats[0])
    assert np.asarray(out).shape == (8, 4)


def test_fused_fisher_vector_matches_numpy_on_voc_codebook():
    """Same reference-codebook check for the fused Pallas path
    (the enceval-native parallel, external/FisherVector.scala:17)."""
    from keystone_tpu.ops.images.fisher_vector import FisherVectorFused

    gmm = GaussianMixtureModel.load(
        f"{VOC_CODEBOOK}/means.csv",
        f"{VOC_CODEBOOK}/variances.csv",
        f"{VOC_CODEBOOK}/priors",
    )
    rng = np.random.default_rng(0)
    d = gmm.dim
    x = rng.standard_normal((d, 50)).astype(np.float32) * 100
    got = np.asarray(FisherVectorFused(gmm).apply(x))
    expect = _np_fisher_vector(
        np.asarray(gmm.means, np.float64),
        np.asarray(gmm.variances, np.float64),
        np.asarray(gmm.weights, np.float64),
        x.astype(np.float64),
    )
    assert got.shape == (d, 2 * gmm.k)
    np.testing.assert_allclose(got, expect, rtol=2e-2, atol=2e-2)
