"""Image representation conversions (ops/images/conversions.py) —
round-trip exactness."""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.images.conversions import (
    bytes_to_image,
    chw_to_hwc,
    gray_to_rgb,
    hwc_to_chw,
    image_to_rgb_ints,
    rgb_ints_to_image,
    unvectorize,
    vectorize,
)


def test_bytes_bgr_to_rgb():
    # one 1x2 BGR image: pixel0 = (b=1,g=2,r=3), pixel1 = (4,5,6)
    img = bytes_to_image(bytes([1, 2, 3, 4, 5, 6]), 1, 2, 3, order="bgr")
    np.testing.assert_array_equal(
        np.asarray(img), [[[3, 2, 1], [6, 5, 4]]]
    )


def test_bytes_abgr_drops_alpha():
    img = bytes_to_image(
        bytes([9, 1, 2, 3, 8, 4, 5, 6]), 1, 2, 4, order="abgr"
    )
    np.testing.assert_array_equal(
        np.asarray(img), [[[3, 2, 1], [6, 5, 4]]]
    )


def test_bytes_order_validation():
    with pytest.raises(ValueError):
        bytes_to_image(bytes(4), 1, 1, 4, order="bgr")
    with pytest.raises(ValueError):
        bytes_to_image(bytes(1), 1, 1, 1, order="nope")


def test_gray_to_rgb_replicates():
    g = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    rgb = gray_to_rgb(g)
    assert rgb.shape == (2, 2, 3)
    for c in range(3):
        np.testing.assert_array_equal(np.asarray(rgb[:, :, c]), np.asarray(g))


def test_packed_rgb_round_trip_exact():
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.integers(0, 256, (5, 7, 3)).astype(np.float32))
    packed = image_to_rgb_ints(img)
    back = rgb_ints_to_image(packed)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(img))


def test_packed_rgb_scaling():
    img = jnp.asarray([[[-1.0, 0.0, 3.0]]])  # out of byte range
    packed = image_to_rgb_ints(img, scale=True)
    back = np.asarray(rgb_ints_to_image(packed))[0, 0]
    assert back[0] == 0 and back[2] == 255  # min -> 0, max -> 255


def test_layout_round_trips():
    rng = np.random.default_rng(1)
    img = jnp.asarray(rng.standard_normal((4, 6, 3)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(chw_to_hwc(hwc_to_chw(img))), np.asarray(img)
    )
    v = vectorize(img)
    assert v.shape == (4 * 6 * 3,)
    # channel-major: first H*W entries are channel 0
    np.testing.assert_array_equal(
        np.asarray(v[: 4 * 6]), np.asarray(img[:, :, 0]).ravel()
    )
    np.testing.assert_array_equal(
        np.asarray(unvectorize(v, (4, 6, 3))), np.asarray(img)
    )
