"""FusedTextHashTF: native C++ text featurization is hash-identical to
the composed Python chain (Trim -> LowerCase -> Tokenizer ->
NGramsHashingTF)."""

import numpy as np
import pytest
from jax.experimental import sparse as jsparse

from keystone_tpu import native
from keystone_tpu.ops.nlp import FusedTextHashTF, NGramsHashingTF
from keystone_tpu.ops.nlp.string_utils import LowerCase, Tokenizer, Trim
from keystone_tpu.parallel.dataset import Dataset

DOCS = [
    "  The quick Brown-Fox; jumps!! over_the lazy dog 42  ",
    "hello",
    "",
    "a b a b a  --  punct,punct;punct",
    "Numbers 123 and under_scores mix_9 OK",
    # Scala-split edge cases: a doc that starts with a separator AFTER
    # trim emits a leading "" token (a word token follows), while a
    # punctuation-only doc strips ALL trailing empties and tokenizes to
    # [] (Java: "?!?".split("[^\\w]+") is an EMPTY array) — the native
    # path must hash identically, including emitting zero n-grams for
    # the separator-only doc
    "!great product",
    "  !! leading punct after trim",
    "?!?",
]


def test_tokenizer_scala_split_semantics():
    """The Java/Scala String.split contract the fused path mirrors:
    no-match returns the whole string (so "" -> [""]), trailing empty
    tokens are ALL stripped (separator-only input -> []), leading empty
    tokens are kept."""
    t = Tokenizer()
    assert t.apply("") == [""]
    assert t.apply("?!?") == []
    assert t.apply("a,b,,") == ["a", "b"]
    assert t.apply("!great product") == ["", "great", "product"]


def _python_reference(doc, orders, nf):
    toks = Tokenizer().apply(LowerCase().apply(Trim().apply(doc)))
    return NGramsHashingTF(orders, nf).apply(toks)


@pytest.mark.parametrize("orders", [[1], [1, 2], [2, 3]])
def test_fused_matches_python_chain(orders):
    nf = 4096
    node = FusedTextHashTF(orders, nf)
    for doc in DOCS:
        got = node.apply(doc)
        want = _python_reference(doc, orders, nf)
        np.testing.assert_array_equal(
            np.asarray(got.indices), np.asarray(want.indices)
        )
        np.testing.assert_array_equal(
            np.asarray(got.data), np.asarray(want.data)
        )


def test_fused_batch_and_binarize():
    nf = 512
    ds = Dataset.from_items(DOCS)
    mat = FusedTextHashTF([1, 2], nf).apply_batch(ds).padded()
    assert mat.shape == (len(DOCS), nf)
    dense = np.asarray(mat.todense())
    # row parity vs per-doc python reference
    for r, doc in enumerate(DOCS):
        want = np.zeros(nf, np.float32)
        ref = _python_reference(doc, [1, 2], nf)
        want[np.asarray(ref.indices).reshape(-1)] = np.asarray(ref.data)
        np.testing.assert_array_equal(dense[r], want)
    binar = FusedTextHashTF([1, 2], nf, binarize=True).apply_batch(ds)
    db = np.asarray(binar.padded().todense())
    np.testing.assert_array_equal(db, (dense > 0).astype(np.float32))


def test_non_ascii_falls_back_to_python():
    node = FusedTextHashTF([1], 256)
    doc = "café résumé test"
    got = node.apply(doc)  # must not crash; python path handles unicode
    want = _python_reference(doc, [1], 256)
    np.testing.assert_array_equal(
        np.asarray(got.indices), np.asarray(want.indices)
    )
    np.testing.assert_array_equal(
        np.asarray(got.data), np.asarray(want.data)
    )


def test_native_path_is_active():
    if native.text_ngram_hash_tf(["probe doc"], 1, 1, 64) is None:
        pytest.skip("native library unavailable")
    out = native.text_ngram_hash_tf(["a b c", "c d"], 1, 2, 1024)
    row_ptr, cols, vals = out
    assert row_ptr[-1] == len(cols) == len(vals)
    assert row_ptr.tolist() == [0, 5, 8]  # 3+2 unigrams, 2+1 bigrams


def test_zero_num_features_raises_not_sigfpe():
    with pytest.raises(ValueError):
        FusedTextHashTF([1], 0)
    with pytest.raises(ValueError):
        native.text_ngram_hash_tf(["a"], 1, 1, 0)
