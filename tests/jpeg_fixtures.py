"""Shared synthetic-JPEG fixture generator for the loader/native suites
(one formula — smooth low-frequency content that JPEG round-trips
closely — so decode-parity bars stay comparable across suites)."""

import os
import tarfile

import numpy as np


def jpeg_array(w, h, seed):
    rng = np.random.default_rng(seed)
    x, y = np.meshgrid(np.arange(w), np.arange(h))
    img = (
        128
        + 80 * np.sin(x / (3 + seed % 5)) * np.cos(y / (4 + seed % 3))
        + rng.normal(0, 4, (h, w))
    )
    return np.clip(
        np.repeat(img[:, :, None], 3, axis=2), 0, 255
    ).astype(np.uint8)


def jpeg_bytes(w, h, seed, quality=92) -> bytes:
    import io

    from PIL import Image as PILImage

    buf = io.BytesIO()
    PILImage.fromarray(jpeg_array(w, h, seed)).save(
        buf, format="JPEG", quality=quality
    )
    return buf.getvalue()


def write_jpeg(path, w, h, seed, quality=92) -> None:
    with open(path, "wb") as f:
        f.write(jpeg_bytes(w, h, seed, quality))


def make_image_tar(tar_path, wnid, n, size=(48, 40), seed0=0):
    """A fixture tar of ``n`` small JPEGs named like ImageNet members
    (``{wnid}_{i}.JPEG``)."""
    tmpdir = os.path.dirname(tar_path)
    with tarfile.open(tar_path, "w") as tf:
        for i in range(n):
            p = os.path.join(tmpdir, f"{wnid}_{i}.JPEG")
            write_jpeg(p, *size, seed0 + i)
            tf.add(p, arcname=f"{wnid}_{i}.JPEG")
            os.unlink(p)
