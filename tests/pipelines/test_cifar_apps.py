"""Remaining CIFAR app tests (reference: pipelines/images/cifar/*)."""

import numpy as np
import pytest

from keystone_tpu.pipelines.images.cifar_apps import (
    RandomCifarAugmentedConfig,
    RandomCifarKernelConfig,
    linear_pixels,
    random_cifar,
    random_patch_cifar_augmented,
    random_patch_cifar_kernel,
)
from keystone_tpu.pipelines.images.random_patch_cifar import synthetic_cifar


def _spatial_cifar(n_train, n_test, seed=0):
    """Class-dependent spatial gray patterns (plain color blobs collapse
    to colliding scalars under GrayScaler, which no linear-in-gray model
    can separate 10 ways)."""
    import jax.numpy as jnp

    from keystone_tpu.loaders.cifar import LabeledImages
    from keystone_tpu.parallel.dataset import Dataset

    rng = np.random.default_rng(seed)
    x, y = np.meshgrid(np.arange(32), np.arange(32))
    patterns = [
        100 + 80 * np.sin(2 * np.pi * (x * np.cos(a) + y * np.sin(a)) / p)
        for a, p in zip(np.linspace(0, np.pi, 10, endpoint=False),
                        [4, 6, 8, 10, 12, 5, 7, 9, 11, 13])
    ]

    def make(n):
        ys = rng.integers(0, 10, n)
        imgs = np.stack(
            [patterns[c] + rng.normal(0, 10, (32, 32)) for c in ys]
        )
        imgs = np.repeat(imgs[:, :, :, None], 3, axis=3).clip(0, 255)
        return LabeledImages(
            labels=Dataset.from_array(jnp.asarray(ys.astype(np.int32))),
            images=Dataset.from_array(
                jnp.asarray(imgs.astype(np.float32))
            ),
        )

    return make(n_train), make(n_test)


def test_linear_pixels(mesh8):
    # n must exceed the 1024 gray-pixel feature dim: the exact solver has
    # no regularization (reference runs n=50000)
    train, test = _spatial_cifar(n_train=2048, n_test=64, seed=0)
    _, metrics = linear_pixels(train, test)
    assert metrics.total_accuracy > 0.8


def test_random_cifar(mesh8):
    train, test = synthetic_cifar(n_train=96, n_test=24, seed=1)
    _, metrics = random_cifar(
        train, test, num_filters=12, pool_size=14, pool_stride=13, lam=100.0
    )
    assert metrics.total_accuracy > 0.3  # better than 0.1 chance


def test_random_patch_cifar_kernel(mesh8):
    train, test = synthetic_cifar(n_train=64, n_test=16, seed=2)
    conf = RandomCifarKernelConfig(
        num_filters=8, patch_size=6, patch_steps=4,
        gamma=1e-2, block_size=32, num_epochs=3, lam=1.0,
    )
    _, metrics = random_patch_cifar_kernel(train, test, conf)
    assert metrics.total_accuracy > 0.6


def test_random_patch_cifar_augmented(mesh8):
    train, test = synthetic_cifar(n_train=48, n_test=12, seed=3)
    conf = RandomCifarAugmentedConfig(
        num_filters=8, patch_size=6, patch_steps=4, lam=50.0,
        augment_patch_size=24, augment_copies=3,
    )
    _, metrics = random_patch_cifar_augmented(train, test, conf)
    assert 0.0 <= metrics.total_accuracy <= 1.0


def test_random_patch_cifar_augmented_kernel(mesh8):
    """Augmented train crops + random flips, KRR solve, augmented-test
    merge (reference: RandomPatchCifarAugmentedKernel.scala:33)."""
    from keystone_tpu.pipelines.images.cifar_apps import (
        RandomCifarAugmentedKernelConfig,
        random_patch_cifar_augmented_kernel,
    )

    train, test = synthetic_cifar(n_train=48, n_test=12, seed=4)
    conf = RandomCifarAugmentedKernelConfig(
        num_filters=8, patch_size=6, patch_steps=4, lam=1.0,
        augment_patch_size=24, augment_copies=3,
        gamma=1e-2, block_size=48, num_epochs=2,
    )
    _, metrics = random_patch_cifar_augmented_kernel(train, test, conf)
    assert metrics.total_accuracy > 0.5  # learns on separable textures
