"""End-to-end MnistRandomFFT on the virtual 8-device mesh (reference:
pipelines/images/mnist/MnistRandomFFT.scala)."""

import numpy as np

from keystone_tpu.pipelines.images.mnist_random_fft import (
    MnistRandomFFTConfig,
    run,
    synthetic_mnist,
)


def test_mnist_random_fft_end_to_end(mesh8):
    # n=256 < D=1024 is the interpolation regime: lam must be large enough
    # to regularize (the reference app runs n=60000 >> D)
    train, test = synthetic_mnist(n_train=256, n_test=64, seed=0)
    conf = MnistRandomFFTConfig(num_ffts=2, block_size=512, lam=10.0)
    pipeline, metrics = run(train, test, conf)
    # well-separated synthetic blobs: near-perfect accuracy
    assert metrics.total_accuracy > 0.9


def test_mnist_fitted_pipeline_serves(mesh8):
    train, test = synthetic_mnist(n_train=256, n_test=8, seed=1)
    conf = MnistRandomFFTConfig(num_ffts=2, block_size=512, lam=10.0)
    pipeline, _ = run(train, test, conf)
    fitted = pipeline.fit()
    batch = np.asarray(fitted.apply(test.data).array())
    one = fitted.jit()(test.data.array()[0])
    assert int(one) == int(batch[0])
