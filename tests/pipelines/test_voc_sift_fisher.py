"""VOCSIFTFisher end-to-end on the reference's voctest.tar fixture."""

import numpy as np
import pytest

from keystone_tpu.loaders.image_loaders import VOCLoader
from keystone_tpu.pipelines.images.voc_sift_fisher import (
    SIFTFisherConfig,
    run,
)

VOC_TAR = "/root/reference/src/test/resources/images/voc/voctest.tar"
VOC_LABELS = "/root/reference/src/test/resources/images/voclabels.csv"


def test_voc_loader_reads_reference_fixture():
    ds = VOCLoader(VOC_TAR, VOC_LABELS)
    assert ds.n > 0
    first = ds.first()
    assert hasattr(first, "labels") and len(first.labels) >= 1


def test_voc_sift_fisher_end_to_end(mesh8):
    ds = VOCLoader(VOC_TAR, VOC_LABELS)
    # shrink images for test speed
    from keystone_tpu.parallel.dataset import Dataset

    small = ds.map(
        lambda li: type(li)(
            li.image[:96, :96], li.label, li.filename
        )
    )
    for a, b in zip(small.items(), ds.items()):
        a.labels = b.labels
    conf = SIFTFisherConfig(
        desc_dim=8, vocab_size=2, lam=0.5,
        num_pca_samples_per_image=20, num_gmm_samples_per_image=20,
    )
    predictor, mean_ap = run(small, small, conf)
    assert 0.0 <= mean_ap <= 1.0
