"""End-to-end flagship pipeline on tiny synthetic data (reference:
pipelines/images/imagenet/ImageNetSiftLcsFV.scala), plus a loader test
against the reference's test tar fixture."""

import numpy as np
import pytest

from keystone_tpu.loaders.image_loaders import (
    ImageExtractor,
    ImageNetLoader,
    LabeledImage,
    LabelExtractor,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    run,
)

IMAGENET_TAR = (
    "/root/reference/src/test/resources/images/imagenet/n15075141.tar"
)
IMAGENET_LABELS = (
    "/root/reference/src/test/resources/images/imagenet-test-labels"
)


def test_imagenet_loader_reads_reference_fixture():
    ds = ImageNetLoader(IMAGENET_TAR, IMAGENET_LABELS)
    assert ds.n > 0
    first = ds.first()
    assert first.label == 12
    assert first.image.ndim == 3 and first.image.shape[2] == 3


def _synthetic_imagenet(n_per_class=6, num_classes=3, size=48, seed=0):
    rng = np.random.default_rng(seed)
    items = []
    for c in range(num_classes):
        # class-dependent texture frequency so SIFT/LCS carry signal
        freq = 2.0 + 3.0 * c
        for i in range(n_per_class):
            x, y = np.meshgrid(np.arange(size), np.arange(size))
            base = 128 + 100 * np.sin(x / freq) * np.cos(y / freq)
            noise = rng.normal(0, 10, (size, size))
            img = np.stack([base + noise] * 3, axis=-1).clip(0, 255)
            items.append(
                LabeledImage(img.astype(np.float32), c, f"c{c}_{i}")
            )
    return Dataset.from_items(items)


def test_flagship_end_to_end_tiny(mesh8):
    """Proves LEARNING, not just plumbing: 6 classes make top-5 falsifiable
    (a degenerate fixed-5 predictor has top-5 err 1/6) and top-1 must beat
    the best degenerate baseline (5/6 err) by a wide margin. Reference
    accuracy check: ImageNetSiftLcsFV.scala:134-148."""
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=8,
        vocab_size=2,
        lam=1e-4,
        mixture_weight=0.25,
        num_classes=6,
        lcs_stride=8,
        lcs_border=16,
        lcs_patch=6,
        num_pca_samples_per_image=20,
        num_gmm_samples_per_image=20,
    )
    train = _synthetic_imagenet(n_per_class=6, num_classes=6, seed=0)
    test = _synthetic_imagenet(n_per_class=3, num_classes=6, seed=1)
    predictor, err = run(train, test, conf)
    assert err <= 1.0 / 6.0  # beats the degenerate fixed-5-classes baseline

    # top-1: first entry of the top-5 output is the argmax prediction
    test_images = ImageExtractor.apply(test)
    test_labels = np.asarray(LabelExtractor.apply(test).array())
    top5 = np.asarray(predictor(test_images).get().array())
    top1_err = (top5[:, 0] != test_labels).mean()
    assert top1_err <= 0.5  # degenerate single-class baseline is 5/6


def test_flagship_branch_feature_dims(mesh8):
    """Each FV branch must emit 2·descDim·vocabSize features (fv1 ‖ fv2),
    2·2·descDim·vocabSize after the two-branch gather — the num_features
    hint the solver receives (ImageNetSiftLcsFV.scala:139-142)."""
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        compute_pca_and_fisher_branch,
    )
    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.ops.images.sift import SIFTExtractor
    from keystone_tpu.ops.stats import SignedHellingerMapper

    conf = ImageNetSiftLcsFVConfig(
        desc_dim=8,
        vocab_size=2,
        num_classes=6,
        num_pca_samples_per_image=20,
        num_gmm_samples_per_image=20,
    )
    train = _synthetic_imagenet(n_per_class=3, num_classes=2, seed=0)
    images = ImageExtractor.apply(train)
    prefix = (
        PixelScaler()
        .and_then(GrayScaler())
        .and_then(SIFTExtractor(scale_step=1))
        .and_then(SignedHellingerMapper())
    )
    branch = compute_pca_and_fisher_branch(prefix, images, conf, None, None)
    feats = np.asarray(branch(images).get().array())
    assert feats.shape == (images.n, 2 * conf.desc_dim * conf.vocab_size)


def test_flagship_featurize_jit_batch_matches_executor():
    """FittedPipeline.jit_batch lowers the WHOLE SIFT+LCS -> PCA -> FV
    featurize graph (gather join, bucket-vmapped extractors, Hellinger/
    L2 chain) into one compiled program; it must match the node-by-node
    graph-executor path."""
    import jax.numpy as jnp

    from keystone_tpu.ops.images.core import GrayScaler, PixelScaler
    from keystone_tpu.ops.images.fisher_vector import FisherVector
    from keystone_tpu.ops.images.lcs import LCSExtractor
    from keystone_tpu.ops.images.sift import SIFTExtractor
    from keystone_tpu.ops.learning import BatchPCATransformer
    from keystone_tpu.ops.learning.gmm import GaussianMixtureModel
    from keystone_tpu.ops.stats import NormalizeRows, SignedHellingerMapper
    from keystone_tpu.ops.util.nodes import (
        FloatToDouble, MatrixVectorizer, VectorCombiner,
    )
    from keystone_tpu.workflow.api import Pipeline

    rng = np.random.default_rng(0)
    desc_dim, vocab = 8, 4

    def branch(prefix, in_dim):
        pca = jnp.asarray(
            rng.standard_normal((desc_dim, in_dim)).astype(np.float32) * 0.1
        )
        gmm = GaussianMixtureModel(
            jnp.asarray(rng.standard_normal((desc_dim, vocab)), jnp.float32),
            jnp.ones((desc_dim, vocab), jnp.float32),
            jnp.ones((vocab,), jnp.float32) / vocab,
        )
        return (
            prefix
            .and_then(BatchPCATransformer(pca.T))
            .and_then(FisherVector(gmm))
            .and_then(FloatToDouble())
            .and_then(MatrixVectorizer())
            .and_then(NormalizeRows())
            .and_then(SignedHellingerMapper())
            .and_then(NormalizeRows())
        )

    sift = branch(
        PixelScaler().and_then(GrayScaler())
        .and_then(SIFTExtractor(step=8, bin=4, num_scales=1))
        .and_then(SignedHellingerMapper()),
        128,
    )
    lcs = branch(LCSExtractor(8, 16, 4).to_pipeline(), 96)
    pipe = Pipeline.gather([sift, lcs]).and_then(VectorCombiner())

    imgs = jnp.asarray(
        rng.integers(0, 255, (4, 48, 48, 3)).astype(np.float32)
    )
    ref = pipe.apply(Dataset.from_array(imgs)).get().padded()
    out = pipe.fit().jit_batch()(imgs)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5
    )
