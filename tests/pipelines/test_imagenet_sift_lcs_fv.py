"""End-to-end flagship pipeline on tiny synthetic data (reference:
pipelines/images/imagenet/ImageNetSiftLcsFV.scala), plus a loader test
against the reference's test tar fixture."""

import numpy as np
import pytest

from keystone_tpu.loaders.image_loaders import (
    ImageExtractor,
    ImageNetLoader,
    LabeledImage,
    LabelExtractor,
)
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
    ImageNetSiftLcsFVConfig,
    run,
)

IMAGENET_TAR = (
    "/root/reference/src/test/resources/images/imagenet/n15075141.tar"
)
IMAGENET_LABELS = (
    "/root/reference/src/test/resources/images/imagenet-test-labels"
)


def test_imagenet_loader_reads_reference_fixture():
    ds = ImageNetLoader(IMAGENET_TAR, IMAGENET_LABELS)
    assert ds.n > 0
    first = ds.first()
    assert first.label == 12
    assert first.image.ndim == 3 and first.image.shape[2] == 3


def _synthetic_imagenet(n_per_class=6, num_classes=3, size=48, seed=0):
    rng = np.random.default_rng(seed)
    items = []
    for c in range(num_classes):
        # class-dependent texture frequency so SIFT/LCS carry signal
        freq = 2.0 + 3.0 * c
        for i in range(n_per_class):
            x, y = np.meshgrid(np.arange(size), np.arange(size))
            base = 128 + 100 * np.sin(x / freq) * np.cos(y / freq)
            noise = rng.normal(0, 10, (size, size))
            img = np.stack([base + noise] * 3, axis=-1).clip(0, 255)
            items.append(
                LabeledImage(img.astype(np.float32), c, f"c{c}_{i}")
            )
    return Dataset.from_items(items)


def test_flagship_end_to_end_tiny(mesh8):
    conf = ImageNetSiftLcsFVConfig(
        desc_dim=8,
        vocab_size=2,
        lam=1e-4,
        mixture_weight=0.25,
        num_classes=3,
        lcs_stride=8,
        lcs_border=16,
        lcs_patch=6,
        num_pca_samples_per_image=20,
        num_gmm_samples_per_image=20,
    )
    train = _synthetic_imagenet(n_per_class=6, seed=0)
    test = _synthetic_imagenet(n_per_class=2, seed=1)
    predictor, err = run(train, test, conf)
    # 3 classes, top-5 of 3 => every prediction contains the label
    assert err <= 0.5  # sanity: pipeline runs and is not degenerate
