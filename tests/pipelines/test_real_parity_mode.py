"""The real-data parity mode's code path, exercised on the reference
fixture tar (VERDICT r4 next #4): ``bench.py --imagenet-data`` must
stream tars through the full SIFT+LCS Fisher Vector chain, fit the
weighted BCD solver, and emit train/val top-k metrics end-to-end — so
the mode works the day a real ImageNet mount appears. Small CPU
shapes; the 5-image fixture is one class, so the assertions pin the
METRIC PLUMBING (rows present, errors in range, counts correct), not
accuracy."""

import os

import numpy as np
import pytest

FIXTURE_TAR_DIR = "/root/reference/src/test/resources/images/imagenet"
FIXTURE_LABELS = (
    "/root/reference/src/test/resources/images/imagenet-test-labels"
)

pytestmark = pytest.mark.skipif(
    not (os.path.isdir(FIXTURE_TAR_DIR) and os.path.exists(FIXTURE_LABELS)),
    reason="reference fixture tar unavailable",
)


def test_parity_mode_end_to_end_on_fixture(monkeypatch):
    import bench

    rows = []
    monkeypatch.setattr(
        bench, "emit",
        lambda metric, value, unit, vs=None, tflops=None, extra=None:
        rows.append({"metric": metric, "value": value, "unit": unit,
                     **(extra or {})}),
    )
    bench.bench_imagenet_real(
        FIXTURE_TAR_DIR, FIXTURE_LABELS, val_dir=FIXTURE_TAR_DIR,
        desc_dim=8, vocab=2, num_classes=16, size=64, batch=4,
    )
    assert len(rows) == 1
    row = rows[0]
    assert row["metric"] == "imagenet_real_end_to_end"
    assert row["unit"] == "examples/sec/chip"
    assert row["value"] > 0
    # the fixture tar holds 5 labeled images (class 12); val reuses it
    assert row["n_train"] == 5 and row["n_val"] == 5
    for key in ("train_top1_err", "train_top5_err",
                "val_top1_err", "val_top5_err"):
        assert 0.0 <= row[key] <= 1.0, (key, row[key])
    # one class, separable: the fitted model must at least rank the
    # true class into the top 5 of a 16-way indicator
    assert row["train_top5_err"] == 0.0
    assert row["val_top5_err"] == 0.0
