"""End-to-end RandomPatchCifar on synthetic data (reference:
pipelines/images/cifar/RandomPatchCifar.scala)."""

import numpy as np

from keystone_tpu.pipelines.images.random_patch_cifar import (
    RandomCifarConfig,
    run,
    synthetic_cifar,
)


def test_random_patch_cifar_end_to_end(mesh8):
    train, test = synthetic_cifar(n_train=128, n_test=32, seed=0)
    conf = RandomCifarConfig(
        num_filters=16, patch_size=6, patch_steps=3, lam=10.0
    )
    _, metrics = run(train, test, conf)
    # patch normalization removes most of the synthetic color-blob signal
    # by design (contrast normalization); well above the 0.1 chance level
    # is what this featurization can give here
    assert metrics.total_accuracy > 0.6
