"""Text/speech/NLP pipeline tests (reference: pipelines/text/*,
pipelines/speech/TimitPipeline.scala, pipelines/nlp/*)."""

import numpy as np
import pytest

from keystone_tpu.loaders.csv_loader import LabeledData
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.pipelines.nlp.stupid_backoff_pipeline import (
    StupidBackoffConfig,
)
from keystone_tpu.pipelines.nlp.stupid_backoff_pipeline import run as sb_run
from keystone_tpu.pipelines.speech.timit import TimitConfig
from keystone_tpu.pipelines.speech.timit import run as timit_run
from keystone_tpu.pipelines.text.amazon_reviews import (
    AmazonReviewsConfig,
)
from keystone_tpu.pipelines.text.amazon_reviews import run as amazon_run
from keystone_tpu.pipelines.text.newsgroups import NewsgroupsConfig
from keystone_tpu.pipelines.text.newsgroups import run as news_run

POS_WORDS = ["great", "love", "excellent", "awesome", "perfect"]
NEG_WORDS = ["bad", "hate", "terrible", "awful", "poor"]


def _sentiment_data(n=60, seed=0):
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        pos = rng.random() < 0.5
        words = rng.choice(POS_WORDS if pos else NEG_WORDS, 5)
        texts.append(" ".join(words) + " product")
        labels.append(1 if pos else 0)
    import jax.numpy as jnp

    return LabeledData(
        labels=Dataset.from_array(jnp.asarray(labels, jnp.int32)),
        data=Dataset.from_items(texts),
    )


def test_amazon_reviews_pipeline(mesh8):
    train = _sentiment_data(80, seed=0)
    test = _sentiment_data(20, seed=1)
    conf = AmazonReviewsConfig(common_features=256, num_iters=30)
    _, metrics = amazon_run(train, test, conf)
    assert metrics.accuracy > 0.9


def test_newsgroups_pipeline(mesh8):
    # two synthetic "newsgroups" with disjoint vocab, mapped onto the
    # first two class ids
    rng = np.random.default_rng(2)
    vocabs = [["compiler", "kernel", "gpu"], ["baseball", "pitcher", "inning"]]
    texts, labels = [], []
    for _ in range(60):
        c = int(rng.random() < 0.5)
        texts.append(" ".join(rng.choice(vocabs[c], 6)))
        labels.append(c)
    import jax.numpy as jnp

    data = LabeledData(
        labels=Dataset.from_array(jnp.asarray(labels, jnp.int32)),
        data=Dataset.from_items(texts),
    )
    conf = NewsgroupsConfig(n_grams=2, common_features=128)
    _, metrics = news_run(data, data, conf)
    assert metrics.total_accuracy > 0.95


def test_stupid_backoff_pipeline():
    text = Dataset.from_items(
        ["the cat sat", "the cat ran", "the dog sat"]
    )
    model, encoder = sb_run(text, StupidBackoffConfig(n=3))
    the = encoder.word_index["the"]
    cat = encoder.word_index["cat"]
    score = model.score((the, cat))
    assert score == pytest.approx(2 / 3)


def test_timit_pipeline_tiny(mesh8):
    rng = np.random.default_rng(3)
    import jax.numpy as jnp

    n, d, k = 200, 20, 5
    centers = rng.standard_normal((k, d)) * 3
    y = rng.integers(0, k, n)
    X = (centers[y] + rng.standard_normal((n, d))).astype(np.float32)
    train = LabeledData(
        labels=Dataset.from_array(jnp.asarray(y, jnp.int32)),
        data=Dataset.from_array(jnp.asarray(X)),
    )
    conf = TimitConfig(
        num_cosines=2, gamma=0.1, num_epochs=2, lam=1e-3,
        num_cosine_features=64, dim=d, num_classes=k,
    )
    _, metrics = timit_run(train, train, conf)
    assert metrics.total_accuracy > 0.9


def test_newsgroups_hashing_mode(mesh8):
    rng = np.random.default_rng(5)
    vocabs = [["compiler", "kernel", "gpu"], ["baseball", "pitcher", "inning"]]
    texts, labels = [], []
    for _ in range(60):
        c = int(rng.random() < 0.5)
        texts.append(" ".join(rng.choice(vocabs[c], 6)))
        labels.append(c)
    import jax.numpy as jnp

    data = LabeledData(
        labels=Dataset.from_array(jnp.asarray(labels, jnp.int32)),
        data=Dataset.from_items(texts),
    )
    conf = NewsgroupsConfig(common_features=1024, hashing=True)
    _, metrics = news_run(data, data, conf)
    assert metrics.total_accuracy > 0.9


def test_amazon_hashing_mode(mesh8):
    train = _sentiment_data(80, seed=0)
    test = _sentiment_data(20, seed=1)
    conf = AmazonReviewsConfig(
        common_features=1024, num_iters=30, hashing=True
    )
    _, metrics = amazon_run(train, test, conf)
    assert metrics.accuracy > 0.9
