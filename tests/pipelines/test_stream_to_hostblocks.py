"""The full out-of-core training flow, end to end: tar stream →
featurize per batch → features accumulated as HOST-RAM column blocks →
out-of-aggregate-HBM weighted BCD fit.

This is the reference's flagship workflow shape
(ImageNetSiftLcsFV.scala:106-142: stream-decode on executors, featurize,
cache features in cluster RAM, block-solve) composed from this
framework's pieces: StreamingImageNetLoader (bounded-memory decode),
``Dataset.host_blocks_from_batches`` (the cluster-RAM cache tier), and
``BlockWeightedLeastSquaresEstimator`` on host blocks (slab-streamed
PCG). Small CPU shapes; the contracts are composition correctness and
parity with the all-in-device-memory path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import io
import tarfile

from jpeg_fixtures import jpeg_array
from keystone_tpu.loaders.streaming import StreamingImageNetLoader
from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator
from keystone_tpu.ops.util.nodes import ClassLabelIndicators
from keystone_tpu.parallel.dataset import Dataset


def _class_tar(tar_path, wnid, cls, n):
    """A tar of images sharing a CLASS-coherent channel signature
    (class c is dominant in channel c) over per-image texture — so a
    linear model on pooled features can actually learn the classes."""
    from PIL import Image as PILImage

    gains = np.eye(3, dtype=np.float32) * 0.8 + 0.2
    with tarfile.open(tar_path, "w") as tf:
        for i in range(n):
            arr = jpeg_array(40, 40, cls * 977 + i).astype(np.float32)
            arr = np.clip(arr * gains[cls][None, None, :], 0, 255)
            buf = io.BytesIO()
            PILImage.fromarray(arr.astype(np.uint8)).save(
                buf, format="JPEG", quality=92
            )
            info = tarfile.TarInfo(f"{wnid}_{i}.JPEG")
            data = buf.getvalue()
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


@pytest.fixture
def tar_dir(tmp_path):
    d = tmp_path / "tars"
    d.mkdir()
    wnids = ["n02000001", "n02000002", "n02000003"]
    for i, wnid in enumerate(wnids):
        _class_tar(str(d / f"{wnid}.tar"), wnid, i, 8)
    labels = tmp_path / "labels.txt"
    labels.write_text(
        "".join(f"{w} {i}\n" for i, w in enumerate(wnids))
    )
    return str(d), str(labels)


def _featurize(u8_batch):
    """A small whole-batch featurize standing in for the FV chain:
    downsample + flatten + a fixed random projection (device compute,
    fixed output width)."""
    x = jnp.asarray(u8_batch, jnp.float32) / 255.0
    pooled = x.reshape(x.shape[0], 8, 4, 8, 4, 3).mean(axis=(2, 4))
    flat = pooled.reshape(x.shape[0], -1)
    rng = np.random.default_rng(0)
    proj = jnp.asarray(
        rng.standard_normal((flat.shape[1], 96)).astype(np.float32) / 10
    )
    return flat @ proj


def test_stream_featurize_hostblocks_fit_end_to_end(tar_dir):
    loc, labels_path = tar_dir
    loader = StreamingImageNetLoader(
        loc, labels_path, decode_size=32, shard_index=0, num_shards=1,
    )

    ys = []

    def batches():
        for imgs, labs, nv in loader.batches(8, np.uint8):
            ys.extend(labs[:nv])
            yield _featurize(imgs[:nv])

    host_ds = Dataset.host_blocks_from_batches(batches(), block_size=32)
    assert host_ds.is_host
    assert host_ds.n == 24
    assert host_ds.block_widths == [32, 32, 32]

    y = np.asarray(ys, np.int32)
    labels = ClassLabelIndicators(3).apply_batch(
        Dataset.from_array(jnp.asarray(y))
    )
    est = BlockWeightedLeastSquaresEstimator(
        block_size=32, num_iter=2, lam=1e-3, mixture_weight=0.5,
        solve="pcg",
    )
    model = est.fit(host_ds, labels)

    # parity: the same features fit through the all-in-device path
    dense = np.concatenate(host_ds.host_blocks, axis=1)
    dev = est.fit(
        Dataset.from_array(jnp.asarray(dense)), labels
    )
    np.testing.assert_allclose(
        np.asarray(model.W), np.asarray(dev.W), rtol=2e-4, atol=2e-5
    )

    # and the composed flow actually learned the classes
    preds = np.asarray(model.apply_batch(host_ds).array())
    assert (preds.argmax(1) == y).mean() == 1.0


def test_host_blocks_from_batches_contracts():
    with pytest.raises(ValueError, match="empty"):
        Dataset.host_blocks_from_batches(iter([]), block_size=8)
    ragged = iter([np.zeros((4, 16), np.float32),
                   np.zeros((4, 24), np.float32)])
    with pytest.raises(ValueError, match="width changed"):
        Dataset.host_blocks_from_batches(ragged, block_size=8)
    # uneven tail column block
    ds = Dataset.host_blocks_from_batches(
        iter([np.ones((2, 20), np.float32)] * 3), block_size=8
    )
    assert ds.block_widths == [8, 8, 4]
    assert ds.n == 6
