"""NodeOptimizationRule exercised THROUGH the rule and the default
optimizer — fake Optimizable transformer/estimator/label-estimator nodes
assert which physical operator the rule installs, sample-size accounting,
the data/label sample alignment, and the not-downstream-of-source guard.

Reference: src/test/scala/workflow/NodeOptimizationRuleSuite.scala:12-56
(choices some-false / all-true, no-opts, one-opt; the optimizable
transformer must stay default on test data because its input is the
pipeline source). Unlike the reference (which installs a custom
optimizer containing only the rule), these tests run through the DEFAULT
optimizer, so they fail if NodeOptimizationRule is ever dropped from it
(VERDICT r3 weak #5).
"""

import dataclasses
from typing import Optional

import numpy as np
import pytest

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Estimator, LabelEstimator, Transformer
from keystone_tpu.workflow.graph import SourceId
from keystone_tpu.workflow.node_optimization import (
    NodeOptimizationRule,
    Optimizable,
)


@dataclasses.dataclass(frozen=True)
class State:
    choice: Optional[bool] = None
    transformer_choice: Optional[bool] = None
    estimator_choice: Optional[bool] = None
    label_estimator_choice: Optional[bool] = None


def _map_transformer(**field):
    class _T(Transformer):
        def apply(self, x):
            return dataclasses.replace(x, **field)

    return _T()


transformer_do_nothing = _map_transformer(transformer_choice=None)
transformer_a = _map_transformer(transformer_choice=False)
transformer_b = _map_transformer(transformer_choice=True)


class OptimizableT(Transformer, Optimizable):
    """default = do-nothing; optimize picks A iff any sampled choice is
    False (reference: optimizableTransformer)."""

    def __init__(self):
        self.seen_n_total = None

    def apply(self, x):
        return dataclasses.replace(x, transformer_choice=None)

    def optimize(self, samples, n_total):
        self.seen_n_total = n_total
        if any(s.choice is False for s in samples[0].items()):
            return transformer_a
        return transformer_b


class _FixedEstimator(Estimator):
    def __init__(self, value):
        self.value = value

    def fit(self, data):
        return _map_transformer(estimator_choice=self.value)


class OptimizableE(Estimator, Optimizable):
    def __init__(self):
        self.seen_n_total = None

    def fit(self, data):
        return _map_transformer(estimator_choice=None)

    def optimize(self, samples, n_total):
        self.seen_n_total = n_total
        if any(s.choice is False for s in samples[0].items()):
            return _FixedEstimator(False)
        return _FixedEstimator(True)


class _FixedLabelEstimator(LabelEstimator):
    def __init__(self, value):
        self.value = value

    def fit(self, data, labels):
        return _map_transformer(label_estimator_choice=self.value)


class OptimizableLE(LabelEstimator, Optimizable):
    def __init__(self):
        self.seen_n_total = None

    def fit(self, data, labels):
        return _map_transformer(label_estimator_choice=None)

    def optimize(self, samples, n_total):
        self.seen_n_total = n_total
        data_sample, label_sample = samples
        # the data and label samples must stay aligned (the reference's
        # optimize asserts the zip: NodeOptimizationRuleSuite.scala:176)
        for s, l in zip(data_sample.items(), label_sample.items()):
            assert s.choice == l, "label and choice must be equal!"
        if any(s.choice is False for s in data_sample.items()):
            return _FixedLabelEstimator(False)
        return _FixedLabelEstimator(True)


def _choices_pipeline(choices):
    """optimizableTransformer -> (optimizableEstimator, data) ->
    (optimizableLabelEstimator, data, labels), mirroring the reference
    pipeline shape."""
    states = [State(choice=c) for c in choices]
    train = Dataset.from_items(states)
    labels = train.map(lambda s: s.choice)
    t, e, le = OptimizableT(), OptimizableE(), OptimizableLE()
    pipe = (
        t.and_then(e, train)
        .and_then(le, train, labels)
    )
    return pipe, (t, e, le), len(states)


def test_choices_some_false():
    rng = np.random.default_rng(0)
    choices = [bool(v) for v in rng.integers(0, 2, 600)]
    assert False in choices[:96]  # the sampled prefix must see a False
    pipe, (t, e, le), n = _choices_pipeline(choices)
    out = pipe.apply(State()).get()
    assert out.transformer_choice is None, (
        "the optimizable transformer must use the default on test data"
    )
    assert out.estimator_choice is False
    assert out.label_estimator_choice is False
    # sample-size accounting: optimize saw the TRUE dataset size, not
    # the sample's
    assert e.seen_n_total == n
    assert le.seen_n_total == n


def test_choices_all_true():
    pipe, (t, e, le), n = _choices_pipeline([True] * 600)
    out = pipe.apply(State()).get()
    assert out.transformer_choice is None
    assert out.estimator_choice is True
    assert out.label_estimator_choice is True


def test_no_opts_to_make():
    states = [State(choice=True) for _ in range(200)]
    train = Dataset.from_items(states)
    labels = train.map(lambda s: s.choice)
    pipe = (
        transformer_a
        .and_then(_FixedEstimator(True), train)
        .and_then(_FixedLabelEstimator(True), train, labels)
    )
    out = pipe.apply(State()).get()
    assert out == State(None, False, True, True)


def test_one_opt_to_make():
    states = [State(choice=True) for _ in range(200)]
    train = Dataset.from_items(states)
    labels = train.map(lambda s: s.choice)
    pipe = (
        transformer_a
        .and_then(_FixedEstimator(True), train)
        .and_then(OptimizableLE(), train, labels)
    )
    out = pipe.apply(State()).get()
    assert out == State(None, False, True, True)


def test_source_downstream_guard_through_rule():
    """NodeOptimizationRule.apply directly: an optimizable node whose
    input is (transitively) the pipeline source must NOT be optimized —
    its runtime input is not yet spliced in."""
    t = OptimizableT()
    pipe = t.to_pipeline()
    g = pipe._graph
    opt_nodes = [
        nid for nid, op in g.operators.items() if isinstance(op, Optimizable)
    ]
    assert len(opt_nodes) == 1
    g2, _ = NodeOptimizationRule().apply(g, {})
    assert g2.operators[opt_nodes[0]] is t, (
        "source-fed optimizable node must keep its default operator"
    )
    assert t.seen_n_total is None  # optimize() never ran


def test_rule_swaps_operator_in_graph():
    """The rule physically swaps the graph operator (not just the
    executed result): after apply, the estimator node holds the chosen
    physical estimator."""
    states = [State(choice=False) for _ in range(150)]
    train = Dataset.from_items(states)
    e = OptimizableE()
    pipe = e.with_data(train)
    g = pipe._graph
    g2, _ = NodeOptimizationRule().apply(g, {})
    swapped = [
        op for op in g2.operators.values()
        if isinstance(op, _FixedEstimator)
    ]
    assert len(swapped) == 1 and swapped[0].value is False
    assert not any(
        isinstance(op, OptimizableE) for op in g2.operators.values()
    )
