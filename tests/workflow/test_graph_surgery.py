"""Exhaustive Graph IR surgery semantics — the accessor/mutator edge
cases of the reference's GraphSuite (786 LoC, workflow/Graph.scala per-op
contracts) expressed against this IR: accessor failures, every surgery
op's success + error paths, id-allocation rules, and splice argument
checks. Complements tests/workflow/test_graph.py (happy paths)."""

import pytest

from keystone_tpu.workflow.graph import (
    EMPTY_GRAPH,
    NodeId,
    SinkId,
    SourceId,
    linearize,
)
from graph_test_helpers import op


def diamond():
    """src -> a -> (b, c) -> d -> sink (b, c are parallel branches)."""
    g, src = EMPTY_GRAPH.add_source()
    g, a = g.add_node(op("a"), (src,))
    g, b = g.add_node(op("b"), (a,))
    g, c = g.add_node(op("c"), (a,))
    g, d = g.add_node(op("d"), (b, c))
    g, snk = g.add_sink(d)
    return g, src, a, b, c, d, snk


# -- accessors ------------------------------------------------------------


def test_accessors_on_missing_ids_raise():
    g, src, a, b, c, d, snk = diamond()
    with pytest.raises(KeyError):
        g.get_operator(NodeId(99))
    with pytest.raises(KeyError):
        g.get_dependencies(NodeId(99))
    with pytest.raises(KeyError):
        g.get_sink_dependency(SinkId(99))


def test_accessors_return_structure():
    g, src, a, b, c, d, snk = diamond()
    assert g.get_dependencies(d) == (b, c)
    assert g.get_sink_dependency(snk) == d
    assert g.get_operator(a).label == "a"
    assert g.nodes == {a, b, c, d}
    assert g.sinks == {snk}
    assert g.sources == frozenset({src})


# -- add ops on the empty graph ------------------------------------------


def test_add_node_on_empty_graph_no_deps():
    g, n = EMPTY_GRAPH.add_node(op("n"), ())
    assert g.nodes == {n}
    assert g.get_dependencies(n) == ()
    assert g.sources == frozenset()


def test_add_source_on_empty_graph():
    g, s = EMPTY_GRAPH.add_source()
    assert g.sources == frozenset({s})
    assert g.nodes == set()
    # a sink may depend directly on a source
    g, snk = g.add_sink(s)
    assert g.get_sink_dependency(snk) == s


def test_id_allocation_monotone_and_disjoint_per_kind():
    g, s0 = EMPTY_GRAPH.add_source()
    g, s1 = g.add_source()
    g, n0 = g.add_node(op("x"), (s0,))
    g, k0 = g.add_sink(n0)
    assert (s0.id, s1.id) == (0, 1)
    assert n0.id == 0 and k0.id == 0  # kinds number independently
    # ids are max+1 over the CURRENT population (reference semantics:
    # Graph.scala nextId = max + 1), so removing the only sink lets its
    # id be reused — safe because all surgery is functional
    g2 = g.remove_sink(k0)
    g2, k1 = g2.add_sink(n0)
    assert k1.id == k0.id


# -- setters --------------------------------------------------------------


def test_set_dependencies():
    g, src, a, b, c, d, snk = diamond()
    g2 = g.set_dependencies(d, (c, b))
    assert g2.get_dependencies(d) == (c, b)
    assert g.get_dependencies(d) == (b, c)  # original untouched
    with pytest.raises(KeyError):
        g.set_dependencies(NodeId(99), (a,))


def test_set_operator():
    g, src, a, b, c, d, snk = diamond()
    g2 = g.set_operator(b, op("b2"))
    assert g2.get_operator(b).label == "b2"
    assert g.get_operator(b).label == "b"
    with pytest.raises(KeyError):
        g.set_operator(NodeId(99), op("x"))


def test_set_sink_dependency():
    g, src, a, b, c, d, snk = diamond()
    g2 = g.set_sink_dependency(snk, b)
    assert g2.get_sink_dependency(snk) == b
    assert g.get_sink_dependency(snk) == d
    with pytest.raises(KeyError):
        g.set_sink_dependency(SinkId(99), b)


# -- removals -------------------------------------------------------------


def test_remove_sink_leaves_nodes():
    g, src, a, b, c, d, snk = diamond()
    g2 = g.remove_sink(snk)
    assert g2.sinks == set()
    assert g2.nodes == {a, b, c, d}
    with pytest.raises(KeyError):
        g.remove_sink(SinkId(99))


def test_remove_source_requires_unreferenced():
    g, src, a, b, c, d, snk = diamond()
    with pytest.raises(ValueError):
        g.remove_source(src)  # a still depends on it
    g2 = g.set_dependencies(a, ())
    g3 = g2.remove_source(src)
    assert g3.sources == frozenset()


def test_remove_node_requires_unreferenced():
    g, src, a, b, c, d, snk = diamond()
    with pytest.raises(ValueError):
        g.remove_node(b)  # d still depends on it
    with pytest.raises(ValueError):
        g.remove_node(d)  # the sink still depends on it
    g2 = g.remove_sink(snk).set_dependencies(d, ())
    g3 = g2.remove_node(d)
    assert d not in g3.nodes


def test_replace_dependency_rewrites_nodes_and_sinks():
    g, src, a, b, c, d, snk = diamond()
    # reroute every consumer of b onto c; b becomes dead
    g2 = g.replace_dependency(b, c)
    assert g2.get_dependencies(d) == (c, c)
    g3 = g2.set_sink_dependency(snk, b).replace_dependency(b, a)
    assert g3.get_sink_dependency(snk) == a


# -- graph composition ----------------------------------------------------


def test_add_graph_remaps_without_collisions():
    g1, src1, a1, b1, c1, d1, snk1 = diamond()
    g2, src2, a2, b2, c2, d2, snk2 = diamond()
    merged, smap, kmap = g1.add_graph(g2)
    # old structure intact
    assert merged.get_dependencies(d1) == (b1, c1)
    # imported structure intact under fresh ids
    new_src = smap[src2]
    new_snk = kmap[snk2]
    assert new_src != src1 and new_snk != snk1
    assert len(merged.nodes) == 8
    assert len(merged.sources) == 2
    # imported sink resolves through remapped nodes back to its source
    tip = merged.get_sink_dependency(new_snk)
    assert tip in merged.nodes and tip != d1


def test_connect_graph_missing_splice_ids_raise():
    g1, src1, a1, b1, c1, d1, snk1 = diamond()
    g2, src2, a2, b2, c2, d2, snk2 = diamond()
    with pytest.raises(KeyError):
        g1.connect_graph(g2, {SourceId(99): snk1})
    with pytest.raises(KeyError):
        g1.connect_graph(g2, {src2: SinkId(99)})


def test_connect_graph_removes_spliced_endpoints():
    g1, src1, a1, b1, c1, d1, snk1 = diamond()
    g2, src2, a2, b2, c2, d2, snk2 = diamond()
    merged, smap, kmap = g1.connect_graph(g2, {src2: snk1})
    # the spliced source and sink are gone; the imported head now feeds
    # from g1's old tip
    assert src2 not in smap  # consumed by the splice
    assert snk1 not in merged.sinks
    assert len(merged.sources) == 1
    remapped_heads = [
        n for n in merged.nodes
        if merged.get_dependencies(n) and
        merged.get_dependencies(n)[0] == d1 and n not in (b1, c1, d1)
    ]
    assert remapped_heads  # g2's `a` now consumes g1's `d`


def test_replace_nodes_missing_ids_raise():
    g1, src1, a1, b1, c1, d1, snk1 = diamond()
    rep, rsrc = EMPTY_GRAPH.add_source()
    rep, rn = rep.add_node(op("r"), (rsrc,))
    rep, rsnk = rep.add_sink(rn)
    with pytest.raises(KeyError):
        g1.replace_nodes({b1}, rep, {SourceId(99): a1}, {b1: rsnk})
    with pytest.raises(KeyError):
        g1.replace_nodes({b1}, rep, {rsrc: a1}, {b1: SinkId(99)})


def test_linearize_topological_and_deterministic():
    g, src, a, b, c, d, snk = diamond()
    order = linearize(g)
    pos = {gid: i for i, gid in enumerate(order)}
    assert pos[src] < pos[a] < pos[d]
    assert pos[a] < pos[b] and pos[a] < pos[c]
    assert order == linearize(g)  # deterministic


# -- analyses (reference AnalysisUtilsSuite depth) ------------------------


def test_children_and_parents():
    from keystone_tpu.workflow.graph import get_children, get_parents

    g, src, a, b, c, d, snk = diamond()
    assert get_children(g, src) == {a}
    assert get_children(g, a) == {b, c}
    assert get_children(g, d) == {snk}
    assert get_parents(g, a) == {src}
    assert get_parents(g, d) == {b, c}
    assert get_parents(g, snk) == {d}
    assert get_parents(g, src) == set()


def test_descendants_and_ancestors():
    from keystone_tpu.workflow.graph import get_ancestors, get_descendants

    g, src, a, b, c, d, snk = diamond()
    assert get_descendants(g, src) == {a, b, c, d, snk}
    assert get_descendants(g, b) == {d, snk}
    assert get_descendants(g, d) == {snk}
    assert get_ancestors(g, snk) == {src, a, b, c, d}
    assert get_ancestors(g, d) == {src, a, b, c}
    assert get_ancestors(g, a) == {src}
    assert get_ancestors(g, src) == set()


def test_analyses_on_disconnected_components():
    from keystone_tpu.workflow.graph import get_ancestors, get_descendants

    g, src, a, b, c, d, snk = diamond()
    g, lone = g.add_node(op("lone"), ())
    assert get_descendants(g, lone) == set()
    assert get_ancestors(g, lone) == set()
    # the diamond is unaffected
    assert get_descendants(g, b) == {d, snk}


def test_linearize_is_sink_reachable_only():
    """Nodes that feed no sink are excluded (reference AnalysisUtils
    .linearize walks back from sinks — the property dead-branch removal
    keys on)."""
    g, src, a, b, c, d, snk = diamond()
    g, lone = g.add_node(op("lone"), ())
    order = linearize(g)
    assert len(order) == len(set(order))
    assert set(order) >= {src, a, b, c, d}
    assert lone not in order
