"""eq_key must digest array fields once per instance and never embed raw
bytes in the key (VERDICT r1 weak item 4: uncached, prefix/CSE cost scaled
with total parameter bytes)."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow import api
from keystone_tpu.workflow.api import Transformer


@dataclasses.dataclass(eq=False)
class BigModel(Transformer):
    W: np.ndarray

    def apply(self, x):
        return x @ self.W


def test_array_digest_called_once_per_instance(monkeypatch):
    calls = []
    real = api._array_digest

    def counting(a):
        calls.append(a.nbytes)
        return real(a)

    monkeypatch.setattr(api, "_array_digest", counting)
    # jnp arrays (and frozen np arrays) are immutable -> digest cached
    t = BigModel(W=jnp.ones((512, 256), jnp.float32))
    k1 = t.eq_key()
    k2 = t.eq_key()
    k3 = t.eq_key()
    assert k1 == k2 == k3
    assert len(calls) == 1  # one serialization ever


def test_mutable_np_array_not_cached(monkeypatch):
    """Writeable np.ndarray fields must be re-digested each call: in-place
    mutation has to produce a fresh key (identity caching would go
    stale)."""
    t = BigModel(W=np.zeros((8, 8), np.float32))
    k1 = t.eq_key()
    t.W[0, 0] = 5.0  # in-place mutation
    assert t.eq_key() != k1


def test_scalar_field_mutation_refreshes_key(monkeypatch):
    """Only the array digest is cached — config-field mutation after
    construction must still produce a fresh structural key."""

    @dataclasses.dataclass(eq=False)
    class WithScalar(Transformer):
        W: np.ndarray
        lam: float = 0.1

        def apply(self, x):
            return x

    t = WithScalar(W=np.ones((4, 4), np.float32))
    k1 = t.eq_key()
    t.lam = 0.5
    assert t.eq_key() != k1


def test_digest_cache_not_pickled():
    import pickle

    t = BigModel(W=jnp.ones((64, 64), jnp.float32))
    t.eq_key()
    assert "_arr_digest_cache" in t.__dict__
    t2 = pickle.loads(pickle.dumps(t))
    assert "_arr_digest_cache" not in t2.__dict__
    assert t2.eq_key() == t.eq_key()


def test_key_is_digest_not_raw_bytes():
    t = BigModel(W=np.zeros((1024, 1024), np.float32))  # 4 MB array
    key = t.eq_key()

    def total_size(obj):
        if isinstance(obj, (tuple, list)):
            return sum(total_size(x) for x in obj)
        if isinstance(obj, (bytes, str)):
            return len(obj)
        return 8

    assert total_size(key) < 4096  # fixed-size key, not 4 MB of bytes


def test_equal_arrays_same_key_different_arrays_differ():
    a = BigModel(W=np.arange(12, dtype=np.float32).reshape(3, 4))
    b = BigModel(W=np.arange(12, dtype=np.float32).reshape(3, 4))
    c = BigModel(W=np.arange(12, dtype=np.float32).reshape(3, 4) + 1)
    assert a.eq_key() == b.eq_key()  # CSE still merges equal models
    assert a.eq_key() != c.eq_key()
    d = BigModel(W=np.arange(12, dtype=np.float32).reshape(4, 3))
    assert a.eq_key() != d.eq_key()  # same bytes, different shape
