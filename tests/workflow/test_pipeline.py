"""Pipeline API semantics (modeled on the reference PipelineSuite):
chaining, laziness, gather, the fit-once memoization guarantee, fitted
pipeline save/load."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow import (
    Estimator,
    LabelEstimator,
    Pipeline,
    PipelineEnv,
    Transformer,
)
from keystone_tpu.ops.util import VectorCombiner


@dataclasses.dataclass(eq=False)
class Scale(Transformer):
    factor: float

    def apply(self, x):
        return x * self.factor


@dataclasses.dataclass(eq=False)
class AddConst(Transformer):
    c: float

    def apply(self, x):
        return x + self.c


class MeanCenterEstimator(Estimator):
    def __init__(self):
        self.fit_count = 0

    def fit(self, data: Dataset) -> Transformer:
        self.fit_count += 1
        mean = jnp.mean(data.array(), axis=0)
        return AddConst(-mean)


class OffsetLabelEstimator(LabelEstimator):
    def __init__(self):
        self.fit_count = 0

    def fit(self, data: Dataset, labels: Dataset) -> Transformer:
        self.fit_count += 1
        delta = jnp.mean(labels.array() - data.array())
        return AddConst(delta)


def test_transformer_single_and_batch():
    t = Scale(2.0)
    out = t.to_pipeline().apply_datum(jnp.asarray([1.0, 2.0])).get()
    np.testing.assert_allclose(out, [2.0, 4.0])
    ds = Dataset.from_array(jnp.ones((4, 3)))
    out = t(ds).get()
    np.testing.assert_allclose(np.asarray(out.array()), 2 * np.ones((4, 3)))


def test_chaining():
    pipe = Scale(2.0).and_then(AddConst(1.0)).and_then(Scale(10.0))
    out = pipe.apply_datum(jnp.asarray([1.0])).get()
    np.testing.assert_allclose(out, [30.0])


def test_estimator_chaining_and_laziness():
    data = Dataset.from_array(jnp.asarray([[1.0], [3.0]]))  # mean 2
    est = MeanCenterEstimator()
    pipe = Scale(1.0).and_then(est, data)
    assert est.fit_count == 0  # nothing executed yet
    out = pipe.apply_datum(jnp.asarray([5.0]))
    assert est.fit_count == 0  # still lazy
    np.testing.assert_allclose(out.get(), [3.0])
    assert est.fit_count == 1


def test_fit_once_guarantee():
    """Reference PipelineSuite 'Do not fit estimators multiple times'."""
    data = Dataset.from_array(jnp.asarray([[1.0], [3.0]]))
    est = MeanCenterEstimator()
    pipe = Scale(1.0).and_then(est, data)
    a = pipe.apply_datum(jnp.asarray([5.0]))
    a.get()
    # A *new* pipeline built from the same estimator + data shares the prefix
    pipe2 = Scale(1.0).and_then(est, data)
    b = pipe2.apply_datum(jnp.asarray([7.0]))
    np.testing.assert_allclose(b.get(), [5.0])
    assert est.fit_count == 1  # memoized via PipelineEnv prefix state


def test_label_estimator():
    data = Dataset.from_array(jnp.zeros((3, 1)))
    labels = Dataset.from_array(jnp.ones((3, 1)))
    est = OffsetLabelEstimator()
    pipe = Scale(1.0).and_then(est, data, labels)
    out = pipe.apply_datum(jnp.asarray([0.5])).get()
    np.testing.assert_allclose(out, [1.5])
    assert est.fit_count == 1


def test_gather_and_combine():
    branches = [Scale(1.0), Scale(2.0), Scale(3.0)]
    pipe = Pipeline.gather(branches).and_then(VectorCombiner())
    ds = Dataset.from_array(jnp.ones((2, 2)))
    out = pipe(ds).get()
    np.testing.assert_allclose(
        np.asarray(out.array()),
        [[1, 1, 2, 2, 3, 3], [1, 1, 2, 2, 3, 3]],
    )
    single = pipe.apply_datum(jnp.ones((2,))).get()
    np.testing.assert_allclose(single, [1, 1, 2, 2, 3, 3])


def test_fit_returns_frozen_pipeline(tmp_path):
    data = Dataset.from_array(jnp.asarray([[2.0], [4.0]]))  # mean 3
    est = MeanCenterEstimator()
    pipe = Scale(1.0).and_then(est, data)
    fitted = pipe.fit()
    assert est.fit_count == 1
    np.testing.assert_allclose(fitted.apply(jnp.asarray([4.0])), [1.0])
    # batch apply
    out = fitted.apply(Dataset.from_array(jnp.asarray([[3.0], [6.0]])))
    np.testing.assert_allclose(np.asarray(out.array()), [[0.0], [3.0]])
    # fitting again doesn't refit
    pipe.fit()
    assert est.fit_count == 1
    # save/load
    p = tmp_path / "fitted.pkl"
    fitted.save(str(p))
    from keystone_tpu.workflow import FittedPipeline

    loaded = FittedPipeline.load(str(p))
    np.testing.assert_allclose(loaded.apply(jnp.asarray([4.0])), [1.0])


def test_fitted_pipeline_jit():
    pipe = Scale(2.0).and_then(AddConst(1.0))
    # a transformer-only pipeline is fit-able without estimators
    fitted = pipe.fit()
    f = fitted.jit()
    np.testing.assert_allclose(f(jnp.asarray([1.0, 2.0])), [3.0, 5.0])


def test_cse_merges_equal_branches():
    """Two structurally equal dataclass transformers merge (CSE)."""
    from keystone_tpu.workflow.executor import GraphExecutor

    pipe = Pipeline.gather([Scale(2.0), Scale(2.0)])
    ds = Dataset.from_array(jnp.ones((2, 1)))
    result = pipe(ds)
    result.get()
    optimized = result._executor.graph
    # gather + one merged Scale + data node = 3 operators
    assert len(optimized.operators) == 3


def test_unexecutable_source_dependent():
    pipe = Scale(2.0).to_pipeline()
    with pytest.raises(ValueError):
        pipe.executor.execute(pipe.sink)


def test_apply_pipeline_dataset_chains_lazily():
    data = Dataset.from_array(jnp.ones((2, 2)))
    stage1 = Scale(3.0)(data)  # PipelineDataset
    stage2 = AddConst(1.0)(stage1)
    out = stage2.get()
    np.testing.assert_allclose(np.asarray(out.array()), 4 * np.ones((2, 2)))


def test_incremental_extension_reuses_executed_prefix():
    """Reference PipelineSuite 'Incrementally update execution state':
    extending an already-executed pipeline with and_then must not refit
    the earlier estimator — its prefix is already in PipelineEnv state."""
    data = Dataset.from_array(jnp.asarray([[1.0], [3.0]]))
    est = MeanCenterEstimator()
    pipe = Scale(1.0).and_then(est, data)
    pipe.apply_datum(jnp.asarray([5.0])).get()
    assert est.fit_count == 1

    extended = pipe.and_then(Scale(10.0))
    out = extended.apply_datum(jnp.asarray([5.0])).get()
    np.testing.assert_allclose(out, [30.0])
    assert est.fit_count == 1  # prefix reused, not refit


def test_incremental_extension_with_label_estimator():
    data = Dataset.from_array(jnp.zeros((3, 1)))
    labels = Dataset.from_array(jnp.ones((3, 1)))
    est = OffsetLabelEstimator()
    pipe = Scale(1.0).and_then(est, data, labels)
    pipe.apply_datum(jnp.asarray([0.0])).get()

    extended = pipe.and_then(AddConst(5.0))
    out = extended.apply_datum(jnp.asarray([0.0])).get()
    np.testing.assert_allclose(out, [6.0])
    assert est.fit_count == 1


def test_incremental_second_estimator_fits_on_first_output():
    """Chaining a SECOND estimator whose training data flows through the
    first: the first stays fit-once, the second sees transformed data."""
    data = Dataset.from_array(jnp.asarray([[2.0], [4.0]]))
    est1 = MeanCenterEstimator()
    pipe = Scale(1.0).and_then(est1, data)
    pipe.apply_datum(jnp.asarray([1.0])).get()

    est2 = MeanCenterEstimator()
    # est2 trains on est1's OUTPUT of the same data (mean 0 after
    # centering), so its learned offset is 0
    extended = pipe.and_then(est2, data)
    out = extended.apply_datum(jnp.asarray([1.0])).get()
    np.testing.assert_allclose(out, [-2.0])  # 1 - mean(3) + 0
    assert est1.fit_count == 1
    assert est2.fit_count == 1


def test_fitted_pipeline_jit_batch_matches_executor():
    """jit_batch lowers the WHOLE fitted transformer graph into one
    compiled program (SURVEY §7 staging); it must match the node-by-node
    executor path on an array-mode chain, including a gather join."""
    import jax.numpy as jnp

    from keystone_tpu.ops.stats import (
        LinearRectifier, NormalizeRows, RandomSignNode,
    )
    from keystone_tpu.ops.util.nodes import VectorCombiner
    from keystone_tpu.workflow.api import Pipeline

    branches = [
        RandomSignNode.create(12, seed=i)
        .and_then(LinearRectifier(0.0))
        .and_then(NormalizeRows())
        for i in range(2)
    ]
    pipe = Pipeline.gather(branches).and_then(VectorCombiner())
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((6, 12)).astype(np.float32)
    )
    ref = pipe.apply(Dataset.from_array(x)).get().padded()
    out = pipe.fit().jit_batch()(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
