"""Shared helpers for the graph suites (importable because
pytest puts each test dir on sys.path in rootdir mode)."""

from keystone_tpu.workflow.operators import DatumOperator


def op(name):
    """A labeled constant-datum operator — the graph suites' stand-in
    node payload."""
    return DatumOperator(name, label=name)
