"""AutoCacheRule tests (reference: AutocCacheRuleSuite — cache-insertion
decisions with fake profiles)."""

import numpy as np
import pytest

from keystone_tpu.ops.util.cacher import Cacher
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Pipeline, Transformer, transformer
from keystone_tpu.workflow.auto_cache import (
    AutoCacheRule,
    Profile,
    estimate_cached_runtime,
    get_node_weights,
    get_runs,
    profile_nodes,
)
from keystone_tpu.workflow.graph import EMPTY_GRAPH, NodeId
from keystone_tpu.workflow.operators import DatasetOperator, TransformerOperator


class _CountingOp(TransformerOperator):
    def __init__(self, weight=1):
        self.weight = weight
        self.calls = 0

    def single_transform(self, inputs):
        return inputs[0]

    def batch_transform(self, inputs):
        self.calls += 1
        return inputs[0]

    def eq_key(self):
        return id(self)


def _diamond_graph():
    """data -> a -> (b, c) where b and c both consume a (a runs twice)."""
    ds = Dataset.of(np.ones((8, 2), np.float32))
    g, d = EMPTY_GRAPH.add_node(DatasetOperator(ds), ())
    a_op = _CountingOp()
    g, a = g.add_node(a_op, (d,))
    g, b = g.add_node(_CountingOp(), (a,))
    g, c = g.add_node(_CountingOp(weight=3), (a,))
    g, s1 = g.add_sink(b)
    g, s2 = g.add_sink(c)
    return g, {"data": d, "a": a, "b": b, "c": c}


def test_get_runs_counts_consumer_passes():
    g, ids = _diamond_graph()
    weights = get_node_weights(g)
    runs = get_runs(g, set(), weights)
    # a is consumed by b (weight 1) and c (weight 3) -> 4 evaluations
    assert runs[ids["a"]] == 4
    # caching a brings it to one evaluation for runtime purposes
    rt_uncached = estimate_cached_runtime(
        g, set(), {ids["a"]: Profile(100, 10, 0)}, weights
    )
    rt_cached = estimate_cached_runtime(
        g, {ids["a"]}, {ids["a"]: Profile(100, 10, 0)}, weights
    )
    assert rt_uncached == 400 and rt_cached == 100


def test_aggressive_cache_selects_multiply_used():
    g, ids = _diamond_graph()
    rule = AutoCacheRule("aggressive")
    selected = rule.aggressive_cache(g, get_node_weights(g))
    assert ids["a"] in selected
    assert ids["b"] not in selected


def test_greedy_respects_budget():
    g, ids = _diamond_graph()
    rule = AutoCacheRule("greedy", mem_budget_bytes=5)
    profiles = {ids["a"]: Profile(100, 10, 0)}  # too big for budget
    assert rule.greedy_cache(g, profiles, get_node_weights(g)) == set()
    rule2 = AutoCacheRule("greedy", mem_budget_bytes=50)
    assert rule2.greedy_cache(g, profiles, get_node_weights(g)) == {
        ids["a"]
    }


def test_add_caches_inserts_cacher_between_node_and_children():
    g, ids = _diamond_graph()
    g2 = AutoCacheRule.add_caches(g, {ids["a"]})
    cachers = [
        n for n, op in g2.operators.items() if isinstance(op, Cacher)
    ]
    assert len(cachers) == 1
    cacher = cachers[0]
    assert g2.dependencies[cacher] == (ids["a"],)
    assert g2.dependencies[ids["b"]] == (cacher,)
    assert g2.dependencies[ids["c"]] == (cacher,)


def test_profile_nodes_produces_estimates():
    g, ids = _diamond_graph()
    profiles = profile_nodes(g, sorted(g.operators))
    assert ids["a"] in profiles
    assert profiles[ids["a"]].ns >= 0
