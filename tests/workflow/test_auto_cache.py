"""AutoCacheRule tests (reference: AutocCacheRuleSuite — cache-insertion
decisions with fake profiles)."""

import numpy as np
import pytest

from keystone_tpu.ops.util.cacher import Cacher
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.workflow.api import Pipeline, Transformer, transformer
from keystone_tpu.workflow.auto_cache import (
    AutoCacheRule,
    Profile,
    estimate_cached_runtime,
    get_node_weights,
    get_runs,
    profile_nodes,
)
from keystone_tpu.workflow.graph import EMPTY_GRAPH, NodeId
from keystone_tpu.workflow.operators import DatasetOperator, TransformerOperator


class _CountingOp(TransformerOperator):
    def __init__(self, weight=1):
        self.weight = weight
        self.calls = 0

    def single_transform(self, inputs):
        return inputs[0]

    def batch_transform(self, inputs):
        self.calls += 1
        return inputs[0]

    def eq_key(self):
        return id(self)


def _diamond_graph():
    """data -> a -> (b, c) where b and c both consume a (a runs twice)."""
    ds = Dataset.of(np.ones((8, 2), np.float32))
    g, d = EMPTY_GRAPH.add_node(DatasetOperator(ds), ())
    a_op = _CountingOp()
    g, a = g.add_node(a_op, (d,))
    g, b = g.add_node(_CountingOp(), (a,))
    g, c = g.add_node(_CountingOp(weight=3), (a,))
    g, s1 = g.add_sink(b)
    g, s2 = g.add_sink(c)
    return g, {"data": d, "a": a, "b": b, "c": c}


def test_get_runs_counts_consumer_passes():
    g, ids = _diamond_graph()
    weights = get_node_weights(g)
    runs = get_runs(g, set(), weights)
    # a is consumed by b (weight 1) and c (weight 3) -> 4 evaluations
    assert runs[ids["a"]] == 4
    # caching a brings it to one evaluation for runtime purposes
    rt_uncached = estimate_cached_runtime(
        g, set(), {ids["a"]: Profile(100, 10, 0)}, weights
    )
    rt_cached = estimate_cached_runtime(
        g, {ids["a"]}, {ids["a"]: Profile(100, 10, 0)}, weights
    )
    assert rt_uncached == 400 and rt_cached == 100


def test_aggressive_cache_selects_multiply_used():
    g, ids = _diamond_graph()
    rule = AutoCacheRule("aggressive")
    selected = rule.aggressive_cache(g, get_node_weights(g))
    assert ids["a"] in selected
    assert ids["b"] not in selected


def test_greedy_respects_budget():
    g, ids = _diamond_graph()
    rule = AutoCacheRule("greedy", mem_budget_bytes=5)
    profiles = {ids["a"]: Profile(100, 10, 0)}  # too big for budget
    assert rule.greedy_cache(g, profiles, get_node_weights(g)) == set()
    rule2 = AutoCacheRule("greedy", mem_budget_bytes=50)
    assert rule2.greedy_cache(g, profiles, get_node_weights(g)) == {
        ids["a"]
    }


def test_add_caches_inserts_cacher_between_node_and_children():
    g, ids = _diamond_graph()
    g2 = AutoCacheRule.add_caches(g, {ids["a"]})
    cachers = [
        n for n, op in g2.operators.items() if isinstance(op, Cacher)
    ]
    assert len(cachers) == 1
    cacher = cachers[0]
    assert g2.dependencies[cacher] == (ids["a"],)
    assert g2.dependencies[ids["b"]] == (cacher,)
    assert g2.dependencies[ids["c"]] == (cacher,)


def test_profile_nodes_produces_estimates():
    g, ids = _diamond_graph()
    profiles = profile_nodes(g, sorted(g.operators))
    assert ids["a"] in profiles
    assert profiles[ids["a"]].ns >= 0


# -- the reference suite's 13-node plan + profile staircase -------------
# (AutocCacheRuleSuite.scala:27-73: train branch 0->1->2->(3,4)->5->
# estimator(weight 4)->delegating; test branch 8..12 downstream of the
# source; greedy selections must follow the exact budget staircase)


class _Plus(TransformerOperator):
    def __init__(self, plus, weight=1):
        self.plus = plus
        self.weight = weight

    def single_transform(self, inputs):
        return inputs[0] + self.plus

    def batch_transform(self, inputs):
        return inputs[0].map_arrays(lambda a: a + self.plus)

    def eq_key(self):
        return ("plus", self.plus)

    def __repr__(self):
        return f"Plus({self.plus})"


class _WeightedEstimatorOp(TransformerOperator):
    """Stands in for the reference's weight-4 estimator node (only the
    weight matters to the cache rule)."""

    weight = 4

    def single_transform(self, inputs):
        return inputs[0]

    def batch_transform(self, inputs):
        return inputs[0]

    def eq_key(self):
        return id(self)


def _reference_plan():
    from keystone_tpu.workflow.graph import Graph, SinkId, SourceId
    from keystone_tpu.workflow.operators import DelegatingOperator

    ds = Dataset.of(np.arange(8, dtype=np.float32)[:, None])
    nid = {i: NodeId(i) for i in range(13)}
    g = Graph(
        sources=frozenset({SourceId(0)}),
        sink_dependencies={SinkId(0): nid[7]},
        operators={
            nid[0]: DatasetOperator(ds),
            nid[1]: _Plus(1),
            nid[2]: _Plus(2),
            nid[3]: _Plus(3),
            nid[4]: _Plus(4),
            nid[5]: _Plus(5),
            nid[6]: _WeightedEstimatorOp(),
            nid[7]: DelegatingOperator(),
            nid[8]: _Plus(8),
            nid[9]: _Plus(9),
            nid[10]: _Plus(10),
            nid[11]: _Plus(11),
            nid[12]: _Plus(12),
        },
        dependencies={
            nid[0]: (),
            nid[1]: (nid[0],),
            nid[2]: (nid[1],),
            nid[3]: (nid[2],),
            nid[4]: (nid[2],),
            nid[5]: (nid[3], nid[4]),
            nid[6]: (nid[5],),
            nid[7]: (nid[6], nid[12]),
            nid[8]: (SourceId(0),),
            nid[9]: (nid[8],),
            nid[10]: (nid[9],),
            nid[11]: (nid[9],),
            nid[12]: (nid[10], nid[11]),
        },
    )
    profiles = {
        nid[0]: Profile(10, float("inf"), 0),
        nid[1]: Profile(10, 50, 0),
        nid[2]: Profile(30, 200, 0),
        nid[3]: Profile(20, 1000, 0),
        nid[4]: Profile(20, 1000, 0),
        nid[5]: Profile(20, 100, 0),
    }
    return g, nid, profiles


def test_reference_plan_aggressive_selection():
    """Aggressive = direct-consumer weight sum > 1, source descendants
    excluded (AutocCacheRuleSuite 'Aggressive cacher': {+2, +5} — NOT
    the transitively-hot nodes 3/4, and NOT the twice-consumed test-
    branch node 9)."""
    g, nid, _ = _reference_plan()
    rule = AutoCacheRule("aggressive")
    assert rule.aggressive_cache(g, get_node_weights(g)) == {
        nid[2], nid[5]
    }


@pytest.mark.parametrize("budget,expected", [
    (10, set()),
    (75, {1}),
    (125, {5}),
    (175, {1, 5}),
    (350, {2, 5}),
    (10000, {2, 5}),
])
def test_reference_plan_greedy_staircase(budget, expected):
    """The six greedy budget selections of AutocCacheRuleSuite.scala:
    111-193, ported verbatim."""
    g, nid, profiles = _reference_plan()
    rule = AutoCacheRule("greedy", mem_budget_bytes=budget)
    got = rule.greedy_cache(g, profiles, get_node_weights(g))
    assert got == {nid[i] for i in expected}, (budget, got)
