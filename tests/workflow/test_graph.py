"""Graph IR surgery semantics (modeled on the reference GraphSuite)."""

import pytest

from keystone_tpu.workflow.graph import (
    EMPTY_GRAPH,
    NodeId,
    SinkId,
    SourceId,
    get_ancestors,
    get_children,
    get_descendants,
    get_parents,
    linearize,
)
from graph_test_helpers import op


def chain3():
    """source -> a -> b -> c -> sink"""
    g, src = EMPTY_GRAPH.add_source()
    g, a = g.add_node(op("a"), (src,))
    g, b = g.add_node(op("b"), (a,))
    g, c = g.add_node(op("c"), (b,))
    g, snk = g.add_sink(c)
    return g, src, a, b, c, snk


def test_add_node_and_ids():
    g, src = EMPTY_GRAPH.add_source()
    assert src == SourceId(0)
    g, a = g.add_node(op("a"), (src,))
    g, b = g.add_node(op("b"), (a,))
    assert (a, b) == (NodeId(0), NodeId(1))
    g, snk = g.add_sink(b)
    assert snk == SinkId(0)
    assert g.nodes == {a, b}
    assert g.get_dependencies(b) == (a,)


def test_immutability():
    g, src = EMPTY_GRAPH.add_source()
    g2, a = g.add_node(op("a"), (src,))
    assert a not in g.nodes
    assert a in g2.nodes


def test_remove_referenced_node_fails():
    g, src, a, b, c, snk = chain3()
    with pytest.raises(ValueError):
        g.remove_node(a)  # b depends on it
    with pytest.raises(ValueError):
        g.remove_node(c)  # sink depends on it
    with pytest.raises(ValueError):
        g.remove_source(src)


def test_replace_dependency():
    g, src, a, b, c, snk = chain3()
    g2 = g.replace_dependency(b, a)  # c now reads a directly
    assert g2.get_dependencies(c) == (a,)
    g3 = g2.remove_node(b)
    assert b not in g3.nodes


def test_add_graph_disjoint_union():
    g1, src1, a1, b1, c1, snk1 = chain3()
    g2, src2, a2, b2, c2, snk2 = chain3()
    merged, smap, kmap = g1.add_graph(g2)
    assert len(merged.nodes) == 6
    assert len(merged.sources) == 2
    assert len(merged.sinks) == 2
    # remapped ids are fresh
    assert smap[src2] != src1
    new_c = merged.get_sink_dependency(kmap[snk2])
    assert merged.get_operator(new_c).datum == "c"


def test_connect_graph_splices():
    g1, src1, a1, b1, c1, snk1 = chain3()
    g2, src2, a2, b2, c2, snk2 = chain3()
    merged, smap, kmap = g1.connect_graph(g2, {src2: snk1})
    # g2's source and g1's sink are gone; g2's 'a' now reads g1's 'c'
    assert len(merged.sources) == 1
    assert len(merged.sinks) == 1
    assert src2 not in smap
    new_a2 = None
    for n, deps in merged.dependencies.items():
        if merged.operators[n].datum == "a" and deps and deps[0] == c1:
            new_a2 = n
    assert new_a2 is not None


def test_analyses():
    g, src, a, b, c, snk = chain3()
    assert get_parents(g, c) == {b}
    assert get_ancestors(g, c) == {src, a, b}
    assert get_children(g, a) == {b}
    assert get_descendants(g, src) == {a, b, c, snk}
    order = linearize(g)
    assert order.index(src) < order.index(a) < order.index(b) < order.index(c)


def test_to_dot():
    g, *_ = chain3()
    dot = g.to_dot()
    assert dot.startswith("digraph")
    assert '"node0"' in dot and '"source0"' in dot


def test_replace_nodes():
    g, src, a, b, c, snk = chain3()
    # replacement subgraph: rsrc -> x -> rsink
    rg, rsrc = EMPTY_GRAPH.add_source()
    rg, x = rg.add_node(op("x"), (rsrc,))
    rg, rsnk = rg.add_sink(x)
    g2 = g.replace_nodes(
        nodes_to_remove={b},
        replacement=rg,
        replacement_source_splice={rsrc: a},
        replacement_sink_splice={b: rsnk},
    )
    assert b not in g2.nodes
    labels = {g2.operators[n].datum for n in g2.nodes}
    assert labels == {"a", "x", "c"}
    # c now depends on the new x node
    (cdep,) = g2.get_dependencies(c)
    assert g2.get_operator(cdep).datum == "x"
