"""Aux subsystem tests: prefix-state persistence, profiling hooks, CLI,
DOT export, external NLP wrappers."""

import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.utils.profiling import PhaseTimer, instrument_executor
from keystone_tpu.workflow.api import Pipeline, Transformer
from keystone_tpu.workflow.executor import PipelineEnv


import dataclasses

from keystone_tpu.workflow.api import Estimator


@dataclasses.dataclass(eq=False)
class _Demean(Transformer):
    """Module-level so FittedPipeline/state pickling works."""

    mu: float

    def apply(self, x):
        return x - self.mu


_FIT_CALLS = {"n": 0}


@dataclasses.dataclass(eq=False)
class _MeanEstimator(Estimator):
    def fit(self, data):
        _FIT_CALLS["n"] += 1
        return _Demean(float(np.asarray(data.array()).mean()))

    def eq_key(self):
        return ("mean_estimator",)


def test_prefix_state_persistence_across_reset(tmp_path, mesh8):
    """Fit once, persist, reset (simulating a new process), reload —
    the refit must be skipped (reference guarantee: 'Do not fit
    estimators multiple times' + FittedPipeline save/load)."""
    calls = _FIT_CALLS
    calls["n"] = 0
    MeanEstimator = _MeanEstimator

    data = Dataset.of(np.ones((8, 2), np.float32) * 5)
    est = MeanEstimator()
    pipe = est.with_data(data)
    out1 = pipe.apply(np.zeros((4, 2), np.float32)).get()
    assert calls["n"] == 1

    env = PipelineEnv.get_or_create()
    path = tmp_path / "state"  # save_state writes a directory
    env.save_state(str(path))
    env.reset()

    n = env.load_state(str(path))
    assert n >= 1
    # rebuild the same pipeline structure over the same data object
    pipe2 = MeanEstimator().with_data(data)
    out2 = pipe2.apply(np.zeros((4, 2), np.float32)).get()
    assert calls["n"] == 1  # loaded state: no refit
    np.testing.assert_allclose(
        np.asarray(out1.array()), np.asarray(out2.array())
    )


def test_phase_timer_and_instrumentation(mesh8):
    timer = PhaseTimer("test")
    with timer.phase("work"):
        pass
    assert "work" in timer.times

    from keystone_tpu.ops.stats import LinearRectifier

    pipe = LinearRectifier(0.0).to_pipeline()
    result = pipe.apply(np.ones((4, 3), np.float32))
    times = instrument_executor(result._executor)
    result.get()
    assert len(times) >= 1


def test_dot_export(mesh8):
    from keystone_tpu.ops.stats import LinearRectifier, NormalizeRows

    pipe = LinearRectifier(0.0).and_then(NormalizeRows())
    dot = pipe.to_dot()
    assert "digraph" in dot


def test_cli_help():
    from keystone_tpu.__main__ import main

    assert main(["--help"]) == 0
    assert main(["NoSuchApp"]) == 2


def test_external_nlp_wrappers():
    from keystone_tpu.ops.nlp.external import (
        NER,
        CoreNLPFeatureExtractor,
        POSTagger,
    )

    # defaults work out of the box (rule-based annotators)
    assert POSTagger().apply(["hello"]) == [("hello", "NN")]
    tagged = POSTagger(annotator=lambda ts: ["X"] * len(ts)).apply(
        ["a", "b"]
    )
    assert tagged == [("a", "X"), ("b", "X")]
    assert NER().apply(["hello"]) == ["O"]
    grams = CoreNLPFeatureExtractor(orders=[1]).apply("Dogs running fast")
    assert ["dog"] in grams or ["dogs"] in grams


def test_optimizer_rule_trace_logging(caplog):
    """Each effective rule application logs a node-count delta (reference:
    RuleExecutor.scala:44-50 logs the plan after every rule)."""
    import logging

    from keystone_tpu.ops.stats import LinearRectifier, NormalizeRows
    from keystone_tpu.parallel.dataset import Dataset

    # two identical branches -> CSE has something to merge
    a = LinearRectifier(0.0).and_then(NormalizeRows())
    b = LinearRectifier(0.0).and_then(NormalizeRows())
    from keystone_tpu.workflow.api import Pipeline

    pipe = Pipeline.gather([a, b])
    with caplog.at_level(logging.INFO, logger="keystone_tpu.workflow.rules"):
        import numpy as np

        pipe.apply(Dataset.from_array(np.ones((4, 3), np.float32))).get()
    merges = [
        r for r in caplog.records if "EquivalentNodeMergeRule" in r.message
    ]
    assert merges, "CSE merge should have been logged"
    assert "-> " in merges[0].getMessage()


def test_save_state_large_arrays_per_file_and_budget(tmp_path):
    """Large arrays persist to individual .npy files (streamed, not one
    monolithic pickle) and max_total_bytes drops over-budget entries."""
    import os

    from keystone_tpu.workflow.executor import PipelineEnv
    from keystone_tpu.workflow.expressions import DatasetExpression
    from keystone_tpu.parallel.dataset import Dataset

    env = PipelineEnv.get_or_create()
    big = np.ones((600, 600), np.float32)  # 1.44 MB > 1 MB threshold
    small = np.ones((4, 4), np.float32)
    env.state["bigp"] = DatasetExpression.of(Dataset.from_array(big))
    env.state["smallp"] = DatasetExpression.of(Dataset.from_array(small))
    # force both
    env.state["bigp"].get(); env.state["smallp"].get()

    d = tmp_path / "state"
    env.save_state(str(d))
    npys = [f for f in os.listdir(d) if f.endswith(".npy")]
    assert len(npys) == 1  # only the big array got its own file
    env.reset()
    assert env.load_state(str(d)) == 2
    restored = env.state["bigp"].get().padded()
    np.testing.assert_allclose(np.asarray(restored), big)

    # budget smaller than the big array: entry dropped, small kept
    env.reset()
    env.state["bigp"] = DatasetExpression.of(Dataset.from_array(big))
    env.state["smallp"] = DatasetExpression.of(Dataset.from_array(small))
    env.state["bigp"].get(); env.state["smallp"].get()
    d2 = tmp_path / "state2"
    env.save_state(str(d2), max_total_bytes=1 << 20)
    env.reset()
    assert env.load_state(str(d2)) == 1
    assert "smallp" in env.state and "bigp" not in env.state
