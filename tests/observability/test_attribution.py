"""The per-model device-cost ledger: charge accumulation, the
row-claim queue, CSE fair-split weights, the exact sum invariant
(per-model charges sum to engine totals), document shapes, and the
reconstruction from exported ``keystone_attr_*`` samples."""

import math

import pytest

from keystone_tpu.observability.attribution import (
    CELL_FIELDS,
    AttributionLedger,
    EngineAttribution,
    RowClaimQueue,
    attribution_document,
    attribution_from_samples,
)
from keystone_tpu.observability.registry import MetricsRegistry


# -- RowClaimQueue ---------------------------------------------------------


def test_claim_queue_drains_fifo():
    q = RowClaimQueue()
    q.claim("a", 2)
    q.claim("b", 3)
    assert q.drain(2) == {"a": 2.0}
    assert q.drain(3) == {"b": 3.0}
    assert len(q) == 0


def test_claim_queue_splits_partial_claims_across_windows():
    # a 4-row claim split over two 2-row dispatch windows must charge
    # 2 rows to each window, never 4 to the first
    q = RowClaimQueue()
    q.claim("a", 4)
    assert q.drain(2) == {"a": 2.0}
    assert q.drain(2) == {"a": 2.0}
    assert q.drain(2) == {}


def test_claim_queue_merges_same_model_within_a_window():
    q = RowClaimQueue()
    q.claim("a", 1)
    q.claim("b", 1)
    q.claim("a", 1)
    assert q.drain(3) == {"a": 2.0, "b": 1.0}


def test_claim_queue_fractional_claims():
    # predict_many claims 1/len(members) per member — fractions must
    # survive the FIFO intact
    q = RowClaimQueue()
    q.claim("a", 0.5)
    q.claim("b", 0.5)
    assert q.drain(1) == {"a": 0.5, "b": 0.5}


# -- ledger charges + registry export --------------------------------------


def test_ledger_charges_accumulate_and_total():
    led = AttributionLedger()
    led.charge("a", device_seconds=1.0, goodput_rows=4)
    led.charge("a", device_seconds=0.5)
    led.charge("b", goodput_rows=2)
    assert led.per_model()["a"]["device_seconds"] == pytest.approx(1.5)
    assert led.per_model()["a"]["goodput_rows"] == pytest.approx(4)
    assert led.totals()["goodput_rows"] == pytest.approx(6)
    assert sorted(led.models()) == ["a", "b"]


def test_ledger_registry_export_absent_not_zero():
    reg = MetricsRegistry()
    led = AttributionLedger()
    led.register(reg)
    led.charge("a", device_seconds=0.25, device_flops=100.0)
    led.set_staging_bytes("a", 2048)
    from keystone_tpu.observability import prometheus

    body = prometheus.render(reg.collect())
    assert (
        'keystone_attr_device_seconds_total{model="a"} 0.25' in body
    )
    assert (
        'keystone_attr_device_flops_total{model="a"} 100' in body
    )
    assert 'keystone_attr_staging_bytes{model="a"} 2048' in body
    # never-charged fields stay ABSENT for the model, not zero
    assert 'keystone_attr_h2d_bytes_total{model="a"}' not in body


# -- EngineAttribution: the sum invariant ----------------------------------


def _totals_match(led, expect):
    totals = led.totals()
    for field, want in expect.items():
        got = totals[field]
        rel = abs(got - want) / abs(want) if want else abs(got)
        assert rel <= 1e-6, (field, got, want)


def test_solo_engine_charges_everything_to_its_model():
    led = AttributionLedger()
    binding = EngineAttribution(led, ["only"])
    binding.on_dispatch(8, n_valid=5, padded=3, flops=1000.0,
                        seconds=0.5, h2d_bytes=64)
    assert led.per_model()["only"]["goodput_rows"] == pytest.approx(5)
    assert led.per_model()["only"]["padded_rows"] == pytest.approx(3)
    assert led.per_model()["only"]["device_seconds"] == pytest.approx(0.5)


def test_shared_engine_row_share_split_sums_exactly():
    """Without split cost models the fair split degrades to pure row
    share — and per-model charges still sum EXACTLY to what the engine
    recorded, whatever the interleaving."""
    led = AttributionLedger()
    q = RowClaimQueue()
    binding = EngineAttribution(led, ["a", "b"], shares_fn=q.drain)
    totals = {f: 0.0 for f in CELL_FIELDS}
    for i in range(7):
        q.claim("a", 2)
        q.claim("b", 1)
        binding.on_dispatch(4, n_valid=3, padded=1,
                            flops=100.0 * (i + 1), seconds=0.01 * i,
                            h2d_bytes=96)
        totals["goodput_rows"] += 3
        totals["padded_rows"] += 1
        totals["dispatches"] += 1
        totals["device_flops"] += 100.0 * (i + 1)
        totals["device_seconds"] += 0.01 * i
        totals["h2d_bytes"] += 96
    _totals_match(led, totals)
    # 2:1 row claims -> 2:1 goodput
    assert led.per_model()["a"]["goodput_rows"] == pytest.approx(14)
    assert led.per_model()["b"]["goodput_rows"] == pytest.approx(7)


def test_shared_engine_split_cost_fair_split():
    """With a split cost model, the shared prefix's FLOPs are
    apportioned by row share while each head's own FLOPs stay with its
    model: w[m] = rowshare[m] * prefix + head[m], normalized. The sum
    invariant must hold bit-for-bit regardless."""
    led = AttributionLedger()
    q = RowClaimQueue()
    binding = EngineAttribution(
        led, ["a", "b"], shares_fn=q.drain,
        # prefix 1000 FLOPs, head a 300, head b 100
        split_cost_fn=lambda bucket: (1000.0, {"a": 300.0, "b": 100.0}),
    )
    q.claim("a", 3)
    q.claim("b", 1)
    binding.on_dispatch(4, n_valid=4, padded=0, flops=1400.0,
                        seconds=1.0, h2d_bytes=0)
    # w_a = 0.75*1000 + 300 = 1050; w_b = 0.25*1000 + 100 = 350
    assert led.per_model()["a"]["device_flops"] == pytest.approx(
        1400.0 * 1050 / 1400
    )
    assert led.per_model()["b"]["device_flops"] == pytest.approx(
        1400.0 * 350 / 1400
    )
    assert led.per_model()["a"]["device_seconds"] == pytest.approx(0.75)
    _totals_match(led, {"device_flops": 1400.0, "device_seconds": 1.0,
                        "goodput_rows": 4.0, "dispatches": 1.0})


def test_pending_seconds_split_on_complete():
    """The pipelined path reports seconds at completion, not dispatch:
    the binding must remember the dispatched windows' weights and
    split the completion-timed seconds with THEM, not with whatever
    the claim queue holds by then. One completion covers EVERY
    dispatch since the last sync point, so a two-window sync splits
    by the summed weights."""
    led = AttributionLedger()
    q = RowClaimQueue()
    binding = EngineAttribution(led, ["a", "b"], shares_fn=q.drain)
    q.claim("a", 4)
    binding.on_dispatch(4, n_valid=4, padded=0, flops=0.0,
                        seconds=None, h2d_bytes=0)
    q.claim("b", 4)
    q.claim("b", 4)
    binding.on_dispatch(8, n_valid=8, padded=0, flops=0.0,
                        seconds=None, h2d_bytes=0)
    # windows a:1.0 and b:1.0 pending -> the 1.5 s covering both
    # splits evenly, untouched by whatever was claimed afterwards
    q.claim("a", 100)
    binding.on_complete(1.5)
    assert led.per_model()["a"]["device_seconds"] == pytest.approx(0.75)
    assert led.per_model()["b"]["device_seconds"] == pytest.approx(0.75)
    _totals_match(led, {"device_seconds": 1.5})


def test_per_window_completions_pair_with_their_dispatch():
    """Serial lanes sync once per window: dispatch -> complete ->
    dispatch -> complete keeps each window's seconds with that
    window's models."""
    led = AttributionLedger()
    q = RowClaimQueue()
    binding = EngineAttribution(led, ["a", "b"], shares_fn=q.drain)
    q.claim("a", 4)
    binding.on_dispatch(4, n_valid=4, padded=0, flops=0.0,
                        seconds=None, h2d_bytes=0)
    binding.on_complete(1.0)
    q.claim("b", 4)
    binding.on_dispatch(4, n_valid=4, padded=0, flops=0.0,
                        seconds=None, h2d_bytes=0)
    binding.on_complete(0.5)
    assert led.per_model()["a"]["device_seconds"] == pytest.approx(1.0)
    assert led.per_model()["b"]["device_seconds"] == pytest.approx(0.5)
    _totals_match(led, {"device_seconds": 1.5})


# -- documents -------------------------------------------------------------


def test_attribution_document_shares_and_topk():
    led = AttributionLedger()
    led.charge("a", device_seconds=3.0, device_flops=3e9,
               goodput_rows=30, padded_rows=0, dispatches=3)
    led.charge("b", device_seconds=1.0, device_flops=1e9,
               goodput_rows=5, padded_rows=5, dispatches=1)
    doc = attribution_document(led, top_k=1)
    assert doc["totals"]["device_seconds"] == pytest.approx(4.0)
    a = doc["models"]["a"]
    assert a["device_seconds_share"] == pytest.approx(0.75)
    assert a["goodput_fraction"] == pytest.approx(1.0)
    assert doc["models"]["b"]["goodput_fraction"] == pytest.approx(0.5)
    assert math.isclose(
        sum(m["device_seconds_share"]
            for m in doc["models"].values()),
        1.0,
    )
    assert len(doc["top"]) == 1 and doc["top"][0]["model"] == "a"


def test_attribution_from_samples_round_trips():
    """The admin endpoint and the fleet router rebuild the document
    from exported samples — the reconstruction must agree with the
    ledger's own document."""
    reg = MetricsRegistry()
    led = AttributionLedger()
    led.register(reg)
    led.charge("a", device_seconds=2.0, device_flops=4e9,
               goodput_rows=20, dispatches=2, h2d_bytes=512)
    led.charge("b", device_seconds=2.0, goodput_rows=10, dispatches=1)
    led.set_staging_bytes("a", 4096)
    from keystone_tpu.observability import prometheus

    samples = prometheus.parse_samples(
        prometheus.render(reg.collect())
    )
    rebuilt = attribution_from_samples(samples)
    direct = attribution_document(led)
    assert rebuilt["totals"] == direct["totals"]
    assert rebuilt["models"]["a"]["device_seconds"] == pytest.approx(
        direct["models"]["a"]["device_seconds"]
    )
    assert rebuilt["models"]["a"]["staging_bytes"] == 4096
    assert "staging_bytes" not in rebuilt["models"]["b"]


def test_attribution_from_samples_ignores_foreign_families():
    rebuilt = attribution_from_samples(
        [("keystone_gateway_inflight", {"gateway": "g"}, 3.0)]
    )
    assert rebuilt["models"] == {}
