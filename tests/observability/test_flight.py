"""Flight recorder: tail-sampling decisions, span-tree capture by
trace id, bounded forensic ring, Chrome-trace round-trip, /debugz
rendering, and the disabled fast path."""

import json

from keystone_tpu.observability.flight import (
    FlightRecorder,
    debugz_status,
    find_record,
)
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.observability.tracing import Tracer


def traced_request(tracer, slow=False):
    """One request-shaped span tree; returns its trace id."""
    with tracer.span("gateway.admit", gateway="t") as admit:
        with tracer.span("microbatch.coalesce", window=1):
            with tracer.span("serving.dispatch", bucket=4):
                pass
    return admit.trace_id


def make_recorder(tracer, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return FlightRecorder(tracer=tracer, **kw)


class TestTailSampling:
    def test_breach_captures_full_span_tree(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.1)
        trace_id = traced_request(tr)
        record = rec.maybe_capture(trace_id, duration_s=0.5)
        assert record is not None and record.reason == "slo_breach"
        assert record.trace_id == trace_id
        names = {s.name for s in record.spans}
        assert names == {
            "gateway.admit", "microbatch.coalesce", "serving.dispatch",
        }
        # parent links intact inside the captured tree
        by_name = {s.name: s for s in record.spans}
        assert (
            by_name["serving.dispatch"].parent_id
            == by_name["microbatch.coalesce"].span_id
        )

    def test_fast_request_not_captured(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.1)
        trace_id = traced_request(tr)
        assert rec.maybe_capture(trace_id, duration_s=0.01) is None
        assert rec.records() == []

    def test_error_captures_regardless_of_latency(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.1)
        trace_id = traced_request(tr)
        record = rec.maybe_capture(
            trace_id, duration_s=0.001,
            error=RuntimeError("lane exploded"),
        )
        assert record.reason == "error"
        assert "lane exploded" in record.attrs["error"]

    def test_per_call_threshold_overrides_default(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=10.0)
        trace_id = traced_request(tr)
        record = rec.maybe_capture(
            trace_id, duration_s=0.2, threshold_s=0.1
        )
        assert record is not None
        assert record.attrs["threshold_ms"] == 100.0

    def test_no_threshold_no_latency_capture(self):
        tr = Tracer()
        rec = make_recorder(tr)  # no threshold configured anywhere
        trace_id = traced_request(tr)
        assert rec.maybe_capture(trace_id, duration_s=100.0) is None

    def test_disabled_recorder_captures_nothing(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.0, enabled=False)
        trace_id = traced_request(tr)
        assert rec.maybe_capture(trace_id, duration_s=1.0) is None
        assert rec.records() == []

    def test_extra_attrs_ride_along(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.1)
        trace_id = traced_request(tr)
        record = rec.maybe_capture(
            trace_id, duration_s=0.5, gateway="gw0", lane=1
        )
        assert record.attrs["gateway"] == "gw0"
        assert record.attrs["lane"] == 1

    def test_capture_counter_by_reason(self):
        tr = Tracer()
        reg = MetricsRegistry()
        rec = FlightRecorder(
            tracer=tr, latency_threshold_s=0.1, registry=reg
        )
        rec.maybe_capture(traced_request(tr), duration_s=0.5)
        rec.maybe_capture(
            traced_request(tr), duration_s=0.0, error=ValueError("x")
        )
        c = reg.counter(
            "keystone_flight_records_total", "", ("reason",)
        )
        assert c.get(("slo_breach",)) == 1
        assert c.get(("error",)) == 1


class TestRingAndQueries:
    def test_ring_is_bounded(self):
        tr = Tracer()
        rec = make_recorder(tr, capacity=3, latency_threshold_s=0.0)
        ids = [traced_request(tr) for _ in range(6)]
        for tid in ids:
            rec.maybe_capture(tid, duration_s=1.0)
        kept = [r.trace_id for r in rec.records()]
        assert kept == ids[-3:]  # oldest evicted, order preserved

    def test_find_and_clear(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.0)
        tid = traced_request(tr)
        rec.maybe_capture(tid, duration_s=1.0)
        assert rec.find(tid).trace_id == tid
        assert rec.find("nope") is None
        rec.clear()
        assert rec.records() == []

    def test_module_level_debugz_view(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.0)
        tid = traced_request(tr)
        rec.maybe_capture(tid, duration_s=1.0, gateway="gw-z")
        doc = debugz_status()
        assert any(r["trace_id"] == tid for r in doc["records"])
        # filtered view
        doc = debugz_status(trace_id=tid)
        assert [r["trace_id"] for r in doc["records"]] == [tid]
        assert find_record(tid) is not None

    def test_record_to_dict_is_json_able(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.0)
        rec.maybe_capture(traced_request(tr), duration_s=0.25)
        (record,) = rec.records()
        doc = json.loads(json.dumps(record.to_dict()))
        assert doc["reason"] == "slo_breach"
        assert doc["duration_ms"] == 250.0
        assert len(doc["spans"]) == 3
        assert all(s["trace_id"] == doc["trace_id"] for s in doc["spans"])


class TestChromeTrace:
    def test_record_round_trips_to_chrome_trace(self):
        tr = Tracer()
        rec = make_recorder(tr, latency_threshold_s=0.0)
        tid = traced_request(tr)
        record = rec.maybe_capture(tid, duration_s=1.0)
        doc = json.loads(json.dumps(record.to_chrome_trace()))
        assert set(doc) == {"traceEvents", "displayTimeUnit"}
        events = doc["traceEvents"]
        span_events = [e for e in events if e["ph"] == "X"]
        assert len(span_events) == 3
        for e in span_events:
            assert e["args"]["trace_id"] == tid
            assert isinstance(e["ts"], float)
            assert isinstance(e["dur"], float)
        # the capture verdict rides as an instant event
        (marker,) = [e for e in events if e["ph"] == "i"]
        assert marker["name"] == "flight:slo_breach"
