"""SLO burn-rate evaluation: windowed deltas over cumulative series,
latency/availability constructors, gauges, /slz rendering, and the
sampling thread."""

import threading

import pytest

from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.observability.slo import (
    Slo,
    SloMonitor,
    monitors,
    slz_status,
)


def make_counting_slo(name="api", target=0.99):
    """An Slo over a hand-cranked cumulative (total, bad) pair."""
    state = {"total": 0.0, "bad": 0.0}

    def read():
        return state["total"], state["bad"]

    return Slo(name, target, read), state


class TestBurnRateMath:
    def test_burn_is_bad_fraction_over_budget(self):
        slo, state = make_counting_slo(target=0.99)  # budget 1%
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        mon.sample(now=0.0)
        state["total"], state["bad"] = 100.0, 2.0  # 2% bad in-window
        mon.sample(now=10.0)
        burns = mon.burn_rates("api")
        # 2% bad against a 1% budget = burn 2.0, on both windows (the
        # slow window falls back to the oldest sample while young)
        assert burns["fast"] == pytest.approx(2.0)
        assert burns["slow"] == pytest.approx(2.0)

    def test_burn_one_means_budget_exactly(self):
        slo, state = make_counting_slo(target=0.999)  # budget 0.1%
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        mon.sample(now=0.0)
        state["total"], state["bad"] = 1000.0, 1.0
        mon.sample(now=10.0)
        assert mon.burn_rates("api")["fast"] == pytest.approx(1.0)

    def test_no_traffic_burns_nothing(self):
        slo, state = make_counting_slo()
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        mon.sample(now=0.0)
        mon.sample(now=10.0)  # no deltas
        assert mon.burn_rates("api")["fast"] == 0.0

    def test_single_sample_has_no_burn_yet(self):
        slo, _ = make_counting_slo()
        mon = SloMonitor(registry=MetricsRegistry())
        mon.add(slo)
        mon.sample(now=0.0)
        assert mon.burn_rates("api") == {"fast": None, "slow": None}

    def test_fast_window_recovers_while_slow_remembers(self):
        """The multiwindow point: after a burst stops, the fast burn
        falls to 0 quickly while the slow window still shows it."""
        slo, state = make_counting_slo(target=0.99)
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=1000,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        mon.sample(now=0.0)
        state["total"], state["bad"] = 100.0, 50.0  # the burst
        mon.sample(now=5.0)
        state["total"] = 200.0  # clean traffic afterwards
        mon.sample(now=30.0)
        burns = mon.burn_rates("api")
        # fast window (last 10 s) saw 100 clean requests, 0 bad
        assert burns["fast"] == 0.0
        # slow window still contains the burst: 50/200 bad / 1% budget
        assert burns["slow"] == pytest.approx(25.0)
        assert not mon.breaching("api")  # fast recovered -> not both

    def test_breaching_needs_both_windows(self):
        slo, state = make_counting_slo(target=0.99)
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        mon.sample(now=0.0)
        state["total"], state["bad"] = 100.0, 50.0
        mon.sample(now=10.0)
        assert mon.breaching("api")  # young: both windows see the burst

    def test_history_pruned_beyond_slow_window(self):
        slo, state = make_counting_slo()
        mon = SloMonitor(
            fast_window_s=1, slow_window_s=10,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        for t in range(100):
            state["total"] += 10
            mon.sample(now=float(t))
        # one baseline older than the slow window + in-window samples
        assert len(mon._samples["api"]) <= 13

    def test_counter_reset_does_not_go_negative(self):
        slo, state = make_counting_slo()
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100,
            registry=MetricsRegistry(),
        )
        mon.add(slo)
        state["total"], state["bad"] = 100.0, 10.0
        mon.sample(now=0.0)
        state["total"], state["bad"] = 150.0, 0.0  # bad "reset"
        mon.sample(now=10.0)
        assert mon.burn_rates("api")["fast"] >= 0.0


class TestConstructors:
    def test_latency_slo_reads_histogram_buckets(self):
        reg = MetricsRegistry()
        hist = reg.histogram(
            "lat", "l", ("gw",), buckets=(0.1, 0.25, 1.0)
        )
        slo = Slo.latency(
            "gw:latency", hist, threshold_s=0.25, target=0.9,
            labels=("g0",),
        )
        assert slo.threshold_s == 0.25  # on a bucket edge: exact
        for v in (0.05, 0.2, 0.25):  # all good (le 0.25 is inclusive)
            hist.observe(v, ("g0",))
        hist.observe(0.5, ("g0",))  # bad
        total, bad = slo.read()
        assert (total, bad) == (4.0, 1.0)

    def test_latency_threshold_snaps_up_to_bucket_resolution(self):
        reg = MetricsRegistry()
        hist = reg.histogram("lat2", "l", (), buckets=(0.1, 1.0))
        slo = Slo.latency("x", hist, threshold_s=0.5, target=0.9)
        assert slo.threshold_s == 1.0  # smallest bound >= 0.5
        assert "declared 500ms" in slo.description

    def test_availability_slo_reads_outcome_counter(self):
        reg = MetricsRegistry()
        c = reg.counter("req", "r", ("gw", "status"))
        slo = Slo.availability(
            "gw:avail", c, target=0.999, base_labels=("g0",)
        )
        c.inc(("g0", "ok"), by=95)
        c.inc(("g0", "shed"), by=3)  # deliberate, not "bad" by default
        c.inc(("g0", "error"), by=2)
        total, bad = slo.read()
        assert (total, bad) == (100.0, 2.0)

    def test_latency_threshold_beyond_buckets_rejected(self):
        """A threshold past the largest finite bucket would snap to
        +Inf — every observation counts as good and the objective can
        never burn. Fail loud at declaration time instead."""
        reg = MetricsRegistry()
        hist = reg.histogram("lat3", "l", (), buckets=(0.1, 1.0))
        with pytest.raises(ValueError, match="unobservable"):
            Slo.latency("x", hist, threshold_s=5.0, target=0.9)

    def test_target_must_be_a_fraction(self):
        with pytest.raises(ValueError):
            Slo("x", 1.0, lambda: (0, 0))
        with pytest.raises(ValueError):
            Slo("x", 0.0, lambda: (0, 0))

    def test_duplicate_name_rejected(self):
        mon = SloMonitor(registry=MetricsRegistry())
        slo, _ = make_counting_slo()
        mon.add(slo)
        with pytest.raises(ValueError):
            mon.add(make_counting_slo()[0])


class TestExportAndStatus:
    def test_burn_gauge_exported(self):
        reg = MetricsRegistry()
        slo, state = make_counting_slo(target=0.99)
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100, registry=reg
        )
        mon.add(slo)
        mon.sample(now=0.0)
        state["total"], state["bad"] = 100.0, 2.0
        mon.sample(now=10.0)
        fams = {f.name: f for f in reg.collect()}
        fam = fams["keystone_slo_burn_rate"]
        cells = {
            (s.labels["slo"], s.labels["window"]): s.value
            for s in fam.samples
        }
        assert cells[("api", "fast")] == pytest.approx(2.0)

    def test_status_and_slz_render(self):
        reg = MetricsRegistry()
        slo, state = make_counting_slo()
        mon = SloMonitor(
            fast_window_s=10, slow_window_s=100, registry=reg
        )
        mon.add(slo)
        state["total"] = 10.0
        mon.sample(now=0.0)
        status = mon.status()
        (entry,) = status["slos"]
        assert entry["name"] == "api"
        assert entry["total"] == 10.0
        assert entry["burn_rate"] == {"fast": None, "slow": None}
        # module-level view (the /slz source) includes this monitor
        assert mon in monitors()
        assert any(
            s["name"] == "api" for s in slz_status()["slos"]
        )

    def test_listener_fires_per_sample(self):
        mon = SloMonitor(registry=MetricsRegistry())
        slo, _ = make_counting_slo()
        mon.add(slo)
        hits = []
        mon.add_listener(lambda m: hits.append(m))
        mon.sample(now=0.0)
        mon.sample(now=1.0)
        assert hits == [mon, mon]

    def test_broken_listener_does_not_stop_sampling(self):
        mon = SloMonitor(registry=MetricsRegistry())
        slo, state = make_counting_slo()
        mon.add(slo)

        def boom(m):
            raise RuntimeError("listener bug")

        mon.add_listener(boom)
        mon.sample(now=0.0)
        state["total"] = 5.0
        mon.sample(now=1.0)  # must not raise
        assert mon.burn_rates("api")["fast"] == 0.0


def test_sampling_thread_runs_and_stops():
    mon = SloMonitor(
        fast_window_s=0.05, slow_window_s=1.0,
        registry=MetricsRegistry(),
    )
    slo, state = make_counting_slo()
    mon.add(slo)
    sampled = threading.Event()
    mon.add_listener(lambda m: sampled.set())
    mon.start(interval_s=0.01)
    try:
        assert sampled.wait(5.0), "sampler thread never fired"
    finally:
        mon.stop()
    assert mon._thread is None
