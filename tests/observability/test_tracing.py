"""Span tracer: parent links, bounded ring, Chrome trace export."""

import json
import threading

from keystone_tpu.observability.tracing import (
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
)


def test_span_nesting_records_parent_links():
    tr = Tracer()
    with tr.span("outer") as outer:
        with tr.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        with tr.span("sibling") as sib:
            assert sib.parent_id == outer.span_id
    spans = {s.name: s for s in tr.recent()}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["sibling"].parent_id == spans["outer"].span_id
    assert spans["outer"].parent_id is None
    # children finish before their parent
    names = [s.name for s in tr.recent()]
    assert names.index("inner") < names.index("outer")


def test_span_attrs_and_set_attr():
    tr = Tracer()
    with tr.span("work", bucket=8) as sp:
        sp.set_attr("rows", 5)
    (done,) = tr.recent()
    assert done.attrs == {"bucket": 8, "rows": 5}
    assert done.duration_s >= 0


def test_ring_is_bounded():
    tr = Tracer(capacity=10)
    for i in range(25):
        with tr.span(f"s{i}"):
            pass
    spans = tr.recent()
    assert len(spans) == 10
    assert spans[-1].name == "s24"  # most recent kept
    assert tr.recent(3)[0].name == "s22"


def test_disabled_tracer_records_nothing():
    tr = Tracer(enabled=False)
    with tr.span("invisible") as sp:
        sp.set_attr("k", "v")  # no-op, no crash
    assert tr.recent() == []
    assert tr.start_span("also_invisible").span_id is None


def test_parent_links_are_thread_local():
    tr = Tracer()
    seen = {}

    def worker(name):
        with tr.span(name):
            pass

    with tr.span("main_outer"):
        t = threading.Thread(target=worker, args=("other_thread",))
        t.start()
        t.join()
    spans = {s.name: s for s in tr.recent()}
    # the other thread's span must NOT parent under main's open span
    assert spans["other_thread"].parent_id is None
    assert spans["other_thread"].thread_id != spans["main_outer"].thread_id


def test_chrome_trace_structure_loads_as_json(tmp_path):
    tr = Tracer()
    with tr.span("outer", engine="e0"):
        with tr.span("inner"):
            pass
    doc = tr.to_chrome_trace()
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    assert len(events) == 2
    for e in events:
        assert e["ph"] == "X"  # complete events
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert "span_id" in e["args"] and "parent_id" in e["args"]
    by_name = {e["name"]: e for e in events}
    assert (
        by_name["inner"]["args"]["parent_id"]
        == by_name["outer"]["args"]["span_id"]
    )
    # inner nests temporally within outer
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]

    path = tr.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        reloaded = json.load(f)
    assert reloaded["traceEvents"][0]["name"] in ("outer", "inner")


def test_global_tracer_enable_disable():
    tr = get_tracer()
    assert tr is get_tracer()
    try:
        enable_tracing()
        assert tr.enabled
        with tr.span("global_span"):
            pass
        assert any(s.name == "global_span" for s in tr.recent())
    finally:
        disable_tracing()
        tr.clear()
    assert not tr.enabled


def test_out_of_order_end_is_tolerated():
    tr = Tracer()
    a = tr.start_span("a")
    b = tr.start_span("b")
    tr.end_span(a)  # ended before its child
    tr.end_span(b)
    assert {s.name for s in tr.recent()} == {"a", "b"}


# -- trace ids (request identity across the span tree) ---------------------


def test_trace_id_shared_down_the_tree():
    tr = Tracer()
    with tr.span("root") as root:
        assert root.trace_id is not None and len(root.trace_id) == 32
        with tr.span("child") as child:
            assert child.trace_id == root.trace_id
    spans = {s.name: s for s in tr.recent()}
    assert spans["child"].trace_id == spans["root"].trace_id


def test_separate_roots_get_separate_traces():
    tr = Tracer()
    with tr.span("a") as a:
        pass
    with tr.span("b") as b:
        pass
    assert a.trace_id != b.trace_id


def test_pinned_cross_thread_parent_joins_the_trace():
    """The gateway chain: the admit span ENDS before the coalesce span
    starts on another thread, yet the pinned parent_id must carry the
    trace id across."""
    tr = Tracer()
    with tr.span("gateway.admit") as admit:
        pass  # finished before the dispatcher thread runs

    def dispatcher():
        with tr.span("microbatch.coalesce", parent_id=admit.span_id):
            with tr.span("serving.dispatch"):
                pass

    t = threading.Thread(target=dispatcher)
    t.start()
    t.join()
    spans = {s.name: s for s in tr.recent()}
    assert spans["microbatch.coalesce"].trace_id == admit.trace_id
    assert spans["serving.dispatch"].trace_id == admit.trace_id
    assert tr.spans_for_trace(admit.trace_id) == tr.recent()


def test_unknown_pinned_parent_roots_a_new_trace():
    tr = Tracer()
    with tr.span("orphan", parent_id=999_999_999) as sp:
        pass
    assert sp.trace_id is not None
    (done,) = tr.recent()
    assert done.parent_id == 999_999_999


def test_spans_for_trace_filters_the_ring():
    tr = Tracer()
    with tr.span("t1") as a:
        pass
    with tr.span("t2"):
        pass
    only = tr.spans_for_trace(a.trace_id)
    assert [s.name for s in only] == ["t1"]
    assert tr.spans_for_trace("") == []


def test_chrome_trace_args_carry_trace_id():
    tr = Tracer()
    with tr.span("x") as sp:
        pass
    (event,) = tr.to_chrome_trace()["traceEvents"]
    assert event["args"]["trace_id"] == sp.trace_id


# -- sinks -----------------------------------------------------------------


def test_sink_sees_finished_spans_and_unhooks():
    tr = Tracer()
    seen = []
    tr.add_sink(seen.append)
    with tr.span("observed"):
        pass
    assert [s.name for s in seen] == ["observed"]
    tr.remove_sink(seen.append)
    with tr.span("unobserved"):
        pass
    assert len(seen) == 1


def test_broken_sink_does_not_break_spans():
    tr = Tracer()

    def boom(span):
        raise RuntimeError("exporter bug")

    tr.add_sink(boom)
    with tr.span("survives"):
        pass
    assert [s.name for s in tr.recent()] == ["survives"]


# -- enable_tracing capacity swap vs concurrent writers --------------------


def test_enable_tracing_capacity_swap_is_atomic_with_writers():
    """Regression: enable_tracing(capacity=...) rebuilt the global
    ring via deque(old, maxlen=new) WITHOUT the tracer lock — a
    concurrent end_span could append mid-copy (RuntimeError: deque
    mutated during iteration) or land its span in the doomed old ring.
    The swap now happens under the tracer lock."""
    tr = enable_tracing()
    tr.clear()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            try:
                span = tr.start_span(f"w{i}")
                tr.end_span(span)
            except Exception as e:  # the pre-fix failure mode
                errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=writer) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        # hammer the resize path against the writers
        for round_ in range(200):
            enable_tracing(capacity=64 + (round_ % 2))
    finally:
        stop.set()
        for t in threads:
            t.join()
        disable_tracing()
        tr.clear()
    assert errors == []


def test_enable_tracing_preserves_recent_spans_across_resize():
    tr = enable_tracing(capacity=8)
    try:
        tr.clear()
        with tr.span("keep-me"):
            pass
        enable_tracing(capacity=16)
        assert any(s.name == "keep-me" for s in tr.recent())
        assert tr._ring.maxlen == 16
    finally:
        disable_tracing()
        tr.clear()


# -- W3C trace context (the cross-process wire format) ----------------------


def test_traceparent_round_trips():
    from keystone_tpu.observability.tracing import (
        format_traceparent,
        parse_traceparent,
    )

    tid = "0af7651916cd43dd8448eb211c80319c"
    header = format_traceparent(tid, 0x00F067AA0BA902B7)
    assert header == f"00-{tid}-00f067aa0ba902b7-01"
    ctx = parse_traceparent(header)
    assert ctx.trace_id == tid
    assert ctx.parent_span_id == "00f067aa0ba902b7"
    assert ctx.flags == "01"


def test_traceparent_rejects_malformed_and_all_zero():
    from keystone_tpu.observability.tracing import parse_traceparent

    tid = "0af7651916cd43dd8448eb211c80319c"
    bad = [
        None,
        "",
        "garbage",
        f"00-{tid}-00f067aa0ba902b7",          # missing flags
        f"zz-{tid}-00f067aa0ba902b7-01",        # non-hex version
        f"ff-{tid}-00f067aa0ba902b7-01",        # forbidden version
        "00-" + "0" * 32 + "-00f067aa0ba902b7-01",  # zero trace id
        f"00-{tid}-" + "0" * 16 + "-01",        # zero parent id
        f"00-{tid[:30]}-00f067aa0ba902b7-01",   # short trace id
        # version 00 defines EXACTLY four fields; trailing data means
        # restart-the-trace, not adopt-and-ignore
        f"00-{tid}-00f067aa0ba902b7-01-extra",
    ]
    for header in bad:
        assert parse_traceparent(header) is None, header
    # uppercase input normalizes (the spec says lowercase on the wire,
    # receivers are lenient)
    assert parse_traceparent(
        f"00-{tid.upper()}-00F067AA0BA902B7-01"
    ).trace_id == tid


def test_start_span_adopts_explicit_trace_id():
    """An explicit trace_id (an inbound traceparent's) roots the local
    chain under the REMOTE trace: children inherit it through both the
    thread stack and cross-thread parent pinning."""
    from keystone_tpu.observability.tracing import Tracer

    tr = Tracer(enabled=True)
    tid = "ab" * 16
    root = tr.start_span("gateway.admit", trace_id=tid)
    assert root.trace_id == tid
    with tr.span("inner") as inner:
        assert inner.trace_id == tid
        assert inner.parent_id == root.span_id
    tr.end_span(root)
    # cross-thread pinning joins the adopted trace too
    pinned = tr.start_span("microbatch.coalesce", parent_id=root.span_id)
    assert pinned.trace_id == tid
    tr.end_span(pinned)
    assert {s.trace_id for s in tr.spans_for_trace(tid)} == {tid}


def test_disabled_tracer_span_accepts_trace_id():
    from keystone_tpu.observability.tracing import Tracer

    tr = Tracer(enabled=False)
    span = tr.start_span("gateway.admit", trace_id="cd" * 16)
    assert span.trace_id is None  # the shared null span records nothing
    with tr.span("x", trace_id="cd" * 16) as s:
        assert s.trace_id is None
    assert tr.recent() == []
