"""Span tracer: parent links, bounded ring, Chrome trace export."""

import json
import threading

from keystone_tpu.observability.tracing import (
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
)


def test_span_nesting_records_parent_links():
    tr = Tracer()
    with tr.span("outer") as outer:
        with tr.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        with tr.span("sibling") as sib:
            assert sib.parent_id == outer.span_id
    spans = {s.name: s for s in tr.recent()}
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["sibling"].parent_id == spans["outer"].span_id
    assert spans["outer"].parent_id is None
    # children finish before their parent
    names = [s.name for s in tr.recent()]
    assert names.index("inner") < names.index("outer")


def test_span_attrs_and_set_attr():
    tr = Tracer()
    with tr.span("work", bucket=8) as sp:
        sp.set_attr("rows", 5)
    (done,) = tr.recent()
    assert done.attrs == {"bucket": 8, "rows": 5}
    assert done.duration_s >= 0


def test_ring_is_bounded():
    tr = Tracer(capacity=10)
    for i in range(25):
        with tr.span(f"s{i}"):
            pass
    spans = tr.recent()
    assert len(spans) == 10
    assert spans[-1].name == "s24"  # most recent kept
    assert tr.recent(3)[0].name == "s22"


def test_disabled_tracer_records_nothing():
    tr = Tracer(enabled=False)
    with tr.span("invisible") as sp:
        sp.set_attr("k", "v")  # no-op, no crash
    assert tr.recent() == []
    assert tr.start_span("also_invisible").span_id is None


def test_parent_links_are_thread_local():
    tr = Tracer()
    seen = {}

    def worker(name):
        with tr.span(name):
            pass

    with tr.span("main_outer"):
        t = threading.Thread(target=worker, args=("other_thread",))
        t.start()
        t.join()
    spans = {s.name: s for s in tr.recent()}
    # the other thread's span must NOT parent under main's open span
    assert spans["other_thread"].parent_id is None
    assert spans["other_thread"].thread_id != spans["main_outer"].thread_id


def test_chrome_trace_structure_loads_as_json(tmp_path):
    tr = Tracer()
    with tr.span("outer", engine="e0"):
        with tr.span("inner"):
            pass
    doc = tr.to_chrome_trace()
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    assert len(events) == 2
    for e in events:
        assert e["ph"] == "X"  # complete events
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert "span_id" in e["args"] and "parent_id" in e["args"]
    by_name = {e["name"]: e for e in events}
    assert (
        by_name["inner"]["args"]["parent_id"]
        == by_name["outer"]["args"]["span_id"]
    )
    # inner nests temporally within outer
    assert by_name["inner"]["ts"] >= by_name["outer"]["ts"]

    path = tr.export_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        reloaded = json.load(f)
    assert reloaded["traceEvents"][0]["name"] in ("outer", "inner")


def test_global_tracer_enable_disable():
    tr = get_tracer()
    assert tr is get_tracer()
    try:
        enable_tracing()
        assert tr.enabled
        with tr.span("global_span"):
            pass
        assert any(s.name == "global_span" for s in tr.recent())
    finally:
        disable_tracing()
        tr.clear()
    assert not tr.enabled


def test_out_of_order_end_is_tolerated():
    tr = Tracer()
    a = tr.start_span("a")
    b = tr.start_span("b")
    tr.end_span(a)  # ended before its child
    tr.end_span(b)
    assert {s.name for s in tr.recent()} == {"a", "b"}
