"""Cross-process trace stitching, unit level (no sockets): span-id
qualification, grafting, the phase-decomposition arithmetic, partial
handling + its counter, and the ``keystone_request_phase_seconds``
federation golden strings."""

import pytest

from keystone_tpu.observability import prometheus
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.observability.stitch import (
    PHASES,
    TraceStitcher,
    phase_decomposition,
    qualify_spans,
)
from keystone_tpu.observability.tracing import Tracer

TID = "ab" * 16


def span_dict(
    name,
    span_id,
    start_s,
    duration_ms,
    parent_id=None,
    process=None,
    **attrs,
):
    d = {
        "name": name,
        "span_id": span_id,
        "parent_id": parent_id,
        "trace_id": TID,
        "start_s": start_s,
        "duration_ms": duration_ms,
        "thread_id": 1,
        "attrs": attrs,
    }
    if process is not None:
        d["process"] = process
    return d


# -- qualification -----------------------------------------------------------


def test_qualify_namespaces_ids_and_degrades_unknown_parents():
    spans = qualify_spans(
        [
            span_dict("a", 1, 0.0, 1.0),
            span_dict("b", 2, 0.0, 1.0, parent_id=1),
            span_dict("c", 3, 0.0, 1.0, parent_id=99),  # fell out
        ],
        "p0",
    )
    by_name = {s["name"]: s for s in spans}
    assert by_name["a"]["span_id"] == "p0:1"
    assert by_name["a"]["parent_id"] is None
    assert by_name["b"]["parent_id"] == "p0:1"
    assert by_name["c"]["parent_id"] is None
    assert all(s["process"] == "p0" for s in spans)


# -- phase arithmetic --------------------------------------------------------


def _stitched_spans():
    """A hand-built two-process trace with known numbers (seconds):
    forward [0.000, 0.100]; replica admit starts 0.010, coalesce
    [0.030, +20ms], dispatch [0.050, +30ms] ending the envelope at
    0.080."""
    return (
        qualify_spans(
            [
                span_dict(
                    "router.forward", 1, 0.0, 100.0,
                    router="r", replica="host:1",
                ),
            ],
            "r",
        )
        + qualify_spans(
            [
                span_dict("gateway.admit", 1, 0.010, 5.0),
                span_dict("microbatch.coalesce", 2, 0.030, 20.0),
                span_dict("serving.dispatch", 3, 0.050, 30.0),
            ],
            "replica:host:1",
        )
    )


def test_phase_decomposition_partitions_the_forward_duration():
    doc = phase_decomposition(_stitched_spans(), "r")
    assert doc["total_ms"] == 100.0
    ph = doc["phases_ms"]
    assert set(ph) == set(PHASES)
    # envelope = 0.010 -> 0.080 = 70ms; hop = 100 - 70 = 30
    assert ph["router_hop"] == pytest.approx(30.0)
    # coalesce start - admit start
    assert ph["queue_wait"] == pytest.approx(20.0)
    assert ph["coalesce"] == pytest.approx(20.0)
    assert ph["device"] == pytest.approx(30.0)
    # remainder
    assert ph["deliver"] == pytest.approx(0.0)
    assert sum(ph.values()) == pytest.approx(doc["total_ms"])


def test_phase_decomposition_staged_lanes_use_upload_plus_compute():
    spans = qualify_spans(
        [
            span_dict(
                "router.forward", 1, 0.0, 100.0,
                router="r", replica="host:1",
            ),
        ],
        "r",
    ) + qualify_spans(
        [
            span_dict("gateway.admit", 1, 0.000, 5.0),
            span_dict("microbatch.coalesce", 2, 0.010, 10.0),
            span_dict("pipeline.host_prep", 3, 0.020, 10.0),
            span_dict("pipeline.upload", 4, 0.030, 10.0),
            span_dict("pipeline.compute", 5, 0.040, 30.0),
            span_dict("pipeline.deliver", 6, 0.070, 20.0),
        ],
        "replica:host:1",
    )
    ph = phase_decomposition(spans, "r")["phases_ms"]
    assert ph["device"] == pytest.approx(40.0)  # upload + compute
    assert ph["queue_wait"] == pytest.approx(10.0)
    assert ph["coalesce"] == pytest.approx(10.0)
    # envelope 0 -> 90ms; hop 10; deliver = 100-10-10-10-40 = 30
    assert ph["router_hop"] == pytest.approx(10.0)
    assert ph["deliver"] == pytest.approx(30.0)


def test_phase_decomposition_router_only_is_hop_only():
    """A partial (router-side) trace reports ONLY the hop: the replica
    phases are unknown, not zero — absent, per the repo's
    absent-not-zero doctrine, so partial stitches can't drag the
    federated phase quantiles toward 0."""
    spans = qualify_spans(
        [
            span_dict(
                "router.forward", 1, 0.0, 42.0,
                router="r", replica="host:1",
            ),
        ],
        "r",
    )
    doc = phase_decomposition(spans, "r")
    assert doc["phases_ms"] == {"router_hop": 42.0}


def test_phase_decomposition_empty_is_none():
    assert phase_decomposition([], "r")["total_ms"] is None


def test_negative_clock_skew_cannot_go_negative():
    """A replica whose wall clock is AHEAD (envelope appears after the
    forward window) must clamp hop/queue to zero, not negative."""
    spans = qualify_spans(
        [
            span_dict(
                "router.forward", 1, 0.0, 10.0,
                router="r", replica="host:1",
            ),
        ],
        "r",
    ) + qualify_spans(
        [
            # skewed 1000s into the future, envelope wider than total
            span_dict("gateway.admit", 1, 1000.0, 30.0),
            span_dict("microbatch.coalesce", 2, 999.9, 5.0),
        ],
        "replica:host:1",
    )
    ph = phase_decomposition(spans, "r")["phases_ms"]
    assert all(v >= 0.0 for v in ph.values()), ph


# -- the stitcher over a real tracer ----------------------------------------


def _forwarding_tracer(name="r0", replica="h:1"):
    tracer = Tracer(enabled=True)
    span = tracer.start_span(
        "router.forward", trace_id=TID, router=name,
        replica=replica, attempt=0,
    )
    tracer.end_span(span)
    return tracer


def test_stitch_unknown_replica_counts_partial():
    reg = MetricsRegistry()
    stitcher = TraceStitcher(
        name="r0", tracer=_forwarding_tracer(), registry=reg
    )
    stitched = stitcher.stitch(TID, lambda name: None)
    assert stitched.partial is True
    assert stitched.processes == ["r0"]
    assert "not in the registry" in stitched.partial_detail[0]
    counter = reg.counter(
        "keystone_trace_stitch_partial_total", "", ("reason",)
    )
    assert counter.get(("unknown_replica",)) == 1


def test_stitch_unreachable_replica_counts_partial():
    reg = MetricsRegistry()
    stitcher = TraceStitcher(
        name="r0", tracer=_forwarding_tracer(), registry=reg,
        fetch_timeout_s=0.3,
    )
    # nothing listens on this port — the fetch must fail fast and
    # degrade, never raise out of the stitch
    stitched = stitcher.stitch(
        TID, lambda name: "http://127.0.0.1:9"
    )
    assert stitched.partial is True
    counter = reg.counter(
        "keystone_trace_stitch_partial_total", "", ("reason",)
    )
    assert counter.get(("unreachable",)) == 1
    # the document still renders (router-side tree + hop-only phases)
    assert stitched.to_dict()["phases_ms"]["router_hop"] > 0


def test_stitch_unknown_trace_is_none_and_document_404s():
    reg = MetricsRegistry()
    stitcher = TraceStitcher(
        name="r0", tracer=Tracer(enabled=True), registry=reg
    )
    assert stitcher.stitch("cd" * 16, lambda name: None) is None
    code, doc = stitcher.document("cd" * 16, "", lambda name: None)
    assert code == 404
    code, doc = stitcher.document(None, "", lambda name: None)
    assert code == 400


def test_stitch_records_phase_histogram():
    reg = MetricsRegistry()
    stitcher = TraceStitcher(
        name="r0", tracer=_forwarding_tracer(), registry=reg
    )
    stitcher.stitch(TID, lambda name: None)
    text = prometheus.render(reg.collect())
    assert 'keystone_request_phase_seconds_count{phase="router_hop"} 1' in text
    # a PARTIAL stitch measured only the hop: the replica phases are
    # unknown and must stay ABSENT from the family, not appear as 0.0
    # observations dragging the federated quantiles down
    for phase in PHASES:
        if phase != "router_hop":
            assert f'phase="{phase}"' not in text


def test_restitching_a_trace_does_not_multiply_count_phases():
    """The histogram is per-REQUEST: an operator re-querying /debugz
    (or asking for format=chrome after the JSON) must not skew the
    family toward investigated requests."""
    reg = MetricsRegistry()
    stitcher = TraceStitcher(
        name="r0", tracer=_forwarding_tracer(), registry=reg
    )
    for _ in range(3):
        stitcher.stitch(TID, lambda name: None)
    text = prometheus.render(reg.collect())
    assert 'keystone_request_phase_seconds_count{phase="router_hop"} 1' in text


def test_phases_read_only_the_winning_replicas_clock():
    """A retried trace carries a FAILED attempt's spans from another
    replica (possibly another host, skewed clock): the decomposition
    must restrict itself to the winning attempt's replica — the
    failed attempt's spans can't manufacture phantom queue time."""
    spans = qualify_spans(
        [
            span_dict(
                "router.forward", 1, 0.0, 20.0,
                router="r", replica="dead:1", attempt=0,
                error="untyped 500",
            ),
            span_dict(
                "router.forward", 2, 0.025, 100.0,
                router="r", replica="win:2", attempt=1,
            ),
        ],
        "r",
    ) + qualify_spans(
        # the failed replica's half, on a clock 500s ahead
        [
            span_dict("gateway.admit", 1, 500.0, 5.0),
            span_dict("microbatch.coalesce", 2, 500.1, 5.0),
        ],
        "replica:dead:1",
    ) + qualify_spans(
        [
            span_dict("gateway.admit", 1, 0.035, 5.0),
            span_dict("microbatch.coalesce", 2, 0.055, 20.0),
            span_dict("serving.dispatch", 3, 0.075, 30.0),
        ],
        "replica:win:2",
    )
    doc = phase_decomposition(spans, "r")
    assert doc["total_ms"] == 100.0
    ph = doc["phases_ms"]
    # winner envelope 0.035 -> 0.105 = 70ms; hop 30; queue 20
    assert ph["router_hop"] == pytest.approx(30.0)
    assert ph["queue_wait"] == pytest.approx(20.0)
    assert sum(ph.values()) == pytest.approx(100.0)


# -- federation golden strings ----------------------------------------------


def test_phase_family_federates_by_summing_le_buckets():
    """Two processes' ``keystone_request_phase_seconds`` expositions
    merge into one fleet family: identical-label bucket/count/sum
    samples SUM (the merge_expositions contract every other le family
    rides) — asserted against golden strings."""

    def exposition(ms_values):
        reg = MetricsRegistry()
        stitcher = TraceStitcher(name="r", tracer=None, registry=reg)
        for ms in ms_values:
            stitcher._phases.observe(ms / 1e3, ("device",))
        return prometheus.render(reg.collect())

    a = exposition([0.4, 30.0])   # -> le 0.0005 and le 0.05
    b = exposition([30.0])
    merged = prometheus.merge_expositions([a, b], on_conflict="drop")
    golden = [
        'keystone_request_phase_seconds_bucket{le="0.0005",phase="device"} 1',
        'keystone_request_phase_seconds_bucket{le="0.025",phase="device"} 1',
        'keystone_request_phase_seconds_bucket{le="0.05",phase="device"} 3',
        'keystone_request_phase_seconds_bucket{le="+Inf",phase="device"} 3',
        'keystone_request_phase_seconds_count{phase="device"} 3',
    ]
    for line in golden:
        assert line in merged, (line, merged)
    # and the summed _sum (0.0304 + 0.03, float arithmetic verbatim)
    (sum_line,) = [
        line for line in merged.splitlines()
        if line.startswith("keystone_request_phase_seconds_sum")
    ]
    assert float(sum_line.rsplit(" ", 1)[1]) == pytest.approx(0.0604)
