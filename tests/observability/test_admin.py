"""Admin endpoint end-to-end (ephemeral port, fast) + stack-wide
integration: a live engine's counters in /metrics, executor node spans
in /tracez with parent links, Chrome trace export of a serving run.
"""

import json
import urllib.request

import numpy as np
import pytest

from keystone_tpu.observability import (
    AdminServer,
    MetricsRegistry,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
)


def _get(server, path):
    with urllib.request.urlopen(server.url(path), timeout=10) as resp:
        return resp.status, resp.headers, resp.read().decode("utf-8")


@pytest.fixture
def traced():
    tracer = enable_tracing()
    tracer.clear()
    yield tracer
    disable_tracing()
    tracer.clear()


def test_healthz_and_404():
    with AdminServer(registry=MetricsRegistry(), tracer=Tracer()) as srv:
        status, _, body = _get(srv, "/healthz")
        assert status == 200 and body == "ok\n"
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(srv, "/nope")
        assert e.value.code == 404


def test_metrics_scrape_content_type_and_body():
    reg = MetricsRegistry()
    reg.counter("hits_total", "hits", ("path",)).inc(("/x",), by=3)
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        status, headers, body = _get(srv, "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
    assert 'hits_total{path="/x"} 3' in body


def test_metrics_negotiates_openmetrics_for_exemplars():
    """A scraper sending the OpenMetrics Accept header (a real
    Prometheus server does by default) gets exemplar tails + # EOF;
    a plain scrape of the same registry stays classic v0.0.4 text
    with no mid-line '#' to trip the old parser."""
    reg = MetricsRegistry()
    reg.histogram("lat_s", "l", buckets=(1.0,)).observe(
        0.5, trace_id="tid42"
    )
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        req = urllib.request.Request(
            srv.url("/metrics"),
            headers={"Accept": "application/openmetrics-text"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            om_ctype = resp.headers["Content-Type"]
            om_body = resp.read().decode("utf-8")
        _, _, plain_body = _get(srv, "/metrics")
    assert om_ctype.startswith("application/openmetrics-text")
    assert '# {trace_id="tid42"}' in om_body
    assert om_body.endswith("# EOF\n")
    assert "# {" not in plain_body


def test_varz_json():
    reg = MetricsRegistry()
    reg.gauge("depth").set(2)
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        _, headers, body = _get(srv, "/varz")
    assert headers["Content-Type"].startswith("application/json")
    doc = json.loads(body)
    assert doc["depth"]["values"][0]["value"] == 2.0


def test_live_engine_scrape_end_to_end(traced):
    """Acceptance: GET /metrics on a live engine returns Prometheus text
    with per-bucket compile/dispatch counters and latency quantiles;
    /tracez shows the dispatch spans."""
    from keystone_tpu.serving.bench import build_pipeline

    reg = MetricsRegistry()
    fitted = build_pipeline(d=8, hidden=8, depth=2)
    engine = fitted.compiled(buckets=(4, 8))
    label = engine.metrics.register(registry=reg, engine="test-engine")
    assert label == "test-engine"
    rng = np.random.default_rng(0)
    engine.apply(rng.standard_normal((3, 8)).astype(np.float32), sync=True)
    engine.apply(rng.standard_normal((7, 8)).astype(np.float32), sync=True)

    with AdminServer(registry=reg, tracer=get_tracer()) as srv:
        _, _, metrics = _get(srv, "/metrics")
        _, _, tracez = _get(srv, "/tracez")
        _, _, healthz = _get(srv, "/healthz")

    assert healthz == "ok\n"
    want = [
        'keystone_serving_compiles_total{engine="test-engine",bucket="4"} 1',
        'keystone_serving_compiles_total{engine="test-engine",bucket="8"} 1',
        'keystone_serving_dispatches_total{engine="test-engine",bucket="4"} 1',
        'keystone_serving_dispatches_total{engine="test-engine",bucket="8"} 1',
        'keystone_serving_request_size_total{engine="test-engine",size="3"} 1',
        'keystone_serving_dispatch_latency_seconds{engine="test-engine",'
        'quantile="0.5"}',
        'keystone_serving_dispatch_latency_seconds{engine="test-engine",'
        'quantile="0.99"}',
        'keystone_serving_dispatch_latency_seconds_count'
        '{engine="test-engine"} 2',
        'keystone_serving_examples_total{engine="test-engine"} 10',
    ]
    for line in want:
        assert line in metrics, f"missing {line!r} in:\n{metrics}"

    spans = json.loads(tracez)["spans"]
    dispatches = [s for s in spans if s["name"] == "serving.dispatch"]
    assert len(dispatches) == 2
    assert {d["attrs"]["bucket"] for d in dispatches} == {4, 8}


def test_executor_node_spans_in_tracez_with_parent_links(traced, mesh8):
    """Acceptance: workflow executor node spans appear in /tracez with
    parent links (the consumer that demanded a node is its parent)."""
    from keystone_tpu.ops.stats import LinearRectifier, NormalizeRows

    pipe = LinearRectifier(0.0).and_then(NormalizeRows())
    pipe.apply(np.ones((4, 3), np.float32)).get()

    with AdminServer(registry=MetricsRegistry(), tracer=get_tracer()) as srv:
        _, _, body = _get(srv, "/tracez")
    doc = json.loads(body)
    assert doc["enabled"] is True
    nodes = [s for s in doc["spans"] if s["name"].startswith("node:")]
    assert len(nodes) >= 2
    by_id = {s["span_id"]: s for s in nodes}
    linked = [
        s for s in nodes
        if s["parent_id"] is not None and s["parent_id"] in by_id
    ]
    assert linked, f"want node->node parent links, got {nodes}"
    # every node span carries its own wall time
    assert all("self_ms" in s["attrs"] for s in nodes)


def test_chrome_trace_export_of_serving_run(traced, tmp_path):
    """Acceptance: a recorded serving run exports Chrome trace JSON
    that is structurally loadable (traceEvents of complete "X" events
    with numeric ts/dur) — the chrome://tracing / Perfetto format."""
    from keystone_tpu.serving import MicroBatcher
    from keystone_tpu.serving.bench import build_pipeline

    fitted = build_pipeline(d=8, hidden=8, depth=2)
    engine = fitted.compiled(buckets=(4,))
    engine.warmup(example=np.zeros((8,), np.float32))
    with MicroBatcher(engine, max_delay_ms=1.0) as mb:
        futs = [
            mb.submit(np.ones((8,), np.float32)) for _ in range(3)
        ]
        for f in futs:
            f.result(timeout=30)

    path = str(tmp_path / "serving_trace.json")
    get_tracer().export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events, "serving run recorded no spans"
    assert all(e["ph"] == "X" for e in events)
    assert all(
        isinstance(e["ts"], (int, float))
        and isinstance(e["dur"], (int, float))
        for e in events
    )
    names = {e["name"] for e in events}
    assert "serving.dispatch" in names
    assert "microbatch.coalesce" in names
    # the dispatch span parents under its coalesce window
    coalesce_ids = {
        e["args"]["span_id"]
        for e in events
        if e["name"] == "microbatch.coalesce"
    }
    dispatch_parents = {
        e["args"]["parent_id"]
        for e in events
        if e["name"] == "serving.dispatch"
    }
    assert dispatch_parents & coalesce_ids

    # /tracez?format=chrome serves the same document
    with AdminServer(registry=MetricsRegistry(), tracer=get_tracer()) as srv:
        _, _, body = _get(srv, "/tracez?format=chrome")
    assert {e["name"] for e in json.loads(body)["traceEvents"]} == names


def test_disabled_admin_means_no_server_and_no_spans():
    """The whole plane is off by default: the global tracer records
    nothing and engine construction alone opens no sockets (nothing to
    assert beyond: tracer off, span() is the null object)."""
    tracer = get_tracer()
    assert not tracer.enabled
    before = len(tracer.recent())
    with tracer.span("ghost"):
        pass
    assert len(tracer.recent()) == before


def test_varz_build_info_block():
    reg = MetricsRegistry()
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        _, _, body = _get(srv, "/varz")
        _, _, metrics = _get(srv, "/metrics")
    build = json.loads(body)["build"]
    for key in (
        "git_sha", "start_time_unix_s", "uptime_s", "pid",
        "python_version", "jax_version", "device_kind",
    ):
        assert key in build, f"missing {key} in build block: {build}"
    assert build["uptime_s"] >= 0
    # identity also on the scrape surface: constant info gauge +
    # standard process start time
    assert "# TYPE keystone_build_info gauge" in metrics
    assert 'keystone_build_info{git_sha="' in metrics
    assert "keystone_process_start_time_seconds" in metrics
    # the detected device table rides the build block (cached one-time
    # like the rest) and the scrape carries the device info gauge +
    # the memory sampler's family (host-RAM fallback on CPU backends)
    assert build["devices"], build
    assert build["devices"][0]["platform"] == "cpu"
    assert "peak_flops" in build["devices"][0]
    assert 'keystone_device_info{kind="' in metrics
    assert "keystone_device_memory_bytes{" in metrics


def test_slz_endpoint_renders_monitors():
    from keystone_tpu.observability.slo import Slo, SloMonitor

    reg = MetricsRegistry()
    mon = SloMonitor(
        fast_window_s=10, slow_window_s=100, registry=reg
    )
    state = {"total": 0.0, "bad": 0.0}
    mon.add(
        Slo(
            "adminz:api", 0.99,
            lambda: (state["total"], state["bad"]),
        )
    )
    mon.sample(now=0.0)
    state["total"], state["bad"] = 10.0, 1.0  # 10% bad in-window
    mon.sample(now=10.0)
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        _, headers, body = _get(srv, "/slz")
    assert headers["Content-Type"].startswith("application/json")
    doc = json.loads(body)
    (entry,) = [
        s for s in doc["slos"] if s["name"] == "adminz:api"
    ]
    assert entry["burn_rate"]["fast"] == pytest.approx(10.0)  # 10%/1%
    assert entry["breaching"] is True


def test_debugz_endpoint_lists_and_dumps_records(traced):
    from keystone_tpu.observability.flight import FlightRecorder

    reg = MetricsRegistry()
    rec = FlightRecorder(
        tracer=traced, latency_threshold_s=0.05, registry=reg
    )
    with traced.span("gateway.admit") as admit:
        with traced.span("serving.dispatch"):
            pass
    rec.maybe_capture(admit.trace_id, duration_s=0.2, gateway="gw-a")
    with AdminServer(registry=reg, tracer=traced) as srv:
        _, _, body = _get(srv, "/debugz")
        _, _, one = _get(srv, f"/debugz?trace_id={admit.trace_id}")
        _, _, chrome = _get(
            srv, f"/debugz?trace_id={admit.trace_id}&format=chrome"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(srv, "/debugz?trace_id=deadbeef&format=chrome")
    assert e.value.code == 404
    doc = json.loads(body)
    assert doc["recorders"] >= 1
    assert any(r["trace_id"] == admit.trace_id for r in doc["records"])
    (record,) = json.loads(one)["records"]
    assert record["reason"] == "slo_breach"
    assert {s["name"] for s in record["spans"]} == {
        "gateway.admit", "serving.dispatch",
    }
    chrome_doc = json.loads(chrome)
    assert {e["name"] for e in chrome_doc["traceEvents"]} >= {
        "gateway.admit", "serving.dispatch",
    }
