"""Admin endpoint end-to-end (ephemeral port, fast) + stack-wide
integration: a live engine's counters in /metrics, executor node spans
in /tracez with parent links, Chrome trace export of a serving run.
"""

import json
import urllib.request

import numpy as np
import pytest

from keystone_tpu.observability import (
    AdminServer,
    MetricsRegistry,
    Tracer,
    disable_tracing,
    enable_tracing,
    get_tracer,
)


def _get(server, path):
    with urllib.request.urlopen(server.url(path), timeout=10) as resp:
        return resp.status, resp.headers, resp.read().decode("utf-8")


@pytest.fixture
def traced():
    tracer = enable_tracing()
    tracer.clear()
    yield tracer
    disable_tracing()
    tracer.clear()


def test_healthz_and_404():
    with AdminServer(registry=MetricsRegistry(), tracer=Tracer()) as srv:
        status, _, body = _get(srv, "/healthz")
        assert status == 200 and body == "ok\n"
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(srv, "/nope")
        assert e.value.code == 404


def test_metrics_scrape_content_type_and_body():
    reg = MetricsRegistry()
    reg.counter("hits_total", "hits", ("path",)).inc(("/x",), by=3)
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        status, headers, body = _get(srv, "/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain; version=0.0.4")
    assert 'hits_total{path="/x"} 3' in body


def test_varz_json():
    reg = MetricsRegistry()
    reg.gauge("depth").set(2)
    with AdminServer(registry=reg, tracer=Tracer()) as srv:
        _, headers, body = _get(srv, "/varz")
    assert headers["Content-Type"].startswith("application/json")
    doc = json.loads(body)
    assert doc["depth"]["values"][0]["value"] == 2.0


def test_live_engine_scrape_end_to_end(traced):
    """Acceptance: GET /metrics on a live engine returns Prometheus text
    with per-bucket compile/dispatch counters and latency quantiles;
    /tracez shows the dispatch spans."""
    from keystone_tpu.serving.bench import build_pipeline

    reg = MetricsRegistry()
    fitted = build_pipeline(d=8, hidden=8, depth=2)
    engine = fitted.compiled(buckets=(4, 8))
    label = engine.metrics.register(registry=reg, engine="test-engine")
    assert label == "test-engine"
    rng = np.random.default_rng(0)
    engine.apply(rng.standard_normal((3, 8)).astype(np.float32), sync=True)
    engine.apply(rng.standard_normal((7, 8)).astype(np.float32), sync=True)

    with AdminServer(registry=reg, tracer=get_tracer()) as srv:
        _, _, metrics = _get(srv, "/metrics")
        _, _, tracez = _get(srv, "/tracez")
        _, _, healthz = _get(srv, "/healthz")

    assert healthz == "ok\n"
    want = [
        'keystone_serving_compiles_total{engine="test-engine",bucket="4"} 1',
        'keystone_serving_compiles_total{engine="test-engine",bucket="8"} 1',
        'keystone_serving_dispatches_total{engine="test-engine",bucket="4"} 1',
        'keystone_serving_dispatches_total{engine="test-engine",bucket="8"} 1',
        'keystone_serving_request_size_total{engine="test-engine",size="3"} 1',
        'keystone_serving_dispatch_latency_seconds{engine="test-engine",'
        'quantile="0.5"}',
        'keystone_serving_dispatch_latency_seconds{engine="test-engine",'
        'quantile="0.99"}',
        'keystone_serving_dispatch_latency_seconds_count'
        '{engine="test-engine"} 2',
        'keystone_serving_examples_total{engine="test-engine"} 10',
    ]
    for line in want:
        assert line in metrics, f"missing {line!r} in:\n{metrics}"

    spans = json.loads(tracez)["spans"]
    dispatches = [s for s in spans if s["name"] == "serving.dispatch"]
    assert len(dispatches) == 2
    assert {d["attrs"]["bucket"] for d in dispatches} == {4, 8}


def test_executor_node_spans_in_tracez_with_parent_links(traced, mesh8):
    """Acceptance: workflow executor node spans appear in /tracez with
    parent links (the consumer that demanded a node is its parent)."""
    from keystone_tpu.ops.stats import LinearRectifier, NormalizeRows

    pipe = LinearRectifier(0.0).and_then(NormalizeRows())
    pipe.apply(np.ones((4, 3), np.float32)).get()

    with AdminServer(registry=MetricsRegistry(), tracer=get_tracer()) as srv:
        _, _, body = _get(srv, "/tracez")
    doc = json.loads(body)
    assert doc["enabled"] is True
    nodes = [s for s in doc["spans"] if s["name"].startswith("node:")]
    assert len(nodes) >= 2
    by_id = {s["span_id"]: s for s in nodes}
    linked = [
        s for s in nodes
        if s["parent_id"] is not None and s["parent_id"] in by_id
    ]
    assert linked, f"want node->node parent links, got {nodes}"
    # every node span carries its own wall time
    assert all("self_ms" in s["attrs"] for s in nodes)


def test_chrome_trace_export_of_serving_run(traced, tmp_path):
    """Acceptance: a recorded serving run exports Chrome trace JSON
    that is structurally loadable (traceEvents of complete "X" events
    with numeric ts/dur) — the chrome://tracing / Perfetto format."""
    from keystone_tpu.serving import MicroBatcher
    from keystone_tpu.serving.bench import build_pipeline

    fitted = build_pipeline(d=8, hidden=8, depth=2)
    engine = fitted.compiled(buckets=(4,))
    engine.warmup(example=np.zeros((8,), np.float32))
    with MicroBatcher(engine, max_delay_ms=1.0) as mb:
        futs = [
            mb.submit(np.ones((8,), np.float32)) for _ in range(3)
        ]
        for f in futs:
            f.result(timeout=30)

    path = str(tmp_path / "serving_trace.json")
    get_tracer().export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    assert events, "serving run recorded no spans"
    assert all(e["ph"] == "X" for e in events)
    assert all(
        isinstance(e["ts"], (int, float))
        and isinstance(e["dur"], (int, float))
        for e in events
    )
    names = {e["name"] for e in events}
    assert "serving.dispatch" in names
    assert "microbatch.coalesce" in names
    # the dispatch span parents under its coalesce window
    coalesce_ids = {
        e["args"]["span_id"]
        for e in events
        if e["name"] == "microbatch.coalesce"
    }
    dispatch_parents = {
        e["args"]["parent_id"]
        for e in events
        if e["name"] == "serving.dispatch"
    }
    assert dispatch_parents & coalesce_ids

    # /tracez?format=chrome serves the same document
    with AdminServer(registry=MetricsRegistry(), tracer=get_tracer()) as srv:
        _, _, body = _get(srv, "/tracez?format=chrome")
    assert {e["name"] for e in json.loads(body)["traceEvents"]} == names


def test_disabled_admin_means_no_server_and_no_spans():
    """The whole plane is off by default: the global tracer records
    nothing and engine construction alone opens no sockets (nothing to
    assert beyond: tracer off, span() is the null object)."""
    tracer = get_tracer()
    assert not tracer.enabled
    before = len(tracer.recent())
    with tracer.span("ghost"):
        pass
    assert len(tracer.recent()) == before
