"""OTLP/HTTP span export: wire-format mapping, background batching
against an in-process stub collector, drop-not-block behavior, and the
tracer-sink lifecycle."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from keystone_tpu.observability.otlp import (
    OtlpSpanExporter,
    encode_spans,
    format_span_id,
    span_to_otlp,
)
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.observability.tracing import Span, Tracer


class StubCollector:
    """A stdlib OTLP collector double: records every POSTed body."""

    def __init__(self, status=200):
        self.bodies = []
        self.paths = []
        self._got = threading.Event()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                outer.bodies.append(
                    json.loads(self.rfile.read(length))
                )
                outer.paths.append(self.path)
                outer._got.set()
                self.send_response(status)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def endpoint(self):
        return f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def wait(self, timeout=5.0):
        return self._got.wait(timeout)

    def spans(self):
        out = []
        for body in self.bodies:
            for rs in body["resourceSpans"]:
                for ss in rs["scopeSpans"]:
                    out.extend(ss["spans"])
        return out

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


@pytest.fixture
def collector():
    c = StubCollector()
    yield c
    c.close()


def make_span(name="work", span_id=7, parent_id=None, trace_id="ab" * 16,
              attrs=None):
    return Span(
        name=name, span_id=span_id, parent_id=parent_id,
        start_s=1700000000.0, duration_s=0.125, thread_id=1,
        attrs=attrs if attrs is not None else {"bucket": 8},
        trace_id=trace_id,
    )


class TestWireFormat:
    def test_span_mapping(self):
        otlp = span_to_otlp(make_span(parent_id=3))
        assert otlp["traceId"] == "ab" * 16
        assert otlp["spanId"] == format_span_id(7)
        assert otlp["parentSpanId"] == format_span_id(3)
        assert otlp["name"] == "work"
        assert otlp["startTimeUnixNano"] == str(1700000000 * 10**9)
        assert (
            int(otlp["endTimeUnixNano"])
            - int(otlp["startTimeUnixNano"])
        ) == 125_000_000
        # int attrs serialize as strings (proto3 JSON int64 rule)
        attrs = {a["key"]: a["value"] for a in otlp["attributes"]}
        assert attrs["bucket"] == {"intValue": "8"}
        assert attrs["thread.id"] == {"intValue": "1"}

    def test_root_span_has_no_parent_field(self):
        assert "parentSpanId" not in span_to_otlp(make_span())

    def test_span_id_is_16_hex_chars(self):
        assert format_span_id(1) == "0000000000000001"
        assert len(format_span_id(2**70)) == 16

    def test_attr_value_types(self):
        otlp = span_to_otlp(
            make_span(attrs={
                "f": 0.5, "b": True, "s": "x", "o": [1, 2],
            })
        )
        attrs = {a["key"]: a["value"] for a in otlp["attributes"]}
        assert attrs["f"] == {"doubleValue": 0.5}
        assert attrs["b"] == {"boolValue": True}
        assert attrs["s"] == {"stringValue": "x"}
        assert attrs["o"] == {"stringValue": "[1, 2]"}

    def test_orphan_trace_id_is_nonzero(self):
        otlp = span_to_otlp(make_span(trace_id=None))
        assert otlp["traceId"] == "f" * 32

    def test_encode_spans_envelope(self):
        body = encode_spans([make_span()], service_name="svc-x")
        (rs,) = body["resourceSpans"]
        res_attrs = {
            a["key"]: a["value"] for a in rs["resource"]["attributes"]
        }
        assert res_attrs["service.name"] == {"stringValue": "svc-x"}
        (ss,) = rs["scopeSpans"]
        assert len(ss["spans"]) == 1


class TestExporter:
    def test_posts_batches_to_v1_traces(self, collector):
        exp = OtlpSpanExporter(
            collector.endpoint, flush_interval_s=0.05,
            registry=MetricsRegistry(),
        )
        exp.start()
        try:
            exp.submit(make_span(span_id=1))
            exp.submit(make_span(span_id=2))
            assert exp.flush(5.0)
            assert collector.wait()
        finally:
            exp.shutdown()
        assert all(p == "/v1/traces" for p in collector.paths)
        ids = {s["spanId"] for s in collector.spans()}
        assert ids == {format_span_id(1), format_span_id(2)}

    def test_endpoint_path_appended_once(self):
        reg = MetricsRegistry()
        exp = OtlpSpanExporter("http://x:4318", registry=reg)
        assert exp.endpoint == "http://x:4318/v1/traces"
        exp2 = OtlpSpanExporter(
            "http://x:4318/v1/traces", registry=reg
        )
        assert exp2.endpoint == "http://x:4318/v1/traces"

    def test_installed_as_tracer_sink_exports_finished_spans(
        self, collector
    ):
        tr = Tracer()
        exp = OtlpSpanExporter(
            collector.endpoint, flush_interval_s=0.05,
            registry=MetricsRegistry(),
        )
        exp.install(tr)
        try:
            with tr.span("outer", gateway="g") as outer:
                with tr.span("inner"):
                    pass
            assert exp.flush(5.0)
            assert collector.wait()
        finally:
            exp.shutdown()
        spans = {s["name"]: s for s in collector.spans()}
        assert set(spans) == {"outer", "inner"}
        assert spans["inner"]["traceId"] == outer.trace_id
        assert spans["inner"]["parentSpanId"] == format_span_id(
            outer.span_id
        )
        # shutdown unhooked the sink: new spans no longer enqueue
        with tr.span("after"):
            pass
        assert len(tr._sinks) == 0

    def test_full_queue_drops_oldest_not_blocks(self):
        reg = MetricsRegistry()
        exp = OtlpSpanExporter(
            "http://127.0.0.1:9",  # nothing listens; never started
            batch_size=4, queue_capacity=4, registry=reg,
        )
        for i in range(10):
            exp.submit(make_span(span_id=i))
        assert len(exp._q) == 4
        dropped = reg.counter(
            "keystone_otlp_spans_total", "", ("result",)
        ).get(("dropped",))
        assert dropped == 6

    def test_dead_collector_counts_errors_and_drops(self):
        reg = MetricsRegistry()
        exp = OtlpSpanExporter(
            "http://127.0.0.1:9", flush_interval_s=0.05,
            timeout_s=0.5, registry=reg,
        )
        exp.start()
        try:
            exp.submit(make_span())
            assert exp.flush(10.0)
        finally:
            exp.shutdown()
        c = reg.counter("keystone_otlp_posts_total", "", ("result",))
        assert c.get(("error",)) >= 1

    def test_export_health_counters(self, collector):
        reg = MetricsRegistry()
        exp = OtlpSpanExporter(
            collector.endpoint, flush_interval_s=0.05, registry=reg
        )
        exp.start()
        try:
            exp.submit(make_span())
            assert exp.flush(5.0)
        finally:
            exp.shutdown()
        spans_c = reg.counter(
            "keystone_otlp_spans_total", "", ("result",)
        )
        posts_c = reg.counter(
            "keystone_otlp_posts_total", "", ("result",)
        )
        assert spans_c.get(("exported",)) == 1
        assert posts_c.get(("ok",)) == 1


def test_encode_spans_stamps_resource_attrs():
    """The fleet's resource identity (service.name + replica) rides
    the OTLP RESOURCE, not the spans, so an external collector lays N
    processes' halves of one trace out as the stitched topology."""
    from keystone_tpu.observability.otlp import encode_spans
    from keystone_tpu.observability.tracing import Span

    span = Span(
        name="router.forward", span_id=1, parent_id=None,
        start_s=1.0, duration_s=0.01, thread_id=1, attrs={},
        trace_id="ab" * 16,
    )
    doc = encode_spans(
        [span], service_name="keystone-router",
        resource_attrs={"replica": "host-a:8000"},
    )
    attrs = {
        kv["key"]: kv["value"]["stringValue"]
        for kv in doc["resourceSpans"][0]["resource"]["attributes"]
    }
    assert attrs == {
        "service.name": "keystone-router",
        "replica": "host-a:8000",
    }
