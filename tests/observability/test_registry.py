"""MetricsRegistry: named/labeled metrics, collectors, global plane."""

import gc
import threading

import pytest

from keystone_tpu.observability.registry import (
    MetricFamily,
    MetricsRegistry,
    Sample,
    get_global_registry,
)


def test_counter_labels_and_total():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests", labelnames=("bucket",))
    c.inc(("8",))
    c.inc(("8",), by=2)
    c.inc(("64",))
    assert c.get(("8",)) == 3
    assert c.get(("64",)) == 1
    fam = c.collect()
    assert fam.mtype == "counter"
    assert {tuple(s.labels.items()): s.value for s in fam.samples} == {
        (("bucket", "8"),): 3,
        (("bucket", "64"),): 1,
    }


def test_counter_rejects_decrease_and_bad_labels():
    reg = MetricsRegistry()
    c = reg.counter("c_total", labelnames=("a",))
    with pytest.raises(ValueError):
        c.inc(("x",), by=-1)
    with pytest.raises(ValueError):
        c.inc()  # missing label value
    with pytest.raises(ValueError):
        c.inc(("x", "y"))  # too many


def test_reregistration_is_idempotent_but_type_mismatch_raises():
    reg = MetricsRegistry()
    c1 = reg.counter("shared_total", "h", labelnames=("l",))
    c2 = reg.counter("shared_total", "h", labelnames=("l",))
    assert c1 is c2
    with pytest.raises(ValueError):
        reg.gauge("shared_total")
    with pytest.raises(ValueError):
        reg.counter("shared_total", labelnames=("other",))


def test_gauge_set_and_func_gauge():
    reg = MetricsRegistry()
    g = reg.gauge("depth", labelnames=("engine",))
    g.set(3, ("e0",))
    g.set(5.5, ("e1",))
    vals = {s.labels["engine"]: s.value for s in g.collect().samples}
    assert vals == {"e0": 3.0, "e1": 5.5}

    state = {"v": 7.0}
    reg.gauge_func("live", lambda: state["v"])
    fam = [f for f in reg.collect() if f.name == "live"][0]
    assert fam.samples[0].value == 7.0
    state["v"] = 9.0
    fam = [f for f in reg.collect() if f.name == "live"][0]
    assert fam.samples[0].value == 9.0  # polled at collect time


def test_func_gauge_labeled_dict():
    reg = MetricsRegistry()
    reg.gauge_func(
        "per_bucket", lambda: {("8",): 1.0, ("64",): 2.0},
        labelnames=("bucket",),
    )
    fam = reg.collect()[0]
    assert {s.labels["bucket"]: s.value for s in fam.samples} == {
        "8": 1.0, "64": 2.0,
    }


def test_summary_quantiles_count_sum():
    reg = MetricsRegistry()
    s = reg.summary("lat_seconds", labelnames=("engine",))
    for v in [0.010, 0.020, 0.030, 0.040]:
        s.observe(v, ("e0",))
    fam = s.collect()
    assert fam.mtype == "summary"
    by_suffix = {}
    for sample in fam.samples:
        by_suffix.setdefault(sample.suffix, []).append(sample)
    assert by_suffix["_count"][0].value == 4
    assert by_suffix["_sum"][0].value == pytest.approx(0.1)
    quantiles = {s.labels["quantile"] for s in by_suffix[""]}
    assert quantiles == {"0.5", "0.95", "0.99"}


def test_collector_callback_and_weakref_prune():
    reg = MetricsRegistry()

    class Owner:
        pass

    owner = Owner()
    import weakref

    ref = weakref.ref(owner)

    def collect():
        if ref() is None:
            return None
        return [
            MetricFamily("owned_total", "counter", "", [Sample("", {}, 1)])
        ]

    reg.register_collector(collect)
    assert any(f.name == "owned_total" for f in reg.collect())
    del owner
    gc.collect()
    assert not any(f.name == "owned_total" for f in reg.collect())
    # pruned: the dead collector is gone from the registry entirely
    assert reg._collectors == []


def test_collect_merges_same_name_families():
    """Two collectors exporting the same family name (two engines) get
    one merged family, so exposition has a single TYPE block."""
    reg = MetricsRegistry()
    for label in ("a", "b"):
        reg.register_collector(
            lambda label=label: [
                MetricFamily(
                    "x_total", "counter", "",
                    [Sample("", {"engine": label}, 1)],
                )
            ]
        )
    fams = [f for f in reg.collect() if f.name == "x_total"]
    assert len(fams) == 1
    assert len(fams[0].samples) == 2


def test_varz_shape():
    reg = MetricsRegistry()
    reg.counter("a_total", "help a", ("l",)).inc(("v",))
    v = reg.varz()
    assert v["a_total"]["type"] == "counter"
    assert v["a_total"]["values"][0] == {
        "suffix": "", "labels": {"l": "v"}, "value": 1,
    }


def test_global_registry_is_singleton_and_threadsafe():
    assert get_global_registry() is get_global_registry()
    reg = MetricsRegistry()
    c = reg.counter("hits_total")
    threads = [
        threading.Thread(
            target=lambda: [c.inc() for _ in range(1000)]
        )
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.get() == 8000
