"""MetricsRegistry: named/labeled metrics, collectors, global plane."""

import gc
import threading

import pytest

from keystone_tpu.observability.registry import (
    MetricFamily,
    MetricsRegistry,
    Sample,
    get_global_registry,
)


def test_counter_labels_and_total():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total", "requests", labelnames=("bucket",))
    c.inc(("8",))
    c.inc(("8",), by=2)
    c.inc(("64",))
    assert c.get(("8",)) == 3
    assert c.get(("64",)) == 1
    fam = c.collect()
    assert fam.mtype == "counter"
    assert {tuple(s.labels.items()): s.value for s in fam.samples} == {
        (("bucket", "8"),): 3,
        (("bucket", "64"),): 1,
    }


def test_counter_rejects_decrease_and_bad_labels():
    reg = MetricsRegistry()
    c = reg.counter("c_total", labelnames=("a",))
    with pytest.raises(ValueError):
        c.inc(("x",), by=-1)
    with pytest.raises(ValueError):
        c.inc()  # missing label value
    with pytest.raises(ValueError):
        c.inc(("x", "y"))  # too many


def test_reregistration_is_idempotent_but_type_mismatch_raises():
    reg = MetricsRegistry()
    c1 = reg.counter("shared_total", "h", labelnames=("l",))
    c2 = reg.counter("shared_total", "h", labelnames=("l",))
    assert c1 is c2
    with pytest.raises(ValueError):
        reg.gauge("shared_total")
    with pytest.raises(ValueError):
        reg.counter("shared_total", labelnames=("other",))


def test_gauge_set_and_func_gauge():
    reg = MetricsRegistry()
    g = reg.gauge("depth", labelnames=("engine",))
    g.set(3, ("e0",))
    g.set(5.5, ("e1",))
    vals = {s.labels["engine"]: s.value for s in g.collect().samples}
    assert vals == {"e0": 3.0, "e1": 5.5}

    state = {"v": 7.0}
    reg.gauge_func("live", lambda: state["v"])
    fam = [f for f in reg.collect() if f.name == "live"][0]
    assert fam.samples[0].value == 7.0
    state["v"] = 9.0
    fam = [f for f in reg.collect() if f.name == "live"][0]
    assert fam.samples[0].value == 9.0  # polled at collect time


def test_func_gauge_labeled_dict():
    reg = MetricsRegistry()
    reg.gauge_func(
        "per_bucket", lambda: {("8",): 1.0, ("64",): 2.0},
        labelnames=("bucket",),
    )
    fam = reg.collect()[0]
    assert {s.labels["bucket"]: s.value for s in fam.samples} == {
        "8": 1.0, "64": 2.0,
    }


def test_summary_quantiles_count_sum():
    reg = MetricsRegistry()
    s = reg.summary("lat_seconds", labelnames=("engine",))
    for v in [0.010, 0.020, 0.030, 0.040]:
        s.observe(v, ("e0",))
    fam = s.collect()
    assert fam.mtype == "summary"
    by_suffix = {}
    for sample in fam.samples:
        by_suffix.setdefault(sample.suffix, []).append(sample)
    assert by_suffix["_count"][0].value == 4
    assert by_suffix["_sum"][0].value == pytest.approx(0.1)
    quantiles = {s.labels["quantile"] for s in by_suffix[""]}
    assert quantiles == {"0.5", "0.95", "0.99"}


def test_collector_callback_and_weakref_prune():
    reg = MetricsRegistry()

    class Owner:
        pass

    owner = Owner()
    import weakref

    ref = weakref.ref(owner)

    def collect():
        if ref() is None:
            return None
        return [
            MetricFamily("owned_total", "counter", "", [Sample("", {}, 1)])
        ]

    reg.register_collector(collect)
    assert any(f.name == "owned_total" for f in reg.collect())
    del owner
    gc.collect()
    assert not any(f.name == "owned_total" for f in reg.collect())
    # pruned: the dead collector is gone from the registry entirely
    assert reg._collectors == []


def test_collect_merges_same_name_families():
    """Two collectors exporting the same family name (two engines) get
    one merged family, so exposition has a single TYPE block."""
    reg = MetricsRegistry()
    for label in ("a", "b"):
        reg.register_collector(
            lambda label=label: [
                MetricFamily(
                    "x_total", "counter", "",
                    [Sample("", {"engine": label}, 1)],
                )
            ]
        )
    fams = [f for f in reg.collect() if f.name == "x_total"]
    assert len(fams) == 1
    assert len(fams[0].samples) == 2


def test_varz_shape():
    reg = MetricsRegistry()
    reg.counter("a_total", "help a", ("l",)).inc(("v",))
    v = reg.varz()
    assert v["a_total"]["type"] == "counter"
    assert v["a_total"]["values"][0] == {
        "suffix": "", "labels": {"l": "v"}, "value": 1,
    }


def test_global_registry_is_singleton_and_threadsafe():
    assert get_global_registry() is get_global_registry()
    reg = MetricsRegistry()
    c = reg.counter("hits_total")
    threads = [
        threading.Thread(
            target=lambda: [c.inc() for _ in range(1000)]
        )
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.get() == 8000


def test_histogram_buckets_cumulative_sum_count():
    reg = MetricsRegistry()
    h = reg.histogram(
        "lat_seconds", "latency", labelnames=("gw",),
        buckets=(0.01, 0.1, 1.0),
    )
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v, ("g",))
    assert h.get_count(("g",)) == 5
    fam = h.collect()
    assert fam.mtype == "histogram"
    by_le = {
        s.labels["le"]: s.value
        for s in fam.samples
        if s.suffix == "_bucket"
    }
    # cumulative: le buckets ADD (the aggregability summaries lack)
    assert by_le == {"0.01": 1, "0.1": 3, "1": 4, "+Inf": 5}
    count = [s for s in fam.samples if s.suffix == "_count"][0]
    total = [s for s in fam.samples if s.suffix == "_sum"][0]
    assert count.value == 5
    assert total.value == pytest.approx(5.605)


def test_histogram_le_boundary_is_inclusive():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0))
    h.observe(1.0)  # le="1" must include exactly-1.0 (Prometheus <=)
    by_le = {
        s.labels["le"]: s.value
        for s in h.collect().samples
        if s.suffix == "_bucket"
    }
    assert by_le["1"] == 1


def test_histogram_validation_and_reregistration():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad", buckets=())
    with pytest.raises(ValueError):
        reg.histogram("bad2", buckets=(2.0, 1.0))
    h = reg.histogram("ok_seconds", buckets=(1.0, 2.0))
    assert reg.histogram("ok_seconds", buckets=(1.0, 2.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("ok_seconds", buckets=(1.0, 3.0))
    with pytest.raises(ValueError):
        reg.counter("ok_seconds")


def test_histogram_concurrent_observes():
    reg = MetricsRegistry()
    h = reg.histogram("conc_seconds", buckets=(0.5,))

    def worker():
        for _ in range(500):
            h.observe(0.25)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.get_count() == 2000


def test_histogram_rejects_explicit_inf_bound():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("inf_seconds", buckets=(1.0, float("inf")))


# -- RegistryHistogram edge cases ------------------------------------------


class TestHistogramEdges:
    def test_observation_exactly_on_le_boundary_counts_le(self):
        """Prometheus `le` is INCLUSIVE: an observation equal to a
        bound belongs to that bound's bucket, not the next one up."""
        reg = MetricsRegistry()
        h = reg.histogram("edge", "e", buckets=(0.1, 0.25, 1.0))
        h.observe(0.25)
        fam = h.collect()
        buckets = {
            s.labels["le"]: s.value
            for s in fam.samples
            if s.suffix == "_bucket"
        }
        assert buckets["0.1"] == 0
        assert buckets["0.25"] == 1  # on the boundary: counted here
        assert buckets["1"] == 1
        assert buckets["+Inf"] == 1

    def test_all_observations_above_every_bound(self):
        """An +Inf-only population: every bucket 0, overflow carries
        the count, `_sum` still exact."""
        reg = MetricsRegistry()
        h = reg.histogram("over", "o", buckets=(1.0,))
        h.observe(5.0)
        h.observe(7.0)
        fam = h.collect()
        by = {(s.suffix, s.labels.get("le")): s.value for s in fam.samples}
        assert by[("_bucket", "1")] == 0
        assert by[("_bucket", "+Inf")] == 2
        assert by[("_count", None)] == 2
        assert by[("_sum", None)] == 12.0

    def test_zero_observation_family_collects_empty(self):
        reg = MetricsRegistry()
        h = reg.histogram("silent", "s", ("lane",))
        fam = h.collect()
        assert fam.mtype == "histogram"
        assert fam.samples == []

    def test_cumulative_count_and_le_index(self):
        reg = MetricsRegistry()
        h = reg.histogram("cum", "c", buckets=(0.1, 0.25, 1.0))
        for v in (0.05, 0.2, 0.25, 0.5, 3.0):
            h.observe(v)
        assert h.le_index(0.25) == 1
        assert h.le_index(0.3) == 2  # snaps up to the 1.0 bound
        assert h.le_index(99.0) == 3  # past every bound
        assert h.cumulative_count(0) == 1  # <= 0.1
        assert h.cumulative_count(1) == 3  # <= 0.25 inclusive
        assert h.cumulative_count(2) == 4  # <= 1.0
        assert h.cumulative_count(3) == 5  # everything
        assert h.get_sum() == pytest.approx(4.0)

    def test_exemplar_stored_per_bucket_latest_wins(self):
        reg = MetricsRegistry()
        h = reg.histogram("exm", "x", buckets=(0.1, 1.0))
        h.observe(0.05, trace_id="old")
        h.observe(0.07, trace_id="new")
        h.observe(0.5)  # no trace: leaves no exemplar
        fam = h.collect()
        by_le = {
            s.labels["le"]: s for s in fam.samples
            if s.suffix == "_bucket"
        }
        assert by_le["0.1"].exemplar.labels == {"trace_id": "new"}
        assert by_le["0.1"].exemplar.value == 0.07
        assert by_le["1"].exemplar is None
        assert by_le["+Inf"].exemplar is None

    def test_exemplar_lands_in_overflow_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("exo", "x", buckets=(0.1,))
        h.observe(9.0, trace_id="slowpoke")
        fam = h.collect()
        (inf_sample,) = [
            s for s in fam.samples
            if s.suffix == "_bucket" and s.labels["le"] == "+Inf"
        ]
        assert inf_sample.exemplar.labels == {"trace_id": "slowpoke"}
