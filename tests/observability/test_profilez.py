"""On-demand profiling (observability/profilez.py): parameter
validation, the one-capture-at-a-time 409 contract, and the e2e
round-trip on ephemeral admin + gateway ports."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from keystone_tpu.observability import AdminServer, MetricsRegistry, Tracer
from keystone_tpu.observability import profilez


def test_bad_seconds_is_400():
    code, doc = profilez.profilez_document("not-a-number")
    assert code == 400 and doc["error"] == "bad_request"
    code, _ = profilez.profilez_document("0")
    assert code == 400
    code, _ = profilez.profilez_document("-2")
    assert code == 400
    code, _ = profilez.profilez_document(
        str(profilez.MAX_CAPTURE_SECONDS + 1)
    )
    assert code == 400


def test_capture_writes_trace_files(tmp_path):
    code, doc = profilez.profilez_document("0.2", base_dir=str(tmp_path))
    assert code == 200, doc
    assert doc["trace_dir"].startswith(str(tmp_path))
    assert doc["file_count"] >= 1, doc
    assert doc["captured_s"] >= 0.2


def test_capture_retention_is_bounded(tmp_path):
    """Only the newest MAX_RETAINED_CAPTURES dirs survive: a probe
    hitting /profilez periodically must not fill the disk."""
    import os
    import time as time_mod

    for i in range(4):
        d = tmp_path / f"trace-2026-{i}"
        d.mkdir()
        (d / "plane.pb").write_bytes(b"x")
        # distinct mtimes so newest-wins ordering is deterministic
        stamp = time_mod.time() - (4 - i) * 10
        os.utime(d, (stamp, stamp))
    (tmp_path / "unrelated").mkdir()  # non-capture dirs untouched
    profilez._prune_captures(str(tmp_path), keep=2)
    kept = sorted(p.name for p in tmp_path.iterdir())
    assert kept == ["trace-2026-2", "trace-2026-3", "unrelated"]
    # the live endpoint prunes as it captures: the newest capture is
    # always retained
    code, doc = profilez.profilez_document("0.1", base_dir=str(tmp_path))
    assert code == 200
    assert doc["trace_dir"] in [str(p) for p in tmp_path.iterdir()]


def test_dead_process_dirs_are_swept(tmp_path):
    import os

    mine = tmp_path / f"keystone-profilez-{os.getpid()}"
    dead = tmp_path / "keystone-profilez-999999999"  # no such pid
    alive = tmp_path / f"keystone-profilez-{os.getppid()}"
    other = tmp_path / "keystone-profilez-notapid"
    for d in (mine, dead, alive, other):
        d.mkdir()
    profilez._sweep_dead_process_dirs(str(mine))
    kept = sorted(p.name for p in tmp_path.iterdir())
    assert dead.name not in kept  # dead pid's captures reclaimed
    assert mine.name in kept and alive.name in kept
    assert other.name in kept  # unparseable names left alone


def test_concurrent_capture_is_409(tmp_path):
    """jax.profiler allows one trace per process: while a capture
    holds the lock, a second request must get a typed 409, and the
    lock must release afterwards."""
    with profilez._capture_lock:
        code, doc = profilez.profilez_document("0.1")
        assert code == 409
        assert doc["error"] == "capture_in_progress"
    # lock released: capture works again
    code, _ = profilez.profilez_document("0.1", base_dir=str(tmp_path))
    assert code == 200


def test_profilez_e2e_on_admin_and_gateway_ports(tmp_path):
    """The acceptance drill: GET /profilez?seconds=N on an ephemeral
    admin port returns a capture while the concurrent second request
    409s; the gateway port mirrors the route."""
    with AdminServer(registry=MetricsRegistry(), tracer=Tracer()) as srv:
        results = []

        def hit(seconds):
            try:
                with urllib.request.urlopen(
                    srv.url(f"/profilez?seconds={seconds}"), timeout=30
                ) as resp:
                    results.append((resp.status, json.loads(resp.read())))
            except urllib.error.HTTPError as e:
                results.append((e.code, json.loads(e.read())))

        t1 = threading.Thread(target=hit, args=(1.0,))
        t2 = threading.Thread(target=hit, args=(1.0,))
        t1.start()
        t2.start()
        t1.join()
        t2.join()
        codes = sorted(c for c, _ in results)
        assert codes == [200, 409], results
        ok = next(doc for c, doc in results if c == 200)
        assert ok["file_count"] >= 1
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                srv.url("/profilez?seconds=oops"), timeout=10
            )
        assert e.value.code == 400


def test_profilez_route_on_gateway_port():
    from keystone_tpu.gateway import Gateway, GatewayServer
    from keystone_tpu.serving.bench import build_pipeline

    import numpy as np

    fitted = build_pipeline(d=8, hidden=8, depth=2)
    with Gateway(
        fitted, buckets=(4,), n_lanes=1,
        warmup_example=np.zeros((8,), np.float32),
        registry=MetricsRegistry(), name="pz-gw",
    ) as gw:
        with GatewayServer(gw, port=0, registry=MetricsRegistry()) as srv:
            with urllib.request.urlopen(
                srv.url("/profilez?seconds=0.2"), timeout=30
            ) as resp:
                doc = json.loads(resp.read())
            assert resp.status == 200
            assert doc["file_count"] >= 1
