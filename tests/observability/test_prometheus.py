"""Prometheus text exposition v0.0.4 golden-string tests — no sockets.

The scrape contract: metric-name sanitization, label-value escaping
(backslash, quote, newline), counter/gauge/summary line formats.
"""

from keystone_tpu.observability.prometheus import (
    escape_help,
    escape_label_value,
    format_value,
    render,
    render_family,
    sanitize_label_name,
    sanitize_metric_name,
)
from keystone_tpu.observability.registry import (
    MetricFamily,
    MetricsRegistry,
    Sample,
)


def test_metric_name_sanitization():
    assert sanitize_metric_name("my.metric-name") == "my_metric_name"
    assert sanitize_metric_name("ok_name:sub") == "ok_name:sub"
    assert sanitize_metric_name("0starts_with_digit") == "_0starts_with_digit"
    assert sanitize_metric_name("sp ace/slash") == "sp_ace_slash"


def test_label_name_sanitization():
    assert sanitize_label_name("a.b") == "a_b"
    assert sanitize_label_name("with:colon") == "with_colon"  # no colons
    assert sanitize_label_name("9lead") == "_9lead"


def test_label_value_escaping():
    assert escape_label_value('plain') == 'plain'
    assert escape_label_value('say "hi"') == r'say \"hi\"'
    assert escape_label_value('line1\nline2') == r'line1\nline2'
    assert escape_label_value('back\\slash') == 'back\\\\slash'
    # backslash escapes first: a literal `\n` (two chars) round-trips
    # distinctly from a newline
    assert escape_label_value('\\n') == r'\\n'
    assert escape_label_value('\n') == r'\n'


def test_help_escaping():
    assert escape_help('multi\nline \\ "quoted"') == (
        r'multi\nline \\ "quoted"'
    )


def test_format_value():
    assert format_value(3) == "3"
    assert format_value(3.0) == "3"
    assert format_value(0.25) == "0.25"
    assert format_value(float("inf")) == "+Inf"
    assert format_value(float("-inf")) == "-Inf"
    assert format_value(float("nan")) == "NaN"


def test_counter_family_golden():
    fam = MetricFamily(
        "keystone_serving_compiles_total", "counter",
        "XLA compiles per bucket",
        [
            Sample("", {"engine": "e0", "bucket": "8"}, 1),
            Sample("", {"engine": "e0", "bucket": "64"}, 2),
        ],
    )
    assert render_family(fam) == (
        "# HELP keystone_serving_compiles_total XLA compiles per bucket\n"
        "# TYPE keystone_serving_compiles_total counter\n"
        'keystone_serving_compiles_total{engine="e0",bucket="8"} 1\n'
        'keystone_serving_compiles_total{engine="e0",bucket="64"} 2\n'
    )


def test_gauge_no_labels_golden():
    fam = MetricFamily("queue_depth", "gauge", "", [Sample("", {}, 5)])
    assert render_family(fam) == (
        "# TYPE queue_depth gauge\n"
        "queue_depth 5\n"
    )


def test_summary_family_golden():
    fam = MetricFamily(
        "req_latency_seconds", "summary", "request latency",
        [
            Sample("", {"quantile": "0.5"}, 0.25),
            Sample("", {"quantile": "0.99"}, 0.5),
            Sample("_count", {}, 4),
            Sample("_sum", {}, 1.5),
        ],
    )
    assert render_family(fam) == (
        "# HELP req_latency_seconds request latency\n"
        "# TYPE req_latency_seconds summary\n"
        'req_latency_seconds{quantile="0.5"} 0.25\n'
        'req_latency_seconds{quantile="0.99"} 0.5\n'
        "req_latency_seconds_count 4\n"
        "req_latency_seconds_sum 1.5\n"
    )


def test_hostile_label_values_golden():
    fam = MetricFamily(
        "evil_total", "counter", "",
        [Sample("", {"path": 'a\\b\n"c"'}, 1)],
    )
    assert render_family(fam) == (
        "# TYPE evil_total counter\n"
        'evil_total{path="a\\\\b\\n\\"c\\""} 1\n'
    )


def test_render_full_registry_sorted_with_trailing_newline():
    reg = MetricsRegistry()
    reg.counter("z_total", "zs").inc()
    reg.gauge("a_gauge", "the a").set(1.5)
    body = render(reg.collect())
    assert body == (
        "# HELP a_gauge the a\n"
        "# TYPE a_gauge gauge\n"
        "a_gauge 1.5\n"
        "# HELP z_total zs\n"
        "# TYPE z_total counter\n"
        "z_total 1\n"
    )
    assert body.endswith("\n")


def test_invalid_name_sanitized_in_render():
    fam = MetricFamily(
        "bad.name-here", "counter", "", [Sample("", {"l.x": "v"}, 1)]
    )
    out = render_family(fam)
    assert "bad_name_here" in out
    assert 'l_x="v"' in out


def test_format_le():
    from keystone_tpu.observability.prometheus import format_le

    assert format_le(0.005) == "0.005"
    assert format_le(1.0) == "1"
    assert format_le(2.5) == "2.5"
    assert format_le(float("inf")) == "+Inf"


def test_histogram_family_golden():
    """A RegistryHistogram renders as native Prometheus histogram
    exposition: cumulative _bucket lines with le labels, then _count
    and _sum — the promtool-parseable shape histogram_quantile eats."""
    from keystone_tpu.observability.registry import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram(
        "gw_wait_seconds", "queue wait", ("gateway",),
        buckets=(0.01, 0.25, 1.0),
    )
    h.observe(0.005, ("g",))
    h.observe(0.2, ("g",))
    h.observe(3.0, ("g",))
    assert render_family(h.collect()) == (
        "# HELP gw_wait_seconds queue wait\n"
        "# TYPE gw_wait_seconds histogram\n"
        'gw_wait_seconds_bucket{gateway="g",le="0.01"} 1\n'
        'gw_wait_seconds_bucket{gateway="g",le="0.25"} 2\n'
        'gw_wait_seconds_bucket{gateway="g",le="1"} 2\n'
        'gw_wait_seconds_bucket{gateway="g",le="+Inf"} 3\n'
        'gw_wait_seconds_count{gateway="g"} 3\n'
        'gw_wait_seconds_sum{gateway="g"} 3.205\n'
    )


# -- exemplars (OpenMetrics syntax) ----------------------------------------


def test_exemplar_golden_string():
    from keystone_tpu.observability.registry import Exemplar

    fam = MetricFamily(
        "lat_seconds", "histogram", "",
        [
            Sample(
                "_bucket", {"le": "0.25"}, 3,
                exemplar=Exemplar(
                    {"trace_id": "4bf92f3577b34da6"}, 0.2, 1700000000.5
                ),
            ),
            Sample("_bucket", {"le": "+Inf"}, 3),
            Sample("_count", {}, 3),
        ],
    )
    assert render_family(fam, exemplars=True) == (
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.25"} 3'
        ' # {trace_id="4bf92f3577b34da6"} 0.2 1700000000.5\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_count 3\n"
    )
    # the classic v0.0.4 rendering must NEVER carry the exemplar tail:
    # that parser reads the mid-line '#' as a malformed timestamp and
    # fails the whole scrape
    assert render_family(fam) == (
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.25"} 3\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_count 3\n"
    )


def test_exemplar_rendered_from_live_histogram():
    reg = MetricsRegistry()
    h = reg.histogram("hist_x", "", ("gw",), buckets=(0.5,))
    h.observe(0.25, ("g0",), trace_id="abc123")
    body = render(reg.collect(), openmetrics=True)
    assert '# {trace_id="abc123"} 0.25 ' in body
    assert body.endswith("# EOF\n")
    # the exemplar rides the 0.5 bucket line specifically, not +Inf
    lines = {
        ln.split(" ", 1)[0]: ln for ln in body.splitlines()
        if ln.startswith("hist_x_bucket")
    }
    assert " # {" in lines['hist_x_bucket{gw="g0",le="0.5"}']
    assert " # {" not in lines['hist_x_bucket{gw="g0",le="+Inf"}']
    # classic rendering: same counts, no exemplar tails anywhere
    plain = render(reg.collect())
    assert 'hist_x_bucket{gw="g0",le="0.5"} 1' in plain
    assert "# {" not in plain and "# EOF" not in plain


def test_negotiate_render_by_accept_header():
    from keystone_tpu.observability.prometheus import (
        CONTENT_TYPE,
        OPENMETRICS_CONTENT_TYPE,
        negotiate_render,
    )

    reg = MetricsRegistry()
    h = reg.histogram("neg_x", "", buckets=(1.0,))
    h.observe(0.5, trace_id="tid9")
    # a real Prometheus server's default Accept prefers openmetrics
    om_accept = (
        "application/openmetrics-text;version=1.0.0,"
        "text/plain;version=0.0.4;q=0.5"
    )
    body, ctype = negotiate_render(reg.collect(), om_accept)
    assert ctype == OPENMETRICS_CONTENT_TYPE
    assert '# {trace_id="tid9"}' in body and body.endswith("# EOF\n")
    for accept in (None, "", "text/plain", "*/*"):
        body, ctype = negotiate_render(reg.collect(), accept)
        assert ctype == CONTENT_TYPE
        assert "# {" not in body


def test_zero_observation_histogram_renders_valid_block():
    reg = MetricsRegistry()
    reg.histogram("empty_hist", "nothing yet", ("lane",))
    body = render(reg.collect())
    assert "# HELP empty_hist nothing yet\n" in body
    assert "# TYPE empty_hist histogram\n" in body
    assert body.endswith("\n")
    # no sample lines for the silent family
    assert not any(
        ln.startswith("empty_hist_") for ln in body.splitlines()
    )


# -- scrape-side parsing (the bench's /metrics reader) ---------------------


def test_parse_samples_round_trip_with_exemplars_and_escapes():
    from keystone_tpu.observability.prometheus import parse_samples

    reg = MetricsRegistry()
    c = reg.counter("hits_total", "h", ("path",))
    c.inc(('/x "q"\n',), by=3)
    h = reg.histogram("lat_s", "", ("gw",), buckets=(0.5,))
    h.observe(0.1, ("g0",), trace_id="tid1")
    rows = parse_samples(render(reg.collect(), openmetrics=True))
    by_name = {}
    for name, labels, value in rows:
        by_name.setdefault(name, []).append((labels, value))
    assert by_name["hits_total"] == [({"path": '/x "q"\n'}, 3.0)]
    bucket_rows = dict(
        (labels["le"], value)
        for labels, value in by_name["lat_s_bucket"]
    )
    # the exemplar tail must NOT corrupt the parsed value
    assert bucket_rows == {"0.5": 1.0, "+Inf": 1.0}


def test_histogram_buckets_filters_by_labels():
    from keystone_tpu.observability.prometheus import histogram_buckets

    reg = MetricsRegistry()
    h = reg.histogram("lat_s2", "", ("gw",), buckets=(0.1, 1.0))
    h.observe(0.05, ("a",))
    h.observe(0.5, ("a",))
    h.observe(99.0, ("b",))
    body = render(reg.collect())
    got = histogram_buckets(body, "lat_s2", {"gw": "a"})
    assert got == [(0.1, 1.0), (1.0, 2.0), (float("inf"), 2.0)]
    assert histogram_buckets(body, "lat_s2", {"gw": "zzz"}) == []


def test_quantile_from_buckets_matches_promql_interpolation():
    from keystone_tpu.observability.prometheus import (
        quantile_from_buckets,
    )

    # 10 observations <= 1.0, 10 more in (1.0, 2.0]
    buckets = [(1.0, 10.0), (2.0, 20.0), (float("inf"), 20.0)]
    # p50 rank = 10 -> exactly the 1.0 bound
    assert quantile_from_buckets(0.5, buckets) == 1.0
    # p75 rank = 15 -> halfway through the (1.0, 2.0] bucket
    assert quantile_from_buckets(0.75, buckets) == 1.5
    # p0..first bucket interpolates from lower bound 0
    assert quantile_from_buckets(0.25, buckets) == 0.5
    # quantile in +Inf clamps to the highest finite bound
    assert quantile_from_buckets(
        0.99, [(1.0, 1.0), (float("inf"), 10.0)]
    ) == 1.0
    # empty / zero-count
    assert quantile_from_buckets(0.5, []) is None
    assert quantile_from_buckets(0.5, [(1.0, 0.0)]) is None
