"""Device truth (observability/device.py): peak detection, the one
None-guarded memory_stats probe, cost-model normalization, the cached
device table, and the memory sampler's publish/absent/host-fallback
behaviour."""

from keystone_tpu.observability import device as device_mod
from keystone_tpu.observability.prometheus import render
from keystone_tpu.observability.registry import MetricsRegistry


class FakeDevice:
    def __init__(self, kind="TPU v4", platform="tpu", stats=None,
                 raise_on_stats=False):
        self.device_kind = kind
        self.platform = platform
        self._stats = stats
        self._raise = raise_on_stats

    def memory_stats(self):
        if self._raise:
            raise RuntimeError("no stats on this backend")
        return self._stats


# -- peak detection --------------------------------------------------------

def test_peaks_for_known_kinds():
    flops, membw = device_mod.peaks_for("TPU v4")
    assert flops == 275e12 and membw == 1200e9
    flops, _ = device_mod.peaks_for("TPU v5 lite")
    assert flops == 197e12
    flops, _ = device_mod.peaks_for("NVIDIA A100-SXM4-40GB")
    assert flops == 312e12


def test_peaks_for_unknown_is_none():
    assert device_mod.peaks_for("cpu") == (None, None)
    assert device_mod.peaks_for(None) == (None, None)
    assert device_mod.peaks_for("quantum-annealer") == (None, None)


def test_peaks_matching_is_word_bounded():
    # "l4" must not claim an L40S — a false table hit would export a
    # fabricated MFU denominator; unknown parts stay absent
    assert device_mod.peaks_for("NVIDIA L40S") == (None, None)
    assert device_mod.peaks_for("NVIDIA L4")[0] == 121e12
    assert device_mod.peaks_for("NVIDIA T400") == (None, None)
    # both spellings the runtime uses for Trillium resolve
    assert device_mod.peaks_for("TPU v6e")[0] == 918e12
    assert device_mod.peaks_for("TPU v6 lite")[0] == 918e12


def test_peaks_env_override(monkeypatch):
    monkeypatch.setenv("KEYSTONE_PEAK_FLOPS", "5e12")
    monkeypatch.setenv("KEYSTONE_PEAK_MEMBW_GBPS", "100")
    assert device_mod.peaks_for("cpu") == (5e12, 100e9)
    # override beats the table too
    assert device_mod.peaks_for("TPU v4") == (5e12, 100e9)


def test_peaks_env_partial_override_merges_with_table(monkeypatch):
    monkeypatch.setenv("KEYSTONE_PEAK_FLOPS", "5e12")
    flops, membw = device_mod.peaks_for("TPU v4")
    assert flops == 5e12 and membw == 1200e9


def test_peaks_env_garbage_ignored(monkeypatch):
    monkeypatch.setenv("KEYSTONE_PEAK_FLOPS", "not-a-number")
    assert device_mod.peaks_for("TPU v4")[0] == 275e12


# -- the one memory_stats probe --------------------------------------------

def test_device_memory_stats_none_guard():
    assert device_mod.device_memory_stats(FakeDevice(stats=None)) is None
    assert device_mod.device_memory_stats(FakeDevice(stats={})) is None
    assert (
        device_mod.device_memory_stats(FakeDevice(raise_on_stats=True))
        is None
    )
    stats = {"bytes_in_use": 10, "bytes_limit": 100}
    assert device_mod.device_memory_stats(FakeDevice(stats=stats)) == stats


def test_device_memory_stats_default_device_cpu_is_none():
    # the CPU backend reports no allocator stats: the shared probe
    # (weighted_ls/auto_cache route through it) lands on None, never
    # an exception
    assert device_mod.device_memory_stats() is None


def test_host_memory_stats_reports_limit():
    stats = device_mod.host_memory_stats()
    assert stats is not None
    assert stats.get("bytes_limit", 0) > 0


# -- cost-model normalization ----------------------------------------------

class FakeCompiled:
    def __init__(self, cost=None, mem=None, raise_cost=False):
        self._cost = cost
        self._mem = mem
        self._raise = raise_cost

    def cost_analysis(self):
        if self._raise:
            raise NotImplementedError("backend has no cost analysis")
        return self._cost

    def memory_analysis(self):
        if self._mem is None:
            raise NotImplementedError
        return self._mem


class FakeMem:
    temp_size_in_bytes = 4096
    argument_size_in_bytes = 256
    output_size_in_bytes = 128


def test_cost_model_from_plain_dict():
    model = device_mod.compiled_cost_model(
        FakeCompiled(cost={"flops": 100.0, "bytes accessed": 50.0})
    )
    assert model == {"flops": 100.0, "bytes_accessed": 50.0}


def test_cost_model_from_list_wrapped_dict_and_memory():
    model = device_mod.compiled_cost_model(
        FakeCompiled(cost=[{"flops": 7.0}], mem=FakeMem())
    )
    assert model["flops"] == 7.0
    assert model["temp_bytes"] == 4096
    assert model["argument_bytes"] == 256


def test_cost_model_absent_yields_empty_never_raises():
    assert device_mod.compiled_cost_model(FakeCompiled(cost=None)) == {}
    assert device_mod.compiled_cost_model(FakeCompiled(cost=[])) == {}
    assert (
        device_mod.compiled_cost_model(FakeCompiled(raise_cost=True)) == {}
    )
    assert (
        device_mod.compiled_cost_model(
            FakeCompiled(cost={"flops": "garbage", "bytes accessed": -1})
        )
        == {}
    )


# -- the cached device table -----------------------------------------------

def test_device_table_detects_and_caches():
    device_mod.reset_device_table()
    try:
        table = device_mod.device_table()
        assert table, "CPU backend should still yield one row"
        row = table[0]
        assert row["platform"] == "cpu"
        assert row["count"] >= 1
        # cached: a second call returns an equal COPY (mutating the
        # returned rows must not corrupt the cache)
        again = device_mod.device_table()
        assert again == table
        again[0]["kind"] = "mutated"
        assert device_mod.device_table()[0]["kind"] != "mutated"
    finally:
        device_mod.reset_device_table()


def test_register_device_metrics_info_gauge():
    device_mod.reset_device_table()
    try:
        reg = MetricsRegistry()
        device_mod.register_device_metrics(reg)
        text = render(reg.collect())
        assert "# TYPE keystone_device_info gauge" in text
        assert 'keystone_device_info{kind="' in text
        assert 'platform="cpu"' in text
    finally:
        device_mod.reset_device_table()


# -- the memory sampler ----------------------------------------------------

def test_sampler_publishes_per_device_gauges():
    reg = MetricsRegistry()
    sampler = device_mod.DeviceMemorySampler(
        registry=reg,
        devices=[
            FakeDevice(
                kind="TPU v4",
                stats={
                    "bytes_in_use": 11,
                    "peak_bytes_in_use": 22,
                    "bytes_limit": 33,
                },
            ),
            FakeDevice(kind="TPU v4", stats=None),  # no stats: absent
        ],
    )
    assert sampler.sample_once() == 1
    text = render(reg.collect())
    assert (
        'keystone_device_memory_bytes{device="0",kind="TPU v4",'
        'stat="in_use"} 11' in text
    )
    assert (
        'keystone_device_memory_bytes{device="0",kind="TPU v4",'
        'stat="peak"} 22' in text
    )
    assert (
        'keystone_device_memory_bytes{device="0",kind="TPU v4",'
        'stat="limit"} 33' in text
    )
    # the stats-less accelerator contributed NO series (absent != zero)
    assert 'device="1"' not in text
    # non-cpu devices present: no host-RAM fallback row either
    assert 'memory_bytes{device="host"' not in text


def test_sampler_cpu_without_stats_falls_back_to_host_ram():
    reg = MetricsRegistry()
    sampler = device_mod.DeviceMemorySampler(
        registry=reg,
        devices=[FakeDevice(kind="cpu", platform="cpu", stats=None)],
    )
    assert sampler.sample_once() == 0
    text = render(reg.collect())
    assert (
        'keystone_device_memory_bytes{device="host",kind="host-ram",'
        'stat="limit"}' in text
    )


def test_sampler_empty_device_list_stays_absent():
    # backend-init failure (no devices at all) must NOT scrape like a
    # healthy CPU host: no host-RAM fallback, family absent
    reg = MetricsRegistry()
    sampler = device_mod.DeviceMemorySampler(registry=reg, devices=[])
    assert sampler.sample_once() == 0
    assert "keystone_device_memory_bytes{" not in render(reg.collect())


def test_acquire_memory_sampler_tightest_interval_wins():
    # a second holder asking for a tighter cadence must not be
    # silently handed the first holder's slower one
    reg = MetricsRegistry()
    a = device_mod.acquire_memory_sampler(registry=reg, interval_s=60.0)
    b = device_mod.acquire_memory_sampler(registry=reg, interval_s=1.0)
    c = device_mod.acquire_memory_sampler(registry=reg, interval_s=30.0)
    try:
        assert a is b is c
        assert a.interval_s == 1.0  # tightened, never loosened
    finally:
        for s in (a, b, c):
            device_mod.release_memory_sampler(s)


def test_acquire_release_memory_sampler_refcounts():
    # admin + gateway in one process share ONE thread per registry
    reg = MetricsRegistry()
    a = device_mod.acquire_memory_sampler(registry=reg, interval_s=60.0)
    b = device_mod.acquire_memory_sampler(registry=reg)
    try:
        assert a is b
        assert a._thread is not None and a._thread.is_alive()
        device_mod.release_memory_sampler(a)
        assert a._thread.is_alive()  # still held by b
    finally:
        device_mod.release_memory_sampler(b)
    assert a._thread is None  # last release stopped the thread
    # a directly-constructed sampler releases to a plain stop()
    solo = device_mod.DeviceMemorySampler(registry=reg, devices=[])
    solo.start()
    device_mod.release_memory_sampler(solo)
    assert solo._thread is None


def test_sampler_start_stop_thread():
    reg = MetricsRegistry()
    sampler = device_mod.DeviceMemorySampler(
        registry=reg, interval_s=0.05,
        devices=[FakeDevice(stats={"bytes_in_use": 5})],
    )
    sampler.start()
    try:
        assert sampler._thread.is_alive()
        gauge = reg.gauge(
            "keystone_device_memory_bytes", "",
            ("device", "kind", "stat"),
        )
        assert gauge.get(("0", "TPU v4", "in_use")) == 5.0
    finally:
        sampler.stop()
    assert sampler._thread is None
