"""Live-histogram drift detection: the PSI math, windowed
observation, absent-not-zero scoring (no baseline / too few rows),
threshold flagging with flight-recorder capture, and the registry
export."""

import pytest

from keystone_tpu.observability.drift import (
    DEFAULT_THRESHOLD,
    DriftDetector,
    psi,
)
from keystone_tpu.observability.flight import FlightRecorder
from keystone_tpu.observability.registry import MetricsRegistry


# -- psi -------------------------------------------------------------------


def test_psi_identical_distributions_is_zero():
    assert psi({1: 80, 2: 20}, {1: 40, 2: 10}) == pytest.approx(
        0.0, abs=1e-9
    )


def test_psi_grows_with_divergence():
    base = {1: 80, 2: 20}
    mild = psi(base, {1: 70, 2: 30})
    wild = psi(base, {1: 10, 2: 90})
    assert 0 < mild < wild


def test_psi_disjoint_support_is_large():
    # a full population swap must land far past any sane threshold
    assert psi({1: 100}, {32: 100}) > 1.0


def test_psi_empty_inputs_are_none():
    assert psi({}, {1: 10}) is None
    assert psi({1: 10}, {}) is None
    assert psi({}, {}) is None


def test_psi_symmetric_in_magnitude():
    a, b = {1: 90, 8: 10}, {1: 10, 8: 90}
    assert psi(a, b) == pytest.approx(psi(b, a))


# -- DriftDetector ---------------------------------------------------------


def _detector(**kw):
    kw.setdefault("min_rows", 4)
    clock = {"t": 0.0}
    det = DriftDetector(clock=lambda: clock["t"], **kw)
    return det, clock


def test_no_baseline_means_no_score():
    det, _ = _detector()
    for _ in range(10):
        det.observe("m", 1)
    assert det.scores() == {}
    assert det.drifted() == []


def test_too_few_rows_means_no_score():
    det, _ = _detector(min_rows=8)
    det.set_baseline("m", {1: 80, 2: 20})
    for _ in range(7):
        det.observe("m", 1)
    assert "m" not in det.scores()
    det.observe("m", 1)
    assert "m" in det.scores()


def test_matching_traffic_scores_low_and_shifted_high():
    det, _ = _detector()
    det.set_baseline("m", {1: 80, 2: 20})
    for _ in range(8):
        det.observe("m", 1)
    for _ in range(2):
        det.observe("m", 2)
    assert det.scores()["m"] < 0.1
    det2, _ = _detector()
    det2.set_baseline("m", {1: 100})
    for _ in range(10):
        det2.observe("m", 32)
    assert det2.scores()["m"] > DEFAULT_THRESHOLD
    assert det2.drifted() == ["m"]


def test_window_prunes_old_observations():
    det, clock = _detector(window_s=10.0)
    det.set_baseline("m", {1: 100})
    for _ in range(6):
        det.observe("m", 32)  # t=0: shifted traffic
    clock["t"] = 11.0  # the shifted burst ages out of the window
    for _ in range(6):
        det.observe("m", 1)  # matching traffic again
    assert det.scores()["m"] < 0.1
    assert det.live_histogram("m") == {1: 6}


def test_flight_capture_on_threshold_entry_only():
    """Crossing the threshold captures ONE forensic record (reason
    ``drift``); staying over it must not spam the ring."""
    reg = MetricsRegistry()
    flight = FlightRecorder(registry=reg)
    det, _ = _detector(flight=flight)
    det.set_baseline("m", {1: 100})
    for _ in range(4):
        det.observe("m", 32)
    det.scores()
    det.observe("m", 32)
    det.scores()  # still drifted: no second record
    records = [r for r in flight.records() if r.reason == "drift"]
    assert len(records) == 1
    assert records[0].attrs["model"] == "m"
    assert records[0].attrs["psi"] > DEFAULT_THRESHOLD


def test_clearing_baseline_clears_score_and_flag():
    det, _ = _detector()
    det.set_baseline("m", {1: 100})
    for _ in range(4):
        det.observe("m", 32)
    assert det.drifted() == ["m"]
    det.set_baseline("m", {})
    assert det.scores() == {}
    assert det.drifted() == []


def test_registry_export_absent_until_scoreable():
    from keystone_tpu.observability import prometheus

    reg = MetricsRegistry()
    det, _ = _detector()
    det.register(reg)
    det.set_baseline("m", {1: 100})
    body = prometheus.render(reg.collect())
    # metadata may render, but no SAMPLE exists until scoreable
    assert "keystone_drift_score{" not in body
    for _ in range(4):
        det.observe("m", 32)
    body = prometheus.render(reg.collect())
    assert 'keystone_drift_score{model="m"}' in body


def test_document_shape():
    det, _ = _detector()
    det.set_baseline("m", {1: 100})
    for _ in range(4):
        det.observe("m", 1)
    doc = det.document()
    assert doc["threshold"] == DEFAULT_THRESHOLD
    assert doc["min_rows"] == 4
    assert doc["scores"]["m"] == pytest.approx(0.0, abs=1e-6)
    assert doc["drifted"] == []
    # histogram keys are stringified — the document is JSON-bound
    assert doc["baselines"]["m"] == {"1": 100.0}
    assert doc["live"]["m"] == {"1": 4}
