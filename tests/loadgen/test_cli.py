"""The serve-loadgen CLI: a requested fault that never fires must
turn the verdict red (a green verdict may never mean "the chaos
silently didn't happen"), and the happy path exits 0."""

import json

from keystone_tpu.loadgen import cli


def _verdict_from(out: str) -> dict:
    # the verdict is the last (indented) JSON document on stdout
    return json.loads(out[out.index('{\n "passed"'):])


def test_cli_red_when_requested_fault_never_fires(capsys):
    # match lane 99: the 1-lane gateway never routes there, so the
    # armed point can never fire — the run must NOT pass
    rc = cli.main([
        "--self-gateway", "--d", "8", "--buckets", "4,8",
        "--lanes", "1",
        "--synthetic", "30", "--rate", "100",
        "--fault", "gateway.lane.kill=lane:99",
        "--fault-at", "0.05", "--fault-for", "0.1",
        "--settle-s", "0.3", "--recovery-s", "1",
    ])
    assert rc == 1
    doc = _verdict_from(capsys.readouterr().out)
    assert doc["passed"] is False
    fired = [
        r for r in doc["invariants"]
        if r["name"] == "requested_fault_actually_fired"
    ]
    assert len(fired) == 1 and not fired[0]["passed"]
    assert doc["stats"]["injections"]["gateway.lane.kill"] == 0


def test_cli_green_fault_fires_and_verdict_reports_injections(capsys):
    # short run on a shared-CPU test host: the point here is the
    # injection-audit plumbing, so the p99 bound is deliberately
    # generous (the tight 1.5x contract is exercised by the bench
    # rows and smoke-chaos over properly sized runs)
    rc = cli.main([
        "--self-gateway", "--d", "8", "--buckets", "4,8",
        "--lanes", "2",
        "--synthetic", "160", "--rate", "80",
        "--fault", "gateway.lane.kill=lane:0",
        "--fault-at", "0.6", "--fault-for", "0.4",
        "--settle-s", "1.5", "--recovery-s", "8",
        "--p99-factor", "20",
    ])
    doc = _verdict_from(capsys.readouterr().out)
    assert rc == 0, doc
    assert doc["passed"] is True
    fired = [
        r for r in doc["invariants"]
        if r["name"] == "requested_fault_actually_fired"
    ]
    assert len(fired) == 1 and fired[0]["passed"]
    assert doc["stats"]["injections"]["gateway.lane.kill"] >= 1
