"""The fault-injection plane: deterministic arm/fire/clear semantics,
the unarmed-is-a-no-op hot-path contract, env arming, triggers, and
each WIRED fault point firing in the real code path it claims to."""

import threading
import time

import numpy as np
import pytest

from keystone_tpu.loadgen import faults
from keystone_tpu.loadgen.faults import (
    FAULT_POINTS,
    FaultInjected,
    FaultInjector,
)

from gateway_fixtures import D, batch, make_fitted, reference


# -- injector semantics ----------------------------------------------------


def test_unarmed_fire_is_none_and_armed_flag_false():
    inj = FaultInjector()
    assert inj.armed is False
    assert inj.fire("anything") is None


def test_unarmed_hot_path_does_no_slow_work():
    """The no-op contract: with nothing armed, fire() never reaches
    the slow path — asserted with a counting stub standing in for
    _fire_slow (the first thing that would lock/allocate)."""
    inj = FaultInjector()
    calls = [0]

    def counting_stub(point, ctx):
        calls[0] += 1
        return None

    inj._fire_slow = counting_stub
    for _ in range(10_000):
        assert inj.fire("gateway.lane.kill") is None
    assert calls[0] == 0, (
        f"unarmed fire() reached the slow path {calls[0]} times"
    )
    # arming flips the gate: the same call now consults the stub
    inj.armed = True
    inj.fire("gateway.lane.kill")
    assert calls[0] == 1


def test_armed_gate_tracks_injector_state():
    """The wired call sites guard with faults.armed() so the unarmed
    path never even builds a ctx dict; the gate must track arming
    exactly."""
    assert faults.armed() is False
    faults.arm("gate.point", count=1)
    assert faults.armed() is True
    faults.fire("gate.point")  # count exhausted -> auto-disarm
    assert faults.armed() is False
    faults.arm("gate.point")
    faults.disarm("gate.point")
    assert faults.armed() is False


def test_global_unarmed_fire_skips_slow_path_too():
    inj = faults.get_injector()
    orig = inj._fire_slow
    calls = [0]

    def counting_stub(point, ctx):
        calls[0] += 1
        return orig(point, ctx)

    inj._fire_slow = counting_stub
    try:
        for _ in range(1000):
            faults.fire("engine.dispatch.error")
        assert calls[0] == 0
    finally:
        inj._fire_slow = orig


def test_count_bounds_fires_and_auto_disarms():
    inj = FaultInjector()
    inj.arm("p", count=2)
    assert inj.fire("p") is not None
    assert inj.fire("p") is not None
    assert inj.fire("p") is None  # exhausted
    assert inj.armed is False     # gate dropped with the last spec
    assert inj.fired_count("p") == 2


def test_for_s_expires_the_spec():
    inj = FaultInjector()
    inj.arm("p", for_s=0.05)
    assert inj.fire("p") is not None
    time.sleep(0.1)
    assert inj.fire("p") is None
    assert "p" not in inj.status()["armed"]


def test_match_filters_by_context():
    inj = FaultInjector()
    inj.arm("p", match={"lane": 0})
    assert inj.fire("p", {"lane": 1}) is None
    assert inj.fire("p") is None          # no ctx can't match
    assert inj.fire("p", {"lane": 0}) is not None


def test_disarm_and_disarm_all():
    inj = FaultInjector()
    inj.arm("a")
    inj.arm("b")
    assert inj.disarm("a") is True
    assert inj.disarm("a") is False
    assert inj.armed is True
    inj.disarm_all()
    assert inj.armed is False
    assert inj.fire("b") is None


def test_rearm_replaces_spec():
    inj = FaultInjector()
    inj.arm("p", count=1)
    inj.arm("p", count=5)  # replaces; fired resets on the new spec
    for _ in range(5):
        assert inj.fire("p") is not None
    assert inj.fire("p") is None


def test_status_surfaces_catalog_armed_and_fired():
    inj = FaultInjector()
    inj.arm("gateway.lane.kill", count=3, match={"lane": 1})
    inj.fire("gateway.lane.kill", {"lane": 1})
    doc = inj.status()
    assert set(doc["points"]) == set(FAULT_POINTS)
    armed = doc["armed"]["gateway.lane.kill"]
    assert armed["count"] == 3 and armed["fired"] == 1
    assert armed["match"] == {"lane": 1}
    assert doc["fired_total"]["gateway.lane.kill"] == 1


def test_injection_counter_on_global_registry():
    from keystone_tpu.observability.registry import get_global_registry

    counter = get_global_registry().counter(
        "keystone_fault_injections_total",
        "chaos fault-point fires, by point",
        ("point",),
    )
    before = counter.get(("test.counter.point",))
    faults.arm("test.counter.point", count=2)
    faults.fire("test.counter.point")
    faults.fire("test.counter.point")
    assert counter.get(("test.counter.point",)) == before + 2


# -- env arming ------------------------------------------------------------


def test_parse_fault_spec_grammar():
    kw = faults.parse_fault_spec("a.b=count:3,delay_ms:7.5,for_s:2,lane:0")
    assert kw == {
        "point": "a.b", "count": 3, "delay_ms": 7.5, "for_s": 2.0,
        "match": {"lane": 0},
    }
    assert faults.parse_fault_spec("bare.point") == {"point": "bare.point"}
    with pytest.raises(ValueError):
        faults.parse_fault_spec("p=notakv")
    with pytest.raises(ValueError):
        faults.parse_fault_spec("")


def test_arm_from_env_arms_each_clause():
    specs = faults.arm_from_env(
        {"KEYSTONE_FAULTS": "env.a=count:2 env.b=delay_ms:5,engine:x"}
    )
    assert [s.point for s in specs] == ["env.a", "env.b"]
    inj = faults.get_injector()
    assert inj.fire("env.a") is not None
    assert inj.fire("env.b", {"engine": "x"}).delay_ms == 5.0
    assert faults.arm_from_env({}) == []  # absent env: no-op


# -- triggers --------------------------------------------------------------


def test_trigger_runs_on_arm_and_unregister_stops_it():
    inj = FaultInjector()
    ran = threading.Event()
    seen = []

    def trig(spec):
        seen.append(spec.point)
        ran.set()

    unregister = inj.register_trigger("t.point", trig, ctx={"g": "a"})
    inj.arm("t.point")
    assert ran.wait(2.0), "trigger never ran"
    assert seen == ["t.point"]
    assert inj.fired_count("t.point") == 1
    # trigger points are one-shot per arm: the spec auto-disarms once
    # the callbacks ran, so the hot-path gate doesn't stay pinned True
    deadline = time.perf_counter() + 2.0
    while inj.armed and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert not inj.armed, "trigger spec stayed armed after firing"
    unregister()
    ran.clear()
    inj.arm("t.point")
    time.sleep(0.1)
    assert not ran.is_set(), "unregistered trigger still ran"


def test_trigger_match_filters_on_registration_ctx():
    inj = FaultInjector()
    ran = threading.Event()
    inj.register_trigger("t.m", lambda s: ran.set(), ctx={"g": "a"})
    inj.arm("t.m", match={"g": "OTHER"})
    time.sleep(0.1)
    assert not ran.is_set()
    inj.arm("t.m", match={"g": "a"})
    assert ran.wait(2.0)


# -- the wired fault points fire in their real code paths ------------------


def test_engine_dispatch_error_fires_and_clears(fitted):
    engine = fitted.compiled(buckets=(4, 8), name="chaos-engine")
    xs = batch(3, seed=7)
    want = reference(fitted, xs)
    np.testing.assert_allclose(
        np.asarray(engine.apply(xs, sync=True)), want,
        rtol=1e-4, atol=1e-5,
    )
    faults.arm(
        "engine.dispatch.error", match={"engine": "chaos-engine"},
        count=1,
    )
    with pytest.raises(FaultInjected):
        engine.apply(xs, sync=True)
    # count=1 auto-disarmed: the next dispatch is healthy again
    np.testing.assert_allclose(
        np.asarray(engine.apply(xs, sync=True)), want,
        rtol=1e-4, atol=1e-5,
    )


def test_engine_dispatch_error_match_spares_other_engines(fitted):
    target = fitted.compiled(buckets=(4, 8), name="chaos-target")
    other = fitted.compiled(buckets=(4, 8), name="chaos-other")
    xs = batch(2, seed=8)
    faults.arm(
        "engine.dispatch.error", match={"engine": "chaos-target"}
    )
    with pytest.raises(FaultInjected):
        target.apply(xs, sync=True)
    # the unmatched engine is untouched while the point stays armed
    np.testing.assert_allclose(
        np.asarray(other.apply(xs, sync=True)),
        reference(fitted, xs), rtol=1e-4, atol=1e-5,
    )


def test_lane_kill_is_absorbed_by_pool_retry(fitted):
    """gateway.lane.kill matched to lane 0: requests route, die on
    lane 0, retry on lane 1, and resolve CORRECTLY — the caller never
    sees the fault."""
    from keystone_tpu.gateway.pool import EnginePool

    pool = EnginePool(
        lambda name: fitted.compiled(buckets=(4, 8), name=name),
        2, name="chaos-pool", max_delay_ms=1.0,
    )
    try:
        faults.arm("gateway.lane.kill", match={"lane": 0})
        xs = batch(6, seed=9)
        want = reference(fitted, xs)
        futures = [pool.submit(x) for x in xs]
        for i, f in enumerate(futures):
            np.testing.assert_allclose(
                np.asarray(f.result(timeout=30)), want[i],
                rtol=1e-4, atol=1e-5,
            )
        assert faults.get_injector().fired_count("gateway.lane.kill") > 0
    finally:
        faults.disarm_all()
        pool.close()


def test_host_prep_stall_delays_but_stays_correct(fitted):
    from keystone_tpu.serving.batching import MicroBatcher

    engine = fitted.compiled(buckets=(4, 8), name="chaos-stall")
    engine.warmup(example=np.zeros(D, np.float32))
    xs = batch(4, seed=10)
    want = reference(fitted, xs)
    with MicroBatcher(
        engine, max_delay_ms=1.0, pipeline_depth=2
    ) as mb:
        # unarmed pass warms the staged path
        for i, f in enumerate([mb.submit(x) for x in xs]):
            np.testing.assert_allclose(
                np.asarray(f.result(timeout=30)), want[i],
                rtol=1e-4, atol=1e-5,
            )
        faults.arm("pipeline.host_prep.stall", delay_ms=30.0, count=1)
        t0 = time.perf_counter()
        futures = [mb.submit(x) for x in xs]
        for i, f in enumerate(futures):
            np.testing.assert_allclose(
                np.asarray(f.result(timeout=30)), want[i],
                rtol=1e-4, atol=1e-5,
            )
        assert time.perf_counter() - t0 >= 0.03, (
            "the stall point never stalled"
        )
    assert (
        faults.get_injector().fired_count("pipeline.host_prep.stall") >= 1
    )


def test_otlp_blackhole_drops_batches_without_posting():
    from keystone_tpu.observability.otlp import OtlpSpanExporter
    from keystone_tpu.observability.tracing import Span

    exporter = OtlpSpanExporter(
        # a port nothing listens on: if blackhole failed to intercept,
        # the POST path would count result="error" instead
        "http://127.0.0.1:9/v1/traces",
        batch_size=2, flush_interval_s=60.0,
    )
    faults.arm("otlp.export.blackhole")
    span = Span(
        name="s", span_id=1, parent_id=None, start_s=0.0,
        duration_s=0.001, thread_id=0, attrs={},
    )
    exporter.submit(span)
    exporter.submit(span)
    exporter._flush_once()
    assert exporter._posts.get(("blackhole",)) == 1
    assert exporter._posts.get(("error",)) == 0
    assert exporter._spans.get(("dropped",)) >= 2
    assert faults.get_injector().fired_count("otlp.export.blackhole") == 1


def test_swap_force_trigger_forces_a_live_rebucket(fitted):
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway

    gw = Gateway(
        fitted, buckets=(4, 8), n_lanes=1, max_delay_ms=1.0,
        warmup_example=jnp.zeros(D, jnp.float32),
        name="chaos-swap-gw",
    )
    try:
        assert gw.metrics.swap_count() == 0
        faults.arm("gateway.swap.force", match={"gateway": "chaos-swap-gw"})
        deadline = time.perf_counter() + 30
        while (
            gw.metrics.swap_count() == 0
            and time.perf_counter() < deadline
        ):
            time.sleep(0.05)
        assert gw.metrics.swap_count() == 1, (
            "arming gateway.swap.force never forced a swap"
        )
        # traffic still serves across the chaos-forced swap
        xs = batch(2, seed=11)
        for i, f in enumerate([gw.predict(x) for x in xs]):
            np.testing.assert_allclose(
                np.asarray(f.result(timeout=30)),
                reference(fitted, xs)[i], rtol=1e-4, atol=1e-5,
            )
    finally:
        faults.disarm_all()
        gw.close()
    # close() unregistered the trigger: re-arming swaps nothing
    swaps = gw.metrics.swap_count()
    faults.arm("gateway.swap.force", match={"gateway": "chaos-swap-gw"})
    time.sleep(0.2)
    assert gw.metrics.swap_count() == swaps
