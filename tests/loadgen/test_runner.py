"""The open-loop generator: gap preservation with speed scaling,
response-independence (open loop, not closed), the chaos timeline,
and outcome classification through both targets."""

import threading
import time

import pytest

from keystone_tpu.loadgen.runner import (
    FaultPlan,
    InprocTarget,
    LoadGenerator,
    LoadReport,
    RequestRecord,
)
from keystone_tpu.loadgen.trace import TraceEvent


class StubTarget:
    """Records issue times; responds after ``service_s``."""

    def __init__(self, service_s=0.0):
        self.service_s = service_s
        self.issued = []
        self.armed = []
        self.disarmed = []
        self._lock = threading.Lock()

    def send(self, event):
        with self._lock:
            self.issued.append(time.perf_counter())
        if self.service_s:
            time.sleep(self.service_s)
        return RequestRecord(
            0, 0.0, 0.0, "ok", n_rows=event.n_rows,
            latency_s=self.service_s,
        )

    def ready(self):
        return True

    def arm_fault(self, spec):
        self.armed.append((time.perf_counter(), dict(spec)))

    def disarm_fault(self, point):
        self.disarmed.append(point)


def _events(gaps):
    ts, out = 0.0, []
    for g in gaps:
        ts += g
        out.append(TraceEvent(ts=ts))
    return out


def test_replay_preserves_gaps():
    target = StubTarget()
    events = _events([0.0, 0.15, 0.15])
    LoadGenerator(target).run(events)
    gaps = [
        b - a for a, b in zip(target.issued, target.issued[1:])
    ]
    assert gaps[0] == pytest.approx(0.15, abs=0.05)
    assert gaps[1] == pytest.approx(0.15, abs=0.05)


def test_speed_scales_the_clock():
    target = StubTarget()
    events = _events([0.0, 0.2, 0.2])
    LoadGenerator(target).run(events, speed=4.0)
    gaps = [
        b - a for a, b in zip(target.issued, target.issued[1:])
    ]
    assert gaps[0] == pytest.approx(0.05, abs=0.04)
    assert gaps[1] == pytest.approx(0.05, abs=0.04)


def test_open_loop_issues_do_not_wait_for_responses():
    """A 300 ms server must not stretch a 3 x 30 ms arrival schedule:
    issue times follow the generator's clock, not the responses."""
    target = StubTarget(service_s=0.3)
    events = _events([0.0, 0.03, 0.03])
    report = LoadGenerator(target).run(events)
    assert len(target.issued) == 3
    span = target.issued[-1] - target.issued[0]
    assert span < 0.25, (
        f"arrivals took {span:.3f}s — the generator went closed-loop"
    )
    assert report.by_status() == {"ok": 3}
    # and every record still resolved with its latency
    assert all(r.latency_s for r in report.records)


def test_records_carry_schedule_lag():
    target = StubTarget()
    report = LoadGenerator(target).run(_events([0.0, 0.01]))
    for rec in report.records:
        assert rec.behind_s >= 0.0
        assert rec.t_send >= rec.t_sched


def test_fault_timeline_arms_mid_run_and_clears_at_end():
    target = StubTarget()
    events = _events([0.0] + [0.02] * 9)  # ~0.18s of arrivals
    plan = FaultPlan(
        spec={"point": "x.y", "delay_ms": 1}, at_s=0.1, for_s=5.0,
    )
    report = LoadGenerator(target).run(
        events, faults=[plan], recovery_probe_s=0.5
    )
    assert len(target.armed) == 1
    t_arm, spec = target.armed[0]
    assert spec["point"] == "x.y"
    assert spec["for_s"] == 5.0  # the self-disarm rides the spec
    # armed ~0.1s in, not at the start
    assert t_arm - target.issued[0] == pytest.approx(0.1, abs=0.06)
    # for_s outlived the run: the runner disarmed it explicitly and
    # stamped the actual clear time
    assert target.disarmed == ["x.y"]
    w = report.fault_windows[0]
    assert w.t_clear is not None and w.t_clear <= report.duration_s
    # target was ready: recovery measured
    assert report.ready_probed
    assert report.ready_recovery_s is not None


def test_fault_window_t_clear_honors_spec_level_for_s():
    """A duration given INSIDE the spec clause (for_s:N) must stamp
    the window's clear time just like FaultPlan.for_s — otherwise the
    recovery invariants measure against the wrong window."""
    target = StubTarget()
    events = _events([0.0, 0.02])
    plan = FaultPlan(
        spec={"point": "x.y", "for_s": 0.05}, at_s=0.0, for_s=None,
    )
    report = LoadGenerator(target).run(
        events, faults=[plan], recovery_probe_s=0.2, settle_s=0.1
    )
    w = report.fault_windows[0]
    assert w.t_clear == pytest.approx(w.t_arm + 0.05, abs=0.001)
    # the server self-disarms; the driver must NOT disarm again after
    # the window already closed on its own
    assert target.disarmed == []


def test_fault_at_waits_through_a_sparse_gap():
    """A plan must arm at ITS instant, not at the head of a long
    inter-arrival gap — arming early would let for_s expire the fault
    before any request ever meets it."""
    target = StubTarget()
    events = _events([0.0, 0.6])
    plan = FaultPlan(spec={"point": "x.y"}, at_s=0.3, for_s=0.1)
    LoadGenerator(target).run(
        events, faults=[plan], recovery_probe_s=0.2
    )
    t_arm, _ = target.armed[0]
    assert t_arm - target.issued[0] == pytest.approx(0.3, abs=0.08)


def test_report_stats_shape():
    target = StubTarget()
    report = LoadGenerator(target).run(_events([0.0, 0.01, 0.01]))
    stats = report.stats()
    assert stats["issued"] == 3
    assert stats["resolved"] == 3
    assert stats["lost"] == 0
    assert stats["untyped_failures"] == 0
    assert stats["shed_rate"] == 0.0
    assert stats["duration_s"] > 0


def test_p99_windows_select_by_send_time():
    report = LoadReport()
    for t, lat in [(0.0, 0.010), (1.0, 0.020), (2.0, 0.500)]:
        report.add(RequestRecord(0, t, t, "ok", latency_s=lat))
    assert report.p99(0.0, 2.0) == pytest.approx(0.02, rel=0.01)
    assert report.p99(2.0) == pytest.approx(0.5)
    assert report.p99(5.0) is None


# -- the in-process target classifies real gateway outcomes ----------------


def test_inproc_target_classifies_shed_and_ok(fitted):
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway

    from gateway_fixtures import D

    gw = Gateway(
        fitted, buckets=(4, 8), n_lanes=1, max_delay_ms=1.0,
        warmup_example=jnp.zeros(D, jnp.float32),
        name="runner-inproc",
    )
    try:
        target = InprocTarget(gw, default_shape=(D,))
        ok = target.send(TraceEvent(ts=0.0, n_rows=2, shape=(D,)))
        assert ok.status == "ok" and not ok.untyped
        assert ok.latency_s is not None
    finally:
        gw.close()
    # a draining gateway sheds typed ("closed") — not an untyped error
    shed = target.send(TraceEvent(ts=0.0, n_rows=1, shape=(D,)))
    assert shed.status == "shed"
    assert shed.reason == "closed"
    assert not shed.untyped


def test_inproc_target_untyped_error_is_flagged(fitted):
    """An engine fault that escapes the retry plane must classify as
    an UNTYPED failure — the thing the invariant checker exists to
    catch. One lane + a dispatch error on it = no retry lane, the
    fault reaches the caller."""
    import jax.numpy as jnp

    from keystone_tpu.gateway import Gateway
    from keystone_tpu.loadgen import faults

    from gateway_fixtures import D

    with Gateway(
        fitted, buckets=(4, 8), n_lanes=1, max_delay_ms=1.0,
        warmup_example=jnp.zeros(D, jnp.float32),
        name="runner-untyped",
    ) as gw:
        target = InprocTarget(gw, default_shape=(D,))
        faults.arm("engine.dispatch.error")
        try:
            rec = target.send(TraceEvent(ts=0.0, n_rows=1, shape=(D,)))
        finally:
            faults.disarm_all()
        assert rec.status == "error"
        assert rec.untyped
        assert "FaultInjected" in rec.reason


class _FakeResponse:
    def read(self):
        return b"{}"

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_feedback_sender_samples_and_posts(monkeypatch):
    import urllib.request

    import numpy as np

    from keystone_tpu.loadgen import runner

    posted = []

    def fake_urlopen(req, timeout=None):
        posted.append(req)
        return _FakeResponse()

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    sender = runner.FeedbackSender(
        "http://example.invalid",
        labeler=lambda xs: np.zeros_like(xs),
        fraction=0.25,
        max_queue=256,
    )
    for _ in range(100):
        sender.offer(np.ones((2, 4), np.float32))
    stats = sender.close()
    # deterministic integer-part sampling: exactly fraction of offers
    assert len(posted) == 25
    assert stats["sent"] == 25 * 2  # rows, not requests
    assert stats["dropped"] == 0
    assert stats["errors"] == 0
    assert all(r.full_url.endswith("/feedback") for r in posted)


def test_feedback_sender_errors_never_block(monkeypatch):
    import urllib.request

    import numpy as np

    from keystone_tpu.loadgen import runner

    def exploding_urlopen(req, timeout=None):
        raise OSError("connection refused")

    monkeypatch.setattr(urllib.request, "urlopen", exploding_urlopen)
    sender = runner.FeedbackSender(
        "http://example.invalid",
        labeler=lambda xs: np.zeros_like(xs),
        fraction=1.0,
    )
    for _ in range(5):
        sender.offer(np.ones((1, 4), np.float32))
    stats = sender.close()
    assert stats["errors"] == 5
    assert stats["sent"] == 0


def test_feedback_sender_fraction_validation():
    from keystone_tpu.loadgen.runner import FeedbackSender

    with pytest.raises(ValueError):
        FeedbackSender("http://x", labeler=lambda xs: xs, fraction=1.5)
