"""Trace parsing (old + new request-log formats, POST collapsing) and
synthetic workload generation (arrival processes, size mixtures,
deadline distributions — all seeded-deterministic)."""

import json

import numpy as np
import pytest

from keystone_tpu.loadgen import trace


def _new_line(ts, n_rows=1, shape=(6,), deadline_ms=None, status=200,
              model=None):
    doc = {
        "ts": ts, "path": "/predict", "status": status,
        "latency_ms": 2.0, "lane": 0, "trace_id": "ab" * 16,
        "n_rows": n_rows, "shape": list(shape),
        "deadline_ms": deadline_ms,
    }
    if model is not None:
        doc["model"] = model
    return json.dumps(doc)


def _old_line(ts, status=200):
    # the pre-loadgen format: no n_rows / shape / deadline_ms
    return json.dumps({
        "ts": ts, "path": "/predict", "status": status,
        "latency_ms": 1.5, "lane": 1, "trace_id": None,
    })


def test_parse_new_format_line():
    ev = trace.parse_request_log_line(
        _new_line(12.5, n_rows=3, shape=(4, 2), deadline_ms=50.0)
    )
    assert ev.ts == 12.5
    assert ev.n_rows == 3
    assert ev.shape == (4, 2)
    assert ev.deadline_ms == 50.0
    assert ev.status == 200


def test_parse_old_format_tolerated_as_single_instance():
    ev = trace.parse_request_log_line(_old_line(3.25))
    assert ev is not None
    assert ev.n_rows == 1
    assert ev.shape is None
    assert ev.deadline_ms is None
    assert ev.lane == 1


def test_parse_skips_non_record_lines():
    lines = [
        "gateway: http://127.0.0.1:1234 (POST /predict, ...)",
        "",
        "{not json",
        json.dumps({"no_ts_field": 1}),
        json.dumps({"ts": 1.0, "path": "/other"}),  # not a predict
        _old_line(1.0),
        _new_line(2.0),
    ]
    events = trace.parse_request_log(lines)
    assert len(events) == 2


def test_parse_model_round_trip():
    # zoo / lifecycle gateways tag every request-log line with the
    # model id; the parsed event must carry it so a replay hits the
    # same per-model route (/predict/<model>)
    ev = trace.parse_request_log_line(_new_line(1.0, model="resnet"))
    assert ev.model == "resnet"
    ev = trace.parse_request_log_line(_new_line(1.0))
    assert ev.model is None


def test_collapse_never_merges_across_models():
    # two adjacent same-shape lines from DIFFERENT models are two
    # POSTs — without the model guard the adjacency fallback would
    # fold them into one
    lines = [
        _new_line(1.0, n_rows=2, model="a"),
        _new_line(1.0, n_rows=2, model="b"),
    ]
    events = trace.collapse_posts(trace.parse_request_log(lines))
    assert len(events) == 2
    assert [e.model for e in events] == ["a", "b"]
    # same model: the pair is one 2-instance POST again
    lines = [
        _new_line(1.0, n_rows=2, model="a"),
        _new_line(1.0, n_rows=2, model="a"),
    ]
    events = trace.collapse_posts(trace.parse_request_log(lines))
    assert len(events) == 1
    assert events[0].model == "a"
    assert events[0].n_rows == 2


def test_normalize_preserves_model():
    events = trace.parse_request_log(
        [_new_line(5.0, model="m0"), _new_line(6.0, model="m0")]
    )
    normalized = trace.normalize(events)
    assert normalized[0].ts == 0.0
    assert all(e.model == "m0" for e in normalized)


def test_collapse_folds_per_instance_lines_into_posts():
    # a 3-instance POST logs 3 adjacent lines with n_rows=3
    lines = [_new_line(1.0, n_rows=3) for _ in range(3)]
    # then a 1-instance POST
    lines.append(_new_line(1.2, n_rows=1))
    # then a SHED 2-instance POST that logged only one line
    lines.append(_new_line(1.4, n_rows=2, status=429))
    events = trace.collapse_posts(trace.parse_request_log(lines))
    assert [e.n_rows for e in events] == [3, 1, 2]


def test_collapse_splits_runs_longer_than_n_rows():
    # two back-to-back 2-instance POSTs: 4 identical-looking lines
    lines = [_new_line(1.0, n_rows=2) for _ in range(4)]
    events = trace.collapse_posts(trace.parse_request_log(lines))
    assert [e.n_rows for e in events] == [2, 2]


def test_collapse_dedupes_by_post_seq_despite_interleaving():
    """Concurrent handler threads interleave their per-instance lines
    in the file; post_seq (stamped per POST since this subsystem
    landed) makes collapsing immune to the ordering."""
    def seq_line(ts, n_rows, seq):
        doc = json.loads(_new_line(ts, n_rows=n_rows))
        doc["post_seq"] = seq
        return json.dumps(doc)

    # a 4-row POST (seq 1) fragmented by a 1-row POST (seq 2)
    lines = [
        seq_line(1.0, 4, 1),
        seq_line(1.0, 4, 1),
        seq_line(1.001, 1, 2),
        seq_line(1.001, 4, 1),
        seq_line(1.002, 4, 1),
    ]
    events = trace.collapse_posts(trace.parse_request_log(lines))
    assert [(e.n_rows, e.post_seq) for e in events] == [(4, 1), (1, 2)]


def test_collapse_respects_the_post_window():
    # same shape/n_rows but seconds apart: different POSTs
    lines = [_new_line(1.0, n_rows=2), _new_line(3.0, n_rows=2)]
    events = trace.collapse_posts(trace.parse_request_log(lines))
    assert len(events) == 2


def test_load_trace_no_collapse_is_one_instance_per_line(tmp_path):
    # keeping n_rows on every per-instance line would replay n_rows^2
    # instances per POST; --no-collapse means one 1-instance request
    # per recorded line
    path = tmp_path / "req.jsonl"
    path.write_text(
        "\n".join(_new_line(1.0, n_rows=4) for _ in range(4)) + "\n"
    )
    events = trace.load_trace(str(path), collapse=False)
    assert len(events) == 4
    assert all(e.n_rows == 1 for e in events)


def test_load_trace_round_trip(tmp_path):
    path = tmp_path / "req.jsonl"
    path.write_text(
        "\n".join(
            ["banner"]
            + [_new_line(10.0, n_rows=2) for _ in range(2)]
            + [_new_line(10.5, n_rows=1, deadline_ms=25.0)]
        ) + "\n"
    )
    events = trace.load_trace(str(path))
    assert [e.n_rows for e in events] == [2, 1]
    assert events[0].ts == 0.0          # normalized to start at 0
    assert events[1].ts == pytest.approx(0.5)
    assert events[1].deadline_ms == 25.0


# -- synthesis -------------------------------------------------------------


def test_poisson_mean_rate_and_monotone_ts():
    events = trace.synthesize(
        4000, arrivals="poisson", rate=200.0, seed=5
    )
    ts = np.asarray([e.ts for e in events])
    assert (np.diff(ts) >= 0).all()
    assert ts[0] == 0.0
    mean_gap = float(np.diff(ts).mean())
    assert mean_gap == pytest.approx(1 / 200.0, rel=0.1)


def test_heavy_tail_arrivals_are_heavier_than_poisson():
    n, rate = 4000, 100.0
    gaps = {}
    for arr in ("poisson", "lognormal", "pareto"):
        events = trace.synthesize(
            n, arrivals=arr, rate=rate, seed=6, sigma=1.5, alpha=1.2
        )
        g = np.diff([e.ts for e in events])
        # all processes are calibrated to the same mean rate...
        assert g.mean() == pytest.approx(1 / rate, rel=0.25), arr
        gaps[arr] = g
    # ...so the heavy tails must show in the extreme quantile
    p999 = {a: float(np.percentile(g, 99.9)) for a, g in gaps.items()}
    assert p999["lognormal"] > p999["poisson"]
    assert p999["pareto"] > p999["poisson"]


def test_uniform_arrivals_are_constant_gap():
    events = trace.synthesize(10, arrivals="uniform", rate=50.0)
    gaps = np.diff([e.ts for e in events])
    assert np.allclose(gaps, 0.02)


def test_pareto_requires_finite_mean():
    with pytest.raises(ValueError, match="alpha > 1"):
        trace.synthesize(10, arrivals="pareto", alpha=0.9)


def test_unknown_arrival_process_rejected():
    with pytest.raises(ValueError, match="unknown arrival"):
        trace.synthesize(10, arrivals="bursty")


def test_size_mix_proportions_and_shapes():
    events = trace.synthesize(
        2000, size_mix=((1, 0.75), (8, 0.25)), shape=(16,), seed=7
    )
    rows = np.asarray([e.n_rows for e in events])
    assert set(rows) == {1, 8}
    assert (rows == 8).mean() == pytest.approx(0.25, abs=0.05)
    assert all(e.shape == (16,) for e in events)


def test_deadline_distribution():
    fixed = trace.synthesize(50, deadline_ms=100.0)
    assert all(e.deadline_ms == 100.0 for e in fixed)
    jittered = trace.synthesize(
        500, deadline_ms=100.0, deadline_sigma=0.5, seed=8
    )
    ds = np.asarray([e.deadline_ms for e in jittered])
    assert (ds > 0).all()
    assert ds.std() > 0
    assert ds.mean() == pytest.approx(100.0, rel=0.2)


def test_synthesize_is_deterministic_per_seed():
    a = trace.synthesize(100, seed=9, size_mix=((1, 0.5), (4, 0.5)))
    b = trace.synthesize(100, seed=9, size_mix=((1, 0.5), (4, 0.5)))
    assert [(e.ts, e.n_rows) for e in a] == [(e.ts, e.n_rows) for e in b]


def test_parse_size_mix():
    assert trace.parse_size_mix("1:0.8,4:0.2") == [(1, 0.8), (4, 0.2)]
    with pytest.raises(ValueError):
        trace.parse_size_mix("1")


def test_summarize():
    events = trace.synthesize(
        100, rate=100.0, size_mix=((1, 0.5), (2, 0.5)),
        deadline_ms=10.0, seed=1,
    )
    doc = trace.summarize(events)
    assert doc["requests"] == 100
    assert doc["with_deadline"] == 100
    assert set(doc["size_counts"]) <= {"1", "2"}
    assert trace.summarize([]) == {"requests": 0}
