"""Loadgen's forensic handles: ``HttpTarget`` records the server's
``X-Keystone-Trace`` echo per request, the verdict surfaces exemplar
trace ids (worst-latency + every lost/untyped request), and the CLI
prints them as ready-to-curl ``/debugz?trace_id=`` URLs."""

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
import threading

import pytest

from keystone_tpu.loadgen.cli import _print_forensic_urls
from keystone_tpu.loadgen.invariants import InvariantChecker
from keystone_tpu.loadgen.runner import (
    HttpTarget,
    LoadReport,
    RequestRecord,
)
from keystone_tpu.loadgen.trace import (
    TraceEvent,
    parse_request_log_line,
)


class _StubGateway(BaseHTTPRequestHandler):
    """Answers /predict with a fixed X-Keystone-Trace header; /shed
    sheds typed WITH the header (the contract under test)."""

    trace_id = "fe" * 16

    def do_POST(self):  # noqa: N802 (stdlib handler API)
        length = int(self.headers.get("Content-Length", 0) or 0)
        self.rfile.read(length)
        if self.path == "/predict":
            body = json.dumps({"predictions": [[1.0]]}).encode()
            code = 200
        else:
            body = json.dumps(
                {"error": "overloaded", "reason": "queue_full"}
            ).encode()
            code = 429
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("X-Keystone-Trace", self.trace_id)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


@pytest.fixture
def stub_url():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StubGateway)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_http_target_records_trace_header_on_ok(stub_url):
    rec = HttpTarget(stub_url).send(TraceEvent(ts=0.0, shape=(2,)))
    assert rec.status == "ok"
    assert rec.trace_id == _StubGateway.trace_id


def test_http_target_records_trace_header_on_typed_shed(stub_url):
    target = HttpTarget(stub_url)
    target.base_url = stub_url + "/x"  # routes POSTs to the shed path
    rec = target.send(TraceEvent(ts=0.0, shape=(2,)))
    assert rec.status == "shed"
    assert rec.trace_id == _StubGateway.trace_id


# -- verdict exemplars -------------------------------------------------------


def _report(records):
    report = LoadReport()
    for r in records:
        report.add(r)
    report.issued = len(records)
    report.duration_s = 1.0
    return report


def test_verdict_lists_exemplars_for_worst_lost_and_untyped():
    report = _report([
        RequestRecord(0, 0.0, 0.0, "ok", latency_s=0.010,
                      trace_id="aa" * 16),
        RequestRecord(1, 0.1, 0.1, "ok", latency_s=0.500,
                      trace_id="bb" * 16),
        RequestRecord(2, 0.2, 0.2, "lost", reason="timeout"),
        RequestRecord(3, 0.3, 0.3, "error", code=500, untyped=True,
                      trace_id="cc" * 16, reason="internal"),
    ])
    verdict = InvariantChecker().check(report)
    assert not verdict.passed  # lost + untyped
    ex = verdict.stats["exemplars"]
    assert ex["worst_latency"]["trace_id"] == "bb" * 16
    assert ex["worst_latency"]["latency_ms"] == 500.0
    assert [e["index"] for e in ex["lost"]] == [2]
    assert ex["lost"][0]["trace_id"] is None  # lost = no response
    assert [e["trace_id"] for e in ex["untyped"]] == ["cc" * 16]
    # exemplars survive the JSON round trip the CLI/report emit
    assert json.loads(verdict.to_json())["stats"]["exemplars"] == ex


def test_green_verdict_still_carries_worst_latency_exemplar():
    report = _report([
        RequestRecord(0, 0.0, 0.0, "ok", latency_s=0.010,
                      trace_id="aa" * 16),
    ])
    verdict = InvariantChecker().check(report)
    assert verdict.passed
    ex = verdict.stats["exemplars"]
    assert ex["worst_latency"]["trace_id"] == "aa" * 16
    assert ex["lost"] == [] and ex["untyped"] == []


def test_cli_prints_ready_to_curl_debugz_urls(capsys):
    _print_forensic_urls("http://r:1/", {
        "worst_latency": {"index": 7, "trace_id": "aa" * 16},
        "lost": [{"index": 9, "trace_id": None}],
        "untyped": [{"index": 11, "trace_id": "bb" * 16}],
    })
    out = capsys.readouterr().out
    assert (
        "worst-latency (request #7): "
        f"curl 'http://r:1/debugz?trace_id={'aa' * 16}'" in out
    )
    assert "lost (request #9): no trace id" in out
    assert f"curl 'http://r:1/debugz?trace_id={'bb' * 16}'" in out


# -- fleet fields parse ------------------------------------------------------


def test_parser_tolerates_router_fields():
    line = json.dumps({
        "ts": 12.5, "path": "/predict", "status": 200,
        "latency_ms": 9.1, "lane": None, "trace_id": "ab" * 16,
        "n_rows": 2, "shape": [4], "deadline_ms": None,
        "post_seq": "deadbeef-1", "replica": "127.0.0.1:8000",
        "attempts": 2,
    })
    ev = parse_request_log_line(line)
    assert ev is not None
    assert ev.replica == "127.0.0.1:8000"
    assert ev.attempts == 2
    assert ev.n_rows == 2 and ev.shape == (4,)
