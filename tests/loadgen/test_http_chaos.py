"""HTTP-surface satellites: /chaosz arm/disarm round-trips over the
wire, the file-backed --request-log records replayable lines, and a
recorded log round-trips through the loadgen parser back into the
same requests."""

import itertools
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.loadgen import faults, trace

from gateway_fixtures import D, batch, make_fitted

_ids = itertools.count()


@pytest.fixture
def served(tmp_path):
    fitted = make_fitted()
    gw = Gateway(
        fitted,
        buckets=(4, 8),
        n_lanes=2,
        max_delay_ms=2.0,
        warmup_example=np.zeros(D, np.float32),
        name=f"chaos-http{next(_ids)}",
    )
    log_path = tmp_path / "requests.jsonl"
    srv = GatewayServer(gw, port=0, request_log=str(log_path)).start()
    yield gw, srv, log_path
    gw.close()
    srv.stop()


def _get(srv, path):
    with urllib.request.urlopen(srv.url(path), timeout=15) as resp:
        return resp.status, json.loads(resp.read())


def _post(srv, path, doc):
    req = urllib.request.Request(
        srv.url(path),
        data=json.dumps(doc).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


# -- /chaosz ---------------------------------------------------------------


def test_chaosz_get_lists_catalog(served):
    _, srv, _ = served
    status, doc = _get(srv, "/chaosz")
    assert status == 200
    assert "gateway.lane.kill" in doc["points"]
    assert doc["armed"] == {}


def test_chaosz_arm_disarm_round_trip(served):
    _, srv, _ = served
    fired_before = faults.get_injector().fired_count(
        "pipeline.host_prep.stall"
    )
    status, doc = _post(srv, "/chaosz", {
        "arm": {
            "point": "pipeline.host_prep.stall",
            "delay_ms": 5, "count": 3, "match": {"engine": "x"},
        },
    })
    assert status == 200
    armed = doc["armed"]["pipeline.host_prep.stall"]
    assert armed["count"] == 3
    assert armed["delay_ms"] == 5
    assert armed["match"] == {"engine": "x"}
    # the arm landed on the PROCESS-global injector (what the hot
    # paths consult), not some HTTP-local state
    assert (
        faults.get_injector().fire(
            "pipeline.host_prep.stall", {"engine": "x"}
        ) is not None
    )
    status, doc = _post(
        srv, "/chaosz", {"disarm": "pipeline.host_prep.stall"}
    )
    assert doc["armed"] == {}
    # fired_total is a lifetime audit (kept across disarms — and so
    # across tests in one process): assert the delta
    assert (
        doc["fired_total"]["pipeline.host_prep.stall"]
        == fired_before + 1
    )


def test_chaosz_disarm_star_clears_everything(served):
    _, srv, _ = served
    _post(srv, "/chaosz", {"arm": {"point": "gateway.lane.kill"}})
    _post(srv, "/chaosz", {"arm": {"point": "engine.dispatch.error"}})
    _, doc = _post(srv, "/chaosz", {"disarm": "*"})
    assert doc["armed"] == {}
    assert not faults.get_injector().armed


def test_chaosz_rejects_unknown_point_and_bad_body(served):
    _, srv, _ = served
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/chaosz", {"arm": {"point": "not.a.point"}})
    assert e.value.code == 400
    doc = json.loads(e.value.read())
    assert doc["error"] == "unknown_fault_point"
    assert "gateway.lane.kill" in doc["known"]
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/chaosz", {"neither": 1})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/chaosz", {"arm": {"point": "gateway.lane.kill",
                                       "count": 0}})
    assert e.value.code == 400


def test_chaosz_armed_kill_still_serves_typed(served):
    """Arm a lane kill over HTTP, then predict: the pool retries to
    the healthy lane and the client sees a clean 200."""
    _, srv, _ = served
    _post(srv, "/chaosz", {
        "arm": {"point": "gateway.lane.kill", "match": {"lane": 0},
                "for_s": 30.0},
    })
    xs = batch(4, seed=21)
    status, doc = _post(srv, "/predict", {"instances": xs.tolist()})
    assert status == 200
    assert len(doc["predictions"]) == 4
    _post(srv, "/chaosz", {"disarm": "*"})


def test_chaos_routes_can_be_disabled():
    """chaos_routes=False removes the sabotage surface: /chaosz 404s
    (both methods) while /predict keeps serving."""
    fitted = make_fitted()
    gw = Gateway(
        fitted, buckets=(4, 8), n_lanes=1, max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=f"chaos-http{next(_ids)}",
    )
    srv = GatewayServer(gw, port=0, chaos_routes=False).start()
    try:
        for do in (
            lambda: _get(srv, "/chaosz"),
            lambda: _post(srv, "/chaosz",
                          {"arm": {"point": "gateway.lane.kill"}}),
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                do()
            assert e.value.code == 404
            assert json.loads(e.value.read())["error"] == (
                "chaos_routes_disabled"
            )
        assert not faults.get_injector().armed
        status, doc = _post(
            srv, "/predict", {"instances": [batch(1, seed=30)[0].tolist()]}
        )
        assert status == 200 and len(doc["predictions"]) == 1
    finally:
        gw.close()
        srv.stop()


# -- file-backed request log + replay round trip ---------------------------


def test_request_log_file_records_replayable_lines(served):
    gw, srv, log_path = served
    xs = batch(3, seed=22)
    _post(srv, "/predict", {
        "instances": xs.tolist(), "deadline_ms": 5000,
    })
    _post(srv, "/predict", {"instances": [xs[0].tolist()]})
    lines = log_path.read_text().strip().splitlines()
    assert len(lines) == 4  # 3 instances + 1 instance
    recs = [json.loads(l) for l in lines]
    assert all(r["status"] == 200 for r in recs)
    assert [r["n_rows"] for r in recs] == [3, 3, 3, 1]
    assert all(r["shape"] == [D] for r in recs)
    assert [r["deadline_ms"] for r in recs] == [5000, 5000, 5000, None]
    assert all("latency_ms" in r and "ts" in r for r in recs)
    # one POST's lines share ONE post_seq and ONE (arrival) ts —
    # replay preserves the arrival pattern, not completion order
    assert recs[0]["post_seq"] == recs[1]["post_seq"] == recs[2]["post_seq"]
    assert recs[3]["post_seq"] != recs[0]["post_seq"]
    assert recs[0]["ts"] == recs[1]["ts"] == recs[2]["ts"]

    # the parser reconstructs the two POSTs, normalized to t=0
    events = trace.load_trace(str(log_path))
    assert [e.n_rows for e in events] == [3, 1]
    assert events[0].shape == (D,)
    assert events[0].deadline_ms == 5000
    assert events[0].ts == 0.0


def test_request_log_file_records_typed_sheds_with_meta(served):
    gw, srv, log_path = served
    gw.close()  # draining: /predict sheds typed 503/closed
    xs = batch(2, seed=23)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv, "/predict", {
            "instances": xs.tolist(), "deadline_ms": 100,
        })
    assert e.value.code == 503
    recs = [
        json.loads(l)
        for l in log_path.read_text().strip().splitlines()
    ]
    assert len(recs) == 1  # one line for the whole shed POST
    assert recs[0]["status"] == 503
    assert recs[0]["error"] == "closed"
    # the replay fields survived the error path
    assert recs[0]["n_rows"] == 2
    assert recs[0]["shape"] == [D]
    assert recs[0]["deadline_ms"] == 100
    # and the parser replays it as one full-size event
    events = trace.load_trace(str(log_path))
    assert [e.n_rows for e in events] == [2]


def test_request_log_stdout_mode_still_works(capsys):
    """Bare request_log=True keeps the original stdout behavior."""
    fitted = make_fitted()
    gw = Gateway(
        fitted, buckets=(4, 8), n_lanes=1, max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=f"chaos-http{next(_ids)}",
    )
    srv = GatewayServer(gw, port=0, request_log=True).start()
    try:
        xs = batch(1, seed=24)
        _post(srv, "/predict", {"instances": xs.tolist()})
    finally:
        gw.close()
        srv.stop()
    out = capsys.readouterr().out
    recs = [
        json.loads(l) for l in out.strip().splitlines()
        if l.startswith("{")
    ]
    assert len(recs) == 1
    assert recs[0]["n_rows"] == 1
    assert recs[0]["shape"] == [D]
