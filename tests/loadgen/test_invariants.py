"""The invariant checker must be able to FAIL: a stub gateway that
loses a future, returns an untyped 500, or never recovers readiness
must each produce a red verdict — otherwise the green verdicts the
bench rows assert are worthless."""

import pytest

from keystone_tpu.loadgen.invariants import InvariantChecker, Verdict
from keystone_tpu.loadgen.runner import (
    FaultWindow,
    LoadGenerator,
    LoadReport,
    RequestRecord,
)
from keystone_tpu.loadgen.trace import TraceEvent


def _report(
    records,
    fault=None,
    issued=None,
    ready_recovery_s="unset",
    probed=True,
):
    rep = LoadReport()
    for r in records:
        rep.add(r)
    rep.issued = issued if issued is not None else len(records)
    rep.duration_s = max((r.t_send for r in records), default=0.0) + 1.0
    if fault is not None:
        rep.fault_windows.append(fault)
        rep.ready_probed = probed
        rep.ready_recovery_s = (
            1.0 if ready_recovery_s == "unset" else ready_recovery_s
        )
    return rep


def _ok(t, lat=0.01):
    return RequestRecord(0, t, t, "ok", latency_s=lat)


def _steady(n=60, lat=0.01, t0=0.0, dt=0.1):
    return [_ok(t0 + i * dt, lat) for i in range(n)]


def _fault(t_arm=2.0, t_clear=3.0):
    return FaultWindow(point="gateway.lane.kill", t_arm=t_arm,
                       t_clear=t_clear)


def _failed_names(verdict):
    return {r.name for r in verdict.failures()}


def test_clean_report_is_green():
    v = InvariantChecker().check(_report(_steady(), fault=_fault()))
    assert v.passed, v.to_json()
    assert isinstance(v, Verdict)
    assert v.stats["pre_fault_p99_ms"] is not None


def test_lost_future_fails_resolution_invariant():
    records = _steady() + [
        RequestRecord(0, 1.0, 1.0, "lost", reason="hung 30s")
    ]
    v = InvariantChecker().check(_report(records, fault=_fault()))
    assert not v.passed
    assert "every_admitted_request_resolves" in _failed_names(v)


def test_vanished_request_fails_resolution_invariant():
    # issued 61, only 60 records came back: a request with NO record
    # (the stub gateway swallowed the future entirely)
    v = InvariantChecker().check(
        _report(_steady(), fault=_fault(), issued=61)
    )
    assert not v.passed
    assert "every_admitted_request_resolves" in _failed_names(v)
    detail = [
        r for r in v.invariants
        if r.name == "every_admitted_request_resolves"
    ][0].detail
    assert "vanished" in detail


def test_untyped_500_fails_typed_only_invariant():
    records = _steady() + [
        RequestRecord(
            0, 1.0, 1.0, "error", code=500,
            reason="internal", untyped=True,
        )
    ]
    v = InvariantChecker().check(_report(records, fault=_fault()))
    assert not v.passed
    assert "failures_are_typed_sheds_only" in _failed_names(v)


def test_typed_sheds_do_not_fail_typed_only():
    records = _steady() + [
        RequestRecord(
            0, 1.0, 1.0, "shed", code=429, reason="queue_full",
        )
    ]
    v = InvariantChecker().check(_report(records, fault=_fault()))
    assert "failures_are_typed_sheds_only" not in _failed_names(v)


def test_never_recovered_readiness_fails():
    v = InvariantChecker().check(
        _report(_steady(), fault=_fault(), ready_recovery_s=None)
    )
    assert not v.passed
    assert "readiness_recovers_after_fault" in _failed_names(v)


def test_unprobed_readiness_with_faults_fails():
    # fault windows ran but nobody probed /readyz: the invariant must
    # refuse to pass on missing evidence
    v = InvariantChecker().check(
        _report(_steady(), fault=_fault(), probed=False,
                ready_recovery_s=None)
    )
    assert "readiness_recovers_after_fault" in _failed_names(v)


def test_p99_that_never_recovers_fails():
    # pre-fault 10ms; everything after the fault is 200ms forever
    records = _steady(n=30, lat=0.01)  # t in [0, 3)
    records += [_ok(3.0 + i * 0.1, 0.2) for i in range(150)]
    v = InvariantChecker(recovery_within_s=5.0).check(
        _report(records, fault=_fault(t_arm=2.5, t_clear=3.0))
    )
    assert not v.passed
    assert "p99_recovers_after_fault" in _failed_names(v)


def test_p99_recovery_slides_past_the_drain_transient():
    # 2s of 300ms drain right after the fault clears, then healthy:
    # the sliding window finds the recovery; whole-post-window p99
    # alone would have failed it
    records = _steady(n=30, lat=0.01)
    records += [_ok(3.0 + i * 0.1, 0.3) for i in range(20)]   # drain
    records += [_ok(5.0 + i * 0.1, 0.01) for i in range(100)]  # healthy
    v = InvariantChecker(recovery_within_s=10.0).check(
        _report(records, fault=_fault(t_arm=2.5, t_clear=3.0))
    )
    assert "p99_recovers_after_fault" not in _failed_names(v)
    assert v.stats["p99_recovery_s"] is not None
    assert v.stats["recovered_p99_ms"] < 50


def test_no_pre_fault_traffic_fails_rather_than_guesses():
    records = [_ok(3.0 + i * 0.1) for i in range(50)]
    v = InvariantChecker().check(
        _report(records, fault=_fault(t_arm=0.0, t_clear=1.0))
    )
    assert "p99_recovers_after_fault" in _failed_names(v)


def test_no_faults_skips_chaos_invariants():
    v = InvariantChecker().check(_report(_steady()))
    names = {r.name for r in v.invariants}
    assert "p99_recovers_after_fault" not in names
    assert "readiness_recovers_after_fault" not in names
    assert v.passed


def test_shed_rate_bound():
    records = _steady(n=50) + [
        RequestRecord(0, 1.0, 1.0, "shed", reason="queue_full")
        for _ in range(50)
    ]
    red = InvariantChecker(max_shed_rate=0.25).check(_report(records))
    assert "shed_rate_bounded" in _failed_names(red)
    green = InvariantChecker(max_shed_rate=0.6).check(_report(records))
    assert green.passed


def test_absolute_p99_bound():
    v = InvariantChecker(max_p99_s=0.005).check(
        _report(_steady(lat=0.02))
    )
    assert "p99_bounded" in _failed_names(v)


def test_verdict_json_round_trip():
    import json

    v = InvariantChecker().check(_report(_steady()))
    doc = json.loads(v.to_json())
    assert doc["passed"] is True
    assert {r["name"] for r in doc["invariants"]} == {
        "every_admitted_request_resolves",
        "failures_are_typed_sheds_only",
    }


# -- end to end: a stub gateway whose bugs the checker must catch ----------


class _LosingTarget:
    """A 'gateway' that silently never answers one request in ten and
    500s another — the checker is the only line of defense."""

    def __init__(self):
        self.n = 0

    def send(self, event):
        self.n += 1
        if self.n % 10 == 0:
            return RequestRecord(
                0, 0.0, 0.0, "lost", reason="future never resolved"
            )
        if self.n % 10 == 5:
            return RequestRecord(
                0, 0.0, 0.0, "error", code=500,
                reason="internal", untyped=True,
            )
        return RequestRecord(0, 0.0, 0.0, "ok", latency_s=0.001)

    def ready(self):
        return False  # and it never comes back

    def arm_fault(self, spec):
        pass

    def disarm_fault(self, point):
        pass


def test_checker_catches_a_lying_stub_gateway_end_to_end():
    from keystone_tpu.loadgen.runner import FaultPlan

    events = [TraceEvent(ts=i * 0.005) for i in range(30)]
    gen = LoadGenerator(_LosingTarget())
    report = gen.run(
        events,
        faults=[FaultPlan(
            spec={"point": "gateway.lane.kill"}, at_s=0.05, for_s=0.05,
        )],
        recovery_probe_s=0.3,
    )
    v = InvariantChecker().check(report)
    assert not v.passed
    failed = _failed_names(v)
    assert "every_admitted_request_resolves" in failed
    assert "failures_are_typed_sheds_only" in failed
    assert "readiness_recovers_after_fault" in failed
