"""The step/ramp offered-load shape (``synthesize_steps``) and its
CLI spec — the deterministic load staircase the autoscale drills and
the capacity planner script."""

import numpy as np
import pytest

from keystone_tpu.loadgen.trace import (
    parse_steps,
    synthesize,
    synthesize_steps,
)


def test_parse_steps_grammar():
    assert parse_steps("5:4,40:8,5:6") == [
        (5.0, 4.0), (40.0, 8.0), (5.0, 6.0),
    ]
    assert parse_steps("2.5:0.5") == [(2.5, 0.5)]
    with pytest.raises(ValueError, match="rate:duration"):
        parse_steps("5,40")
    with pytest.raises(ValueError):
        parse_steps("x:1")


def test_steps_produce_per_step_rates():
    events = synthesize_steps(
        [(10.0, 10.0), (100.0, 10.0), (10.0, 10.0)],
        arrivals="uniform",
        shape=(4,),
        seed=3,
    )
    ts = np.asarray([e.ts for e in events])
    low1 = ((ts >= 0) & (ts < 10)).sum()
    high = ((ts >= 10) & (ts < 20)).sum()
    low2 = ((ts >= 20) & (ts < 30)).sum()
    # uniform arrivals: counts are exact up to edge effects
    assert low1 == pytest.approx(100, abs=2)
    assert high == pytest.approx(1000, abs=2)
    assert low2 == pytest.approx(100, abs=2)
    # arrivals stay inside the schedule and ascend
    assert ts.max() < 30.0
    assert (np.diff(ts) > 0).all()


def test_steps_poisson_rates_are_approximate():
    events = synthesize_steps(
        [(20.0, 20.0), (200.0, 5.0)], shape=(4,), seed=11
    )
    ts = np.asarray([e.ts for e in events])
    low = ((ts >= 0) & (ts < 20)).sum()
    high = ((ts >= 20) & (ts < 25)).sum()
    assert 250 <= low + high <= 2000
    # the surge is an order of magnitude denser than the baseline
    assert (high / 5.0) > 4 * (low / 20.0)


def test_zero_rate_step_is_a_silence():
    events = synthesize_steps(
        [(50.0, 2.0), (0.0, 3.0), (50.0, 2.0)],
        arrivals="uniform",
        shape=(4,),
        seed=0,
    )
    ts = np.asarray([e.ts for e in events])
    assert ((ts >= 2.0) & (ts < 5.0)).sum() == 0
    assert ((ts >= 5.0) & (ts < 7.0)).sum() > 0


def test_steps_deterministic_per_seed():
    kw = dict(shape=(4,), size_mix=((1, 0.5), (4, 0.5)))
    a = synthesize_steps([(30.0, 3.0)], seed=7, **kw)
    b = synthesize_steps([(30.0, 3.0)], seed=7, **kw)
    c = synthesize_steps([(30.0, 3.0)], seed=8, **kw)
    assert [(e.ts, e.n_rows) for e in a] == [(e.ts, e.n_rows) for e in b]
    assert [(e.ts, e.n_rows) for e in a] != [(e.ts, e.n_rows) for e in c]


def test_steps_carry_sizes_shapes_deadlines():
    events = synthesize_steps(
        [(40.0, 2.0)],
        shape=(16,),
        size_mix=((2, 1.0),),
        deadline_ms=50.0,
        seed=1,
    )
    assert all(e.shape == (16,) for e in events)
    assert all(e.n_rows == 2 for e in events)
    assert all(e.deadline_ms == 50.0 for e in events)


def test_steps_validation():
    with pytest.raises(ValueError, match="at least one step"):
        synthesize_steps([])
    with pytest.raises(ValueError, match="durations"):
        synthesize_steps([(10.0, 0.0)])
    with pytest.raises(ValueError, match="rates"):
        synthesize_steps([(-1.0, 5.0)])
    with pytest.raises(ValueError, match="no arrivals"):
        synthesize_steps([(0.001, 0.5)], seed=0)
    # a typo'd rate must fail loud, never loop/allocate forever
    with pytest.raises(ValueError, match="rates must be finite"):
        synthesize_steps([(float("inf"), 5.0)])
    with pytest.raises(ValueError, match="durations must be finite"):
        synthesize_steps([(10.0, float("inf"))])
    with pytest.raises(ValueError, match="2e6"):
        synthesize_steps([(1e7, 60.0)])


def test_single_step_matches_synthesize_statistics():
    """One step at rate r for T seconds is the same workload family
    as synthesize(n~rT) — the staircase generalizes, not replaces."""
    steps = synthesize_steps([(100.0, 5.0)], shape=(4,), seed=5)
    flat = synthesize(500, rate=100.0, shape=(4,), seed=5)
    assert len(steps) == pytest.approx(len(flat), rel=0.25)


def test_cli_ramp_builds_step_events():
    from keystone_tpu.loadgen.cli import _build_events, build_parser

    args = build_parser().parse_args(
        ["--ramp", "10:1,50:1", "--arrivals", "uniform", "--d", "8"]
    )
    events = _build_events(args)
    assert len(events) == pytest.approx(60, abs=3)
    assert all(e.shape == (8,) for e in events)


def test_cli_ramp_is_exclusive_with_other_workloads():
    from keystone_tpu.loadgen.cli import _build_events, build_parser

    args = build_parser().parse_args(
        ["--ramp", "10:1", "--synthetic", "5"]
    )
    with pytest.raises(SystemExit, match="exactly one"):
        _build_events(args)
