"""Lazy PEP 562 package exports (keystone_tpu/_lazy.py): re-exported
names, on-demand submodule access, and the error-discrimination contract
(missing submodule -> AttributeError; missing DEPENDENCY inside a real
submodule -> the original ModuleNotFoundError, not a masked
AttributeError). The laziness exists so the streaming loader's spawn
decode workers never import jax."""

import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_reexports_and_submodule_access():
    import keystone_tpu

    assert keystone_tpu.Pipeline.__name__ == "Pipeline"
    assert keystone_tpu.Dataset.__name__ == "Dataset"
    # eager imports used to bind subpackages as side effects; the lazy
    # fallback must keep attribute-style submodule access working
    assert keystone_tpu.workflow.__name__ == "keystone_tpu.workflow"
    assert keystone_tpu.loaders.CsvDataLoader.__name__ == "CsvDataLoader"


def test_missing_attribute_is_attribute_error():
    import keystone_tpu

    with pytest.raises(AttributeError, match="no attribute"):
        keystone_tpu.definitely_not_a_thing


def test_streaming_import_stays_light():
    """Importing the streaming loader must not pull the heavy compute
    modules through the package __init__ (spawn decode workers pay this
    import)."""
    import subprocess

    code = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        import keystone_tpu.loaders.streaming
        heavy = [m for m in sys.modules
                 if m.startswith('keystone_tpu.')
                 and ('workflow' in m or 'dataset' in m or '.ops' in m)]
        assert not heavy, heavy
        # the point of the laziness: no jax either (unless a site hook
        # preloads it before ANY import — measure against a no-op
        # baseline so this CI's axon site preload doesn't false-fail)
        print('JAXFREE' if 'jax' not in sys.modules else 'JAXLOADED')
        print('LIGHT')
    """ % (REPO,))
    env = {k: v for k, v in os.environ.items()}
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "LIGHT" in out.stdout
    baseline = subprocess.run(
        [sys.executable, "-c",
         "import sys; print('JAXFREE' if 'jax' not in sys.modules else "
         "'JAXLOADED')"],
        capture_output=True, text=True, env=env,
    )
    if "JAXFREE" in baseline.stdout:
        # in a clean interpreter (no site preload), importing the
        # streaming loader must not pull jax in
        assert "JAXFREE" in out.stdout, out.stdout


def test_missing_dependency_stays_loud(tmp_path, monkeypatch):
    """A submodule that exists but fails on a missing dependency must
    surface the REAL ModuleNotFoundError, not an AttributeError claiming
    the submodule doesn't exist."""
    pkg = tmp_path / "fakepkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(textwrap.dedent("""
        from keystone_tpu._lazy import make_getattr
        _EXPORTS = {}
        __getattr__ = make_getattr(__name__, _EXPORTS)
    """))
    (pkg / "needs_dep.py").write_text("import not_a_real_dependency\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    import fakepkg

    with pytest.raises(ModuleNotFoundError, match="not_a_real_dependency"):
        fakepkg.needs_dep
    with pytest.raises(AttributeError, match="no attribute"):
        fakepkg.not_a_submodule
