"""Graceful retirement + the request-path partition: registry
``remove``, the router's ``POST /deregisterz``, and the
``router.replica.partition`` chaos point failing over exactly like a
connection refusal."""

import itertools
import json
import urllib.request

import numpy as np
import pytest

from keystone_tpu.fleet import ReplicaRegistry, RouterServer
from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.loadgen import faults
from keystone_tpu.observability.registry import MetricsRegistry

from gateway_fixtures import D, batch, make_fitted

_ids = itertools.count()


# -- registry.remove --------------------------------------------------------


def test_registry_remove_is_idempotent_roster_removal():
    reg = ReplicaRegistry(["http://127.0.0.1:9001"])
    assert len(reg) == 1
    assert reg.remove("http://127.0.0.1:9001") is True
    assert len(reg) == 0
    assert reg.remove("http://127.0.0.1:9001") is False
    with pytest.raises(ValueError, match="http"):
        reg.remove("not-a-url")


def test_removed_replica_is_never_picked():
    reg = ReplicaRegistry(
        ["http://127.0.0.1:9001", "http://127.0.0.1:9002"]
    )
    reg.remove("http://127.0.0.1:9001")
    for _ in range(5):
        assert reg.pick().url == "http://127.0.0.1:9002"


# -- router /deregisterz + partition, end to end ----------------------------


def _make_replica(name):
    reg = MetricsRegistry()
    gw = Gateway(
        make_fitted(),
        buckets=(4, 8),
        n_lanes=1,
        max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=name,
        registry=reg,
    )
    srv = GatewayServer(gw, port=0, registry=reg).start()
    return gw, srv


@pytest.fixture
def fleet():
    replicas = [
        _make_replica(f"dereg-r{next(_ids)}") for _ in range(2)
    ]
    router = RouterServer(
        [srv.url() for _, srv in replicas],
        port=0,
        name=f"dereg-router{next(_ids)}",
        registry=MetricsRegistry(),
        probe_interval_s=0.1,
        recovery_after_s=0.3,
    ).start()
    router.fleet.probe_once()
    yield router, replicas
    router.stop()
    for gw, srv in replicas:
        gw.close()
        srv.stop()


def _post(url, doc, timeout=30):
    req = urllib.request.Request(
        url,
        data=json.dumps(doc).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _predict(router, n=2, seed=0):
    return _post(
        router.url("/predict"),
        {"instances": batch(n, seed=seed).tolist()},
    )


def test_deregisterz_removes_and_routes_around(fleet):
    router, replicas = fleet
    (gw0, srv0), (gw1, srv1) = replicas
    status, doc = _post(
        router.url("/deregisterz"), {"url": srv0.url()}
    )
    assert status == 200
    assert doc == {"deregistered": True, "replicas": 1}
    # idempotent: a second deregister of the same URL is a no-op
    status, doc = _post(
        router.url("/deregisterz"), {"url": srv0.url()}
    )
    assert doc == {"deregistered": False, "replicas": 1}
    # every forward now lands on the survivor
    for seed in range(4):
        status, _ = _predict(router, seed=seed)
        assert status == 200
    assert gw0.metrics.outcome_count("ok") == 0.0
    assert gw1.metrics.outcome_count("ok") == 8.0


def test_deregisterz_rejects_garbage(fleet):
    router, _ = fleet
    import urllib.error

    for body in ({}, {"url": 7}, {"url": "nope"}):
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(router.url("/deregisterz"), body)
        assert err.value.code == 400


def test_reregister_after_deregister_rejoins(fleet):
    router, replicas = fleet
    _, srv0 = replicas[0]
    _post(router.url("/deregisterz"), {"url": srv0.url()})
    assert len(router.fleet) == 1
    status, doc = _post(
        router.url("/registerz"), {"url": srv0.url()}
    )
    assert doc["registered"] is True and doc["created"] is True
    assert len(router.fleet) == 2


def test_partition_fails_over_and_charges_health(fleet):
    """``router.replica.partition`` severs the forward BEFORE it
    dials: the matched replica never sees the request, traffic fails
    over to the sibling, and the replica is benched on request
    evidence — exactly the connection-refusal contract."""
    router, replicas = fleet
    (gw0, srv0), (gw1, srv1) = replicas
    fired_before = faults.get_injector().fired_count(
        "router.replica.partition"
    )
    faults.arm("router.replica.partition", match={"index": 0})
    try:
        for seed in range(6):
            status, doc = _predict(router, seed=seed)
            assert status == 200
            assert len(doc["predictions"]) == 2
    finally:
        faults.disarm("router.replica.partition")
    # the partitioned replica served NOTHING (request-path severed,
    # unlike blackhole where the work happens and the response drops)
    assert gw0.metrics.outcome_count("ok") == 0.0
    assert gw1.metrics.outcome_count("ok") == 12.0
    fired = (
        faults.get_injector().fired_count("router.replica.partition")
        - fired_before
    )
    assert fired >= 3
    # request evidence benched it
    r0 = router.fleet.find_by_name(
        srv0.url().replace("http://", "").rstrip("/")
    )
    assert r0 is not None
    assert r0.state in ("unhealthy", "half-open")


def test_partition_of_whole_fleet_sheds_typed(fleet):
    """With every replica partitioned, the router must shed a TYPED
    503 (closed) — never a naked 500 — the invariant the autoscale
    drill holds while a partition races a scale-up."""
    router, _ = fleet
    import urllib.error

    faults.arm("router.replica.partition")  # no match: everyone
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _predict(router)
        assert err.value.code == 503
        doc = json.loads(err.value.read())
        assert doc["error"] == "overloaded"
        assert doc["reason"] == "closed"
    finally:
        faults.disarm("router.replica.partition")
