import os
import sys

import pytest

from keystone_tpu.loadgen import faults

# the shared tiny-pipeline helpers live next to the gateway suite;
# rootdir conftest only puts tests/ itself on the path
sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "gateway",
    ),
)

from gateway_fixtures import make_fitted  # noqa: E402


@pytest.fixture(scope="session")
def fitted():
    return make_fitted()


@pytest.fixture(autouse=True)
def clean_injector():
    """The injector is process-global state: every test starts and
    ends with nothing armed, so a failing chaos test can't leak its
    faults into the rest of the suite."""
    faults.disarm_all()
    yield
    faults.disarm_all()
