"""Distributed tracing across the fleet hop, over real sockets: W3C
``traceparent`` round-trip (router → replica → stitched ``/debugz``),
retry attempts as sibling spans, the ``X-Keystone-Trace`` echo on
success AND typed shed, phase decomposition summing to the measured
latency, the ``router.trace.drop`` graceful-degradation drill, and
``serve-router --request-log`` parity with the gateway's replayable
schema."""

import itertools
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.fleet import RouterServer
from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.loadgen import faults
from keystone_tpu.loadgen import trace as trace_mod
from keystone_tpu.observability import tracing
from keystone_tpu.observability.prometheus import parse_samples
from keystone_tpu.observability.registry import MetricsRegistry

from gateway_fixtures import D, make_fitted

_ids = itertools.count()


@pytest.fixture(autouse=True)
def traced():
    """Every test here runs with the process-global tracer ON (the
    serve-router default) and restores the disabled default after."""
    tracing.enable_tracing()
    yield tracing.get_tracer()
    tracing.disable_tracing()


def _make_replica(name):
    reg = MetricsRegistry()
    gw = Gateway(
        make_fitted(),
        buckets=(4, 8),
        n_lanes=1,
        max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=name,
        registry=reg,
    )
    srv = GatewayServer(gw, port=0, registry=reg).start()
    return gw, srv


@pytest.fixture
def fleet(tmp_path):
    replicas = [
        _make_replica(f"trace-r{next(_ids)}") for _ in range(2)
    ]
    router = RouterServer(
        [srv.url() for _, srv in replicas],
        port=0,
        name=f"trace-router{next(_ids)}",
        registry=MetricsRegistry(),
        probe_interval_s=0.1,
        recovery_after_s=0.3,
        request_log=str(tmp_path / "router-requests.jsonl"),
    ).start()
    router.fleet.probe_once()
    yield router, replicas, tmp_path / "router-requests.jsonl"
    router.stop()
    for gw, srv in replicas:
        gw.close()
        srv.stop()


def _predict(url, headers=None, timeout=30):
    body = json.dumps({"instances": [[0.5] * D]}).encode()
    req = urllib.request.Request(
        url + "/predict",
        data=body,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()
        return (
            resp.status,
            resp.headers.get("X-Keystone-Trace"),
            time.perf_counter() - t0,
        )


def _get_json(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _metric_value(text, family, want_labels):
    total = 0.0
    found = False
    for name, labels, value in parse_samples(text):
        if name == family and all(
            labels.get(k) == v for k, v in want_labels.items()
        ):
            total += value
            found = True
    return total if found else None


# -- one trace id across processes ------------------------------------------


def test_inbound_traceparent_is_adopted_fleet_wide(fleet, traced):
    """A client-minted traceparent survives client → router → replica:
    the echoed header, the router's forward span, and the replica's
    admit/coalesce chain all carry the CLIENT's trace id."""
    router, replicas, _ = fleet
    tid = tracing.new_trace_id()
    header = tracing.format_traceparent(tid, 7)
    status, echoed, _ = _predict(
        router.url(), headers={"traceparent": header}
    )
    assert status == 200
    assert echoed == tid
    time.sleep(0.3)
    spans = traced.spans_for_trace(tid)
    names = {s.name for s in spans}
    assert "router.forward" in names
    assert "gateway.admit" in names, names
    assert "microbatch.coalesce" in names, names


def test_cross_process_round_trip_stitches_at_the_router(fleet, traced):
    """The acceptance path: one /predict with no client context yields
    ONE minted trace id visible in the response header, both
    processes' span rings, and the router's stitched /debugz — with
    router-hop and replica spans in one tree and phases summing to
    within 10% of the stitched total."""
    router, replicas, _ = fleet
    status, tid, measured_s = _predict(router.url())
    assert status == 200 and tid
    time.sleep(0.4)  # replica stage spans end just after the response

    doc = _get_json(router.url(f"/debugz?trace_id={tid}"))
    assert not doc["partial"], doc["partial_detail"]
    assert len(doc["processes"]) == 2, doc["processes"]
    names = {s["name"] for s in doc["spans"]}
    assert {"router.forward", "gateway.admit"} <= names, names
    # replica roots grafted under the router hop
    grafted = [s for s in doc["spans"] if s.get("grafted")]
    assert grafted
    forward_ids = {
        s["span_id"] for s in doc["spans"]
        if s["name"] == "router.forward"
    }
    assert {s["parent_id"] for s in grafted} <= forward_ids

    phases = doc["phases_ms"]
    assert set(phases) == {
        "router_hop", "queue_wait", "coalesce", "device", "deliver",
    }
    total = doc["total_ms"]
    assert abs(sum(phases.values()) - total) <= 0.1 * total
    # the stitched total is the router-measured forward; client adds
    # only its own hop on localhost
    assert total <= measured_s * 1e3 + 1.0

    chrome = _get_json(
        router.url(f"/debugz?trace_id={tid}&format=chrome")
    )
    pids = {
        e["pid"] for e in chrome["traceEvents"] if e.get("ph") == "X"
    }
    assert len(pids) == 2, pids
    # the phase family landed on the router registry -> federation
    fed = urllib.request.urlopen(
        router.url("/metrics"), timeout=15
    ).read().decode()
    assert "keystone_request_phase_seconds_bucket" in fed


def test_unknown_trace_404s_and_missing_id_400s(fleet):
    router, _, _ = fleet
    with pytest.raises(urllib.error.HTTPError) as err:
        _get_json(router.url("/debugz?trace_id=" + "ab" * 16))
    assert err.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as err:
        _get_json(router.url("/debugz"))
    assert err.value.code == 400


# -- retries are sibling spans ----------------------------------------------


def test_retry_produces_sibling_spans_with_retry_reason(fleet, traced):
    """A black-holed first attempt must show up in the trace as TWO
    root-level router.forward siblings — the failed hop (error attr)
    and the winning retry (retry_reason attr naming why)."""
    router, replicas, _ = fleet
    faults.arm("router.replica.blackhole", count=1)
    status, tid, _ = _predict(router.url())
    assert status == 200 and tid
    forwards = [
        s for s in traced.spans_for_trace(tid)
        if s.name == "router.forward"
    ]
    assert len(forwards) == 2
    assert all(s.parent_id is None for s in forwards), (
        "attempts must be SIBLINGS (roots), not nested"
    )
    first, second = sorted(forwards, key=lambda s: s.attrs["attempt"])
    assert "error" in first.attrs
    assert "blackhole" in second.attrs["retry_reason"]
    assert second.attrs["status"] == 200
    assert first.attrs["replica"] != second.attrs["replica"]


# -- the echo survives typed sheds ------------------------------------------


def test_typed_shed_response_carries_trace_header(fleet):
    """A fleet-wide drain propagates the replicas' typed 503-closed —
    and that shed response must STILL carry X-Keystone-Trace: the
    shed client needs the forensic handle most."""
    router, replicas, _ = fleet
    for gw, _srv in replicas:
        gw.close()
    with pytest.raises(urllib.error.HTTPError) as err:
        _predict(router.url())
    assert err.value.code == 503
    doc = json.loads(err.value.read())
    assert doc["error"] == "overloaded"
    assert err.value.headers.get("X-Keystone-Trace"), (
        "typed shed lost the trace id"
    )


def test_gateway_typed_shed_carries_trace_header(fleet):
    """Same contract one tier down: the REPLICA's own typed shed
    (direct hit, closed gateway) echoes the inbound trace id."""
    _router, replicas, _ = fleet
    gw, srv = replicas[0]
    gw.close()
    tid = tracing.new_trace_id()
    with pytest.raises(urllib.error.HTTPError) as err:
        _predict(
            srv.url(),
            headers={"traceparent": tracing.format_traceparent(tid, 3)},
        )
    assert err.value.code == 503
    assert err.value.headers.get("X-Keystone-Trace") == tid


# -- router.trace.drop: graceful degradation --------------------------------


def test_trace_drop_degrades_to_counted_partial_stitch(fleet, traced):
    """With ``router.trace.drop`` armed the forward loses its
    traceparent: serving is unaffected, the replica self-mints a
    DIFFERENT id, and the router's stitch returns its partial
    router-side tree with keystone_trace_stitch_partial_total
    counted."""
    router, replicas, _ = fleet
    faults.arm("router.trace.drop")
    try:
        status, tid, _ = _predict(router.url())
    finally:
        faults.disarm("router.trace.drop")
    assert status == 200 and tid, "serving must be unaffected"
    time.sleep(0.3)
    # the replica minted its own id: the router's id has no replica
    # spans anywhere
    replica_span_names = {
        s.name
        for s in traced.spans_for_trace(tid)
    }
    assert "gateway.admit" not in replica_span_names
    doc = _get_json(router.url(f"/debugz?trace_id={tid}"))
    assert doc["partial"] is True
    assert doc["processes"] == [router.name]
    assert any("no spans" in d for d in doc["partial_detail"])
    # phases degrade to router_hop-only, never crash
    assert doc["phases_ms"]["router_hop"] == doc["total_ms"]
    fed = urllib.request.urlopen(
        router.url("/metrics"), timeout=15
    ).read().decode()
    partials = _metric_value(
        fed, "keystone_trace_stitch_partial_total",
        {"reason": "no_spans"},
    )
    assert partials is not None and partials >= 1


# -- --request-log parity ----------------------------------------------------


def test_router_request_log_is_replayable_with_fleet_fields(fleet):
    """The router's --request-log lines parse with the SAME loadgen
    trace parser as the gateway's, replay with real n_rows/shape, and
    carry the fleet fields (replica, attempts, trace_id)."""
    router, replicas, log_path = fleet
    for _ in range(3):
        status, tid, _ = _predict(router.url())
        assert status == 200
    router.stop()  # flush/close the log file
    lines = log_path.read_text().splitlines()
    assert len(lines) == 3
    events = trace_mod.parse_request_log(lines)
    assert len(events) == 3
    for ev in events:
        assert ev.status == 200
        assert ev.n_rows == 1
        assert ev.shape == (D,)
        assert ev.trace_id
        assert ev.attempts == 1
        assert ev.replica in {
            r.name for r in router.fleet.replicas()
        }
        assert ev.post_seq is not None
    # collapse_posts dedupes by post_seq — one event per POST
    assert len(trace_mod.collapse_posts(events)) == 3
    # and the whole file round-trips through load_trace (normalize)
    loaded = trace_mod.load_trace(str(log_path))
    assert len(loaded) == 3
    assert loaded[0].ts == 0.0
