"""Model-id propagation through the fleet tier: replicas advertise
their zoo roster at ``/registerz``, the router forwards
``/predict/<model>`` path-preserved to ADVERTISING replicas only, and
a model nobody advertises is a typed 503 ``no_replica_for_model`` —
never a blind forward into a replica's 404. Plus the
``ReplicaRegistry`` model-filter unit behavior underneath."""

import itertools
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.fleet import RouterServer
from keystone_tpu.fleet.client import post_roster
from keystone_tpu.fleet.registry import ReplicaRegistry
from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.observability.registry import MetricsRegistry
from keystone_tpu.serving.bench import build_pipeline
from keystone_tpu.zoo import (
    BuiltModel,
    ModelRegistry,
    ModelSpec,
    ModelZoo,
)

from gateway_fixtures import D, make_fitted

_ids = itertools.count()
ZD = 6  # the zoo replica's feature dim (matches gateway_fixtures.D)


def _post(url, doc, timeout=60):
    req = urllib.request.Request(
        url,
        data=json.dumps(doc).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _make_plain_replica(name):
    reg = MetricsRegistry()
    gw = Gateway(
        make_fitted(),
        buckets=(4, 8),
        n_lanes=1,
        max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=name,
        registry=reg,
    )
    srv = GatewayServer(gw, port=0, registry=reg).start()
    return gw, srv


def _make_zoo_replica(name, model_ids):
    reg = MetricsRegistry()
    registry = ModelRegistry()
    for i, mid in enumerate(model_ids):
        head = build_pipeline(d=ZD, hidden=8, depth=2, seed=i + 1)
        registry.register(ModelSpec(
            model_id=mid,
            build=lambda h=head: BuiltModel(fitted=h),
            buckets=(2, 4),
            lanes=1,
            max_delay_ms=1.0,
            warmup_example=np.zeros(ZD, np.float32),
            default=(i == 0),
        ))
    zoo = ModelZoo(
        registry, cse=False, aot_namespaces=False,
        metrics_registry=reg,
    )
    zoo.host()
    srv = GatewayServer(zoo=zoo, port=0, registry=reg).start()
    return zoo, srv


@pytest.fixture
def mixed_fleet():
    """One plain single-model replica (configured at startup, no
    roster) + one zoo replica self-registering with its model ids."""
    plain_gw, plain_srv = _make_plain_replica(
        f"models-plain{next(_ids)}"
    )
    zoo, zoo_srv = _make_zoo_replica(
        f"models-zoo{next(_ids)}", ("m1", "m2")
    )
    router = RouterServer(
        [plain_srv.url()],
        port=0,
        name=f"models-router{next(_ids)}",
        registry=MetricsRegistry(),
        probe_interval_s=0.1,
        probe_timeout_s=5.0,
        recovery_after_s=0.3,
    ).start()
    post_roster(
        router.url(), "/registerz", zoo_srv.url(),
        models=("m1", "m2"),
    )
    router.fleet.probe_once()
    yield router, (plain_gw, plain_srv), (zoo, zoo_srv)
    router.stop()
    plain_gw.close()
    plain_srv.stop()
    zoo.close()
    zoo_srv.stop()


def test_model_request_routes_to_advertising_replica(mixed_fleet):
    router, _, (zoo, _zoo_srv) = mixed_fleet
    doc = {"instances": [np.linspace(-1, 1, ZD).tolist()]}
    for _ in range(4):
        status, body = _post(router.url("/predict/m1"), doc)
        assert status == 200
        assert len(body["predictions"]) == 1
    # every forward landed on the advertiser: the zoo replica's m1
    # gateway served all of them
    assert (
        zoo.gateway_for("m1").metrics.outcome_count("ok") == 4.0
    )
    # the two heads answer differently through the same router
    _, m2 = _post(router.url("/predict/m2"), doc)
    _, m1 = _post(router.url("/predict/m1"), doc)
    assert m1["predictions"] != m2["predictions"]


def test_unadvertised_model_is_typed_503(mixed_fleet):
    router, _, _ = mixed_fleet
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(
            router.url("/predict/ghost"),
            {"instances": [[0.0] * ZD]},
        )
    assert ei.value.code == 503
    body = json.loads(ei.value.read())
    assert body["error"] == "no_replica_for_model"
    assert body["model"] == "ghost"
    # bare /predict still routes (any replica can serve it)
    status, _ = _post(
        router.url("/predict"), {"instances": [[0.0] * D]}
    )
    assert status == 200


def test_registerz_heartbeat_refreshes_models(mixed_fleet):
    router, _, (_zoo, zoo_srv) = mixed_fleet
    url = zoo_srv.url().rstrip("/")
    _, doc = _post(
        router.url("/registerz"),
        {"url": url, "models": ["m1", "m2", "m3"]},
    )
    assert not doc["created"]  # a heartbeat, not a new replica
    assert doc["models"] == ["m1", "m2", "m3"]
    row = next(
        r for r in router.fleet.roster()["replicas"]
        if r["url"] == url
    )
    assert row["models"] == ["m1", "m2", "m3"]
    # a heartbeat WITHOUT models leaves the roster untouched
    _, doc = _post(router.url("/registerz"), {"url": url})
    assert doc["models"] == ["m1", "m2", "m3"]


def test_registerz_rejects_bad_models_field(mixed_fleet):
    router, _, (_zoo, zoo_srv) = mixed_fleet
    for models in ("m1", [1, 2], {"m": 1}):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(
                router.url("/registerz"),
                {"url": zoo_srv.url(), "models": models},
            )
        assert ei.value.code == 400


# -- ReplicaRegistry model filter, no sockets -------------------------------


def _by_url(fleet, url):
    return next(r for r in fleet.replicas() if r.url == url)


def test_pick_filters_advertisers_before_health_tiers():
    fleet = ReplicaRegistry(["http://a:1", "http://b:2"])
    a = _by_url(fleet, "http://a:1")
    b = _by_url(fleet, "http://b:2")
    a.set_models(("m1",))
    # bare picks see both; model picks see only the advertiser —
    # even though b is equally healthy
    assert fleet.pick(model="m1") is a
    assert fleet.pick(model="m1", exclude=(a,)) is None
    # health fallbacks relax HEALTH, never the advertiser filter: an
    # unhealthy advertiser still beats a healthy non-advertiser
    for _ in range(3):
        a.mark_failed("boom")
    assert not a.healthy
    assert fleet.pick(model="m1") is a
    assert fleet.pick(model="m2") is None
    assert fleet.pick() in (a, b)


def test_registry_add_refreshes_models_and_status_reports_them():
    fleet = ReplicaRegistry()
    replica, created = fleet.add(
        "http://a:1", models=("zeta", "alpha")
    )
    assert created
    assert replica.advertises("zeta")
    assert not replica.advertises("omega")
    row = fleet.roster()["replicas"][0]
    assert row["models"] == ["alpha", "zeta"]
    # heartbeat with a new roster replaces; without one, keeps
    _, created = fleet.add("http://a:1", models=("m9",))
    assert not created
    assert replica.models == frozenset({"m9"})
    fleet.add("http://a:1")
    assert replica.models == frozenset({"m9"})
