"""Replica registry unit coverage: membership, the two-layer health
state machine with half-open recovery (mirroring ``Lane.healthy``),
least-loaded picks with the availability-over-purity fallbacks, and
the ``/fleetz`` roster shape. No sockets here — the probe/HTTP half
is covered by ``test_router_http.py`` against live servers."""

import time

import pytest

from keystone_tpu.fleet.registry import Replica, ReplicaRegistry


def _registry(**kwargs):
    kwargs.setdefault("probe_interval_s", 60.0)  # probes never fire
    return ReplicaRegistry(**kwargs)


# -- membership ------------------------------------------------------------


def test_static_urls_and_registration_dedupe():
    reg = _registry(urls=["http://127.0.0.1:1", "http://127.0.0.1:2/"])
    assert len(reg) == 2
    r, created = reg.add("http://127.0.0.1:3", source="registered")
    assert created and r.index == 2 and r.source == "registered"
    # re-registration (trailing slash and all) is a heartbeat
    r2, created = reg.add("http://127.0.0.1:3/")
    assert not created and r2 is r
    assert len(reg) == 3
    assert [x.index for x in reg.replicas()] == [0, 1, 2]


def test_bad_urls_rejected():
    with pytest.raises(ValueError):
        Replica("ftp://127.0.0.1:1", index=0)
    with pytest.raises(ValueError):
        _registry().add("not a url")


# -- the health state machine ----------------------------------------------


def test_request_failures_bench_then_half_open_then_restore():
    r = Replica(
        "http://127.0.0.1:9", index=0,
        unhealthy_after=3, recovery_after_s=0.05,
    )
    r.record_probe(alive=True, ready=True, detail="ok")
    assert r.healthy and r.state == "healthy"
    r.mark_failed("boom")
    r.mark_failed("boom")
    assert r.healthy  # two strikes: still in
    r.mark_failed("boom")
    assert not r.healthy and r.state == "unhealthy"
    time.sleep(0.06)
    # cool-down elapsed: half-open, probe traffic allowed again
    assert r.healthy and r.state == "half-open"
    r.mark_ok()
    assert r.state == "healthy"
    assert r.status()["consecutive_failures"] == 0


def test_probe_liveness_overrides_but_does_not_reset_request_health():
    r = Replica(
        "http://127.0.0.1:9", index=0,
        unhealthy_after=3, recovery_after_s=0.05,
    )
    for _ in range(3):
        r.mark_failed("blackholed")
    r.record_probe(alive=True, ready=True, detail="ok")
    # a PASSING probe must not overrule failing traffic: the replica
    # stays benched until the half-open window, probes notwithstanding
    assert not r.healthy and r.state == "unhealthy"
    time.sleep(0.06)
    assert r.state == "half-open"
    # and a dead process is out regardless of request history
    r.mark_ok()
    r.record_probe(alive=False, detail="probe failed: refused")
    assert not r.healthy and r.state == "unreachable"


# -- routing picks ----------------------------------------------------------


def _fleet_of_three():
    reg = _registry(
        urls=[f"http://127.0.0.1:{p}" for p in (11, 12, 13)],
        recovery_after_s=60.0,
    )
    replicas = reg.replicas()
    for i, r in enumerate(replicas):
        r.record_probe(alive=True, ready=True, detail="ok", load=i)
    return reg, replicas


def test_pick_least_loaded_and_exclude():
    reg, (r0, r1, r2) = _fleet_of_three()
    assert reg.pick() is r0
    assert reg.pick(exclude=[r0]) is r1
    assert reg.pick(exclude=[r0, r1]) is r2
    assert reg.pick(exclude=[r0, r1, r2]) is None


def test_router_inflight_counts_toward_load():
    reg, (r0, r1, r2) = _fleet_of_three()
    for _ in range(3):
        r0.begin_request()
    assert r0.load == 3.0
    assert reg.pick() is r1
    r0.end_request()
    assert r0.load == 2.0


def test_pick_prefers_ready_then_healthy_then_anything():
    reg, (r0, r1, r2) = _fleet_of_three()
    # r0 draining (alive, not ready): skipped while a ready one exists
    r0.record_probe(alive=True, ready=False, detail="draining", load=0)
    assert reg.pick() is r1
    # everyone draining: a healthy-but-unready replica beats nothing
    for r in (r1, r2):
        r.record_probe(alive=True, ready=False, detail="draining",
                       load=r.index)
    assert reg.pick() is r0
    # everyone benched: availability over purity (and probe traffic)
    for r in (r0, r1, r2):
        for _ in range(3):
            r.mark_failed("x")
    assert reg.pick() in (r0, r1, r2)


# -- roster -----------------------------------------------------------------


def test_roster_shape_and_counts():
    reg, (r0, r1, r2) = _fleet_of_three()
    for _ in range(3):
        r2.mark_failed("kaboom")
    doc = reg.roster()
    assert [row["index"] for row in doc["replicas"]] == [0, 1, 2]
    assert doc["counts"] == {"healthy": 2, "unhealthy": 1}
    row = doc["replicas"][2]
    assert row["state"] == "unhealthy" and row["healthy"] is False
    assert row["last_failure"] == "kaboom"
    assert doc["replicas"][0]["ready"] is True
    assert set(row) >= {
        "url", "name", "index", "source", "ready", "ready_detail",
        "load", "router_inflight", "consecutive_failures", "build",
        "state", "healthy",
    }
