"""Histogram/exposition federation: golden-string merges of
per-replica scrapes (summed ``le`` buckets incl. ``+Inf``, counter
families, gauge summation, metadata carry-over, conflicting-layout
rejection) and PromQL quantiles over the merged result."""

import math

import pytest

from keystone_tpu.observability.slo import Slo
from keystone_tpu.observability.prometheus import (
    histogram_buckets,
    merge_expositions,
    merge_histograms,
    parse_samples,
    quantile_from_buckets,
)

INF = float("inf")

SCRAPE_A = """\
# HELP keystone_gateway_request_latency_seconds end-to-end latency
# TYPE keystone_gateway_request_latency_seconds histogram
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="0.1"} 5
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="0.5"} 8
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="+Inf"} 10
keystone_gateway_request_latency_seconds_count{gateway="g"} 10
keystone_gateway_request_latency_seconds_sum{gateway="g"} 1.5
# HELP keystone_gateway_requests_total terminal outcomes
# TYPE keystone_gateway_requests_total counter
keystone_gateway_requests_total{gateway="g",status="ok"} 10
"""

SCRAPE_B = """\
# TYPE keystone_gateway_request_latency_seconds histogram
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="0.1"} 1
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="0.5"} 9
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="+Inf"} 12
keystone_gateway_request_latency_seconds_count{gateway="g"} 12
keystone_gateway_request_latency_seconds_sum{gateway="g"} 2.2
# TYPE keystone_gateway_requests_total counter
keystone_gateway_requests_total{gateway="g",status="ok"} 12
keystone_gateway_requests_total{gateway="g",status="shed"} 3
"""

MERGED_GOLDEN = """\
# HELP keystone_gateway_request_latency_seconds end-to-end latency
# TYPE keystone_gateway_request_latency_seconds histogram
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="0.1"} 6
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="0.5"} 17
keystone_gateway_request_latency_seconds_bucket{gateway="g",le="+Inf"} 22
keystone_gateway_request_latency_seconds_count{gateway="g"} 22
keystone_gateway_request_latency_seconds_sum{gateway="g"} 3.7
# HELP keystone_gateway_requests_total terminal outcomes
# TYPE keystone_gateway_requests_total counter
keystone_gateway_requests_total{gateway="g",status="ok"} 22
keystone_gateway_requests_total{gateway="g",status="shed"} 3
"""


# -- merge_histograms (the SLO-federation primitive) -----------------------


def test_merge_histograms_sums_by_le():
    merged = merge_histograms(
        [
            [(0.1, 5.0), (0.5, 8.0), (INF, 10.0)],
            [(0.1, 1.0), (0.5, 9.0), (INF, 12.0)],
        ]
    )
    assert merged == [(0.1, 6.0), (0.5, 17.0), (INF, 22.0)]


def test_merge_histograms_skips_empty_and_keeps_layout():
    merged = merge_histograms([[], [(0.1, 1.0), (INF, 2.0)], []])
    assert merged == [(0.1, 1.0), (INF, 2.0)]
    assert merge_histograms([[], []]) == []


def test_merge_histograms_collapses_duplicate_le_within_one_scrape():
    # one scrape can carry several series of the family (two gateways
    # in one process): same le entries collapse by summing first
    merged = merge_histograms(
        [
            [(0.1, 1.0), (INF, 2.0), (0.1, 3.0), (INF, 4.0)],
            [(0.1, 10.0), (INF, 20.0)],
        ]
    )
    assert merged == [(0.1, 14.0), (INF, 26.0)]


def test_merge_histograms_rejects_conflicting_layouts():
    with pytest.raises(ValueError, match="conflicting"):
        merge_histograms(
            [
                [(0.1, 1.0), (INF, 2.0)],
                [(0.25, 1.0), (INF, 2.0)],
            ]
        )


def test_fleet_quantile_over_merged_buckets():
    a = [(0.1, 99.0), (0.5, 99.0), (INF, 100.0)]  # fast replica
    b = [(0.1, 0.0), (0.5, 80.0), (INF, 100.0)]   # slow replica
    merged = merge_histograms([a, b])
    q_fleet = quantile_from_buckets(0.5, merged)
    q_a = quantile_from_buckets(0.5, a)
    q_b = quantile_from_buckets(0.5, b)
    # the fleet median is the quantile of the UNION: between the
    # per-replica medians, equal to neither
    assert q_a < q_fleet < q_b
    # and +Inf clamping still behaves on the merge
    assert quantile_from_buckets(0.999, merged) == 0.5


# -- merge_expositions (the router's /metrics body) ------------------------


def test_merge_expositions_golden():
    assert merge_expositions([SCRAPE_A, SCRAPE_B]) == MERGED_GOLDEN


def test_merged_body_round_trips_through_the_scrape_parsers():
    body = merge_expositions([SCRAPE_A, SCRAPE_B])
    buckets = histogram_buckets(
        body, "keystone_gateway_request_latency_seconds",
        {"gateway": "g"},
    )
    assert buckets == merge_histograms(
        [
            histogram_buckets(
                t, "keystone_gateway_request_latency_seconds"
            )
            for t in (SCRAPE_A, SCRAPE_B)
        ]
    )
    rows = dict(
        ((name, tuple(sorted(labels.items()))), value)
        for name, labels, value in parse_samples(body)
    )
    key = (
        "keystone_gateway_requests_total",
        (("gateway", "g"), ("status", "ok")),
    )
    assert rows[key] == 22.0


def test_merge_expositions_ratio_families_take_max_not_sum():
    """Identical-label RATIO gauges federate by worst-case: two
    replicas each at MFU 0.4 are not a fleet at 0.8, and two burn
    rates of 0.9 must not sum into a page-worthy fabricated 1.8."""
    a = (
        'keystone_serving_mfu{engine="g-lane0"} 0.4\n'
        'keystone_slo_burn_rate{slo="g:latency",window="fast"} 0.9\n'
        'keystone_gateway_inflight{gateway="g"} 3\n'
    )
    b = (
        'keystone_serving_mfu{engine="g-lane0"} 0.3\n'
        'keystone_slo_burn_rate{slo="g:latency",window="fast"} 0.7\n'
        'keystone_gateway_inflight{gateway="g"} 4\n'
    )
    body = merge_expositions([a, b])
    assert 'keystone_serving_mfu{engine="g-lane0"} 0.4' in body
    assert (
        'keystone_slo_burn_rate{slo="g:latency",window="fast"} 0.9'
        in body
    )
    # additive gauges still sum (fleet load truth)
    assert 'keystone_gateway_inflight{gateway="g"} 7' in body


def test_merge_expositions_distinct_labels_coexist():
    a = 'keystone_gateway_inflight{gateway="r0"} 3\n'
    b = 'keystone_gateway_inflight{gateway="r1"} 4\n'
    body = merge_expositions([a, b])
    assert 'keystone_gateway_inflight{gateway="r0"} 3' in body
    assert 'keystone_gateway_inflight{gateway="r1"} 4' in body


def test_merge_expositions_conflicting_layout_raise_and_drop():
    conflicted = SCRAPE_B.replace('le="0.5"', 'le="0.25"')
    with pytest.raises(ValueError, match="conflicting"):
        merge_expositions([SCRAPE_A, conflicted])
    body = merge_expositions(
        [SCRAPE_A, conflicted], on_conflict="drop"
    )
    # the un-summable family is gone entirely...
    assert "keystone_gateway_request_latency_seconds" not in body
    # ...while the counters still federate
    assert (
        'keystone_gateway_requests_total{gateway="g",status="ok"} 22'
        in body
    )


def test_merge_expositions_rejects_bad_mode():
    with pytest.raises(ValueError, match="on_conflict"):
        merge_expositions([SCRAPE_A], on_conflict="ignore")


# -- model-labeled families across replicas --------------------------------


REPLICA_A_ZOO = """\
# HELP keystone_attr_device_seconds_total device seconds charged per model
# TYPE keystone_attr_device_seconds_total counter
keystone_attr_device_seconds_total{model="alpha"} 2.5
keystone_attr_device_seconds_total{model="beta"} 1.0
keystone_attr_goodput_rows_total{model="alpha"} 100
keystone_attr_goodput_rows_total{model="beta"} 40
keystone_zoo_resident{model="alpha"} 1
keystone_zoo_resident{model="beta"} 1
keystone_zoo_pageins_total{model="alpha"} 1
keystone_drift_score{model="alpha"} 0.4
keystone_drift_score{model="beta"} 0.05
"""

# overlapping (alpha) AND distinct (gamma) model sets vs replica A
REPLICA_B_ZOO = """\
# TYPE keystone_attr_device_seconds_total counter
keystone_attr_device_seconds_total{model="alpha"} 0.5
keystone_attr_device_seconds_total{model="gamma"} 4.0
keystone_attr_goodput_rows_total{model="alpha"} 20
keystone_attr_goodput_rows_total{model="gamma"} 200
keystone_zoo_resident{model="alpha"} 1
keystone_zoo_resident{model="gamma"} 1
keystone_zoo_pageins_total{model="alpha"} 2
keystone_drift_score{model="alpha"} 0.1
keystone_drift_score{model="gamma"} 0.3
"""


def _rows(body):
    return {
        (name, tuple(sorted(labels.items()))): value
        for name, labels, value in parse_samples(body)
    }


def test_merge_expositions_model_label_sets_sum_per_model():
    """Counters with the SAME model label sum across replicas; each
    label set stays its own series — no cross-model bleed."""
    rows = _rows(merge_expositions([REPLICA_A_ZOO, REPLICA_B_ZOO]))

    def row(name, model):
        return rows[(name, (("model", model),))]

    # overlapping model: per-replica values sum
    assert row("keystone_attr_device_seconds_total", "alpha") == 3.0
    assert row("keystone_attr_goodput_rows_total", "alpha") == 120.0
    assert row("keystone_zoo_pageins_total", "alpha") == 3.0
    # distinct models: carried through verbatim, not blended
    assert row("keystone_attr_device_seconds_total", "beta") == 1.0
    assert row("keystone_attr_device_seconds_total", "gamma") == 4.0
    assert row("keystone_attr_goodput_rows_total", "gamma") == 200.0
    # residency is additive (replica count holding the model)
    assert row("keystone_zoo_resident", "alpha") == 2.0
    assert row("keystone_zoo_resident", "beta") == 1.0


def test_merge_expositions_no_cross_model_bleed():
    """The merged body must contain EXACTLY the union of the input
    label sets per family — no invented models, none dropped."""
    rows = _rows(merge_expositions([REPLICA_A_ZOO, REPLICA_B_ZOO]))
    models = sorted(
        labels[0][1]
        for (name, labels), _ in rows.items()
        if name == "keystone_attr_device_seconds_total"
    )
    assert models == ["alpha", "beta", "gamma"]
    # beta only ever appeared on replica A: its value is A's alone
    assert rows[
        ("keystone_attr_goodput_rows_total", (("model", "beta"),))
    ] == 40.0


def test_merge_expositions_drift_score_takes_fleet_max():
    """``keystone_drift_score`` is a divergence ratio, not a
    quantity: the fleet's score per model is the WORST replica's, and
    two replicas each under threshold must never sum into a
    fabricated page."""
    rows = _rows(merge_expositions([REPLICA_A_ZOO, REPLICA_B_ZOO]))
    assert rows[("keystone_drift_score", (("model", "alpha"),))] == 0.4
    assert rows[("keystone_drift_score", (("model", "beta"),))] == 0.05
    assert rows[("keystone_drift_score", (("model", "gamma"),))] == 0.3


def test_attribution_document_from_federated_scrape():
    """The router's ``/attributionz`` path: federate, parse, rebuild —
    per-model cells are fleet sums and the shares are computed over
    the fleet totals."""
    from keystone_tpu.observability.attribution import (
        attribution_from_samples,
    )

    body = merge_expositions([REPLICA_A_ZOO, REPLICA_B_ZOO])
    doc = attribution_from_samples(parse_samples(body))
    assert set(doc["models"]) == {"alpha", "beta", "gamma"}
    assert doc["models"]["alpha"]["device_seconds"] == 3.0
    assert doc["totals"]["device_seconds"] == 8.0
    assert doc["models"]["gamma"]["device_seconds_share"] == 0.5
    assert math.isclose(
        sum(m["device_seconds_share"] for m in doc["models"].values()),
        1.0,
    )


# -- Slo.latency_from_buckets (the fleet-SLO read) -------------------------


def test_slo_latency_from_buckets_reads_total_and_bad():
    buckets = [(0.1, 80.0), (0.5, 95.0), (INF, 100.0)]
    slo = Slo.latency_from_buckets(
        "fleet:lat", lambda: buckets, threshold_s=0.1, target=0.99
    )
    assert slo.read() == (100.0, 20.0)  # 20 requests over 100ms
    # snap UP to the next finite bound, same rule as Slo.latency
    slo = Slo.latency_from_buckets(
        "fleet:lat2", lambda: buckets, threshold_s=0.2, target=0.99
    )
    assert slo.read() == (100.0, 5.0)
    empty = Slo.latency_from_buckets(
        "fleet:lat3", lambda: [], threshold_s=0.1, target=0.99
    )
    assert empty.read() == (0.0, 0.0)


def test_slo_latency_from_buckets_unobservable_threshold_clamps(caplog):
    """A threshold past every finite bound must NOT snap to +Inf
    (everything good, a dead objective that can never burn): it
    clamps DOWN to the largest finite bound with a one-time warning,
    keeping the SLO live and conservatively strict."""
    buckets = [(0.1, 80.0), (0.5, 95.0), (INF, 100.0)]
    slo = Slo.latency_from_buckets(
        "fleet:dead", lambda: buckets, threshold_s=30.0, target=0.99
    )
    with caplog.at_level("WARNING"):
        assert slo.read() == (100.0, 5.0)  # judged at 0.5s, not +Inf
        assert slo.read() == (100.0, 5.0)
    warnings = [
        r for r in caplog.records if "clamping" in r.getMessage()
    ]
    assert len(warnings) == 1  # warned once, not per sample


def test_slo_latency_from_buckets_dead_replica_reads():
    """The control loop hits these constantly: a read fn that raises
    mid-scrape is survived by the monitor, but the read itself must
    also degrade — None and empty-merge inputs are (0, 0), never an
    exception, never invented zeros-as-bad."""
    slo = Slo.latency_from_buckets(
        "fleet:none", lambda: None, threshold_s=0.1, target=0.99
    )
    assert slo.read() == (0.0, 0.0)
    # a fleet where EVERY replica's scrape was empty merges to []
    slo = Slo.latency_from_buckets(
        "fleet:dead",
        lambda: merge_histograms([[], []]),
        threshold_s=0.1,
        target=0.99,
    )
    assert slo.read() == (0.0, 0.0)


def test_slo_latency_from_buckets_partial_merge():
    """One replica dead (empty contribution), one alive: the merged
    read is the survivor's distribution — partial, not absent."""
    alive = [(0.1, 80.0), (0.5, 95.0), (INF, 100.0)]
    slo = Slo.latency_from_buckets(
        "fleet:partial",
        lambda: merge_histograms([[], alive, []]),
        threshold_s=0.1,
        target=0.99,
    )
    assert slo.read() == (100.0, 20.0)


def test_slo_latency_from_buckets_inf_only_layout():
    """A degenerate scrape carrying only the +Inf bucket cannot judge
    any request good or bad at a finite threshold — total counted,
    zero bad (unjudgeable, not failing)."""
    slo = Slo.latency_from_buckets(
        "fleet:inf", lambda: [(INF, 7.0)], threshold_s=0.1, target=0.99
    )
    assert slo.read() == (7.0, 0.0)


def test_merge_expositions_single_scrape_is_normalizing_identity():
    body = merge_expositions([SCRAPE_A])
    assert parse_samples(body) == parse_samples(SCRAPE_A)
    assert math.isclose(
        dict(
            (name, value)
            for name, labels, value in parse_samples(body)
            if labels.get("le") == "+Inf"
        )["keystone_gateway_request_latency_seconds_bucket"],
        10.0,
    )
