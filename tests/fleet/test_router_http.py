"""The fleet tier end to end, in-process: a ``RouterServer`` fronting
two real ``GatewayServer`` replicas over actual sockets — routing,
retry-on-replica-failure, typed ``Overloaded`` propagation across the
hop, ``/registerz`` self-registration, the ``/fleetz`` roster through
a kill/restart cycle, federated ``/metrics``, and the
``router.replica.blackhole`` chaos point."""

import itertools
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from keystone_tpu.fleet import RouterServer
from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.loadgen import faults
from keystone_tpu.observability.prometheus import (
    histogram_buckets,
    merge_histograms,
    parse_samples,
    quantile_from_buckets,
)
from keystone_tpu.observability.registry import MetricsRegistry

from gateway_fixtures import D, batch, make_fitted

_ids = itertools.count()


def _make_replica(name):
    """One 'host': gateway + HTTP frontend on a PRIVATE registry
    (in one test process the replicas must not share series, exactly
    like real processes wouldn't)."""
    reg = MetricsRegistry()
    gw = Gateway(
        make_fitted(),
        buckets=(4, 8),
        n_lanes=1,
        max_delay_ms=1.0,
        warmup_example=np.zeros(D, np.float32),
        name=name,
        registry=reg,
    )
    srv = GatewayServer(gw, port=0, registry=reg).start()
    return gw, srv


@pytest.fixture
def fleet():
    """Two replicas + a router with fast probes/recovery."""
    replicas = [
        _make_replica(f"fleet-r{next(_ids)}") for _ in range(2)
    ]
    router = RouterServer(
        [srv.url() for _, srv in replicas],
        port=0,
        name=f"router{next(_ids)}",
        registry=MetricsRegistry(),
        probe_interval_s=0.1,
        probe_timeout_s=5.0,
        recovery_after_s=0.3,
    ).start()
    router.fleet.probe_once()
    yield router, replicas
    router.stop()
    for gw, srv in replicas:
        gw.close()
        srv.stop()


def _get(url, timeout=15):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(url, doc, timeout=30):
    req = urllib.request.Request(
        url,
        data=json.dumps(doc).encode("utf-8"),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _predict(router, n=2, seed=0, **extra):
    doc = {"instances": batch(n, seed=seed).tolist(), **extra}
    return _post(router.url("/predict"), doc)


# -- plain routing ----------------------------------------------------------


def test_predict_routes_and_spreads_load(fleet):
    router, replicas = fleet
    for seed in range(6):
        status, doc = _predict(router, n=2, seed=seed)
        assert status == 200
        assert len(doc["predictions"]) == 2
    served = [
        gw.metrics.outcome_count("ok") for gw, _ in replicas
    ]
    assert sum(served) == 12.0
    assert router.metrics.outcome_count("ok") == 6.0


def test_readyz_and_healthz(fleet):
    router, _ = fleet
    status, body = _get(router.url("/readyz"))
    assert status == 200 and b"2/2 replicas ready" in body
    assert _get(router.url("/healthz"))[0] == 200


def test_client_errors_propagate_without_retry(fleet):
    router, _ = fleet
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(router.url("/predict"), {"instances": []})
    assert e.value.code == 400
    assert json.loads(e.value.read())["error"] == "bad_request"
    assert router.metrics.retry_count() == 0.0


# -- failover + health ------------------------------------------------------


def test_killed_replica_routed_around_and_fleetz_tracks_recovery(fleet):
    router, replicas = fleet
    gw0, srv0 = replicas[0]
    # remember the port so the "restart" comes back at the same URL
    url0 = srv0.url().rstrip("/")
    port0 = srv0.port
    # kill the LISTENER abruptly (the process-death analogue: no
    # drain, connections refused from here on)
    srv0.stop()
    # every request still answers: the router either retried onto
    # replica 1 (request-path failure) or a probe benched replica 0
    # first and routing skipped it — both are the failover working
    # (the blackhole test below pins the retry path deterministically)
    for seed in range(5):
        status, doc = _predict(router, n=1, seed=seed)
        assert status == 200
    # the roster shows the dead replica benched (request-path
    # failures) or unreachable (once a probe lands)
    router.fleet.probe_once()
    _, body = _get(router.url("/fleetz"))
    roster = json.loads(body)
    row = next(
        r for r in roster["replicas"] if r["url"] == url0
    )
    assert row["healthy"] is False
    assert row["state"] in ("unhealthy", "unreachable", "half-open")
    # router stays ready: one replica is enough
    assert _get(router.url("/readyz"))[0] == 200

    # "restart the process" at the same address
    srv0b = GatewayServer(
        gw0, port=port0, registry=replicas[0][0].metrics.registry
    ).start()
    try:
        import time

        deadline = time.time() + 10.0
        while time.time() < deadline:
            router.fleet.probe_once()
            replica = next(
                r for r in router.fleet.replicas() if r.url == url0
            )
            if replica.state in ("half-open", "healthy"):
                break
            time.sleep(0.05)
        # half-open: the next request is the probe, and one success
        # fully restores the replica
        assert replica.state in ("half-open", "healthy")
        for seed in range(8):
            assert _predict(router, n=1, seed=10 + seed)[0] == 200
        assert replica.state == "healthy"
    finally:
        srv0b.stop()


def test_typed_overloaded_propagates_when_whole_fleet_drains(fleet):
    router, replicas = fleet
    for gw, _ in replicas:
        gw.close()  # typed 503/closed from every replica
    router.fleet.probe_once()
    with pytest.raises(urllib.error.HTTPError) as e:
        _predict(router, n=1)
    assert e.value.code == 503
    doc = json.loads(e.value.read())
    # the typed semantics survived the extra hop: still an
    # "overloaded"/"closed" body, never a naked 500
    assert doc["error"] == "overloaded"
    assert doc["reason"] == "closed"
    assert _get(router.url("/readyz"))[0] == 503


def test_untyped_500_reproduced_propagates_as_error(fleet):
    """An untyped 5xx that reproduces on the retry replica must
    surface AS the error it is — a 500-ing fleet must look like one,
    never like a typed shed (the invariant checker's cardinal sin
    would otherwise be invisible behind the router)."""
    router, replicas = fleet
    # every lane of every replica fails its dispatch: the gateways
    # themselves answer 500 prediction_failed (untyped)
    faults.arm("engine.dispatch.error", for_s=30.0)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _predict(router, n=1, seed=3)
        assert e.value.code == 500
        doc = json.loads(e.value.read())
        assert doc.get("error") != "overloaded"
        assert router.metrics.outcome_count("error") >= 1.0
    finally:
        faults.disarm_all()


def test_single_dead_replica_counts_no_retry():
    """keystone_router_retries_total means 'a second attempt actually
    dispatched' — a fleet with nowhere to retry TO must not count
    one per request."""
    gw, srv = _make_replica(f"fleet-r{next(_ids)}")
    router = RouterServer(
        [srv.url()], port=0, name=f"router{next(_ids)}",
        registry=MetricsRegistry(), probe_interval_s=30.0,
    ).start()
    try:
        srv.stop()  # the only replica is gone
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(router.url("/predict"), {"instances": [[0.0] * D]})
        assert e.value.code == 503
        assert json.loads(e.value.read())["reason"] == "closed"
        assert router.metrics.retry_count() == 0.0
    finally:
        router.stop()
        gw.close()


def test_no_replicas_sheds_typed():
    router = RouterServer(
        [], port=0, name=f"router{next(_ids)}",
        registry=MetricsRegistry(), probe_interval_s=30.0,
    ).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(router.url("/predict"), {"instances": [[0.0] * D]})
        assert e.value.code == 503
        assert json.loads(e.value.read())["reason"] == "closed"
    finally:
        router.stop()


# -- self-registration ------------------------------------------------------


def test_registerz_adds_probes_and_serves(fleet):
    router, _ = fleet
    gw, srv = _make_replica(f"fleet-r{next(_ids)}")
    try:
        status, doc = _post(
            router.url("/registerz"), {"url": srv.url()}
        )
        assert status == 200
        assert doc["registered"] and doc["created"]
        assert doc["replicas"] == 3
        # idempotent: re-registration is a heartbeat
        _, doc = _post(router.url("/registerz"), {"url": srv.url()})
        assert not doc["created"] and doc["replicas"] == 3
        router.fleet.probe_once()
        _, body = _get(router.url("/fleetz"))
        row = next(
            r
            for r in json.loads(body)["replicas"]
            if r["url"] == srv.url().rstrip("/")
        )
        assert row["source"] == "registered"
        assert row["ready"] is True
    finally:
        gw.close()
        srv.stop()


def test_registerz_rejects_garbage(fleet):
    router, _ = fleet
    for doc in ({"url": "not a url"}, {"nope": 1}):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(router.url("/registerz"), doc)
        assert e.value.code == 400


# -- federation -------------------------------------------------------------


def test_metrics_federates_replica_scrapes(fleet):
    router, replicas = fleet
    # drive BOTH replicas directly (sequential requests through the
    # router all land on the least-loaded first replica — correct
    # routing, but a one-replica histogram is no federation test)...
    for gw, srv in replicas:
        for seed in range(4):
            status, doc = _post(
                srv.url("/predict"),
                {"instances": batch(1, seed=seed).tolist()},
            )
            assert status == 200
    # ...plus traffic through the router itself
    for seed in range(8):
        assert _predict(router, n=1, seed=seed)[0] == 200
    _, body = _get(router.url("/metrics"))
    text = body.decode("utf-8")
    # per-replica latency buckets from the ONE federated body merge
    # into the true fleet histogram
    per_replica = [
        histogram_buckets(
            text, "keystone_gateway_request_latency_seconds",
            {"gateway": gw.name},
        )
        for gw, _ in replicas
    ]
    assert all(b for b in per_replica)
    fleet_buckets = merge_histograms(per_replica)
    assert fleet_buckets[-1][1] == 16.0  # +Inf count = all requests
    assert quantile_from_buckets(0.99, fleet_buckets) is not None
    # outcome counters from both replicas rode along, as did the
    # router's own series
    rows = {
        (name, labels.get("gateway") or labels.get("router")): value
        for name, labels, value in parse_samples(text)
        if name in (
            "keystone_gateway_requests_total",
            "keystone_router_requests_total",
        )
        and labels.get("status") in ("ok", None)
    }
    total_ok = sum(
        v
        for (name, _), v in rows.items()
        if name == "keystone_gateway_requests_total"
    )
    assert total_ok == 16.0
    assert (
        "keystone_router_requests_total",
        router.name,
    ) in rows


def test_probe_reads_load_header_and_build_info(fleet):
    router, replicas = fleet
    router.fleet.probe_once()
    for replica in router.fleet.replicas():
        row = replica.status()
        assert row["ready"] is True
        # the X-Keystone-Load header parsed to a number (idle: 0)
        assert row["load"] == 0.0
        # build info came off the replica's own scrape
        assert "jax" in row["build"] or row["build"] == {}


# -- chaos: the fleet fault point -------------------------------------------


def test_blackhole_fault_retried_and_benches_replica(fleet):
    router, replicas = fleet
    retries_before = router.metrics.retry_count()
    fired_before = faults.get_injector().fired_count(
        "router.replica.blackhole"
    )
    # arm over the ROUTER's own /chaosz, like the loadgen would
    status, doc = _post(router.url("/chaosz"), {
        "arm": {
            "point": "router.replica.blackhole",
            "match": {"index": 0},
            "count": 3,
        },
    })
    assert status == 200
    assert "router.replica.blackhole" in doc["armed"]
    # replica 0's responses drop until its 3 strikes bench it; every
    # client call still answers 200 via the retry
    for seed in range(10):
        assert _predict(router, n=1, seed=seed)[0] == 200
    fired = faults.get_injector().fired_count(
        "router.replica.blackhole"
    ) - fired_before
    assert fired == 3
    assert router.metrics.retry_count() - retries_before == 3.0
    replica0 = next(
        r for r in router.fleet.replicas() if r.index == 0
    )
    assert replica0.state in ("unhealthy", "half-open")
    _post(router.url("/chaosz"), {"disarm": "*"})


def test_chaosz_rejects_unknown_point(fleet):
    router, _ = fleet
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(router.url("/chaosz"), {"arm": {"point": "not.a.point"}})
    assert e.value.code == 400
    assert "router.replica.blackhole" in json.loads(e.value.read())[
        "known"
    ]
