"""REAL multi-process execution of the multi-host runtime: two OS
processes, each with 4 virtual CPU devices, joined into one 8-device
runtime via jax.distributed — then a sharded BlockLS fit over the
process-spanning mesh, checked against a host numpy solve in each
process (reference substrate: bin/run-pipeline.sh:9-55 launches one JVM
per machine; here one SPMD process per host, parallel/runtime.py).

Also unit-tests the initialize() failure contract: partial config is a
clear error, and auto-detect failure on something that looks like a pod
raises instead of silently degrading to single-host.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKER = r"""
import os
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel import runtime
from keystone_tpu.parallel.dataset import Dataset
from keystone_tpu.ops.learning import BlockLeastSquaresEstimator

runtime.initialize()  # from COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 4, jax.local_device_count()
assert jax.device_count() == 8, jax.device_count()

mesh = mesh_lib.make_mesh()  # (8, 1) global mesh spanning both processes
N, D, K = 512, 96, 5
rng = np.random.default_rng(0)
Xh = rng.standard_normal((N, D)).astype(np.float32)
Yh = Xh @ rng.standard_normal((D, K)).astype(np.float32)
sh = NamedSharding(mesh, P("data"))
X = jax.make_array_from_callback((N, D), sh, lambda idx: Xh[idx])
Y = jax.make_array_from_callback((N, K), sh, lambda idx: Yh[idx])

with mesh_lib.use_mesh(mesh):
    est = BlockLeastSquaresEstimator(block_size=D, num_iter=1, lam=0.0)
    model = est.fit(Dataset.from_array(X, n=N), Dataset.from_array(Y, n=N))

# host reference: centered unregularized LS (what one pass over one
# full-width block solves exactly)
Xc = Xh - Xh.mean(0)
Yc = Yh - Yh.mean(0)
Wref = np.linalg.lstsq(Xc, Yc, rcond=None)[0]
# model.W is replicated; compare on device so no host gather is needed
err = float(jax.numpy.abs(model.W - jax.numpy.asarray(Wref)).max())
assert err < 1e-2, err
print("MPOK", jax.process_index(), err, flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_sharded_fit():
    """Two real processes x 4 virtual CPU devices -> one 8-device mesh,
    sharded BlockLS fit, result matches the host solve in each process."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        # the parent conftest's virtual-device env must not leak through
        env.pop("KEYSTONE_TPU_TEST_REAL", None)
        # nor any attached-accelerator plugin env (it would override
        # JAX_PLATFORMS=cpu and pin the worker to the single real chip)
        for v in list(env):
            if v.startswith(("PALLAS_AXON", "AXON_")):
                env.pop(v)
        env.pop("TPU_WORKER_HOSTNAMES", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process fit timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert "MPOK" in out, f"process {pid} missing OK marker:\n{out}"


def _fresh_runtime():
    from keystone_tpu.parallel import runtime

    runtime._initialized = False
    return runtime


def test_partial_config_is_clear_error(monkeypatch):
    runtime = _fresh_runtime()
    try:
        monkeypatch.setenv("NUM_PROCESSES", "2")
        monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="COORDINATOR_ADDRESS"):
            runtime.initialize()
    finally:
        runtime._initialized = True  # don't poison later tests


def test_pod_detection_refuses_silent_degrade(monkeypatch):
    runtime = _fresh_runtime()
    try:
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a,host-b")
        for v in ("COORDINATOR_ADDRESS", "NUM_PROCESSES", "PROCESS_ID"):
            monkeypatch.delenv(v, raising=False)
        # auto-detect init fails in this CPU test process (backend is
        # already up / no cluster metadata); on a pod that must raise
        with pytest.raises(RuntimeError, match="multi-host pod"):
            runtime.initialize()
    finally:
        runtime._initialized = True
