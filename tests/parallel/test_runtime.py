"""Multi-slice mesh shape logic + an end-to-end sharded fit on a
(dcn, data, model) mesh over the 8 virtual CPU devices (reference
equivalent: the Spark cluster substrate, SURVEY.md §2.10 comm-backend row;
multi-host orchestration via jax.distributed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.runtime import (
    make_multislice_mesh,
    multislice_shape,
)

# The 2x2x2 multislice mesh needs 8 devices — present on the virtual CPU
# mesh, absent on a single real chip (shared gate in tests/conftest.py)
mesh8 = pytest.mark.needs_mesh8


def test_multislice_shape_logic():
    assert multislice_shape(64, n_slices=4, n_model=2) == (4, 8, 2)
    assert multislice_shape(8, n_slices=2, n_model=1) == (2, 4, 1)
    assert multislice_shape(256, n_slices=4, n_model=8) == (4, 8, 8)
    with pytest.raises(ValueError):
        multislice_shape(8, n_slices=3)
    with pytest.raises(ValueError):
        multislice_shape(8, n_slices=2, n_model=3)


@mesh8
def test_multislice_mesh_axes():
    mesh = make_multislice_mesh(n_slices=2, n_model=2)
    assert mesh.axis_names == ("dcn", "data", "model")
    assert mesh.shape["dcn"] == 2
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 2
    # data sharding spans dcn x data
    assert mesh_lib.n_data_shards(mesh) == 4
    sh = mesh_lib.data_sharding(mesh)
    assert sh.spec == P(("dcn", "data"), None)


@mesh8
def test_block_ls_fit_on_multislice_mesh():
    """The solver's Gram psums must compile + run with examples sharded
    over (dcn, data) and features over model — the full dp x tp x slice
    layout."""
    from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
    from keystone_tpu.parallel.dataset import Dataset

    mesh = make_multislice_mesh(n_slices=2, n_model=2)
    with mesh_lib.use_mesh(mesh):
        n, d, k = 64, 16, 4
        rng = np.random.default_rng(0)
        X_host = rng.standard_normal((n, d)).astype(np.float32)
        W_true = rng.standard_normal((d, k)).astype(np.float32)
        Y_host = X_host @ W_true
        X = jax.device_put(
            jnp.asarray(X_host),
            NamedSharding(mesh, P(("dcn", "data"), "model")),
        )
        Y = jax.device_put(
            jnp.asarray(Y_host),
            NamedSharding(mesh, P(("dcn", "data"), None)),
        )
        est = BlockLeastSquaresEstimator(block_size=8, num_iter=2, lam=0.01)
        model = est.fit(Dataset.from_array(X), Dataset.from_array(Y))
        preds = model.apply_batch(Dataset.from_array(X, n=n))
        err = float(jnp.abs(preds.padded() - Y).max())
        assert err < 1.0, err


def test_initialize_single_host_is_noop():
    from keystone_tpu.parallel import runtime

    runtime.initialize()  # no cluster env -> logs and returns
    runtime.initialize()  # idempotent
