"""Out-of-core streaming input pipeline tests (reference:
loaders/ImageLoaderUtils.scala:22-47 — per-executor tar streaming that
never materializes the dataset).

Covers: stream == eager-loader content parity, fixed-shape batching with
tail padding, cycle/limit semantics, per-process shard disjointness, the
VERDICT r3 "two processes read disjoint shards and produce the same
model as one" contract through REAL OS processes, and the bounded-RSS
guarantee the streaming design exists for.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu.loaders.streaming import (
    StreamingImageLoader,
    StreamingImageNetLoader,
    imagenet_label_fn,
    tar_shard_paths,
)

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


from jpeg_fixtures import make_image_tar  # noqa: E402  (shared generator)


@pytest.fixture
def tar_dir(tmp_path):
    """Four tars, two WNIDs, 5 images each + the WNID->class map file."""
    d = tmp_path / "tars"
    d.mkdir()
    wnids = ["n01000001", "n01000002", "n01000003", "n01000004"]
    for i, wnid in enumerate(wnids):
        make_image_tar(str(d / f"{wnid}.tar"), wnid, 5, seed0=i * 100)
    labels = tmp_path / "labels.txt"
    labels.write_text(
        "".join(f"{wnid} {i}\n" for i, wnid in enumerate(wnids))
    )
    return str(d), str(labels)


def test_stream_matches_eager_loader(tar_dir):
    """The streaming reader yields exactly what the eager ImageNetLoader
    materializes (same names, labels, pixel data)."""
    loc, labels = tar_dir
    from keystone_tpu.loaders.image_loaders import ImageNetLoader

    eager = ImageNetLoader(loc, labels).items()
    stream = list(
        StreamingImageNetLoader(
            loc, labels, shard_index=0, num_shards=1
        ).items()
    )
    assert len(stream) == len(eager) == 20
    for (name, label, arr), item in zip(stream, eager):
        assert name == item.filename
        assert label == item.label
        np.testing.assert_allclose(arr, item.image)


def test_batches_fixed_shape_and_tail_padding(tar_dir):
    loc, labels = tar_dir
    loader = StreamingImageNetLoader(
        loc, labels, decode_size=32, shard_index=0, num_shards=1
    )
    batches = list(loader.batches(8))
    assert len(batches) == 3  # 20 images -> 8 + 8 + 4
    for imgs, labs, n_valid in batches[:-1]:
        assert imgs.shape == (8, 32, 32, 3)
        assert n_valid == 8 and len(labs) == 8
    imgs, labs, n_valid = batches[-1]
    assert n_valid == 4 and len(labs) == 4
    assert np.all(imgs[4:] == 0.0)  # zero tail padding
    # labels arrive in stream order: tars sorted by wnid, 5 images each
    all_labels = [l for _, labs, _ in batches for l in labs]
    assert all_labels == [c for c in range(4) for _ in range(5)]


def test_featurized_batches_rides_fused_engine(tar_dir):
    """The fit-path loaders ride the SAME fused engine serving runs
    (``featurized_batches``): raw uint8 on the H2D wire with exact
    byte accounting, ONE compiled program, and features identical to
    driving the engine over ``batches()`` by hand."""
    loc, labels = tar_dir
    from keystone_tpu.serving.featurize import build_featurize_pipeline

    feat, feat_d = build_featurize_pipeline(img=16)
    engine = feat.compiled(buckets=(8,), aot_store=False)
    loader = StreamingImageNetLoader(
        loc, labels, decode_size=16, shard_index=0, num_shards=1
    )
    outs, labs_all, tot = [], [], 0
    for feats, labs, n_valid in loader.featurized_batches(engine, 8):
        outs.append(np.asarray(feats)[:n_valid])
        labs_all += labs
        tot += n_valid
    assert tot == len(labs_all) == 20
    got = np.concatenate(outs)
    assert got.shape == (20, feat_d)
    # 3 dispatches of the (8, 16, 16, 3) uint8 staging buffer — raw
    # pixels, never f32, padding included (real wire traffic)
    assert engine.metrics.h2d_bytes.total == 3 * 8 * 16 * 16 * 3
    assert engine.metrics.compile_count == 1

    want = np.concatenate([
        np.asarray(engine.apply(u8, sync=True))[:nv]
        for u8, _, nv in StreamingImageNetLoader(
            loc, labels, decode_size=16, shard_index=0, num_shards=1
        ).batches(8, np.uint8)
    ])
    np.testing.assert_array_equal(got, want)


def test_cycle_and_limit(tar_dir):
    loc, labels = tar_dir
    loader = StreamingImageNetLoader(
        loc, labels, shard_index=0, num_shards=1, cycle=3, limit=47
    )
    assert sum(1 for _ in loader.items()) == 47
    unlimited = StreamingImageNetLoader(
        loc, labels, shard_index=0, num_shards=1, cycle=3
    )
    assert sum(1 for _ in unlimited.items()) == 60


def test_shards_are_disjoint_and_cover(tar_dir):
    loc, _ = tar_dir
    s0 = tar_shard_paths(loc, 0, 2)
    s1 = tar_shard_paths(loc, 1, 2)
    assert not set(s0) & set(s1)
    assert sorted(s0 + s1) == tar_shard_paths(loc, 0, 1)
    # 3-way split with 4 files: sizes 2/1/1, still a partition
    parts = [tar_shard_paths(loc, i, 3) for i in range(3)]
    assert sorted(p for ps in parts for p in ps) == tar_shard_paths(loc, 0, 1)


def test_shard_statistics_sum_to_full_read(tar_dir):
    """Shard-and-sum == single-read for the statistics solvers consume
    (in-process version of the two-process contract below)."""
    loc, labels = tar_dir
    full_g, full_s = None, None
    for sh, world in [(0, 1)] + [(i, 2) for i in range(2)]:
        loader = StreamingImageNetLoader(
            loc, labels, decode_size=16, shard_index=sh, num_shards=world
        )
        g = np.zeros((16 * 16 * 3, 4))
        s = np.zeros((4,))
        for imgs, labs, n_valid in loader.batches(4):
            X = imgs[:n_valid].astype(np.float64).reshape(n_valid, -1) / 255.0
            onehot = np.eye(4)[np.asarray(labs)]
            g += X.T @ onehot
            s += onehot.sum(0)
        if world == 1:
            full_g, full_s = g, s
            shard_g, shard_s = np.zeros_like(g), np.zeros_like(s)
        else:
            shard_g += g
            shard_s += s
    np.testing.assert_allclose(shard_g, full_g, rtol=1e-12)
    np.testing.assert_allclose(shard_s, full_s)


_SHARD_WORKER = r"""
import os, sys
import numpy as np
from keystone_tpu.loaders.streaming import StreamingImageNetLoader

loc, labels, sh, world, out = sys.argv[1:6]
loader = StreamingImageNetLoader(
    loc, labels, decode_size=16, shard_index=int(sh), num_shards=int(world)
)
d = 16 * 16 * 3
xtx = np.zeros((d, d)); xty = np.zeros((d, 4)); n = 0
for imgs, labs, n_valid in loader.batches(4):
    X = imgs[:n_valid].astype(np.float64).reshape(n_valid, -1) / 255.0
    Y = np.eye(4)[np.asarray(labs)]
    xtx += X.T @ X; xty += X.T @ Y; n += n_valid
np.savez(out, xtx=xtx, xty=xty, n=n)
print("SHARDOK", sh, n, flush=True)
"""


def test_two_process_disjoint_shards_same_model(tar_dir, tmp_path):
    """VERDICT r3 missing #1 'done' contract: two OS processes stream
    disjoint tar shards, their summed normal-equation statistics produce
    the SAME ridge model as one process reading everything."""
    loc, labels = tar_dir
    outs = [str(tmp_path / f"shard{i}.npz") for i in range(2)]
    procs = []
    for i in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (
            REPO + os.pathsep + env.get("PYTHONPATH", "")
        )
        for v in list(env):
            if v.startswith(("PALLAS_AXON", "AXON_")):
                env.pop(v)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _SHARD_WORKER,
                 loc, labels, str(i), "2", outs[i]],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, cwd=REPO,
            )
        )
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, f"shard {i} failed:\n{out}"
        assert "SHARDOK" in out

    loaded = [np.load(o) for o in outs]
    xtx = sum(z["xtx"] for z in loaded)
    xty = sum(z["xty"] for z in loaded)
    n = sum(int(z["n"]) for z in loaded)
    assert n == 20

    # single-reader reference statistics
    loader = StreamingImageNetLoader(
        loc, labels, decode_size=16, shard_index=0, num_shards=1
    )
    xtx1 = np.zeros_like(xtx)
    xty1 = np.zeros_like(xty)
    for imgs, labs, n_valid in loader.batches(4):
        X = imgs[:n_valid].astype(np.float64).reshape(n_valid, -1) / 255.0
        Y = np.eye(4)[np.asarray(labs)]
        xtx1 += X.T @ X
        xty1 += X.T @ Y

    lam = 1e-3
    eye = lam * np.eye(xtx.shape[0])
    W_sharded = np.linalg.solve(xtx + eye, xty)
    W_single = np.linalg.solve(xtx1 + eye, xty1)
    # f64 accumulation-order roundoff through the ~4e6-condition
    # solve; the statistics themselves match to ~1e-12
    np.testing.assert_allclose(W_sharded, W_single, atol=1e-8)


def _vm_rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    raise RuntimeError("no VmRSS")


def test_streaming_rss_stays_flat(tar_dir):
    """The whole point of streaming: cycling the fixture tars to 4000
    images (an eager load would be 4000·96²·3·4B ≈ 440 MB) moves
    process RSS by far less than the eager footprint."""
    loc, labels = tar_dir
    loader = StreamingImageNetLoader(
        loc, labels, decode_size=96, shard_index=0, num_shards=1,
        cycle=200, decode_window=32,
    )
    seen = 0
    rss0 = None
    peak = 0.0
    for imgs, labs, n_valid in loader.batches(32):
        seen += n_valid
        if rss0 is None:
            rss0 = _vm_rss_mb()  # after pipeline spin-up
        peak = max(peak, _vm_rss_mb())
    assert seen == 4000
    growth = peak - rss0
    assert growth < 120, (
        f"RSS grew {growth:.0f} MB while streaming 4000 images "
        f"(eager load would be ~440 MB) — pipeline is materializing"
    )


def test_voc_stream_matches_eager_loader(tmp_path):
    """VOC multi-label path: the streaming reader and the eager
    VOCLoader must label the same members identically (the ImageNet
    parity test alone left the VOC csv path uncovered)."""
    from keystone_tpu.loaders.image_loaders import VOCLoader
    from keystone_tpu.loaders.streaming import StreamingVOCLoader

    d = tmp_path / "voc"
    d.mkdir()
    make_image_tar(str(d / "voc_imgs.tar"), "img", 6, seed0=7)
    labels = tmp_path / "voclabels.csv"
    rows = ["id,class,classname,traintesteval,filename"]
    # images 0..4 labeled (img_2 multi-label); img_5 unlabeled -> dropped
    rows += [
        "1,1,aeroplane,train,VOC2007/img_0.JPEG",
        "2,2,bicycle,train,VOC2007/img_1.JPEG",
        "3,1,aeroplane,train,VOC2007/img_2.JPEG",
        "4,3,bird,train,VOC2007/img_2.JPEG",
        "5,2,bicycle,train,VOC2007/img_3.JPEG",
        "6,1,aeroplane,train,VOC2007/img_4.JPEG",
    ]
    labels.write_text("\n".join(rows) + "\n")

    eager = VOCLoader(str(d), str(labels)).items()
    stream = list(
        StreamingVOCLoader(
            str(d), str(labels), shard_index=0, num_shards=1
        ).items()
    )
    assert len(stream) == len(eager) == 5
    for (name, labs, arr), item in zip(stream, eager):
        assert name.split("/")[-1] == item.filename
        assert labs == item.labels
        np.testing.assert_allclose(arr, item.image)
    # the multi-label member carries both classes (0-indexed)
    multi = [l for n, l, _ in stream if "img_2" in n]
    assert multi == [[0, 2]]


def test_process_pool_decode_matches_threads(tar_dir):
    """decode_processes > 0 (spawn workers, GIL-free) must yield the
    exact same ordered stream as the thread path."""
    loc, labels = tar_dir
    thread = list(
        StreamingImageNetLoader(
            loc, labels, decode_size=32, shard_index=0, num_shards=1
        ).items()
    )
    proc = list(
        StreamingImageNetLoader(
            loc, labels, decode_size=32, shard_index=0, num_shards=1,
            decode_processes=2, decode_window=8,
        ).items()
    )
    assert len(proc) == len(thread) == 20
    for (n1, l1, a1), (n2, l2, a2) in zip(proc, thread):
        assert n1 == n2 and l1 == l2
        np.testing.assert_array_equal(a1, a2)


def test_decode_is_run_to_run_deterministic(tar_dir):
    """Regression: the native decoder's lazy ctypes load used to race the
    decode THREAD pool on first use — threads arriving mid-load silently
    took the PIL fallback, so the first read of a stream decoded a
    nondeterministic mix of native/PIL pixels. A fresh subprocess (cold
    load, first decode inside the pool) must equal an in-process read."""
    loc, labels = tar_dir
    worker = (
        "import sys, numpy as np\n"
        "from keystone_tpu.loaders.streaming import StreamingImageNetLoader\n"
        "arrs = [a for _, _, a in StreamingImageNetLoader(\n"
        "    sys.argv[1], sys.argv[2], decode_size=24, shard_index=0,\n"
        "    num_shards=1).items()]\n"
        "np.save(sys.argv[3], np.stack(arrs))\n"
    )
    out = os.path.join(loc, "cold.npy")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-c", worker, loc, labels, out],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    cold = np.load(out)
    warm = np.stack([
        a
        for _, _, a in StreamingImageNetLoader(
            loc, labels, decode_size=24, shard_index=0, num_shards=1
        ).items()
    ])
    np.testing.assert_array_equal(cold, warm)
