"""parallel/mesh.py helpers — until now exercised only indirectly
through the engines. The conftest provisions 8 virtual CPU devices, so
1-device, 1-D, 2-D, and dcn-prefixed meshes are all constructible."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec

from keystone_tpu.parallel import mesh as mesh_lib


@pytest.fixture
def devices():
    return jax.devices()


def test_make_mesh_shapes(devices):
    m = mesh_lib.make_mesh()
    assert m.shape[mesh_lib.DATA_AXIS] == len(devices)
    assert m.shape[mesh_lib.MODEL_AXIS] == 1

    m2 = mesh_lib.make_mesh(n_model=4)
    assert m2.shape[mesh_lib.DATA_AXIS] == len(devices) // 4
    assert m2.shape[mesh_lib.MODEL_AXIS] == 4

    m3 = mesh_lib.make_mesh(n_data=1, n_model=1, devices=devices[:1])
    assert m3.devices.size == 1


def test_make_mesh_rejects_mismatched_factorization(devices):
    with pytest.raises(ValueError, match="devices"):
        mesh_lib.make_mesh(n_data=3, n_model=2, devices=devices[:8])


@pytest.mark.needs_mesh8
def test_data_sharding_on_2d_mesh(devices):
    m = mesh_lib.make_mesh(n_data=2, n_model=4)
    s = mesh_lib.data_sharding(m, ndim=3)
    assert s.mesh is m
    # leading (example) axis over data, the rest replicated — model
    # axis untouched, which is what lets batch sharding compose with
    # param sharding on the same mesh
    assert s.spec == PartitionSpec(mesh_lib.DATA_AXIS, None, None)
    assert mesh_lib.n_data_shards(m) == 2


def test_data_sharding_on_1_device_mesh(devices):
    m = mesh_lib.make_mesh(n_data=1, n_model=1, devices=devices[:1])
    assert mesh_lib.n_data_shards(m) == 1
    s = mesh_lib.data_sharding(m, ndim=2)
    assert s.spec == PartitionSpec(mesh_lib.DATA_AXIS, None)
    # placement through a 1-device sharding is a plain put
    arr = jax.device_put(np.ones((4, 2), np.float32), s)
    assert np.asarray(arr).sum() == 8.0


def test_replicated_sharding_spec(devices):
    m = mesh_lib.make_mesh(n_model=2)
    s = mesh_lib.replicated_sharding(m)
    assert s.spec == PartitionSpec()
    assert s.mesh is m


@pytest.mark.needs_mesh8
def test_dcn_axis_spans_data_shards(devices):
    arr = np.array(devices[:8]).reshape(2, 2, 2)
    m = Mesh(arr, ("dcn", mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS))
    # DP spans slices: examples shard over (dcn, data) = 4 ways
    assert mesh_lib.n_data_shards(m) == 4
    s = mesh_lib.data_sharding(m, ndim=2)
    assert s.spec == PartitionSpec(("dcn", mesh_lib.DATA_AXIS), None)


def test_use_mesh_nesting_restores(devices):
    outer = mesh_lib.make_mesh(n_model=1)
    inner = mesh_lib.make_mesh(n_model=2)
    with mesh_lib.use_mesh(outer):
        assert mesh_lib.current_mesh() is outer
        with mesh_lib.use_mesh(inner):
            assert mesh_lib.current_mesh() is inner
        assert mesh_lib.current_mesh() is outer
    # the conftest reset leaves no mesh pinned; the default is built
    # lazily over all devices
    mesh_lib.set_mesh(None)
    assert mesh_lib.current_mesh().devices.size == len(devices)


def test_use_mesh_restores_on_exception(devices):
    pinned = mesh_lib.make_mesh(n_model=1)
    mesh_lib.set_mesh(pinned)
    inner = mesh_lib.make_mesh(n_model=2)
    with pytest.raises(RuntimeError, match="boom"):
        with mesh_lib.use_mesh(inner):
            raise RuntimeError("boom")
    assert mesh_lib.current_mesh() is pinned
