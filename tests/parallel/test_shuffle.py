"""Device-side all_to_all shuffle/repartition (parallel/shuffle.py) on the
virtual 8-device mesh."""

import jax
import pytest as _pytest

if len(jax.devices()) < 8:  # real-hardware sweep on fewer chips
    pytestmark = _pytest.mark.skip(
        reason="needs the 8-device (virtual) mesh"
    )


import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.shuffle import (
    all_to_all_repartition,
    device_shuffle,
    repartition_by_key,
)


def _mesh8():
    return mesh_lib.make_mesh(n_data=8, n_model=1)


def test_repartition_by_key_groups_classes():
    mesh = _mesh8()
    with mesh_lib.use_mesh(mesh):
        rng = np.random.default_rng(0)
        n, d = 128, 5
        keys = rng.integers(0, 8, n).astype(np.int32)
        x = rng.standard_normal((n, d)).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x), mesh_lib.data_sharding(mesh))
        ks = jax.device_put(jnp.asarray(keys), mesh_lib.data_sharding(mesh, 1))

        cap = 32  # >= max rows any one shard sends to one destination
        (out,), valid, over = repartition_by_key((xs,), ks, cap, mesh)
        assert int(over) == 0
        out_h = np.asarray(out).reshape(8, -1, d)  # per-dest-shard blocks
        valid_h = np.asarray(valid).reshape(8, -1).astype(bool)
        # every valid row on shard j has key % 8 == j, and all rows arrive
        got = []
        for j in range(8):
            rows = out_h[j][valid_h[j]]
            for r in rows:
                src = np.where((x == r).all(axis=1))[0]
                assert len(src) == 1 and keys[src[0]] % 8 == j
                got.append(src[0])
        assert sorted(got) == list(range(n))


def test_repartition_overflow_is_counted_not_silent():
    mesh = _mesh8()
    with mesh_lib.use_mesh(mesh):
        n = 64
        x = jnp.arange(n, dtype=jnp.float32)[:, None]
        keys = jnp.zeros((n,), jnp.int32)  # everything to shard 0
        xs = jax.device_put(x, mesh_lib.data_sharding(mesh))
        ks = jax.device_put(keys, mesh_lib.data_sharding(mesh, 1))
        (out,), valid, over = repartition_by_key((xs,), ks, 2, mesh)
        # 8 rows/shard all headed to dest 0 with capacity 2 -> 6 dropped
        # per source shard
        assert int(over) == 8 * (8 - 2)
        assert int(jnp.sum(valid)) == 8 * 2


def test_repartition_discards_negative_keys():
    mesh = _mesh8()
    with mesh_lib.use_mesh(mesh):
        n = 32
        x = jnp.arange(n, dtype=jnp.float32)[:, None]
        keys = jnp.where(jnp.arange(n) % 2 == 0, jnp.arange(n) % 8, -1)
        xs = jax.device_put(x, mesh_lib.data_sharding(mesh))
        ks = jax.device_put(
            keys.astype(jnp.int32), mesh_lib.data_sharding(mesh, 1)
        )
        (out,), valid, over = repartition_by_key((xs,), ks, 8, mesh)
        assert int(over) == 0
        assert int(jnp.sum(valid)) == n // 2


def test_device_shuffle_matches_host_permutation():
    mesh = _mesh8()
    with mesh_lib.use_mesh(mesh):
        rng = np.random.default_rng(3)
        n, n_pad, d = 50, 64, 4
        x = np.zeros((n_pad, d), np.float32)
        x[:n] = rng.standard_normal((n, d))
        xs = jax.device_put(jnp.asarray(x), mesh_lib.data_sharding(mesh))

        out = np.asarray(device_shuffle(xs, n, seed=11, mesh=mesh))
        perm = np.random.default_rng(11).permutation(n)
        np.testing.assert_array_equal(out[:n], x[:n][perm])
        np.testing.assert_array_equal(out[n:], 0.0)


def test_all_to_all_repartition_multi_payload():
    mesh = _mesh8()
    with mesh_lib.use_mesh(mesh):
        n = 64
        x = jnp.arange(n, dtype=jnp.float32)[:, None]
        tag = jnp.arange(n, dtype=jnp.int32)
        dest = (jnp.arange(n) % 8).astype(jnp.int32)
        sh = mesh_lib.data_sharding
        (xo, to), valid, over = all_to_all_repartition(
            (jax.device_put(x, sh(mesh)), jax.device_put(tag, sh(mesh, 1))),
            jax.device_put(dest, sh(mesh, 1)),
            capacity=8, mesh=mesh,
        )
        assert int(over) == 0
        v = np.asarray(valid).astype(bool)
        # payload leaves stay row-aligned through the exchange
        np.testing.assert_array_equal(
            np.asarray(xo)[v][:, 0].astype(np.int32), np.asarray(to)[v]
        )
