"""Device-side all_to_all shuffle/repartition (parallel/shuffle.py).

Mesh size adapts to the available devices (8 on the virtual CPU mesh,
1 on the real-hardware single-chip sweep) so the collective path is
exercised everywhere, not only where 8 devices exist.
"""

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.shuffle import (
    all_to_all_repartition,
    device_shuffle,
    repartition_by_key,
)

P = min(8, len(jax.devices()))


def _mesh():
    return mesh_lib.make_mesh(n_data=P, n_model=1)


def test_repartition_by_key_groups_classes():
    mesh = _mesh()
    with mesh_lib.use_mesh(mesh):
        rng = np.random.default_rng(0)
        n, d = 128, 5
        keys = rng.integers(0, P, n).astype(np.int32)
        x = rng.standard_normal((n, d)).astype(np.float32)
        xs = jax.device_put(jnp.asarray(x), mesh_lib.data_sharding(mesh))
        ks = jax.device_put(jnp.asarray(keys), mesh_lib.data_sharding(mesh, 1))

        cap = n  # >= max rows any one shard sends to one destination
        (out,), valid, over = repartition_by_key((xs,), ks, cap, mesh)
        assert int(over) == 0
        out_h = np.asarray(out).reshape(P, -1, d)  # per-dest-shard blocks
        valid_h = np.asarray(valid).reshape(P, -1).astype(bool)
        # every valid row on shard j has key % P == j, and all rows arrive
        got = []
        for j in range(P):
            rows = out_h[j][valid_h[j]]
            for r in rows:
                src = np.where((x == r).all(axis=1))[0]
                assert len(src) == 1 and keys[src[0]] % P == j
                got.append(src[0])
        assert sorted(got) == list(range(n))


def test_repartition_overflow_is_counted_not_silent():
    mesh = _mesh()
    with mesh_lib.use_mesh(mesh):
        n = 64
        x = jnp.arange(n, dtype=jnp.float32)[:, None]
        keys = jnp.zeros((n,), jnp.int32)  # everything to shard 0
        xs = jax.device_put(x, mesh_lib.data_sharding(mesh))
        ks = jax.device_put(keys, mesh_lib.data_sharding(mesh, 1))
        (out,), valid, over = repartition_by_key((xs,), ks, 2, mesh)
        # n/P rows/shard all headed to dest 0 with capacity 2
        assert int(over) == P * (n // P - 2)
        assert int(jnp.sum(valid)) == P * 2


def test_repartition_discards_negative_keys():
    mesh = _mesh()
    with mesh_lib.use_mesh(mesh):
        n = 32
        x = jnp.arange(n, dtype=jnp.float32)[:, None]
        keys = jnp.where(jnp.arange(n) % 2 == 0, jnp.arange(n) % P, -1)
        xs = jax.device_put(x, mesh_lib.data_sharding(mesh))
        ks = jax.device_put(
            keys.astype(jnp.int32), mesh_lib.data_sharding(mesh, 1)
        )
        (out,), valid, over = repartition_by_key((xs,), ks, n, mesh)
        assert int(over) == 0
        assert int(jnp.sum(valid)) == n // 2


def test_device_shuffle_matches_host_permutation():
    mesh = _mesh()
    with mesh_lib.use_mesh(mesh):
        rng = np.random.default_rng(3)
        n, n_pad, d = 50, 64, 4
        x = np.zeros((n_pad, d), np.float32)
        x[:n] = rng.standard_normal((n, d))
        xs = jax.device_put(jnp.asarray(x), mesh_lib.data_sharding(mesh))

        out = np.asarray(device_shuffle(xs, n, seed=11, mesh=mesh))
        perm = np.random.default_rng(11).permutation(n)
        np.testing.assert_array_equal(out[:n], x[:n][perm])
        np.testing.assert_array_equal(out[n:], 0.0)


def test_all_to_all_repartition_multi_payload():
    mesh = _mesh()
    with mesh_lib.use_mesh(mesh):
        n = 64
        x = jnp.arange(n, dtype=jnp.float32)[:, None]
        tag = jnp.arange(n, dtype=jnp.int32)
        dest = (jnp.arange(n) % P).astype(jnp.int32)
        sh = mesh_lib.data_sharding
        (xo, to), valid, over = all_to_all_repartition(
            (jax.device_put(x, sh(mesh)), jax.device_put(tag, sh(mesh, 1))),
            jax.device_put(dest, sh(mesh, 1)),
            capacity=n // P, mesh=mesh,
        )
        assert int(over) == 0
        v = np.asarray(valid).astype(bool)
        # payload leaves stay row-aligned through the exchange
        np.testing.assert_array_equal(
            np.asarray(xo)[v][:, 0].astype(np.int32), np.asarray(to)[v]
        )
