"""Host-resident feature blocks — out-of-aggregate-HBM training.

Reference capability: the block solvers train from features cached in
CLUSTER RAM, streamed block-by-block (BlockLinearMapper.scala:50-73
iterates per-block feature RDDs; AutoCacheRule.scala:559-602 budgets
75% of cluster memory for the cache). The TPU-native equivalent is
``Dataset.from_host_blocks``: X lives in host RAM as contiguous column
blocks, and ``BlockLeastSquaresEstimator`` double-buffers each slab's
async ``device_put`` against the previous block's Gram/solve/update —
HBM holds two slabs + the residual regardless of D.

Contracts covered: host fit == in-HBM fit (single and multi sweep,
padded rows, mesh-sharded rows), determinism (two host fits bitwise
equal), blockwise apply == dense apply, checkpoint resume, and the
dataset-mode plumbing.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.ops.learning import BlockLeastSquaresEstimator
from keystone_tpu.parallel.dataset import Dataset


def _problem(n=96, d=48, k=3, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(dtype)
    Y = (
        X.astype(np.float32) @ rng.standard_normal((d, k))
        + 0.3 * rng.standard_normal((n, k))
    ).astype(np.float32)
    return X, Y


def test_host_blocks_dataset_modes():
    X, _ = _problem()
    ds = Dataset.from_host_array(X, block_size=16)
    assert ds.is_host and not ds.is_array
    assert ds.n == 96 and ds.padded_n == 96
    assert ds.block_widths == [16, 16, 16]
    # uneven tail block
    ds2 = Dataset.from_host_array(X, block_size=20)
    assert ds2.block_widths == [20, 20, 8]
    # materialization round-trip (small-data escape hatch)
    np.testing.assert_array_equal(np.asarray(ds.to_array_mode().array()), X)
    with pytest.raises(ValueError):
        Dataset.from_host_blocks([])
    with pytest.raises(ValueError):
        Dataset.from_host_blocks([X[:10], X[:20]])


@pytest.mark.parametrize("num_iter", [1, 2])
def test_host_fit_matches_in_hbm_fit(num_iter):
    """The host-streamed fit and the device-resident fit run the same
    block algebra; results agree to f32 reduction-order tolerance (the
    two paths' programs have different operand shapes, so XLA may tile
    reductions differently — bitwise equality is pinned separately)."""
    X, Y = _problem()
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=num_iter,
                                     lam=0.1)
    dev = est.fit(Dataset.from_array(jnp.asarray(X)),
                  Dataset.from_array(jnp.asarray(Y)))
    host = est.fit(Dataset.from_host_array(X, block_size=16),
                   Dataset.from_array(jnp.asarray(Y)))
    np.testing.assert_allclose(
        np.asarray(host.W), np.asarray(dev.W), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(host.feature_mean), np.asarray(dev.feature_mean),
        rtol=1e-6, atol=1e-7,
    )
    np.testing.assert_allclose(
        np.asarray(host.label_mean), np.asarray(dev.label_mean),
        rtol=1e-6,
    )


def test_host_fit_is_deterministic():
    X, Y = _problem(seed=1)
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=0.05)
    Yd = Dataset.from_array(jnp.asarray(Y))
    W1 = np.asarray(est.fit(Dataset.from_host_array(X, 16), Yd).W)
    W2 = np.asarray(est.fit(Dataset.from_host_array(X, 16), Yd).W)
    np.testing.assert_array_equal(W1, W2)


def test_host_fit_bf16_features():
    """bf16 host blocks (the HBM-scale dtype) flow through the same
    centered-Gram algebra the in-HBM bf16 path uses."""
    import ml_dtypes

    X, Y = _problem(d=32, dtype=np.float32)
    Xb = X.astype(ml_dtypes.bfloat16)
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.1)
    dev = est.fit(
        Dataset.from_array(jnp.asarray(Xb)),
        Dataset.from_array(jnp.asarray(Y)),
    )
    host = est.fit(
        Dataset.from_host_array(Xb, block_size=16),
        Dataset.from_array(jnp.asarray(Y)),
    )
    np.testing.assert_allclose(
        np.asarray(host.W), np.asarray(dev.W), rtol=2e-3, atol=2e-4
    )


def test_host_fit_padded_rows():
    """Zero pad rows past n contribute nothing (mask discipline), same
    as the in-HBM path."""
    X, Y = _problem(n=90)
    pad = 6
    Xp = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)])
    Yp = np.concatenate([Y, np.zeros((pad, Y.shape[1]), Y.dtype)])
    est = BlockLeastSquaresEstimator(block_size=24, num_iter=1, lam=0.1)
    unpadded = est.fit(
        Dataset.from_host_array(X, 24),
        Dataset.from_array(jnp.asarray(Y)),
    )
    padded = est.fit(
        Dataset.from_host_blocks(
            [Xp[:, s : s + 24] for s in range(0, X.shape[1], 24)], n=90
        ),
        Dataset.from_array(jnp.asarray(Yp), n=90),
    )
    np.testing.assert_allclose(
        np.asarray(padded.W), np.asarray(unpadded.W), rtol=2e-4,
        atol=2e-5,
    )


@pytest.mark.needs_mesh8
def test_host_fit_sharded_rows(mesh8):
    """With an active mesh and row count divisible by the data-shard
    count, slabs are placed over the data axis (the multichip layout)
    and the fit still matches the single-placement result."""
    X, Y = _problem(n=96)  # 96 % 8 == 0
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.1)
    host = est.fit(Dataset.from_host_array(X, 16),
                   Dataset.from_array(jnp.asarray(Y)))
    dev = est.fit(
        Dataset.from_array(jnp.asarray(X)).shard(mesh8),
        Dataset.from_array(jnp.asarray(Y)).shard(mesh8),
    )
    np.testing.assert_allclose(
        np.asarray(host.W), np.asarray(dev.W), rtol=2e-4, atol=2e-5
    )


def test_host_apply_matches_dense_apply():
    X, Y = _problem()
    est = BlockLeastSquaresEstimator(block_size=16, num_iter=1, lam=0.1)
    model = est.fit(Dataset.from_host_array(X, 16),
                    Dataset.from_array(jnp.asarray(Y)))
    dense = np.asarray(
        model.apply_batch(Dataset.from_array(jnp.asarray(X))).array()
    )
    blockwise = np.asarray(
        model.apply_batch(Dataset.from_host_array(X, 16)).array()
    )
    np.testing.assert_allclose(blockwise, dense, rtol=2e-5, atol=2e-5)
    # width mismatch is an error, not a wrong answer
    with pytest.raises(ValueError):
        model.apply_batch(Dataset.from_host_array(X[:, :32], 16))


class _Interrupt(RuntimeError):
    pass


def _fail_after(k):
    def cb(done):
        if done >= k:
            raise _Interrupt(f"injected failure after {k} blocks")

    return cb


def test_host_fit_resume_matches_uninterrupted(tmp_path):
    X, Y = _problem()
    Xh = Dataset.from_host_array(X, 16)
    Yd = Dataset.from_array(jnp.asarray(Y))
    base = BlockLeastSquaresEstimator(block_size=16, num_iter=2, lam=0.1)
    W_ref = np.asarray(base.fit(Xh, Yd).W)

    p = str(tmp_path / "bls_host.npz")
    est = dataclasses.replace(
        base, checkpoint_path=p, checkpoint_every=2,
        block_callback=_fail_after(4),
    )
    with pytest.raises(_Interrupt):
        est.fit(Xh, Yd)
    resumed = dataclasses.replace(base, checkpoint_path=p,
                                  checkpoint_every=2)
    W_res = np.asarray(resumed.fit(Xh, Yd).W)
    np.testing.assert_allclose(W_res, W_ref, rtol=2e-4, atol=2e-5)


def test_weighted_host_fit_matches_in_hbm_pcg():
    """The flagship solver's host-blocks path: streamed-slab PCG must
    match the device-resident pcg fit (same block layout)."""
    from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator

    rng = np.random.default_rng(11)
    n, d, C = 192, 64, 4
    centers = rng.standard_normal((C, d)).astype(np.float32) * 2
    yc = rng.integers(0, C, n)
    X = (centers[yc] + rng.standard_normal((n, d))).astype(np.float32)
    Y = (2.0 * np.eye(C, dtype=np.float32)[yc] - 1.0)
    Yd = Dataset.from_array(jnp.asarray(Y))
    kw = dict(block_size=32, num_iter=2, lam=0.01, mixture_weight=0.5,
              solve="pcg")
    dev = BlockWeightedLeastSquaresEstimator(**kw).fit(
        Dataset.from_array(jnp.asarray(X)), Yd
    )
    host = BlockWeightedLeastSquaresEstimator(**kw).fit(
        Dataset.from_host_array(X, block_size=32), Yd
    )
    np.testing.assert_allclose(
        np.asarray(host.W), np.asarray(dev.W), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(host.intercept), np.asarray(dev.intercept),
        rtol=2e-4, atol=2e-5,
    )
    # and the model actually classifies
    pred = np.asarray(
        host.apply_batch(Dataset.from_array(jnp.asarray(X))).array()
    )
    assert (pred.argmax(1) == yc).mean() > 0.95


def test_weighted_host_fit_rejects_chol():
    from keystone_tpu.ops.learning import BlockWeightedLeastSquaresEstimator

    X = np.zeros((8, 8), np.float32)
    Y = np.ones((8, 2), np.float32)
    with pytest.raises(ValueError, match="pcg"):
        BlockWeightedLeastSquaresEstimator(
            block_size=4, num_iter=1, lam=0.1, mixture_weight=0.5,
            solve="chol",
        ).fit(
            Dataset.from_host_array(X, 4),
            Dataset.from_array(jnp.asarray(Y)),
        )
