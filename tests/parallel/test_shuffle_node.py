"""Shuffler node device path agrees bit-for-bit with the host path."""

import jax
import pytest as _pytest

if len(jax.devices()) < 8:  # real-hardware sweep on fewer chips
    pytestmark = _pytest.mark.skip(
        reason="needs the 8-device (virtual) mesh"
    )


import jax.numpy as jnp
import numpy as np

from keystone_tpu.ops.util.nodes import Shuffler
from keystone_tpu.parallel import mesh as mesh_lib
from keystone_tpu.parallel.dataset import Dataset


def test_shuffler_device_matches_host():
    mesh = mesh_lib.make_mesh(n_data=8, n_model=1)
    with mesh_lib.use_mesh(mesh):
        rng = np.random.default_rng(7)
        x = rng.standard_normal((64, 6)).astype(np.float32)
        ds = Dataset.from_array(
            jax.device_put(jnp.asarray(x), mesh_lib.data_sharding(mesh))
        )
        host = Shuffler(seed=5).apply_batch(ds)
        dev = Shuffler(seed=5, device=True).apply_batch(ds)
        np.testing.assert_array_equal(
            np.asarray(dev.padded())[: dev.n], np.asarray(host.padded())
        )
