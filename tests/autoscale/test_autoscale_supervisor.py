"""Supervisor unit tests over a fake launcher: convergence, the
deregister -> drain -> wait retirement order, and dead-replica
replacement — no processes, no sockets (the subprocess path is
bin/smoke-autoscale.sh's)."""

import threading
import time
from typing import List

from keystone_tpu.autoscale.supervisor import Supervisor


class FakeHandle:
    def __init__(self, index):
        self.index = index
        self.name = f"replica-{index}"
        self.pid = 1000 + index
        self.url = f"http://127.0.0.1:{9000 + index}"
        self._alive = True
        self.calls: List[str] = []

    def wait_listening(self, timeout_s):
        self.calls.append("wait_listening")
        return self.url

    def alive(self):
        return self._alive

    def drain(self):
        self.calls.append("drain")
        self._alive = False

    def kill(self):
        self.calls.append("kill")
        self._alive = False

    def wait(self, timeout_s):
        self.calls.append("wait")
        return True

    def status(self):
        return {"name": self.name, "url": self.url, "alive": self._alive}


class FakeLauncher:
    self_registering = True  # keep HTTP out of the unit tests

    def __init__(self):
        self.launched: List[FakeHandle] = []

    def launch(self, index):
        handle = FakeHandle(index)
        self.launched.append(handle)
        return handle


class RecordingSupervisor(Supervisor):
    """Records deregistration calls instead of dialing a router."""

    def __init__(self, launcher, **kw):
        super().__init__(launcher, "http://router:1", **kw)
        self.deregistered: List[str] = []
        self._launcher_ref = launcher

    def _deregister(self, url):
        # intercept the HTTP half; the ordering stays observable on
        # the handle's call log
        if url:
            self.deregistered.append(url)
            for h in self._launcher_ref.launched:
                if h.url == url:
                    h.calls.append("deregister")


def wait_until(pred, timeout_s=5.0):
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def make(launcher=None, **kw):
    launcher = launcher or FakeLauncher()
    return launcher, RecordingSupervisor(launcher, **kw)


def test_scale_to_grows_and_shrinks():
    launcher, sup = make()
    sup.scale_to(3)
    assert sup.target == 3
    # concurrent launches: membership is exact, append ORDER is not
    assert sorted(h.index for h in sup.replicas()) == [0, 1, 2]
    sup.scale_to(1)
    assert sup.target == 1
    assert len(sup.replicas()) == 1
    # the retired replicas drained on background threads
    survivors = set(sup.replicas())
    retired = [h for h in launcher.launched if h not in survivors]
    assert len(retired) == 2
    assert wait_until(
        lambda: all("drain" in h.calls for h in retired)
    )


def test_retirement_order_is_deregister_then_drain():
    """No new forwards may land on a draining replica: the roster
    removal must happen BEFORE the drain starts."""
    launcher, sup = make()
    sup.scale_to(2)
    sup.scale_to(1)
    survivors = set(sup.replicas())
    retired = next(
        h for h in launcher.launched if h not in survivors
    )
    assert wait_until(lambda: "drain" in retired.calls)
    assert retired.calls.index("deregister") < retired.calls.index("drain")
    assert sup.deregistered == [retired.url]


def test_reap_replaces_dead_replicas_and_counts():
    launcher, sup = make()
    sup.scale_to(2)
    launcher.launched[0]._alive = False  # kill -9
    assert sup.reap() == 1
    assert sup.replaced_total == 1
    # the dead one is gone from the roster, a replacement launched,
    # and the stale URL was deregistered
    assert len(sup.replicas()) == 2
    assert launcher.launched[0] not in sup.replicas()
    assert launcher.launched[0].url in sup.deregistered
    assert len(launcher.launched) == 3


def test_reap_without_deaths_is_a_noop():
    launcher, sup = make()
    sup.scale_to(2)
    assert sup.reap() == 0
    assert len(launcher.launched) == 2


def test_stop_retires_everything_and_refuses_further_work():
    launcher, sup = make()
    sup.scale_to(2)
    sup.stop()
    assert sup.target == 0
    assert sup.replicas() == []
    assert all("drain" in h.calls for h in launcher.launched)
    sup.scale_to(3)  # must be refused, not half-honored
    assert sup.replicas() == []
    assert len(launcher.launched) == 2
    assert sup.reap() == 0


def test_reap_counts_only_replacements_that_came_up():
    """A death whose replacement failed to start is NOT healed: the
    replaced count (and the exported counter fed from it) must say
    so, while the death itself stays visible as its event."""

    class DiesThenFails(FakeLauncher):
        def launch(self, index):
            handle = super().launch(index)
            if index > 0:  # every replacement fails the handshake
                handle.wait_listening = lambda timeout_s: None
            return handle

    events = []
    launcher = DiesThenFails()
    sup = RecordingSupervisor(
        launcher, startup_timeout_s=0.1, on_event=events.append
    )
    sup.scale_to(1)
    launcher.launched[0]._alive = False
    assert sup.reap() == 0
    assert sup.replaced_total == 0
    names = [e["event"] for e in events]
    assert "replica_died" in names
    replaced_ev = next(
        e for e in events if e["event"] == "replicas_replaced"
    )
    assert replaced_ev == {
        "event": "replicas_replaced", "died": 1, "replaced": 0,
    }


def test_failed_launch_is_killed_and_not_rostered():
    class NeverBinds(FakeLauncher):
        def launch(self, index):
            handle = super().launch(index)
            handle.wait_listening = lambda timeout_s: None
            return handle

    launcher, sup = make(NeverBinds(), startup_timeout_s=0.1)
    sup.scale_to(1)
    assert sup.replicas() == []
    assert "kill" in launcher.launched[0].calls


def test_events_emitted_for_lifecycle():
    events = []
    launcher = FakeLauncher()
    sup = RecordingSupervisor(launcher, on_event=events.append)
    sup.scale_to(1)
    launcher.launched[0]._alive = False
    sup.reap()
    names = [e["event"] for e in events]
    assert "replica_started" in names
    assert "replica_died" in names
    assert "replicas_replaced" in names


def test_status_snapshot():
    launcher, sup = make()
    sup.scale_to(2)
    doc = sup.status()
    assert doc["target"] == 2 and doc["running"] == 2
    assert len(doc["replicas"]) == 2


def test_concurrent_scale_and_reap_hold_the_target():
    """The control loop's reap and a scale_to racing must never
    overshoot the target or lose a handle."""
    launcher, sup = make()
    sup.scale_to(2)

    def churn():
        for _ in range(20):
            launcher.launched[-1]._alive = False
            sup.reap()

    t = threading.Thread(target=churn)
    t.start()
    for _ in range(10):
        sup.scale_to(2)
    t.join()
    sup.reap()
    live = [h for h in sup.replicas() if h.alive()]
    assert len(sup.replicas()) == 2, sup.status()
    assert len(live) == 2
