"""Capacity-planner unit tests: the curve fit, the artifact shape,
and the plan -> ``PolicyConfig`` round trip (the measured-not-guessed
path). The full replay grid is exercised by the CLI against a live
fleet; here the arithmetic is pinned."""

import json

import pytest

from keystone_tpu.autoscale.planner import (
    build_artifact,
    derive_policy,
    fit_capacity,
)
from keystone_tpu.autoscale.policy import PolicyConfig


def test_fit_capacity_least_squares_through_origin():
    # a perfectly linear fleet: capacity(k) = 50k
    assert fit_capacity({1: 50.0, 2: 100.0, 3: 150.0}) == pytest.approx(50.0)
    # sub-linear scaling pulls the slope down, never up
    slope = fit_capacity({1: 50.0, 2: 80.0})
    assert slope < 50.0
    # zero-capacity cells (never held the SLO) don't drag the fit
    assert fit_capacity({1: 50.0, 2: 0.0}) == pytest.approx(50.0)
    assert fit_capacity({1: 0.0}) is None
    assert fit_capacity({}) is None


def test_derive_policy_fields():
    policy = derive_policy(42.0, 0.25, target_utilization=0.6)
    assert policy == {
        "slo_latency_s": 0.25,
        "target_utilization": 0.6,
        "per_replica_rps": 42.0,
    }
    assert "per_replica_rps" not in derive_policy(None, 0.25)


def _rows():
    return [
        {"replicas": 1, "speed": 1.0, "offered_rps": 20.0,
         "p99_ms": 30.0, "shed_rate": 0.0, "lost": 0, "errors": 0,
         "slo_held": True},
        {"replicas": 1, "speed": 2.0, "offered_rps": 40.0,
         "p99_ms": 900.0, "shed_rate": 0.2, "lost": 0, "errors": 0,
         "slo_held": False},
        {"replicas": 2, "speed": 2.0, "offered_rps": 40.0,
         "p99_ms": 35.0, "shed_rate": 0.0, "lost": 0, "errors": 0,
         "slo_held": True},
    ]


def test_build_artifact_capacity_is_best_held_cell():
    artifact = build_artifact(_rows(), 0.25, 0.99)
    assert artifact["capacity_rps_by_replicas"] == {
        "1": 20.0, "2": 40.0,
    }
    assert artifact["fit"]["per_replica_rps"] == pytest.approx(20.0)
    assert artifact["policy"]["per_replica_rps"] == pytest.approx(20.0)
    assert artifact["slo"]["latency_s"] == 0.25


def test_artifact_round_trips_into_policy_config(tmp_path):
    artifact = build_artifact(_rows(), 0.25, 0.99)
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(artifact))
    cfg = PolicyConfig.from_plan(str(path), max_replicas=6)
    assert cfg.per_replica_rps == pytest.approx(20.0)
    assert cfg.slo_latency_s == 0.25
    assert cfg.target_utilization == 0.7
    assert cfg.max_replicas == 6  # explicit overrides win


def test_from_plan_rejects_junk():
    with pytest.raises(ValueError, match="dict artifact"):
        PolicyConfig.from_plan([1, 2])
    with pytest.raises(ValueError, match="unknown policy fields"):
        PolicyConfig.from_plan({"policy": {"warp_factor": 9}})


def test_from_plan_overrides_win_over_derived():
    plan = {
        "slo": {"latency_s": 0.5},
        "fit": {"per_replica_rps": 10.0},
        "policy": {"target_utilization": 0.9},
    }
    cfg = PolicyConfig.from_plan(
        plan, slo_latency_s=0.2, per_replica_rps=33.0
    )
    assert cfg.slo_latency_s == 0.2
    assert cfg.per_replica_rps == 33.0
    assert cfg.target_utilization == 0.9
