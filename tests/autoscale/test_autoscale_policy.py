"""PolicyEngine unit tests: the hysteresis/cooldown/veto arithmetic
on synthetic observations — the edge cases a live fleet would need
hours of flapping load to reproduce."""

import pytest

from keystone_tpu.autoscale.policy import (
    FleetObservation,
    PolicyConfig,
    PolicyEngine,
    phase_shares,
)


def cfg(**kw):
    base = dict(
        min_replicas=1,
        max_replicas=4,
        slo_latency_s=0.1,
        up_burn=1.5,
        down_burn=0.5,
        up_consecutive=2,
        down_consecutive=3,
        up_cooldown_s=5.0,
        down_cooldown_s=5.0,
        down_p99_headroom=0.5,
    )
    base.update(kw)
    return PolicyConfig(**base)


def obs(t, p99=None, burn=None, metrics_ok=True, **kw):
    # metrics_ok defaults True here: these are ticks whose scrape
    # SUCCEEDED (the blind-scrape case is tested explicitly)
    return FleetObservation(
        t=float(t), fleet_p99_s=p99, burn_fast=burn,
        metrics_ok=metrics_ok, **kw
    )


HOT = dict(p99=0.5)    # far over the 100ms objective
COLD = dict(p99=0.01)  # far inside the 50ms headroom band


# -- scale-up ---------------------------------------------------------------


def test_scale_up_needs_consecutive_hot_ticks():
    e = PolicyEngine(cfg())
    assert e.decide(1, obs(0, **HOT)).action == "hold"
    d = e.decide(1, obs(1, **HOT))
    assert d.action == "scale_up" and d.target == 2
    assert d.reason == "slo_pressure"


def test_burn_rate_alone_trips_hot():
    e = PolicyEngine(cfg())
    e.decide(1, obs(0, burn=2.0))
    d = e.decide(1, obs(1, burn=2.0))
    assert d.action == "scale_up" and d.reason == "burn_rate"


def test_flapping_at_the_threshold_never_oscillates():
    """Alternating hot/cold ticks forever: neither streak can reach
    its consecutive count, so the fleet must never move."""
    e = PolicyEngine(cfg())
    for i in range(40):
        d = e.decide(2, obs(i, **(HOT if i % 2 == 0 else COLD)))
        assert d.action == "hold", (i, d)


def test_in_band_ticks_reset_both_streaks():
    e = PolicyEngine(cfg())
    e.decide(1, obs(0, **HOT))
    # 60ms: over the 50ms cold headroom, under the 100ms objective —
    # the dead band
    assert e.decide(1, obs(1, p99=0.06)).reason == "in_band"
    # the earlier hot tick must not still count
    assert e.decide(1, obs(2, **HOT)).action == "hold"


def test_up_cooldown_blocks_back_to_back_scale_ups():
    e = PolicyEngine(cfg())
    e.decide(1, obs(0, **HOT))
    assert e.decide(1, obs(1, **HOT)).action == "scale_up"
    e.decide(2, obs(2, **HOT))
    d = e.decide(2, obs(3, **HOT))
    assert d.action == "hold" and d.reason == "up_cooldown"
    # cooldown elapsed: the (still-sustained) burn acts immediately
    assert e.decide(2, obs(7, **HOT)).action == "scale_up"


def test_max_replicas_bounds_scale_up():
    e = PolicyEngine(cfg(max_replicas=2))
    e.decide(2, obs(0, **HOT))
    d = e.decide(2, obs(1, **HOT))
    assert d.action == "hold" and d.reason == "at_max_replicas"


def test_device_bound_latency_vetoes_scale_up():
    """A device-dominated phase decomposition means more replicas
    cannot help — the one veto that outranks a burning SLO."""
    e = PolicyEngine(cfg())
    shares = {"device": 0.7, "queue_wait": 0.2, "deliver": 0.1}
    e.decide(1, obs(0, phase_shares=shares, **HOT))
    d = e.decide(1, obs(1, phase_shares=shares, **HOT))
    assert d.action == "hold" and d.reason == "device_bound"


def test_queue_wait_dominated_latency_scales_up():
    e = PolicyEngine(cfg())
    shares = {"device": 0.2, "queue_wait": 0.6, "coalesce": 0.2}
    e.decide(1, obs(0, phase_shares=shares, **HOT))
    assert (
        e.decide(1, obs(1, phase_shares=shares, **HOT)).action
        == "scale_up"
    )


def test_absent_phase_evidence_does_not_veto():
    e = PolicyEngine(cfg())
    e.decide(1, obs(0, **HOT))
    assert e.decide(1, obs(1, **HOT)).action == "scale_up"


def test_capacity_plan_feeds_forward_past_one_step():
    """With a fitted per-replica rate, a big load step jumps straight
    to the replica count the curve says it needs."""
    e = PolicyEngine(cfg(per_replica_rps=10.0, target_utilization=0.5))
    e.decide(1, obs(0, offered_rps=20.0, **HOT))
    d = e.decide(1, obs(1, offered_rps=20.0, **HOT))
    # ceil(20 / (0.5 * 10)) = 4 replicas, not 2
    assert d.action == "scale_up" and d.target == 4


# -- scale-down -------------------------------------------------------------


def test_scale_down_needs_longer_cold_streak():
    e = PolicyEngine(cfg())
    for i in range(2):
        assert e.decide(3, obs(i, **COLD)).action == "hold"
    d = e.decide(3, obs(2, **COLD))
    assert d.action == "scale_down" and d.target == 2
    assert d.reason == "idle"


def test_idle_fleet_with_healthy_scrape_reads_cold():
    """Scrape fine, zero traffic in the window (p99 None, burn None)
    is idle — the drain-back-to-baseline path after a load drop."""
    e = PolicyEngine(cfg())
    for i in range(2):
        e.decide(2, obs(i))
    assert e.decide(2, obs(2)).action == "scale_down"


def test_blind_scrape_never_reads_cold():
    """A FAILED /metrics scrape shows the same p99=None as an idle
    fleet — but blindness must never accumulate into shrinking a
    fleet that may be under live load."""
    e = PolicyEngine(cfg())
    for i in range(20):
        d = e.decide(3, obs(i, metrics_ok=False))
        assert d.action == "hold", (i, d)
        assert d.reason == "in_band"
    # evidence returns and says idle: the cold streak starts FRESH
    assert e.decide(3, obs(21, **COLD)).reason == "cold_streak_building"


def test_min_replicas_bounds_scale_down():
    e = PolicyEngine(cfg())
    for i in range(2):
        e.decide(1, obs(i, **COLD))
    d = e.decide(1, obs(2, **COLD))
    assert d.action == "hold" and d.reason == "at_min_replicas"


def test_down_cooldown_spaces_scale_downs():
    e = PolicyEngine(cfg())
    for i in range(2):
        e.decide(4, obs(i, **COLD))
    assert e.decide(4, obs(2, **COLD)).action == "scale_down"
    for i in range(3, 5):
        e.decide(3, obs(i, **COLD))
    d = e.decide(3, obs(5, **COLD))
    assert d.action == "hold" and d.reason == "down_cooldown"


def test_half_open_replica_bans_scale_down():
    """A benched/half-open replica means the fleet is mid-recovery:
    shrinking now shoots the survivors (the ISSUE's explicit ban)."""
    e = PolicyEngine(cfg())
    for i in range(2):
        e.decide(3, obs(i, **COLD))
    d = e.decide(3, obs(2, replicas_half_open=1, **COLD))
    assert d.action == "hold" and d.reason == "replica_recovering"
    # also banned on unhealthy
    e2 = PolicyEngine(cfg())
    for i in range(2):
        e2.decide(3, obs(i, **COLD))
    d2 = e2.decide(3, obs(2, replicas_unhealthy=1, **COLD))
    assert d2.action == "hold" and d2.reason == "replica_recovering"


def test_p99_inside_objective_but_over_headroom_is_not_cold():
    e = PolicyEngine(cfg())  # headroom band ends at 50ms
    for i in range(5):
        d = e.decide(3, obs(i, p99=0.08))
        assert d.action == "hold"
        assert d.reason == "in_band"


# -- config validation + phase math -----------------------------------------


def test_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        PolicyConfig(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        PolicyConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis"):
        PolicyConfig(up_burn=1.0, down_burn=1.0)


def test_phase_shares_aggregates_and_degrades():
    assert phase_shares([]) == {}
    assert phase_shares([{}]) == {}
    shares = phase_shares(
        [
            {"device": 2.0, "queue_wait": 6.0},
            {"device": 1.0, "queue_wait": 1.0, "deliver": None},
        ]
    )
    assert shares["device"] == pytest.approx(0.3)
    assert shares["queue_wait"] == pytest.approx(0.7)


def test_decision_as_dict_is_json_shaped():
    import json

    e = PolicyEngine(cfg())
    d = e.decide(1, obs(0, p99=0.2, phase_shares={"queue_wait": 1.0}))
    doc = json.loads(json.dumps(d.as_dict()))
    assert doc["action"] == "hold"
    assert doc["observation"]["dominant_phase"] == "queue_wait"
    assert "latency_buckets" not in doc["observation"]
