"""Controller parsing unit tests: canned router surfaces ->
``FleetObservation``, and the windowed-p99 bucket arithmetic the
control loop reacts to (the lifetime quantile would never come back
down after one overload episode)."""

import math

import pytest

from keystone_tpu.autoscale.controller import (
    fleet_latency_buckets,
    observation_from,
    windowed_p99,
)

INF = float("inf")

METRICS = """\
# TYPE keystone_gateway_request_latency_seconds histogram
keystone_gateway_request_latency_seconds_bucket{gateway="r0",le="0.01"} 80
keystone_gateway_request_latency_seconds_bucket{gateway="r0",le="0.1"} 95
keystone_gateway_request_latency_seconds_bucket{gateway="r0",le="+Inf"} 100
keystone_gateway_request_latency_seconds_bucket{gateway="r1",le="0.01"} 40
keystone_gateway_request_latency_seconds_bucket{gateway="r1",le="0.1"} 50
keystone_gateway_request_latency_seconds_bucket{gateway="r1",le="+Inf"} 50
keystone_router_requests_total{router="r",status="ok"} 140
keystone_router_requests_total{router="r",status="shed"} 10
keystone_gateway_queue_depth{gateway="r0"} 3
keystone_gateway_inflight{gateway="r0"} 2
"""

FLEETZ = {
    "counts": {"healthy": 2, "half-open": 1},
    "replicas": [
        {"ready": True, "healthy": True},
        {"ready": True, "healthy": True},
        {"ready": False, "healthy": False},
    ],
}

SLZ = {
    "slos": [
        {"name": "other:latency", "burn_rate": {"fast": 9.0, "slow": 9.0}},
        {
            "name": "autoscaler:fleet_latency",
            "burn_rate": {"fast": 2.5, "slow": 0.8},
        },
    ]
}


def test_fleet_latency_buckets_merges_label_sets():
    buckets = fleet_latency_buckets(METRICS)
    assert buckets[0.01] == 120.0
    assert buckets[0.1] == 145.0
    assert buckets[INF] == 150.0


def test_observation_from_full_surfaces():
    obs = observation_from(METRICS, SLZ, FLEETZ, [], t=10.0)
    assert obs.replicas_total == 3
    assert obs.replicas_half_open == 1
    assert obs.replicas_ready == 2
    assert obs.burn_fast == 2.5 and obs.burn_slow == 0.8
    assert obs.load_total == 5.0
    assert obs.requests_total == 150.0
    # first tick: lifetime quantile (all 150 requests)
    assert obs.fleet_p99_s == pytest.approx(0.1, abs=0.05)


def test_observation_offered_rps_from_counter_delta():
    obs = observation_from(
        METRICS, None, FLEETZ, [], t=20.0,
        prev_requests=100.0, prev_t=10.0,
    )
    assert obs.offered_rps == pytest.approx(5.0)


def test_observation_degrades_on_absent_surfaces():
    obs = observation_from(None, None, None, [], t=0.0)
    assert obs.fleet_p99_s is None
    assert obs.burn_fast is None
    assert obs.replicas_total == 0
    assert obs.phase_shares == {}
    # a failed scrape is BLIND, not idle — the policy's cold path
    # keys off this flag
    assert obs.metrics_ok is False
    assert observation_from(METRICS, None, None, [], t=0.0).metrics_ok


def test_windowed_p99_reflects_only_the_window():
    base = {0.01: 1000.0, 0.1: 1000.0, INF: 1000.0}  # 1000 fast ones
    # the window adds 10 slow ones
    curr = {0.01: 1000.0, 0.1: 1000.0, INF: 1010.0}
    p99 = windowed_p99(curr, base)
    # ALL 10 window requests sit past the largest finite bound, which
    # the quantile clamps to — the SLOWEST representable value
    assert p99 == pytest.approx(0.1)
    # the lifetime view of the same snapshot reads fast (1000 of 1010
    # under 10ms) — exactly the signal a control loop must NOT use
    assert windowed_p99(curr, None) < 0.1


def test_windowed_p99_empty_window_is_none():
    snap = {0.01: 5.0, INF: 5.0}
    assert windowed_p99(snap, dict(snap)) is None
    assert windowed_p99({}, None) is None


def test_windowed_p99_clamps_membership_churn():
    """A deregistered replica removes its counts from the federation;
    the negative delta is membership churn, not traffic."""
    base = {0.01: 200.0, INF: 220.0}
    curr = {0.01: 120.0, INF: 130.0}  # counts went DOWN
    assert windowed_p99(curr, base) is None
    # one bucket shrank (churn, clamped to 0) while the tail grew:
    # the 10 genuinely-new slow requests still read as slow
    mixed = {0.01: 120.0, INF: 230.0}
    p99 = windowed_p99(mixed, base)
    assert p99 == pytest.approx(0.01)  # +Inf mass clamps to last finite
    assert not math.isinf(p99)


def test_phase_samples_land_in_observation():
    obs = observation_from(
        None, None, None,
        [{"queue_wait": 30.0, "device": 10.0}],
        t=0.0,
    )
    assert obs.dominant_phase == "queue_wait"
    assert obs.phase_shares["queue_wait"] == pytest.approx(0.75)
