"""Native IO library tests (vs the numpy fallbacks)."""

import numpy as np
import pytest

from keystone_tpu.native import native_available, read_cifar, read_csv_f32


def test_native_csv_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.standard_normal((50, 7)).astype(np.float32)
    p = tmp_path / "data.csv"
    np.savetxt(p, arr, delimiter=",")
    got = read_csv_f32(str(p))
    expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_native_cifar_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    n, dim, c = 5, 32, 3
    labels = rng.integers(0, 10, n).astype(np.uint8)
    planes = rng.integers(0, 256, (n, c, dim, dim)).astype(np.uint8)
    records = np.concatenate(
        [labels[:, None], planes.reshape(n, -1)], axis=1
    )
    p = tmp_path / "cifar.bin"
    records.tofile(p)
    got_labels, got_images = read_cifar(str(p), c, dim)
    np.testing.assert_array_equal(got_labels, labels.astype(np.int32))
    expect = planes.transpose(0, 2, 3, 1).astype(np.float32)
    np.testing.assert_allclose(got_images, expect)


def test_native_library_built():
    # the shared library builds in this environment (g++ is baked in)
    assert native_available()
