"""Native IO library tests (vs the numpy fallbacks)."""

import numpy as np
import pytest

from keystone_tpu.native import native_available, read_cifar, read_csv_f32


def test_native_csv_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.standard_normal((50, 7)).astype(np.float32)
    p = tmp_path / "data.csv"
    np.savetxt(p, arr, delimiter=",")
    got = read_csv_f32(str(p))
    expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_native_cifar_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    n, dim, c = 5, 32, 3
    labels = rng.integers(0, 10, n).astype(np.uint8)
    planes = rng.integers(0, 256, (n, c, dim, dim)).astype(np.uint8)
    records = np.concatenate(
        [labels[:, None], planes.reshape(n, -1)], axis=1
    )
    p = tmp_path / "cifar.bin"
    records.tofile(p)
    got_labels, got_images = read_cifar(str(p), c, dim)
    np.testing.assert_array_equal(got_labels, labels.astype(np.int32))
    expect = planes.transpose(0, 2, 3, 1).astype(np.float32)
    np.testing.assert_allclose(got_images, expect)


def test_native_library_built():
    # the shared library builds in this environment (g++ is baked in)
    assert native_available()


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_csv_edge_cases_agree_with_numpy(tmp_path):
    """The C++ parser and the numpy fallback must agree on whitespace,
    scientific notation, negative zero, and trailing newlines."""
    cases = {
        "plain": "1.5,2.5\n-3.25,4e-2\n",
        "scientific": "1e10,-2.5E-3\n+0.0,-0.0\n",
        "no_trailing_newline": "9,8\n7,6",
        "blank_trailing_lines": "1,2\n3,4\n\n\n",
        "spaces_around_values": " 1.0 , 2.0 \n 3.0 , 4.0 \n",
        "single_row": "5,6,7\n",
        "single_col": "1\n2\n3\n",
    }
    for name, text in cases.items():
        p = tmp_path / f"{name}.csv"
        p.write_text(text)
        got = read_csv_f32(str(p))
        expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
        np.testing.assert_allclose(got, expect, rtol=1e-6, err_msg=name)
        assert got.shape == expect.shape, name


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_csv_ragged_falls_back(tmp_path):
    """Ragged rows must not silently mis-parse: the wrapper falls back to
    numpy, which raises its usual error."""
    p = tmp_path / "ragged.csv"
    p.write_text("1,2,3\n4,5\n")
    with pytest.raises(ValueError):
        read_csv_f32(str(p))


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_csv_large_file(tmp_path):
    """The C++ layer's reason to exist is large-file throughput (measured
    ~2x np.loadtxt warm on one core); this asserts correctness at that
    scale — wall-clock assertions are too flake-prone for CI."""
    rng = np.random.default_rng(0)
    arr = rng.standard_normal((40_000, 128)).astype(np.float32)
    p = tmp_path / "big.csv"
    np.savetxt(p, arr, delimiter=",", fmt="%.6e")

    got = read_csv_f32(str(p))
    expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_cifar_truncated_record_ignored(tmp_path):
    """A trailing partial record (torn write) is ignored, matching the
    numpy fallback's floor-division record count."""
    rng = np.random.default_rng(2)
    n, dim, c = 3, 8, 3
    rec = np.concatenate(
        [
            rng.integers(0, 10, (n, 1)).astype(np.uint8),
            rng.integers(0, 256, (n, c * dim * dim)).astype(np.uint8),
        ],
        axis=1,
    )
    p = tmp_path / "trunc.bin"
    with open(p, "wb") as f:
        f.write(rec.tobytes())
        f.write(b"\x01\x02\x03")  # partial 4th record
    labels, images = read_cifar(str(p), c, dim)
    assert labels.shape == (n,)
    assert images.shape == (n, dim, dim, c)


# -- JPEG fast path ---------------------------------------------------------


from jpeg_fixtures import jpeg_bytes as _make_jpeg_bytes  # noqa: E402


def test_jpeg_native_library_built():
    from keystone_tpu.native import jpeg_native_available

    # libjpeg + headers are baked into this image; the decoder must build
    assert jpeg_native_available()


def test_jpeg_native_matches_pil_draft_path(tmp_path):
    """native/jpeg.cc tracks the PIL draft-decode + BILINEAR-resize
    fallback within quantization tolerance (both decode the same DCT at
    draft scale and use triangle-filter resampling; PIL rounds to uint8
    after resize, the native path keeps float — so ±1 level plus a small
    mean bound, across down- and up-scaling targets)."""
    from keystone_tpu.loaders.streaming import _decode_payload
    from keystone_tpu.native import jpeg_decode_f32

    for seed, (w, h) in enumerate([(333, 251), (64, 80), (512, 384)]):
        data = _make_jpeg_bytes(w, h, seed)
        for target in (32, 96, 256):
            nat = jpeg_decode_f32(data, target)
            pil = _decode_payload((data, target), use_native=False)
            assert nat is not None and pil is not None
            assert nat.shape == pil.shape == (target, target, 3)
            d = np.abs(nat - pil)
            assert d.max() <= 2.0, (seed, target, d.max())
            assert d.mean() < 0.5, (seed, target, d.mean())


def test_jpeg_native_grayscale_expands_to_rgb():
    import io as _io

    from PIL import Image as PILImage

    from keystone_tpu.native import jpeg_decode_f32

    arr = (np.arange(64 * 64).reshape(64, 64) % 256).astype(np.uint8)
    buf = _io.BytesIO()
    PILImage.fromarray(arr, mode="L").save(buf, format="JPEG")
    out = jpeg_decode_f32(buf.getvalue(), 32)
    assert out is not None and out.shape == (32, 32, 3)
    # grayscale: all three channels identical
    np.testing.assert_array_equal(out[..., 0], out[..., 1])
    np.testing.assert_array_equal(out[..., 0], out[..., 2])


def test_jpeg_native_corrupt_returns_none_and_loader_falls_back(tmp_path):
    from keystone_tpu.native import jpeg_decode_f32

    assert jpeg_decode_f32(b"not a jpeg at all", 32) is None
    # truncated stream: header ok, body gone
    data = _make_jpeg_bytes(100, 100, 3)
    assert jpeg_decode_f32(data[: len(data) // 4], 32) is None


def test_jpeg_native_batch_matches_single():
    from keystone_tpu.native import jpeg_decode_batch_f32, jpeg_decode_f32

    blobs = [_make_jpeg_bytes(120, 90, s) for s in range(4)]
    blobs.insert(2, b"corrupt")  # one bad slot must not poison the rest
    imgs, ok = jpeg_decode_batch_f32(blobs, 48, num_threads=2)
    assert ok.tolist() == [True, True, False, True, True]
    for i, b in enumerate(blobs):
        if not ok[i]:
            continue
        np.testing.assert_array_equal(imgs[i], jpeg_decode_f32(b, 48))


def test_streaming_native_decode_matches_pil_decode(tmp_path):
    """The streaming loader's native and PIL decode paths agree within
    decode tolerance on the same tar (the pool-parity test pins the two
    POOLS to identical bytes; this pins the two DECODERS)."""
    import tarfile

    from keystone_tpu.loaders.streaming import StreamingImageLoader

    tar = tmp_path / "imgs.tar"
    with tarfile.open(tar, "w") as tf:
        for i in range(6):
            p = tmp_path / f"m_{i}.JPEG"
            p.write_bytes(_make_jpeg_bytes(90 + 7 * i, 70 + 5 * i, i))
            tf.add(str(p), arcname=f"m_{i}.JPEG")

    def mk(native):
        return list(
            StreamingImageLoader(
                [str(tar)], lambda name: 0, decode_size=64,
                use_native_decode=native,
            ).items()
        )

    nat, pil = mk(True), mk(False)
    assert len(nat) == len(pil) == 6
    for (n1, _, a1), (n2, _, a2) in zip(nat, pil):
        assert n1 == n2
        assert np.abs(a1 - a2).max() <= 2.0
