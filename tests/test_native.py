"""Native IO library tests (vs the numpy fallbacks)."""

import numpy as np
import pytest

from keystone_tpu.native import native_available, read_cifar, read_csv_f32


def test_native_csv_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.standard_normal((50, 7)).astype(np.float32)
    p = tmp_path / "data.csv"
    np.savetxt(p, arr, delimiter=",")
    got = read_csv_f32(str(p))
    expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_native_cifar_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    n, dim, c = 5, 32, 3
    labels = rng.integers(0, 10, n).astype(np.uint8)
    planes = rng.integers(0, 256, (n, c, dim, dim)).astype(np.uint8)
    records = np.concatenate(
        [labels[:, None], planes.reshape(n, -1)], axis=1
    )
    p = tmp_path / "cifar.bin"
    records.tofile(p)
    got_labels, got_images = read_cifar(str(p), c, dim)
    np.testing.assert_array_equal(got_labels, labels.astype(np.int32))
    expect = planes.transpose(0, 2, 3, 1).astype(np.float32)
    np.testing.assert_allclose(got_images, expect)


def test_native_library_built():
    # the shared library builds in this environment (g++ is baked in)
    assert native_available()


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_csv_edge_cases_agree_with_numpy(tmp_path):
    """The C++ parser and the numpy fallback must agree on whitespace,
    scientific notation, negative zero, and trailing newlines."""
    cases = {
        "plain": "1.5,2.5\n-3.25,4e-2\n",
        "scientific": "1e10,-2.5E-3\n+0.0,-0.0\n",
        "no_trailing_newline": "9,8\n7,6",
        "blank_trailing_lines": "1,2\n3,4\n\n\n",
        "spaces_around_values": " 1.0 , 2.0 \n 3.0 , 4.0 \n",
        "single_row": "5,6,7\n",
        "single_col": "1\n2\n3\n",
    }
    for name, text in cases.items():
        p = tmp_path / f"{name}.csv"
        p.write_text(text)
        got = read_csv_f32(str(p))
        expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
        np.testing.assert_allclose(got, expect, rtol=1e-6, err_msg=name)
        assert got.shape == expect.shape, name


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_csv_ragged_falls_back(tmp_path):
    """Ragged rows must not silently mis-parse: the wrapper falls back to
    numpy, which raises its usual error."""
    p = tmp_path / "ragged.csv"
    p.write_text("1,2,3\n4,5\n")
    with pytest.raises(ValueError):
        read_csv_f32(str(p))


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_csv_large_file(tmp_path):
    """The C++ layer's reason to exist is large-file throughput (measured
    ~2x np.loadtxt warm on one core); this asserts correctness at that
    scale — wall-clock assertions are too flake-prone for CI."""
    rng = np.random.default_rng(0)
    arr = rng.standard_normal((40_000, 128)).astype(np.float32)
    p = tmp_path / "big.csv"
    np.savetxt(p, arr, delimiter=",", fmt="%.6e")

    got = read_csv_f32(str(p))
    expect = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(got, expect, rtol=1e-5)


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_native_cifar_truncated_record_ignored(tmp_path):
    """A trailing partial record (torn write) is ignored, matching the
    numpy fallback's floor-division record count."""
    rng = np.random.default_rng(2)
    n, dim, c = 3, 8, 3
    rec = np.concatenate(
        [
            rng.integers(0, 10, (n, 1)).astype(np.uint8),
            rng.integers(0, 256, (n, c * dim * dim)).astype(np.uint8),
        ],
        axis=1,
    )
    p = tmp_path / "trunc.bin"
    with open(p, "wb") as f:
        f.write(rec.tobytes())
        f.write(b"\x01\x02\x03")  # partial 4th record
    labels, images = read_cifar(str(p), c, dim)
    assert labels.shape == (n,)
    assert images.shape == (n, dim, dim, c)
