#!/usr/bin/env bash
# Smoke-test the online model lifecycle end to end, both directions:
#
#  1. the `serving_online_refit` bench row — refit -> shadow -> canary
#     -> promote under open-loop in-process load with ZERO failed
#     requests and a candidate that beats the stale incumbent on
#     held-out labels, then a poisoned refit that auto-rolls back
#     within one policy tick of its shadow start (asserts re-checked
#     here off the emitted JSON);
#  2. a live `serve-gateway --refit` subprocess fed by a real
#     `serve-loadgen` run that labels a fraction of its own traffic
#     with the synthetic teacher and POSTs it to /feedback: the
#     controller must walk idle -> shadow -> canary -> promoted on
#     /lifecyclez, the loadgen invariant verdict must stay green, and
#     the keystone_lifecycle_* families must show up on /metrics;
#  3. same live gateway, `lifecycle.refit.poison` armed over /chaosz:
#     the next refit cycle's candidate must be caught by the accuracy
#     gate and auto-rolled back (reason on /lifecyclez, counted on
#     keystone_lifecycle_rollbacks_total) while the loadgen verdict
#     stays green — served traffic never notices;
#  4. the request log round-trips through the loadgen trace parser
#     (model-tagged lines included), and keystone-lint stays at 0
#     findings.
#
# CI-friendly: CPU backend, ~2-4 min, no network beyond localhost.
#
#   bin/smoke-rollout.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
SERVER_LOG="$TMPDIR/server.log"
BENCH_OUT="$TMPDIR/bench.jsonl"
REQ_LOG="$TMPDIR/requests.jsonl"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    [[ -n "${LOADGEN_PID:-}" ]] && kill "$LOADGEN_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

echo "== serving_online_refit bench row =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-bench --lifecycle-only \
    | tee "$BENCH_OUT"

python - "$BENCH_OUT" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
row = next(r for r in rows if r.get("metric") == "serving_online_refit")
assert row["failures"] == 0, row
assert row["promotions"] == 1, row
assert row["candidate_err"] < row["incumbent_err"], row
assert row["rollback_reason"] in ("accuracy", "shadow_diff"), row
assert row["rollback_ticks_after_shadow"] <= 1, row
print(
    f"row OK: promoted in {row['ticks_to_promote']} ticks under load "
    f"({row['requests']} requests, 0 failed, p99 {row['value']} "
    f"{row['unit']}), candidate {row['candidate_err']} vs stale "
    f"incumbent {row['incumbent_err']}, poison rollback "
    f"({row['rollback_reason']}) {row['rollback_ticks_after_shadow']} "
    f"tick(s) after shadow"
)
PY
echo "PASS serving_online_refit row"

echo "== live serve-gateway --refit + loadgen feedback drill =="
D=24 HIDDEN=32 DEPTH=3 HEAD_SEED=7
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    --refit --d $D --hidden $HIDDEN --depth $DEPTH \
    --buckets 4,8 --refit-interval-s 0.5 --refit-min-samples 128 \
    --canary-fraction 0.25 --request-log "$REQ_LOG" \
    >"$SERVER_LOG" 2>&1 &
SERVER_PID=$!

BASE=""
for _ in $(seq 1 240); do
    BASE="$(python - "$SERVER_LOG" <<'PY'
import json, sys
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            print(json.loads(line)["listening"]); break
except Exception:
    pass
PY
)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: refit gateway died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || { echo "FAIL: no handshake after 120s"; cat "$SERVER_LOG"; exit 1; }
echo "refit gateway up on $BASE"

# the lifecycle surface exists and starts idle
python - "$BASE" <<'PY'
import json, sys, urllib.request
doc = json.loads(urllib.request.urlopen(
    sys.argv[1] + "/lifecyclez", timeout=15).read())
st = doc["models"]["default"]
assert st["state"] == "idle", st
assert st["version"] == 0, st
print(f"/lifecyclez OK: default model idle at v0")
PY

# labeled open-loop traffic: half the issued payloads also go to
# /feedback, labeled by the teacher whose HEAD differs from the
# served (now stale) model — the refit must learn the new head
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-loadgen --target "$BASE" --d $D \
    --synthetic 4000 --rate 150 --seed 1 \
    --feedback-fraction 0.5 \
    --teacher "hidden=$HIDDEN,depth=$DEPTH,head_seed=$HEAD_SEED" \
    --report "$TMPDIR/loadgen-promote.json" \
    >"$TMPDIR/loadgen-promote.log" 2>&1 &
LOADGEN_PID=$!

# watch the walk: the feedback stream keeps flowing, so the
# controller may start MORE candidate cycles after the first
# promotion — sample until the monotonic promotions counter moves and
# capture THAT status (v1 vs the genuinely stale incumbent)
PROMOTED=""
for _ in $(seq 1 240); do
    PROMOTED="$(python - "$BASE" "$TMPDIR/promoted.json" <<'PY'
import json, sys, urllib.request
try:
    doc = json.loads(urllib.request.urlopen(
        sys.argv[1] + "/lifecyclez", timeout=15).read())
    st = doc["models"]["default"]
    if st["promotions"] >= 1:
        with open(sys.argv[2], "w") as f:
            json.dump(st, f)
        print("yes")
except Exception:
    pass
PY
)"
    [[ "$PROMOTED" == "yes" ]] && break
    kill -0 "$LOADGEN_PID" 2>/dev/null || break
    sleep 0.5
done

wait "$LOADGEN_PID" && LOADGEN_RC=0 || LOADGEN_RC=$?
LOADGEN_PID=""
[[ "$LOADGEN_RC" == 0 ]] || {
    echo "FAIL: promote-phase loadgen verdict went red (rc=$LOADGEN_RC)"
    cat "$TMPDIR/loadgen-promote.log"; exit 1; }
grep -q '"feedback"' "$TMPDIR/loadgen-promote.log" || {
    echo "FAIL: loadgen never reported its feedback counters"
    cat "$TMPDIR/loadgen-promote.log"; exit 1; }
[[ "$PROMOTED" == "yes" ]] || {
    echo "FAIL: no promotion observed on /lifecyclez"
    python -c 'import sys, urllib.request; \
print(urllib.request.urlopen(sys.argv[1] + "/lifecyclez", timeout=15).read().decode())' \
        "$BASE" || true
    exit 1; }

python - "$TMPDIR/promoted.json" <<'PY'
import json, sys
st = json.load(open(sys.argv[1]))
assert st["version"] >= 1, st
assert st["promotions"] >= 1, st
errs = st["errors"]
assert errs["candidate"] is not None and errs["incumbent"] is not None, st
assert errs["candidate"] < errs["incumbent"], (
    f"promoted candidate must beat the stale incumbent on held-out "
    f"labels: {errs}")
print(
    f"promotion OK: v{st['version']} promoted, held-out err "
    f"{errs['candidate']} vs stale {errs['incumbent']}"
)
PY
echo "PASS live refit -> shadow -> canary -> promoted (green verdict)"

METRICS="$(python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=15).read().decode())' \
    "$BASE/metrics")"
for fam in \
    keystone_lifecycle_state \
    keystone_lifecycle_version \
    keystone_lifecycle_refit_samples_total \
    keystone_lifecycle_shadow_pairs_total \
    keystone_lifecycle_canary_requests_total \
    keystone_lifecycle_promotions_total; do
    grep -q "^$fam" <<<"$METRICS" || {
        echo "FAIL: /metrics missing $fam family:"
        grep keystone_lifecycle <<<"$METRICS" || true
        exit 1; }
done
echo "PASS /metrics keystone_lifecycle_* families"

echo "== poisoned refit: auto-rollback drill =="
# arm the poison over the chaos surface; the NEXT refit cycle's
# accumulated chunks are corrupted (the holdout stays clean), so the
# accuracy gate must catch the candidate in shadow and roll back
python - "$BASE" <<'PY'
import json, sys, urllib.request
req = urllib.request.Request(
    sys.argv[1] + "/chaosz",
    data=json.dumps(
        {"arm": {"point": "lifecycle.refit.poison", "count": 16}}
    ).encode(),
    headers={"Content-Type": "application/json"},
)
body = json.loads(urllib.request.urlopen(req, timeout=15).read())
print(f"armed: {body}")
PY

JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-loadgen --target "$BASE" --d $D \
    --synthetic 2500 --rate 150 --seed 2 \
    --feedback-fraction 0.5 \
    --teacher "hidden=$HIDDEN,depth=$DEPTH,head_seed=$HEAD_SEED" \
    --report "$TMPDIR/loadgen-poison.json" \
    >"$TMPDIR/loadgen-poison.log" 2>&1 &
LOADGEN_PID=$!

# the rollback needs TICKS, not traffic: a poisoned candidate solved
# from the tail of the feedback stream is caught by the accuracy gate
# on the next 0.5s tick even after the loadgen exits — so keep
# polling through a grace window once the traffic stops
ROLLED=""
GRACE=0
for _ in $(seq 1 240); do
    ROLLED="$(python - "$BASE" <<'PY'
import re, sys, urllib.request
try:
    text = urllib.request.urlopen(
        sys.argv[1] + "/metrics", timeout=15).read().decode()
    total = sum(
        float(m.group(1)) for m in re.finditer(
            r"^keystone_lifecycle_rollbacks_total\{[^}]*\} (\S+)",
            text, re.M)
    )
    if total >= 1:
        print("yes")
except Exception:
    pass
PY
)"
    [[ "$ROLLED" == "yes" ]] && break
    if ! kill -0 "$LOADGEN_PID" 2>/dev/null; then
        GRACE=$((GRACE + 1))
        [[ "$GRACE" -ge 60 ]] && break
    fi
    sleep 0.5
done
[[ "$ROLLED" == "yes" ]] || {
    echo "FAIL: poisoned refit never rolled back"
    python -c 'import sys, urllib.request; \
print(urllib.request.urlopen(sys.argv[1] + "/lifecyclez", timeout=15).read().decode())' \
        "$BASE" || true
    exit 1; }

wait "$LOADGEN_PID" && LOADGEN_RC=0 || LOADGEN_RC=$?
LOADGEN_PID=""
[[ "$LOADGEN_RC" == 0 ]] || {
    echo "FAIL: poison-phase loadgen verdict went red (rc=$LOADGEN_RC) "
    echo "— served traffic must never notice a rolled-back candidate"
    cat "$TMPDIR/loadgen-poison.log"; exit 1; }

# the rollback is visible, attributed, and serving still answers
python - "$BASE" "$D" <<'PY'
import json, re, sys, urllib.request
base, d = sys.argv[1], int(sys.argv[2])
text = urllib.request.urlopen(base + "/metrics", timeout=15).read().decode()
rb = {
    m.group(0): float(m.group(1)) for m in re.finditer(
        r"^keystone_lifecycle_rollbacks_total\{[^}]*\} (\S+)", text, re.M)
}
assert rb and sum(rb.values()) >= 1, rb
assert any("accuracy" in k or "shadow_diff" in k for k in rb), rb
fired = [
    l for l in text.splitlines()
    if l.startswith("keystone_fault_injections_total")
    and "lifecycle.refit.poison" in l
]
assert fired, "the poison never counted on keystone_fault_injections_total"
req = urllib.request.Request(
    base + "/predict",
    data=json.dumps({"instances": [[0.1] * d]}).encode(),
    headers={"Content-Type": "application/json"},
)
body = json.loads(urllib.request.urlopen(req, timeout=60).read())
assert len(body["predictions"]) == 1, body
print(f"rollback OK: {rb}; poison audited: {fired[0]}; serving answers")
PY
echo "PASS poisoned refit -> auto-rollback (green verdict, serving up)"

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
SERVER_PID=""

echo "== request-log round trip through the trace parser =="
PYTHONPATH="$ROOT" python - "$REQ_LOG" <<'PY'
import sys
from keystone_tpu.loadgen import trace
with open(sys.argv[1]) as f:
    events = trace.parse_request_log(f)
assert events, "request log parsed to zero events"
posts = trace.normalize(trace.collapse_posts(events))
assert posts and posts[0].ts == 0.0, posts[:3]
models = {e.model for e in events}
print(f"round trip OK: {len(events)} lines -> {len(posts)} POSTs, "
      f"models seen: {sorted(models, key=str)}")
PY
echo "PASS request-log round trip"

echo "== keystone-lint self-clean =="
PYTHONPATH="$ROOT" python -m keystone_tpu keystone-lint
echo "PASS keystone-lint 0 findings"

echo "smoke-rollout: all checks passed"
