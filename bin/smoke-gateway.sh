#!/usr/bin/env bash
# Smoke-test the request plane end to end: start a gateway (admission +
# 2 replica lanes + live swap) over a toy pipeline on an ephemeral
# port, POST a /predict, scrape /metrics for the gateway series,
# trigger one FORCED live engine swap via POST /swap, verify traffic
# still predicts after it, then POST /drain and assert /readyz flips to
# 503 while already-admitted work resolves. CI-friendly: CPU backend,
# ~20s, no network beyond localhost.
#
#   bin/smoke-gateway.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
PORT_FILE="$TMPDIR/port"
SERVER_LOG="$TMPDIR/server.log"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

# the gateway demo entry on port 0 (ephemeral); patched to report the
# bound port to $PORT_FILE via a tiny wrapper. A deliberately
# unmeetable latency SLO (0.1 ms) makes every request an injected-slow
# request: burn gauges light up, the flight recorder captures span
# trees, and the latency histogram carries trace_id exemplars.
# KEYSTONE_PEAK_* pin a fake hardware peak so MFU/roofline light up on
# the CPU backend (absent without them — graceful degradation)
JAX_PLATFORMS=cpu KEYSTONE_PEAK_FLOPS=1e12 KEYSTONE_PEAK_MEMBW_GBPS=100 \
    PYTHONPATH="$ROOT" python - "$PORT_FILE" >"$SERVER_LOG" 2>&1 <<'PY' &
import sys, time
import jax.numpy as jnp
from keystone_tpu.gateway import Gateway, GatewayServer
from keystone_tpu.observability import enable_tracing
from keystone_tpu.serving.bench import build_pipeline

enable_tracing()
fitted = build_pipeline(d=8, hidden=8, depth=2)
gateway = Gateway(
    fitted, buckets=(4, 8), n_lanes=2,
    warmup_example=jnp.zeros((8,), jnp.float32), name="smoke",
    slo_latency_s=0.0001, slo_sample_interval_s=0.5,
)
server = GatewayServer(gateway, port=0).start()
with open(sys.argv[1], "w") as f:
    f.write(str(server.port))
time.sleep(120)  # hold the plane alive for the drill
PY
SERVER_PID=$!

for _ in $(seq 1 120); do
    [[ -s "$PORT_FILE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: server process died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -s "$PORT_FILE" ]] || { echo "FAIL: no port after 60s"; cat "$SERVER_LOG"; exit 1; }
PORT="$(cat "$PORT_FILE")"
BASE="http://127.0.0.1:$PORT"
echo "gateway up on $BASE"

fetch() {  # fetch <url> [timeout_s] — curl when present, stdlib urllib otherwise
    local timeout="${2:-15}"
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time "$timeout" "$1"
    else
        python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=float(sys.argv[2])).read().decode())' \
            "$1" "$timeout"
    fi
}

fetch_om() {  # fetch with the OpenMetrics Accept header (exemplars)
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time 15 \
            -H 'Accept: application/openmetrics-text' "$1"
    else
        python -c 'import sys, urllib.request; \
req = urllib.request.Request(sys.argv[1], \
headers={"Accept": "application/openmetrics-text"}); \
sys.stdout.write(urllib.request.urlopen(req, timeout=15).read().decode())' "$1"
    fi
}

post() {  # post <url> <json-body>
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time 30 -X POST -H 'Content-Type: application/json' \
            -d "$2" "$1"
    else
        python -c 'import sys, urllib.request; \
req = urllib.request.Request(sys.argv[1], data=sys.argv[2].encode(), \
headers={"Content-Type": "application/json"}); \
sys.stdout.write(urllib.request.urlopen(req, timeout=30).read().decode())' "$1" "$2"
    fi
}

status_of() {  # status_of <url> — status code even for non-2xx
    python -c 'import sys, urllib.request, urllib.error
try:
    print(urllib.request.urlopen(sys.argv[1], timeout=15).status)
except urllib.error.HTTPError as e:
    print(e.code)' "$1"
}

READY="$(fetch "$BASE/readyz")"
[[ "$READY" == "ok" ]] || { echo "FAIL: /readyz said '$READY'"; exit 1; }
echo "PASS /readyz"

PRED="$(post "$BASE/predict" '{"instances": [[1,0,1,0,1,0,1,0], [0,1,0,1,0,1,0,1]]}')"
grep -q '"predictions"' <<<"$PRED" || {
    echo "FAIL: /predict returned: $PRED"; exit 1; }
echo "PASS /predict"

METRICS="$(fetch "$BASE/metrics")"
for want in \
    'keystone_gateway_requests_total{gateway="smoke",status="ok"} 2' \
    'keystone_gateway_request_latency_seconds_bucket{gateway="smoke",le="+Inf"} 2' \
    'keystone_gateway_queue_wait_seconds_count{gateway="smoke"} 2' \
    'keystone_gateway_ready{gateway="smoke"} 1' \
    '# TYPE keystone_gateway_request_latency_seconds histogram' \
    'keystone_serving_examples_total{engine="smoke-lane0"}'
do
    grep -qF "$want" <<<"$METRICS" || {
        echo "FAIL: /metrics missing: $want"; echo "$METRICS"; exit 1; }
done
echo "PASS /metrics ($(grep -c '^keystone_gateway' <<<"$METRICS") gateway lines)"

# staged lane pipeline: every lane dispatches through host-prep ->
# upload -> compute -> deliver stage threads (pipeline_depth=2 is the
# gateway default), so the per-stage seconds series, window counter,
# bottleneck attribution, and overlap-efficiency gauge must be on the
# scrape, and /tracez must show the per-stage spans parented under the
# window's microbatch.coalesce span
for want in \
    'keystone_serving_stage_seconds_count{engine="smoke-lane0",stage="host_prep"}' \
    'keystone_serving_stage_seconds_count{engine="smoke-lane0",stage="upload"}' \
    'keystone_serving_stage_seconds_count{engine="smoke-lane0",stage="compute"}' \
    'keystone_serving_stage_seconds_count{engine="smoke-lane0",stage="deliver"}' \
    'keystone_serving_pipeline_windows_total{engine="smoke-lane0"}' \
    '# TYPE keystone_serving_pipeline_bottleneck gauge' \
    'keystone_serving_pipeline_overlap_efficiency{engine="smoke-lane0"}' \
    'keystone_serving_stage_queue_depth{engine="smoke-lane0",stage="host_prep"}'
do
    grep -qF "$want" <<<"$METRICS" || {
        echo "FAIL: /metrics missing pipeline series: $want"
        echo "$METRICS" | grep keystone_serving || true; exit 1; }
done
echo "PASS /metrics pipeline stage series"

# device-truth plane on the GATEWAY port: per-bucket cost models from
# each lane engine's warmup, live goodput/padding-efficiency, MFU +
# roofline (pinned peaks), staging-buffer bytes from the lane pools,
# the device info gauge, and the memory sampler the GatewayServer runs
for want in \
    'keystone_device_flops_per_dispatch{engine="smoke-lane0",bucket="4"}' \
    'keystone_serving_goodput_rows_total{engine="smoke-lane0",bucket="' \
    'keystone_serving_padding_efficiency{engine="smoke-lane0"}' \
    'keystone_serving_mfu{engine="smoke-lane0"}' \
    'keystone_device_roofline_bound{engine="smoke-lane0",bucket="4",bound="' \
    'keystone_serving_staging_bytes{engine="smoke-lane0"}' \
    'keystone_device_info{kind="' \
    'keystone_device_memory_bytes{device="host",kind="host-ram",stat="limit"}'
do
    grep -qF "$want" <<<"$METRICS" || {
        echo "FAIL: /metrics missing device-truth series: $want"
        echo "$METRICS" | grep -E 'keystone_(device|serving_(goodput|padd|mfu|stag))' || true
        exit 1; }
done
echo "PASS /metrics device-truth series (cost model, goodput, MFU, roofline, memory)"

# on-demand profiling mirrored on the gateway port; first start_trace
# initializes the profiler backend (~10s observed) — allow extra time
PROFILEZ="$(fetch "$BASE/profilez?seconds=1" 45)"
grep -q '"trace_dir"' <<<"$PROFILEZ" || {
    echo "FAIL: /profilez returned: $PROFILEZ"; exit 1; }
echo "PASS /profilez (on-demand jax.profiler capture while serving)"

TRACEZ="$(fetch "$BASE/tracez")"
for span in pipeline.host_prep pipeline.upload pipeline.compute \
    pipeline.deliver microbatch.coalesce gateway.admit
do
    grep -qF "\"$span\"" <<<"$TRACEZ" || {
        echo "FAIL: /tracez missing span: $span"; exit 1; }
done
# the stage spans carry the coalesce span as parent (cross-thread link)
printf '%s' "$TRACEZ" | python -c '
import json, sys
doc = json.load(sys.stdin)
spans = {}
for s in doc["spans"]:
    spans.setdefault(s["name"], []).append(s)
coalesce_ids = {s["span_id"] for s in spans.get("microbatch.coalesce", [])}
for name in ("pipeline.host_prep", "pipeline.upload",
             "pipeline.compute", "pipeline.deliver"):
    assert any(
        s.get("parent_id") in coalesce_ids for s in spans.get(name, [])
    ), f"{name} spans are not parented under microbatch.coalesce"
print("stage span chain OK")
' || exit 1
echo "PASS /tracez pipeline stage spans"

# forensic chain: the SLO objectives render at /slz with burn rates,
# the injected-slow requests are tail-sampled at /debugz with their
# span trees, and the latency histogram links to them via exemplars
fetch "$BASE/slz" | grep -q '"smoke:latency"' || {
    echo "FAIL: /slz missing the smoke:latency SLO"; exit 1; }
echo "PASS /slz"
DEBUGZ="$(fetch "$BASE/debugz")"
grep -q '"slo_breach"' <<<"$DEBUGZ" || {
    echo "FAIL: /debugz has no slo_breach record"; echo "$DEBUGZ"; exit 1; }
grep -q '"gateway.admit"' <<<"$DEBUGZ" || {
    echo "FAIL: /debugz record is missing its span tree"; exit 1; }
echo "PASS /debugz (injected-slow request captured with span tree)"
# exemplars only travel in the OpenMetrics rendering (the classic
# v0.0.4 parser would reject the mid-line '#'), so scrape with the
# Accept header a real Prometheus server sends; the plain scrape above
# must stay exemplar-free
OM_METRICS="$(fetch_om "$BASE/metrics")"
grep -q '# {trace_id="' <<<"$OM_METRICS" || {
    echo "FAIL: openmetrics /metrics has no trace_id exemplar"; exit 1; }
grep -q '# {trace_id="' <<<"$METRICS" && {
    echo "FAIL: classic /metrics scrape carries exemplar tails"; exit 1; }
echo "PASS exemplars (openmetrics only)"

SWAP="$(post "$BASE/swap" '{}')"
grep -q '"swapped": *true' <<<"$SWAP" || {
    echo "FAIL: /swap returned: $SWAP"; exit 1; }
PRED2="$(post "$BASE/predict" '{"instances": [[1,1,1,1,1,1,1,1]]}')"
grep -q '"predictions"' <<<"$PRED2" || {
    echo "FAIL: post-swap /predict returned: $PRED2"; exit 1; }
fetch "$BASE/metrics" | grep -qF \
    'keystone_gateway_engine_swaps_total{gateway="smoke"} 1' || {
    echo "FAIL: swap counter missing after /swap"; exit 1; }
echo "PASS /swap (forced live engine swap, traffic still serving)"

post "$BASE/drain" '{}' >/dev/null
for _ in $(seq 1 40); do
    [[ "$(status_of "$BASE/readyz")" == "503" ]] && break
    sleep 0.25
done
CODE="$(status_of "$BASE/readyz")"
[[ "$CODE" == "503" ]] || {
    echo "FAIL: /readyz still $CODE after /drain"; exit 1; }
echo "PASS /readyz flipped to 503 during drain"
echo "smoke-gateway: all checks passed"
