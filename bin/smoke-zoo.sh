#!/usr/bin/env bash
# Smoke-test the model-zoo serving plane end to end:
#
#  1. the `serving_zoo` bench row — two models sharing the flagship
#     SIFT+LCS->FV featurize prefix served through one ModelZoo
#     (cross-model CSE: ONE SharedPrefixEngine) vs two independent
#     gateways at equal device count, with the row's own asserts
#     (per-model output parity, prefix compiled once per bucket,
#     strictly fewer device dispatches, >= 1.5x ensemble ex/s)
#     re-checked here off the emitted JSON;
#  2. a real two-model `serve-gateway --zoo` subprocess: per-model
#     POST /predict/<model> (bare /predict serves the default model
#     and must match it bit-for-bit), a typed 404 for an unknown
#     model id enumerating the registered ids, /planz reporting the
#     plan-vs-actual placement, and the `model`-labeled zoo gauges
#     on /metrics;
#  3. keystone-lint self-clean stays at 0 findings (the zoo subsystem
#     plays by the repo's own rules).
#
# CI-friendly: CPU backend, ~2-3 min, no network beyond localhost.
#
#   bin/smoke-zoo.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
SERVER_LOG="$TMPDIR/server.log"
BENCH_OUT="$TMPDIR/bench.jsonl"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

echo "== serving_zoo bench row =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-bench --zoo-only \
    | tee "$BENCH_OUT"

python - "$BENCH_OUT" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
row = next(r for r in rows if r.get("metric") == "serving_zoo")
assert row["outputs_allclose"] is True, row
assert row["speedup_vs_two_gateways"] >= row["min_speedup"], row
assert sorted(row["models"]) in row["cse_groups"] or \
    any(sorted(g) == sorted(row["models"]) for g in row["cse_groups"]), row
assert row["zoo_compiles"] <= len(row["buckets"]), row
assert row["baseline_compiles"] >= 2 * row["zoo_compiles"], row
assert row["zoo_dispatches"] < row["baseline_dispatches"], row
print(
    f"row OK: {row['zoo_examples_per_sec']} ensemble ex/s zoo vs "
    f"{row['baseline_examples_per_sec']} two-gateway baseline "
    f"({row['speedup_vs_two_gateways']}x), compiles "
    f"{row['zoo_compiles']} vs {row['baseline_compiles']}, dispatches "
    f"{row['zoo_dispatches']} vs {row['baseline_dispatches']}"
)
PY
echo "PASS serving_zoo row"

echo "== serve-gateway --zoo drill (two models, one port) =="
D=24
cat > "$TMPDIR/zoo.json" <<SPEC
{"models": [
  {"name": "alpha", "d": $D, "hidden": 32, "depth": 2, "seed": 1,
   "buckets": [4, 8], "lanes": 1, "default": true, "pinned": true},
  {"name": "beta", "d": $D, "hidden": 32, "depth": 2, "seed": 2,
   "buckets": [4, 8], "lanes": 1}
]}
SPEC
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    --zoo "$TMPDIR/zoo.json" >"$SERVER_LOG" 2>&1 &
SERVER_PID=$!

BASE=""
for _ in $(seq 1 240); do
    BASE="$(python - "$SERVER_LOG" <<'PY'
import json, sys
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            print(json.loads(line)["listening"]); break
except Exception:
    pass
PY
)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: zoo gateway died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || { echo "FAIL: no handshake after 120s"; cat "$SERVER_LOG"; exit 1; }
grep -q '"models": \["alpha", "beta"\]' "$SERVER_LOG" || {
    echo "FAIL: handshake line missing the model roster"; cat "$SERVER_LOG"; exit 1; }
echo "zoo gateway up on $BASE serving [alpha, beta]"

# per-model routing + default-model parity + head divergence, one shot
python - "$BASE" "$D" <<'PY'
import json, sys, urllib.request
base, d = sys.argv[1], int(sys.argv[2])
inst = [((7 * i) % 13) / 13.0 for i in range(d)]

def predict(path):
    req = urllib.request.Request(
        base + path,
        data=json.dumps({"instances": [inst]}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = json.loads(urllib.request.urlopen(req, timeout=120).read())
    return body["predictions"]

bare = predict("/predict")
alpha = predict("/predict/alpha")
beta = predict("/predict/beta")
assert bare == alpha, (
    f"bare /predict must serve the DEFAULT model: {bare} != {alpha}")
assert alpha != beta, (
    "alpha and beta returned identical predictions — the zoo is not "
    f"routing per model ({alpha})")
print(f"per-model routing OK: alpha={alpha} beta={beta} (bare==alpha)")
PY
echo "PASS /predict/<model> routing + default-model parity"

# unknown model id: typed 404 enumerating the registered ids
python - "$BASE" "$D" <<'PY'
import json, sys, urllib.request, urllib.error
base, d = sys.argv[1], int(sys.argv[2])
req = urllib.request.Request(
    base + "/predict/nope",
    data=json.dumps({"instances": [[0.0] * d]}).encode(),
    headers={"Content-Type": "application/json"},
)
try:
    urllib.request.urlopen(req, timeout=30)
    raise SystemExit("FAIL: unknown model id did not 404")
except urllib.error.HTTPError as e:
    assert e.code == 404, f"want 404, got {e.code}"
    body = json.loads(e.read())
    assert body["error"] == "unknown_model", body
    assert sorted(body["registered"]) == ["alpha", "beta"], body
    print(f"unknown-model 404 OK: {body}")
PY
echo "PASS unknown model -> typed 404 with registered ids"

# /planz: the placement report knows both models and who is resident
python - "$BASE" <<'PY'
import json, sys, urllib.request
plan = json.loads(urllib.request.urlopen(
    sys.argv[1] + "/planz", timeout=15).read())
assert plan["default_model"] == "alpha", plan
actual = plan["actual"]
assert set(actual) == {"alpha", "beta"}, plan
assert actual["alpha"]["resident"] is True, plan
assert actual["alpha"]["pinned"] is True, plan
print(f"planz OK: default={plan['default_model']} "
      f"resident={[m for m, a in actual.items() if a['resident']]}")
PY
echo "PASS /planz plan-vs-actual"

METRICS="$(python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=15).read().decode())' \
    "$BASE/metrics")"
for want in \
    'keystone_zoo_resident{model="alpha"} 1' \
    'keystone_zoo_resident{model="beta"} 1' \
    'keystone_zoo_pageins_total{model="beta"} 1'; do
    grep -qF "$want" <<<"$METRICS" || {
        echo "FAIL: /metrics missing '$want':"
        grep keystone_zoo <<<"$METRICS" || true
        exit 1; }
done
echo "PASS /metrics model-labeled zoo gauges"

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
SERVER_PID=""

echo "== keystone-lint self-clean =="
PYTHONPATH="$ROOT" python -m keystone_tpu keystone-lint
echo "PASS keystone-lint 0 findings"

echo "smoke-zoo: all checks passed"
