#!/usr/bin/env bash
# Smoke-test mesh-sharded serving end to end:
#
#  1. the `serving_sharded_vs_replicated` bench row on an 8-device
#     host-platform mesh — the same model served mesh-sharded vs N
#     replicated lanes, with the row's own asserts (output parity at
#     every size both paths serve, the over-one-device-budget model
#     serving SHARDED while the replicated path is refused, the
#     crossover curve emitted) re-checked here off the emitted JSON;
#  2. a real `serve-gateway --shard-model` subprocess next to an
#     unsharded one over the SAME model: /predict answers match, the
#     sharded gateway's AOT store holds entries whose fingerprint meta
#     carries the `sharding_token` (a mesh-sharded program can never
#     collide with a replicated one), and the AOT counters are on
#     /metrics;
#  3. keystone-lint self-clean stays at 0 findings (the new
#     serving/sharding.py module included).
#
# CI-friendly: CPU backend with 8 virtual devices, ~3 min, no network
# beyond localhost.
#
#   bin/smoke-shard.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
BENCH_OUT="$TMPDIR/bench.jsonl"
AOT_DIR="$TMPDIR/aot"
SHARD_LOG="$TMPDIR/shard.log"
PLAIN_LOG="$TMPDIR/plain.log"
DEV8="--xla_force_host_platform_device_count=8"
cleanup() {
    [[ -n "${SHARD_PID:-}" ]] && kill "$SHARD_PID" 2>/dev/null || true
    [[ -n "${PLAIN_PID:-}" ]] && kill "$PLAIN_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

echo "== serving_sharded_vs_replicated bench row =="
XLA_FLAGS="$DEV8" JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-bench --shard-only --no-cache \
    | tee "$BENCH_OUT"

python - "$BENCH_OUT" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
row = next(
    r for r in rows if r.get("metric") == "serving_sharded_vs_replicated"
)
curve = row["crossover_curve"]
assert len(curve) >= 2, row
fitting = [e for e in curve if e["fits_one_device"]]
assert fitting and all(e["outputs_allclose"] for e in fitting), row
assert all(
    "replicated_examples_per_sec" in e and "sharded_examples_per_sec" in e
    for e in fitting
), row
big = curve[-1]
assert not big["fits_one_device"] and big["replicated"] == "over_budget", row
assert big["sharded_examples_per_sec"] > 0, row
assert big["max_device_params_mb"] <= row["device_budget_mb"] \
    < big["params_mb"], row
print(
    f"row OK: over-budget model ({big['params_mb']} MB params, "
    f"{big['max_device_params_mb']} MB/device sharded) served at "
    f"{big['sharded_examples_per_sec']} ex/s; "
    f"{len(fitting)} crossover points with output parity"
)
PY
echo "PASS bench row"

echo "== serve-gateway --shard-model vs unsharded parity drill =="
GWARGS=(--gateway-port 0 --buckets 4,8 --lanes 1 --d 64 --hidden 64 --depth 2)
XLA_FLAGS="$DEV8" JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    KEYSTONE_AOT_CACHE="$AOT_DIR" \
    python -m keystone_tpu serve-gateway "${GWARGS[@]}" --shard-model \
    >"$SHARD_LOG" 2>&1 &
SHARD_PID=$!
XLA_FLAGS="$DEV8" JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway "${GWARGS[@]}" --no-cache \
    >"$PLAIN_LOG" 2>&1 &
PLAIN_PID=$!

wait_for_base() {
    local log="$1" pid="$2" base=""
    for _ in $(seq 1 240); do
        base="$(python - "$log" <<'PY'
import json, sys
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            print(json.loads(line)["listening"]); break
except Exception:
    pass
PY
)"
        [[ -n "$base" ]] && { echo "$base"; return 0; }
        kill -0 "$pid" 2>/dev/null || {
            echo "FAIL: gateway died before binding" >&2
            cat "$log" >&2; return 1; }
        sleep 0.5
    done
    echo "FAIL: no handshake after 120s" >&2; cat "$log" >&2; return 1
}
SHARD_BASE="$(wait_for_base "$SHARD_LOG" "$SHARD_PID")"
PLAIN_BASE="$(wait_for_base "$PLAIN_LOG" "$PLAIN_PID")"
echo "sharded gateway on $SHARD_BASE, unsharded on $PLAIN_BASE"

python - "$SHARD_BASE" "$PLAIN_BASE" <<'PY'
import json, sys, urllib.request
import numpy as np

shard, plain = sys.argv[1], sys.argv[2]
rng = np.random.default_rng(7)
inst = rng.standard_normal((64,)).astype(float).round(4).tolist()
def predict(base):
    req = urllib.request.Request(
        base + "/predict",
        data=json.dumps({"instances": [inst]}).encode(),
        headers={"Content-Type": "application/json"},
    )
    return np.asarray(
        json.loads(urllib.request.urlopen(req, timeout=60).read())
        ["predictions"][0]
    )
a, b = predict(shard), predict(plain)
assert np.allclose(a, b, rtol=1e-4, atol=1e-5), (
    f"sharded /predict diverges: max abs diff {np.abs(a - b).max()}"
)
print(f"/predict parity OK (max abs diff {np.abs(a - b).max():.2e})")
PY
echo "PASS /predict parity (sharded vs unsharded)"

# the sharded gateway's AOT entries: counters scraped on /metrics and
# every stored fingerprint meta carrying the sharding_token
METRICS="$(python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=15).read().decode())' \
    "$SHARD_BASE/metrics")"
grep -q 'keystone_aot_cache_misses_total' <<<"$METRICS" || {
    echo "FAIL: /metrics missing keystone_aot_cache_* on the sharded gateway"
    grep keystone_aot <<<"$METRICS" || true
    exit 1; }
echo "PASS /metrics keystone_aot_cache_* present"

PYTHONPATH="$ROOT" python - "$AOT_DIR" <<'PY'
import sys
from keystone_tpu.serving.aot import AotStore
from keystone_tpu.observability.registry import MetricsRegistry

store = AotStore(sys.argv[1], registry=MetricsRegistry())
entries = store.entries()
assert entries, "sharded gateway saved no AOT entries"
for key in entries:
    meta = store.read_meta(key)
    assert meta is not None, f"unreadable entry {key}"
    assert meta.get("sharding_token"), (
        f"entry {key} meta lacks the sharding_token: {sorted(meta)}"
    )
print(f"{len(entries)} AOT entries, every meta pins a sharding_token")
PY
echo "PASS sharded AOT entries fingerprinted with sharding_token"

kill "$SHARD_PID" "$PLAIN_PID" 2>/dev/null || true
wait "$SHARD_PID" 2>/dev/null || true
wait "$PLAIN_PID" 2>/dev/null || true
SHARD_PID=""; PLAIN_PID=""

echo "== keystone-lint self-clean =="
PYTHONPATH="$ROOT" python -m keystone_tpu keystone-lint
echo "PASS keystone-lint 0 findings"

echo "smoke-shard: all checks passed"
