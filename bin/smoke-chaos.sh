#!/usr/bin/env bash
# Smoke-test the load-generator + chaos harness end to end:
#
#   1. the chaos bench rows (serving_chaos_lane_kill /
#      serving_chaos_prep_stall) — in-process open-loop load with a
#      fault fired mid-run, the invariant verdict ASSERTED inside the
#      row (every admitted request resolves, typed sheds only,
#      readiness + p99 recover after the fault clears);
#   2. a real two-process drill — serve-gateway with a file-backed
#      --request-log, serve-loadgen replaying a synthetic Poisson
#      trace against it over HTTP with gateway.lane.kill armed
#      mid-run via POST /chaosz, verdict must be green, and
#      keystone_fault_injections_total{point="gateway.lane.kill"}
#      must show on the gateway's own /metrics;
#   3. record/replay — the request log the drill produced is parsed
#      and replayed back at 8x (the satellite: logs are replayable,
#      no process-output scraping).
#
# CI-friendly: CPU backend, localhost only, ~2 min.
#
#   bin/smoke-chaos.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
SERVER_LOG="$TMPDIR/server.log"
REQ_LOG="$TMPDIR/requests.jsonl"
VERDICT="$TMPDIR/verdict.json"
BENCH_LOG="$TMPDIR/bench.log"
LOADGEN_LOG="$TMPDIR/loadgen.log"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

D=64

# ---- 1. the chaos bench rows (invariants asserted in-row) ----------------
echo "== chaos bench rows (in-process) =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-bench --chaos-only \
    --d "$D" --hidden "$D" --depth 2 --buckets 4,16 --no-cache \
    | tee "$BENCH_LOG"
for metric in serving_chaos_lane_kill serving_chaos_prep_stall; do
    grep -q "\"metric\": \"$metric\"" "$BENCH_LOG" || {
        echo "FAIL: bench row $metric missing"; exit 1; }
    grep "\"metric\": \"$metric\"" "$BENCH_LOG" \
        | grep -q '"verdict": "green"' || {
        echo "FAIL: bench row $metric verdict not green"; exit 1; }
done
echo "PASS chaos bench rows (both verdicts green)"

# ---- 2. two-process drill over HTTP --------------------------------------
echo "== gateway + loadgen drill (two processes) =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    --d "$D" --hidden "$D" --depth 2 --buckets 4,16 --lanes 2 \
    --no-cache --request-log "$REQ_LOG" >"$SERVER_LOG" 2>&1 &
SERVER_PID=$!

BASE=""
for _ in $(seq 1 240); do
    BASE="$(grep -o 'http://127.0.0.1:[0-9]*' "$SERVER_LOG" | head -1 || true)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: gateway died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || { echo "FAIL: no gateway URL after 120s"; cat "$SERVER_LOG"; exit 1; }
echo "gateway up on $BASE"

fetch() {  # fetch <url> [timeout_s]
    local timeout="${2:-15}"
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time "$timeout" "$1"
    else
        python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=float(sys.argv[2])).read().decode())' \
            "$1" "$timeout"
    fi
}

post() {  # post <url> <json-body>
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time 30 -X POST -H 'Content-Type: application/json' \
            -d "$2" "$1"
    else
        python -c 'import sys, urllib.request; \
req = urllib.request.Request(sys.argv[1], data=sys.argv[2].encode(), \
headers={"Content-Type": "application/json"}); \
sys.stdout.write(urllib.request.urlopen(req, timeout=30).read().decode())' "$1" "$2"
    fi
}

# the fault-point catalog is served before anything is armed
fetch "$BASE/chaosz" | grep -q '"gateway.lane.kill"' || {
    echo "FAIL: /chaosz catalog missing gateway.lane.kill"; exit 1; }
echo "PASS /chaosz catalog"

# a /chaosz arm/disarm round-trip from the shell (the loadgen below
# arms its own fault the same way, mid-run)
post "$BASE/chaosz" '{"arm": {"point": "otlp.export.blackhole", "count": 1}}' \
    | grep -q '"otlp.export.blackhole"' || {
    echo "FAIL: /chaosz arm did not round-trip"; exit 1; }
post "$BASE/chaosz" '{"disarm": "*"}' | grep -q '"armed": {}' || {
    echo "FAIL: /chaosz disarm did not round-trip"; exit 1; }
echo "PASS /chaosz arm/disarm round-trip"

# open-loop synthetic trace with a lane killed mid-run; the loadgen
# exits nonzero unless the invariant verdict is green. The tight
# 1.5x p99-recovery contract is asserted by the serving_chaos_* rows
# above (in-process, steadier clock); this two-process drill also
# fights socket + client-thread scheduling noise on a shared CI
# host, so its tail bound gets headroom — the hard invariants
# (nothing lost, typed-only, readiness back) stay exact — AND one
# bounded retry: the p99-recovery clock races the host scheduler, so
# a single red drill on a loaded box gets one fresh chance (the drill
# is idempotent — it arms its own fault over /chaosz each run and the
# fired-count audit is delta-based) before the smoke fails for real.
DRILL_OK=""
for attempt in 1 2; do
    if JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
        python -m keystone_tpu serve-loadgen --target "$BASE" --d "$D" \
        --synthetic 240 --arrivals poisson --rate 60 \
        --fault 'gateway.lane.kill=lane:0' --fault-at 1.5 --fault-for 1.5 \
        --settle-s 4 --recovery-s 10 --p99-factor 2.0 --max-shed-rate 0.8 \
        --report "$VERDICT" | tee "$LOADGEN_LOG" \
        && grep -q '"passed": true' "$VERDICT"; then
        DRILL_OK=1
        break
    fi
    echo "drill attempt $attempt not green; $([ "$attempt" -lt 2 ] \
        && echo 'retrying once (host-load flake guard)' \
        || echo 'out of retries')"
    cat "$VERDICT" 2>/dev/null || true
done
[[ -n "$DRILL_OK" ]] || {
    echo "FAIL: serve-loadgen drill red on both attempts"; exit 1; }
echo "PASS loadgen drill (verdict green: every admitted request" \
     "resolved, typed sheds only, readiness + p99 recovered)"

# the injections are auditable on the gateway's own scrape surface
fetch "$BASE/metrics" \
    | grep -q 'keystone_fault_injections_total{point="gateway.lane.kill"}' || {
    echo "FAIL: /metrics missing keystone_fault_injections_total"; exit 1; }
echo "PASS /metrics keystone_fault_injections_total{point=\"gateway.lane.kill\"}"

# ---- 3. record/replay ----------------------------------------------------
[[ -s "$REQ_LOG" ]] || { echo "FAIL: --request-log file is empty"; exit 1; }
grep -q '"n_rows"' "$REQ_LOG" && grep -q '"shape"' "$REQ_LOG" || {
    echo "FAIL: request log lines missing the replay fields"; exit 1; }
LINES="$(wc -l < "$REQ_LOG")"
echo "request log captured $LINES lines; replaying at 8x"
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-loadgen --target "$BASE" --d "$D" \
    --trace "$REQ_LOG" --speed 8 --no-verdict \
    | grep -q '"stats"' || {
    echo "FAIL: trace replay did not complete"; exit 1; }
echo "PASS record/replay (the drill's own request log replayed back)"

post "$BASE/drain" '{}' >/dev/null || true
echo "smoke-chaos: all checks passed"
