#!/usr/bin/env bash
# Smoke-test the keystone-lint CI gate:
#
#   1. `keystone-lint --json` over the repo must exit 0 (every finding
#      fixed, suppressed-with-justification, or baselined) and emit a
#      JSON document that parses against the expected schema;
#   2. the human renderer agrees with the JSON verdict;
#   3. `--changed-only` (the fast local loop over `git diff
#      --name-only`) runs and exits 0 on a clean tree;
#   4. the analyzer still has teeth: a scratch file with a known
#      violation of each quick rule must fail with exit 1 and name the
#      rules — a gate that can't fail isn't a gate.
#
# CI-friendly: stdlib-only analyzer (no jax import), < 10 s.
#
#   bin/smoke-lint.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
cleanup() { rm -rf "$TMPDIR"; }
trap cleanup EXIT

cd "$ROOT"

# ---- 1. clean JSON run ---------------------------------------------------
echo "== keystone-lint --json (the CI gate) =="
python -m keystone_tpu keystone-lint --json > "$TMPDIR/lint.json"
python - "$TMPDIR/lint.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
for key in ("version", "root", "clean", "counts", "findings", "rules"):
    if key not in doc:
        raise SystemExit(f"FAIL: JSON output missing key {key!r}")
if doc["version"] != 1:
    raise SystemExit(f"FAIL: unexpected schema version {doc['version']}")
if not doc["clean"]:
    raise SystemExit(f"FAIL: repo not lint-clean: {doc['findings']}")
if len(doc["rules"]) != 6:
    raise SystemExit(f"FAIL: expected 6 rules, got {doc['rules']}")
counts = doc["counts"]
for key in ("findings", "baselined", "suppressed", "stale_baseline"):
    if key not in counts:
        raise SystemExit(f"FAIL: counts missing {key!r}")
print(f"PASS schema + clean (suppressed={counts['suppressed']}, "
      f"baselined={counts['baselined']})")
EOF

# ---- 2. human renderer agrees --------------------------------------------
python -m keystone_tpu keystone-lint | tail -1 | grep -q '0 finding(s)' || {
    echo "FAIL: human output disagrees with the JSON verdict"; exit 1; }
echo "PASS human renderer"

# ---- 3. --changed-only fast path -----------------------------------------
echo "== keystone-lint --changed-only =="
python -m keystone_tpu keystone-lint --changed-only --json \
    > "$TMPDIR/changed.json"
python - "$TMPDIR/changed.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
if not doc.get("changed_only"):
    raise SystemExit("FAIL: changed_only not marked in output")
if not doc["clean"]:
    raise SystemExit(f"FAIL: changed-only run dirty: {doc['findings']}")
print("PASS --changed-only")
EOF

# ---- 4. the gate can fail ------------------------------------------------
echo "== seeded violations must fail =="
FIXTURE_ROOT="$TMPDIR/proj"
mkdir -p "$FIXTURE_ROOT/pkg"
cat > "$FIXTURE_ROOT/pkg/bad.py" <<'EOF'
import threading
import time


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}  # guarded-by: _lock

    def bad_write(self):
        self._state = {}

    def bad_block(self):
        with self._lock:
            time.sleep(1.0)


def gate(ok):
    assert ok, "stripped under -O"
EOF
set +e
python -m keystone_tpu keystone-lint --root "$FIXTURE_ROOT" \
    --baseline absent.json pkg > "$TMPDIR/bad.out" 2>&1
RC=$?
set -e
[[ "$RC" -eq 1 ]] || {
    echo "FAIL: seeded violations exited $RC (want 1)"
    cat "$TMPDIR/bad.out"; exit 1; }
for rule in guarded-by blocking-under-lock strippable-assert; do
    grep -q "$rule" "$TMPDIR/bad.out" || {
        echo "FAIL: seeded $rule violation not reported"
        cat "$TMPDIR/bad.out"; exit 1; }
done
echo "PASS seeded violations fail with exit 1"

echo "smoke-lint: all checks passed"
