#!/usr/bin/env bash
# Smoke-test device-side featurization end to end:
#
#  1. the `serving_device_featurize` and `serving_flagship_featurize`
#     bench rows — the demo conv chain and the flagship SIFT+LCS->FV
#     chain, each served through a host_featurize gateway vs a
#     device_featurize gateway, with the rows' own asserts (outputs
#     allclose, device-path H2D bytes/request <= 1/3 of the host path,
#     device examples/sec >= host, and — flagship — the fused
#     program's cost-model/MFU/roofline series present) re-checked
#     here off the emitted JSON. KEYSTONE_PEAK_* exports give the CPU
#     backend known "hardware" peaks so the MFU/roofline series are
#     concretely present, not skipped-as-unknown;
#  2. a real `serve-gateway --device-featurize` subprocess (demo
#     chain): POST a raw uint8 image to /predict, assert predictions
#     come back and that `keystone_serving_h2d_bytes_total` is on
#     /metrics with the raw byte footprint (bucket * img * img * 3) —
#     the wire-bytes win as a scraped fact;
#  3. the same drill against `--device-featurize flagship` — the
#     branched Pallas-kernel chain behind the same gateway seam.
#
# CI-friendly: CPU backend, ~2-3 min, no network beyond localhost.
#
#   bin/smoke-featurize.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
SERVER_LOG="$TMPDIR/server.log"
BENCH_OUT="$TMPDIR/bench.jsonl"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

echo "== serving_device_featurize + serving_flagship_featurize bench rows =="
# CPU has no PEAK_TABLE entry; the env overrides give the backend
# known peaks so the flagship row's MFU/roofline series must be
# PRESENT (the row raises on absence when peaks are known)
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    KEYSTONE_PEAK_FLOPS=1e12 KEYSTONE_PEAK_MEMBW_GBPS=100 \
    python -m keystone_tpu serve-bench --featurize-only \
    | tee "$BENCH_OUT"

python - "$BENCH_OUT" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
row = next(r for r in rows if r.get("metric") == "serving_device_featurize")
assert row["outputs_allclose"] is True, row
assert row["h2d_reduction"] >= 3.0, row
assert row["device_examples_per_sec"] >= row["host_examples_per_sec"], row
assert row["device_bottleneck"] not in ("host_prep", "upload"), row
print(
    f"row OK: {row['device_examples_per_sec']} ex/s device vs "
    f"{row['host_examples_per_sec']} host, "
    f"{row['h2d_reduction']}x fewer H2D bytes/request, "
    f"bottleneck {row['host_bottleneck']} -> {row['device_bottleneck']}"
)
fl = next(r for r in rows if r.get("metric") == "serving_flagship_featurize")
assert fl["outputs_allclose"] is True, fl
assert fl["h2d_reduction"] >= 3.0, fl
assert fl["device_examples_per_sec"] >= fl["host_examples_per_sec"], fl
assert fl["fv_kernel"] == "pallas_fused", fl
assert fl["cost_model_buckets"], fl
assert fl["peaks_known"] is True, fl
assert fl["mfu"] is not None, fl
assert all(v in ("compute", "bandwidth") for v in fl["roofline"].values()), fl
print(
    f"flagship row OK: {fl['device_examples_per_sec']} ex/s fused vs "
    f"{fl['host_examples_per_sec']} host, "
    f"{fl['h2d_reduction']}x fewer H2D bytes/bucket-row, "
    f"mfu={fl['mfu']}, roofline={fl['roofline']}"
)
PY
echo "PASS bench rows"

echo "== serve-gateway --device-featurize drill =="
IMG=8
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    --device-featurize --img "$IMG" --buckets 4,8 --lanes 1 \
    --hidden 64 --depth 2 >"$SERVER_LOG" 2>&1 &
SERVER_PID=$!

BASE=""
for _ in $(seq 1 240); do
    BASE="$(python - "$SERVER_LOG" <<'PY'
import json, sys
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            print(json.loads(line)["listening"]); break
except Exception:
    pass
PY
)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: gateway died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || { echo "FAIL: no handshake after 120s"; cat "$SERVER_LOG"; exit 1; }
echo "gateway up on $BASE"

# one raw uint8 image instance (IMG x IMG x 3 nested JSON ints)
PRED="$(python - "$BASE" "$IMG" <<'PY'
import json, sys, urllib.request
base, img = sys.argv[1], int(sys.argv[2])
inst = [[[x % 251, y % 251, (x + y) % 251] for y in range(img)]
        for x in range(img)]
req = urllib.request.Request(
    base + "/predict",
    data=json.dumps({"instances": [inst]}).encode(),
    headers={"Content-Type": "application/json"},
)
print(urllib.request.urlopen(req, timeout=60).read().decode())
PY
)"
grep -q '"predictions"' <<<"$PRED" || {
    echo "FAIL: /predict returned: $PRED"; cat "$SERVER_LOG"; exit 1; }
echo "PASS /predict (raw uint8 image in, predictions out)"

# malformed raw payload: a pixel out of uint8 range is the CLIENT's
# error — typed 400 bad_request, never a 500 + server stack trace
BADCODE="$(python - "$BASE" <<'PY'
import json, sys, urllib.request, urllib.error
req = urllib.request.Request(
    sys.argv[1] + "/predict",
    data=json.dumps({"instances": [[[[256, 0, 0]]]]}).encode(),
    headers={"Content-Type": "application/json"},
)
try:
    print(urllib.request.urlopen(req, timeout=30).status)
except urllib.error.HTTPError as e:
    print(e.code)
PY
)"
[[ "$BADCODE" == "400" ]] || {
    echo "FAIL: out-of-range pixel returned $BADCODE, want 400"; exit 1; }
echo "PASS /predict out-of-range pixel -> 400 bad_request"

METRICS="$(python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=15).read().decode())' \
    "$BASE/metrics")"
# the single-instance window dispatches bucket 4: 4 * IMG*IMG*3 raw
# uint8 bytes staged — raw-on-the-wire, exactly accounted
WANT_BYTES=$((4 * IMG * IMG * 3))
grep -qF "keystone_serving_h2d_bytes_total{engine=\"gateway-lane0\",bucket=\"4\"} $WANT_BYTES" \
    <<<"$METRICS" || {
    echo "FAIL: /metrics missing the h2d bytes counter ($WANT_BYTES expected):"
    grep keystone_serving_h2d <<<"$METRICS" || true
    exit 1; }
echo "PASS /metrics keystone_serving_h2d_bytes_total ($WANT_BYTES raw bytes)"

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
SERVER_PID=""

echo "== serve-gateway --device-featurize flagship drill =="
# img must clear the LCS border (> 32); 34 keeps the CPU warmup quick
FIMG=34
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    --device-featurize flagship --img "$FIMG" --buckets 4,8 --lanes 1 \
    --hidden 64 --depth 2 >"$SERVER_LOG.flagship" 2>&1 &
SERVER_PID=$!

BASE=""
for _ in $(seq 1 240); do
    BASE="$(python - "$SERVER_LOG.flagship" <<'PY'
import json, sys
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            print(json.loads(line)["listening"]); break
except Exception:
    pass
PY
)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: flagship gateway died before binding"
        cat "$SERVER_LOG.flagship"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || {
    echo "FAIL: no flagship handshake after 120s"
    cat "$SERVER_LOG.flagship"; exit 1; }
echo "flagship gateway up on $BASE"

PRED="$(python - "$BASE" "$FIMG" <<'PY'
import json, sys, urllib.request
base, img = sys.argv[1], int(sys.argv[2])
inst = [[[x % 251, y % 251, (x + y) % 251] for y in range(img)]
        for x in range(img)]
req = urllib.request.Request(
    base + "/predict",
    data=json.dumps({"instances": [inst]}).encode(),
    headers={"Content-Type": "application/json"},
)
print(urllib.request.urlopen(req, timeout=120).read().decode())
PY
)"
grep -q '"predictions"' <<<"$PRED" || {
    echo "FAIL: flagship /predict returned: $PRED"
    cat "$SERVER_LOG.flagship"; exit 1; }
echo "PASS flagship /predict (raw uint8 image through the SIFT+LCS->FV DAG)"

METRICS="$(python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=15).read().decode())' \
    "$BASE/metrics")"
# single instance -> bucket 4: 4 * FIMG*FIMG*3 raw uint8 bytes staged
WANT_BYTES=$((4 * FIMG * FIMG * 3))
grep -qF "keystone_serving_h2d_bytes_total{engine=\"gateway-lane0\",bucket=\"4\"} $WANT_BYTES" \
    <<<"$METRICS" || {
    echo "FAIL: flagship /metrics missing the h2d bytes counter ($WANT_BYTES expected):"
    grep keystone_serving_h2d <<<"$METRICS" || true
    exit 1; }
echo "PASS flagship /metrics keystone_serving_h2d_bytes_total ($WANT_BYTES raw bytes)"

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
SERVER_PID=""
echo "smoke-featurize: all checks passed"
