#!/usr/bin/env bash
# Smoke-test the fleet tier end to end:
#
#   1. the fleet bench row (serving_router_failover) — open-loop load
#      through an in-process router + two HTTP replicas, one replica's
#      responses black-holed mid-run, the invariant verdict ASSERTED
#      inside the row and the fleet p99 read from the router's own
#      federated /metrics;
#   2. a real THREE-process drill — serve-router + two serve-gateway
#      replicas that self-register (--register) after binding
#      ephemeral ports (--gateway-port 0 prints the bound address as
#      a parseable JSON line — no port races), both pointed at ONE
#      shared KEYSTONE_AOT_CACHE so replica #2 must start warm
#      (keystone_aot_cache_hits_total > 0 on its own /metrics);
#   3. chaos across hosts — serve-loadgen replays a synthetic trace
#      through the ROUTER while replica #1's process is kill -9'd
#      mid-load; the invariant checker must report green (zero lost
#      futures, typed sheds only) and /fleetz must show the replica
#      leave the healthy set;
#   4. half-open recovery — replica #1 restarts AT THE SAME PORT;
#      /fleetz must show it healthy again once router traffic
#      half-opens and restores it;
#   5. SLO federation — histogram_quantile over the router's
#      federated /metrics must agree with the per-replica quantiles
#      to within one bucket boundary;
#   6. distributed tracing — one /predict through the three-process
#      drill must come back with an X-Keystone-Trace id that appears
#      in BOTH processes' /tracez and stitches at the router's
#      /debugz?trace_id= into one tree with spans from both processes
#      and a phase decomposition summing to within 10% of the
#      measured total (the serving_router_trace_overhead bench row in
#      step 1 bounds the cost of all this at <= 1.05x p99).
#
# CI-friendly: CPU backend, localhost only, ~3 min.
#
#   bin/smoke-fleet.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
ROUTER_LOG="$TMPDIR/router.log"
R1_LOG="$TMPDIR/replica1.log"
R2_LOG="$TMPDIR/replica2.log"
BENCH_LOG="$TMPDIR/bench.log"
VERDICT="$TMPDIR/verdict.json"
AOT_CACHE="$TMPDIR/aot"
cleanup() {
    for pid in "${ROUTER_PID:-}" "${R1_PID:-}" "${R2_PID:-}"; do
        [[ -n "$pid" ]] && kill "$pid" 2>/dev/null || true
    done
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

D=64
# --trace: replicas adopt the router's W3C traceparent so step 6's
# stitched-trace assertion has both halves to join
GW_ARGS=(--d "$D" --hidden "$D" --depth 2 --buckets 4,16 --lanes 2 --trace)

listen_url() {  # listen_url <logfile> — the parseable {"listening": ...} line
    python -c '
import json, sys
for line in open(sys.argv[1]):
    line = line.strip()
    if line.startswith("{"):
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if "listening" in doc:
            print(doc["listening"])
            break
' "$1"
}

wait_listen() {  # wait_listen <logfile> <pid> <what> -> URL on stdout
    local url=""
    for _ in $(seq 1 240); do
        url="$(listen_url "$1")"
        [[ -n "$url" ]] && { echo "$url"; return 0; }
        kill -0 "$2" 2>/dev/null || {
            echo "FAIL: $3 died before binding" >&2; cat "$1" >&2; return 1; }
        sleep 0.5
    done
    echo "FAIL: no $3 URL after 120s" >&2; cat "$1" >&2; return 1
}

fetch() {  # fetch <url> [timeout_s]
    local timeout="${2:-15}"
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time "$timeout" "$1"
    else
        python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=float(sys.argv[2])).read().decode())' \
            "$1" "$timeout"
    fi
}

# ---- 1. the fleet bench row (verdict + federation asserted in-row) -------
# One bounded retry, same as smoke-chaos's drill: the row's
# p99-recovery clock races the host scheduler (router + 2 replicas +
# client threads share this box), so a single red attempt on a loaded
# host gets one fresh chance (the row is idempotent — the fired-count
# audit is delta-based) before the smoke fails for real.
echo "== fleet bench rows (in-process router + HTTP replicas) =="
# each row runs in its OWN process with its OWN bounded retry: a
# p99-recovery (or p99-ratio) clock on a loaded 2-core host gets one
# fresh chance per row, and the tracing A/B measures a quiet process
# instead of the failover row's thread aftermath
bench_row() {  # bench_row <rows> <metric>
    local rows="$1" metric="$2" attempt
    for attempt in 1 2; do
        if JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
            python -m keystone_tpu serve-bench --fleet-only \
            --fleet-rows "$rows" \
            --d "$D" --hidden "$D" --depth 2 --buckets 4,16 --no-cache \
            | tee "$BENCH_LOG" \
            && grep "\"metric\": \"$metric\"" "$BENCH_LOG" \
                | grep -q '"verdict": "green"'; then
            return 0
        fi
        echo "$metric attempt $attempt not green; $([ "$attempt" -lt 2 ] \
            && echo 'retrying once (host-load flake guard)' \
            || echo 'out of retries')"
    done
    return 1
}
bench_row failover serving_router_failover || {
    echo "FAIL: serving_router_failover red on both attempts"; exit 1; }
echo "PASS serving_router_failover (verdict green, fleet p99 federated)"
bench_row trace serving_router_trace_overhead || {
    echo "FAIL: serving_router_trace_overhead red on both attempts"; exit 1; }
echo "PASS serving_router_trace_overhead (tracing-on p99 <= 1.05x off)"

# ---- 2. three-process fleet: router + 2 self-registering replicas --------
echo "== three-process drill: router + 2 replicas =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-router --router-port 0 \
    --probe-interval 0.5 --recovery-after 2 >"$ROUTER_LOG" 2>&1 &
ROUTER_PID=$!
ROUTER="$(wait_listen "$ROUTER_LOG" "$ROUTER_PID" router)"
echo "router up on $ROUTER"

start_replica() {  # start_replica <logfile> <extra args...>
    local log="$1"; shift
    JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
        KEYSTONE_AOT_CACHE="$AOT_CACHE" \
        python -m keystone_tpu serve-gateway --gateway-port 0 \
        "${GW_ARGS[@]}" --register "$ROUTER" "$@" >"$log" 2>&1 &
}

KEYSTONE_COMPILE_CACHE="$TMPDIR/xc1" start_replica "$R1_LOG"
R1_PID=$!
R1="$(wait_listen "$R1_LOG" "$R1_PID" replica1)"
# replica 1 fully warm (and the shared AOT store populated) BEFORE
# replica 2 starts, so replica 2's warmup has executables to load
for _ in $(seq 1 240); do
    fetch "$R1/readyz" >/dev/null 2>&1 && break
    sleep 0.5
done
echo "replica1 up on $R1 (cold start populated $AOT_CACHE)"

KEYSTONE_COMPILE_CACHE="$TMPDIR/xc2" start_replica "$R2_LOG"
R2_PID=$!
R2="$(wait_listen "$R2_LOG" "$R2_PID" replica2)"
for _ in $(seq 1 240); do
    fetch "$R2/readyz" >/dev/null 2>&1 && break
    sleep 0.5
done
echo "replica2 up on $R2"

# the PR 8 follow-on: replica 2 must have started WARM off the shared
# executable store — its own /metrics proves it
fetch "$R2/metrics" | PYTHONPATH="$ROOT" python -c '
import sys
from keystone_tpu.observability.prometheus import parse_samples
hits = sum(v for n, _, v in parse_samples(sys.stdin.read())
           if n == "keystone_aot_cache_hits_total")
assert hits > 0, "replica 2 reported zero AOT cache hits: not a warm start"
print(f"replica2 AOT cache hits: {hits:g}")
' || { echo "FAIL: replica 2 did not start warm off the shared AOT store"; exit 1; }
echo "PASS shared-AOT warm start"

# both replicas self-registered and probed ready
for _ in $(seq 1 60); do
    READY="$(fetch "$ROUTER/fleetz" \
        | python -c 'import json,sys; d=json.load(sys.stdin); \
print(sum(1 for r in d["replicas"] if r["ready"] and r["healthy"]))' )"
    [[ "$READY" == "2" ]] && break
    sleep 0.5
done
[[ "$READY" == "2" ]] || {
    echo "FAIL: /fleetz never showed 2 ready replicas"; fetch "$ROUTER/fleetz"; exit 1; }
echo "PASS self-registration (/fleetz: 2 replicas ready)"

# ---- 3. kill a replica PROCESS mid-load; verdict must stay green ---------
echo "== chaos across hosts: kill -9 replica1 mid-load =="
( sleep 2; kill -9 "$R1_PID" 2>/dev/null || true ) &
KILLER_PID=$!
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-loadgen --target "$ROUTER" --d "$D" \
    --synthetic 240 --arrivals poisson --rate 50 \
    --settle-s 3 --max-shed-rate 0.5 --report "$VERDICT" \
    >"$TMPDIR/loadgen.log" 2>&1 || {
    echo "FAIL: loadgen through the router went red with a replica killed"
    cat "$TMPDIR/loadgen.log"; exit 1; }
wait "$KILLER_PID" 2>/dev/null || true
grep -q '"passed": true' "$VERDICT" || {
    echo "FAIL: invariant verdict not green"; cat "$VERDICT"; exit 1; }
echo "PASS kill-mid-load (every admitted request resolved, typed sheds only)"

# the dead replica left the healthy set
for _ in $(seq 1 30); do
    DEAD_STATE="$(fetch "$ROUTER/fleetz" | python -c '
import json, sys
doc = json.load(sys.stdin)
row = next(r for r in doc["replicas"] if r["url"] == sys.argv[1])
print("dead" if not row["healthy"] else "alive")
' "$R1")"
    [[ "$DEAD_STATE" == "dead" ]] && break
    sleep 0.5
done
[[ "$DEAD_STATE" == "dead" ]] || {
    echo "FAIL: /fleetz still shows the killed replica healthy"
    fetch "$ROUTER/fleetz"; exit 1; }
echo "PASS /fleetz shows killed replica unhealthy"

# ---- 4. restart at the SAME port; half-open recovery -----------------------
echo "== restart replica1; half-open recovery =="
R1_PORT="${R1##*:}"
KEYSTONE_COMPILE_CACHE="$TMPDIR/xc1" start_replica "$R1_LOG.2" \
    --gateway-port "$R1_PORT"
R1_PID=$!
for _ in $(seq 1 240); do
    fetch "$R1/readyz" >/dev/null 2>&1 && break
    kill -0 "$R1_PID" 2>/dev/null || {
        echo "FAIL: restarted replica1 died"; cat "$R1_LOG.2"; exit 1; }
    sleep 0.5
done
# a little router traffic lets the half-open replica earn its restore
for i in 1 2 3 4 5 6 7 8; do
    python -c '
import json, sys, urllib.request
body = json.dumps({"instances": [[0.0] * int(sys.argv[2])]}).encode()
req = urllib.request.Request(sys.argv[1] + "/predict", data=body,
                             headers={"Content-Type": "application/json"})
urllib.request.urlopen(req, timeout=30).read()
' "$ROUTER" "$D" >/dev/null 2>&1 || true
    sleep 0.5
done
RECOVERED=""
for _ in $(seq 1 60); do
    STATE="$(fetch "$ROUTER/fleetz" | python -c '
import json, sys
doc = json.load(sys.stdin)
row = next(r for r in doc["replicas"] if r["url"] == sys.argv[1])
print(row["state"])
' "$R1")"
    if [[ "$STATE" == "healthy" ]]; then RECOVERED=1; break; fi
    sleep 0.5
done
[[ -n "$RECOVERED" ]] || {
    echo "FAIL: replica1 never recovered to healthy (last state: $STATE)"
    fetch "$ROUTER/fleetz"; exit 1; }
echo "PASS half-open recovery (/fleetz: replica1 healthy after restart)"

# ---- 5. federated quantile agrees with the per-replica quantiles ---------
echo "== SLO federation: fleet quantile vs per-replica quantiles =="
PYTHONPATH="$ROOT" python -c '
import sys, urllib.request
from keystone_tpu.observability.prometheus import (
    histogram_buckets, merge_histograms, quantile_from_buckets)

router, r1, r2 = sys.argv[1:4]
FAMILY = "keystone_gateway_request_latency_seconds"

def scrape(url):
    with urllib.request.urlopen(url + "/metrics", timeout=15) as resp:
        return resp.read().decode()

fed = histogram_buckets(scrape(router), FAMILY)
per = [histogram_buckets(scrape(u), FAMILY) for u in (r1, r2)]
assert fed, "router /metrics had no federated latency buckets"
assert all(per), "a replica scrape had no latency buckets"
# both replicas share the default gateway name, so the router body
# carries ONE summed fleet series; its count must cover both replicas
assert fed[-1][1] >= max(b[-1][1] for b in per), (fed[-1], [b[-1] for b in per])

bounds = [le for le, _ in fed]
def covering(q):
    return next(i for i, le in enumerate(bounds) if q <= le)

qf = quantile_from_buckets(0.99, fed)
qs = [quantile_from_buckets(0.99, b) for b in per]
idx_f, idx = covering(qf), [covering(q) for q in qs]
lo, hi = min(idx) - 1, max(idx) + 1
assert lo <= idx_f <= hi, (
    "federated p99 %.1fms (bucket %d) outside one bucket of "
    "per-replica p99s %sms (buckets %s)"
    % (qf * 1e3, idx_f, [round(q * 1e3, 1) for q in qs], idx))
print("fleet p99 %.1fms agrees with per-replica %sms "
      "within one bucket boundary"
      % (qf * 1e3, [round(q * 1e3, 1) for q in qs]))
' "$ROUTER" "$R1" "$R2" || {
    echo "FAIL: federated quantile disagreed with per-replica quantiles"; exit 1; }
echo "PASS SLO federation"

# ---- 6. distributed tracing: one id, two processes, one stitched tree ----
echo "== distributed tracing: cross-process stitch through the router =="
PYTHONPATH="$ROOT" python -c '
import json, sys, time, urllib.request

router, r1, r2, d = sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4])

body = json.dumps({"instances": [[0.25] * d]}).encode()
req = urllib.request.Request(router + "/predict", data=body,
                             headers={"Content-Type": "application/json"})
t0 = time.perf_counter()
with urllib.request.urlopen(req, timeout=60) as resp:
    resp.read()
    measured_ms = (time.perf_counter() - t0) * 1e3
    tid = resp.headers.get("X-Keystone-Trace")
assert tid, "/predict response carried no X-Keystone-Trace header"
print(f"trace id {tid} (measured {measured_ms:.1f}ms)")
time.sleep(0.5)  # replica stage spans finish just after the response

def get_json(url):
    with urllib.request.urlopen(url, timeout=15) as resp:
        return json.loads(resp.read())

# the id is visible in the router Tracer ring AND at least one replica
rt = get_json(router + "/tracez")
assert any(s["trace_id"] == tid for s in rt["spans"]), \
    "router /tracez does not show the trace"
replica_hits = [
    url for url in (r1, r2)
    if any(s["trace_id"] == tid
           for s in get_json(url + "/tracez")["spans"])
]
assert replica_hits, "no replica /tracez shows the trace id"
print(f"trace visible in router + {len(replica_hits)} replica /tracez")

# the stitched tree: spans from both processes under ONE trace id
doc = get_json(router + f"/debugz?trace_id={tid}")
assert len(doc["processes"]) >= 2, (
    "stitch is router-only: %s (partial_detail=%s)"
    % (doc["processes"], doc["partial_detail"]))
assert not doc["partial"], doc["partial_detail"]
names = {s["name"] for s in doc["spans"]}
assert "router.forward" in names and "gateway.admit" in names, names
grafted = [s for s in doc["spans"] if s.get("grafted")]
assert grafted, "no replica span was grafted under a router hop"

# chrome render loads as one multi-process trace
chrome = get_json(router + f"/debugz?trace_id={tid}&format=chrome")
pids = {e["pid"] for e in chrome["traceEvents"] if e.get("ph") == "X"}
assert len(pids) >= 2, f"chrome trace has one pid only: {pids}"

# phase decomposition sums to within 10% of the measured request
# latency (the router-measured total). The client clock only bounds
# it from above: client-side connection setup on a loaded host is
# NOT part of the server-side request.
phases = doc["phases_ms"]
total = doc["total_ms"]
ph_sum = sum(phases.values())
assert abs(ph_sum - total) <= 0.1 * total, (phases, total)
assert total <= measured_ms + 1.0, (
    f"stitched total {total}ms exceeds client-measured "
    f"{measured_ms:.1f}ms")
assert total >= 0.2 * measured_ms, (
    f"stitched total {total}ms implausibly small vs client-measured "
    f"{measured_ms:.1f}ms")
print(f"phases {phases} sum {ph_sum:.1f}ms ~ total {total}ms "
      f"(client measured {measured_ms:.1f}ms)")

# the phase family rides the router/federated /metrics
with urllib.request.urlopen(router + "/metrics", timeout=15) as resp:
    fed = resp.read().decode()
assert "keystone_request_phase_seconds_bucket" in fed, \
    "keystone_request_phase_seconds missing from federated /metrics"
print("keystone_request_phase_seconds present in federated /metrics")
' "$ROUTER" "$R1" "$R2" "$D" || {
    echo "FAIL: cross-process trace did not stitch"; exit 1; }
echo "PASS distributed tracing (one trace id, stitched /debugz, phases sum)"

echo "smoke-fleet: all checks passed"
