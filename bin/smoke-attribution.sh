#!/usr/bin/env bash
# Smoke-test the attribution & drift plane end to end:
#
#  1. the `serving_attribution_drift` bench row — a two-model zoo
#     driven through a mid-run workload shift, with the row's own
#     gates (per-model attribution sums to the engine totals <= 1e-6
#     relative, drift fires on the shifted model ONLY, the /driftz
#     re-plan diff is non-empty and tightens the shifted model's
#     covering bucket, attribution-on p99 <= 1.05x off) re-checked
#     here off the emitted JSON;
#  2. a real two-model `serve-gateway --zoo --optimize` subprocess:
#     shifted traffic at one model only, then `keystone_drift_score`
#     above threshold for it on /metrics, /driftz carrying a
#     non-empty recommendation-only plan diff, and /attributionz
#     per-model device-FLOP cells reconciling against the engines'
#     own `keystone_serving_device_flops_total` (skipped gracefully
#     when the backend reports no cost analysis);
#  3. keystone-lint self-clean stays at 0 findings (the new
#     metric-family-drift rule included — the catalog table and the
#     registration sites agree).
#
# CI-friendly: CPU backend, ~2-3 min, no network beyond localhost.
#
#   bin/smoke-attribution.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
SERVER_LOG="$TMPDIR/server.log"
BENCH_OUT="$TMPDIR/bench.jsonl"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

echo "== serving_attribution_drift bench row =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-bench --attribution-only \
    | tee "$BENCH_OUT"

python - "$BENCH_OUT" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
row = next(
    r for r in rows if r.get("metric") == "serving_attribution_drift"
)
assert row["attribution_rel_err_max"] <= 1e-6, row
assert row["drifted"] == ["alpha"], row
assert row["scores"]["alpha"] > row["threshold"], row
assert row["scores"]["beta"] <= row["threshold"], row
assert row["replan_changed_models"], row
assert "alpha" in row["replan_changed_models"], row
assert row["p99_ratio"] <= 1.05, row
print(
    f"row OK: psi={row['scores']} drifted={row['drifted']} "
    f"rel_err={row['attribution_rel_err_max']:.2e} "
    f"replan={row['replan_changed_models']} "
    f"p99_ratio={row['p99_ratio']}"
)
PY
echo "PASS serving_attribution_drift row"

echo "== serve-gateway --zoo --optimize drift drill =="
D=6
cat > "$TMPDIR/zoo.json" <<SPEC
{"models": [
  {"name": "alpha", "d": $D, "hidden": 32, "depth": 2, "seed": 1,
   "buckets": [2, 8, 32], "lanes": 1, "default": true, "pinned": true,
   "expected_sizes": {"1": 80, "2": 20}},
  {"name": "beta", "d": $D, "hidden": 32, "depth": 2, "seed": 2,
   "buckets": [2, 8, 32], "lanes": 1,
   "expected_sizes": {"1": 100}}
]}
SPEC
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    --zoo "$TMPDIR/zoo.json" --optimize >"$SERVER_LOG" 2>&1 &
SERVER_PID=$!

# with --optimize a {"plan": ...} line precedes the handshake: scan
# every JSON line for the one carrying "listening"
BASE=""
for _ in $(seq 1 240); do
    BASE="$(python - "$SERVER_LOG" <<'PY'
import json, sys
try:
    for line in open(sys.argv[1]):
        line = line.strip()
        if line.startswith("{"):
            doc = json.loads(line)
            if "listening" in doc:
                print(doc["listening"]); break
except Exception:
    pass
PY
)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: zoo gateway died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || { echo "FAIL: no handshake after 120s"; cat "$SERVER_LOG"; exit 1; }
echo "zoo gateway up on $BASE (planned, baselines pinned)"

# shifted mixture: alpha's plan assumed sizes {1,2}, the live traffic
# is all size-24 windows; beta stays on its assumed size-1 mixture
python - "$BASE" "$D" <<'PY'
import json, sys, urllib.request
base, d = sys.argv[1], int(sys.argv[2])

def predict(path, n_rows):
    inst = [[((7 * i + r) % 13) / 13.0 for i in range(d)]
            for r in range(n_rows)]
    req = urllib.request.Request(
        base + path,
        data=json.dumps({"instances": inst}).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = json.loads(urllib.request.urlopen(req, timeout=120).read())
    assert len(body["predictions"]) == n_rows, body
for _ in range(40):
    predict("/predict/alpha", 24)   # shifted: plan assumed 1-2 rows
    predict("/predict/beta", 1)     # on-plan
print("drove 40 shifted alpha requests + 40 on-plan beta requests")
PY

# drift visible on /metrics: alpha above threshold, beta quiet
python - "$BASE" <<'PY'
import sys, urllib.request
body = urllib.request.urlopen(
    sys.argv[1] + "/metrics", timeout=15).read().decode()
scores = {}
for line in body.splitlines():
    if line.startswith("keystone_drift_score{"):
        labels, value = line.rsplit(" ", 1)
        model = labels.split('model="')[1].split('"')[0]
        scores[model] = float(value)
assert "alpha" in scores, f"no alpha drift score exported: {scores}"
assert scores["alpha"] > 0.25, scores
assert scores.get("beta", 0.0) <= 0.25, scores
print(f"drift scores OK: {scores}")
PY
echo "PASS keystone_drift_score rises on the shifted model only"

# /driftz: drifted roster + non-empty recommendation-only plan diff
python - "$BASE" <<'PY'
import json, sys, urllib.request
doc = json.loads(urllib.request.urlopen(
    sys.argv[1] + "/driftz", timeout=15).read())
assert "alpha" in doc["drifted"], doc["drifted"]
assert "beta" not in doc["drifted"], doc["drifted"]
rec = doc.get("recommendation")
assert rec, "drift tripped but /driftz has no recommendation"
assert rec["changes"], rec
assert "alpha" in rec["changes"], rec["changes"]
assert "recommendation only" in rec["note"], rec
print(f"driftz OK: drifted={doc['drifted']} "
      f"changed={sorted(rec['changes'])}")
PY
echo "PASS /driftz non-empty recommendation-only plan diff"

# /attributionz reconciles against the engines' own FLOP counters
python - "$BASE" <<'PY'
import json, sys, urllib.request
base = sys.argv[1]
attr = json.loads(urllib.request.urlopen(
    base + "/attributionz", timeout=15).read())
models = attr["models"]
assert set(models) >= {"alpha", "beta"}, models
assert all(m["goodput_rows"] > 0 for m in models.values()), models
metrics = urllib.request.urlopen(
    base + "/metrics", timeout=15).read().decode()
engine_flops = sum(
    float(line.rsplit(" ", 1)[1])
    for line in metrics.splitlines()
    if line.startswith("keystone_serving_device_flops_total{")
)
ledger_flops = attr["totals"]["device_flops"]
if engine_flops == 0.0:
    # backend reported no cost analysis: absent-not-zero contract
    assert ledger_flops == 0.0, attr["totals"]
    print("attribution OK (no cost analysis on this backend; "
          f"rows={attr['totals']['goodput_rows']})")
else:
    rel = abs(ledger_flops - engine_flops) / engine_flops
    assert rel <= 1e-6, (ledger_flops, engine_flops, rel)
    print(f"attribution OK: ledger {ledger_flops:.3e} FLOPs == "
          f"engines {engine_flops:.3e} (rel err {rel:.1e})")
PY
echo "PASS /attributionz reconciles with engine FLOP counters"

kill "$SERVER_PID" 2>/dev/null || true
wait "$SERVER_PID" 2>/dev/null || true
SERVER_PID=""

echo "== keystone-lint self-clean =="
PYTHONPATH="$ROOT" python -m keystone_tpu keystone-lint
echo "PASS keystone-lint 0 findings"

echo "smoke-attribution: all checks passed"
