#!/usr/bin/env bash
# Smoke-test the observability plane end to end: start a toy serving
# engine with the admin endpoint on an ephemeral port, scrape /healthz
# and /metrics, verify the per-bucket serving counters are present, and
# exit nonzero on any failure. CI-friendly: CPU backend, ~15s, no
# network beyond localhost.
#
#   bin/smoke-admin.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
PORT_FILE="$TMPDIR/port"
SERVER_LOG="$TMPDIR/server.log"
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

# toy engine + admin endpoint on port 0 (ephemeral); writes the real
# port to $PORT_FILE, serves a little traffic, then idles until killed.
# KEYSTONE_PEAK_* pin a fake hardware peak so the MFU gauge and the
# roofline classification light up on the CPU backend too (unset, those
# series are simply absent — the graceful-degradation contract).
JAX_PLATFORMS=cpu KEYSTONE_PEAK_FLOPS=1e12 KEYSTONE_PEAK_MEMBW_GBPS=100 \
    PYTHONPATH="$ROOT" python - "$PORT_FILE" >"$SERVER_LOG" 2>&1 <<'PY' &
import sys, time
import numpy as np
from keystone_tpu.observability import enable_tracing, start_admin_server
from keystone_tpu.serving.bench import build_pipeline

enable_tracing()
server = start_admin_server(port=0)
fitted = build_pipeline(d=8, hidden=8, depth=2)
engine = fitted.compiled(buckets=(4, 8), name="smoke")
# warmup registers each bucket program's XLA cost model (flops/bytes)
engine.warmup(example=np.zeros((8,), np.float32))
rng = np.random.default_rng(0)
engine.apply(rng.standard_normal((3, 8)).astype(np.float32), sync=True)
engine.apply(rng.standard_normal((7, 8)).astype(np.float32), sync=True)
with open(sys.argv[1], "w") as f:
    f.write(str(server.port))
time.sleep(120)  # hold the engine + endpoint alive for the scrape
PY
SERVER_PID=$!

for _ in $(seq 1 120); do
    [[ -s "$PORT_FILE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: server process died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -s "$PORT_FILE" ]] || { echo "FAIL: no port after 60s"; cat "$SERVER_LOG"; exit 1; }
PORT="$(cat "$PORT_FILE")"
BASE="http://127.0.0.1:$PORT"
echo "admin endpoint up on $BASE"

fetch() {  # fetch <url> [timeout_s] — curl when present, stdlib urllib otherwise
    local timeout="${2:-10}"
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time "$timeout" "$1"
    else
        python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=float(sys.argv[2])).read().decode())' \
            "$1" "$timeout"
    fi
}

HEALTH="$(fetch "$BASE/healthz")"
[[ "$HEALTH" == "ok" ]] || { echo "FAIL: /healthz said '$HEALTH'"; exit 1; }
echo "PASS /healthz"

METRICS="$(fetch "$BASE/metrics")"
for want in \
    'keystone_serving_compiles_total{engine="smoke",bucket="4"} 1' \
    'keystone_serving_compiles_total{engine="smoke",bucket="8"} 1' \
    'keystone_serving_dispatches_total{engine="smoke",bucket="4"} 1' \
    'keystone_serving_examples_total{engine="smoke"} 10' \
    'quantile="0.99"' \
    '# TYPE keystone_serving_dispatch_latency_seconds summary'
do
    grep -qF "$want" <<<"$METRICS" || {
        echo "FAIL: /metrics missing: $want"; echo "$METRICS"; exit 1; }
done
echo "PASS /metrics ($(grep -c '^keystone_' <<<"$METRICS") keystone series)"

# device-truth plane: per-bucket cost models (flops/bytes from XLA's
# cost analysis at warmup), goodput accounting, the MFU + roofline
# series (lit by the pinned KEYSTONE_PEAK_* env), the detected-device
# info gauge, and the memory sampler (host-RAM fallback on CPU)
for want in \
    'keystone_device_flops_per_dispatch{engine="smoke",bucket="4"}' \
    'keystone_device_flops_per_dispatch{engine="smoke",bucket="8"}' \
    'keystone_device_bytes_per_dispatch{engine="smoke",bucket="4"}' \
    'keystone_serving_goodput_rows_total{engine="smoke",bucket="4"} 3' \
    'keystone_serving_goodput_rows_total{engine="smoke",bucket="8"} 7' \
    'keystone_serving_padded_rows_total{engine="smoke",bucket="4"} 1' \
    'keystone_serving_padding_efficiency{engine="smoke"}' \
    'keystone_serving_mfu{engine="smoke"}' \
    'keystone_device_roofline_bound{engine="smoke",bucket="4",bound="' \
    'keystone_serving_device_flops_total{engine="smoke"}' \
    'keystone_device_info{kind="' \
    'keystone_device_memory_bytes{device="host",kind="host-ram",stat="limit"}'
do
    grep -qF "$want" <<<"$METRICS" || {
        echo "FAIL: /metrics missing device-truth series: $want"
        echo "$METRICS" | grep -E 'keystone_(device|serving_(goodput|padd|mfu))' || true
        exit 1; }
done
echo "PASS /metrics device-truth series (cost model, goodput, MFU, roofline, memory)"

fetch "$BASE/tracez" | grep -q '"serving.dispatch"' || {
    echo "FAIL: /tracez has no serving.dispatch span"; exit 1; }
echo "PASS /tracez"

# /slz renders even with no SLOs declared (empty objective list), and
# /varz carries the build/uptime identity block
fetch "$BASE/slz" | grep -q '"slos"' || {
    echo "FAIL: /slz did not render"; exit 1; }
echo "PASS /slz"
VARZ="$(fetch "$BASE/varz")"
for want in '"build"' '"git_sha"' '"uptime_s"' '"jax_version"' \
    '"devices"' '"peak_flops"'; do
    grep -q "$want" <<<"$VARZ" || {
        echo "FAIL: /varz missing $want"; exit 1; }
done
fetch "$BASE/metrics" | grep -q '^keystone_build_info{' || {
    echo "FAIL: /metrics missing keystone_build_info"; exit 1; }
echo "PASS /varz build info + device table"
fetch "$BASE/debugz" | grep -q '"records"' || {
    echo "FAIL: /debugz did not render"; exit 1; }
echo "PASS /debugz"

# on-demand profiling: one /profilez capture returns a trace directory
# listing (jax.profiler XPlane capture, CPU backend included)
# first start_trace in a fresh process initializes the profiler
# backend (~10s observed on this CPU image) — allow well beyond the
# 1s capture window
PROFILEZ="$(fetch "$BASE/profilez?seconds=1" 45)"
grep -q '"trace_dir"' <<<"$PROFILEZ" || {
    echo "FAIL: /profilez returned: $PROFILEZ"; exit 1; }
grep -q '"file_count"' <<<"$PROFILEZ" || {
    echo "FAIL: /profilez capture listed no files: $PROFILEZ"; exit 1; }
echo "PASS /profilez (on-demand jax.profiler capture)"
echo "smoke-admin: all checks passed"
