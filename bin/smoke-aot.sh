#!/usr/bin/env bash
# Smoke-test zero-cold-start serving end to end:
#
#   1. serve-aot-build pre-populates the AOT serialized-executable
#      store (one compile pass, executables fingerprinted + written);
#   2. a brand-new serve-gateway process starts against that store and
#      must flip /readyz within budget — WITHOUT paying trace/compile:
#      its own /metrics must show keystone_aot_cache_hits_total > 0
#      and keystone_serving_compiles_total must stay absent (no bucket
#      ever traced);
#   3. /predict works, and a forced live swap (POST /swap) rotates
#      next-generation engines that ALSO ride the store (hits or
#      entries grow). The /varz aot_cache status block rides the ADMIN
#      endpoint (not the gateway port this drill uses) and is covered
#      by tests/serving/test_aot.py's varz-status test.
#
# CI-friendly: CPU backend, localhost only, ~1 min.
#
#   bin/smoke-aot.sh
set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
TMPDIR="$(mktemp -d)"
SERVER_LOG="$TMPDIR/server.log"
AOT_DIR="$TMPDIR/aot"
# readiness budget for the warm start (seconds). Generous for loaded
# CI hosts — the real zero-compile proof is the hit counter below, the
# budget just catches a gateway that silently fell back to compiling
# something pathological.
READY_BUDGET_S=60
cleanup() {
    [[ -n "${SERVER_PID:-}" ]] && kill "$SERVER_PID" 2>/dev/null || true
    rm -rf "$TMPDIR"
}
trap cleanup EXIT

D=64
SHAPE_ARGS=(--d "$D" --hidden 64 --depth 2 --buckets 4,16)

fetch() {  # fetch <url> [timeout_s]
    local timeout="${2:-15}"
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time "$timeout" "$1"
    else
        python -c 'import sys, urllib.request; \
sys.stdout.write(urllib.request.urlopen(sys.argv[1], timeout=float(sys.argv[2])).read().decode())' \
            "$1" "$timeout"
    fi
}

# ---- 1. build the store --------------------------------------------------
echo "== serve-aot-build (populate the executable store) =="
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    KEYSTONE_AOT_CACHE="$AOT_DIR" \
    KEYSTONE_COMPILE_CACHE="$TMPDIR/xc-build" \
    python -m keystone_tpu serve-aot-build "${SHAPE_ARGS[@]}" \
    | tee "$TMPDIR/build.json"
grep -q '"saved"' "$TMPDIR/build.json" || {
    echo "FAIL: serve-aot-build saved no executables"; exit 1; }
ENTRIES="$(ls "$AOT_DIR"/*.aotx 2>/dev/null | wc -l)"
[[ "$ENTRIES" -ge 2 ]] || {
    echo "FAIL: expected >= 2 store entries, found $ENTRIES"; exit 1; }
echo "PASS store built ($ENTRIES entries in $AOT_DIR)"

# ---- 2. fresh gateway must start hot -------------------------------------
echo "== fresh serve-gateway against the store =="
START_S=$(date +%s)
# a FRESH compile cache dir: the fast start must be attributable to
# the AOT store, not to replayed XLA cache entries
JAX_PLATFORMS=cpu PYTHONPATH="$ROOT" \
    KEYSTONE_AOT_CACHE="$AOT_DIR" \
    KEYSTONE_COMPILE_CACHE="$TMPDIR/xc-fresh" \
    python -m keystone_tpu serve-gateway --gateway-port 0 \
    "${SHAPE_ARGS[@]}" --lanes 2 >"$SERVER_LOG" 2>&1 &
SERVER_PID=$!

BASE=""
for _ in $(seq 1 240); do
    BASE="$(grep -o 'http://127.0.0.1:[0-9]*' "$SERVER_LOG" | head -1 || true)"
    [[ -n "$BASE" ]] && break
    kill -0 "$SERVER_PID" 2>/dev/null || {
        echo "FAIL: gateway died before binding"; cat "$SERVER_LOG"; exit 1; }
    sleep 0.5
done
[[ -n "$BASE" ]] || { echo "FAIL: no gateway URL after 120s"; cat "$SERVER_LOG"; exit 1; }

READY=""
for _ in $(seq 1 $((READY_BUDGET_S * 4))); do
    if fetch "$BASE/readyz" 2 >/dev/null 2>&1; then READY=1; break; fi
    sleep 0.25
done
[[ -n "$READY" ]] || {
    echo "FAIL: /readyz not 200 within ${READY_BUDGET_S}s"; cat "$SERVER_LOG"; exit 1; }
ELAPSED=$(( $(date +%s) - START_S ))
[[ "$ELAPSED" -le "$READY_BUDGET_S" ]] || {
    echo "FAIL: ready took ${ELAPSED}s (> ${READY_BUDGET_S}s budget)"; exit 1; }
echo "PASS /readyz in ${ELAPSED}s (budget ${READY_BUDGET_S}s)"

hits_total() {  # sum of keystone_aot_cache_hits_total sample lines
    printf '%s\n' "$1" \
        | awk '$1 == "keystone_aot_cache_hits_total" {s += $2} END {print int(s)}'
}

METRICS="$(fetch "$BASE/metrics")"
HITS="$(hits_total "$METRICS")"
[[ "${HITS:-0}" -gt 0 ]] || {
    echo "FAIL: keystone_aot_cache_hits_total not > 0 on /metrics"
    printf '%s\n' "$METRICS" | grep keystone_aot_cache || true
    exit 1; }
echo "PASS keystone_aot_cache_hits_total = $HITS"
# the strong form of zero-cold-start: NO bucket was ever traced, so
# the per-bucket compile counter never came into existence
if printf '%s\n' "$METRICS" | grep -q 'keystone_serving_compiles_total{'; then
    echo "FAIL: gateway traced/compiled despite a warm store:"
    printf '%s\n' "$METRICS" | grep 'keystone_serving_compiles_total{'
    exit 1
fi
echo "PASS keystone_serving_compiles_total absent (zero traces)"

# ---- 3. traffic + warm-pool swap also ride the store ---------------------
post() {
    if command -v curl >/dev/null 2>&1; then
        curl -fsS --max-time 60 -X POST -H 'Content-Type: application/json' \
            -d "$2" "$1"
    else
        python -c 'import sys, urllib.request; \
req = urllib.request.Request(sys.argv[1], data=sys.argv[2].encode(), \
headers={"Content-Type": "application/json"}); \
sys.stdout.write(urllib.request.urlopen(req, timeout=60).read().decode())' "$1" "$2"
    fi
}
BODY="{\"instances\": [$(python -c "print([0.0]*$D)")]}"
post "$BASE/predict" "$BODY" | grep -q '"predictions"' || {
    echo "FAIL: /predict against the AOT-loaded engines"; exit 1; }
echo "PASS /predict"

# a forced live swap builds next-generation engines THROUGH the store:
# same proposal -> hits grow; a re-bucketed proposal -> fresh entries
# get saved for the next generation. Either way the store must move.
post "$BASE/swap" '{}' | grep -q '"buckets"' || {
    echo "FAIL: POST /swap"; exit 1; }
HITS2="$(hits_total "$(fetch "$BASE/metrics")")"
ENTRIES2="$(ls "$AOT_DIR"/*.aotx 2>/dev/null | wc -l)"
if [[ "${HITS2:-0}" -le "$HITS" && "$ENTRIES2" -le "$ENTRIES" ]]; then
    echo "FAIL: swap moved neither AOT hits ($HITS -> ${HITS2:-0}) nor" \
         "store entries ($ENTRIES -> $ENTRIES2) — next-generation" \
         "engines bypassed the store"
    exit 1
fi
echo "PASS forced swap rode the store (hits $HITS -> $HITS2," \
     "entries $ENTRIES -> $ENTRIES2)"

post "$BASE/drain" '{}' >/dev/null || true
echo "smoke-aot: all checks passed"
